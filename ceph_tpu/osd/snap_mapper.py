"""SnapMapper: the persistent snap -> clone index + purged_snaps cursor.

The snaptrim subsystem's durable state (ref: src/osd/SnapMapper.h —
the MAPPING_PREFIX snap->object keys the trimmer walks, written in the
SAME transaction as the clone it indexes; src/osd/osd_types.h
pg_info_t::purged_snaps).  Both live in the pgmeta object's omap next
to the durable pg log, so:

* creating a clone and indexing it is ONE store transaction — a crash
  can never leave an unindexed clone (space leak) or an index entry
  with no clone (phantom trim work);
* trimming a clone and unindexing it is ONE transaction — the index
  IS the fine-grained resume cursor: a primary killed mid-trim leaves
  exactly the untrimmed entries behind, and the promoted primary's
  walk resumes from them with no re-deletes;
* `purged_snaps` records fully-trimmed snapids as a durable interval
  set on EVERY acting shard, so `removed_snaps - purged_snaps` is the
  outstanding trim work no matter which shard becomes primary.

Key layout (fixed-width prefixes make parsing unambiguous even for
object names containing the separator):

    sm.{snap:012d}.{clone:012d}.{oid}  -> wire-encoded covers list
    ps                                 -> wire-encoded [[lo, hi], ...]
"""
from __future__ import annotations

from ..store import ObjectId, StoreError, Transaction

PGMETA = ObjectId("pgmeta")

_SNAP_PREFIX = "sm."
_PURGED_KEY = "ps"


def _key(snap: int, clone: int, oid: str) -> str:
    return f"{_SNAP_PREFIX}{snap:012d}.{clone:012d}.{oid}"


def _parse_key(key: str):
    """(snap, clone, oid) from an index key, or None."""
    if not key.startswith(_SNAP_PREFIX):
        return None
    body = key[len(_SNAP_PREFIX):]
    try:
        snap = int(body[:12])
        clone = int(body[13:25])
    except ValueError:
        return None
    return snap, clone, body[26:]


class IntervalSet:
    """Sorted, coalesced closed intervals over snapids (ref:
    src/include/interval_set.h — purged_snaps' representation)."""

    def __init__(self, intervals=None):
        self._iv: list[list[int]] = [list(p) for p in (intervals or [])]

    def contains(self, snap: int) -> bool:
        return any(lo <= snap <= hi for lo, hi in self._iv)

    __contains__ = contains

    def add(self, snap: int) -> None:
        if self.contains(snap):
            return
        self._iv.append([snap, snap])
        self._iv.sort()
        merged: list[list[int]] = []
        for lo, hi in self._iv:
            if merged and lo <= merged[-1][1] + 1:
                merged[-1][1] = max(merged[-1][1], hi)
            else:
                merged.append([lo, hi])
        self._iv = merged

    def to_list(self) -> list:
        return [list(p) for p in self._iv]

    def __repr__(self) -> str:
        return "IntervalSet(%s)" % (
            ",".join(f"[{lo},{hi}]" for lo, hi in self._iv) or "empty")


class SnapMapper:
    """Stateless view over one PG collection's snap index — every read
    goes to the store, every write rides a caller-supplied transaction,
    so transient shard views, restarted daemons and promoted primaries
    all see the same truth with no cache to invalidate."""

    def __init__(self, store, cid: str):
        self.store = store
        self.cid = cid

    # ------------------------------------------------------- raw omap
    def _omap(self) -> dict:
        if not self.store.collection_exists(self.cid) or \
                not self.store.exists(self.cid, PGMETA):
            return {}
        return self.store.omap_get(self.cid, PGMETA)

    # -------------------------------------------------------- index IO
    def add_clone(self, txn: Transaction, oid: str, clone: int,
                  covers: list[int]) -> None:
        """Index a freshly-made clone under every snapid it serves —
        called inside the COW transaction (ref: SnapMapper::add_oid
        riding the repop txn)."""
        from ..msg import encoding as wire
        if not covers:
            return
        txn.touch(self.cid, PGMETA)
        txn.omap_setkeys(self.cid, PGMETA,
                         {_key(s, clone, oid): wire.encode(list(covers))
                          for s in covers})

    def rm(self, txn: Transaction, snap: int, oid: str,
           clone: int) -> None:
        """Drop one (snap, clone) index entry inside `txn`."""
        txn.touch(self.cid, PGMETA)
        txn.omap_rmkeys(self.cid, PGMETA, [_key(snap, clone, oid)])

    def rm_clone(self, txn: Transaction, oid: str, clone: int,
                 covers: list[int]) -> None:
        """Drop every index entry of a clone being deleted (its
        covered snapids are known from the head's clones map)."""
        txn.touch(self.cid, PGMETA)
        txn.omap_rmkeys(self.cid, PGMETA,
                        [_key(s, clone, oid) for s in covers])

    def replace_object(self, txn: Transaction, oid: str,
                       clones: dict[int, list[int]]) -> None:
        """Wholesale re-index of one object (recovery push adopted an
        authoritative clone set): stale entries out, pushed set in."""
        from ..msg import encoding as wire
        stale = [k for k in self._omap()
                 if (p := _parse_key(k)) is not None and p[2] == oid]
        txn.touch(self.cid, PGMETA)
        if stale:
            txn.omap_rmkeys(self.cid, PGMETA, stale)
        sets = {}
        for clone, covers in clones.items():
            for s in covers:
                sets[_key(int(s), int(clone), oid)] = \
                    wire.encode([int(c) for c in covers])
        if sets:
            txn.omap_setkeys(self.cid, PGMETA, sets)

    # ------------------------------------------------------- index read
    def objects_for_snap(self, snap: int) -> list[tuple[str, int]]:
        """[(oid, clone)] still indexed under `snap` — the trim
        work-list AND the resume cursor (trimmed entries are gone)."""
        out = []
        prefix = f"{_SNAP_PREFIX}{snap:012d}."
        for k in sorted(self._omap()):
            if k.startswith(prefix):
                p = _parse_key(k)
                if p is not None:
                    out.append((p[2], p[1]))
        return out

    def dump(self) -> list[dict]:
        """Whole index for offline debugging (objectstore_tool
        dump-snap-index)."""
        from ..msg import encoding as wire
        out = []
        for k, v in sorted(self._omap().items()):
            p = _parse_key(k)
            if p is None:
                continue
            try:
                covers = wire.decode(v)
            except (wire.WireError, IndexError):
                covers = None   # undecodable entry shows as unknown;
                # anything else (a programming error) propagates
            out.append({"snap": p[0], "clone": p[1], "oid": p[2],
                        "covers": covers})
        return out

    def split_keys(self, txn: Transaction,
                   moved_to: dict[str, str]) -> None:
        """PG split: move index entries (and copy the purged cursor)
        along with the objects that re-homed to child collections —
        the snap-index leg of PG::split_into."""
        omap = self._omap()
        by_child: dict[str, dict] = {}
        gone: list[str] = []
        for k, v in omap.items():
            p = _parse_key(k)
            if p is None or p[2] not in moved_to:
                continue
            gone.append(k)
            by_child.setdefault(moved_to[p[2]], {})[k] = v
        if gone:
            txn.omap_rmkeys(self.cid, PGMETA, gone)
        purged = omap.get(_PURGED_KEY)
        targets = set(by_child) | (set(moved_to.values())
                                   if purged is not None else set())
        for ccid in targets:
            txn.touch(ccid, PGMETA)
            sets = dict(by_child.get(ccid, {}))
            if purged is not None:
                sets[_PURGED_KEY] = purged
            txn.omap_setkeys(ccid, PGMETA, sets)

    # ---------------------------------------------------- purged cursor
    def purged_snaps(self) -> IntervalSet:
        from ..msg import encoding as wire
        raw = self._omap().get(_PURGED_KEY)
        if raw is None:
            return IntervalSet()
        try:
            return IntervalSet(wire.decode(raw))
        except Exception:
            return IntervalSet()

    def mark_purged(self, snap: int) -> None:
        self.mark_purged_many([snap])

    def mark_purged_many(self, snaps) -> None:
        """Record fully-trimmed snapids durably — one read + one
        write for the whole batch, skipped when nothing is new (by
        the time this runs every clone of these snaps is already
        gone, so the mark only ever says something true)."""
        from ..msg import encoding as wire
        if not snaps or not self.store.collection_exists(self.cid):
            return
        ps = self.purged_snaps()
        changed = False
        for snap in snaps:
            if int(snap) not in ps:
                ps.add(int(snap))
                changed = True
        if not changed:
            return
        txn = Transaction()
        txn.touch(self.cid, PGMETA)
        txn.omap_setkeys(self.cid, PGMETA,
                         {_PURGED_KEY: wire.encode(ps.to_list())})
        self.store.queue_transaction(txn)


def collection_bytes(store, cid: str) -> int:
    """Physical bytes stored in one PG collection — heads, snap clones
    and EC shard streams alike (the store-accounting feed behind the
    leak-vs-reclaim gauges)."""
    if not store.collection_exists(cid):
        return 0
    total = 0
    for o in store.collection_list(cid):
        try:
            total += store.stat(cid, o)["size"]
        except StoreError:
            pass
    return total
