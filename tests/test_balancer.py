"""Upmap balancer: try_remap_rule validity + calc_pg_upmaps convergence
(ref: src/osd/OSDMap.cc:4360, src/crush/CrushWrapper.cc:3987,
src/test/cli/osdmaptool/upmap*.t behavior)."""
import numpy as np
import pytest

from ceph_tpu.crush import remap
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osd.balancer import Balancer, calc_pg_upmaps
from ceph_tpu.osd.mapping import OSDMapMapping
from ceph_tpu.osd.osdmap import Incremental, OSDMap
from ceph_tpu.osd.types import PG, PGPool


def build_map(n_osd=16, osds_per_host=4, pg_num=256, size=3):
    m = OSDMap()
    m.build_simple(n_osd, PGPool(pg_num=pg_num, pgp_num=pg_num, size=size),
                   osds_per_host=osds_per_host)
    return m


def host_of(cmap, parent, osd):
    return remap.get_parent_of_type(cmap, osd, 1, parent)


# ------------------------------------------------------------- tree walk
def test_parent_and_subtree():
    m = build_map()
    parent = remap.build_parent_map(m.crush)
    # osds 0-3 under first host; host under the root (type 10)
    h0 = host_of(m.crush, parent, 0)
    assert h0 < 0 and h0 == host_of(m.crush, parent, 3)
    assert h0 != host_of(m.crush, parent, 4)
    root = remap.get_parent_of_type(m.crush, 0, 10, parent)
    assert root < 0
    assert remap.subtree_contains(m.crush, root, 7)
    assert remap.subtree_contains(m.crush, h0, 2)
    assert not remap.subtree_contains(m.crush, h0, 4)


def test_rule_weight_osd_map_normalized():
    m = build_map(n_osd=8)
    w = remap.get_rule_weight_osd_map(m.crush, 0)
    assert set(w) == set(range(8))
    assert abs(sum(w.values()) - 1.0) < 1e-6
    assert all(abs(v - 1 / 8) < 1e-6 for v in w.values())


# --------------------------------------------------------- try_remap_rule
def test_try_remap_swaps_overfull_for_underfull_other_host():
    m = build_map()
    orig = m.pg_to_raw_upmap(PG(0, 0))
    assert len(orig) == 3
    parent = remap.build_parent_map(m.crush)
    hosts = {host_of(m.crush, parent, o) for o in orig}
    victim = orig[1]
    # pick an underfull osd on a host not used by orig
    cand = next(o for o in range(16)
                if host_of(m.crush, parent, o) not in hosts)
    out = remap.try_remap_rule(m.crush, 0, 3, {victim}, [cand], orig)
    assert out != orig
    assert victim not in out and cand in out
    # failure domains stay distinct
    out_hosts = [host_of(m.crush, parent, o) for o in out]
    assert len(set(out_hosts)) == 3


def test_try_remap_keeps_placement_when_candidate_collides():
    """An underfull osd whose host is already in the placement must not
    be chosen (chooseleaf host constraint)."""
    m = build_map()
    orig = m.pg_to_raw_upmap(PG(0, 0))
    parent = remap.build_parent_map(m.crush)
    victim = orig[0]
    other = orig[1]
    # candidate sharing a host with `other` (and not in orig)
    sib = next(o for o in range(16)
               if o not in orig and
               host_of(m.crush, parent, o) == host_of(m.crush, parent, other))
    out = remap.try_remap_rule(m.crush, 0, 3, {victim}, [sib], orig)
    # cannot swap victim -> sib (host collision): placement unchanged
    assert out == orig


def test_try_remap_no_overfull_is_identity():
    m = build_map()
    orig = m.pg_to_raw_upmap(PG(0, 0))
    out = remap.try_remap_rule(m.crush, 0, 3, set(), [5], orig)
    assert out == orig


# --------------------------------------------------------- calc_pg_upmaps
def max_deviation(m, pool_ids=None):
    mapping = OSDMapMapping()
    mapping.update(m)
    counts = mapping.osd_pg_counts(m.max_osd, acting=False)
    target = counts.sum() / m.max_osd
    return np.abs(counts - target).max(), counts


def apply_pending(m, inc):
    inc.epoch = m.epoch + 1
    m2 = m.clone()
    m2.apply_incremental(inc)
    return m2


def test_calc_pg_upmaps_balances_and_respects_failure_domains():
    m = build_map(n_osd=16, pg_num=256, size=3)
    before_dev, before_counts = max_deviation(m)
    inc = Incremental(epoch=m.epoch + 1)
    n = calc_pg_upmaps(m, 0.001, 100, set(), inc)
    assert n > 0
    assert len(inc.new_pg_upmap_items) > 0
    m2 = apply_pending(m, inc)
    after_dev, after_counts = max_deviation(m2)
    assert after_counts.sum() == before_counts.sum()  # no PGs lost
    assert after_dev < before_dev
    assert after_dev <= 2.0  # near-perfect on a uniform tree
    # every resulting placement keeps 3 distinct hosts
    parent = remap.build_parent_map(m2.crush)
    mapping = OSDMapMapping()
    mapping.update(m2)
    up = mapping.pools[0].up
    for row in up:
        osds = [int(o) for o in row if o != CRUSH_ITEM_NONE]
        assert len(osds) == 3
        assert len({host_of(m2.crush, parent, o) for o in osds}) == 3


def test_calc_pg_upmaps_already_balanced_is_noop():
    m = build_map(n_osd=16, pg_num=256, size=3)
    inc = Incremental(epoch=m.epoch + 1)
    n = calc_pg_upmaps(m, 0.001, 100, set(), inc)
    m2 = apply_pending(m, inc)
    inc2 = Incremental(epoch=m2.epoch + 1)
    n2 = calc_pg_upmaps(m2, 0.001, 100, set(), inc2)
    # converged: second run finds little or nothing
    assert n2 <= max(2, n // 10)


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_calc_pg_upmaps_only_pools_filter():
    m = build_map(n_osd=16, pg_num=128, size=3)
    m.pools[1] = PGPool(pg_num=128, pgp_num=128, size=3)
    m.pool_names[1] = "two"
    inc = Incremental(epoch=m.epoch + 1)
    calc_pg_upmaps(m, 0.001, 50, {1}, inc)
    assert all(pg.pool == 1 for pg in inc.new_pg_upmap_items)
    assert all(pg.pool == 1 for pg in inc.old_pg_upmap_items)


def test_calc_pg_upmaps_retracts_stale_items():
    """Existing pg_upmap_items that pile PGs onto an overfull osd get
    dropped (the un-remap path, OSDMap.cc:4565)."""
    m = build_map(n_osd=16, pg_num=256, size=3)
    # manufacture imbalance: remap many PGs onto osd 0
    mapping = OSDMapMapping()
    mapping.update(m)
    up = mapping.pools[0].up
    made = 0
    for ps in range(256):
        row = [int(o) for o in up[ps]]
        if 0 in row:
            continue
        # replace first osd whose host differs from osd0's host
        parent = remap.build_parent_map(m.crush)
        h0 = host_of(m.crush, parent, 0)
        for o in row:
            if host_of(m.crush, parent, o) != h0 and \
                    not any(host_of(m.crush, parent, x) == h0 for x in row):
                m.pg_upmap_items[PG(0, ps)] = [(o, 0)]
                made += 1
                break
        if made >= 30:
            break
    assert made >= 30
    dev0, counts0 = max_deviation(m)
    assert counts0[0] > counts0.mean() + 10
    inc = Incremental(epoch=m.epoch + 1)
    n = calc_pg_upmaps(m, 0.001, 200, set(), inc)
    assert n > 0
    assert len(inc.old_pg_upmap_items) > 0  # retractions happened
    m2 = apply_pending(m, inc)
    dev2, counts2 = max_deviation(m2)
    assert counts2[0] <= counts0[0] - 10


def test_calc_pg_upmaps_inc_collections_disjoint():
    """A PG retracted and later re-upmapped in one run must appear in
    only one of old/new pg_upmap_items (the reference erases from the
    opposite pending collection), else apply_incremental drops it."""
    m = build_map(n_osd=16, pg_num=256, size=3)
    parent = remap.build_parent_map(m.crush)
    h0 = host_of(m.crush, parent, 0)
    mapping = OSDMapMapping()
    mapping.update(m)
    up = mapping.pools[0].up
    made = 0
    for ps in range(256):
        row = [int(o) for o in up[ps]]
        if 0 in row or any(host_of(m.crush, parent, x) == h0 for x in row):
            continue
        for o in row:
            m.pg_upmap_items[PG(0, ps)] = [(o, 0)]
            made += 1
            break
        if made >= 40:
            break
    inc = Incremental(epoch=m.epoch + 1)
    calc_pg_upmaps(m, 0.001, 300, set(), inc)
    overlap = set(inc.new_pg_upmap_items) & set(inc.old_pg_upmap_items)
    assert not overlap
    # applying must produce exactly the balancer's view
    m2 = apply_pending(m, inc)
    for pg in inc.new_pg_upmap_items:
        assert m2.pg_upmap_items.get(pg) == inc.new_pg_upmap_items[pg]


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_calc_pg_upmaps_survives_weightless_upmap_target():
    """Stale pg_upmap_items pointing at a marked-out osd must not crash
    the run when retracted (the out osd has no crush-weight target)."""
    m = build_map(n_osd=16, pg_num=256, size=3)
    parent = remap.build_parent_map(m.crush)
    h15 = host_of(m.crush, parent, 15)
    mapping = OSDMapMapping()
    mapping.update(m)
    up = mapping.pools[0].up
    made = 0
    for ps in range(256):
        row = [int(o) for o in up[ps]]
        if 15 in row or any(host_of(m.crush, parent, x) == h15 for x in row):
            continue
        m.pg_upmap_items[PG(0, ps)] = [(row[0], 15)]
        made += 1
        if made >= 20:
            break
    m.osd_weight[15] = 0  # mark out: osd 15 now carries no target
    inc = Incremental(epoch=m.epoch + 1)
    n = calc_pg_upmaps(m, 0.001, 200, set(), inc)
    assert n > 0  # ran to completion and made progress
    m2 = apply_pending(m, inc)
    mapping2 = OSDMapMapping()
    mapping2.update(m2)
    counts = mapping2.osd_pg_counts(m2.max_osd, acting=False)
    assert counts[15] == 0 or counts[15] < 20


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_balancer_driver_multi_pool():
    m = build_map(n_osd=16, pg_num=128, size=3)
    m.pools[1] = PGPool(pg_num=64, pgp_num=64, size=2)
    m.pool_names[1] = "two"
    b = Balancer(max_deviation=1, max_iterations=500)
    before = b.score(m)
    inc = b.optimize(m)
    m2 = apply_pending(m, inc)
    after = b.score(m2)
    assert after["stddev"] < before["stddev"]
    assert after["max_deviation"] <= before["max_deviation"]


def test_osdmaptool_upmap_cli(tmp_path, capsys):
    """--upmap writes pg-upmap-items commands and rebalances the stored
    map (ref: src/test/cli/osdmaptool/upmap.t)."""
    from ceph_tpu.tools import osdmaptool
    mapfile = str(tmp_path / "om.json")
    outfile = str(tmp_path / "upmap.txt")
    assert osdmaptool.main(
        ["--createsimple", "16", mapfile, "--pg-num", "256"]) == 0
    assert osdmaptool.main(
        [mapfile, "--upmap", outfile, "--upmap-max", "100",
         "--upmap-deviation", "1"]) == 0
    cmds = open(outfile).read().strip().splitlines()
    assert cmds and all(
        c.startswith(("ceph osd pg-upmap-items ",
                      "ceph osd rm-pg-upmap-items ")) for c in cmds)
    # without --upmap-save the mapfile is untouched (dry-run planner)
    m1 = osdmaptool.load_map(mapfile)
    assert len(m1.pg_upmap_items) == 0
    # with --upmap-save the rebalanced map is written back
    assert osdmaptool.main(
        [mapfile, "--upmap", outfile, "--upmap-max", "100",
         "--upmap-deviation", "1", "--upmap-save"]) == 0
    m2 = osdmaptool.load_map(mapfile)
    assert len(m2.pg_upmap_items) > 0
    dev, _ = max_deviation(m2)
    assert dev <= 2.0


def test_balancer_score_shape():
    m = build_map(n_osd=8, osds_per_host=2, pg_num=64)
    s = Balancer().score(m)
    assert set(s) == {"stddev", "max_deviation", "osds"}
    assert len(s["osds"]) == 8
    total = sum(v["pgs"] for v in s["osds"].values())
    assert total == 64 * 3


def test_contains_up_matches_subtree_contains_shared_subtree():
    """A bucket referenced by TWO roots (shared subtree): the upward
    parent-map walk only sees one ancestry, so _contains_up must fall
    back to the exact recursion for flagged items."""
    from ceph_tpu.crush.remap import (_contains_up, build_parent_map,
                                      subtree_contains)
    from ceph_tpu.crush.types import CRUSH_BUCKET_STRAW2, CrushBucket, CrushMap
    m = CrushMap()
    host = m.add_bucket(CrushBucket(
        id=0, type=1, alg=CRUSH_BUCKET_STRAW2, items=[0, 1],
        item_weights=[0x10000, 0x10000], weight=0x20000))
    root_a = m.add_bucket(CrushBucket(
        id=0, type=2, alg=CRUSH_BUCKET_STRAW2, items=[host],
        item_weights=[0x20000], weight=0x20000))
    root_b = m.add_bucket(CrushBucket(
        id=0, type=2, alg=CRUSH_BUCKET_STRAW2, items=[host],
        item_weights=[0x20000], weight=0x20000))
    m.max_devices = 2
    parent = build_parent_map(m)
    assert host in parent.multi
    for root in (root_a, root_b):
        for item in (host, 0, 1):
            assert _contains_up(m, parent, root, item) == \
                subtree_contains(m, root, item), (root, item)
    assert not _contains_up(m, parent, root_a, 99)
