"""Distributed tracing: blkin-style spans across client -> primary ->
replicas/shards (ref: src/common/zipkin_trace.h, Message.h:263,
OpRequest::pg_trace into ECBackend.cc:1508)."""
import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.common.tracing import Tracer, child_of, new_trace
from ceph_tpu.testing import MiniCluster


def test_span_primitives():
    root = new_trace()
    child = child_of(root)
    assert child["trace_id"] == root["trace_id"]
    assert child["parent"] == root["span"]
    assert child_of(None) is None
    t = Tracer("osd.0", keep=2)
    assert t.start_span(None, "x") is None     # tracing off: no-op
    for i in range(3):
        sp = t.start_span(new_trace(), f"op{i}")
        sp.event("did a thing")
        t.finish(sp)
    dumped = t.dump()
    assert len(dumped) == 2                    # ring bounded
    assert dumped[-1]["name"] == "op2"
    assert dumped[-1]["events"][0]["event"] == "did a thing"
    assert dumped[-1]["duration"] >= 0


@pytest.mark.parametrize("pool_kind", ["replicated", "erasure"])
def test_cross_daemon_trace(pool_kind):
    """One traced client write produces spans on the CLIENT (the
    objecter roots the trace), the primary, and every replica/shard
    daemon — plus the encode-kernel span on an EC pool — all stitched
    by trace_id with correct parent links."""
    c = MiniCluster(n_osd=4, threaded=True)
    cfg = global_config()
    try:
        c.wait_all_up()
        r = c.rados()
        if pool_kind == "erasure":
            r.mon_command({"prefix": "osd erasure-code-profile set",
                           "name": "k2m1",
                           "profile": {"plugin": "tpu", "k": "2",
                                       "m": "1",
                                       "crush-failure-domain": "osd"}})
            r.pool_create("tp", pg_num=8, pool_type="erasure",
                          erasure_code_profile="k2m1")
        else:
            r.pool_create("tp", pg_num=8)
        io = r.open_ioctx("tp")
        cfg.set("blkin_trace_all", True)
        io.write_full("traced", b"follow me" * 200)
        cfg.set("blkin_trace_all", False)
        client_spans = r.objecter.dump_traces()
        spans = client_spans + \
            [s for d in c.osds.values() for s in d.tracer.dump()]
        # the objecter leg is the trace root
        roots = [s for s in client_spans
                 if s["name"].startswith("objecter_op")
                 and s["parent"] is None]
        assert len(roots) == 1
        root = roots[0]
        tid = root["trace_id"]
        spans = [s for s in spans if s["trace_id"] == tid]
        # every send attempt lands an osd_op child under the client
        # span; the successful one carries reply_sent
        prim = [s for s in spans if s["name"].startswith("osd_op")
                and any(e["event"] == "reply_sent"
                        for e in s["events"])]
        assert len(prim) == 1
        assert prim[0]["parent"] == root["span_id"]
        sub = "rep_write" if pool_kind == "replicated" \
            else "ec_sub_write"
        kids = [s for s in spans if s["name"] == sub]
        # replicated: 2 remote replicas; EC: 2 remote shards (the
        # primary's own shard applies inline, no message)
        assert len(kids) == 2
        assert all(k["parent"] == prim[0]["span_id"] for k in kids)
        services = {k["service"] for k in kids}
        assert prim[0]["service"] not in services
        if pool_kind == "erasure":
            # the Pallas encode region gets its OWN span on the
            # primary, so staged-encode cost is visible per stage
            enc = [s for s in spans
                   if s["name"] == "ec_encode_kernel"]
            assert len(enc) == 1
            assert enc[0]["parent"] == prim[0]["span_id"]
            assert enc[0]["service"] == prim[0]["service"]
        # the assembled tree renders with the client span as the root
        from ceph_tpu.common.tracing import format_tree, span_tree
        trees = span_tree(spans)
        top = [t for t in trees if t["span_id"] == root["span_id"]]
        assert len(top) == 1
        assert any("osd_op" in ln for ln in format_tree(spans))
    finally:
        cfg.set("blkin_trace_all", False)
        c.shutdown()


def test_trace_context_survives_tcp_wire():
    """The Message `trace` field rides the versioned TCP frame codec
    byte-faithfully (ref: Message.h:263 — the blkin trace is part of
    the wire envelope, not an in-process convenience)."""
    from ceph_tpu.msg import encoding as wire
    from ceph_tpu.msg.messages import ECSubWrite, OSDOp

    ctx = new_trace()
    child = child_of(ctx)
    msg = OSDOp(oid="o", op="write", tid=7, data=b"x", trace=child)
    back = wire.decode_message(wire.encode_message(msg))
    assert back.trace == child
    assert back.trace["parent"] == ctx["span"]
    sub = ECSubWrite(tid=9, shard=1, trace=child_of(child))
    back = wire.decode_message(wire.encode_message(sub))
    assert back.trace["trace_id"] == ctx["trace_id"]
    assert back.trace["parent"] == child["span"]
    # untraced messages stay untraced over the wire
    assert wire.decode_message(
        wire.encode_message(OSDOp(oid="o"))).trace is None


def test_ec_decode_span_splits_into_stage_and_kernel_children():
    """The ec_decode_kernel span carries `stage` (host survivor
    gather) and `kernel` (device decode) CHILD spans, so the
    decode_incl_stage gap BENCH_r05 exposed is visible per op in
    assembled traces."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_ec_backend import Cluster, _payload
    from ceph_tpu.common.tracing import span_tree

    cl = Cluster()
    tracer = Tracer("osd.0")
    cl.backend.tracer = tracer
    data = _payload(2 * cl.backend.sinfo.stripe_width)
    assert cl.write("obj", 0, data)
    cl.kill(1)          # degraded read: reconstruction must run
    out = {}
    cl.backend.objects_read_and_reconstruct(
        {"obj": (0, 0)},
        lambda r, e: out.update(results=r, errors=e),
        trace=new_trace())
    assert out["results"]["obj"] == data
    spans = tracer.dump()
    parents = [s for s in spans if s["name"] == "ec_decode_kernel"]
    assert len(parents) == 1
    kids = [s for s in spans if s["parent"] == parents[0]["span_id"]]
    names = sorted(k["name"] for k in kids)
    assert names == ["kernel", "stage"]
    for k in kids:
        assert 0 <= k["duration"] <= parents[0]["duration"] + 1e-6
    # the tree renders with the children nested under the decode span
    tree = span_tree(spans)
    node = [n for n in tree if n["name"] == "ec_decode_kernel"]
    assert node and len(node[0]["children"]) == 2
