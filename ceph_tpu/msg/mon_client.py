"""MonHunter: shared mon-session failover for daemons/clients.

The MonClient hunting behavior (ref: src/mon/MonClient.cc
reopen_session / _reopen_session rank rotation): an entity holds a mon
list, talks to one, and on a connection reset rotates to the next,
re-sending its session greeting (subscription/boot).  The walk is
iterative — a hunt send to another dead mon reports its reset
synchronously and must not recurse.
"""
from __future__ import annotations

from ..common.log import dout


class MonHunter:
    """Mixin; the host class must expose `self.ms` and override
    `_hunt_greeting()` with the session (re)establishment messages."""

    def _init_mons(self, mon) -> None:
        self.mons = [mon] if isinstance(mon, str) else list(mon)
        self._mon_i = 0
        self._mon_hunting = False

    @property
    def mon(self) -> str:
        return self.mons[self._mon_i]

    def _hunt_greeting(self) -> list:
        """Messages that re-establish the session at a new mon."""
        return []

    def _maybe_hunt(self, peer: str) -> bool:
        """Handle a reset of our current mon; True when it was ours
        (hunted or nothing else to do)."""
        if peer != self.mon:
            return False
        if len(self.mons) <= 1 or self._mon_hunting:
            return True
        self._mon_hunting = True
        try:
            for _ in range(len(self.mons) - 1):
                self._mon_i = (self._mon_i + 1) % len(self.mons)
                dout("ms", 1).write("%s: mon hunt -> %s",
                                    getattr(self, "name", "?"), self.mon)
                msgs = self._hunt_greeting()
                if not msgs:
                    break
                if self.ms.connect(self.mon).send_message(msgs[0]):
                    for m in msgs[1:]:
                        self.ms.connect(self.mon).send_message(m)
                    break
        finally:
            self._mon_hunting = False
        return True
