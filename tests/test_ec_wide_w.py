"""jerasure wide-word fields (w=16/32) — gf-complete polynomial fields,
matrix techniques, decode sweeps (ref: src/erasure-code/jerasure/
ErasureCodeJerasure.h:152-252 technique/w surface)."""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import gf, gfw
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import factory


# ------------------------------------------------------------- field math
def test_gf8_field_matches_byte_oracle():
    """GF2w(8) (peasant/table impl) agrees with the gf.py byte field —
    two independent implementations of the same 0x11d field."""
    f = gfw.field(8)
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        assert f.mul(a, b) == gf.gf_mul(a, b)
    data = rng.integers(0, 256, (3, 64), dtype=np.uint8)
    mat = gf.isa_rs_matrix(3, 2)[3:]
    assert np.array_equal(f.matmul_bytes(mat, data),
                          gf.gf_matmul_bytes(mat, data))


@pytest.mark.parametrize("w", [16, 32])
def test_field_axioms(w):
    f = gfw.field(w)
    rng = np.random.default_rng(w)
    mask = (1 << w) - 1
    for _ in range(50):
        a = int(rng.integers(1, 1 << min(w, 31))) & mask
        b = int(rng.integers(1, 1 << min(w, 31))) & mask
        c = int(rng.integers(1, 1 << min(w, 31))) & mask
        assert f.mul(a, b) == f.mul(b, a)
        assert f.mul(a, f.mul(b, c)) == f.mul(f.mul(a, b), c)
        assert f.mul(a, b ^ c) == f.mul(a, b) ^ f.mul(a, c)
        assert f.mul(a, f.inv(a)) == 1
    assert f.mul(0, 5) == 0 and f.inv(0) == 0


@pytest.mark.parametrize("w", [16, 32])
def test_mul_words_matches_scalar(w):
    """The vectorized region multiply (tables for w=16, shift folding
    for w=32) agrees with the scalar peasant multiply."""
    f = gfw.field(w)
    rng = np.random.default_rng(w + 1)
    x = rng.integers(0, 1 << min(w, 63), 257, dtype=np.uint64) \
        .astype(f.dtype)
    for c in (0, 1, 2, 3, 0x8001, (1 << w) - 1):
        got = f.mul_words(c, x)
        want = np.array([f.mul(c, int(v)) for v in x], dtype=f.dtype)
        assert np.array_equal(got, want), c


def test_generator_order_w16():
    """2 generates GF(2^16)* under 0x1100b."""
    f = gfw.field(16)
    assert f.pow(2, (1 << 16) - 1) == 1
    assert f.pow(2, ((1 << 16) - 1) // 3) != 1  # order is full


# -------------------------------------------------------------- plugins
@pytest.mark.parametrize("w", [16, 32])
@pytest.mark.parametrize("technique,k,m", [
    ("reed_sol_van", 4, 2),
    ("reed_sol_r6_op", 5, 2),
    ("cauchy_orig", 3, 2),
    ("cauchy_good", 4, 2),
])
def test_wide_w_roundtrip_and_erasures(w, technique, k, m):
    ec = factory("jerasure", {"k": str(k), "m": str(m), "w": str(w),
                              "technique": technique})
    assert ec.get_chunk_count() == k + m
    rng = np.random.default_rng(w * 100 + k)
    obj = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
    n = k + m
    encoded = ec.encode(set(range(n)), obj)
    cs = ec.get_chunk_size(len(obj))
    assert all(len(encoded[i]) == cs for i in range(n))
    assert cs % (w // 8) == 0
    # every erasure pattern up to m decodes
    for sz in range(1, m + 1):
        for erasure in itertools.combinations(range(n), sz):
            avail = {i: encoded[i] for i in range(n)
                     if i not in erasure}
            decoded = ec.decode(set(range(n)), avail)
            for i in range(n):
                assert np.array_equal(decoded[i], encoded[i]), \
                    (technique, w, erasure, i)
    # payload reassembles
    assert ec.decode_concat(encoded)[:len(obj)] == obj


def test_wide_w_structure():
    """Coding rows follow the published constructions, checked with
    scalar field ops."""
    f = gfw.field(16)
    ec = factory("jerasure", {"k": "4", "m": "2", "w": "16",
                              "technique": "reed_sol_r6_op"})
    mat = ec.encode_matrix
    assert list(mat[4]) == [1, 1, 1, 1]
    assert list(mat[5]) == [f.pow(2, j) for j in range(4)]
    ec2 = factory("jerasure", {"k": "4", "m": "2", "w": "16",
                               "technique": "cauchy_orig"})
    for i in range(2):
        for j in range(4):
            assert ec2.encode_matrix[4 + i][j] == f.inv(i ^ (2 + j))


def test_w16_chunks_differ_from_w8():
    """Same data, different field: chunks must differ (guards against a
    silent w-ignored fallback)."""
    obj = bytes(range(256)) * 8
    e8 = factory("jerasure", {"k": "3", "m": "2", "w": "8",
                              "technique": "reed_sol_van"})
    e16 = factory("jerasure", {"k": "3", "m": "2", "w": "16",
                               "technique": "reed_sol_van"})
    # chunk 3 (first parity) is the XOR row in every field — identical
    # by construction; chunk 4 uses field-dependent coefficients
    c8 = e8.encode({3, 4}, obj)
    c16 = e16.encode({3, 4}, obj)
    n = min(len(c8[4]), len(c16[4]))
    assert np.array_equal(c8[3][:n], c16[3][:n])  # XOR row agrees
    assert not np.array_equal(c8[4][:n], c16[4][:n])


def test_bitmatrix_techniques_construct():
    """Round 3: the bitmatrix family is implemented (ENOENT removed;
    full coverage in tests/test_ec_bitmatrix.py)."""
    for technique in ("liberation", "blaum_roth", "liber8tion"):
        ec = factory("jerasure", {"k": "4", "technique": technique,
                                  "w": {"liberation": "5",
                                        "blaum_roth": "4",
                                        "liber8tion": "8"}[technique]})
        assert ec.get_chunk_count() == 6
    # matrix techniques still reject non-(8,16,32) w
    with pytest.raises(ErasureCodeError):
        factory("jerasure", {"k": "4", "m": "2", "w": "7",
                             "technique": "reed_sol_van"})
