"""OSDThrasher: randomized fault injection against a MiniCluster.

Port of the qa thrasher loop (ref: qa/tasks/ceph_manager.py:98
OSDThrasher: choose_action kill/revive/out/in with min-in guards,
interleaved with client IO, then heal and verify).  Deterministic: a
seeded RNG picks actions, the harness pumps the network and drives
heartbeat/mon ticks on simulated time.
"""
from __future__ import annotations

import random

from ..common.options import global_config
from .cluster import MiniCluster


class OSDThrasher:
    def __init__(self, cluster: MiniCluster, seed: int = 0,
                 min_in: int = 3, min_live: int = 3):
        self.c = cluster
        self.rng = random.Random(seed)
        self.min_in = min_in
        self.min_live = min_live
        self.all_osds = sorted(cluster.osds)
        self.dead: set[int] = set()
        self.out: set[int] = set()
        self.now = 10_000.0
        self.log: list[str] = []

    # ------------------------------------------------------------ state
    def _live(self) -> list[int]:
        return [o for o in self.all_osds if o not in self.dead]

    def _in(self) -> list[int]:
        return [o for o in self.all_osds if o not in self.out]

    def _tick_rounds(self, n: int = 3) -> None:
        """Advance simulated time in sub-grace steps so failure
        detection works the way production cadence does."""
        grace = global_config()["osd_heartbeat_grace"]
        for _ in range(n):
            self.now += grace / 2 + 1
            self.c.tick(self.now)

    # ---------------------------------------------------------- actions
    def kill_osd(self, osd: int | None = None) -> None:
        live = [o for o in self._live()]
        if len(live) <= self.min_live:
            return
        osd = osd if osd is not None else self.rng.choice(live)
        if osd in self.dead:
            return
        self.log.append(f"kill osd.{osd}")
        self.c.kill_osd(osd)
        self.dead.add(osd)
        self._tick_rounds()      # peers detect + mon marks down

    def revive_osd(self, osd: int | None = None) -> None:
        if not self.dead:
            return
        osd = osd if osd is not None else self.rng.choice(
            sorted(self.dead))
        self.log.append(f"revive osd.{osd}")
        self.c.revive_osd(osd)
        self.dead.discard(osd)
        if not self.c.threaded:
            self.c.pump()
        self._tick_rounds(1)

    def out_osd(self, osd: int | None = None) -> None:
        candidates = [o for o in self._in()]
        if len(candidates) <= self.min_in:
            return
        osd = osd if osd is not None else self.rng.choice(candidates)
        self.log.append(f"out osd.{osd}")
        self.c.mon.handle_command({"prefix": "osd out", "ids": [osd]})
        self.out.add(osd)
        if not self.c.threaded:
            self.c.pump()

    def in_osd(self, osd: int | None = None) -> None:
        candidates = sorted(o for o in self.out if o not in self.dead)
        if not candidates:
            return
        osd = osd if osd is not None else self.rng.choice(candidates)
        self.log.append(f"in osd.{osd}")
        self.c.mon.handle_command({"prefix": "osd in", "ids": [osd]})
        self.out.discard(osd)
        if not self.c.threaded:
            self.c.pump()

    ACTIONS = ("kill_osd", "revive_osd", "out_osd", "in_osd")

    def choose_action(self) -> str:
        """(ref: ceph_manager.py choose_action weights)."""
        weights = {"kill_osd": 3, "revive_osd": 3,
                   "out_osd": 2, "in_osd": 2}
        names = list(weights)
        return self.rng.choices(names,
                                weights=[weights[n] for n in names])[0]

    def do_thrash(self, rounds: int, between=None) -> None:
        """`between(i)` runs client IO between actions."""
        for i in range(rounds):
            getattr(self, self.choose_action())()
            if between is not None:
                between(i)

    # ------------------------------------------------------------- heal
    def heal(self, timeout_rounds: int = 50) -> None:
        """Revive + mark in everything, wait until no PG is
        recovering (ref: thrasher's final do_join/wait_for_clean)."""
        for osd in sorted(self.dead):
            self.revive_osd(osd)
        for osd in sorted(self.out):
            self.in_osd(osd)
        import time
        for _ in range(timeout_rounds):
            if self.c.threaded:
                time.sleep(0.02)   # let messenger threads drain
            else:
                self.c.pump()
            if all(d.pgs_recovering() == 0
                   for d in self.c.osds.values()):
                return
            self._tick_rounds(1)   # unwedge map-waiting recoveries
        raise TimeoutError(
            f"cluster never went clean; log: {self.log}")
