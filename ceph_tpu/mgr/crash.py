"""mgr crash module: the RECENT_CRASH health agent over the mon's
crash table (ref: src/pybind/mgr/crash/module.py — ingest/storage
live mon-side here (mon/crash_service.py); this module is the health
and summary half: it watches the table and raises RECENT_CRASH for
unarchived crashes inside the warn window, cleared by archiving).

Per tick: pull `crash ls`, cache it (telemetry/insights/prometheus
read the cache — module command handlers run on the mgr dispatch
thread where a sync mon command would deadlock), and report the
RECENT_CRASH slice through the mgr's merged module-health report.
"""
from __future__ import annotations

import time

from ..common.options import global_config


class CrashModule:
    """(ref: crash/module.py Module)."""

    def __init__(self, mgr, warn_recent_interval: float | None = None):
        self.mgr = mgr
        #: unarchived crashes newer than this raise RECENT_CRASH
        #: (ref: mgr/crash warn_recent_interval, default 2 weeks)
        self.warn_recent_interval = (
            warn_recent_interval if warn_recent_interval is not None
            else global_config()["mgr_crash_warn_recent_interval"])
        #: last `crash ls` snapshot (tick-refreshed)
        self.last_crashes: list[dict] = []
        self.last_checks: dict = {}

    # ------------------------------------------------------------ tick
    def tick(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        rc, _, crashes = self.mgr.mon_command({"prefix": "crash ls"})
        if rc != 0 or not isinstance(crashes, list):
            return
        self.last_crashes = crashes
        recent = [c for c in crashes
                  if not c.get("archived")
                  and now - c.get("stamp", 0.0)
                  <= self.warn_recent_interval]
        checks = {}
        if recent:
            daemons = sorted({c.get("entity_name", "?")
                              for c in recent})
            checks["RECENT_CRASH"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(recent)} daemon crashes recently "
                           f"({len(daemons)} daemons); archive with "
                           "`crash archive-all` once triaged",
                "detail": [f"{c.get('entity_name', '?')} crashed at "
                           f"{c.get('timestamp', '?')}: "
                           f"{c.get('exc_type', '?')}: "
                           f"{c.get('exc_msg', '')}"
                           for c in recent]}
        self.last_checks = checks
        # empty replaces the slice away: archiving clears RECENT_CRASH
        # on the next tick (ref: crash/module.py do_archive + health)
        self.mgr.set_health_checks("crash", checks)

    # ------------------------------------------------------- queries
    def ls(self, new_only: bool = False) -> list[dict]:
        return [c for c in self.last_crashes
                if not (new_only and c.get("archived"))]

    def summary(self) -> dict:
        """Counts by entity type + archive state (telemetry's crash
        channel and the prometheus gauge read this)."""
        by_type: dict[str, int] = {}
        new = 0
        for c in self.last_crashes:
            by_type[c.get("entity_type", "?")] = \
                by_type.get(c.get("entity_type", "?"), 0) + 1
            if not c.get("archived"):
                new += 1
        return {"total": len(self.last_crashes), "new": new,
                "by_entity_type": by_type}

    # ---------------------------------------------------- passthrough
    def info(self, crash_id: str) -> dict | None:
        rc, _, meta = self.mgr.mon_command(
            {"prefix": "crash info", "id": crash_id})
        return meta if rc == 0 else None

    def archive(self, crash_id: str) -> int:
        rc, _, _ = self.mgr.mon_command(
            {"prefix": "crash archive", "id": crash_id})
        return rc

    def archive_all(self) -> int:
        rc, _, _ = self.mgr.mon_command({"prefix": "crash archive-all"})
        return rc

    def prune(self, keep_days: float) -> int:
        rc, _, _ = self.mgr.mon_command(
            {"prefix": "crash prune", "keep": keep_days})
        return rc
