"""Snaptrim: crash-safe background snapshot reclamation (ref: the
SnapTrimmer statechart src/osd/PrimaryLogPG.h:1578 + SnapMapper
src/osd/SnapMapper.h).  Deleting a snapshot must actually free store
bytes, the snap->clone index must be written transactionally with the
clones it describes, and a primary killed mid-trim must resume from
the durable cursor on the promoted primary — no re-deletes, no
survivors in the index."""
import random

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.msg.messages import SnapTrim, SnapTrimReply
from ceph_tpu.osd.snap_mapper import IntervalSet, SnapMapper
from ceph_tpu.osd.types import PG
from ceph_tpu.testing import MiniCluster, OSDThrasher


def store_bytes(cluster) -> int:
    return sum(cluster.osds[o].store.statfs()["used"]
               for o in cluster.osds)


def index_entries(cluster) -> int:
    total = 0
    for d in cluster.osds.values():
        for cid in d.store.list_collections():
            if cid.startswith("pg_"):
                total += len(SnapMapper(d.store, cid).dump())
    return total


def tick_rounds(cluster, start: float, rounds: int,
                step: float = 11.0) -> float:
    now = start
    for _ in range(rounds):
        now += step
        cluster.tick(now)
        cluster.pump()
    return now


# ------------------------------------------------------------ unit-ish
def test_interval_set_coalesces():
    s = IntervalSet()
    for x in (3, 1, 2, 7, 5):
        s.add(x)
    assert s.to_list() == [[1, 3], [5, 5], [7, 7]]
    assert 2 in s and 5 in s and 4 not in s
    s.add(6)
    assert s.to_list() == [[1, 3], [5, 7]]
    # idempotent re-add
    s.add(6)
    assert s.to_list() == [[1, 3], [5, 7]]


# ------------------------------------------------------- reclaim + IO
def test_snap_delete_reclaims_store_bytes_under_io():
    """The headline robustness property: removed_snaps stops being a
    space leak.  Clones created by COW are indexed in the same txn;
    removing the snap trims every clone on every shard while client
    IO keeps flowing, and the pg states walk through
    snaptrim/snaptrim_wait back to clean."""
    c = MiniCluster(n_osd=4, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("sp", pg_num=8)
        c.pump()
        io = r.open_ioctx("sp")
        objs = {f"o{i}": bytes([i + 1]) * 4096 for i in range(12)}
        for oid, data in objs.items():
            io.write_full(oid, data)
        c.pump()
        base = store_bytes(c)
        io.snap_create("s1")
        sid = io.snap_lookup("s1")
        for oid in objs:
            io.write_full(oid, b"x" * 4096)
        # a deleted object whose bytes survive only through the snap:
        # the trim must release the clone AND its whiteout head
        io.remove("o11")
        c.pump()
        assert store_bytes(c) > base, "COW clones must occupy bytes"
        assert index_entries(c) > 0, \
            "clone creation must index transactionally"
        assert io.read("o0", snapid=sid) == objs["o0"]

        io.snap_remove("s1")
        c.pump()
        # trim runs from the tick scheduler, with writes interleaved
        # so reclamation provably coexists with client IO
        rng = random.Random(4)
        now = 10_000.0
        for i in range(10):
            # never o11: recreating the deleted object would
            # legitimately resurrect its head
            oid = f"o{rng.randrange(11)}"
            io.write_full(oid, b"y" * 4096)
            now = tick_rounds(c, now, 1)
        now = tick_rounds(c, now, 8)

        assert index_entries(c) == 0, "snap index must drain"
        after = store_bytes(c)
        assert after <= base, (base, after)
        # the deleted object is FULLY gone: clone + whiteout head
        for d in c.osds.values():
            for cid in d.store.list_collections():
                if cid.startswith("pg_"):
                    assert not any(
                        o.name == "o11"
                        for o in d.store.collection_list(cid)), \
                        (d.name, cid)
        # the durable cursor is recorded on EVERY acting shard
        pid = r.pool_lookup("sp")
        for d in c.osds.values():
            for pg, st in d.pgs.items():
                if pg.pool == pid and hasattr(st.shard, "snap_mapper"):
                    assert sid in st.shard.purged_snaps(), (d.name, pg)
        # trimmed snap is unreadable; head reads fine
        assert io.read("o0") in (objs["o0"], b"x" * 4096, b"y" * 4096)
        assert io.list_snaps("o0")["clones"] == {}
        # no PG stuck in a snaptrim state
        for d in c.osds.values():
            for st in d.pgs.values():
                assert st.snaptrim is None
    finally:
        c.shutdown()


def test_trim_reservation_gating_waits_past_cap():
    """osd_max_trimming_pgs bounds concurrent trimming PGs: with the
    cap at 1, some PGs must pass through snaptrim_wait before their
    slot frees, and all of them still converge."""
    cfg = global_config()
    old = cfg["osd_max_trimming_pgs"]
    old_sleep = cfg["osd_snap_trim_sleep"]
    cfg.set("osd_max_trimming_pgs", 1)
    c = MiniCluster(n_osd=3, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("gp", pg_num=8)
        c.pump()
        io = r.open_ioctx("gp")
        for i in range(16):
            io.write_full(f"g{i}", bytes([i + 1]) * 2048)
        c.pump()
        io.snap_create("s1")
        for i in range(16):
            io.write_full(f"g{i}", b"z" * 2048)
        c.pump()
        io.snap_remove("s1")
        c.pump()
        waited = 0
        now = 10_000.0
        for _ in range(14):
            now = tick_rounds(c, now, 1)
            for d in c.osds.values():
                waited += sum(1 for st in d.pgs.values()
                              if st.snaptrim == "wait")
            if index_entries(c) == 0:
                break
        assert index_entries(c) == 0
        assert waited > 0, "cap=1 must queue at least one PG"
    finally:
        cfg.set("osd_max_trimming_pgs", old)
        cfg.set("osd_snap_trim_sleep", old_sleep)
        c.shutdown()


def test_snaptrim_observability_status_df_prometheus_progress():
    """Mid-trim, the subsystem is visible end to end: pg states carry
    snaptrim, `ceph status`/`df` aggregate it (snaptrim_pgs +
    physical store_bytes per pool), prometheus exports the gauges,
    and the progress module opens a trim event like backfill."""
    import types
    import urllib.request

    from ceph_tpu.mgr.progress import ProgressModule
    from ceph_tpu.mgr.prometheus import PrometheusExporter
    c = MiniCluster(n_osd=3, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("op", pg_num=8)
        c.pump()
        io = r.open_ioctx("op")
        for i in range(12):
            io.write_full(f"v{i}", bytes([i + 1]) * 2048)
        c.pump()
        io.snap_create("s1")
        for i in range(12):
            io.write_full(f"v{i}", b"n" * 2048)
        c.pump()
        # stall trim mid-round so the snaptrim state persists across
        # the stat report
        c.network.filter = lambda s, d, m: \
            not isinstance(m, SnapTrimReply)
        io.snap_remove("s1")
        c.pump()
        now = tick_rounds(c, 10_000.0, 2)
        rc, _, status = c.mon.handle_command({"prefix": "status"})
        assert rc == 0
        states = status["pgmap"]["pgs_by_state"]
        assert any("snaptrim" in s for s in states), states
        rc, _, df = c.mon.handle_command({"prefix": "df"})
        pool_df = df["pools"]["op"]
        assert pool_df["snaptrim_pgs"] > 0, pool_df
        # clones still occupy bytes: physical > logical
        assert pool_df["store_bytes"] > pool_df["bytes"], pool_df
        exp = PrometheusExporter(c.mon.handle_command)
        exp.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/metrics",
                    timeout=30) as resp:
                text = resp.read().decode()
        finally:
            exp.shutdown()
        lines = dict(l.rsplit(" ", 1) for l in text.splitlines()
                     if l and not l.startswith("#"))
        assert float(lines['ceph_pool_snaptrim_pgs{pool="op"}']) > 0
        assert float(lines['ceph_pool_store_bytes{pool="op"}']) > \
            float(lines['ceph_pool_bytes{pool="op"}'])
        prog = ProgressModule(types.SimpleNamespace(
            mon_command=c.mon.handle_command))
        assert prog.tick() > 0
        assert any("snaptrim" in e["message"] for e in prog.ls())
        # release the stall: trim completes and the event closes
        c.network.filter = None
        now = tick_rounds(c, now, 8)
        assert index_entries(c) == 0
        rc, _, df2 = c.mon.handle_command({"prefix": "df"})
        pool_df2 = df2["pools"]["op"]
        assert pool_df2["snaptrim_pgs"] == 0
        assert pool_df2["store_bytes"] <= pool_df["bytes"] + 1
        prog.tick()
        assert not any("snaptrim" in e["message"] for e in prog.ls())
        assert any("snaptrim" in e["message"]
                   for e in prog.history())
    finally:
        c.shutdown()


# ------------------------------------------------- crash-safe resume
def test_primary_kill_mid_trim_resumes_from_cursor():
    """Kill the primary mid-trim (OSDThrasher kill model): the
    promoted primary must finish the trim from the persisted snap
    index — resumed SnapTrim ops touch ONLY entries still indexed at
    kill time (no re-deletes), and afterwards no survivors remain in
    the index anywhere."""
    cfg = global_config()
    old_inflight = cfg["osd_pg_max_concurrent_snap_trims"]
    cfg.set("osd_pg_max_concurrent_snap_trims", 1)
    c = MiniCluster(n_osd=5, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("kp", pg_num=4)
        c.pump()
        io = r.open_ioctx("kp")
        objs = {f"k{i}": bytes([i + 1]) * 2048 for i in range(16)}
        for oid, data in objs.items():
            io.write_full(oid, data)
        c.pump()
        io.snap_create("s1")
        sid = io.snap_lookup("s1")
        for oid in objs:
            io.write_full(oid, b"y" * 2048)
        c.pump()
        pid = r.pool_lookup("kp")
        m = c.mon.osdmap
        target_pg = primary = acting_set = None
        for ps in range(4):
            pg = PG(pid, ps)
            _, _, acting, ap = m.pg_to_up_acting_osds(pg)
            st = c.osds[ap].pgs.get(pg)
            if st is not None and sum(
                    1 for o in objs if st.shard.clone_tags(o)) >= 3:
                target_pg, primary = pg, ap
                acting_set = [o for o in acting if o >= 0]
                break
        assert target_pg is not None, "no PG collected enough clones"

        # stall the round mid-flight: drop trim acks so the primary
        # holds in-flight work when it dies
        c.network.filter = lambda s, d, msg: \
            not isinstance(msg, SnapTrimReply)
        io.snap_remove("s1")
        c.pump()
        now = tick_rounds(c, 10_000.0, 1)
        survivor = next(o for o in acting_set if o != primary)
        remaining_at_kill = {
            (e["oid"], e["clone"])
            for e in SnapMapper(c.osds[survivor].store,
                                f"pg_{target_pg}").dump()}
        assert remaining_at_kill, "round completed before the kill"

        c.network.filter = None
        post_kill: list = []

        def counter(src, dst, msg):
            if isinstance(msg, SnapTrim) and msg.pgid == target_pg:
                post_kill.append((msg.oid, msg.clone))
            return True
        c.network.filter = counter
        t = OSDThrasher(c, seed=3, min_in=3, min_live=3)
        t.kill_osd(primary)
        t.now = now + 100
        now = tick_rounds(c, t.now, 12)
        c.network.filter = None

        # promoted primary finished: index empty + cursor durable on
        # every surviving acting shard
        for o in acting_set:
            if o == primary:
                continue
            sm = SnapMapper(c.osds[o].store, f"pg_{target_pg}")
            assert sm.dump() == [], (o, sm.dump())
            assert sid in sm.purged_snaps(), o
        # cursor semantics: the resumed round touched only what was
        # still indexed when the primary died
        assert set(post_kill) <= remaining_at_kill, \
            (post_kill, remaining_at_kill)
        assert io.read("k0") == b"y" * 2048
        # revive for a clean shutdown; the late joiner re-peers
        t.revive_osd(primary)
        tick_rounds(c, now + 50, 2)
    finally:
        cfg.set("osd_pg_max_concurrent_snap_trims", old_inflight)
        c.shutdown()


def test_snap_index_follows_pg_split_and_trims():
    """pg_num growth re-homes objects into child collections; the
    snap index (and purged cursor) must move with them so a
    post-split trim still finds every clone."""
    c = MiniCluster(n_osd=3, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("gp2", pg_num=4)
        c.pump()
        io = r.open_ioctx("gp2")
        for i in range(16):
            io.write_full(f"s{i}", bytes([i + 1]) * 1024)
        c.pump()
        io.snap_create("s1")
        for i in range(16):
            io.write_full(f"s{i}", b"m" * 1024)
        c.pump()
        n_idx = index_entries(c)
        assert n_idx > 0
        for var in ("pg_num", "pgp_num"):
            rc, outs, _ = r.mon_command({"prefix": "osd pool set",
                                         "pool": "gp2", "var": var,
                                         "val": "8"})
            assert rc == 0, outs
        c.pump()
        now = tick_rounds(c, 10_000.0, 3)
        # the split moved entries, it must not lose or duplicate them
        # (replica counts can shift with the remap, so compare the
        # DISTINCT (snap, clone, oid) population instead)
        distinct = set()
        for d in c.osds.values():
            for cid in d.store.list_collections():
                if cid.startswith("pg_"):
                    for e in SnapMapper(d.store, cid).dump():
                        distinct.add((e["snap"], e["clone"], e["oid"]))
        assert len(distinct) == 16, distinct
        io.snap_remove("s1")
        c.pump()
        tick_rounds(c, now, 10)
        assert index_entries(c) == 0
        for i in range(16):
            assert io.read(f"s{i}") == b"m" * 1024
            assert io.list_snaps(f"s{i}")["clones"] == {}
    finally:
        c.shutdown()


def test_replica_down_for_whole_trim_round_reconciles_on_revival():
    """Snap trims write no pg-log entries, so a replica that slept
    through an entire trim round revives log-clean — the purged-
    cursor rebroadcast must make it self-trim its leftovers instead
    of leaking them forever (and flagging every future deep scrub)."""
    c = MiniCluster(n_osd=4, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("dp", pg_num=4)
        c.pump()
        io = r.open_ioctx("dp")
        for i in range(12):
            io.write_full(f"d{i}", bytes([i + 1]) * 2048)
        c.pump()
        io.snap_create("s1")
        sid = io.snap_lookup("s1")
        for i in range(12):
            io.write_full(f"d{i}", b"z" * 2048)
        c.pump()
        # a non-primary acting member of some PG with clones sleeps
        # through the whole round
        pid = r.pool_lookup("dp")
        m = c.mon.osdmap
        victim = None
        for ps in range(4):
            pg = PG(pid, ps)
            _, _, acting, ap = m.pg_to_up_acting_osds(pg)
            st = c.osds[ap].pgs.get(pg)
            if st is not None and any(st.shard.clone_tags(f"d{i}")
                                      for i in range(12)):
                victim = next(o for o in acting
                              if o >= 0 and o != ap)
                break
        assert victim is not None
        c.kill_osd(victim)
        c.mon.handle_command({"prefix": "osd down", "ids": [victim]})
        c.pump()
        io.snap_remove("s1")
        c.pump()
        now = tick_rounds(c, 10_000.0, 8)
        # round complete on the survivors
        live_idx = sum(
            1 for o, d in c.osds.items()
            for cid in d.store.list_collections()
            if cid.startswith("pg_")
            for _ in SnapMapper(d.store, cid).dump())
        assert live_idx == 0
        # the sleeper still holds its stale clones + index on disk
        stale = sum(len(SnapMapper(c._stores[victim], cid).dump())
                    for cid in c._stores[victim].list_collections()
                    if cid.startswith("pg_"))
        assert stale > 0, "victim should hold stale index entries"
        # revival: new interval -> purged-set rebroadcast -> the
        # revived replica trims its own leftovers
        c.revive_osd(victim)
        c.pump()
        now = tick_rounds(c, now, 8)
        assert index_entries(c) == 0
        d = c.osds[victim]
        for cid in d.store.list_collections():
            if cid.startswith("pg_"):
                sm = SnapMapper(d.store, cid)
                assert sm.dump() == []
                assert not any(
                    o.snap not in (-2,)
                    for o in d.store.collection_list(cid)
                    if o.name != "pgmeta"), \
                    "stale clone objects must be trimmed on revival"
    finally:
        c.shutdown()


def test_osd_restart_resumes_trim_from_durable_state():
    """Whole-cluster restart between removal and trim: the removed
    snap is in the map, the index is durable, so the restarted OSDs
    trim with no in-memory state carried over."""
    c = MiniCluster(n_osd=3, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.pool_create("rp", pg_num=4)
        c.pump()
        io = r.open_ioctx("rp")
        for i in range(8):
            io.write_full(f"r{i}", bytes([i + 1]) * 1024)
        c.pump()
        io.snap_create("s1")
        sid = io.snap_lookup("s1")
        for i in range(8):
            io.write_full(f"r{i}", b"w" * 1024)
        c.pump()
        # freeze trim entirely: no ticks happen before the restart
        io.snap_remove("s1")
        c.pump()
        assert index_entries(c) > 0
        for o in sorted(c.osds):
            c.kill_osd(o)
        for o in sorted(c._stores):
            c.start_osd(o)
        c.pump()
        c.wait_all_up()
        tick_rounds(c, 20_000.0, 10)
        assert index_entries(c) == 0
        assert io.read("r0") == b"w" * 1024
        with pytest.raises(Exception):
            io.read("r0", snapid=sid)
    finally:
        c.shutdown()
