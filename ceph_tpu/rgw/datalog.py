"""Sharded datalog: the durable change feed behind multisite sync.

The reference keeps a data log of bucket-index mutations that remote
zones tail to find what changed (ref: src/rgw/rgw_datalog.cc sharded
omap logs; cls_rgw's bilog for the per-bucket variant).  Here the two
collapse into one: every bucket-index shard object carries its own log
under reserved omap keys (`.dl.<seq>` + `.dlmeta`), appended by the
cls_rgw methods **in the same OSD transaction as the index write** —
so an index mutation and its replication record commit atomically (the
PR 2 txn-atomicity lesson; a separate log object could lose one side
of the pair on a crash).

This module is the client half: cursor-based reads (`list` returns
entries after a marker plus the shard head, one exec), head probes for
lag accounting, and trim.  The OSD half lives in `ceph_tpu/cls/rgw.py`
(`_dl_append`, `dl_list`, `dl_trim`).
"""
from __future__ import annotations

import hashlib

from ..client import RadosError
from ..cls.rgw import DL_META, DL_PREFIX, dl_key, is_dl_key  # noqa: F401
# re-exported: gateway listings filter is_dl_key; tests poke dl_key


def shard_obj(bucket: str, shard: int = 0) -> str:
    """Index shard object name — the one place the layout is spelled
    (gateway and datalog must agree or sync reads the wrong log)."""
    return f".rgw.index.{bucket}.{shard}"


def shard_of_key(key: str, nshards: int) -> int:
    """Stable key -> shard placement (ref: rgw_shard_id — hash mod).
    Lives here with the layout: the sync agent must place a peer's
    key with the PEER's shard count, not the local one."""
    if nshards <= 1:
        return 0
    h = hashlib.md5(key.encode()).digest()
    return int.from_bytes(h[:4], "big") % nshards


class DataLog:
    """Cursor reads + trim over a bucket's per-shard datalogs."""

    def __init__(self, io):
        self.io = io

    def list(self, bucket: str, shard: int, marker: int = 0,
             max_entries: int = 64) -> tuple[list[dict], int]:
        """Entries with seq > marker (at most max_entries) and the
        shard's head sequence.  A missing shard object reads as an
        empty log (bucket created elsewhere, nothing written yet)."""
        try:
            out = self.io.exec(shard_obj(bucket, shard), "rgw",
                               "dl_list", {"marker": marker,
                                           "max": max_entries}) or {}
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise       # a shard READ failure (EIO injection,
                # peering trouble) must not masquerade as an empty,
                # caught-up log — head 0 zeroes the very lag gauge
                # that exists to expose it
            return [], 0
        return out.get("entries", []), out.get("head", 0)

    def head(self, bucket: str, shard: int) -> int:
        _, head = self.list(bucket, shard, marker=0, max_entries=0)
        return head

    def heads(self, bucket: str, nshards: int) -> dict[int, int]:
        return {s: self.head(bucket, s) for s in range(nshards)}

    def trim(self, bucket: str, shard: int, upto: int) -> int:
        """Drop entries with seq <= upto; returns how many went.  The
        caller owns the safety argument (every peer's marker has
        passed `upto`) — the reference's datalog trim is likewise an
        admin/trimmer decision, not the log's."""
        out = self.io.exec(shard_obj(bucket, shard), "rgw", "dl_trim",
                           {"upto": upto}) or {}
        return out.get("trimmed", 0)
