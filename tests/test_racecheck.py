"""racecheck: the Eraser-style lockset sanitizer
(ceph_tpu/common/racecheck.py).

Covers the state machine's red path (intersected lockset trips with
both access stacks), the green paths that keep real code quiet
(init-before-publish, common-lock discipline, ownership hand-off,
stale-tolerant external reads), the mixin form, and the
zero-overhead-when-unset contract the tier-1 gate relies on.
"""
import subprocess
import sys
import threading

import pytest

from ceph_tpu.common import racecheck
from ceph_tpu.common.lockdep import make_lock
from ceph_tpu.common.racecheck import (RaceError, RaceTracked,
                                       shared_state,
                                       transfer_ownership)


@pytest.fixture(autouse=True)
def _clean_reports():
    racecheck.reset()
    yield
    racecheck.reset()


def _in_thread(fn):
    """Run fn on a fresh thread, returning what it raised (if)."""
    box = []

    def run():
        try:
            fn()
        except BaseException as e:          # noqa: BLE001 — relayed
            box.append(e)
    t = threading.Thread(target=run, name="racer")
    t.start()
    t.join()
    return box[0] if box else None


def test_racecheck_on_under_tier1():
    """conftest force-sets CEPH_TPU_RACECHECK=1: every tier-1 run is
    a lockset-sanitizer run (like lockdep/jaxguard)."""
    from ceph_tpu.common.options import global_config
    assert global_config()["racecheck"] is True
    assert racecheck.enabled()


def test_unlocked_cross_thread_write_trips_with_both_stacks():
    @shared_state(only=("val",))
    class S:
        def __init__(self):
            self.val = 0

    s = S()
    s.val = 1                      # exclusive phase: silent

    def racer():
        s.val = 2
    err = _in_thread(racer)
    assert isinstance(err, RaceError)
    assert "S.val" in str(err)
    # both access stacks ride the error (the racing pair)
    assert err.cur[0] == "racer"
    assert any(__file__ in fn for fn, _l, _n in err.cur[2])
    assert racecheck.races(), "evidence survives the raise"


def test_common_lock_discipline_stays_green():
    @shared_state(only=("n",))
    class G:
        def __init__(self):
            self.lock = make_lock("racecheck-test.g")
            self.n = 0

        def bump(self):
            with self.lock:
                self.n += 1

    g = G()
    g.bump()
    assert _in_thread(g.bump) is None
    g.bump()
    assert not racecheck.races()


def test_lockset_intersection_trips_on_disjoint_locks():
    """Two threads each hold A lock — just never the same one: the
    candidate set empties and the write trips (the Eraser point: a
    lock is not THE lock)."""
    @shared_state(only=("n",))
    class S:
        def __init__(self):
            self.a = make_lock("racecheck-test.a")
            self.b = make_lock("racecheck-test.b")
            self.n = 0

    s = S()
    with s.a:
        s.n = 1

    def racer():
        with s.b:
            s.n = 2
    # the second thread's first access SEEDS the candidate set {b} —
    # the trip comes when the next access proves no common lock
    assert _in_thread(racer) is None
    with pytest.raises(RaceError):
        with s.a:
            s.n = 3


def test_init_before_publish_is_exclusive_and_silent():
    @shared_state(only=("table",), mutating=("table",))
    class S:
        def __init__(self):
            self.table = {}
            for i in range(32):        # single-threaded init churn
                self.table[i] = i

        def reader(self):
            return len(self.table)

    s = S()
    assert s.reader() == 32
    assert not racecheck.races()


def test_transfer_ownership_documents_handoff():
    @shared_state(only=("payload",))
    class Op:
        def __init__(self):
            self.payload = "built"

    op = Op()
    transfer_ownership(op)

    def consumer():
        op.payload = "consumed"     # new exclusive owner
    assert _in_thread(consumer) is None
    assert not racecheck.races()


def test_mutating_reads_count_as_writes_from_own_methods():
    @shared_state(only=("m",), mutating=("m",))
    class S:
        def __init__(self):
            self.lock = make_lock("racecheck-test.m")
            self.m = {}

        def put(self, k, v):
            with self.lock:
                self.m[k] = v

        def put_unlocked(self, k, v):
            self.m[k] = v

    s = S()
    s.put("a", 1)
    assert _in_thread(lambda: s.put("b", 2)) is None
    err = _in_thread(lambda: s.put_unlocked("c", 3))
    assert isinstance(err, RaceError), \
        "container mutation without the guard must trip"


def test_external_reads_are_stale_tolerant():
    """A harness/test peeking a mutating container from outside the
    object neither trips nor poisons the lockset."""
    @shared_state(only=("m",), mutating=("m",))
    class S:
        def __init__(self):
            self.lock = make_lock("racecheck-test.ext")
            self.m = {"a": 1}

        def put(self, k, v):
            with self.lock:
                self.m[k] = v

    s = S()
    s.put("b", 2)
    assert _in_thread(lambda: s.put("c", 3)) is None
    # external unlocked peek (what every MiniCluster test does)
    assert _in_thread(lambda: s.m.get("a")) is None
    assert _in_thread(lambda: s.put("d", 4)) is None
    assert not racecheck.races()


def test_race_tracked_mixin_registers():
    class H(RaceTracked):
        RACE_TRACK = ("state",)

        def __init__(self):
            self.state = "boot"

    h = H()
    h.state = "up"

    def racer():
        h.state = "down"
    err = _in_thread(racer)
    assert isinstance(err, RaceError)
    assert "H.state" in str(err)


def test_enable_requires_lockdep():
    """Arming without lockdep would make every guarded access look
    unguarded (make_lock hands out invisible RLocks): refused."""
    code = (
        "import os\n"
        "os.environ.pop('CEPH_TPU_LOCKDEP', None)\n"
        "os.environ['CEPH_TPU_RACECHECK'] = '1'\n"
        "from ceph_tpu.common import racecheck\n"
        "try:\n"
        "    racecheck.enable()\n"
        "except RuntimeError as e:\n"
        "    assert 'lockdep' in str(e)\n"
        "else:\n"
        "    raise SystemExit('enable() without lockdep must refuse')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_retro_enable_adopts_pre_arming_instances():
    """enable() after an instance was built must not orphan its
    attribute values (review-found: the descriptor shadowed the
    plain-name dict entry and every read raised AttributeError)."""
    code = (
        "import os\n"
        "os.environ['CEPH_TPU_LOCKDEP'] = '1'\n"
        "os.environ.pop('CEPH_TPU_RACECHECK', None)\n"
        "from ceph_tpu.common import racecheck\n"
        "@racecheck.shared_state(only=('t',), mutating=('t',))\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self.t = {'a': 1}\n"
        "s = S()\n"
        "racecheck.enable()\n"
        "assert s.t == {'a': 1}\n"       # adopted, not orphaned
        "s.t = {'b': 2}\n"
        "assert s.t == {'b': 2}\n"
        "del s.t\n"
        "try:\n"
        "    s.t\n"
        "except AttributeError:\n"
        "    pass\n"
        "else:\n"
        "    raise SystemExit('del did not remove the value')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_zero_overhead_when_env_unset():
    """With CEPH_TPU_RACECHECK unset, shared_state()/RaceTracked only
    register: the class keeps object.__setattr__/__getattribute__,
    no record store appears, and instrumented production classes
    (TcpMessenger, SyncAgent, DecodeTableCache) stay pristine."""
    code = (
        "import os\n"
        "os.environ.pop('CEPH_TPU_RACECHECK', None)\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from ceph_tpu.common import racecheck\n"
        "assert not racecheck.enable_if_configured()\n"
        "assert not racecheck.enabled()\n"
        "@racecheck.shared_state(only=('x',))\n"
        "class S:\n"
        "    pass\n"
        "assert S.__setattr__ is object.__setattr__\n"
        "assert S.__getattribute__ is object.__getattribute__\n"
        "assert 'x' not in vars(S)\n"
        "s = S(); s.x = 1\n"
        "assert s.__dict__ == {'x': 1}\n"
        "from ceph_tpu.msg.tcp import TcpMessenger\n"
        "from ceph_tpu.ec.matrix_code import DecodeTableCache\n"
        "assert '_out' not in vars(TcpMessenger)\n"
        "assert '_lru' not in vars(DecodeTableCache)\n"
        "assert racecheck.stats()['instrumented'] == 0\n"
        "assert racecheck.stats()['registered'] > 0\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr


def test_production_classes_are_instrumented_under_tier1():
    """The tier-1 arming reaches the daemon structures the issue
    names: connection maps, sync cursors, the decode-matrix LRU."""
    from ceph_tpu.ec.matrix_code import DecodeTableCache
    from ceph_tpu.msg.tcp import TcpMessenger
    from ceph_tpu.rgw.multisite import SyncAgent
    for cls, attr in ((DecodeTableCache, "_lru"),
                      (TcpMessenger, "_out"),
                      (SyncAgent, "_markers")):
        assert isinstance(vars(cls).get(attr), property), (cls, attr)


def test_decode_table_cache_locked_end_to_end():
    """The EC decode-matrix LRU under concurrent get/put: every
    access goes through its lock, so the sanitizer stays quiet."""
    from ceph_tpu.ec.matrix_code import DecodeTableCache
    c = DecodeTableCache(capacity=8)
    c.put("+0+1-2", object(), cost=2)

    def churn():
        for i in range(50):
            c.put(f"+0-{i % 4}", object(), cost=1)
            c.get("+0+1-2")
    t = [threading.Thread(target=churn) for _ in range(3)]
    for x in t:
        x.start()
    churn()
    for x in t:
        x.join()
    assert not racecheck.races()
