/* crc32c (Castagnoli) — slice-by-8, raw seed in/out.
 *
 * Native runtime piece of the TPU framework: the per-shard cumulative
 * chunk hash (HashInfo) and transport frame checksums need CPU-side
 * crc32c at memory bandwidth, which a Python byte loop cannot provide.
 * Semantics match the reference's ceph_crc32c(seed, buf, len): the
 * caller passes the running crc (no implicit pre/post inversion), so
 * cumulative hashing chains calls directly
 * (behavioral ref: src/common/sctp_crc32.c, src/common/crc32c.h).
 *
 * Build: cc -O3 -shared -fPIC crc32c.c -o libceph_tpu_native.so
 */
#include <stddef.h>
#include <stdint.h>

#define POLY 0x82F63B78u

static uint32_t table[8][256];

/* Built once at dlopen time (constructor) — no lazy-init publication
 * race when concurrent threads enter with the GIL released. */
__attribute__((constructor)) static void init_tables(void)
{
    uint32_t i, j, crc;
    for (i = 0; i < 256; i++) {
        crc = i;
        for (j = 0; j < 8; j++)
            crc = (crc & 1) ? (crc >> 1) ^ POLY : crc >> 1;
        table[0][i] = crc;
    }
    for (i = 0; i < 256; i++) {
        crc = table[0][i];
        for (j = 1; j < 8; j++) {
            crc = table[0][crc & 0xff] ^ (crc >> 8);
            table[j][i] = crc;
        }
    }
}

uint32_t ceph_tpu_crc32c(uint32_t seed, const uint8_t *data, size_t len)
{
    uint32_t crc = seed;
    /* head: align to 8 bytes */
    while (len && ((uintptr_t)data & 7)) {
        crc = table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
        len--;
    }
    /* body: 8 bytes per step */
    while (len >= 8) {
        const uint64_t word = *(const uint64_t *)data ^ (uint64_t)crc;
        crc = table[7][word & 0xff] ^
              table[6][(word >> 8) & 0xff] ^
              table[5][(word >> 16) & 0xff] ^
              table[4][(word >> 24) & 0xff] ^
              table[3][(word >> 32) & 0xff] ^
              table[2][(word >> 40) & 0xff] ^
              table[1][(word >> 48) & 0xff] ^
              table[0][(word >> 56) & 0xff];
        data += 8;
        len -= 8;
    }
    while (len--)
        crc = table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
    return crc;
}
