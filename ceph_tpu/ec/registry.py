"""Erasure-code plugin registry.

Python analogue of Ceph's singleton dlopen-based ErasureCodePluginRegistry
(ref: src/erasure-code/ErasureCodePlugin.cc:92 factory, :126 load,
:186 preload).  Instead of `libec_<name>.so` with an `__erasure_code_init`
entry point, plugins are Python classes registered by name (either directly
or lazily via a module path, the analogue of deferred dlopen).
"""
from __future__ import annotations

import importlib
import threading

from ..common.lockdep import make_lock
from typing import Callable

from .interface import ErasureCodeInterface, ErasureCodeProfile, ErasureCodeError


class ErasureCodePlugin:
    """A named plugin: a factory making ErasureCodeInterface instances
    (ref: ErasureCodePlugin.h ErasureCodePlugin::factory)."""

    def __init__(self, name: str, factory: Callable[..., ErasureCodeInterface]):
        self.name = name
        self._factory = factory

    def factory(self, profile: ErasureCodeProfile) -> ErasureCodeInterface:
        ec = self._factory()
        ec.init(profile)
        return ec


class ErasureCodePluginRegistry:
    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = make_lock("ec.registry.instance")

    def __init__(self) -> None:
        self._lock = make_lock("ec.registry")
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self._lazy: dict[str, tuple[str, str]] = {}  # name -> (module, attr)
        self.disable_dlclose = False  # parity flag; no-op in Python

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
                cls._instance._register_builtins()
        return cls._instance

    def _register_builtins(self) -> None:
        # analogue of osd_erasure_code_plugins preload list
        for name in ("jerasure", "isa", "tpu", "lrc", "shec", "clay"):
            self._lazy[name] = (f"ceph_tpu.ec.plugins.{name}", "PLUGIN")

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise ErasureCodeError(f"plugin {name} already registered (-EEXIST)")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        # under the lock like add/load: a bare dict read racing load's
        # insert is exactly the guarded-by/racecheck bug class
        with self._lock:
            return self._plugins.get(name)

    def load(self, name: str) -> ErasureCodePlugin:
        """Analogue of dlopen + __erasure_code_init
        (ref: ErasureCodePlugin.cc:126)."""
        with self._lock:
            if name in self._plugins:
                return self._plugins[name]
            if name not in self._lazy:
                raise ErasureCodeError(f"ENOENT: no erasure-code plugin {name!r}")
            module_name, attr = self._lazy[name]
            try:
                mod = importlib.import_module(module_name)
            except ImportError as e:
                raise ErasureCodeError(f"EIO: loading plugin {name}: {e}") from e
            plugin = getattr(mod, attr, None)
            if plugin is None:
                raise ErasureCodeError(
                    f"EXDEV: plugin {name} has no entry point {attr}")
            if not isinstance(plugin, ErasureCodePlugin):
                raise ErasureCodeError(f"EXDEV: plugin {name} bad entry point type")
            self._plugins[name] = plugin
            return plugin

    def factory(self, plugin_name: str, profile: ErasureCodeProfile
                ) -> ErasureCodeInterface:
        """Load (if needed) and instantiate
        (ref: ErasureCodePlugin.cc:92 factory)."""
        plugin = self.load(plugin_name)
        return plugin.factory(dict(profile))

    def preload(self, plugins: list[str]) -> None:
        for name in plugins:
            self.load(name)


def factory(plugin_name: str, profile: ErasureCodeProfile) -> ErasureCodeInterface:
    """Module-level convenience matching ErasureCodePluginRegistry::factory."""
    return ErasureCodePluginRegistry.instance().factory(plugin_name, profile)
