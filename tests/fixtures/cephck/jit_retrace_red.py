"""red: jit cache-miss churn — wrapper per call, per-call static."""
import time

import jax


def encode(x):
    return jax.jit(lambda v: v * 2)(x)      # fresh wrapper per call


stamped = jax.jit(lambda v, stamp: v + stamp,
                  static_argnames=("stamp",))


def encode_stamped(x):
    return stamped(x, stamp=time.time())    # never-repeating cache key
