"""Batch (vmapped) CRUSH mapper vs the scalar oracle.

The scalar engine is validated bit-exact against the reference C core
(tests/test_crush_scalar.py); here the JAX batch engine must reproduce
the scalar engine exactly — including indep NONE holes, firstn skips,
reweight rejections, collisions and chooseleaf recursion."""
import json
import zlib
import os

import numpy as np
import pytest

from ceph_tpu.crush import mapper
from ceph_tpu.crush.batch import BatchUnsupported, compile_map
from ceph_tpu.crush.testing import map_from_spec
from ceph_tpu.crush.types import (
    CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, ChooseArg,
    CrushBucket, CrushMap, CrushRule, CrushRuleStep,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "crush_vectors.json")


def build_hierarchy(n_racks=3, hosts_per_rack=3, osds_per_host=4, seed=0,
                    tunables="jewel"):
    """root(type 3) → racks(2) → hosts(1) → osds(0), all straw2."""
    rng = np.random.default_rng(seed)
    m = CrushMap()
    m.set_tunables_profile(tunables)
    osd = 0
    rack_ids = []
    for _ in range(n_racks):
        host_ids = []
        for _ in range(hosts_per_rack):
            items = list(range(osd, osd + osds_per_host))
            osd += osds_per_host
            weights = [int(rng.integers(1, 4) * 0x10000) for _ in items]
            hid = m.add_bucket(CrushBucket(
                id=0, type=1, alg=CRUSH_BUCKET_STRAW2, items=items,
                item_weights=weights, weight=sum(weights)))
            host_ids.append(hid)
        hw = [m.bucket(h).weight for h in host_ids]
        rid = m.add_bucket(CrushBucket(
            id=0, type=2, alg=CRUSH_BUCKET_STRAW2, items=host_ids,
            item_weights=hw, weight=sum(hw)))
        rack_ids.append(rid)
    rw = [m.bucket(r).weight for r in rack_ids]
    root = m.add_bucket(CrushBucket(
        id=0, type=3, alg=CRUSH_BUCKET_STRAW2, items=rack_ids,
        item_weights=rw, weight=sum(rw)))
    m.max_devices = osd
    return m, root


RULES = {
    "replicated_firstn": lambda root: [
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSELEAF_FIRSTN, 3, 1),
        CrushRuleStep(CRUSH_RULE_EMIT),
    ],
    "ec_indep": lambda root: [
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1),
        CrushRuleStep(CRUSH_RULE_EMIT),
    ],
    "two_level_firstn": lambda root: [
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, 2),
        CrushRuleStep(CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 1),
        CrushRuleStep(CRUSH_RULE_EMIT),
    ],
    "direct_osd_indep": lambda root: [
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSE_INDEP, 4, 0),
        CrushRuleStep(CRUSH_RULE_EMIT),
    ],
    "direct_osd_firstn": lambda root: [
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 3, 0),
        CrushRuleStep(CRUSH_RULE_EMIT),
    ],
}


def make_weight(n_devices, seed=0, frac_out=0.15, frac_partial=0.15):
    rng = np.random.default_rng(seed)
    w = np.full(n_devices, 0x10000, dtype=np.int64)
    rolls = rng.random(n_devices)
    w[rolls < frac_out] = 0
    part = (rolls >= frac_out) & (rolls < frac_out + frac_partial)
    w[part] = rng.integers(0x1000, 0x10000, part.sum())
    return w


def compare(m, ruleno, result_max, weight, xs):
    cc = compile_map(m)
    res, cnt = cc.map_batch(xs, weight, ruleno=ruleno,
                            result_max=result_max, return_counts=True)
    res = np.asarray(res)
    cnt = np.asarray(cnt)
    for i, x in enumerate(xs):
        want = mapper.do_rule(m, ruleno, int(x), result_max, list(weight))
        got = list(res[i][:cnt[i]])
        assert got == want, (
            f"x={x}: batch {got} != scalar {want} (row {res[i]})")


@pytest.mark.parametrize("rule_name", [
    # two_level is the jit-compile-heaviest shape; it stays in the
    # full suite and the TPU parity sweep but out of the tier-1
    # budget (like the other seed-red heavyweights marked below)
    pytest.param(n, marks=pytest.mark.slow)
    if n == "two_level_firstn" else n
    for n in sorted(RULES)])
@pytest.mark.parametrize("tunables", ["jewel", "firefly"])
def test_batch_matches_scalar(rule_name, tunables):
    # deterministic per-rule seed (hash() varies with PYTHONHASHSEED)
    seed = zlib.crc32(rule_name.encode()) % 1000
    m, root = build_hierarchy(seed=seed, tunables=tunables)
    m.rules.append(CrushRule(steps=RULES[rule_name](root)))
    result_max = 6 if rule_name == "ec_indep" else 4
    weight = make_weight(m.max_devices, seed=1)
    compare(m, 0, result_max, weight, list(range(150)))


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_batch_local_retries():
    # choose_local_tries > 0 exercises the in-bucket collide retry
    m, root = build_hierarchy(seed=7)
    m.choose_local_tries = 2
    m.rules.append(CrushRule(steps=RULES["replicated_firstn"](root)))
    weight = make_weight(m.max_devices, seed=2)
    compare(m, 0, 4, weight, list(range(100)))


def test_batch_all_in_weights():
    m, root = build_hierarchy(seed=3)
    m.rules.append(CrushRule(steps=RULES["ec_indep"](root)))
    weight = np.full(m.max_devices, 0x10000, dtype=np.int64)
    compare(m, 0, 6, weight, list(range(100)))


def test_batch_small_cluster_collisions():
    # tiny cluster: numrep close to device count forces many collisions
    m, root = build_hierarchy(n_racks=1, hosts_per_rack=2,
                              osds_per_host=2, seed=5)
    m.rules.append(CrushRule(steps=[
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1),
        CrushRuleStep(CRUSH_RULE_EMIT),
    ]))
    weight = np.full(m.max_devices, 0x10000, dtype=np.int64)
    compare(m, 0, 4, weight, list(range(100)))


def test_batch_choose_args_weight_set():
    m, root = build_hierarchy(seed=11)
    m.rules.append(CrushRule(steps=RULES["ec_indep"](root)))
    # per-position weight overrides on the root bucket
    rng = np.random.default_rng(4)
    rb = m.bucket(root)
    ws = [[int(rng.integers(1, 8) * 0x10000) for _ in rb.items]
          for _ in range(3)]
    ca = {root: ChooseArg(weight_set=ws)}
    weight = make_weight(m.max_devices, seed=5)
    cc = compile_map(m, choose_args=ca)
    xs = list(range(100))
    res, cnt = cc.map_batch(xs, weight, ruleno=0, result_max=6,
                            return_counts=True)
    res, cnt = np.asarray(res), np.asarray(cnt)
    for i, x in enumerate(xs):
        want = mapper.do_rule(m, 0, x, 6, list(weight), choose_args=ca)
        assert list(res[i][:cnt[i]]) == want, f"x={x}"


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_batch_rejects_legacy_algs():
    with open(FIXTURES) as f:
        cases = json.load(f)
    saw_reject = False
    for name, case in cases.items():
        m = map_from_spec(case["spec"])
        algs = {b.alg for b in m.buckets if b is not None}
        if algs == {CRUSH_BUCKET_STRAW2} and \
                m.choose_local_fallback_tries == 0:
            cc = compile_map(m)
            res, cnt = cc.map_batch(
                case["xs"], case["weights"], ruleno=0,
                result_max=case["result_max"], return_counts=True)
            res, cnt = np.asarray(res), np.asarray(cnt)
            for i, (x, want) in enumerate(zip(case["xs"],
                                              case["expected"])):
                assert list(res[i][:cnt[i]]) == want, f"{name} x={x}"
        else:
            with pytest.raises(BatchUnsupported):
                compile_map(m)
            saw_reject = True
    assert saw_reject  # fixture set includes legacy-alg maps


def test_import_does_not_mutate_global_x64():
    import jax.numpy as jnp
    import ceph_tpu.crush.batch  # noqa: F401
    assert jnp.arange(3).dtype == jnp.int32


def test_result_max_required_for_numrep_zero():
    m, root = build_hierarchy(seed=1)
    m.rules.append(CrushRule(steps=RULES["ec_indep"](root)))
    cc = compile_map(m)
    with pytest.raises(BatchUnsupported, match="numrep <= 0"):
        cc.map_batch([1, 2], make_weight(m.max_devices))


def test_bad_ruleno_raises_batch_unsupported():
    m, root = build_hierarchy(seed=1)
    m.rules.append(CrushRule(steps=RULES["ec_indep"](root)))
    cc = compile_map(m)
    with pytest.raises(BatchUnsupported, match="no rule"):
        cc.map_batch([1], make_weight(m.max_devices), ruleno=5,
                     result_max=6)


def test_dangling_bucket_reference_rejected():
    m, root = build_hierarchy(seed=1)
    m.bucket(root).items[0] = -999  # dangling sub-bucket id
    m.rules.append(CrushRule(steps=RULES["ec_indep"](root)))
    with pytest.raises(BatchUnsupported, match="missing bucket"):
        compile_map(m)


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_default_result_max_covers_chained_chooses():
    m, root = build_hierarchy(seed=2)
    m.rules.append(CrushRule(steps=RULES["two_level_firstn"](root)))
    cc = compile_map(m)
    res = np.asarray(cc.map_batch([1, 2, 3], make_weight(m.max_devices)))
    assert res.shape[1] == 4  # 2 racks x 2 hosts


def test_ln16_table_matches_computed():
    """The precomputed 16-bit ln table is bit-identical to the
    arithmetic crush_ln over the whole straw2 domain."""
    import jax.numpy as jnp
    import numpy as np
    from ceph_tpu.crush import batch as B
    with B.enable_x64(True):
        u = jnp.arange(65536, dtype=jnp.int64)
        want = np.asarray(B.crush_ln_vec(u))
    assert np.array_equal(B._LN16, want)


# -- weight-class straw2 path (the argmax-u shortcut) ----------------------

def build_flat(weights_list, tunables="jewel"):
    """root -> osds directly, exact weights as given."""
    m = CrushMap()
    m.set_tunables_profile(tunables)
    items = list(range(len(weights_list)))
    root = m.add_bucket(CrushBucket(
        id=0, type=1, alg=CRUSH_BUCKET_STRAW2, items=items,
        item_weights=list(weights_list), weight=sum(weights_list)))
    m.max_devices = len(weights_list)
    m.rules.append(CrushRule(steps=[
        CrushRuleStep(CRUSH_RULE_TAKE, root),
        CrushRuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 3, 0),
        CrushRuleStep(CRUSH_RULE_EMIT)]))
    return m


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_class_path_tie_heavy_matches_scalar():
    """Huge equal weights collapse distinct hashes onto equal draws —
    the exact case where picking the max-u item instead of the FIRST
    max-draw item would silently diverge from bucket_straw2_choose's
    strict-> update.  2000 xs against the scalar engine."""
    w = [0xFFFF0000] * 20          # draws span only ~2^16 values
    m = build_flat(w)
    cc = compile_map(m)
    assert cc.use_classes and cc.n_class_max == 1
    weight = np.full(20, 0x10000, dtype=np.int64)
    xs = np.arange(2000, dtype=np.int64)
    res, cnt = cc.map_batch(xs, weight, ruleno=0, result_max=3,
                            return_counts=True)
    res = np.asarray(res)
    for i, x in enumerate(xs):
        want = mapper.do_rule(m, 0, int(x), 3, list(weight))
        assert list(res[i][:cnt[i]]) == want, f"x={x}"


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_class_path_and_direct_path_agree_heterogeneous():
    """Same map compiled both ways must map identically (and match
    the scalar oracle) with several distinct weight classes."""
    rng = np.random.default_rng(11)
    w = [int(c) for c in rng.choice(
        [0x8000, 0x10000, 0x18000, 0x20000, 0x28000], size=24)]
    m = build_flat(w)
    c_on = compile_map(m, class_path=True)
    c_off = compile_map(m, class_path=False)
    assert c_on.use_classes and not c_off.use_classes
    weight = make_weight(24, seed=3)
    xs = np.arange(1500, dtype=np.int64)
    r_on, n_on = c_on.map_batch(xs, weight, 0, 3, return_counts=True)
    r_off, n_off = c_off.map_batch(xs, weight, 0, 3,
                                   return_counts=True)
    assert (np.asarray(r_on) == np.asarray(r_off)).all()
    assert (np.asarray(n_on) == np.asarray(n_off)).all()
    for x in range(0, 1500, 97):
        want = mapper.do_rule(m, 0, x, 3, list(weight))
        got = list(np.asarray(r_on)[x][:np.asarray(n_on)[x]])
        assert got == want, f"x={x}"


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_class_path_auto_disables_past_threshold():
    """More distinct weights than CLASS_PATH_MAX -> auto fallback to
    the direct per-item ln path; forcing class_path=True must still
    be bit-identical."""
    from ceph_tpu.crush.batch import CLASS_PATH_MAX
    n = CLASS_PATH_MAX + 8
    w = [0x10000 + i * 0x100 for i in range(n)]   # all distinct
    m = build_flat(w)
    auto = compile_map(m)
    assert not auto.use_classes
    forced = compile_map(m, class_path=True)
    assert forced.use_classes and forced.n_class_max == n
    weight = np.full(n, 0x10000, dtype=np.int64)
    xs = np.arange(800, dtype=np.int64)
    r_a, n_a = auto.map_batch(xs, weight, 0, 3, return_counts=True)
    r_f, n_f = forced.map_batch(xs, weight, 0, 3, return_counts=True)
    assert (np.asarray(r_a) == np.asarray(r_f)).all()
    assert (np.asarray(n_a) == np.asarray(n_f)).all()


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_class_path_ln_boundary_and_wide_sweep():
    """crush_ln dips at u=65535 (x=u+1 overflows the normalization) —
    the class path orders hashes through a key space that swaps the
    65534/65535 pair.  Sweep enough xs that several draws hit those
    boundary hashes, comparing against the direct per-item-ln path
    (itself fixture-pinned to the C core), plus scalar spot checks.
    Regression for the 1M-PG bench divergence at pps=1420417868."""
    from ceph_tpu.crush.batch import LN16_MONO_BY_SWAP
    assert LN16_MONO_BY_SWAP
    m = build_flat([0x20000] * 16)
    c_on = compile_map(m, class_path=True)
    c_off = compile_map(m, class_path=False)
    weight = np.full(16, 0x10000, dtype=np.int64)
    xs = np.arange(120_000, dtype=np.int64)
    r_on, n_on = c_on.map_batch(xs, weight, 0, 3, return_counts=True)
    r_off, n_off = c_off.map_batch(xs, weight, 0, 3,
                                   return_counts=True)
    r_on, r_off = np.asarray(r_on), np.asarray(r_off)
    bad = np.nonzero((r_on != r_off).any(axis=1))[0]
    assert bad.size == 0, f"diverged at xs {bad[:5]}"
    assert (np.asarray(n_on) == np.asarray(n_off)).all()
    for x in (0, 31337, 65534, 65535, 119_999):
        want = mapper.do_rule(m, 0, x, 3, list(weight))
        assert list(r_on[x][:np.asarray(n_on)[x]]) == want, f"x={x}"
