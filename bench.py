#!/usr/bin/env python
"""Driver benchmark: north-star metric, one JSON line on stdout.

Metric (BASELINE.md): `ceph_erasure_code_benchmark` semantics at k=8, m=4,
1 MiB objects — encode + decode (2 erasures) MB/s on the `tpu` erasure-code
plugin, chunks byte-identical to the CPU reference plugins
(ref: src/test/erasure-code/ceph_erasure_code_benchmark.cc:151-181,246-312).

vs_baseline is the ratio against ISA-L AVX2 (`isa` plugin reed_sol_van,
ref: src/erasure-code/isa/ErasureCodeIsa.cc:129) at the same config.  ISA-L
is not runnable in this image (submodule not vendored); we use 5000 MB/s as
the documented stand-in for a modern AVX2 core (ISA-L erasure_code_perf is
typically 3-6 GB/s at k=8,m=4).  The north-star target is vs_baseline >= 4.

Timing methodology: the axon TPU tunnel caches identical dispatches and has
~90 ms round-trip latency, so each measurement chains R unique encodes (input
xor'd with the step index) inside one jitted lax.scan and reads back a single
scalar (see PERF_NOTES.md).
"""
import functools
import json
import sys
import time

import numpy as np

ISA_L_BASELINE_MBPS = 5000.0  # documented AVX2 stand-in (see module docstring)

K, M = 8, 4
OBJECT_SIZE = 1 << 20            # 1 MiB
CHUNK = OBJECT_SIZE // K         # 131072
STRIPES = 256                    # objects per dispatch
REPS = 30                        # scan-chained unique reps per measurement


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ceph_tpu.ec import gf, registry
    from ceph_tpu.ec.kernels.bitmatmul import gf_matmul_xla

    # --- correctness gate: chunks byte-identical to the CPU oracle --------
    tpu = registry.factory("tpu", {"k": str(K), "m": str(M)})
    rng = np.random.default_rng(0)
    obj = rng.integers(0, 256, OBJECT_SIZE, dtype=np.uint8).tobytes()
    encoded = tpu.encode(set(range(K + M)), obj)
    cpu = registry.factory("isa", {"k": str(K), "m": str(M),
                                   "technique": "reed_sol_van"})
    encoded_cpu = cpu.encode(set(range(K + M)), obj)
    for i in range(K + M):
        if not np.array_equal(encoded[i], encoded_cpu[i]):
            print(json.dumps({"metric": "ec_encode_decode_MBps_k8m4_1MiB",
                              "value": 0.0, "unit": "MB/s",
                              "vs_baseline": 0.0,
                              "error": f"chunk {i} parity mismatch"}))
            sys.exit(1)
    avail = {i: encoded[i] for i in range(K + M) if i not in (1, 9)}
    decoded = tpu.decode(set(range(K + M)), avail)
    assert all(np.array_equal(decoded[i], encoded[i]) for i in range(K + M))

    # --- device-side throughput ------------------------------------------
    enc_mat = tpu.encode_matrix[K:]
    B_enc = jnp.asarray(gf.expand_to_bitmatrix(enc_mat).astype(np.int8))
    # decode: erase data chunk 1 and parity chunk 9 -> survivors are the
    # first 8 of the rest; reconstruct both
    from ceph_tpu.ec.matrix_code import make_decode_matrix
    decode_index = [0, 2, 3, 4, 5, 6, 7, 8]
    dmat = make_decode_matrix(tpu.encode_matrix, K, decode_index, [1, 9])
    B_dec = jnp.asarray(gf.expand_to_bitmatrix(dmat).astype(np.int8))

    data = jnp.asarray(
        rng.integers(0, 256, (STRIPES, K, CHUNK), dtype=np.uint8))

    @functools.partial(jax.jit, static_argnames=())
    def chained(B, data):
        def body(c, i):
            out = gf_matmul_xla(B, data ^ i)
            return c + jnp.sum(out, dtype=jnp.int32), None
        acc, _ = lax.scan(body, jnp.int32(0),
                          jnp.arange(REPS, dtype=jnp.uint8))
        return acc

    def measure(B):
        float(chained(B, data))  # warm/compile
        t0 = time.perf_counter()
        float(chained(B, data))
        return (time.perf_counter() - t0) / REPS

    t_enc = measure(B_enc)
    t_dec = measure(B_dec)

    total_mb = STRIPES * OBJECT_SIZE / 1e6
    value = 2 * total_mb / (t_enc + t_dec)   # encode pass + decode pass
    print(json.dumps({
        "metric": "ec_encode_decode_MBps_k8m4_1MiB",
        "value": round(value, 1),
        "unit": "MB/s",
        "vs_baseline": round(value / ISA_L_BASELINE_MBPS, 2),
        "detail": {
            "encode_MBps": round(total_mb / t_enc, 1),
            "decode_MBps": round(total_mb / t_dec, 1),
            "stripes_per_dispatch": STRIPES,
            "chunk_parity_with_cpu_reference": True,
            "baseline": "ISA-L AVX2 stand-in 5000 MB/s (see bench.py docstring)",
        },
    }))


if __name__ == "__main__":
    main()
