#!/usr/bin/env python
"""trace_smoke: one traced S3 PUT + one traced EC write must assemble
into cross-daemon trace trees with every tier present.

The observability half of the ship gate (run from check_green.sh):

* S3 PUT through a gateway: ONE trace tree containing the rgw
  frontend root, the objecter legs beneath it, the OSD primary spans
  beneath those, and the replica sub-op spans beneath those — four
  daemon tiers stitched by trace_id.
* EC pool write + read: the per-shard sub-op spans AND the Pallas
  encode/decode kernel spans (the staged-decode cost) are present
  when tracing is on.

Exit 0 = every tier assembled; anything else = tracing regressed, do
not ship.
"""
from __future__ import annotations

import pathlib
import sys
import urllib.request

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    from ceph_tpu.common.options import global_config
    from ceph_tpu.common.tracing import format_tree, span_tree
    from ceph_tpu.rgw import RGWGateway
    from ceph_tpu.testing import MiniCluster

    cfg = global_config()
    c = MiniCluster(n_osd=3, threaded=True)
    gw = None
    try:
        c.wait_all_up()
        r = c.rados()
        r.mon_command({"prefix": "osd erasure-code-profile set",
                       "name": "k2m1",
                       "profile": {"plugin": "tpu", "k": "2",
                                   "m": "1",
                                   "crush-failure-domain": "osd"}})
        r.pool_create("smoke-ec", pg_num=8, pool_type="erasure",
                      erasure_code_profile="k2m1")
        gw = RGWGateway(c.rados(), pool="rgw-smoke")
        gw.start()
        base = f"http://127.0.0.1:{gw.port}"
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/tb", method="PUT"), timeout=30).read()

        cfg.set("blkin_trace_all", True)
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/tb/traced-key", data=b"trace me" * 512,
                method="PUT"), timeout=30).read()
            ec = r.open_ioctx("smoke-ec")
            ec.write_full("traced-ec", b"follow" * 2048)
            ec.read("traced-ec")
        finally:
            cfg.set("blkin_trace_all", False)

        spans = gw.tracer.dump()
        for cl in c.clients:
            spans += cl.objecter.dump_traces()
        for d in c.osds.values():
            spans += d.tracer.dump()

        # --- tier check 1: the S3 PUT tree -------------------------
        roots = [s for s in spans
                 if s["name"].startswith("rgw_op:PUT /tb/traced-key")]
        if len(roots) != 1:
            print(f"FAIL: expected 1 rgw root span, got {len(roots)}",
                  file=sys.stderr)
            return 1
        tid = roots[0]["trace_id"]
        tree_spans = [s for s in spans if s["trace_id"] == tid]
        tiers = {"rgw_op": 0, "objecter_op": 0, "osd_op": 0,
                 "rep_write": 0}
        for s in tree_spans:
            stage = s["name"].split(":", 1)[0]
            if stage in tiers:
                tiers[stage] += 1
        missing = [t for t, n in tiers.items() if n == 0]
        if missing:
            print(f"FAIL: S3 PUT trace missing tiers {missing} "
                  f"(have {tiers})", file=sys.stderr)
            print("\n".join(format_tree(tree_spans)), file=sys.stderr)
            return 1
        trees = span_tree(tree_spans)
        if not any(t["name"].startswith("rgw_op") for t in trees):
            print("FAIL: rgw span is not the tree root",
                  file=sys.stderr)
            return 1
        print("trace_smoke: S3 PUT tree OK "
              + " ".join(f"{k}={v}" for k, v in sorted(tiers.items())))
        print("\n".join(format_tree(tree_spans)))

        # --- tier check 2: EC shard + kernel spans -----------------
        names = [s["name"] for s in spans]
        for want in ("ec_sub_write", "ec_encode_kernel",
                     "ec_decode_kernel"):
            if not any(n == want for n in names):
                print(f"FAIL: no {want} span from the traced EC op",
                      file=sys.stderr)
                return 1
        print("trace_smoke: EC shard + kernel spans OK")
        return 0
    finally:
        if gw is not None:
            gw.shutdown()
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
