"""OpTracker: in-flight op tracking with per-stage timestamps.

(ref: src/common/TrackedOp.{h,cc} — TrackedOp::mark_event history,
OpTracker::dump_ops_in_flight / dump_historic_ops /
dump_historic_slow_ops served through the admin socket; the slow-op
age warning mirrors osd_op_complaint_time.)

Every daemon type owns one (the reference constructs an OpTracker in
OSD, mon, mds and rgw alike); aged in-flight ops feed the cluster's
SLOW_OPS health warning through each daemon's report path.
"""
from __future__ import annotations

import threading

from .lockdep import make_lock
import time
from collections import deque


class TrackedOp:
    """(ref: TrackedOp.h:214)."""

    __slots__ = ("desc", "start", "events", "done_at")

    def __init__(self, desc: str, now: float):
        self.desc = desc
        self.start = now
        self.events: list[tuple[float, str]] = [(now, "initiated")]
        self.done_at: float | None = None

    def mark_event(self, name: str, now: float | None = None) -> None:
        self.events.append((time.monotonic() if now is None else now,
                            name))

    def dump(self, now: float) -> dict:
        end = self.done_at if self.done_at is not None else now
        return {"description": self.desc,
                "age": round(now - self.start, 6),
                "duration": round(end - self.start, 6),
                "events": [{"time": round(t - self.start, 6),
                            "event": e} for t, e in self.events]}


class OpTracker:
    """(ref: TrackedOp.h:64 OpTracker).

    `complaint_time=None` reads the live `osd_op_complaint_time`
    option per check, so `config set` retunes every daemon's slow-op
    threshold at runtime (the reference observes the same option)."""

    def __init__(self, history_size: int = 20,
                 complaint_time: float | None = None):
        self._lock = make_lock("optracker")
        self._inflight: dict[object, TrackedOp] = {}
        self._historic: deque[TrackedOp] = deque(maxlen=history_size)
        #: completed ops whose total duration exceeded the complaint
        #: threshold (ref: OpTracker's historic_slow ring behind
        #: dump_historic_slow_ops)
        self._historic_slow: deque[TrackedOp] = deque(
            maxlen=history_size)
        self.complaint_time = complaint_time

    @property
    def complaint(self) -> float:
        if self.complaint_time is not None:
            return self.complaint_time
        from .options import global_config
        return global_config()["osd_op_complaint_time"]

    def start(self, key, desc: str) -> TrackedOp:
        op = TrackedOp(desc, time.monotonic())
        with self._lock:
            self._inflight[key] = op
        return op

    def mark(self, key, event: str) -> None:
        with self._lock:
            op = self._inflight.get(key)
        if op is not None:
            op.mark_event(event)

    def finish(self, key, event: str = "done") -> float | None:
        """Retire one op into history; returns its total duration (the
        per-op-class latency histogram feed) or None when untracked."""
        with self._lock:
            op = self._inflight.pop(key, None)
            if op is None:
                return None
            now = time.monotonic()
            op.events.append((now, event))
            op.done_at = now
            self._historic.append(op)
            dur = now - op.start
            if dur > self.complaint:
                self._historic_slow.append(op)
            return dur

    # -- dumps (ref: OpTracker::dump_ops_in_flight :282) ----------------
    def dump_in_flight(self) -> dict:
        now = time.monotonic()
        with self._lock:
            ops = [op.dump(now) for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic(self) -> dict:
        now = time.monotonic()
        with self._lock:
            ops = [op.dump(now) for op in self._historic]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_slow(self) -> dict:
        """(ref: OpTracker::dump_historic_slow_ops)."""
        now = time.monotonic()
        with self._lock:
            ops = [op.dump(now) for op in self._historic_slow]
        return {"num_ops": len(ops), "ops": ops}

    def slow_ops(self) -> list[dict]:
        """Ops older than the complaint threshold
        (ref: OpTracker::check_ops_in_flight)."""
        now = time.monotonic()
        limit = self.complaint
        with self._lock:
            return [op.dump(now) for op in self._inflight.values()
                    if now - op.start > limit]

    def slow_summary(self) -> dict:
        """{count, oldest_age} of aged in-flight ops — the SLOW_OPS
        health feed each daemon ships on its stat report / beacon
        (cleared the moment the ops drain: count 0)."""
        now = time.monotonic()
        limit = self.complaint
        with self._lock:
            ages = [now - op.start for op in self._inflight.values()
                    if now - op.start > limit]
        return {"count": len(ages),
                "oldest_age": round(max(ages), 3) if ages else 0.0}
