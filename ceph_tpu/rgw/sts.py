"""STS: temporary credentials via role assumption.

The reference's Secure Token Service (ref: src/rgw/rgw_sts.cc
STSService::assumeRole; REST surface src/rgw/rgw_rest_sts.cc) in the
same shape:

* **Roles** are cluster-wide objects (omap of `.rgw.roles`): name +
  trust policy (which principals may assume) + max session duration
  (ref: src/rgw/rgw_role.cc RGWRole — the reference persists roles in
  RADOS the same way).  Admin API: `POST /?Action=CreateRole` /
  `DeleteRole` / `ListRoles`.
* **AssumeRole** (authenticated caller, `POST /?Action=AssumeRole
  &RoleArn=...&DurationSeconds=N`): the caller's identity is matched
  against the role's trust policy; on success a temporary credential
  triple is minted — AccessKeyId (STS-prefixed), SecretAccessKey,
  SessionToken — stored in RADOS (`.rgw.sts.creds`) with its expiry,
  so ANY gateway on the pool can validate it (the reference encrypts
  the session token with a cluster key for the same property).
* **Authentication**: SigV4 requests whose access key carries the STS
  prefix resolve their signing secret from the temp-cred table
  instead of the cephx keyring, require the matching
  `X-Amz-Security-Token` header, and die at expiry
  (ref: rgw_auth_s3.cc STSAuthStrategy).
"""
from __future__ import annotations

import json
import secrets
import time

from ..client import RadosError

ROLES_OBJ = ".rgw.roles"
CREDS_OBJ = ".rgw.sts.creds"
#: STS access keys are recognizable by prefix (the reference uses the
#: same trick to route auth to the STS engine)
AKID_PREFIX = "STS"
DEFAULT_DURATION_S = 3600
MAX_DURATION_S = 12 * 3600


class STSError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        self.status = status
        self.code = code
        self.msg = msg or code
        super().__init__(code)


class STSEngine:
    """Role store + temp-credential mint/validate on one pool."""

    def __init__(self, io):
        self.io = io

    # -- roles ---------------------------------------------------------
    def _ensure(self, obj: str) -> None:
        try:
            self.io.create(obj)
        except RadosError:
            pass

    def create_role(self, name: str, trust_principals: list[str],
                    max_duration: int = MAX_DURATION_S) -> dict:
        if not name:
            raise STSError(400, "ValidationError", "RoleName")
        self._ensure(ROLES_OBJ)
        role = {"name": name, "trust": list(trust_principals),
                "max_duration": int(max_duration),
                "created": time.time()}
        self.io.set_omap(ROLES_OBJ, {name: json.dumps(role).encode()})
        return role

    def get_role(self, name: str) -> dict | None:
        try:
            vals = self.io.get_omap_vals_by_keys(ROLES_OBJ, [name])
        except RadosError:
            return None
        return json.loads(vals[name]) if name in vals else None

    def list_roles(self) -> dict[str, dict]:
        try:
            vals, _ = self.io.get_omap_vals(ROLES_OBJ)
        except RadosError:
            return {}
        return {k: json.loads(v) for k, v in vals.items()}

    def delete_role(self, name: str) -> None:
        try:
            self.io.remove_omap_keys(ROLES_OBJ, [name])
        except RadosError:
            pass

    # -- assume / validate ---------------------------------------------
    def assume_role(self, caller: str, role_name: str,
                    duration_s: int | None = None) -> dict:
        """-> {access_key_id, secret_access_key, session_token,
        expiration}.  The caller must appear in the role's trust list
        ('*' = any authenticated principal), mirroring
        sts::AssumeRole's trust-policy evaluation."""
        role = self.get_role(role_name)
        if role is None:
            raise STSError(404, "NoSuchEntity", role_name)
        trust = role.get("trust", [])
        if "*" not in trust and caller not in trust:
            raise STSError(403, "AccessDenied",
                           f"{caller} not trusted by {role_name}")
        duration = int(duration_s or DEFAULT_DURATION_S)
        if duration <= 0 or duration > role.get("max_duration",
                                                MAX_DURATION_S):
            raise STSError(400, "ValidationError",
                           f"DurationSeconds {duration}")
        akid = AKID_PREFIX + secrets.token_hex(10).upper()
        secret = secrets.token_urlsafe(30)
        token = secrets.token_urlsafe(44)
        expires = time.time() + duration
        rec = {"secret": secret, "token": token, "expires": expires,
               "role": role_name, "caller": caller}
        self._ensure(CREDS_OBJ)
        self._sweep_expired()
        self.io.set_omap(CREDS_OBJ, {akid: json.dumps(rec).encode()})
        return {"access_key_id": akid, "secret_access_key": secret,
                "session_token": token,
                "expiration": expires, "role": role_name}

    def _sweep_expired(self) -> None:
        """Reap expired temp creds at mint time — the table must not
        grow one row per AssumeRole forever."""
        now = time.time()
        try:
            vals, _ = self.io.get_omap_vals(CREDS_OBJ)
            dead = [k for k, v in vals.items()
                    if json.loads(v).get("expires", 0) < now]
            if dead:
                self.io.remove_omap_keys(CREDS_OBJ, dead)
        except (RadosError, ValueError):
            pass

    def resolve_secret(self, akid: str, session_token: str) -> str:
        """SigV4 signing secret for an STS access key; raises on
        unknown/expired/token-mismatch (the reference's
        STSAuthStrategy token validation)."""
        try:
            vals = self.io.get_omap_vals_by_keys(CREDS_OBJ, [akid])
        except RadosError:
            raise STSError(403, "InvalidClientTokenId", akid)
        if akid not in vals:
            raise STSError(403, "InvalidClientTokenId", akid)
        rec = json.loads(vals[akid])
        if rec["expires"] < time.time():
            try:
                self.io.remove_omap_keys(CREDS_OBJ, [akid])
            except RadosError:
                pass
            raise STSError(403, "ExpiredToken", akid)
        if rec["token"] != session_token:
            raise STSError(403, "InvalidToken", akid)
        return rec["secret"]

    def identity_of(self, akid: str) -> str | None:
        """The assumed-role identity string for an STS key (shows up
        as the request's acting principal)."""
        try:
            vals = self.io.get_omap_vals_by_keys(CREDS_OBJ, [akid])
        except RadosError:
            return None
        if akid not in vals:
            return None
        rec = json.loads(vals[akid])
        return f"arn:aws:sts:::assumed-role/{rec['role']}/" \
               f"{rec['caller']}"
