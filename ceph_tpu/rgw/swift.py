"""Swift REST frontend over the same buckets as S3.

The reference serves the Swift API from the same radosgw process and
bucket namespace as S3 (ref: src/rgw/rgw_rest_swift.cc;
src/rgw/rgw_swift_auth.cc TempAuth) — a container IS a bucket, an
object IS an S3 object, and both protocols read each other's writes.
Same here:

* **TempAuth**: `GET /auth/v1.0` with `X-Auth-User` (a cephx entity,
  e.g. `client.s3`) + `X-Auth-Key` (its base64 secret) returns
  `X-Auth-Token` + `X-Storage-Url`.  Tokens live in a RADOS omap
  object, so ANY gateway on the pool validates a token issued by
  another (the reference keeps tokens cluster-visible the same way).
  Anonymous gateways (no keyring) skip auth entirely — test mode,
  matching the S3 side.
* **Account**: `GET /swift/v1` lists containers (text or
  `?format=json` with count/bytes), `HEAD` returns
  `X-Account-Container-Count`.
* **Container**: PUT=201 create (idempotent 202), DELETE=204 (409
  when non-empty), HEAD=204 with `X-Container-Object-Count` /
  `X-Container-Bytes-Used`, GET lists objects (prefix/marker/limit;
  text or JSON with name/bytes/hash/last_modified).
* **Object**: PUT=201 (ETag unquoted — Swift style), GET/HEAD with
  ETag/Content-Length/Last-Modified, DELETE=204, and server-side
  copy via `X-Copy-From` on PUT.  Writes run through the gateway's
  `_store_object`, so cls index transactions, versioning state, and
  bucket notifications all apply to Swift traffic too.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import uuid

from ..client import RadosError

#: cluster-visible token table (token -> {user, expires})
TOKENS_OBJ = ".rgw.swift.tokens"
TOKEN_TTL_S = 3600.0


class SwiftError(Exception):
    def __init__(self, status: int, msg: str = ""):
        self.status = status
        self.msg = msg
        super().__init__(msg or str(status))


def _json_or_text(q, rows, text_key):
    """Swift listings: newline-separated names by default, full
    records with ?format=json.  -> (body, content-type, status):
    json is ALWAYS 200 (an empty list has the body '[]' — a 204 with
    a body corrupts HTTP/1.1 keep-alive); only the empty TEXT listing
    is Swift's bodyless 204."""
    if q.get("format") == "json":
        return (json.dumps(rows).encode(), "application/json", 200)
    body = ("".join(r[text_key] + "\n" for r in rows)).encode()
    return (body, "text/plain", 200 if rows else 204)


class SwiftFrontend:
    """Routes /auth/v1.0 and /swift/v1/** against an RGWGateway."""

    def __init__(self, gw):
        self.gw = gw

    # -- TempAuth ------------------------------------------------------
    def _issue_token(self, user: str) -> str:
        token = "AUTH_tk" + uuid.uuid4().hex
        rec = json.dumps({"user": user,
                          "expires": time.time() + TOKEN_TTL_S})
        try:
            self.gw.io.create(TOKENS_OBJ)
        except RadosError:
            pass
        self._sweep_expired()
        self.gw.io.set_omap(TOKENS_OBJ, {token: rec.encode()})
        return token

    def _sweep_expired(self) -> None:
        """Reap every expired token at issue time — without this the
        table grows one row per auth call forever (a client that
        re-auths per request never presents its old tokens again)."""
        now = time.time()
        try:
            vals, _ = self.gw.io.get_omap_vals(TOKENS_OBJ)
            dead = [t for t, rec in vals.items()
                    if json.loads(rec).get("expires", 0) < now]
            if dead:
                self.gw.io.remove_omap_keys(TOKENS_OBJ, dead)
        except (RadosError, ValueError):
            pass

    def _check_token(self, h) -> str:
        """-> authenticated entity name; raises 401.  No keyring =
        anonymous gateway (same contract as the S3 side)."""
        if self.gw.keyring is None:
            return "anonymous"
        token = h.headers.get("X-Auth-Token", "")
        if not token:
            raise SwiftError(401, "missing X-Auth-Token")
        try:
            vals = self.gw.io.get_omap_vals_by_keys(TOKENS_OBJ,
                                                    [token])
        except RadosError:
            raise SwiftError(401, "bad token")
        if token not in vals:
            raise SwiftError(401, "bad token")
        rec = json.loads(vals[token])
        if rec["expires"] < time.time():
            try:
                self.gw.io.remove_omap_keys(TOKENS_OBJ, [token])
            except RadosError:
                pass
            raise SwiftError(401, "token expired")
        return rec["user"]

    def handle_auth(self, h) -> None:
        """GET /auth/v1.0 (ref: rgw_swift_auth.cc RGW_SWIFT_Auth_Get).
        X-Auth-User carries the cephx entity; X-Auth-Key its base64
        secret, compared constant-time."""
        user = h.headers.get("X-Auth-User", "")
        key = h.headers.get("X-Auth-Key", "")
        if self.gw.keyring is not None:
            secret = self.gw.keyring.get(user)
            if secret is None:
                raise SwiftError(401, "no such user")
            want = secret if isinstance(secret, str) \
                else base64.b64encode(secret).decode()
            if not hmac.compare_digest(want, key):
                raise SwiftError(401, "bad key")
        token = self._issue_token(user or "anonymous")
        self.gw._respond(h, 204, b"", "text/plain", {
            "X-Auth-Token": token,
            "X-Storage-Token": token,
            "X-Storage-Url":
                f"http://127.0.0.1:{self.gw.port}/swift/v1"})

    # -- routing -------------------------------------------------------
    def route(self, h, method: str, path: str, q: dict) -> None:
        """Dispatch /swift/v1[/container[/object]]."""
        self._check_token(h)
        rest = path[len("/swift/v1"):].lstrip("/")
        if not rest:
            return self._account_op(h, method, q)
        parts = rest.split("/", 1)
        container = parts[0]
        obj = parts[1] if len(parts) > 1 else ""
        if not obj:
            return self._container_op(h, method, container, q)
        return self._object_op(h, method, container, obj, q)

    # -- account -------------------------------------------------------
    def _account_op(self, h, method: str, q: dict) -> None:
        buckets = self.gw._buckets()
        if method == "HEAD":
            return self.gw._respond(h, 204, b"", "text/plain", {
                "X-Account-Container-Count": str(len(buckets))})
        if method != "GET":
            raise SwiftError(405)
        rows = []
        for name in sorted(buckets):
            # same visibility filter as the container stats: live
            # heads only (no upload bookkeeping, no dm-headed keys)
            idx = {k: v for k, v in self.gw._index(name).items()
                   if not k.startswith(".upload.")
                   and not v.get("dm")}
            rows.append({"name": name, "count": len(idx),
                         "bytes": sum(e.get("size", 0)
                                      for e in idx.values())})
        body, ctype, status = _json_or_text(q, rows, "name")
        self.gw._respond(h, status, body, ctype)

    # -- container -----------------------------------------------------
    def _container_op(self, h, method: str, container: str,
                      q: dict) -> None:
        gw = self.gw
        buckets = gw._buckets()
        if method == "PUT":
            # 201 created / 202 already-there (Swift semantics)
            created = gw._create_bucket(container)
            return gw._respond(h, 201 if created else 202, b"",
                               "text/plain")
        if container not in buckets:
            raise SwiftError(404, container)
        idx = {k: v for k, v in gw._index(container).items()
               if not k.startswith(".upload.") and not v.get("dm")}
        if method == "HEAD":
            return gw._respond(h, 204, b"", "text/plain", {
                "X-Container-Object-Count": str(len(idx)),
                "X-Container-Bytes-Used":
                    str(sum(e.get("size", 0) for e in idx.values()))})
        if method == "DELETE":
            # emptiness judged on the UNFILTERED index (exactly the
            # S3 check): dm-headed version stacks and in-flight
            # multipart uploads still own data objects — dropping the
            # shards would orphan them
            if gw._index(container):
                raise SwiftError(409, "container not empty")
            gw._delete_bucket(container)
            return gw._respond(h, 204, b"", "text/plain")
        if method != "GET":
            raise SwiftError(405)
        prefix = q.get("prefix", "")
        marker = q.get("marker", "")
        try:
            limit = int(q.get("limit", 10000))
        except ValueError:
            raise SwiftError(412, "bad limit")
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)[:limit]
        rows = [{"name": k, "bytes": idx[k].get("size", 0),
                 "hash": idx[k].get("etag", ""),
                 "last_modified": idx[k].get("mtime", "")}
                for k in keys]
        body, ctype, status = _json_or_text(q, rows, "name")
        gw._respond(h, status, body, ctype)

    # -- object --------------------------------------------------------
    def _object_op(self, h, method: str, container: str, obj: str,
                   q: dict) -> None:
        gw = self.gw
        from .gateway import S3Error
        if obj.startswith(gw.RESERVED_KEY_PREFIXES):
            # same guard as the S3 path: these names are index
            # bookkeeping, not objects (a PUT named .dlmeta wedges
            # the shard's datalog head; reads crash on the record's
            # missing etag/size)
            raise SwiftError(400 if method in ("PUT", "POST", "DELETE")
                             else 404, obj)
        bmeta = gw._buckets().get(container)
        if bmeta is None:
            raise SwiftError(404, container)
        if method == "PUT":
            src = h.headers.get("X-Copy-From", "")
            if src:
                s_cont, _, s_obj = src.lstrip("/").partition("/")
                data = self._read_object(s_cont, s_obj)
            else:
                data = gw._read_body(h)
            etag = hashlib.md5(data).hexdigest()
            vid = gw._store_object(container, obj, data, etag, bmeta)
            gw._notify_event(container, obj, "s3:ObjectCreated:Put",
                             len(data), etag, vid, bmeta)
            return gw._respond(h, 201, b"", "text/plain",
                               {"ETag": etag})
        ent = gw._index_entry(container, obj,
                              int(bmeta.get("shards", 1)))
        if ent is None:
            raise SwiftError(404, obj)
        if method in ("GET", "HEAD"):
            try:
                if method == "HEAD":
                    v, data = gw._select_version(ent, "", obj), None
                else:
                    v, data = gw._read_version_data(container, obj,
                                                    ent, "")
            except S3Error:
                raise SwiftError(404, obj)
            hdrs = {"ETag": v.get("etag", ""),
                    "X-Timestamp":
                        str(gw._parse_mtime(v.get("mtime", ""))),
                    "Last-Modified": v.get("mtime", "")}
            if method == "HEAD":
                hdrs["Content-Length"] = str(v.get("size", 0))
                return gw._respond(h, 200, b"",
                                   "application/octet-stream", hdrs)
            return gw._respond(h, 200, data,
                               "application/octet-stream", hdrs)
        if method == "DELETE":
            try:
                gw._select_version(ent, "", obj)
            except S3Error:
                # already deleted (dm head): Swift answers 404,
                # never stacks a second marker
                raise SwiftError(404, obj)
            # route through the S3 delete path: versioning semantics,
            # cls transaction, notification — then Swift's 204
            gw._delete_object(_NullResponder(), container, obj,
                              bmeta, ent, "")
            return gw._respond(h, 204, b"", "text/plain")
        raise SwiftError(405)

    def _read_object(self, container: str, obj: str) -> bytes:
        from .gateway import S3Error
        gw = self.gw
        if obj.startswith(gw.RESERVED_KEY_PREFIXES):
            raise SwiftError(404, f"{container}/{obj}")
        if container not in gw._buckets():
            raise SwiftError(404, container)
        ent = gw._index_entry(container, obj)
        if ent is None:
            raise SwiftError(404, f"{container}/{obj}")
        try:
            return gw._read_version_data(container, obj, ent, "")[1]
        except S3Error:
            raise SwiftError(404, obj)


class _NullResponder:
    """Absorbs the S3-shaped response of a reused handler so the
    Swift layer can send its own status/headers."""

    command = "NULL"

    class _Sink:
        @staticmethod
        def write(_data):
            pass

    wfile = _Sink()

    def send_response(self, *a):
        pass

    def send_header(self, *a):
        pass

    def end_headers(self):
        pass
