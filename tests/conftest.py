"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run on a virtual 8-device CPU platform (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).

Note: the axon sitecustomize sets jax.config jax_platforms='axon,cpu' at
interpreter start, so the JAX_PLATFORMS env var alone is NOT enough — we
must override the config value before any backend initializes.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()
