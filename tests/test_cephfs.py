"""cephfs-lite: MDS + client over RADOS (ref: src/mds, src/client;
dirfrag omap layout, journal replay, striped file data)."""
import pytest

from ceph_tpu.fs import CephFS, MDSDaemon
from ceph_tpu.fs.client import CephFSError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def fs_cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    mds = MDSDaemon(c.network, c.rados())
    mds.init()
    fs = CephFS(c.rados())
    yield c, mds, fs
    mds.shutdown()
    c.shutdown()


def test_namespace_crud(fs_cluster):
    _c, _mds, fs = fs_cluster
    fs.mkdir("/a")
    fs.mkdirs("/a/b/c")
    assert fs.listdir("/a") == ["b"]
    assert fs.listdir("/a/b") == ["c"]
    with pytest.raises(CephFSError, match="EEXIST"):
        fs.mkdir("/a")
    with pytest.raises(CephFSError, match="ENOENT"):
        fs.listdir("/nope")
    st = fs.stat("/a/b")
    assert st["type"] == "d"
    fs.rmdir("/a/b/c")
    assert fs.listdir("/a/b") == []
    with pytest.raises(CephFSError, match="ENOTEMPTY"):
        fs.rmdir("/a")


def test_file_io_striped(fs_cluster):
    c, _mds, fs = fs_cluster
    fs.mkdirs("/data")
    import numpy as np
    payload = np.random.default_rng(5).integers(
        0, 256, 300_000, dtype=np.uint8).tobytes()
    fs.write_file("/data/blob.bin", payload)
    assert fs.read_file("/data/blob.bin") == payload
    st = fs.stat("/data/blob.bin")
    assert st["type"] == "f" and st["size"] == len(payload)
    # partial read + overwrite + sparse hole
    fh = fs.open("/data/blob.bin")
    assert fh.read(1000, 500) == payload[1000:1500]
    fh = fs.open("/data/blob.bin", "r+")
    fh.write(100, b"PATCH")
    fh.close()
    patched = fs.read_file("/data/blob.bin")
    assert patched[100:105] == b"PATCH"
    assert patched[:100] == payload[:100]
    assert patched[105:] == payload[105:]
    # data is striped: more than one rados object holds the bytes
    io = fs.rados.open_ioctx("cephfs_data")
    ino = st["ino"]
    objs = [o for o in io.list_objects() if o.startswith(f"{ino:x}.")]
    assert len(objs) > 1


def test_open_w_truncates(fs_cluster):
    """POSIX O_TRUNC: rewriting a shorter payload over a longer file
    must not leave stale tail bytes (ref: Server::handle_client_openc
    truncate semantics)."""
    _c, _mds, fs = fs_cluster
    fs.mkdirs("/t")
    fs.write_file("/t/f", b"A" * 200_000)
    fs.write_file("/t/f", b"short")
    assert fs.read_file("/t/f") == b"short"
    assert fs.stat("/t/f")["size"] == 5
    # truncated tail objects are purged from the data pool
    io = fs.rados.open_ioctx("cephfs_data")
    ino = fs.stat("/t/f")["ino"]
    objs = [o for o in io.list_objects() if o.startswith(f"{ino:x}.")]
    assert len(objs) == 1
    # 'a' keeps existing bytes
    fh = fs.open("/t/f", "a")
    assert fh.size == 5


def test_rename_and_unlink(fs_cluster):
    _c, _mds, fs = fs_cluster
    fs.mkdirs("/r")
    fs.write_file("/r/one", b"1st")
    fs.rename("/r/one", "/r/two")
    assert not fs.exists("/r/one")
    assert fs.read_file("/r/two") == b"1st"
    # rename over an existing file replaces it
    fs.write_file("/r/three", b"3rd")
    fs.rename("/r/two", "/r/three")
    assert fs.read_file("/r/three") == b"1st"
    st = fs.stat("/r/three")
    fs.unlink("/r/three")
    assert not fs.exists("/r/three")
    # data objects purged
    io = fs.rados.open_ioctx("cephfs_data")
    ino = st["ino"]
    assert not [o for o in io.list_objects()
                if o.startswith(f"{ino:x}.")]
    with pytest.raises(CephFSError, match="ENOENT"):
        fs.unlink("/r/three")


def test_rename_self_and_subtree_guards(fs_cluster):
    _c, _mds, fs = fs_cluster
    fs.mkdirs("/g/sub")
    fs.write_file("/g/f", b"x")
    # POSIX: rename onto itself is a no-op, NOT a delete
    fs.rename("/g/f", "/g/f")
    assert fs.read_file("/g/f") == b"x"
    # a directory cannot move into its own subtree
    with pytest.raises(CephFSError, match="EINVAL"):
        fs.rename("/g", "/g/sub/g2")


def test_statfs(fs_cluster):
    _c, _mds, fs = fs_cluster
    s = fs.statfs()
    assert s["files"] >= 0 and s["dirs"] >= 2


def test_mds_journal_replay():
    """Kill the MDS mid-window (journal written, dirfrags not yet
    marked applied) — a restarted rank replays and converges
    (ref: MDLog::replay)."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        mds = MDSDaemon(c.network, c.rados())
        mds.init()
        fs = CephFS(c.rados())
        fs.mkdirs("/j/deep")
        fs.write_file("/j/deep/f", b"journaled")
        # simulate a crash BEFORE the applied_seq checkpoint: wipe the
        # dirfrag update for one entry by replaying from scratch — the
        # meta object still has an older applied_seq (APPLY_EVERY=8,
        # few ops done, so applied_seq persisted only at mkfs)
        mds.ms.shutdown()               # hard stop: no flush
        mds2 = MDSDaemon(c.network, c.rados())
        mds2.init()
        fs2 = CephFS(c.rados())
        assert fs2.listdir("/j/deep") == ["f"]
        assert fs2.read_file("/j/deep/f") == b"journaled"
        # allocator must not reuse inos after replay
        st_old = fs2.stat("/j/deep/f")
        fs2.write_file("/j/new", b"post-replay")
        assert fs2.stat("/j/new")["ino"] > st_old["ino"]
        mds2.shutdown()
    finally:
        c.shutdown()
