"""Manager daemon: cluster optimization services over the mon
(ref: src/mgr/, src/pybind/mgr/balancer)."""
from .daemon import MgrDaemon

__all__ = ["MgrDaemon"]
