"""Cross-gateway index safety: the version-stack RMW executes inside
the OSD (cls/rgw.py), so two radosgw processes over one pool can race
without losing records — the reference's cls_rgw contract
(ref: src/cls/rgw/cls_rgw.cc; VERDICT r4 weak #4)."""
import threading

from ceph_tpu.common.lockdep import make_lock
import urllib.request
from xml.etree import ElementTree as ET

import pytest

from ceph_tpu.rgw import RGWGateway
from ceph_tpu.testing import MiniCluster

VERS_ON = (b"<VersioningConfiguration>"
           b"<Status>Enabled</Status></VersioningConfiguration>")


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def two_gateways(cluster):
    """Two independent gateway instances — separate RADOS clients,
    separate HTTP servers, NO shared process state — on one pool."""
    g1 = RGWGateway(cluster.rados(), pool="rgwrace")
    g2 = RGWGateway(cluster.rados(), pool="rgwrace")
    g1.start()
    g2.start()
    yield g1, g2
    g1.shutdown()
    g2.shutdown()


def req(gw, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{gw.port}{path}",
                               data=data, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


def test_racing_versioned_puts_lose_nothing(two_gateways):
    """N concurrent PUTs to ONE key through TWO gateways must yield
    exactly N distinct version records."""
    g1, g2 = two_gateways
    req(g1, "PUT", "/race")
    req(g1, "PUT", "/race?versioning", VERS_ON)
    n_threads, per_thread = 8, 6
    vids, errs = [], []
    lock = make_lock("test.rgw_conc.puts")

    def worker(i):
        gw = (g1, g2)[i % 2]
        try:
            for j in range(per_thread):
                _, hdrs, _ = req(gw, "PUT", "/race/hot",
                                 f"w{i}.{j}".encode())
                with lock:
                    vids.append(hdrs["x-amz-version-id"])
        except Exception as e:            # noqa: BLE001
            with lock:
                errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(set(vids)) == n_threads * per_thread
    # every returned vid is actually in the committed stack
    _, _, body = req(g1, "GET", "/race?versions")
    listed = {e.text for e in ET.fromstring(body).iter()
              if e.tag == "VersionId"}
    assert set(vids) <= listed
    assert len(listed) == n_threads * per_thread


def test_racing_plain_puts_different_keys_one_shard(two_gateways):
    """Unversioned PUTs to distinct keys racing through both gateways
    keep every index entry (per-key omap values never clobber each
    other)."""
    g1, g2 = two_gateways
    req(g1, "PUT", "/race2")
    keys = [f"k{i}" for i in range(24)]

    def worker(i):
        gw = (g1, g2)[i % 2]
        req(gw, "PUT", f"/race2/{keys[i]}", b"x" * 10)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(keys))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _, _, body = req(g2, "GET", "/race2")
    listed = {e.text for e in ET.fromstring(body).iter()
              if e.tag == "Key"}
    assert listed == set(keys)


def test_delete_vs_put_race_stays_consistent(two_gateways):
    """Concurrent delete-marker inserts and PUTs through different
    gateways: the final stack contains every PUT's version and every
    returned marker vid — nothing vanishes."""
    g1, g2 = two_gateways
    req(g1, "PUT", "/race3")
    req(g1, "PUT", "/race3?versioning", VERS_ON)
    req(g1, "PUT", "/race3/obj", b"seed")
    put_vids, dm_vids = [], []
    lock = make_lock("test.rgw_conc.race3")

    def putter():
        for j in range(5):
            _, hdrs, _ = req(g1, "PUT", "/race3/obj", b"p%d" % j)
            with lock:
                put_vids.append(hdrs["x-amz-version-id"])

    def deleter():
        for _ in range(5):
            _, hdrs, _ = req(g2, "DELETE", "/race3/obj")
            with lock:
                dm_vids.append(hdrs["x-amz-version-id"])

    t1, t2 = (threading.Thread(target=putter),
              threading.Thread(target=deleter))
    t1.start(), t2.start()
    t1.join(), t2.join()
    _, _, body = req(g1, "GET", "/race3?versions")
    listed = {e.text for e in ET.fromstring(body).iter()
              if e.tag == "VersionId"}
    assert set(put_vids) <= listed
    assert set(dm_vids) <= listed
    assert len(listed) == 1 + len(put_vids) + len(dm_vids)
