"""CephFS snapshots — snaprealm-lite (VERDICT r3 #3; ref:
src/mds/SnapRealm.h, src/mds/snap.h, src/mds/SnapServer.cc,
Server::handle_client_mksnap): per-directory snap create/list/delete,
`.snap` path access through frozen dirfrags, data COW via the
self-managed snap machinery, snapc propagated on writes under a
realm."""
import threading
import time

import pytest

from ceph_tpu.fs import CephFS, MDSDaemon
from ceph_tpu.fs.client import CephFSError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def fscluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    mds = MDSDaemon(c.network, c.rados())
    mds.init()
    yield c, mds
    mds.shutdown()
    c.shutdown()


def _fs(c):
    return CephFS(c.rados())


def test_snap_freezes_data_and_size(fscluster):
    """write -> snap -> overwrite -> the snap serves the old bytes."""
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s1d")
    fs.write_file("/s1d/f", b"before the snapshot")
    fs.mksnap("/s1d", "epoch1")
    fs.write_file("/s1d/f", b"AFTER")          # truncates + rewrites
    assert fs.read_file("/s1d/f") == b"AFTER"
    assert fs.read_file("/s1d/.snap/epoch1/f") == b"before the snapshot"
    assert fs.stat("/s1d/.snap/epoch1/f")["size"] == \
        len(b"before the snapshot")


def test_snap_namespace_frozen(fscluster):
    """Files created/renamed/unlinked after the snap don't leak into
    it; the snapped namespace keeps serving deleted files' data."""
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s2d/sub")
    fs.write_file("/s2d/keep", b"kept bytes")
    fs.write_file("/s2d/gone", b"doomed bytes")
    fs.write_file("/s2d/sub/deep", b"deep bytes")
    fs.mksnap("/s2d", "frozen")
    fs.write_file("/s2d/newfile", b"post-snap")
    fs.unlink("/s2d/gone")
    fs.rename("/s2d/keep", "/s2d/renamed")
    names = set(fs.listdir("/s2d/.snap/frozen"))
    assert names == {"keep", "gone", "sub"}
    assert fs.read_file("/s2d/.snap/frozen/gone") == b"doomed bytes"
    assert fs.read_file("/s2d/.snap/frozen/keep") == b"kept bytes"
    assert fs.read_file("/s2d/.snap/frozen/sub/deep") == b"deep bytes"
    assert not fs.exists("/s2d/.snap/frozen/newfile")
    assert fs.exists("/s2d/renamed")


def test_snapdir_listing_and_lssnap(fscluster):
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s3d")
    fs.write_file("/s3d/x", b"x")
    fs.mksnap("/s3d", "a")
    fs.mksnap("/s3d", "b")
    assert set(fs.listdir("/s3d/.snap")) == {"a", "b"}
    assert set(fs.lssnap("/s3d")) == {"a", "b"}
    with pytest.raises(CephFSError):
        fs.mksnap("/s3d", "a")             # EEXIST
    fs.rmsnap("/s3d", "a")
    assert set(fs.listdir("/s3d/.snap")) == {"b"}
    with pytest.raises(CephFSError):
        fs.read_file("/s3d/.snap/a/x")     # ENOENT after rmsnap


def test_snapshots_read_only(fscluster):
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s4d")
    fs.write_file("/s4d/f", b"data")
    fs.mksnap("/s4d", "ro")
    for fn in (lambda: fs.write_file("/s4d/.snap/ro/f", b"no"),
               lambda: fs.unlink("/s4d/.snap/ro/f"),
               lambda: fs.mkdir("/s4d/.snap/ro/d"),
               lambda: fs.rename("/s4d/.snap/ro/f", "/s4d/z")):
        with pytest.raises(CephFSError) as ei:
            fn()
        assert ei.value.errno_name in ("EROFS",)
    # a read-mode handle works and refuses writes
    fh = fs.open("/s4d/.snap/ro/f", "r")
    assert fh.read(0) == b"data"
    with pytest.raises(CephFSError):
        fh.write(0, b"nope")
    fh.close()


def test_open_handle_cows_after_snap(fscluster):
    """A handle opened BEFORE the snap keeps writing after it; the
    snapc broadcast makes those writes COW, so the snap still reads
    the pre-snap state (the SnapRealm update path)."""
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s5d")
    fh = fs.open("/s5d/live", "w")
    fh.write(0, b"v1-original-bytes")
    fs.mksnap("/s5d", "mid")                 # flushes the EXCL size
    deadline = time.monotonic() + 5          # snapc push is async
    while time.monotonic() < deadline and \
            fh._io.write_snapc is None:
        time.sleep(0.02)
    assert fh._io.write_snapc is not None
    fh.write(0, b"V2-OVERWRITTEN!!!")
    fh.close()
    assert fs.read_file("/s5d/live") == b"V2-OVERWRITTEN!!!"
    assert fs.read_file("/s5d/.snap/mid/live") == b"v1-original-bytes"


def test_nested_realms_union_snapc(fscluster):
    """Snaps on an ancestor AND a descendant both cover a file; each
    realm's `.snap` shows its own frozen view."""
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s6d/inner")
    fs.write_file("/s6d/inner/f", b"gen0")
    fs.mksnap("/s6d", "outer0")
    fs.write_file("/s6d/inner/f", b"gen1")
    fs.mksnap("/s6d/inner", "inner1")
    fs.write_file("/s6d/inner/f", b"gen2")
    assert fs.read_file("/s6d/inner/f") == b"gen2"
    assert fs.read_file("/s6d/.snap/outer0/inner/f") == b"gen0"
    assert fs.read_file("/s6d/inner/.snap/inner1/f") == b"gen1"


def test_unlink_after_snap_preserves_snap_data(fscluster):
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s7d")
    fs.write_file("/s7d/victim", b"survives in the snap")
    fs.mksnap("/s7d", "pre")
    fs.unlink("/s7d/victim")
    assert not fs.exists("/s7d/victim")
    assert fs.read_file("/s7d/.snap/pre/victim") == \
        b"survives in the snap"


def test_concurrent_writers_and_snap(fscluster):
    """mksnap under concurrent writers: the snap captures a
    consistent prefix (every object readable, size frozen at the
    flushed value) and post-snap writes never leak into it."""
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/s8d")
    stop = threading.Event()

    def writer(idx):
        wfs = _fs(c)
        i = 0
        while not stop.is_set():
            try:
                wfs.write_file(f"/s8d/w{idx}", b"%05d" % i)
            except CephFSError:
                pass
            i += 1

    threads = [threading.Thread(target=writer, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        fs.mksnap("/s8d", "undertow", timeout=30.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    for name in fs.listdir("/s8d/.snap/undertow"):
        data = fs.read_file(f"/s8d/.snap/undertow/{name}")
        size = fs.stat(f"/s8d/.snap/undertow/{name}")["size"]
        assert len(data) == size           # frozen size is consistent
        assert data == b"" or (len(data) == 5 and data.isdigit())


def test_snapshots_survive_mds_crash_replay():
    """mksnap rides the MDS journal: a crashed MDS replays it and the
    snap (table + frozen dirfrags) is intact."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        mds = MDSDaemon(c.network, c.rados())
        mds.init()
        fs = _fs(c)
        fs.mkdirs("/crash")
        fs.write_file("/crash/f", b"pre-crash state")
        fs.mksnap("/crash", "s")
        fs.write_file("/crash/f", b"NEWER")
        # crash without the graceful shutdown flush
        mds.ms.shutdown()
        mds2 = MDSDaemon(c.network, c.rados())
        mds2.init()
        fs2 = _fs(c)
        assert set(fs2.lssnap("/crash")) == {"s"}
        assert fs2.read_file("/crash/.snap/s/f") == b"pre-crash state"
        assert fs2.read_file("/crash/f") == b"NEWER"
        mds2.shutdown()
    finally:
        c.shutdown()

def test_dotsnap_substring_names_unaffected(fscluster):
    """Only a literal `.snap` path COMPONENT is read-only — names
    merely containing the substring stay writable."""
    c, _ = fscluster
    fs = _fs(c)
    fs.mkdirs("/subst.snapdir")
    fs.write_file("/subst.snapdir/report.snapshot", b"writable")
    fs.write_file("/subst.snapdir/report.snapshot", b"rewritable")
    assert fs.read_file("/subst.snapdir/report.snapshot") == \
        b"rewritable"
    fs.rename("/subst.snapdir/report.snapshot", "/subst.snapdir/r2")
    fs.unlink("/subst.snapdir/r2")


def test_snapc_monotone_against_reordered_delivery(fscluster):
    """A late-arriving older snapc (reordered broadcast, or a sibling
    open whose MDS reply predates a mksnap) must not roll a handle —
    or the shared per-ino cache io — back to a stale seq (r5 advisor
    follow-up: snapc handling is order-sensitive)."""
    c, _mds = fscluster
    fs = _fs(c)
    fs.mkdirs("/mono")
    fh = fs.open("/mono/f", "w")
    fh.write(0, b"A" * 16)
    fh.fsync()
    fs.mksnap("/mono", "m1")
    fs.mksnap("/mono", "m2")
    time.sleep(0.3)                    # drain the broadcasts
    seq = fh._snapc_seq
    assert seq >= 2
    # simulate an out-of-order older broadcast: must be ignored
    fh.set_snapc({"seq": seq - 1, "snaps": []})
    assert fh._snapc_seq == seq
    # a sibling open (reply snapc can be stale in a real race) adopts
    # the per-ino merged context, never regressing the shared io
    fh2 = fs.open("/mono/f", "r+")
    assert fh2._snapc_seq >= seq
    assert fs._merge_snapc(fh.ino, None)["seq"] >= seq
    fh.close(); fh2.close()
