"""lrc plugin: layered locally-repairable codes.

Faithful re-implementation of the reference lrc plugin
(ref: src/erasure-code/lrc/ErasureCodeLrc.{h,cc}): the profile describes
a list of layers, each a (chunks-map string, sub-profile) pair; each
layer delegates its math to another registered plugin over the subset of
chunk positions its map marks 'D' (data) or 'c' (coding).  Repairing a
single lost chunk only needs the chunks of the *smallest* layer able to
recover it — the layered `_minimum_to_decode` (ErasureCodeLrc.cc:566)
walks layers from the most local upward.

The k/m/l shorthand (parse_kml, ErasureCodeLrc.cc:293) generates the
mapping, one global layer and (k+m)/l local layers, exactly like the
reference, so chunk layouts match byte-for-byte given the same
sub-plugin.
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Mapping

import numpy as np

from ..interface import (ErasureCode, ErasureCodeError, ErasureCodeProfile,
                         to_int)
from ..registry import ErasureCodePlugin

DEFAULT_KML = -1


@dataclass
class Layer:
    """One LRC layer (ErasureCodeLrc.h struct Layer)."""
    chunks_map: str
    profile: dict = field(default_factory=dict)
    data: list[int] = field(default_factory=list)
    coding: list[int] = field(default_factory=list)
    chunks: list[int] = field(default_factory=list)
    chunks_as_set: set = field(default_factory=set)
    erasure_code: object = None


@dataclass
class Step:
    """CRUSH rule step description (ErasureCodeLrc.h struct Step)."""
    op: str
    type: str
    n: int


def _json_loads(s: str):
    """json_spirit tolerates trailing commas in arrays; python json
    does not — normalize before parsing."""
    return json.loads(re.sub(r",\s*([\]}])", r"\1", s))


def _parse_str_map(s: str) -> dict:
    """A JSON object or 'k=v k=v' space-separated pairs
    (common/str_map get_json_str_map semantics)."""
    s = s.strip()
    if not s:
        return {}
    if s.startswith("{"):
        return {k: str(v) for k, v in json.loads(s).items()}
    out = {}
    for kv in s.split():
        if "=" not in kv:
            raise ErasureCodeError(f"expected k=v in {s!r}")
        k, v = kv.split("=", 1)
        out[k] = v
    return out


class ErasureCodeLrc(ErasureCode):
    def __init__(self) -> None:
        super().__init__()
        self.layers: list[Layer] = []
        self.chunk_count_ = 0
        self.data_chunk_count_ = 0
        self.rule_steps = [Step("chooseleaf", "host", 0)]

    # -- interface ----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.chunk_count_

    def get_data_chunk_count(self) -> int:
        return self.data_chunk_count_

    def get_chunk_size(self, object_size: int) -> int:
        # ref: ErasureCodeLrc.cc:559-562
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- init ---------------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse_kml(profile)
        self.parse(profile)
        layers_str = profile.get("layers")
        if layers_str is None:
            raise ErasureCodeError("could not find 'layers' in profile")
        try:
            description = _json_loads(layers_str)
        except ValueError as e:
            raise ErasureCodeError(
                f"failed to parse layers={layers_str!r}: {e}") from e
        if not isinstance(description, list):
            raise ErasureCodeError(
                f"layers={layers_str!r} must be a JSON array")
        self.layers_parse(description)
        self.layers_init()
        mapping = profile.get("mapping")
        if mapping is None:
            raise ErasureCodeError("the 'mapping' profile is missing")
        self.data_chunk_count_ = mapping.count("D")
        self.chunk_count_ = len(mapping)
        self.layers_sanity_checks(layers_str)
        # kml-generated parameters are not exposed back to the caller
        # (ErasureCodeLrc.cc:539-544)
        if profile.get("l") not in (None, str(DEFAULT_KML)):
            profile.pop("mapping", None)
            profile.pop("layers", None)
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.parse_rule(profile)

    def parse_kml(self, profile: ErasureCodeProfile) -> None:
        """Generate mapping/layers/crush steps from k, m, l
        (ref: ErasureCodeLrc.cc:293-397)."""
        super().parse(profile)
        k = to_int("k", profile, str(DEFAULT_KML))
        m = to_int("m", profile, str(DEFAULT_KML))
        lv = to_int("l", profile, str(DEFAULT_KML))
        if k == DEFAULT_KML and m == DEFAULT_KML and lv == DEFAULT_KML:
            return
        if DEFAULT_KML in (k, m, lv):
            raise ErasureCodeError(
                "All of k, m, l must be set or none of them")
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise ErasureCodeError(
                    f"The {generated} parameter cannot be set "
                    "when k, m, l are set")
        if lv == 0 or (k + m) % lv:
            raise ErasureCodeError("k + m must be a multiple of l")
        local_group_count = (k + m) // lv
        if k % local_group_count:
            raise ErasureCodeError("k must be a multiple of (k + m) / l")
        if m % local_group_count:
            raise ErasureCodeError("m must be a multiple of (k + m) / l")
        kd = k // local_group_count
        md = m // local_group_count
        profile["mapping"] = ("D" * kd + "_" * md + "_") * local_group_count
        layers = "[ "
        # global layer
        layers += ' [ "' + ("D" * kd + "c" * md + "_") * local_group_count \
            + '", "" ],'
        # local layers
        for i in range(local_group_count):
            layers += ' [ "'
            for j in range(local_group_count):
                layers += ("D" * lv + "c") if i == j else "_" * (lv + 1)
            layers += '", "" ],'
        profile["layers"] = layers + "]"
        rule_locality = profile.get("crush-locality", "")
        rule_failure_domain = profile.get("crush-failure-domain", "host")
        if rule_locality:
            self.rule_steps = [
                Step("choose", rule_locality, local_group_count),
                Step("chooseleaf", rule_failure_domain, lv + 1)]
        elif rule_failure_domain:
            self.rule_steps = [Step("chooseleaf", rule_failure_domain, 0)]

    def parse_rule(self, profile: ErasureCodeProfile) -> None:
        """ref: ErasureCodeLrc.cc:399-451."""
        self.rule_root = profile.setdefault("crush-root", "default")
        self.rule_device_class = profile.setdefault("crush-device-class", "")
        steps_str = profile.get("crush-steps")
        if steps_str is not None:
            try:
                description = _json_loads(steps_str)
            except ValueError as e:
                raise ErasureCodeError(
                    f"failed to parse crush-steps={steps_str!r}: {e}") from e
            if not isinstance(description, list):
                raise ErasureCodeError("crush-steps must be a JSON array")
            self.rule_steps = []
            for stp in description:
                if not (isinstance(stp, list) and len(stp) >= 3 and
                        isinstance(stp[0], str) and isinstance(stp[1], str)
                        and isinstance(stp[2], int)):
                    raise ErasureCodeError(
                        f"bad crush-steps element {stp!r} "
                        "(expected [op, type, n])")
                self.rule_steps.append(Step(stp[0], stp[1], stp[2]))

    def layers_parse(self, description: list) -> None:
        """ref: ErasureCodeLrc.cc:143-211."""
        for position, layer_json in enumerate(description):
            if not isinstance(layer_json, list):
                raise ErasureCodeError(
                    f"layers element at position {position} must be a "
                    f"JSON array, got {layer_json!r}")
            if not layer_json or not isinstance(layer_json[0], str):
                raise ErasureCodeError(
                    f"the first element of layer {position} must be "
                    "a string (the chunks map)")
            layer = Layer(chunks_map=layer_json[0])
            if len(layer_json) > 1:
                second = layer_json[1]
                if isinstance(second, str):
                    layer.profile = _parse_str_map(second)
                elif isinstance(second, dict):
                    layer.profile = {k: str(v) for k, v in second.items()}
                else:
                    raise ErasureCodeError(
                        f"the second element of layer {position} must be "
                        "a string or object")
            # trailing elements ignored, like the reference
            self.layers.append(layer)

    def layers_init(self) -> None:
        """ref: ErasureCodeLrc.cc:213-250."""
        from ..registry import ErasureCodePluginRegistry
        registry = ErasureCodePluginRegistry.instance()
        for layer in self.layers:
            for position, c in enumerate(layer.chunks_map):
                if c == "D":
                    layer.data.append(position)
                if c == "c":
                    layer.coding.append(position)
                if c in ("c", "D"):
                    layer.chunks_as_set.add(position)
            layer.chunks = layer.data + layer.coding
            layer.profile.setdefault("k", str(len(layer.data)))
            layer.profile.setdefault("m", str(len(layer.coding)))
            layer.profile.setdefault("plugin", "jerasure")
            layer.profile.setdefault("technique", "reed_sol_van")
            layer.erasure_code = registry.factory(
                layer.profile["plugin"], layer.profile)

    def layers_sanity_checks(self, description_string: str) -> None:
        """ref: ErasureCodeLrc.cc:252-279."""
        if len(self.layers) < 1:
            raise ErasureCodeError(
                f"layers parameter has {len(self.layers)} which is less "
                f"than the minimum of one: {description_string}")
        for layer in self.layers:
            if self.chunk_count_ != len(layer.chunks_map):
                raise ErasureCodeError(
                    f"the layer '{layer.chunks_map}' is expected to be "
                    f"{self.chunk_count_} characters long but is "
                    f"{len(layer.chunks_map)} characters long instead")

    # -- minimum_to_decode --------------------------------------------------
    def _minimum_to_decode(self, want_to_read: set, available_chunks: set
                           ) -> set:
        """Layered cheapest-repair walk (ref: ErasureCodeLrc.cc:566-735)."""
        erasures_total = set()
        erasures_not_recovered = set()
        erasures_want = set()
        for i in range(self.get_chunk_count()):
            if i not in available_chunks:
                erasures_total.add(i)
                erasures_not_recovered.add(i)
                if i in want_to_read:
                    erasures_want.add(i)

        # Case 1: nothing wanted is missing
        if not erasures_want:
            return set(want_to_read)

        # Case 2: recover wanted erasures with as few chunks as possible,
        # walking layers from the most local (last) upward
        minimum: set = set()
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                layer_minimum = layer_want
            else:
                erasures = layer.chunks_as_set & erasures_not_recovered
                if len(erasures) > \
                        layer.erasure_code.get_coding_chunk_count():
                    # too many erasures for this layer: hope upward
                    continue
                layer_minimum = layer.chunks_as_set - erasures_not_recovered
                for j in erasures:
                    erasures_not_recovered.discard(j)
                    erasures_want.discard(j)
            minimum |= layer_minimum
        if not erasures_want:
            minimum |= set(want_to_read)
            minimum -= erasures_total
            return minimum

        # Case 3: recover as many chunks as possible even from layers
        # without wanted chunks, hoping it unlocks upper layers
        erasures_total = {i for i in range(self.get_chunk_count())
                          if i not in available_chunks}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= \
                    layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available_chunks)

        raise ErasureCodeError(
            f"EIO: not enough chunks in {sorted(available_chunks)} to "
            f"read {sorted(want_to_read)}")

    # -- local-group repair -------------------------------------------------
    #: weight multiplier for reads outside the wanted chunk's local
    #: parity group in minimum_to_decode_with_cost — a cross-group read
    #: crosses a CRUSH fault domain when crush-locality maps groups to
    #: domains (see parse_kml/create_rule), so it is charged like the
    #: slower, blast-radius-expanding read it is
    CROSS_GROUP_COST = 4

    def local_layer(self, chunk: int):
        """The smallest layer containing `chunk` — for kml profiles,
        its local parity group; the global layer only when no local
        layer covers the chunk."""
        best = None
        for layer in self.layers:
            if chunk in layer.chunks_as_set and (
                    best is None
                    or len(layer.chunks_as_set) < len(best.chunks_as_set)):
                best = layer
        return best

    def _repair_layer(self, chunk: int, available: set):
        """Smallest layer that can rebuild `chunk` from available
        survivors, or None."""
        best = None
        for layer in self.layers:
            if chunk not in layer.chunks_as_set:
                continue
            erased = layer.chunks_as_set - set(available) \
                - {chunk} | {chunk}
            if len(erased) > layer.erasure_code.get_coding_chunk_count():
                continue
            if best is None or \
                    len(layer.chunks_as_set) < len(best.chunks_as_set):
                best = layer
        return best

    def is_repair(self, want_to_read: set, available_chunks: set) -> bool:
        """True when the single wanted erasure rebuilds from a local
        parity group smaller than a k-survivor decode (l << k reads)."""
        want = set(want_to_read)
        if len(want) != 1 or want <= set(available_chunks):
            return False
        layer = self._repair_layer(next(iter(want)),
                                   set(available_chunks))
        return layer is not None and \
            len(layer.chunks_as_set & set(available_chunks)) < \
            self.get_data_chunk_count()

    def minimum_to_repair(self, want_to_read: set, available_chunks: set
                          ) -> dict[int, list[tuple[int, int]]]:
        """The lost chunk's local-group survivors, whole chunks each
        (lrc has no sub-chunk granularity — the saving is reading
        l << k chunks, not partial chunks)."""
        want = set(want_to_read)
        avail = set(available_chunks)
        lost = next(iter(want))
        layer = self._repair_layer(lost, avail)
        if layer is None:
            raise ErasureCodeError(
                f"minimum_to_repair: no layer can rebuild {lost} from "
                f"{sorted(avail)}")
        return {c: [(0, 1)] for c in layer.chunks_as_set & avail}

    def repair_schedule(self, erasures: set, available: set):
        """Single-erasure LRC plan: the local group's l survivors,
        full chunks."""
        erasures = set(erasures)
        available = set(available) - erasures
        if not self.is_repair(erasures, available):
            return None
        from ..repairc import RepairPlan
        return RepairPlan.make(
            erasures, self.minimum_to_repair(erasures, available),
            sub_chunk_no=1)

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set:
        """Cost-weighted survivor choice: reads outside the wanted
        chunks' local parity groups are charged CROSS_GROUP_COST x
        their supplied cost, so degraded reads prefer in-group
        survivors (the base class charges every read the same)."""
        want = set(want_to_read)
        avail = set(available)
        costs = dict(available) if isinstance(available, Mapping) else {}
        home: set = set()
        for c in want:
            layer = self.local_layer(c)
            if layer is not None:
                home |= layer.chunks_as_set
        candidates = [self._minimum_to_decode(want, avail)]
        lost = want - avail
        if len(lost) == 1:
            layer = self._repair_layer(next(iter(lost)), avail)
            if layer is not None:
                candidates.append(
                    (layer.chunks_as_set & avail) | (want & avail))

        def total(chunks: set) -> int:
            return sum(
                costs.get(c, 1) * (1 if c in home
                                   else self.CROSS_GROUP_COST)
                for c in chunks)

        return min(candidates, key=total)

    # -- encode / decode ----------------------------------------------------
    def encode_chunks(self, want_to_encode, encoded) -> None:
        """ref: ErasureCodeLrc.cc:737-775."""
        want = set(want_to_encode)
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if want <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_want = set()
            layer_encoded = {}
            for j, c in enumerate(layer.chunks):
                layer_encoded[j] = encoded[c]
                if c in want:
                    layer_want.add(j)
            layer.erasure_code.encode_chunks(layer_want, layer_encoded)
            for j, c in enumerate(layer.chunks):
                encoded[c] = layer_encoded[j]

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        """ref: ErasureCodeLrc.cc:777-860."""
        want = set(want_to_read)
        available = set()
        erasures = set()
        for i in range(self.get_chunk_count()):
            if i in chunks:
                available.add(i)
            else:
                erasures.add(i)

        want_to_read_erasures: set = set()
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if len(layer_erasures) > \
                    layer.erasure_code.get_coding_chunk_count():
                continue  # too many erasures for this layer
            if not layer_erasures:
                continue  # all chunks already available
            layer_want = set()
            layer_chunks = {}
            layer_decoded = {}
            for j, c in enumerate(layer.chunks):
                # pick from *decoded* so chunks recovered by previous
                # layers are reused (ErasureCodeLrc.cc:806-815)
                if c not in erasures:
                    layer_chunks[j] = decoded[c]
                if c in want:
                    layer_want.add(j)
                layer_decoded[j] = decoded[c]
            layer.erasure_code.decode_chunks(layer_want, layer_chunks,
                                             layer_decoded)
            for j, c in enumerate(layer.chunks):
                decoded[c] = layer_decoded[j]
                erasures.discard(c)
            want_to_read_erasures = erasures & want
            if not want_to_read_erasures:
                break

        if want_to_read_erasures:
            raise ErasureCodeError(
                f"EIO: want to read {sorted(want)} with available "
                f"{sorted(available)} end up unable to read "
                f"{sorted(want_to_read_erasures)}")

    # -- crush rule ---------------------------------------------------------
    def create_rule(self, name: str, crush) -> int:
        """Multi-step rule from rule_steps
        (ref: ErasureCodeLrc.cc:44-112)."""
        from ...crush.types import (
            CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_INDEP,
            CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushRule, CrushRuleMask,
            CrushRuleStep)
        root = crush.get_item_id(self.rule_root)
        if root is None:
            raise ErasureCodeError(
                f"root item {self.rule_root} does not exist")
        steps = [CrushRuleStep(CRUSH_RULE_TAKE, root, 0)]
        for step in self.rule_steps:
            if step.op == "choose":
                op = CRUSH_RULE_CHOOSE_INDEP
            elif step.op == "chooseleaf":
                op = CRUSH_RULE_CHOOSELEAF_INDEP
            else:
                raise ErasureCodeError(
                    f"unknown crush-steps op {step.op!r} (want choose or "
                    "chooseleaf)")
            tid = crush.get_type_id(step.type)
            if tid < 0:
                raise ErasureCodeError(f"unknown type {step.type}")
            steps.append(CrushRuleStep(op, step.n, tid))
        steps.append(CrushRuleStep(CRUSH_RULE_EMIT, 0, 0))
        rule = CrushRule(steps=steps,
                         mask=CrushRuleMask(
                             ruleset=len(crush.crush.rules), type=3,
                             max_size=max(10, self.get_chunk_count())))
        crush.crush.rules.append(rule)
        rid = len(crush.crush.rules) - 1
        crush.rule_name_map[rid] = name
        return rid


PLUGIN = ErasureCodePlugin("lrc", ErasureCodeLrc)
