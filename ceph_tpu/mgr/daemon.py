"""MgrDaemon: the balancer loop as a wire citizen.

The mgr shape (ref: src/mgr/Mgr.cc + the balancer module's serve loop,
src/pybind/mgr/balancer/module.py:340 serve -> optimize -> execute):
subscribe to osdmaps, periodically run the upmap optimizer against the
current map, and submit the resulting pg-upmap-items commands to the
mon, which commits them and publishes the new epoch back.

The optimizer itself is ceph_tpu.osd.balancer (calc_pg_upmaps over the
batched vmapped mapping tables) — the mgr is the scheduling/command
glue around it.
"""
from __future__ import annotations

import itertools
import threading

from ..common.lockdep import make_lock

from ..common.log import dout
from ..common.racecheck import shared_state
from ..common.options import global_config
from ..msg.messages import (MMap, MMgrCommand, MMgrCommandReply,
                            MMonCommand, MMonCommandAck,
                            MMonSubscribe)
from ..msg.mon_client import MonHunter
from ..msg.messenger import Dispatcher, LocalNetwork, Message, Messenger
from ..osd.balancer import Balancer
from ..osd.osdmap import OSDMap


# module state shared between the dispatch thread (command replies,
# map ingest) and the observability/balancer tick — racecheck asserts
# every access holds self._lock
@shared_state(only=("_health_reports", "_pending", "_sync_cmds"),
              mutating=("_health_reports", "_pending", "_sync_cmds"))
class MgrDaemon(Dispatcher, MonHunter):
    def __init__(self, network: LocalNetwork, rank: int = 0,
                 mon="mon.0", threaded: bool = False,
                 max_deviation: int = 1, max_iterations: int = 100):
        self.name = f"mgr.{rank}"
        self._init_mons(mon)
        self.osdmap = OSDMap()
        self.active = True
        self.balancer = Balancer(max_deviation=max_deviation,
                                 max_iterations=max_iterations)
        self.last_optimize: dict = {}
        self._tid = itertools.count(1)
        self._pending: set[int] = set()       # unacked command tids
        self._sync_cmds: dict = {}            # tid -> (Event, slot)
        self.prometheus = None
        #: restful admin API (ref: pybind/mgr/restful); start_restful
        self.restful = None
        self.failed_commands = 0
        #: pg_autoscaler module (ref: pybind/mgr/pg_autoscaler);
        #: enable with start_pg_autoscaler(), driven by autoscale_tick
        self.pg_autoscaler = None
        #: progress module (ref: pybind/mgr/progress); enable with
        #: start_progress(), driven by progress_tick
        self.progress = None
        #: devicehealth module (ref: pybind/mgr/devicehealth); enable
        #: with start_devicehealth(), driven by devicehealth_tick
        self.devicehealth = None
        #: observability modules (ref: pybind/mgr/crash, telemetry,
        #: insights); enable with start_crash()/start_telemetry()/
        #: start_insights(), driven by observability_tick
        self.crash = None
        self.telemetry = None
        self.insights = None
        #: per-module health-check slices, merged into ONE volatile
        #: `mgr health report` so modules never clobber each other
        self._health_reports: dict[str, dict] = {}
        self._lock = make_lock(f"mgr.{self.name}")
        # op tracking + span ring: module commands proxied from the
        # mon are tracked like any daemon's ops (ref: the mgr's
        # DaemonServer op tracking), and the mgr serves the shared
        # dump_ops_in_flight/dump_traces admin surface
        from ..common.options import global_config
        from ..common.tracked_op import OpTracker
        from ..common.tracing import Tracer
        self.op_tracker = OpTracker(
            history_size=global_config()["osd_op_history_size"])
        self.tracer = Tracer(self.name)
        # internal thread-liveness watchdog (the OSD's hbmap, here for
        # the mgr's observability loop): arms on the first
        # observability_tick; a stalled loop surfaces through the
        # module-health path as HEARTBEAT_STALE and in `status`
        from ..common.heartbeat_map import HeartbeatMap
        self.hbmap = HeartbeatMap()
        self._hb_handle = self.hbmap.add_worker(
            f"{self.name}.observability", grace=60.0, arm=False)
        self.asok = None
        self.ms = Messenger.create(network, self.name, threaded=threaded)
        self.ms.add_dispatcher(self)
        # own-crash capture: the mgr posts its reports over the wire
        # like any other daemon
        from ..common.crash import CrashReporter
        self.crash_reporter = CrashReporter(
            self.name, post=self._post_crash_meta)
        self.ms.crash_hook = self.crash_reporter.capture

    def _hunt_greeting(self) -> list:
        return [MMonSubscribe(what="osdmap",
                              start=self.osdmap.epoch + 1),
                MMonCommand(tid=0, cmd={"prefix": "mgr register",
                                        "name": self.name})]

    def ms_handle_reset(self, peer: str) -> None:
        self._maybe_hunt(peer)

    # ------------------------------------------------------------ setup
    def init(self) -> None:
        self.ms.start()
        self.ms.connect(self.mon).send_message(
            MMonSubscribe(what="osdmap", start=1))
        self._register_mgr()

    def _register_mgr(self) -> None:
        """Announce ourselves as the active mgr to EVERY mon — module
        commands (telemetry/insights) may arrive at any of them and
        each proxies from its own volatile registration (re-sent every
        observability tick; ref: MgrMonitor beacons)."""
        for m in self.mons:
            self.ms.connect(m).send_message(MMonCommand(
                tid=0, cmd={"prefix": "mgr register",
                            "name": self.name}))

    def _post_crash_meta(self, meta: dict) -> None:
        self._command({"prefix": "crash post", "meta": meta})

    def shutdown(self) -> None:
        if self.prometheus is not None:
            self.prometheus.shutdown()
        if getattr(self, "restful", None) is not None:
            self.restful.shutdown()
        if self.asok is not None:
            self.asok.shutdown()
            self.asok = None
        self.ms.shutdown()

    def start_admin_socket(self, path: str) -> None:
        """`ceph daemon mgr.N <cmd>` endpoint."""
        from ..common.admin_socket import AdminSocket
        from ..common.obs import register_obs_commands
        a = AdminSocket(path)
        register_obs_commands(a, self.op_tracker, self.tracer)
        a.register("status", "daemon status",
                   lambda c: (0, self.status()))
        a.start()
        self.asok = a

    # -------------------------------------------------------- dispatch
    def ms_dispatch(self, msg: Message) -> bool:
        if isinstance(msg, MMap):
            with self._lock:
                self.osdmap = self.osdmap.ingest(msg.full_map,
                                                 msg.incrementals)
            return True
        if isinstance(msg, MMonCommandAck):
            with self._lock:
                self._pending.discard(msg.tid)
                entry = self._sync_cmds.pop(msg.tid, None)
                if msg.result != 0 and entry is None:
                    self.failed_commands += 1
                    dout("mgr", 0).write(
                        "%s: mon command failed (%d): %s", self.name,
                        msg.result, msg.outs)
            if entry is not None:
                ev, slot = entry
                slot.update(r=msg.result, outs=msg.outs,
                            outb=msg.outb)
                ev.set()
            return True
        if isinstance(msg, MMgrCommand):
            # mon-proxied module command; answer the MON (it relays to
            # the client).  Handlers run on the dispatch thread, so
            # they answer from module-cached state only — a sync
            # mon_command here would deadlock on our own ack.
            opkey = (msg.src, msg.tid)
            self.op_tracker.start(
                opkey, f"module_command({msg.src} tid={msg.tid} "
                       f"{msg.cmd.get('prefix', '?')})")
            r, outs, outb = self._handle_module_command(msg.cmd)
            self.op_tracker.finish(opkey,
                                   "replied" if r == 0 else f"r={r}")
            self.ms.connect(msg.src).send_message(MMgrCommandReply(
                tid=msg.tid, result=r, outs=outs, outb=outb))
            return True
        return False

    def _handle_module_command(self, cmd: dict
                               ) -> tuple[int, str, object]:
        pfx = str(cmd.get("prefix", ""))
        root = pfx.split(" ", 1)[0]
        try:
            if root == "telemetry":
                if self.telemetry is None:
                    return -2, "telemetry module not enabled", None
                return self.telemetry.handle_command(cmd)
            if root == "insights":
                if self.insights is None:
                    return -2, "insights module not enabled", None
                return self.insights.handle_command(cmd)
        except (KeyError, ValueError, TypeError) as ex:
            return -22, f"invalid command arguments: {ex}", None
        except Exception as ex:
            # a broken module handler must still ANSWER: with no reply
            # the client blocks out its 30s deadline and the mon's
            # _mgr_proxy entry for this tid leaks until our connection
            # resets
            dout("mgr", 0).write("%s: module command %r failed: %s",
                                 self.name, pfx, ex)
            return -5, f"module command failed: {ex}", None
        return -22, f"unknown mgr command {pfx!r}", None

    def mon_command(self, cmd: dict,
                    timeout: float = 30.0) -> tuple[int, str, object]:
        """Synchronous round-trip (the prometheus module's command
        channel)."""
        tid = next(self._tid)
        ev, slot = threading.Event(), {}
        with self._lock:
            self._sync_cmds[tid] = (ev, slot)
        self.ms.connect(self.mon).send_message(
            MMonCommand(tid=tid, cmd=cmd))
        if not ev.wait(timeout):
            with self._lock:
                self._sync_cmds.pop(tid, None)
            raise TimeoutError(f"mon command {cmd.get('prefix')!r}")
        return slot["r"], slot["outs"], slot["outb"]

    def start_pg_autoscaler(self, **kw):
        from .pg_autoscaler import PGAutoscaler
        self.pg_autoscaler = PGAutoscaler(self, **kw)
        return self.pg_autoscaler

    def autoscale_tick(self, pool_bytes: dict | None = None) -> int:
        """One pg_autoscaler round (scheduled alongside the balancer
        tick the way the reference's module serve loops both run)."""
        if self.pg_autoscaler is None:
            return 0
        with self._lock:
            return self.pg_autoscaler.tick(pool_bytes)

    def start_progress(self):
        """Track long-running operations (ref: pybind/mgr/progress)."""
        from .progress import ProgressModule
        self.progress = ProgressModule(self)
        return self.progress

    def start_devicehealth(self):
        """Device media-error health (ref: pybind/mgr/devicehealth)."""
        from .devicehealth import DeviceHealth
        self.devicehealth = DeviceHealth(self)
        return self.devicehealth

    def devicehealth_tick(self) -> None:
        if getattr(self, "devicehealth", None) is not None:
            self.devicehealth.tick()

    def progress_tick(self) -> int:
        if self.progress is None:
            return 0
        return self.progress.tick()

    def start_crash(self, **kw):
        """Crash-report health agent (ref: pybind/mgr/crash)."""
        from .crash import CrashModule
        self.crash = CrashModule(self, **kw)
        return self.crash

    def start_telemetry(self, **kw):
        """Anonymized cluster report (ref: pybind/mgr/telemetry)."""
        from .telemetry import TelemetryModule
        self.telemetry = TelemetryModule(self, **kw)
        return self.telemetry

    def start_insights(self, **kw):
        """Time-windowed cluster snapshot (ref: pybind/mgr/insights)."""
        from .insights import InsightsModule
        self.insights = InsightsModule(self, **kw)
        return self.insights

    def set_health_checks(self, module: str, checks: dict) -> None:
        """Replace one module's health-check slice and push the MERGED
        report to the mon (ref: MgrModule.set_health_checks — each
        module owns its slice; the wholesale `mgr health report` wire
        contract stays intact)."""
        with self._lock:
            if checks:
                self._health_reports[module] = dict(checks)
            else:
                self._health_reports.pop(module, None)
            merged: dict = {}
            for part in self._health_reports.values():
                merged.update(part)
        self.mon_command({"prefix": "mgr health report",
                          "checks": merged})

    def observability_tick(self, now: float | None = None) -> None:
        """One observability round: refresh the volatile mgr
        registration on every mon, then tick crash (RECENT_CRASH
        health), insights (history rings), and telemetry (report
        compile) — the serve-loop slice the reference modules run in
        their own threads."""
        self.hbmap.reset_timeout(self._hb_handle)
        self._register_mgr()
        if self.crash is not None:
            self.crash.tick(now)
        if self.insights is not None:
            self.insights.tick(now)
        if self.telemetry is not None:
            self.telemetry.tick(now)
        # liveness slice: unhealthy workers ride the same volatile
        # module-health report every other mgr module uses (cleared
        # the moment the worker beats again)
        self.set_health_checks("hbmap", self.hbmap.health_check())

    def start_prometheus(self, port: int = 0):
        """Serve /metrics (ref: pybind/mgr/prometheus).  Exports
        progress events too when the progress module is running."""
        from .prometheus import PrometheusExporter
        # late-bound: progress may start before OR after the exporter
        self.prometheus = PrometheusExporter(
            self.mon_command, port=port,
            progress_ls=lambda: (self.progress.ls()
                                 if self.progress is not None else []),
            device_ls=lambda: (self.devicehealth.ls()
                               if self.devicehealth is not None
                               else []))
        self.prometheus.start()
        return self.prometheus

    def start_restful(self, port: int = 0):
        """Serve the JSON admin API (ref: pybind/mgr/restful)."""
        from .restful import RestfulServer
        self.restful = RestfulServer(self, port=port)
        self.restful.start()
        return self.restful

    # ------------------------------------------------------- balancing
    def tick(self) -> int:
        """One balancer round: optimize the current map and submit the
        upmap commands (ref: balancer module.py execute :1450 —
        pg-upmap-items mon commands per plan item).  Returns the number
        of commands submitted."""
        with self._lock:
            if not self.active or self.osdmap.epoch == 0 or \
                    not self.osdmap.pools:
                return 0
            inc = self.balancer.optimize(self.osdmap)
            rm = [str(pg) for pg in sorted(inc.old_pg_upmap_items)]
            set_ = [(str(pg), items) for pg, items in
                    sorted(inc.new_pg_upmap_items.items())]
            sent = len(rm) + len(set_)
            if sent:
                # one batched command = one map epoch for the whole
                # plan (an epoch per item would fan N incrementals to
                # every subscriber)
                self._command({"prefix": "osd upmap-batch",
                               "rm": rm, "set": set_})
            self.last_optimize = {
                "epoch": self.osdmap.epoch,
                "commands": sent,
            }
            if sent:
                dout("mgr", 1).write("%s: submitted %d upmap changes "
                                     "at e%d", self.name, sent,
                                     self.osdmap.epoch)
            return sent

    def _command(self, cmd: dict) -> None:
        tid = next(self._tid)
        self._pending.add(tid)
        self.ms.connect(self.mon).send_message(
            MMonCommand(tid=tid, cmd=cmd))

    def status(self) -> dict:
        """(ref: `ceph balancer status`)."""
        with self._lock:
            score = self.balancer.score(self.osdmap) \
                if self.osdmap.pools else {}
            return {"active": self.active,
                    "mode": "upmap",
                    "epoch": self.osdmap.epoch,
                    "last_optimize": dict(self.last_optimize),
                    "hbmap_unhealthy":
                        self.hbmap.get_unhealthy_workers(),
                    "score": {k: score.get(k)
                              for k in ("stddev", "max_deviation")}}
