"""ArtifactStore: paged model artifacts (checkpoints, KV-cache page
pools) on RADOS.

The serving workload (ref: Ragged Paged Attention, arxiv 2604.15464)
needs two access patterns from the same bytes:

* **checkpoint streaming** — N readers each pull a shard front to
  back as fast as the pool allows; sequential readahead wins.
* **KV-cache page gets** — ragged lists of page ids in attention
  order, latency-bound; readahead is waste, residency is managed by
  the caller (pin/unpin), and the fetch must be ONE parallel aio
  wave, not a read-per-page loop (the SSD-array EC study, arxiv
  1709.05365: small-op amplification dominates at scale).

Layout: shard bytes are a fixed page grid striped over epoch-
versioned objects by the osdc Striper; the manifest (see
manifest.py) is the commit point.  Because data objects are
immutable once the manifest names them, the page wave submits its
reads `unordered` — the objecter's per-object ordering would
serialize N same-object reads that have nothing to order.
"""
from __future__ import annotations

import contextlib
import logging

from ..client.rados import IoCtx, RadosError
from ..common.options import global_config
from ..common.tracing import Tracer, child_of, current_trace, \
    new_trace, trace_scope
from ..osdc.object_cacher import ObjectCacher
from ..osdc.striper import StripeLayout, Striper
from .manifest import ArtifactManifest, ShardInfo, data_oid, \
    manifest_oid, paginate, shard_from_pages

#: default artifact page (KV block / fetch granule) — 64 KiB, the
#: ObjectCacher's native page size
DEFAULT_PAGE = 1 << 16


def default_layout(page_size: int = DEFAULT_PAGE) -> StripeLayout:
    """Stripe pages over 2 objects per set, 4 pages per stripe unit:
    wide enough that a stream fans out and a page wave spreads over
    PGs, small enough that tests stay cheap."""
    return StripeLayout(stripe_unit=4 * page_size, stripe_count=2,
                        object_size=16 * page_size)


class ArtifactStore:
    """Pool-level artifact catalog + page fetch engine."""

    def __init__(self, ioctx: IoCtx, page_size: int = DEFAULT_PAGE,
                 layout: StripeLayout | None = None):
        self.io = ioctx
        self.page_size = page_size
        self.layout = layout or default_layout(page_size)
        self.layout.validate()
        self.tracer = Tracer("serve")

    # ------------------------------------------------------------ write
    def put(self, name: str,
            shards: dict[str, bytes] | None = None,
            pages: dict[str, list[bytes]] | None = None
            ) -> ArtifactManifest:
        """Publish an artifact.  `shards` maps shard name -> byte
        stream (checkpoint shards: pages full except a ragged tail);
        `pages` maps shard name -> explicit page list (KV blocks: any
        page ragged).  Data objects land under a FRESH epoch, the
        manifest write is the commit, then the prior epoch's objects
        are removed best-effort — a reader mid-stream on the old
        manifest still sees consistent bytes until its next open."""
        shards = shards or {}
        pages = pages or {}
        if not shards and not pages:
            raise ValueError("put() needs shards= and/or pages=")
        dup = set(shards) & set(pages)
        if dup:
            raise ValueError(f"shard(s) in both shards= and pages=: "
                             f"{sorted(dup)}")
        old = self._manifest_or_none(name)
        epoch = (old.epoch + 1) if old is not None else 1

        info: dict[str, ShardInfo] = {}
        page_lists: dict[str, list[bytes]] = {}
        for s, blob in shards.items():
            n, size, vlens = paginate(blob, self.page_size)
            info[s] = ShardInfo(n_pages=n, size=size, vlens=vlens)
            page_lists[s] = [
                blob[p * self.page_size:(p + 1) * self.page_size]
                for p in range(n)]
        for s, plist in pages.items():
            info[s] = shard_from_pages(plist, self.page_size)
            page_lists[s] = plist

        m = ArtifactManifest(name=name, epoch=epoch,
                             page_size=self.page_size,
                             layout=self.layout, shards=info)
        # compose whole objects host-side, ONE write_full per data
        # object (EC-friendly: no partial-stripe overwrites), then a
        # single parallel write wave
        bufs: dict[str, bytearray] = {}
        for s, plist in page_lists.items():
            for pid, blob in enumerate(plist):
                pos = 0
                for ext in m.page_extents(s, pid):
                    oid = data_oid(name, epoch, s, ext.objectno)
                    buf = bufs.setdefault(oid, bytearray())
                    end = ext.offset + ext.length
                    if len(buf) < end:
                        buf.extend(b"\0" * (end - len(buf)))
                    buf[ext.offset:end] = blob[pos:pos + ext.length]
                    pos += ext.length
        futs = [self.io.aio_write_full(oid, bytes(buf))
                for oid, buf in sorted(bufs.items())]
        for fut in futs:
            self.io._wait(fut)
        self.io.write_full(manifest_oid(name), m.to_json())
        if old is not None:
            self._remove_epoch(old)
        return m

    def _remove_epoch(self, m: ArtifactManifest) -> int:
        futs = [self.io.aio_remove(oid) for oid in m.data_oids()]
        gone = 0
        for fut in futs:
            try:
                self.io._wait(fut)
                gone += 1
            except RadosError as e:
                # already gone is the goal; anything else is garbage
                # we must not fail a successful put over — the next
                # epoch flip retries nothing (objects are orphaned),
                # so at least surface it
                if e.errno_name != "ENOENT":
                    logging.getLogger("ceph_tpu.serve").warning(
                        "epoch cleanup: %s", e)
        return gone

    def delete(self, name: str) -> int:
        """Remove the artifact: data objects then the manifest.
        Returns the number of objects removed."""
        m = self.manifest(name)
        gone = self._remove_epoch(m)
        self.io.remove(manifest_oid(name))
        return gone + 1

    # ------------------------------------------------------------- read
    def manifest(self, name: str) -> ArtifactManifest:
        return ArtifactManifest.from_json(
            self.io.read(manifest_oid(name)))

    def _manifest_or_none(self, name: str
                          ) -> ArtifactManifest | None:
        try:
            return self.manifest(name)
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise
            return None

    def stat(self, name: str) -> dict:
        m = self.manifest(name)
        return {
            "name": m.name, "epoch": m.epoch,
            "page_size": m.page_size,
            "layout": {"stripe_unit": m.layout.stripe_unit,
                       "stripe_count": m.layout.stripe_count,
                       "object_size": m.layout.object_size},
            "objects": len(m.data_oids()),
            "bytes": sum(si.size for si in m.shards.values()),
            "shards": {s: {"size": si.size, "n_pages": si.n_pages,
                           "ragged_pages": len(si.vlens)}
                       for s, si in sorted(m.shards.items())},
        }

    def _read_one(self, oid: str, off: int, length: int) -> bytes:
        """Backing read with sparse semantics: a never-written range
        (ragged-page gap, zero page) reads as empty."""
        try:
            return self.io.read(oid, length, off)
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise
            return b""

    def read_wave(self, fetches: list[tuple[str, int, int]]
                  ) -> list[bytes]:
        """One parallel aio read wave: ALL submits go out before any
        wait, and each read is `unordered` so same-object reads don't
        serialize behind the objecter's per-object queue.  This is
        both the page-fetch engine and the ObjectCacher read_many_fn
        the serve handles mount."""
        futs = [self.io.aio_read(oid, length, off, unordered=True)
                for oid, off, length in fetches]
        out: list[bytes] = []
        for fut in futs:
            try:
                out.append(self.io._wait(fut).data)
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
                out.append(b"")         # sparse: unwritten reads empty
        return out

    def fetch_pages(self, name: str, shard: str,
                    page_ids: list[int], batched: bool = True,
                    manifest: ArtifactManifest | None = None
                    ) -> list[bytes]:
        """Fetch a ragged page-id list, results in page-id order,
        each byte-exact (ragged pages come back at their valid
        length).  `batched=True` (the real path) coalesces adjacent
        extents per object and issues ONE parallel read wave;
        `batched=False` is the read-per-page loop the wave replaces,
        kept as the bench baseline."""
        m = manifest or self.manifest(name)
        si = m.shards[shard]        # KeyError = no such shard
        # segment plan: (oid, obj_off, length, page_index, dest_off)
        segs: list[tuple[str, int, int, int, int]] = []
        sizes: list[int] = []
        for i, pid in enumerate(page_ids):
            sizes.append(si.vlen(pid, m.page_size))
            dest = 0
            for ext in m.page_extents(shard, pid):
                segs.append((data_oid(m.name, m.epoch, shard,
                                      ext.objectno),
                             ext.offset, ext.length, i, dest))
                dest += ext.length
        span = None
        ctx = current_trace()
        if global_config()["blkin_trace_all"]:
            ctx = child_of(ctx) if ctx else new_trace()
            span = self.tracer.start_span(
                ctx, f"serve_fetch:{name}/{shard}")
        scope = trace_scope(ctx) if span is not None \
            else contextlib.nullcontext()
        with scope:
            if batched:
                chunks = self._wave_coalesced(segs, span)
            else:
                chunks = [self._read_one(oid, off, ln)
                          for oid, off, ln, _, _ in segs]
        bufs = [bytearray(sz) for sz in sizes]
        for (_, _, ln, i, dest), chunk in zip(segs, chunks):
            chunk = chunk[:ln]
            bufs[i][dest:dest + len(chunk)] = chunk
        self.tracer.finish(span)
        return [bytes(b) for b in bufs]

    def _wave_coalesced(self, segs, span=None) -> list[bytes]:
        """Coalesce overlapping/adjacent same-object segments into
        runs, read the runs in one wave, slice segments back out."""
        order = sorted(range(len(segs)),
                       key=lambda i: (segs[i][0], segs[i][1]))
        runs: list[list[int]] = []      # [oid, start, end]
        where: dict[int, tuple[int, int]] = {}  # seg -> (run, delta)
        for i in order:
            oid, off, ln = segs[i][:3]
            if runs and runs[-1][0] == oid and off <= runs[-1][2]:
                runs[-1][2] = max(runs[-1][2], off + ln)
            else:
                runs.append([oid, off, off + ln])
            where[i] = (len(runs) - 1, off - runs[-1][1])
        datas = self.read_wave([(oid, start, end - start)
                                for oid, start, end in runs])
        if span is not None:
            span.event(f"pages={len(set(s[3] for s in segs))} "
                       f"segs={len(segs)} runs={len(runs)}")
        out: list[bytes] = []
        for i in range(len(segs)):
            run_i, delta = where[i]
            ln = segs[i][2]
            out.append(datas[run_i][delta:delta + ln])
        return out

    # ---------------------------------------------------------- handles
    def open(self, name: str, policy: str = "checkpoint",
             cache_bytes: int = 32 << 20,
             max_readahead: int = 512 << 10) -> "ArtifactHandle":
        """Open for reading with a per-handle readahead policy:
        `checkpoint` (sequential-doubling) for streaming,
        `kvcache` (no readahead, pin/refcount) for page gets."""
        return ArtifactHandle(self, self.manifest(name), policy,
                              cache_bytes=cache_bytes,
                              max_readahead=max_readahead)


def _ro_write(oid: str, off: int, data: bytes) -> None:
    raise RadosError("EROFS", "serve artifact handles are read-only")


class ArtifactHandle:
    """A read session pinned to one manifest epoch: an ObjectCacher
    over the artifact's data objects with the chosen readahead
    policy, plus pin/unpin residency control for KV pages."""

    def __init__(self, store: ArtifactStore, m: ArtifactManifest,
                 policy: str = "checkpoint",
                 cache_bytes: int = 32 << 20,
                 max_readahead: int = 512 << 10):
        self.store = store
        self.m = m
        self.policy = policy
        self.cacher = ObjectCacher(
            store._read_one, _ro_write,
            max_size=cache_bytes,
            page=min(m.page_size, m.layout.stripe_unit),
            max_readahead=max_readahead, policy=policy,
            read_many_fn=store.read_wave)

    @property
    def stats(self) -> dict:
        return self.cacher.stats

    def _stream_shard(self, shard: str) -> ShardInfo:
        si = self.m.shards[shard]
        if any(k != si.n_pages - 1 for k in si.vlens):
            raise ValueError(
                f"shard {shard!r} has interior ragged pages — a page "
                f"pool, not a stream; use get_pages()")
        return si

    def read(self, shard: str, offset: int = 0,
             length: int | None = None) -> bytes:
        """Stream read of a checkpoint shard's byte range (pages full
        except the ragged tail, so shard bytes == logical bytes
        [0, size))."""
        si = self._stream_shard(shard)
        if length is None:
            length = si.size - offset
        length = max(0, min(length, si.size - offset))
        if length == 0:
            return b""
        parts = []
        for ext in Striper.file_to_extents(self.m.layout, offset,
                                           length):
            oid = data_oid(self.m.name, self.m.epoch, shard,
                           ext.objectno)
            parts.append(self.cacher.read(oid, ext.offset,
                                          ext.length))
        return b"".join(parts)

    def read_shard(self, shard: str, chunk: int = 1 << 20) -> bytes:
        """Whole shard, streamed through the cache in `chunk` steps
        (exercises the policy's sequential detector the way a real
        loader would)."""
        si = self._stream_shard(shard)
        parts = []
        off = 0
        while off < si.size:
            n = min(chunk, si.size - off)
            parts.append(self.read(shard, off, n))
            off += n
        return b"".join(parts)

    def _page_segs(self, shard: str, page_ids: list[int]):
        segs = []       # (oid, off, ln) per extent, page-major order
        sizes = []
        bounds = []     # per page: (first_seg_index, n_segs)
        si = self.m.shards[shard]
        for pid in page_ids:
            sizes.append(si.vlen(pid, self.m.page_size))
            first = len(segs)
            for ext in self.m.page_extents(shard, pid):
                segs.append((data_oid(self.m.name, self.m.epoch,
                                      shard, ext.objectno),
                             ext.offset, ext.length))
            bounds.append((first, len(segs) - first))
        return segs, sizes, bounds

    def get_pages(self, shard: str, page_ids: list[int],
                  pin: bool = False) -> list[bytes]:
        """Batched page get through the cache: one read_many wave
        (one cacher lock acquisition; cold fills batched via the
        store's parallel read wave).  `pin=True` refcounts the pages
        resident until unpin_pages()."""
        segs, sizes, bounds = self._page_segs(shard, page_ids)
        chunks = self.cacher.read_many([s for s in segs])
        out = []
        for (first, n), size in zip(bounds, sizes):
            buf = b"".join(chunks[first:first + n])
            out.append(buf[:size])
        if pin:
            for oid, off, ln in segs:
                self.cacher.pin(oid, off, ln)
        return out

    def unpin_pages(self, shard: str, page_ids: list[int]) -> None:
        segs, _, _ = self._page_segs(shard, page_ids)
        for oid, off, ln in segs:
            self.cacher.unpin(oid, off, ln)

    def close(self) -> None:
        self.cacher.invalidate()
