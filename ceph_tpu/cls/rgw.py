"""cls_rgw: bucket-index transactions executed inside the OSD.

The reference maintains its bucket index with cls methods running on
the index object's primary OSD (ref: src/cls/rgw/cls_rgw.cc,
cls_rgw_ops.h), so every gateway's read-modify-write of an index entry
serializes on the PG — not on any gateway-local lock.  Same contract
here: each method below reads the current entry, computes the new
version stack, and queues the omap update; the daemon runs the method
under its dispatch lock and commits the mutation atomically with the
reply (osd/daemon.py _do_exec).  Two radosgw processes over one pool
therefore cannot lose a concurrent PUT's version record.

Entry format (JSON, one omap value per key; shared with
rgw/gateway.py):
  plain:     {"size", "etag", "mtime"}
  versioned: {"versions": [head..tail], "size", "etag", "mtime", "dm"}
  tombstone: {"tomb": true, "mtime"} — a plain delete leaves this in
             place of the entry (invisible to reads/listings) so a
             peer zone's put record that raced the delete compares
             against the delete's mtime instead of landing on an
             absent key and resurrecting the object.  A newer put
             (local or replicated) overwrites it.
Each version: {"vid", "size", "etag", "mtime", "dm", "obj"} where
"obj" names the RADOS data object backing that version (None for
delete markers).

Methods return the data objects orphaned by the operation in
"removed" — the gateway deletes those AFTER the index commit, the
same order the reference uses (index transaction first, data gc
second) so a crash leaves garbage, never a dangling index entry.

**Datalog (multisite)**: when the caller passes `log={"trace": [...]}`
every mutating method also appends a change record to the shard's
datalog — omap keys `.dl.<seq>` on the SAME index object, queued in
the SAME mutation batch as the index write, so the log entry and the
index entry commit as one transaction (ref: cls_rgw's bilog —
bucket_complete_op writes the bi log entry inside the index op; the
separate-object data log of rgw_datalog.cc would lose the atomicity
that PR 2's persist_log bug taught us to demand).  `trace` lists the
zones the mutation has already been applied at — sync agents skip
entries whose trace contains their own zone, which is what stops
replication loops.
"""
from __future__ import annotations

import calendar
import json
import time

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, cls_method

#: the one timestamp format for index entries — shared with the
#: gateway (rgw/gateway.py imports these; a format drift between
#: writer and OSD-side trimmer would misage every version).  now_str
#: appends real milliseconds (fixed width, so the string comparisons
#: the conflict rules use stay lexicographic): at 1s resolution every
#: same-second pair of writes was a cross-zone ordering tie.
MTIME_FMT = "%Y-%m-%dT%H:%M:%S"


def now_str() -> str:
    t = time.time()
    return time.strftime(MTIME_FMT, time.gmtime(t)) + \
        ".%03dZ" % int(t % 1 * 1000)


def parse_mtime(s: str) -> float:
    try:
        base, _, frac = s.partition(".")
        # timegm, not mktime: the stamp is UTC — local interpretation
        # shifted every parse by the DST hour
        return calendar.timegm(time.strptime(base, MTIME_FMT)) + \
            int(frac.rstrip("Z") or 0) / 1000.0
    except ValueError:
        return 0.0


#: datalog key namespace inside the index shard omap.  Listings and
#: emptiness checks filter these the way they filter `.upload.` keys.
DL_PREFIX = ".dl."
#: omap key holding the shard's datalog head sequence
DL_META = ".dlmeta"


def dl_key(seq: int) -> str:
    """Zero-padded so lexicographic omap order == sequence order."""
    return f"{DL_PREFIX}{seq:016d}"


def is_dl_key(key: str) -> bool:
    return key.startswith(DL_PREFIX) or key == DL_META


def _dl_head(raw: dict) -> int:
    meta = raw.get(DL_META)
    return json.loads(meta)["seq"] if meta else 0


def _dl_append(ctx, d: dict, op: str, key: str,
               raw: dict | None = None, **fields) -> None:
    """Queue a datalog record in the SAME mutation batch as the index
    write (the whole point: a crash commits both or neither).  No-op
    unless the caller opted in with d["log"].  `raw` reuses the
    caller's omap snapshot — queued mutations never touch it, and a
    second full-shard fetch per write is the hot path's biggest
    cost."""
    log = d.get("log")
    if not log:
        return
    seq = _dl_head(ctx.omap_get() if raw is None else raw) + 1
    ent = {"seq": seq, "key": key, "op": op,
           "trace": list(log.get("trace") or ()), **fields}
    ctx.omap_set({DL_META: json.dumps({"seq": seq}).encode(),
                  dl_key(seq): json.dumps(ent).encode()})


def _load(ctx, key: str, raw: dict | None = None) -> dict | None:
    v = (ctx.omap_get() if raw is None else raw).get(key)
    return json.loads(v) if v else None


def is_tomb(ent: dict | None) -> bool:
    """Per-key delete tombstone (see module docstring).  Shared with
    the gateway so its reads/listings drop tombstones the same way
    they drop datalog keys."""
    return bool(ent) and bool(ent.get("tomb"))


def _set_tomb(ctx, key: str, mtime: str) -> None:
    ctx.omap_set({key: json.dumps(
        {"tomb": True, "mtime": mtime}).encode()})


def _fold(ent: dict | None, plain_obj: str | None) -> list:
    """Existing version stack; a pre-versioning plain entry becomes
    the S3 'null' version backed by the plain data object
    (ref: rgw null-version semantics)."""
    if ent is None or is_tomb(ent):
        return []
    if ent.get("versions") is not None:
        return ent["versions"]
    return [{"vid": "null", "size": ent["size"], "etag": ent["etag"],
             "mtime": ent["mtime"], "dm": False,
             "obj": ent.get("obj") or plain_obj}]


def _store(ctx, key: str, versions: list) -> None:
    if not versions:
        ctx.omap_rmkeys([key])
        return
    head = versions[0]
    meta = {"versions": versions, "size": head.get("size", 0),
            "etag": head.get("etag", ""), "mtime": head["mtime"],
            "dm": bool(head.get("dm"))}
    ctx.omap_set({key: json.dumps(meta).encode()})


@cls_method("rgw", "obj_store", CLS_METHOD_WR)
def obj_store(ctx, d):
    """Record a completed PUT in the index
    (ref: cls_rgw bucket_complete_op CLS_RGW_OP_ADD).

    mode "plain": unversioned entry, last writer wins per key.
    mode "enabled": push a new version onto the stack.
    mode "suspended": replace the 'null' version in place.

    Every mode writes its data to a FRESH object first and links it
    here (the reference's instance-object model); the entry this
    commit orphans comes back in "removed" so the caller can gc it —
    a plain overwrite therefore never clobbers bytes a concurrent
    reader (or a version stack that appeared meanwhile) still needs.
    """
    key, mode = d["key"], d.get("mode", "plain")
    raw = ctx.omap_get()
    ent = _load(ctx, key, raw)
    if mode == "plain":
        if ent is not None and ent.get("versions") is not None:
            # versioning got enabled (and a version committed) after
            # the caller read the bucket meta — a plain overwrite
            # would erase that stack.  Caller retries as versioned.
            raise ClsError("ECANCELED", key)
        d = dict(d, mtime=_bump_mtime(
            ent["mtime"] if ent is not None else None, d["mtime"]))
        removed = []
        # a tombstone backs no data object (its delete already gc'd
        # it) — only a live entry orphans anything
        old = (ent.get("obj") or d.get("plain_obj")) \
            if ent is not None and not is_tomb(ent) else None
        if old and old != d["obj"]:
            removed.append(old)
        ctx.omap_set({key: json.dumps(
            {"size": d["size"], "etag": d["etag"],
             "mtime": d["mtime"], "obj": d["obj"]}).encode()})
        _dl_append(ctx, d, "put", key, raw=raw, mode="plain",
                   vid=None, size=d["size"], etag=d["etag"],
                   mtime=d["mtime"])
        return {"vid": None, "removed": removed}
    versions = _fold(ent, d.get("plain_obj"))
    d = dict(d, mtime=_bump_mtime(
        versions[0]["mtime"] if versions else None, d["mtime"]))
    rec = {"vid": d["vid"], "size": d["size"], "etag": d["etag"],
           "mtime": d["mtime"], "dm": False, "obj": d["obj"]}
    removed = []
    if mode == "suspended":
        for v in versions:
            if v["vid"] == "null" and not v.get("dm") and v.get("obj") \
                    and v["obj"] != d["obj"]:
                removed.append(v["obj"])
        versions = [v for v in versions if v["vid"] != "null"]
        rec["vid"] = "null"
    elif mode != "enabled":
        raise ClsError("EINVAL", f"mode {mode}")
    _insert_version(versions, rec)
    _store(ctx, key, versions)
    _dl_append(ctx, d, "put", key, raw=raw, mode=mode,
               vid=rec["vid"], size=d["size"], etag=d["etag"],
               mtime=d["mtime"])
    return {"vid": rec["vid"], "removed": removed}


@cls_method("rgw", "obj_delete_marker", CLS_METHOD_WR)
def obj_delete_marker(ctx, d):
    """Insert a delete marker at the head of the stack (ref: rgw
    delete-marker flow, cls_rgw CLS_RGW_OP_LINK_OLH_DM).

    replace_null: drop the existing 'null' version first (Suspended
    buckets replace the null version with a null marker); its data
    object comes back in "removed".
    if_head_vid / if_mtime: optional guards — ECANCELED when the head
    changed since the caller's read (lifecycle uses them so an expiry
    decided on a stale snapshot never clobbers a fresh PUT).  BOTH are
    needed: a Suspended-bucket overwrite keeps vid "null", so only the
    mtime moves.
    """
    key = d["key"]
    raw = ctx.omap_get()
    versions = _fold(_load(ctx, key, raw), d.get("plain_obj"))
    if "if_head_vid" in d:
        head = versions[0]["vid"] if versions else None
        if head != d["if_head_vid"]:
            raise ClsError("ECANCELED", key)
    if "if_mtime" in d:
        head_mtime = versions[0]["mtime"] if versions else None
        if head_mtime != d["if_mtime"]:
            raise ClsError("ECANCELED", key)
    d = dict(d, mtime=_bump_mtime(
        versions[0]["mtime"] if versions else None, d["mtime"]))
    removed = []
    if d.get("replace_null"):
        for v in versions:
            if v["vid"] == "null" and not v.get("dm") and v.get("obj"):
                removed.append(v["obj"])
        versions = [v for v in versions if v["vid"] != "null"]
    _insert_version(versions, {"vid": d["vid"], "size": 0, "etag": "",
                               "mtime": d["mtime"], "dm": True,
                               "obj": None})
    _store(ctx, key, versions)
    _dl_append(ctx, d, "dm", key, raw=raw, vid=d["vid"],
               mtime=d["mtime"],
               replace_null=bool(d.get("replace_null")))
    return {"vid": d["vid"], "removed": removed}


@cls_method("rgw", "obj_delete_version", CLS_METHOD_WR)
def obj_delete_version(ctx, d):
    """Remove one explicit version (ref: cls_rgw
    CLS_RGW_OP_UNLINK_INSTANCE).  ENOENT when the vid isn't in the
    stack; an emptied stack removes the index entry."""
    key = d["key"]
    raw = ctx.omap_get()
    ent = _load(ctx, key, raw)
    if ent is None or is_tomb(ent):
        raise ClsError("ENOENT", key)
    versions = _fold(ent, d.get("plain_obj"))
    keep = [v for v in versions if v["vid"] != d["vid"]]
    if len(keep) == len(versions):
        raise ClsError("ENOENT", d["vid"])
    removed = [v["obj"] for v in versions
               if v["vid"] == d["vid"] and v.get("obj")
               and not v.get("dm")]
    _store(ctx, key, keep)
    _dl_append(ctx, d, "rmver", key, raw=raw, vid=d["vid"])
    return {"removed": removed}


@cls_method("rgw", "obj_delete_plain", CLS_METHOD_WR)
def obj_delete_plain(ctx, d):
    """Unversioned delete: drop the index entry (ref: cls_rgw
    CLS_RGW_OP_DEL).  ECANCELED if the entry meanwhile grew a version
    stack — the caller re-runs the versioned delete path.
    if_mtime: optional guard for lifecycle (see obj_delete_marker)."""
    key = d["key"]
    raw = ctx.omap_get()
    ent = _load(ctx, key, raw)
    if ent is None or is_tomb(ent):
        return {"removed": []}   # nothing live to delete; an existing
        # tombstone keeps its (newer-or-equal) delete stamp
    if ent.get("versions") is not None:
        raise ClsError("ECANCELED", key)
    if "if_mtime" in d and ent.get("mtime") != d["if_mtime"]:
        raise ClsError("ECANCELED", key)
    dead = ent.get("obj") or d.get("plain_obj")
    # bump past the entry's (possibly future-bumped) mtime like the
    # write paths: a wall-clock stamp could be OLDER than the head a
    # same-millisecond put left behind, and the replica's newer-wins
    # rule would then keep an object the origin dropped
    mtime = _bump_mtime(ent.get("mtime"), d.get("mtime") or now_str())
    # leave a tombstone, not an absent key: a peer's put record that
    # raced this delete must compare against the delete's mtime when
    # it arrives, or the sync apply resurrects the object
    _set_tomb(ctx, key, mtime)
    _dl_append(ctx, d, "del", key, raw=raw, mtime=mtime)
    return {"removed": [dead] if dead else []}


def _bump_mtime(existing: str | None, mtime: str) -> str:
    """Strictly-after the key's current head: sequential same-key
    writes must order by mtime even inside one millisecond, or the
    tie falls to the vid/etag break and read-your-writes fails on the
    origin.  Only LOCAL write paths bump — sync applies preserve the
    origin's stamps."""
    if existing is None or mtime > existing:
        return mtime
    base, _, frac = existing.partition(".")
    ms = int(frac.rstrip("Z") or 0) + 1
    if ms < 1000:
        return f"{base}.{ms:03d}Z"
    t = calendar.timegm(time.strptime(base, MTIME_FMT)) + 1
    return time.strftime(MTIME_FMT, time.gmtime(t)) + ".000Z"


def _insert_version(versions: list, rec: dict) -> None:
    """Place rec by (mtime, vid), newest first — ONE ordering rule
    for local writes AND sync applies.  If the origin inserted by
    arrival while replicas ordered by (mtime, vid), two writes in the
    same millisecond would stack differently per zone; sequential
    writes carry distinct millisecond mtimes, so the vid tie-break
    only ever decides genuinely concurrent pairs."""
    at = len(versions)
    for i, v in enumerate(versions):
        if (v["mtime"], v.get("vid") or "") <= \
                (rec["mtime"], rec.get("vid") or ""):
            at = i              # before the first not-newer version
            break
    versions.insert(at, rec)


def _newer(a_mtime: str, a_etag: str, b_mtime: str, b_etag: str) -> bool:
    """Deterministic cross-zone ordering: later mtime wins; equal
    mtimes (1s format resolution) tie-break on etag so BOTH zones pick
    the same winner regardless of arrival order."""
    return (a_mtime, a_etag) > (b_mtime, b_etag)


@cls_method("rgw", "obj_sync_apply", CLS_METHOD_WR)
def obj_sync_apply(ctx, d):
    """Apply one replicated mutation from a peer zone's datalog —
    idempotently and deterministically (ref: rgw_data_sync.cc's
    RGWObjFetchCR + the squash map; versioned-epoch conflict rules of
    rgw multisite).

    d: {key, op, vid, size, etag, mtime, mode, obj, log:{trace}}
    where "obj" names the LOCAL staged data object for puts (written
    by the caller before this call; unlinked staging is the caller's
    to gc when not applied).

    Rules (the convergence contract tests/test_rgw_multisite.py
    thrashes):
      * put/plain: newest (mtime, etag) wins; identical pair = the
        entry was already applied -> skip.
      * put/versioned + dm: dedupe by vid (a replay after a marker
        rewind must not duplicate a version); insert before the first
        version that is not newer, so same-second replays keep datalog
        order and stacks converge.
      * del: wins ties (on the origin the delete happened after the
        put it removed); absent entry = already applied.
      * rmver: remove if present; absent = already applied.

    Applied mutations re-log to the LOCAL datalog with the caller's
    extended trace so further zones can pull them; skipped ones do not
    (nothing changed).  Returns {"applied", "vid", "removed"}.
    """
    key, op = d["key"], d["op"]
    raw = ctx.omap_get()
    ent = _load(ctx, key, raw)
    removed: list[str] = []

    def skip():
        return {"applied": False, "vid": d.get("vid"),
                "removed": removed}

    if op == "put" and d.get("mode", "plain") == "plain":
        if ent is not None and ent.get("versions") is not None:
            return skip()       # local entry grew a version stack
        if is_tomb(ent):
            # the key was deleted here; only a put STRICTLY newer than
            # the delete may land (ties go to the delete, same rule as
            # the 'del' branch below) — this is the put-racing-
            # cross-zone-delete window the tombstone exists to close
            if not d["mtime"] > ent["mtime"]:
                return skip()
        elif ent is not None and not _newer(d["mtime"], d["etag"],
                                            ent["mtime"], ent["etag"]):
            return skip()       # local state is newer (or identical)
        if ent is not None and ent.get("obj"):
            removed.append(ent["obj"])
        ctx.omap_set({key: json.dumps(
            {"size": d["size"], "etag": d["etag"],
             "mtime": d["mtime"], "obj": d["obj"]}).encode()})
        _dl_append(ctx, d, "put", key, raw=raw, mode="plain",
                   vid=None, size=d["size"], etag=d["etag"],
                   mtime=d["mtime"])
        return {"applied": True, "vid": None, "removed": removed}

    if op == "del":
        if ent is not None and ent.get("versions") is not None:
            return skip()
        if is_tomb(ent) and not d["mtime"] > ent["mtime"]:
            return skip()       # replay, or an older delete
        if ent is not None and not is_tomb(ent) \
                and ent["mtime"] > d["mtime"]:
            return skip()       # a local write outran the delete.
            # Ties go to the delete: a same-second put-then-delete on
            # the origin replays in datalog order, and the delete must
            # win or the replica keeps an object the origin dropped.
        if ent is not None and ent.get("obj"):
            removed.append(ent["obj"])
        # write the tombstone even when the key is absent here: the
        # put this delete removed may still be in flight from a third
        # zone (or this one), and must find the delete's stamp waiting.
        # A replayed delete hit the equal-mtime tombstone skip above,
        # so every path reaching here changed state — re-log it.
        _set_tomb(ctx, key, d["mtime"])
        _dl_append(ctx, d, "del", key, raw=raw, mtime=d["mtime"])
        return {"applied": True, "vid": None, "removed": removed}

    versions = _fold(ent, None)

    if op == "rmver":
        keep = [v for v in versions if v["vid"] != d["vid"]]
        if len(keep) == len(versions):
            return skip()
        removed.extend(v["obj"] for v in versions
                       if v["vid"] == d["vid"] and v.get("obj")
                       and not v.get("dm"))
        _store(ctx, key, keep)
        _dl_append(ctx, d, "rmver", key, raw=raw, vid=d["vid"])
        return {"applied": True, "vid": d["vid"], "removed": removed}

    if op not in ("put", "dm"):
        raise ClsError("EINVAL", f"sync op {op}")

    is_dm = op == "dm"
    for v in versions:
        if v["vid"] == d["vid"] and bool(v.get("dm")) == is_dm \
                and d["vid"] != "null":
            # replayed entry: version already here.  "null" is exempt —
            # every suspended-mode overwrite reuses vid "null", so
            # presence alone cannot tell a replay from a genuinely
            # newer overwrite; the rank rule below decides those.
            return skip()
    if d["vid"] == "null" or (not is_dm and
                              d.get("mode") == "suspended"):
        # null-version semantics: at most one 'null' in the stack.
        # Winner by (mtime, dm, etag): at equal mtimes the marker
        # outranks the put (same tie rule as plain 'del' — on the
        # origin the delete happened after the put), so both zones
        # settle identically regardless of arrival order, and an
        # identical replay compares equal and skips.
        olds = [v for v in versions if v["vid"] == "null"]
        rank = (d["mtime"], is_dm, "" if is_dm else d.get("etag", ""))
        if olds and (olds[0]["mtime"], bool(olds[0].get("dm")),
                     olds[0].get("etag", "")) >= rank:
            return skip()       # local null is newer (or identical)
        removed.extend(v["obj"] for v in olds
                       if v.get("obj") and not v.get("dm"))
        versions = [v for v in versions if v["vid"] != "null"]
    rec = {"vid": d["vid"], "size": 0 if is_dm else d["size"],
           "etag": "" if is_dm else d["etag"], "mtime": d["mtime"],
           "dm": is_dm, "obj": None if is_dm else d["obj"]}
    _insert_version(versions, rec)
    _store(ctx, key, versions)
    if is_dm:
        _dl_append(ctx, d, "dm", key, raw=raw, vid=d["vid"],
                   mtime=d["mtime"])
    else:
        _dl_append(ctx, d, "put", key, raw=raw,
                   mode=d.get("mode", "enabled"), vid=d["vid"],
                   size=d["size"], etag=d["etag"], mtime=d["mtime"])
    return {"applied": True, "vid": d["vid"], "removed": removed}


@cls_method("rgw", "dl_list", CLS_METHOD_RD)
def dl_list(ctx, d):
    """List datalog entries with seq > marker (cursor-based incremental
    read; ref: rgw datalog list_entries + its marker).  Returns the
    shard head too so callers can measure lag with one call."""
    raw = ctx.omap_get()
    lo = dl_key(int(d.get("marker", 0)))
    limit = int(d.get("max", 64))
    ents = []
    # filter to datalog keys BEFORE sorting and stop at the limit:
    # this runs per shard per peer on every sync poll, and the shard's
    # omap is dominated by index entries, not log records
    for k in sorted(k for k in raw if k.startswith(DL_PREFIX)):
        if k <= lo:
            continue
        if len(ents) >= limit:
            break               # max=0 head probes return NO entries
        ents.append(json.loads(raw[k]))
    return {"entries": ents, "head": _dl_head(raw)}


@cls_method("rgw", "dl_trim", CLS_METHOD_WR)
def dl_trim(ctx, d):
    """Drop datalog entries with seq <= upto (ref: rgw datalog trim —
    driven by an admin once every peer's marker has passed them; the
    head counter survives so sequences never regress)."""
    raw = ctx.omap_get()
    upto = dl_key(int(d["upto"]))
    dead = [k for k in raw
            if k.startswith(DL_PREFIX) and k <= upto]
    if dead:
        ctx.omap_rmkeys(dead)
    return {"trimmed": len(dead)}


@cls_method("rgw", "obj_trim_noncurrent", CLS_METHOD_WR)
def obj_trim_noncurrent(ctx, d):
    """Drop noncurrent versions older than max_age_s (lifecycle
    NoncurrentVersionExpiration; ref: src/rgw/rgw_lc.cc noncurrent
    expiry).  The age test runs HERE against the committed stack, so
    two gateways' lifecycle ticks can race without double-freeing."""
    key = d["key"]
    ent = _load(ctx, key)
    if ent is None or ent.get("versions") is None:
        return {"removed": [], "dropped": 0}
    versions = ent["versions"]
    keep, removed = versions[:1], []
    for v in versions[1:]:
        if d["now"] - parse_mtime(v["mtime"]) > d["max_age_s"]:
            if v.get("obj") and not v.get("dm"):
                removed.append(v["obj"])
        else:
            keep.append(v)
    if len(keep) != len(versions):
        _store(ctx, key, keep)
    return {"removed": removed, "dropped": len(versions) - len(keep)}
