"""RED: every failure of a read path becomes one success-shaped (or
ENOENT-shaped) result — the errno dataflow is severed at the
handler, so the caller cannot tell EIO from empty."""


class ShardError(Exception):
    pass


class Shard:
    def list_entries(self, marker):
        try:
            return self._read(marker)
        except Exception:
            return []             # EIO now reads as "caught up"

    def stat_size(self):
        try:
            size = self._io.stat()["size"]
        except Exception:
            size = 0              # replay cursor resets on ANY error
        return self._active, size

    def read_header(self):
        try:
            return self._decode(self._io.read("header"))
        except Exception:
            raise ShardError("ENOENT", "no header")
