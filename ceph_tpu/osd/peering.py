"""PG peering statechart + backfill machinery (replicated pools).

The reference's peering phase machine (ref: src/osd/PG.h:2085-2195 —
the boost::statechart with GetInfo / GetLog / GetMissing / Activating
/ Active{Recovering, Backfilling, Clean}; driven from
src/osd/PeeringState.cc) rebuilt as an explicit phase object owned by
the primary's _PGState:

* **GetInfo** — query pg_info (durable log bounds + data presence)
  from every OSD in the prior set: current up ∪ acting ∪ the previous
  interval's acting set (ref: PastIntervals; prior-set build in
  PeeringState::build_prior).  Peers answer from their persisted
  shard log even without live PG state.
* **GetLog** — choose the authoritative log (newest last_update,
  ref: PeeringState::find_best_info), fetch the segment we lack and
  `merge_log` it (divergent local entries resolved by the five-case
  machinery in pg_log.py, store effects applied via a rollbacker).
  A primary whose log has NO overlap with the authoritative one
  requests a **pg_temp** override from the mon (the data-holding old
  set keeps primacy and serves clients while the new set backfills,
  ref: src/messages/MOSDPGTemp.h + PeeringState choose_acting's
  want_temp) and, in parallel, runs a direct full-copy pull so small
  PGs converge even before the override lands.
* **GetMissing** — replicas with log overlap receive the
  authoritative segment, merge it locally (their own divergence
  handled by the same five-case code), and reply with their missing
  sets (ref: PeeringState::proc_replica_log + activate's missing
  exchange).  Peers with NO overlap (pre-tail last_update, or an
  empty log) become **backfill targets**.
* **Activating/Recovering** — log-based recovery: the primary pulls
  objects from its own missing set, then pushes every (peer, object)
  in peer_missing; client IO resumes when log recovery completes
  (the daemon's existing ESTALE-retry contract).
* **Backfilling** — reservation-gated (osd_max_backfills on BOTH
  ends, ref: src/messages/MBackfillReserve.h REQUEST/GRANT/REJECT +
  the local/remote reservers in PeeringState), then a ranged cursor
  walk: compare the primary's and target's inventories over aligned
  (begin, end] windows of osd_backfill_scan_max objects, push
  stale/missing ones, whiteout-push the target's strays, advance
  last_backfill (ref: PrimaryLogPG::recover_backfill /
  PG::scan_range).  Client writes stay live during backfill: the
  backend fans ops to a backfill target only for objects at or
  before its cursor — later objects are copied by the walk itself
  (ref: last_backfill gating in PrimaryLogPG::issue_repop).
* **Clean** — strays (prior-interval holders no longer in up/acting)
  are told to delete their copy (ref: src/messages/MOSDPGRemove.h);
  a temp primary clears its pg_temp override, flipping the map back
  to the true up set.

EC pools run the same phase machine with shard-aware semantics in
`osd/ec_peering.py` (ECPGPeering): per-shard pg_info from durable EC
shard logs, cross-set chunk sources, and reservation-gated chunk
backfill — sharing this module's phase constants, the daemon's
reservation pools, and the pg_temp plumbing.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from ..common.log import dout
from ..common.options import global_config
from ..crush.types import CRUSH_ITEM_NONE
from ..msg.messages import (BackfillReserve, PGLogPush, PGLogReq,
                            PGNotify, PGPull, PGQuery, PGRemove, PGScan)
from .pg_log import IndexedLog, LogEntryHandler
from .pg_types import EVersion, ZERO_VERSION

if TYPE_CHECKING:  # pragma: no cover
    from .daemon import OSDDaemon

# phases (ref: the statechart's state names)
GETINFO = "getinfo"
GETLOG = "getlog"
GETMISSING = "getmissing"
RECOVERING = "recovering"
WAIT_BACKFILL = "wait_backfill"
BACKFILLING = "backfilling"
CLEAN = "clean"

#: heartbeat ticks a phase may sit without progress before its
#: outstanding messages are re-driven (lost-message recovery)
_RETRY_TICKS = 3


def _ev(v) -> EVersion:
    if v is None:
        return ZERO_VERSION
    if isinstance(v, EVersion):
        return v
    return EVersion(*v)


class StoreRollbacker(LogEntryHandler):
    """Divergence side-effects on the local store: an entry that can't
    roll back removes the object (it re-arrives through recovery at
    the authoritative version; ref: PGLog::LogEntryHandler ->
    PrimaryLogPG::remove_missing_object)."""

    def __init__(self, shard):
        self.shard = shard

    def remove(self, soid: str) -> None:
        from ..store import StoreError
        try:
            self.shard.apply_write(soid, 0, b"", True, None, [])
        except StoreError:
            pass

    def rollback(self, entry) -> None:
        # rollback blobs are not recorded (entries carry
        # rollbackable=False), so statechart case 4 never fires;
        # remove-and-repull is the conservative resolution
        self.remove(entry.soid)


class _Info:
    """One peer's pg_info (ref: pg_info_t reduced to what peering
    consumes)."""

    def __init__(self, osd: int, last_update: EVersion,
                 log_tail: EVersion, have_data: bool):
        self.osd = osd
        self.last_update = last_update
        self.log_tail = log_tail
        self.have_data = have_data

    def __repr__(self):
        return (f"info(osd.{self.osd} lu={self.last_update} "
                f"tail={self.log_tail} data={self.have_data})")


class PGPeering:
    """Primary-side peering driver for one replicated PG.  All entry
    points run under the daemon lock (message dispatch + tick)."""

    def __init__(self, daemon: "OSDDaemon", pg, st,
                 prior_acting: list[int] | None = None):
        self.d = daemon
        self.pg = pg
        self.st = st
        self.epoch = daemon.osdmap.epoch
        self.phase = GETINFO
        self.prior_acting = [o for o in (prior_acting or []) if o >= 0]
        self.infos: dict[int, _Info] = {}
        self.pending_info: set[int] = set()
        self.auth: _Info | None = None
        self.log_peers: list[int] = []
        self.pending_missing: set[int] = set()
        #: osd -> {oid: EVersion} objects each log-peer lacks
        self.peer_missing: dict[int, dict] = {}
        #: peers needing a full-copy walk (no log overlap)
        self.backfill_targets: list[int] = []
        self.pull_pending: set[str] = set()
        self.push_pending = 0
        #: set while we ourselves full-copy from the auth holder
        self.primary_backfill_from: int | None = None
        #: the auth holder's full log landed (_on_full_log); a primary
        #: backfill may not go clean before this — testing head ==
        #: ZERO_VERSION instead would let a stale non-empty local log
        #: slip through when pulls finish before the log reply
        self._log_adopted = False
        # backfill walk state
        self.bf_target: int | None = None
        self.bf_cursor = ""            # exclusive lower bound
        self.bf_end = ""               # current window's end
        self.bf_final_window = False   # this window drains our list
        self.bf_reserved_local = False
        self.bf_reserved_remote = False
        self.bf_pushes_in_chunk = 0
        #: ticks since the current phase last made progress; the tick
        #: hook re-drives a phase stuck past _RETRY_TICKS (lost
        #: message / dropped connection that never marked the peer
        #: down) — every re-drive is idempotent
        self._phase_ticks = 0

    # ------------------------------------------------------------ util
    def _shard(self):
        return self.st.shard

    def _send(self, osd: int, msg) -> bool:
        return self.d.ms.connect(f"osd.{osd}").send_message(msg)

    def _log(self, lvl: int, fmt: str, *args) -> None:
        dout("pg", lvl).write(
            f"{self.d.name}: pg {self.pg} peering[{self.phase}] " + fmt,
            *args)

    def _up_acting_peers(self) -> list[int]:
        m = self.d.osdmap
        up, _, acting, _ = m.pg_to_up_acting_osds(self.pg)
        peers = []
        for o in list(acting) + list(up):
            if 0 <= o < CRUSH_ITEM_NONE and o != self.d.whoami \
                    and o not in peers:
                peers.append(o)
        return peers

    # ---------------------------------------------------------- GetInfo
    def start(self) -> None:
        self.st.recovering = True
        self.st.backfilling = False
        peers = self._up_acting_peers()
        for o in self.prior_acting:
            if o != self.d.whoami and o not in peers:
                peers.append(o)
        peers = [o for o in peers if self.d.osdmap.is_up(o)]
        if not peers:
            self._choose_auth()
            return
        self.pending_info = set(peers)
        self._log(10, "querying %s", peers)
        for o in list(peers):
            if not self._send(o, PGQuery(pgid=self.pg,
                                         epoch=self.epoch)):
                self.pending_info.discard(o)
        if not self.pending_info:
            self._choose_auth()

    def on_info(self, msg: PGNotify) -> None:
        if self.phase != GETINFO or msg.epoch != self.epoch or \
                msg.from_osd not in self.pending_info:
            return
        self._phase_ticks = 0
        self.pending_info.discard(msg.from_osd)
        self.infos[msg.from_osd] = _Info(
            msg.from_osd, _ev(msg.last_update), _ev(msg.log_tail),
            msg.have_data)
        if not self.pending_info:
            self._choose_auth()

    def _my_info(self) -> _Info:
        head, tail = self._shard().log_info()
        return _Info(self.d.whoami, head, tail,
                     bool(self._shard().inventory()))

    def _choose_auth(self) -> None:
        """find_best_info: newest last_update wins, self on ties
        (ref: PeeringState::find_best_info; the longest-log and
        up-primary tiebreaks don't change outcomes here because logs
        share trim policy)."""
        mine = self._my_info()
        best = mine
        for info in self.infos.values():
            if info.last_update > best.last_update:
                best = info
        self.auth = best
        self._log(10, "auth=%r mine=%r", best, mine)
        if best.osd != self.d.whoami and \
                best.last_update > mine.last_update:
            if best.log_tail <= mine.last_update:
                # overlap: fetch just the segment we lack
                self.phase = GETLOG
                if not self._send(best.osd, PGLogReq(
                        pgid=self.pg, since=mine.last_update,
                        epoch=self.epoch)):
                    self._log(1, "auth osd.%d unreachable", best.osd)
                return
            self._primary_backfill(best.osd)
            return
        self._enter_getmissing()

    # ----------------------------------------------------------- GetLog
    def on_auth_log(self, msg: PGLogPush) -> None:
        if msg.full:
            self._on_full_log(msg)
            return
        if self.phase != GETLOG or msg.epoch != self.epoch:
            return
        self._phase_ticks = 0
        shard = self._shard()
        olog = IndexedLog(list(msg.entries), head=_ev(msg.head),
                          tail=_ev(msg.tail))
        try:
            shard.pg_log.merge_log(olog, StoreRollbacker(shard))
        except ValueError:
            # the auth trimmed between info and log reply
            self._primary_backfill(msg.from_osd)
            return
        shard.persist_log()
        self._enter_getmissing()

    def _primary_backfill(self, auth_osd: int) -> None:
        """Our own log has no overlap with the authoritative one.  Two
        converging tracks (whichever lands first wins):

        * ask the mon for pg_temp = the data holder, so IT becomes
          acting primary, serves clients, and backfills US through its
          own statechart (the reference's model — client IO keeps
          flowing);
        * run a direct full-copy pull from the holder, so small PGs
          converge even before the override propagates (clients retry
          on ESTALE meanwhile — the pre-pg_temp availability mode).

        A map flip from the first track tears this round down and the
        holder takes over; completion of the second goes clean and
        clears the override."""
        self.phase = RECOVERING
        self.primary_backfill_from = auth_osd
        holders = sorted(
            o for o, info in self.infos.items()
            if info.last_update == self.infos[auth_osd].last_update
            and self.d.osdmap.is_up(o)) or [auth_osd]
        self.d.request_pg_temp(self.pg, holders)
        self._log(4, "primary backfill from osd.%d (pg_temp=%s)",
                  auth_osd, holders)
        self._send(auth_osd, PGScan(pgid=self.pg, ec=False))

    def on_primary_backfill_scan(self, msg) -> None:
        """Full inventory from the auth holder: pull everything newer,
        drop local objects it does not know (divergent leftovers past
        trimmed history), then adopt its log wholesale."""
        if self.primary_backfill_from != msg.from_osd or \
                self.phase != RECOVERING:
            return
        shard = self._shard()
        mine = shard.inventory()
        theirs = dict(msg.objects)
        rb = StoreRollbacker(shard)
        for oid in set(mine) - set(theirs):
            rb.remove(oid)
        pulls = []
        for oid, (ver, whiteout) in theirs.items():
            my = mine.get(oid, ((0, 0), False))
            if tuple(ver) > tuple(my[0]):
                if whiteout:
                    shard.apply_write(oid, 0, b"", True,
                                      EVersion(*ver), [])
                else:
                    pulls.append(oid)
        self.pull_pending = set(pulls)
        if pulls:
            self.d.perf.inc("recovery_pull", len(pulls))
            self._send(msg.from_osd, PGPull(pgid=self.pg, oids=pulls))
        self._send(msg.from_osd, PGLogReq(
            pgid=self.pg, since=ZERO_VERSION, epoch=self.epoch,
            full=True))

    def _on_full_log(self, msg: PGLogPush) -> None:
        """Wholesale log adoption closing a primary backfill."""
        if self.primary_backfill_from != msg.from_osd or \
                msg.epoch != self.epoch:
            return
        shard = self._shard()
        shard.pg_log.log = IndexedLog(list(msg.entries),
                                      head=_ev(msg.head),
                                      tail=_ev(msg.tail))
        shard.pg_log.log.can_rollback_to = _ev(msg.head)
        shard.persist_log()
        self._log_adopted = True
        self._maybe_pulls_done()

    # ------------------------------------------------------- GetMissing
    def _enter_getmissing(self) -> None:
        self.phase = GETMISSING
        shard = self._shard()
        head, tail = shard.log_info()
        self.log_peers = []
        self.backfill_targets = []
        for o in self._up_acting_peers():
            info = self.infos.get(o)
            if info is None:
                continue
            if head == ZERO_VERSION and \
                    info.last_update == ZERO_VERSION and \
                    not info.have_data:
                continue            # both empty: nothing to recover
            overlap = info.last_update >= tail and \
                info.last_update != ZERO_VERSION
            if overlap:
                self.log_peers.append(o)
            else:
                self.backfill_targets.append(o)
        self.pending_missing = set(self.log_peers)
        self._log(10, "log_peers=%s backfill=%s", self.log_peers,
                  self.backfill_targets)
        entries = list(shard.pg_log.log.entries)
        for o in self.log_peers:
            self._send(o, PGLogPush(
                pgid=self.pg, from_osd=self.d.whoami, entries=entries,
                head=head, tail=tail, activate=True, epoch=self.epoch))
        if not self.pending_missing:
            self._activate()

    def on_missing(self, msg) -> None:
        if self.phase != GETMISSING or msg.epoch != self.epoch or \
                msg.from_osd not in self.pending_missing:
            return
        self._phase_ticks = 0
        self.pending_missing.discard(msg.from_osd)
        if msg.no_overlap:
            self.backfill_targets.append(msg.from_osd)
        else:
            self.peer_missing[msg.from_osd] = {
                oid: _ev(v) for oid, v in msg.missing.items()}
        if not self.pending_missing:
            self._activate()

    # ------------------------------------------------- Active/Recovering
    def _activate(self) -> None:
        self.phase = RECOVERING
        shard = self._shard()
        missing = shard.pg_log.missing
        pulls: dict[int, list[str]] = {}
        for oid, item in list(missing.items.items()):
            if item.is_delete:
                StoreRollbacker(shard).remove(oid)
                missing.rm(oid)
                continue
            holder = self._holder_for(oid, item.need)
            if holder is None:
                self._log(0, "object %s UNFOUND (need %s)", oid,
                          item.need)
                continue
            pulls.setdefault(holder, []).append(oid)
            self.pull_pending.add(oid)
        for osd, oids in pulls.items():
            self.d.perf.inc("recovery_pull", len(oids))
            self._send(osd, PGPull(pgid=self.pg, oids=oids))
        self._maybe_pulls_done()

    def _holder_for(self, oid: str, need: EVersion) -> int | None:
        """A live peer whose log covers `need` and whose own missing
        set does not include the object."""
        for o, info in self.infos.items():
            if info.last_update >= need and \
                    oid not in self.peer_missing.get(o, {}) and \
                    self.d.osdmap.is_up(o):
                return o
        return None

    def on_pull_done(self, oid: str) -> None:
        """A pulled object arrived (the daemon applied it AND ran the
        missing-set recover_got before routing here)."""
        if oid not in self.pull_pending:
            return
        self._phase_ticks = 0
        self.pull_pending.discard(oid)
        self._maybe_pulls_done()

    def _maybe_pulls_done(self) -> None:
        if self.phase != RECOVERING or self.pull_pending:
            return
        if self.primary_backfill_from is not None and \
                not self._log_adopted:
            return      # primary backfill: log adoption still in flight
        jobs = [(oid, osd) for osd, objs in self.peer_missing.items()
                for oid in objs]
        self.push_pending = len(jobs)
        if not jobs:
            self._log_recovery_done()
            return
        for oid, osd in jobs:
            self.d.op_queue.enqueue(
                "recovery",
                lambda oid=oid, osd=osd: self._push_one(oid, osd))
        self.d._drain_op_queue()

    def _push_one(self, oid: str, osd: int) -> None:
        try:
            self.d._push_object(self.pg, self.st, oid, osd)
        finally:
            self.push_pending -= 1
            if self.push_pending <= 0 and self.phase == RECOVERING:
                self._log_recovery_done()

    def _log_recovery_done(self) -> None:
        """Log recovery complete: client IO resumes; backfill targets
        proceed under reservations with IO live."""
        self.st.recovering = False
        if not self.backfill_targets:
            self._enter_clean()
            return
        self.phase = WAIT_BACKFILL
        self.st.backfilling = True
        # install cursor gating BEFORE any backfill traffic: writes
        # fan out to a target only for objects <= its cursor
        b = self.st.backend
        if b is not None:
            for o in self.backfill_targets:
                b.backfill_peers[o] = ""       # nothing copied yet
        self._next_backfill_target()

    # ------------------------------------------------------- Backfilling
    def _next_backfill_target(self) -> None:
        if not self.backfill_targets:
            self._enter_clean()
            return
        self.bf_target = self.backfill_targets[0]
        self.bf_cursor = ""
        self.bf_reserved_remote = False
        self.phase = WAIT_BACKFILL
        self.st.backfilling = True
        if not self.bf_reserved_local and \
                not self.d.reserve_local_backfill(self.pg):
            return          # queued: local_granted() resumes us
        self.bf_reserved_local = True
        self._send(self.bf_target, BackfillReserve(
            pgid=self.pg, from_osd=self.d.whoami, op="request"))

    def local_granted(self) -> None:
        """A queued local reservation came through (AsyncReserver
        callback): proceed to the remote request."""
        if self.phase != WAIT_BACKFILL or self.bf_target is None:
            self.d.release_local_backfill(self.pg)
            return
        self._phase_ticks = 0
        self.bf_reserved_local = True
        self._send(self.bf_target, BackfillReserve(
            pgid=self.pg, from_osd=self.d.whoami, op="request"))

    def on_reserve(self, msg: BackfillReserve) -> bool:
        """Returns False for a grant this round cannot use (superseded
        peering): the caller releases it, or the target's slot leaks
        and jams every later backfill at osd_max_backfills=1.  A
        DUPLICATE grant for the reservation we actively hold (the
        retry tick re-requested, the target re-granted) is consumed
        silently — releasing it would free the in-use slot."""
        if msg.from_osd == self.bf_target and msg.op == "grant" and \
                self.bf_reserved_remote:
            return True                    # duplicate for a held slot
        if self.phase != WAIT_BACKFILL or msg.from_osd != self.bf_target:
            return msg.op != "grant"
        if msg.op == "grant":
            self.bf_reserved_remote = True
            self.phase = BACKFILLING
            self._phase_ticks = 0
            self._log(4, "backfill -> osd.%d starts", self.bf_target)
            self._scan_window()
        elif msg.op == "reject":
            # saturated target (the reference's REJECT_TOOFULL): the
            # retry tick re-requests after the backoff window
            self._phase_ticks = -2 * _RETRY_TICKS
        return True

    def tick(self, now: float) -> None:
        """Stuck-phase re-drive (from heartbeat_tick; `now` may be
        simulated, so pacing is tick-counted, not wall-clock).  Any
        phase whose expected reply got lost — a send that failed, a
        connection that dropped without the peer going down — is
        re-driven idempotently after _RETRY_TICKS."""
        if self.phase == CLEAN:
            return
        self._phase_ticks += 1
        if self._phase_ticks < _RETRY_TICKS:
            return
        self._phase_ticks = 0
        if self.phase == GETINFO and self.pending_info:
            for o in list(self.pending_info):
                if not self._send(o, PGQuery(pgid=self.pg,
                                             epoch=self.epoch)):
                    self.pending_info.discard(o)
            if not self.pending_info:
                self._choose_auth()
        elif self.phase == GETLOG and self.auth is not None:
            self._send(self.auth.osd, PGLogReq(
                pgid=self.pg, since=self._my_info().last_update,
                epoch=self.epoch))
        elif self.phase == GETMISSING and self.pending_missing:
            shard = self._shard()
            head, tail = shard.log_info()
            entries = list(shard.pg_log.log.entries)
            for o in list(self.pending_missing):
                self._send(o, PGLogPush(
                    pgid=self.pg, from_osd=self.d.whoami,
                    entries=entries, head=head, tail=tail,
                    activate=True, epoch=self.epoch))
        elif self.phase == RECOVERING and \
                (self.pull_pending or
                 (self.primary_backfill_from is not None and
                  not self._log_adopted)):
            if self.primary_backfill_from is not None:
                self._send(self.primary_backfill_from,
                           PGScan(pgid=self.pg, ec=False))
            else:
                shard = self._shard()
                missing = shard.pg_log.missing
                by_holder: dict[int, list] = {}
                for oid in list(self.pull_pending):
                    item = missing.items.get(oid)
                    holder = self._holder_for(
                        oid, item.need if item else ZERO_VERSION)
                    if holder is not None:
                        by_holder.setdefault(holder, []).append(oid)
                for osd, oids in by_holder.items():
                    self._send(osd, PGPull(pgid=self.pg, oids=oids))
        elif self.phase == WAIT_BACKFILL and self.bf_target is not None \
                and not self.bf_reserved_remote:
            if not self.bf_reserved_local and \
                    not self.d.reserve_local_backfill(self.pg):
                return
            self.bf_reserved_local = True
            self._send(self.bf_target, BackfillReserve(
                pgid=self.pg, from_osd=self.d.whoami, op="request"))
        elif self.phase == BACKFILLING and \
                self.bf_pushes_in_chunk <= 0:
            # a scan (or its reply) was lost: reissue the window
            self._scan_window()

    def _scan_window(self) -> None:
        """Open the next aligned (begin, end] window: end is our n-th
        object past the cursor, or unbounded on the final window so
        trailing strays on the target surface."""
        n = global_config()["osd_backfill_scan_max"]
        mine = sorted(o for o in self._shard().inventory()
                      if o > self.bf_cursor)
        window = mine[:n]
        self.bf_final_window = len(mine) <= n
        self.bf_end = "" if self.bf_final_window else window[-1]
        self._send(self.bf_target, PGScan(
            pgid=self.pg, ec=False, ranged=True,
            begin=self.bf_cursor, end=self.bf_end))

    def on_backfill_scan(self, msg) -> None:
        """One aligned window of the target's inventory: push what it
        lacks or holds stale, whiteout its strays, advance the cursor
        (ref: PrimaryLogPG::recover_backfill interval comparison)."""
        if self.phase != BACKFILLING or msg.from_osd != self.bf_target \
                or msg.begin != self.bf_cursor or msg.end != self.bf_end:
            return
        self._phase_ticks = 0
        shard = self._shard()
        inv = shard.inventory()
        window = [o for o in sorted(inv) if o > self.bf_cursor and
                  (self.bf_end == "" or o <= self.bf_end)]
        theirs = dict(msg.objects)
        jobs = []
        for oid in window:
            th = theirs.get(oid)
            # push on ANY difference, not just older: a divergent
            # survivor past trimmed history can carry a NEWER version
            # that must not outlive the authoritative interval
            if th is None or tuple(th[0]) != tuple(inv[oid][0]) or \
                    bool(th[1]) != bool(inv[oid][1]):
                jobs.append(oid)
        # target objects in this window that we do not have: divergent
        # strays — whiteout them (a versioned delete outranking the
        # stray's own version)
        for oid, (ver, _wo) in theirs.items():
            if oid not in inv:
                self.d._push_whiteout(self.pg, oid, self.bf_target,
                                      ver)
        self.bf_cursor = window[-1] if window else (self.bf_end or
                                                   self.bf_cursor)
        self.bf_pushes_in_chunk = len(jobs)
        if not jobs:
            self._window_done()
            return
        for oid in jobs:
            self.d.op_queue.enqueue(
                "recovery",
                lambda oid=oid: self._backfill_push(oid))
        self.d._drain_op_queue()

    def _backfill_push(self, oid: str) -> None:
        try:
            self.d._push_object(self.pg, self.st, oid, self.bf_target,
                                backfill=True)
        finally:
            self.bf_pushes_in_chunk -= 1
            if self.bf_pushes_in_chunk <= 0:
                self._window_done()

    def _window_done(self) -> None:
        if self.phase != BACKFILLING:
            return
        # advance write gating only after the window's pushes were
        # sent: a subsequent replica write for an object at or before
        # the cursor rides the same ordered connection as its push
        b = self.st.backend
        target = self.bf_target
        if not self.bf_final_window:
            if b is not None and target in b.backfill_peers:
                b.backfill_peers[target] = self.bf_cursor
            self._scan_window()
            return
        # complete: install the authoritative log on the target (or
        # its pg_info stays pre-tail and every subsequent interval
        # re-walks the whole PG), then drop the gating entry — the
        # target is an ordinary replica now and receives every write
        shard = self._shard()
        head, tail = shard.log_info()
        self._send(target, PGLogPush(
            pgid=self.pg, from_osd=self.d.whoami,
            entries=list(shard.pg_log.log.entries), head=head,
            tail=tail, activate=True, full=True, epoch=self.epoch))
        if b is not None:
            b.backfill_peers.pop(target, None)
        self._log(4, "backfill -> osd.%d complete", target)
        self._send(target, BackfillReserve(
            pgid=self.pg, from_osd=self.d.whoami, op="release"))
        self.bf_reserved_remote = False
        self.backfill_targets.pop(0)
        self.bf_target = None
        self._next_backfill_target()

    # ------------------------------------------------------------ Clean
    def _enter_clean(self) -> None:
        self.phase = CLEAN
        self.st.recovering = False
        self.st.backfilling = False
        if self.bf_reserved_local:
            self.d.release_local_backfill(self.pg)
            self.bf_reserved_local = False
        if self.primary_backfill_from is not None:
            # direct pull converged first: drop the pg_temp request
            self.d.clear_pg_temp(self.pg)
            self.primary_backfill_from = None
        m = self.d.osdmap
        up, _, acting, _ = m.pg_to_up_acting_osds(self.pg)
        current = {o for o in list(up) + list(acting)
                   if 0 <= o < CRUSH_ITEM_NONE}
        if self.d.whoami in current and set(acting) != set(up):
            # we are the temp primary: hand the interval back
            self.d.clear_pg_temp(self.pg)
        for o, info in self.infos.items():
            if o not in current and (info.have_data or
                                     info.last_update != ZERO_VERSION):
                self._send(o, PGRemove(pgid=self.pg,
                                       epoch=self.d.osdmap.epoch))
        self._log(10, "clean")

    # ---------------------------------------------------------- aborts
    def on_map_advance(self) -> None:
        """Same-interval map advance: drop peers that died so a phase
        cannot wedge on a reply that will never come."""
        alive = lambda o: self.d.osdmap.is_up(o)   # noqa: E731
        if self.phase == GETINFO:
            dead = {o for o in self.pending_info if not alive(o)}
            if dead:
                self.pending_info -= dead
                if not self.pending_info:
                    self._choose_auth()
        elif self.phase == GETLOG and self.auth is not None and \
                not alive(self.auth.osd):
            # auth died: re-choose among the survivors
            self.infos.pop(self.auth.osd, None)
            self.phase = GETINFO
            self._choose_auth()
        elif self.phase == GETMISSING:
            dead = {o for o in self.pending_missing if not alive(o)}
            if dead:
                self.pending_missing -= dead
                if not self.pending_missing:
                    self._activate()
        elif self.phase in (WAIT_BACKFILL, BACKFILLING) and \
                self.bf_target is not None and not alive(self.bf_target):
            self.backfill_targets = [o for o in self.backfill_targets
                                     if alive(o)]
            self.bf_target = None
            self.bf_reserved_remote = False
            self._next_backfill_target()

    def abort(self) -> None:
        """A new interval superseded this round: release reservations
        (held OR queued) so the restart — or another PG — can take
        them."""
        self.d.release_local_backfill(self.pg)   # also dequeues
        self.bf_reserved_local = False
        if self.bf_target is not None:
            # release any held/queued remote slot; an unconsumed
            # in-flight grant bounces back via the daemon's
            # release-unconsumed path
            self._send(self.bf_target, BackfillReserve(
                pgid=self.pg, from_osd=self.d.whoami, op="release"))
            self.bf_reserved_remote = False
        self.phase = CLEAN          # inert: no handler acts on us
