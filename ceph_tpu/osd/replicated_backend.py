"""ReplicatedBackend: the replicated-pool PG data plane.

The primary applies each write locally and fans whole-object segments
out to the replicas, acking the client once every acting shard
committed (ref: src/osd/ReplicatedBackend.{h,cc}: submit_transaction
:1069 -> issue_op :999, replica side sub_op_modify/do_repop :1148;
reads are served from the primary's full local copy, unlike the EC
reconstruct path).
"""
from __future__ import annotations

import threading

from ..common.lockdep import make_lock
from dataclasses import dataclass, field
from typing import Callable

from ..common.log import dout
from ..msg.messages import RepOpReply, RepOpWrite
from ..store import ObjectId, StoreError, Transaction
from . import mutations as mut
from .ec_backend import OI_ATTR, pg_cid
from .pg_log import PGLog
from .pg_types import DELETE, EVersion, MODIFY, PGLogEntry, ZERO_VERSION


#: pgmeta omap key prefix for persisted log entries; the key embeds the
#: zero-padded (epoch, version) so lexicographic omap order IS log
#: order (ref: PGLog.cc write_log_and_missing — log entries are rocksdb
#: keys under the pgmeta object the same way)
_LOG_KEY = "l.{:010d}.{:012d}"
_TAIL_KEY = "t"           # persisted log tail marker (EVersion)

PGMETA = ObjectId("pgmeta")


def _log_key(v) -> str:
    return _LOG_KEY.format(v.epoch, v.version)


def build_persist_log_txn(store, cid: str, log) -> Transaction:
    """The full durable-log rewrite transaction (after a peering
    merge, where entries were rewound/replaced, not appended) —
    shared by the replicated and EC shards.  Non-log pgmeta keys —
    the snap-mapper index and the purged_snaps cursor — survive the
    rewrite: wiping them with the stale log keys would silently leak
    every clone awaiting trim."""
    from ..msg import encoding as wire
    txn = Transaction()
    preserved = {}
    if not store.collection_exists(cid):
        txn.create_collection(cid)
    elif store.exists(cid, PGMETA):
        preserved = {k: v for k, v in
                     store.omap_get(cid, PGMETA).items()
                     if not k.startswith("l.") and k != _TAIL_KEY}
    txn.touch(cid, PGMETA)
    txn.omap_clear(cid, PGMETA)
    txn.omap_setkeys(cid, PGMETA, dict(
        {_log_key(e.version): wire.encode(e) for e in log.entries},
        **{_TAIL_KEY: wire.encode(log.tail)},
        **preserved))
    return txn


class ReplicatedPGShard:
    """Per-OSD service for one replicated PG (primary or replica).

    The shard's pg_log is durable: every apply writes its entries into
    the pgmeta object's omap in the SAME store transaction as the data
    (ref: PGLog::write_log_and_missing riding the op's txn), so a
    restarted OSD re-peers from real log bounds instead of an empty
    log that would force a full backfill."""

    def __init__(self, pgid, store, create: bool = True):
        from .snap_mapper import SnapMapper
        self.pgid = pgid
        self.store = store
        self.cid = pg_cid(pgid)
        self.pg_log = PGLog()
        #: persistent snap->clone index + purged_snaps cursor, stored
        #: in the pgmeta omap next to the log (osd/snap_mapper.py)
        self.snap_mapper = SnapMapper(store, self.cid)
        if create and not store.collection_exists(self.cid):
            store.queue_transaction(
                Transaction().create_collection(self.cid))
        self._load_log()

    # -- durable log ---------------------------------------------------
    def _load_log(self) -> None:
        from ..msg import encoding as wire
        if not self.store.collection_exists(self.cid) or \
                not self.store.exists(self.cid, PGMETA):
            return
        omap = self.store.omap_get(self.cid, PGMETA)
        entries = [wire.decode(v) for k, v in sorted(omap.items())
                   if k.startswith("l.")]
        if not entries and _TAIL_KEY not in omap:
            return
        tail = wire.decode(omap[_TAIL_KEY]) if _TAIL_KEY in omap \
            else ZERO_VERSION
        from .pg_log import IndexedLog
        head = entries[-1].version if entries else tail
        self.pg_log = PGLog(IndexedLog(entries, head=head, tail=tail))

    def _log_txn_ops(self, txn: Transaction, new_entries: list) -> list:
        """Append `new_entries` to the durable log inside `txn`, and
        trim when past osd_max_pg_log_entries (down to
        osd_min_pg_log_entries, ref: PG::calc_trim_to).  Returns the
        entries to drop from the in-memory log AFTER the txn commits
        (a failed txn must not trim memory ahead of disk)."""
        from ..common.options import global_config
        from ..msg import encoding as wire
        txn.touch(self.cid, PGMETA)
        txn.omap_setkeys(self.cid, PGMETA,
                         {_log_key(e.version): wire.encode(e)
                          for e in new_entries})
        cfg = global_config()
        total = len(self.pg_log.log) + len(new_entries)
        dropped: list = []
        if total > cfg["osd_max_pg_log_entries"]:
            drop = total - cfg["osd_min_pg_log_entries"]
            dropped = self.pg_log.log.entries[:drop]
            if dropped:
                txn.omap_rmkeys(self.cid, PGMETA,
                                [_log_key(e.version) for e in dropped])
                txn.omap_setkeys(self.cid, PGMETA, {
                    _TAIL_KEY: wire.encode(dropped[-1].version)})
        return dropped

    def persist_log(self) -> None:
        """Rewrite the whole durable log (see build_persist_log_txn —
        non-log pgmeta keys survive)."""
        self.store.queue_transaction(
            build_persist_log_txn(self.store, self.cid,
                                  self.pg_log.log))

    def log_info(self) -> tuple:
        """(last_update, log_tail) — the pg_info_t core the peering
        GetInfo phase exchanges."""
        return self.pg_log.log.head, self.pg_log.log.tail

    # -- local apply (both roles; ref: ReplicatedBackend.cc:1148) ------
    # Deletes leave a zero-length *whiteout* carrying the delete's
    # version (ref: the cache-tier whiteout concept, object_info flag
    # FLAG_WHITEOUT): recovery compares versions, so a delete must be
    # a versioned event or a stale replica would resurrect the object.
    def apply_mutations(self, oid: str, muts: list, version,
                        log_entries, clone_snap=None,
                        clone_covers=None, snap_seq: int = 0) -> bool:
        """Apply a mutation vector as one atomic store transaction
        (the replica-side analogue of the reference's per-repop
        ObjectStore::Transaction built by PrimaryLogPG::do_osd_ops).

        `clone_snap`/`clone_covers`: the primary's COW decision (ref:
        PrimaryLogPG::make_writeable): before the mutation, the current
        head is preserved as `oid@clone_snap`, serving reads for the
        snapids in clone_covers.  Head object-info tracks `snap_seq`
        (pool seq at last write) and the `clones` map."""
        soid = ObjectId(oid)
        txn = Transaction()
        old_oi = self.head_oi(oid)
        clones = dict(old_oi.get("clones", {}))
        head_live = bool(old_oi) and not old_oi.get("whiteout")
        try:
            if clone_snap is not None and head_live:
                # COW: preserve the pre-write head (data+attrs+omap),
                # and index the clone in the SAME txn so the snap
                # trimmer can never miss it (ref: SnapMapper::add_oid
                # riding the repop transaction)
                txn.clone(self.cid, soid,
                          ObjectId(oid, snap=clone_snap))
                clones[clone_snap] = list(clone_covers or [])
                self.snap_mapper.add_clone(txn, oid, clone_snap,
                                           list(clone_covers or []))
            new_seq = max(old_oi.get("snap_seq", 0), snap_seq)
            if mut.is_delete(muts):
                if self.store.exists(self.cid, soid):
                    txn.remove(self.cid, soid)
                txn.touch(self.cid, soid)
                txn.setattr(self.cid, soid, OI_ATTR,
                            {"size": 0, "version": version,
                             "whiteout": True, "snap_seq": new_seq,
                             "clones": clones})
            else:
                if old_oi.get("whiteout"):
                    txn.remove(self.cid, soid)
                    txn.touch(self.cid, soid)
                    size = 0
                else:
                    size = self.object_size(oid)
                    txn.touch(self.cid, soid)
                size = self._build_mutation_txn(txn, soid, muts, size)
                txn.setattr(self.cid, soid, OI_ATTR,
                            {"size": size, "version": version,
                             "snap_seq": new_seq, "clones": clones})
            new_entries = [e for e in log_entries
                           if e.version > self.pg_log.log.head]
            dropped = self._log_txn_ops(txn, new_entries) \
                if new_entries else []
            if not txn.empty():
                self.store.queue_transaction(txn)
            if dropped:
                self.pg_log.log.trim_to(dropped[-1].version)
            for e in new_entries:
                self.pg_log.append(e)
            return True
        except StoreError as err:
            dout("osd", 0).write("%s replicated apply failed: %s",
                                 self.pgid, err)
            return False

    def head_oi(self, oid: str) -> dict:
        """The head's object-info attr ({} when absent)."""
        try:
            return dict(self.store.getattr(self.cid, ObjectId(oid),
                                           OI_ATTR))
        except StoreError:
            return {}

    # -- snapshots (ref: SnapSet resolution in PrimaryLogPG::find_object_context)
    def resolve_snap(self, oid: str, snapid: int):
        """What serves a read at `snapid`: a clone tag, "head", or
        None (the object did not exist at that snap)."""
        oi = self.head_oi(oid)
        covering = sorted(
            int(tag) for tag, covers in oi.get("clones", {}).items()
            if snapid in covers)
        if covering:
            return covering[0]
        if oi and not oi.get("whiteout") and \
                snapid > oi.get("snap_seq", 0):
            return "head"
        return None

    def read_clone(self, oid: str, tag: int, offset: int = 0,
                   length: int = 0) -> bytes:
        csoid = ObjectId(oid, snap=tag)
        try:
            size = self.store.getattr(self.cid, csoid,
                                      OI_ATTR)["size"]
        except StoreError:
            raise StoreError("ENOENT", f"{oid}@{tag}")
        return bytes(self.store.read(
            self.cid, csoid, offset, length or max(0, size - offset)))

    def clone_tags(self, oid: str) -> dict[int, list[int]]:
        return {int(t): list(c) for t, c in
                self.head_oi(oid).get("clones", {}).items()}

    def _build_mutation_txn(self, txn: Transaction, soid: ObjectId,
                            muts: list, size: int) -> int:
        """Append store ops for each mutation; returns the new logical
        size (tracked in the oi xattr like the reference's object_info_t
        size field)."""
        for m in muts:
            kind = m[0]
            if kind == mut.M_WRITE:
                _, off, data = m
                txn.write(self.cid, soid, off, data)
                size = max(size, off + len(data))
            elif kind == mut.M_WRITEFULL:
                data = m[1]
                txn.truncate(self.cid, soid, 0)
                txn.write(self.cid, soid, 0, data)
                size = len(data)
            elif kind == mut.M_APPEND:
                data = m[1]
                txn.write(self.cid, soid, size, data)
                size += len(data)
            elif kind == mut.M_TRUNCATE:
                newsz = m[1]
                txn.truncate(self.cid, soid, newsz)
                size = newsz
            elif kind == mut.M_ZERO:
                _, off, length = m
                # librados zero never extends the object
                # (ref: PrimaryLogPG CEPH_OSD_OP_ZERO: trims the range
                # to the object size)
                end = min(off + length, size)
                if end > off:
                    txn.zero(self.cid, soid, off, end - off)
            elif kind == mut.M_CREATE:
                pass                      # the leading touch created it
            elif kind == mut.M_ROLLBACK:
                # restore head wholesale from the clone: data, xattrs
                # and omap all revert (ref: PrimaryLogPG _rollback_to)
                tag = m[1]
                csoid = ObjectId(soid.name, snap=tag)
                if not self.store.exists(self.cid, csoid):
                    raise StoreError("ENOENT",
                                     f"{soid.name}@{tag} clone")
                txn.clone(self.cid, csoid, soid)
                size = self.store.getattr(self.cid, csoid,
                                          OI_ATTR)["size"]
            elif kind == mut.M_SETXATTRS:
                txn.setattrs(self.cid, soid,
                             {mut.uxattr_key(k): bytes(v)
                              for k, v in m[1].items()})
            elif kind == mut.M_RMXATTR:
                txn.rmattr(self.cid, soid, mut.uxattr_key(m[1]))
            elif kind == mut.M_OMAP_SETKEYS:
                txn.omap_setkeys(self.cid, soid, m[1])
            elif kind == mut.M_OMAP_RMKEYS:
                txn.omap_rmkeys(self.cid, soid, m[1])
            elif kind == mut.M_OMAP_CLEAR:
                txn.omap_clear(self.cid, soid)
                txn.rmattr(self.cid, soid, mut.OMAP_HEADER_ATTR)
            elif kind == mut.M_OMAP_SETHEADER:
                txn.setattr(self.cid, soid, mut.OMAP_HEADER_ATTR,
                            bytes(m[1]))
            else:
                raise StoreError("EINVAL", f"bad mutation {kind}")
        return size

    def apply_write(self, oid: str, offset: int, data: bytes,
                    delete: bool, version, log_entries) -> bool:
        """Whole-object convenience used by recovery pushes."""
        muts = [(mut.M_DELETE,)] if delete \
            else [(mut.M_WRITE, offset, data)]
        return self.apply_mutations(oid, muts, version, log_entries)

    def push_payload(self, oid: str) -> tuple:
        """(data, user_attrs, omap, omap_hdr) for a recovery/repair
        push (ref: ReplicatedBackend::build_push_op gathers data,
        attrs and omap into the PushOp)."""
        soid = ObjectId(oid)
        return (self.read(oid),
                mut.user_xattrs(self.store.getattrs(self.cid, soid)),
                dict(self.store.omap_get(self.cid, soid)),
                self.omap_get_header(oid))

    def _clones_digest(self, oid: str) -> int:
        from ..common.crc32c import crc32c
        clone_digest = {}
        for tag in sorted(self.clone_tags(oid)):
            csoid = ObjectId(oid, snap=tag)
            try:
                cdata = self.store.read(self.cid, csoid, 0, 0)
                # rollback restores attrs/omap too, so scrub must
                # cover them, not just the clone's bytes
                meta = (mut.meta_digest(self.store.getattrs(
                            self.cid, csoid))
                        ^ mut.meta_digest(self.store.omap_get(
                            self.cid, csoid)))
            except StoreError:
                cdata, meta = b"\0MISSING", 0
            clone_digest[str(tag)] = (
                int(crc32c(0xFFFFFFFF, cdata)) ^ meta
            ).to_bytes(8, "big", signed=False)
        return mut.meta_digest(clone_digest)

    def clone_payloads(self, oid: str) -> dict:
        """Snapshot state accompanying a push: the rebuilt copy must
        serve snap reads too (ref: recovery pushes every clone of an
        object, PGBackend::objects_list_range + per-clone PushOps).
        {} when the object has no snapshot history."""
        oi = self.head_oi(oid)
        tags = self.clone_tags(oid)
        if not tags and not oi.get("snap_seq"):
            return {}
        items = []
        for tag, covers in sorted(tags.items()):
            csoid = ObjectId(oid, snap=tag)
            if not self.store.exists(self.cid, csoid):
                continue
            items.append({"snap": tag, "covers": covers,
                          "data": bytes(self.store.read(
                              self.cid, csoid, 0, 0)),
                          "attrs": dict(self.store.getattrs(
                              self.cid, csoid)),
                          "omap": dict(self.store.omap_get(
                              self.cid, csoid))})
        return {"snap_seq": oi.get("snap_seq", 0), "items": items}

    def apply_clone_payloads(self, oid: str, payload: dict) -> None:
        """One atomic transaction for every clone AND the head-oi
        graft: a crash between them would leave clones the head no
        longer references (snap reads ENOENT, COW skipped).

        The pushed history is AUTHORITATIVE: local clones absent from
        it (divergent-write leftovers) are removed and the clones map
        replaced, or scrub repair could never converge."""
        if not payload and not self.clone_tags(oid):
            return
        payload = payload or {}
        txn = Transaction()
        clones_map = {}
        for c in payload.get("items", []):
            clones_map[c["snap"]] = list(c["covers"])
            csoid = ObjectId(oid, snap=c["snap"])
            txn.touch(self.cid, csoid)
            txn.truncate(self.cid, csoid, 0)
            txn.write(self.cid, csoid, 0, c["data"])
            txn.setattrs(self.cid, csoid, c["attrs"])
            if c.get("omap"):
                txn.omap_clear(self.cid, csoid)
                txn.omap_setkeys(self.cid, csoid, c["omap"])
        for tag in self.clone_tags(oid):
            if tag not in clones_map and self.store.exists(
                    self.cid, ObjectId(oid, snap=tag)):
                txn.remove(self.cid, ObjectId(oid, snap=tag))
        # graft the snap history back onto the freshly-pushed head oi
        oi = self.head_oi(oid)
        oi["clones"] = clones_map
        oi["snap_seq"] = max(oi.get("snap_seq", 0),
                             payload.get("snap_seq", 0))
        txn.setattr(self.cid, ObjectId(oid), OI_ATTR, oi)
        # re-index atomically with the adopted clone set: the rebuilt
        # copy must be trimmable exactly like the source was
        self.snap_mapper.replace_object(txn, oid, clones_map)
        self.store.queue_transaction(txn)

    # -- snaptrim (ref: PrimaryLogPG::trim_object — the per-clone trim
    #    transaction both the primary and its replicas apply) ---------
    def apply_snap_trim(self, oid: str, snap: int, clone: int) -> bool:
        """Drop `snap` from `oid`'s clone `clone`: remove it from the
        clone's covers, delete the clone object outright once no
        covered snap remains, and unindex — all one transaction, so
        the snap index stays an exact cursor of remaining work.
        Idempotent: re-applying after a primary failover finds the
        index entry gone and succeeds without touching the store."""
        if not self.store.collection_exists(self.cid):
            return True          # nothing here to trim (map lag view)
        txn = Transaction()
        oi = self.head_oi(oid)
        clones = {int(t): list(c)
                  for t, c in oi.get("clones", {}).items()}
        try:
            if clone in clones:
                covers = [c for c in clones[clone] if c != snap]
                csoid = ObjectId(oid, snap=clone)
                if covers:
                    clones[clone] = covers
                    self.snap_mapper.rm(txn, snap, oid, clone)
                else:
                    old_covers = clones.pop(clone)
                    if self.store.exists(self.cid, csoid):
                        txn.remove(self.cid, csoid)
                    self.snap_mapper.rm_clone(txn, oid, clone,
                                              old_covers)
                if self.store.exists(self.cid, ObjectId(oid)):
                    if not clones and oi.get("whiteout"):
                        # a deleted head kept alive only by its snap
                        # history: the last trimmed clone takes the
                        # whiteout with it (ref: trim_object removing
                        # the head when the SnapSet empties) — a
                        # lagging stray still converges via the
                        # backfill walk's stray-whiteout leg
                        txn.remove(self.cid, ObjectId(oid))
                    else:
                        oi["clones"] = clones
                        txn.setattr(self.cid, ObjectId(oid), OI_ATTR,
                                    oi)
            else:
                # already trimmed (resumed round / duplicate op):
                # clear any stale index key and report success
                self.snap_mapper.rm(txn, snap, oid, clone)
            if not txn.empty():
                self.store.queue_transaction(txn)
            return True
        except StoreError as err:
            dout("osd", 0).write("%s snap trim %s@%s failed: %s",
                                 self.pgid, oid, clone, err)
            return False

    def purged_snaps(self):
        return self.snap_mapper.purged_snaps()

    def mark_purged(self, snap: int) -> None:
        self.snap_mapper.mark_purged(snap)

    def collection_bytes(self) -> int:
        """Physical bytes this PG stores (heads + snap clones) — the
        store-accounting feed for pg stats."""
        from .snap_mapper import collection_bytes
        return collection_bytes(self.store, self.cid)

    def stat_summary(self) -> tuple[int, int, int]:
        """(client_objects, logical_bytes, store_bytes) in ONE
        collection pass — the periodic pg-stat feed (a separate
        objects() + collection_bytes() pair would walk the
        collection twice per report)."""
        if not self.store.collection_exists(self.cid):
            return (0, 0, 0)
        n = logical = store = 0
        for o in self.store.collection_list(self.cid):
            try:
                store += self.store.stat(self.cid, o)["size"]
            except StoreError:
                continue
            if o.name == "pgmeta" or o.snap != -2:
                continue
            try:
                oi = self.store.getattr(self.cid, o, OI_ATTR)
            except StoreError:
                continue
            if oi.get("whiteout"):
                continue
            n += 1
            logical += oi.get("size", 0)
        return (n, logical, store)

    def _is_whiteout(self, soid: ObjectId) -> bool:
        try:
            return bool(self.store.getattr(self.cid, soid,
                                           OI_ATTR).get("whiteout"))
        except StoreError:
            return False

    def handle_rep_write(self, m: RepOpWrite, whoami: int) -> RepOpReply:
        ok = self.apply_mutations(m.oid, m.mutations, m.version,
                                  m.log_entries,
                                  clone_snap=m.clone_snap,
                                  clone_covers=m.clone_covers,
                                  snap_seq=m.snap_seq)
        return RepOpReply(pgid=m.pgid, tid=m.tid, from_osd=whoami,
                          committed=ok)

    def read(self, oid: str, offset: int = 0, length: int = 0) -> bytes:
        size = self.object_size(oid)
        if not self.exists(oid):
            raise StoreError("ENOENT", f"{oid} does not exist")
        buf = self.store.read(self.cid, ObjectId(oid), offset,
                              length or max(0, size - offset))
        return bytes(buf)

    def object_size(self, oid: str) -> int:
        try:
            return self.store.getattr(self.cid, ObjectId(oid),
                                      OI_ATTR)["size"]
        except StoreError:
            return 0

    # -- metadata reads (primary-local; ref: PrimaryLogPG getattr/omap
    #    op handling reads the local object like any replicated read) --
    def getxattrs(self, oid: str) -> dict[str, bytes]:
        if not self.exists(oid):
            raise StoreError("ENOENT", oid)
        return mut.user_xattrs(self.store.getattrs(self.cid,
                                                   ObjectId(oid)))

    def getxattr(self, oid: str, name: str) -> bytes:
        xattrs = self.getxattrs(oid)
        if name not in xattrs:
            raise StoreError("ENODATA", f"{oid} xattr {name}")
        return xattrs[name]

    def omap_get(self, oid: str) -> dict[str, bytes]:
        if not self.exists(oid):
            raise StoreError("ENOENT", oid)
        return dict(self.store.omap_get(self.cid, ObjectId(oid)))

    def omap_get_header(self, oid: str) -> bytes:
        if not self.exists(oid):
            raise StoreError("ENOENT", oid)
        try:
            return bytes(self.store.getattr(self.cid, ObjectId(oid),
                                            mut.OMAP_HEADER_ATTR))
        except StoreError:
            return b""

    def object_version(self, oid: str) -> tuple[int, int]:
        """(epoch, version) from the oi xattr; (0,0) when unknown —
        the recovery inventory's ordering key."""
        try:
            v = self.store.getattr(self.cid, ObjectId(oid),
                                   OI_ATTR).get("version")
        except StoreError:
            return (0, 0)
        if isinstance(v, EVersion):
            return (v.epoch, v.version)
        return tuple(v) if v else (0, 0)

    def objects(self) -> list[str]:
        """Client-visible objects (whiteouts + snap clones excluded)."""
        if not self.store.collection_exists(self.cid):
            return []
        return sorted({o.name for o in self.store.collection_list(self.cid)
                       if o.name != "pgmeta" and o.snap == -2
                       and not self._is_whiteout(o)})

    def inventory(self) -> dict[str, tuple]:
        """Recovery inventory incl. whiteouts (head objects only —
        clones travel with their head's pushes):
        oid -> ((epoch, version), whiteout)."""
        if not self.store.collection_exists(self.cid):
            return {}
        out = {}
        for o in self.store.collection_list(self.cid):
            if o.name == "pgmeta" or o.snap != -2:
                continue
            out[o.name] = (self.object_version(o.name),
                           self._is_whiteout(o))
        return out

    def exists(self, oid: str) -> bool:
        soid = ObjectId(oid)
        return self.store.collection_exists(self.cid) and \
            self.store.exists(self.cid, soid) and \
            not self._is_whiteout(soid)

    def scrub_map(self, deep: bool = True) -> dict:
        """Per-object (version, size, digest) inventory for scrub
        (ref: src/osd/scrubber_common.h ScrubMap;
        PrimaryLogPG::build_scrub_map_chunk)."""
        from ..common.crc32c import crc32c
        out: dict[str, dict] = {}
        for oid, (ver, whiteout) in self.inventory().items():
            if whiteout:
                entry = {"version": ver, "size": 0, "crc": None,
                         "whiteout": True, "ok": True}
                if deep:
                    # a deleted head can still carry live snapshot
                    # clones — they must scrub like any replicated state
                    entry["clones_crc"] = self._clones_digest(oid)
                out[oid] = entry
                continue
            try:
                data = self.read(oid)
            except StoreError:
                out[oid] = {"version": ver, "size": -1, "crc": None,
                            "whiteout": False, "ok": False}
                continue
            entry = {"version": ver, "size": len(data),
                     "crc": int(crc32c(0xFFFFFFFF, data))
                     if deep else None,
                     "whiteout": False, "ok": True}
            if deep:
                # metadata digests: divergent xattrs/omap are an
                # inconsistency too (ref: ScrubMap::object attrs +
                # omap_digest)
                soid = ObjectId(oid)
                entry["attrs_crc"] = mut.meta_digest(
                    mut.user_xattrs(self.store.getattrs(self.cid,
                                                        soid)))
                entry["omap_crc"] = mut.meta_digest(
                    self.store.omap_get(self.cid, soid),
                    self.omap_get_header(oid))
                # snapshot clones are replicated state too: a copy
                # missing (or corrupting) a clone must scrub unequal
                entry["clones_crc"] = self._clones_digest(oid)
            out[oid] = entry
        return out


@dataclass
class _RepWrite:
    tid: int
    on_all_commit: Callable
    pending: set = field(default_factory=set)
    failed: set = field(default_factory=set)


class ReplicatedBackend:
    """Primary-side engine for one replicated PG."""

    def __init__(self, pgid, whoami: int, acting: list[int],
                 local_shard: ReplicatedPGShard,
                 send: Callable[[int, object], bool], epoch: int = 1,
                 tid_gen=None):
        self.pgid = pgid
        self.whoami = whoami
        self.acting = list(acting)
        self.local_shard = local_shard
        self.send = send                 # send(osd_id, msg) -> bool
        self.epoch = epoch
        # version continuity across primary changes: resume AFTER the
        # durable log head, or a rebuilt primary in the same epoch
        # would re-issue versions its log already holds
        self.last_version = local_shard.pg_log.log.head
        #: backfill targets' write-gating cursors (osd -> last_backfill
        #: oid; the entry is REMOVED once the walk completes — ref: the
        #: last_backfill gating in PrimaryLogPG::issue_repop): ops fan
        #: out to a target only for objects the walk already copied
        self.backfill_peers: dict[int, str] = {}
        self._tid = 0
        self._tid_gen = tid_gen    # see ECBackend: no tid reuse across
        self._lock = make_lock(              # backend rebuilds
            f"osd.{whoami}.repbackend.{pgid}")
        self.in_flight: dict[int, _RepWrite] = {}
        # pool snapshot state (daemon refreshes on every map;
        # ref: pg_pool_t snap_seq/snaps/removed_snaps feeding the
        # SnapContext)
        self.pool_snap_seq = 0
        self.pool_snaps: dict[int, str] = {}
        self.pool_removed_snaps: set[int] = set()

    def _next_tid(self) -> int:
        if self._tid_gen is not None:
            return next(self._tid_gen)
        self._tid += 1
        return self._tid

    def fail_in_flight(self) -> None:
        with self._lock:
            ops = list(self.in_flight.values())
            self.in_flight.clear()
        for op in ops:
            op.on_all_commit(False)

    def _next_version(self) -> EVersion:
        self.last_version = EVersion(
            max(self.epoch, self.last_version.epoch),
            self.last_version.version + 1)
        return self.last_version

    def _resolve_muts(self, oid: str, muts: list) -> list:
        """Normalize size-relative mutations (append, zero-clamp)
        against the primary's authoritative object size BEFORE the
        replica fan-out.  A replica whose local state lags (e.g. a
        recovery push racing this write) would otherwise resolve
        `append` against a different size and diverge at the same
        version — the reference avoids this the same way: the primary
        serializes the concrete extent into the repop transaction."""
        out = []
        size = self.local_shard.object_size(oid)
        for m in muts:
            kind = m[0]
            if kind == mut.M_ROLLBACK:
                try:
                    size = self.local_shard.store.getattr(
                        self.local_shard.cid,
                        ObjectId(oid, snap=m[1]), OI_ATTR)["size"]
                except StoreError:
                    size = 0
                out.append(m)
                continue
            if kind == mut.M_APPEND:
                m = (mut.M_WRITE, size, m[1])
            elif kind == mut.M_ZERO:
                end = min(m[1] + m[2], size)
                if end <= m[1]:
                    continue                   # nothing within bounds
                m = (mut.M_ZERO, m[1], end - m[1])
            if m[0] == mut.M_WRITE:
                size = max(size, m[1] + len(m[2]))
            elif m[0] == mut.M_WRITEFULL:
                size = len(m[1])
            elif m[0] == mut.M_TRUNCATE:
                size = m[1]
            out.append(m)
        return out

    def _snap_context(self, snapc) -> tuple[int, list[int]]:
        """Effective snapshot context: the union of the client's snapc
        and this primary's own pool state, newest seq wins — a lagging
        OSD map must not lose a snapshot the client already saw, a
        lagging client must not roll one back, and SELF-MANAGED snapids
        (allocated at the mon but absent from pool.snaps — the librbd
        model) exist only in the client's snapc (ref: the snapc the
        MOSDOp carries vs pool snapc resolution in PrimaryLogPG)."""
        seq = max(self.pool_snap_seq,
                  (snapc or {}).get("seq", 0))
        snaps = sorted((set(self.pool_snaps)
                        | set((snapc or {}).get("snaps", [])))
                       - self.pool_removed_snaps)
        return seq, snaps

    def _cow_decision(self, oid: str, seq: int, snaps: list[int]):
        """Does this write need to preserve the head as a clone first
        (ref: PrimaryLogPG::make_writeable — head snapped since its
        last write -> clone before mutating)?"""
        if not seq:
            return None, []
        oi = self.local_shard.head_oi(oid)
        if not oi or oi.get("whiteout"):
            return None, []
        prev = oi.get("snap_seq", 0)
        if prev >= seq:
            return None, []
        covers = [s for s in snaps if prev < s <= seq]
        if not covers:
            return None, []        # the intervening snaps were deleted
        return seq, covers

    # -- writes (ref: ReplicatedBackend.cc:1069 submit_transaction) ----
    def submit_transaction(self, oid: str, muts: list,
                           on_all_commit: Callable,
                           snapc: dict | None = None,
                           trace: dict | None = None) -> int:
        """Apply a mutation vector locally then fan it out to every
        acting replica; `on_all_commit(ok)` once all committed."""
        with self._lock:
            tid = self._next_tid()
            version = self._next_version()
            muts = self._resolve_muts(oid, muts)
            seq, snaps = self._snap_context(snapc)
            clone_snap, covers = self._cow_decision(oid, seq, snaps)
            prior = EVersion(*self.local_shard.object_version(oid))
            entry = PGLogEntry(DELETE if mut.is_delete(muts) else MODIFY,
                               oid, version, prior_version=prior)
            ok = self.local_shard.apply_mutations(
                oid, muts, version, [entry], clone_snap=clone_snap,
                clone_covers=covers, snap_seq=seq)
            if not ok:
                on_all_commit(False)
                return tid
            replicas = [o for o in self.acting
                        if o >= 0 and o != self.whoami]
            for o in self.backfill_peers:
                if o not in replicas and o != self.whoami:
                    replicas.append(o)
            targets = []
            for o in replicas:
                cursor = self.backfill_peers.get(o)
                if cursor is not None and oid > cursor:
                    # past the target's last_backfill: the walk copies
                    # this object later, already carrying this write
                    # (ref: last_backfill gating in issue_repop)
                    continue
                targets.append(o)
            if not targets:
                on_all_commit(True)
                return tid
            op = _RepWrite(tid=tid, on_all_commit=on_all_commit,
                           pending=set(targets))
            self.in_flight[tid] = op
            from ..common.tracing import child_of
            msg = RepOpWrite(pgid=self.pgid, tid=tid, oid=oid,
                             mutations=list(muts), version=version,
                             log_entries=[entry],
                             clone_snap=clone_snap,
                             clone_covers=covers or [],
                             snap_seq=seq, trace=child_of(trace))
            for o in targets:
                if not self.send(o, msg):
                    op.failed.add(o)
                    op.pending.discard(o)
            self._maybe_done(op)
            return tid

    def handle_rep_reply(self, m: RepOpReply) -> None:
        with self._lock:
            op = self.in_flight.get(m.tid)
            if op is None:
                return
            if m.from_osd in op.pending:
                op.pending.discard(m.from_osd)
                if not m.committed:
                    op.failed.add(m.from_osd)
            self._maybe_done(op)

    def _maybe_done(self, op: _RepWrite) -> None:
        if op.pending:
            return
        self.in_flight.pop(op.tid, None)
        op.on_all_commit(not op.failed)

    # -- reads: primary local copy (ref: ReplicatedBackend::objects_read_sync)
    def read(self, oid: str, offset: int = 0, length: int = 0) -> bytes:
        return self.local_shard.read(oid, offset, length)

    def object_size(self, oid: str) -> int:
        return self.local_shard.object_size(oid)
