"""crushtool-lite: text codec round-trips, tester stats, CLI golden
shapes (ref: src/crush/CrushCompiler.cc, src/crush/CrushTester.cc:477,
src/test/cli/crushtool/compile-decompile-recompile.t model)."""
import numpy as np
import pytest

from ceph_tpu.crush import mapper as crush_mapper
from ceph_tpu.crush.compiler import (CompileError, compile_crushmap,
                                     decompile)
from ceph_tpu.crush.tester import CrushTester
from ceph_tpu.crush.wrapper import CrushWrapper
from ceph_tpu.tools import crushtool

MAP_TXT = """\
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1
tunable straw_calc_version 1

# devices
device 0 osd.0
device 1 osd.1
device 2 osd.2
device 3 osd.3
device 4 osd.4
device 5 osd.5

# types
type 0 osd
type 1 host
type 10 root

# buckets
host host0 {
\tid -2
\talg straw2
\thash 0
\titem osd.0 weight 1.000
\titem osd.1 weight 1.000
}
host host1 {
\tid -3
\talg straw2
\thash 0
\titem osd.2 weight 1.000
\titem osd.3 weight 1.000
}
host host2 {
\tid -4
\talg straw2
\thash 0
\titem osd.4 weight 1.000
\titem osd.5 weight 2.000
}
root default {
\tid -1
\talg straw2
\thash 0
\titem host0 weight 2.000
\titem host1 weight 2.000
\titem host2 weight 3.000
}

# rules
rule replicated_rule {
\tid 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}
rule ec_rule {
\tid 1
\ttype erasure
\tmin_size 3
\tmax_size 6
\tstep set_chooseleaf_tries 5
\tstep take default
\tstep chooseleaf indep 0 type host
\tstep emit
}

# end crush map
"""


def compiled():
    return compile_crushmap(MAP_TXT)


# ----------------------------------------------------------------- codec
def test_compile_structure():
    w = compiled()
    assert w.crush.max_devices == 6
    assert w.get_item_id("default") == -1
    assert w.get_item_id("host2") == -4
    assert w.get_type_id("root") == 10
    b = w.crush.bucket(-4)
    assert b.items == [4, 5]
    assert b.item_weights == [0x10000, 0x20000]
    assert w.crush.choose_total_tries == 50
    assert w.crush.chooseleaf_stable == 1
    assert w.get_rule_id("ec_rule") == 1
    assert w.crush.rules[1].mask.type == 3
    assert w.crush.rules[1].steps[0].arg1 == 5  # set_chooseleaf_tries


def test_decompile_compile_fixed_point():
    """decompile(compile(t)) is canonical: recompiling and decompiling
    again is a fixed point (compile-decompile-recompile.t model)."""
    w1 = compiled()
    t1 = decompile(w1)
    w2 = compile_crushmap(t1)
    t2 = decompile(w2)
    assert t1 == t2


def test_roundtrip_preserves_placements():
    w1 = compiled()
    w2 = compile_crushmap(decompile(w1))
    weights = [0x10000] * 6
    for ruleno in (0, 1):
        for x in range(200):
            a = crush_mapper.do_rule(w1.crush, ruleno, x, 4, weights)
            b = crush_mapper.do_rule(w2.crush, ruleno, x, 4, weights)
            assert a == b, (ruleno, x)


def test_compile_errors():
    with pytest.raises(CompileError):
        compile_crushmap("bogus line\n")
    with pytest.raises(CompileError):
        compile_crushmap("type 0 osd\nhost h { id -1\nitem osd.9 "
                         "weight 1.0\n}\n")  # undefined item
    with pytest.raises(CompileError):
        compile_crushmap("device 0 osd.0\n")  # no types


def test_decompile_matches_reference_shape():
    """Spot-check the exact line grammar the reference golden files pin
    (src/test/cli/crushtool/set-choose.crushmap.txt)."""
    text = decompile(compiled())
    assert text.startswith("# begin crush map\n")
    assert text.endswith("# end crush map\n")
    assert "tunable choose_total_tries 50" in text
    assert "device 0 osd.0" in text
    assert "\titem osd.5 weight 2.000" in text
    assert "\tstep chooseleaf firstn 0 type host" in text
    assert "\tstep set_chooseleaf_tries 5" in text
    assert "rule replicated_rule {" in text


# ---------------------------------------------------------------- tester
@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_tester_counts_match_scalar_engine():
    w = compiled()
    t = CrushTester(w, min_x=0, max_x=255, rule=0, min_rep=3, max_rep=3)
    out = t.test(show_utilization=True)
    # recompute per-device counts with the scalar oracle
    per = np.zeros(6, dtype=np.int64)
    weights = [0x10000] * 6
    for x in range(256):
        for o in crush_mapper.do_rule(w.crush, 0, x, 3, weights):
            per[o] += 1
    assert "rule 0 (replicated_rule), x = 0..255, numrep = 3..3" in out
    assert f"result size == 3:\t256/256" in out
    # "expected" uses the tester's device weight vector (uniform by
    # default), not crush bucket weights — matching the reference,
    # whose proportional_weights come from the --weight vector
    for dev in range(6):
        assert f"  device {dev}:\t\t stored : {per[dev]}\t " \
               f"expected : 128" in out
    # bucket weight skew shows up in `stored`: osd.5 (weight 2) gets
    # the most placements
    assert per[5] == per.max()


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_tester_bad_mappings():
    """Asking for more replicas than hosts yields bad-mapping lines for
    firstn (short result) (bad-mappings.t model)."""
    w = compiled()
    t = CrushTester(w, min_x=0, max_x=63, rule=0, min_rep=5, max_rep=5)
    out = t.test(show_bad_mappings=True)
    assert "bad mapping rule 0 x" in out
    assert "num_rep 5 result [" in out


def test_tester_mappings_format():
    w = compiled()
    t = CrushTester(w, min_x=0, max_x=3, rule=1, min_rep=3, max_rep=3)
    out = t.test(show_mappings=True)
    lines = [ln for ln in out.splitlines()
             if ln.startswith("CRUSH rule 1 x ")]
    assert len(lines) == 4
    assert lines[0].startswith("CRUSH rule 1 x 0 [")


# ------------------------------------------------------------------- CLI
@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_cli_compile_decompile_test(tmp_path, capsys):
    src = tmp_path / "map.txt"
    src.write_text(MAP_TXT)
    mapfile = str(tmp_path / "map.json")
    assert crushtool.main(["-c", str(src), "-o", mapfile]) == 0
    assert crushtool.main(["-d", mapfile]) == 0
    text = capsys.readouterr().out
    assert "rule ec_rule {" in text
    # recompile the decompiled text: placements identical
    src2 = tmp_path / "map2.txt"
    src2.write_text(text)
    mapfile2 = str(tmp_path / "map2.json")
    assert crushtool.main(["-c", str(src2), "-o", mapfile2]) == 0
    assert crushtool.main(
        ["-i", mapfile, "--test", "--show-statistics", "--max-x", "127",
         "--rule", "0", "--num-rep", "3"]) == 0
    out1 = capsys.readouterr().out
    assert crushtool.main(
        ["-i", mapfile2, "--test", "--show-statistics", "--max-x", "127",
         "--rule", "0", "--num-rep", "3"]) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    assert "result size == 3:\t128/128" in out1


def test_cli_tree(tmp_path, capsys):
    src = tmp_path / "map.txt"
    src.write_text(MAP_TXT)
    mapfile = str(tmp_path / "map.json")
    crushtool.main(["-c", str(src), "-o", mapfile])
    capsys.readouterr()
    assert crushtool.main(["-i", mapfile, "--tree"]) == 0
    out = capsys.readouterr().out
    assert "root default" in out and "host host2" in out
    assert "osd.5" in out


def test_cli_build(tmp_path, capsys):
    mapfile = str(tmp_path / "built.json")
    assert crushtool.main(
        ["--build", "--num-osds", "8", "-o", mapfile,
         "host", "straw2", "2", "root", "straw2", "0"]) == 0
    w = crushtool.load(mapfile)
    assert w.crush.max_devices == 8
    hosts = [b for b in w.crush.buckets
             if b is not None and w.type_map[b.type] == "host"]
    assert len(hosts) == 4 and all(len(h.items) == 2 for h in hosts)
    roots = [b for b in w.crush.buckets
             if b is not None and w.type_map[b.type] == "root"]
    assert len(roots) == 1 and len(roots[0].items) == 4
    # the built tree decompiles and recompiles
    text = decompile(w)
    w2 = compile_crushmap(text)
    assert decompile(w2) == text
