"""QA tier 2/3: recovery on remap + the randomized thrasher loop
(ref: qa/tasks/ceph_manager.py:98 OSDThrasher,
qa/standalone/erasure-code/test-erasure-code.sh shapes)."""
import random

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.testing import MiniCluster, OSDThrasher


def make_cluster(n=6):
    c = MiniCluster(n_osd=n, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.pool_create("p", pg_num=16)
    c.pump()
    return c, r


# --------------------------------------------------------------- recovery
def test_out_remap_recovers_data():
    """Mark an OSD out: PGs remap, new members get the objects via
    scan/pull/push recovery, reads keep working."""
    c, r = make_cluster()
    io = r.open_ioctx("p")
    objs = {f"o{i}": bytes([i]) * (100 + i) for i in range(24)}
    for oid, data in objs.items():
        io.write_full(oid, data)
    c.pump()
    r.mon_command({"prefix": "osd out", "ids": [0]})
    c.pump()   # maps propagate, recovery scan/pull/push runs
    c.pump()
    assert all(d.pgs_recovering() == 0 for d in c.osds.values())
    for oid, data in objs.items():
        assert io.read(oid) == data
    # every PG's new acting set holds every object
    pid = r.pool_lookup("p")
    m = c.mon.osdmap
    from ceph_tpu.osd.types import PG
    for ps in range(16):
        pg = PG(pid, ps)
        _, _, acting, _ = m.pg_to_up_acting_osds(pg)
        assert 0 not in acting
        for osd in acting:
            shard = c.osds[osd].pgs[pg].shard
            for oid, data in objs.items():
                if pg == m.pools[pid].raw_pg_to_pg(
                        m.object_locator_to_pg(oid, pid)):
                    assert shard.read(oid) == data, (ps, osd, oid)
    c.shutdown()


def test_new_primary_pulls_before_serving():
    """A remapped-in primary with an empty store must pull objects
    before serving (no phantom ENOENT)."""
    c, r = make_cluster()
    io = r.open_ioctx("p")
    io.write_full("key", b"payload" * 50)
    c.pump()
    # out two osds to force substantial remapping
    r.mon_command({"prefix": "osd out", "ids": [0, 1]})
    c.pump()
    c.pump()
    assert io.read("key") == b"payload" * 50
    r.mon_command({"prefix": "osd in", "ids": [0, 1]})
    c.pump()
    c.pump()
    assert io.read("key") == b"payload" * 50
    c.shutdown()


# --------------------------------------------------------------- thrasher
def test_thrasher_replicated_io_survives():
    """The full loop: random kill/revive/out/in with async IO
    interleaved (a PG whose whole acting set is dead rightly BLOCKS its
    ops until revival, so mid-thrash IO can't be synchronous — same as
    the qa thrasher's radosbench-join-at-end model), heal, wait for
    every op to complete, then verify every object byte-for-byte."""
    import time
    c, r = make_cluster(n=7)
    io = r.open_ioctx("p")
    rng = random.Random(42)
    expected: dict[str, bytes] = {}
    futures: dict[str, object] = {}   # oid -> latest write future

    def do_io(i):
        for _ in range(3):
            oid = f"obj{rng.randrange(30)}"
            data = bytes([rng.randrange(256)]) * rng.randrange(1, 800)
            futures[oid] = io.aio_write_full(oid, data)
            expected[oid] = data
        c.pump()

    t = OSDThrasher(c, seed=7, min_in=4, min_live=4)
    do_io(-1)
    t.do_thrash(12, between=do_io)
    t.heal()
    # drain: parked ops resend via the rescan timer (real-time), so
    # pump until every write future completes
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        c.pump()
        if all(f.done() for f in futures.values()):
            break
        time.sleep(0.02)
    undone = [o for o, f in futures.items() if not f.done()]
    assert not undone, (undone, t.log)
    failed = {o: f.errno_name for o, f in futures.items()
              if f.result < 0}
    assert not failed, (failed, t.log)
    # post-heal: all objects intact
    for oid, data in sorted(expected.items()):
        assert io.read(oid) == data, (oid, t.log)
    # cluster fully up/in again
    assert all(c.mon.osdmap.is_up(o) and c.mon.osdmap.is_in(o)
               for o in range(7)), t.log
    c.shutdown()


def test_deleted_object_not_resurrected_by_stale_replica():
    """Delete while a replica is down: when it returns, the versioned
    whiteout must outrank the stale copy — no resurrection."""
    c, r = make_cluster()
    io = r.open_ioctx("p")
    io.write_full("ghost", b"boo" * 100)
    c.pump()
    from ceph_tpu.osd.types import PG
    pid = r.pool_lookup("p")
    m = c.mon.osdmap
    raw = m.object_locator_to_pg("ghost", pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    stale = next(o for o in acting if o != primary)
    c.kill_osd(stale)
    # mark it down so the delete proceeds on the remaining members
    c.mon.handle_command({"prefix": "osd down", "ids": [stale]})
    c.pump()
    io.remove("ghost")
    c.pump()
    # stale replica returns with its old copy; recovery must spread the
    # whiteout, not the data
    c.revive_osd(stale)
    c.pump()
    c.pump()
    assert all(d.pgs_recovering() == 0 for d in c.osds.values())
    import pytest as _pytest
    from ceph_tpu.client import RadosError
    with _pytest.raises(RadosError) as ei:
        io.read("ghost")
    assert ei.value.errno_name == "ENOENT"
    # and the stale holder's store view agrees it is deleted
    shard = c.osds[stale].pgs[pg].shard
    assert not shard.exists("ghost")
    c.shutdown()


def test_thrasher_respects_min_guards():
    c, _ = make_cluster(n=4)
    t = OSDThrasher(c, seed=1, min_in=3, min_live=3)
    for _ in range(10):
        t.kill_osd()
        t.out_osd()
    assert len(t._live()) >= 3
    assert len(t._in()) >= 3
    c.shutdown()
