#!/usr/bin/env python
"""Serve smoke — the LLM-artifact-store half of the ship gate
(check_green.sh).

Boots a MiniCluster with an EC pool, publishes a small sharded
checkpoint (ragged tail) plus a KV page pool through
ceph_tpu.serve.ArtifactStore, and asserts:

1. the checkpoint streams back byte-identical through BOTH readahead
   policies (`checkpoint` sequential-doubling, `kvcache` pinned
   random-page);
2. the batched page-fetch wave returns the same bytes as the
   per-page read loop it replaces;
3. after an OSD is killed mid-life (EC pool one shard down), a fresh
   handle still streams the checkpoint and fetches random KV pages
   byte-identical — degraded reads reconstruct the lost shard.

Run from the repo root: python scripts/serve_smoke.py
"""
import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from ceph_tpu.serve import ArtifactStore               # noqa: E402
from ceph_tpu.osdc.striper import StripeLayout         # noqa: E402
from ceph_tpu.testing import MiniCluster               # noqa: E402

PAGE = 4096
K, M = 2, 1


def main() -> int:
    c = MiniCluster(n_osd=5, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.mon_command({"prefix": "osd erasure-code-profile set",
                       "name": "serve_smoke",
                       "profile": {"plugin": "tpu", "k": str(K),
                                   "m": str(M),
                                   "crush-failure-domain": "host"}})
        r.pool_create("serve_pool", pg_num=8, pool_type="erasure",
                      erasure_code_profile="serve_smoke")
        c.pump()
        io = r.open_ioctx("serve_pool")
        st = ArtifactStore(
            io, page_size=PAGE,
            layout=StripeLayout(stripe_unit=4 * PAGE, stripe_count=2,
                                object_size=16 * PAGE))
        rng = random.Random(19)
        ckpt = rng.randbytes(150000)          # ragged tail page
        kv = [rng.randbytes(rng.choice([PAGE, PAGE, 777, 0]))
              for _ in range(24)]
        st.put("ckpt", shards={"shard0": ckpt}, pages={"kv": kv})
        c.pump()

        for policy in ("checkpoint", "kvcache"):
            h = st.open("ckpt", policy=policy)
            got = h.read_shard("shard0", chunk=3 * PAGE)
            h.close()
            if got != ckpt:
                print(f"FAIL: stream ({policy}) not byte-identical",
                      file=sys.stderr)
                return 1

        ids = [rng.randrange(len(kv)) for _ in range(16)]
        want = [kv[i] for i in ids]
        if st.fetch_pages("ckpt", "kv", ids) != want:
            print("FAIL: batched page fetch wrong bytes",
                  file=sys.stderr)
            return 1
        if st.fetch_pages("ckpt", "kv", ids, batched=False) != want:
            print("FAIL: per-page loop fetch wrong bytes",
                  file=sys.stderr)
            return 1

        # kill one OSD: k=2/m=1 tolerates a lost shard; degraded
        # reads must reconstruct the same bytes
        victim = 0
        c.kill_osd(victim)
        r.mon_command({"prefix": "osd down", "ids": [victim]})
        c.pump()

        h = st.open("ckpt", policy="checkpoint")
        got = h.read_shard("shard0")
        h.close()
        if got != ckpt:
            print("FAIL: degraded stream not byte-identical",
                  file=sys.stderr)
            return 1
        h = st.open("ckpt", policy="kvcache")
        if h.get_pages("kv", ids, pin=True) != want:
            print("FAIL: degraded KV pages wrong bytes",
                  file=sys.stderr)
            return 1
        h.unpin_pages("kv", ids)
        h.close()
        print(f"serve_smoke: OK ({len(ckpt)} B checkpoint + "
              f"{len(kv)} KV pages byte-identical through both "
              f"policies, healthy and with osd.{victim} down)")
        return 0
    finally:
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
