"""JournaledStore: WAL durability, crash replay, torn tails, daemon
restart persistence (ref: src/os/filestore/FileJournal.cc replay
semantics)."""
import os

import pytest

from ceph_tpu.store import JournaledStore, ObjectId, StoreError, \
    Transaction
from ceph_tpu.testing import MiniCluster


def make_store(path):
    st = JournaledStore(str(path))
    st.mkfs()
    st.mount()
    return st


def test_umount_remount_persists(tmp_path):
    st = make_store(tmp_path / "s")
    st.queue_transaction(Transaction().create_collection("c"))
    st.queue_transaction(
        Transaction().write("c", ObjectId("o"), 0, b"durable")
        .setattr("c", ObjectId("o"), "k", {"v": 1}))
    st.umount()
    st2 = JournaledStore(str(tmp_path / "s"))
    st2.mount()
    assert bytes(st2.read("c", ObjectId("o"), 0, 0)) == b"durable"
    assert st2.getattr("c", ObjectId("o"), "k") == {"v": 1}


def test_crash_replay_from_journal(tmp_path):
    """No umount (crash): the journal alone restores the state."""
    st = make_store(tmp_path / "s")
    st.queue_transaction(Transaction().create_collection("c"))
    for i in range(10):
        st.queue_transaction(Transaction().write(
            "c", ObjectId(f"o{i}"), 0, bytes([i]) * 100))
    # simulate a crash: drop the object without compacting
    st._wal.close()
    st2 = JournaledStore(str(tmp_path / "s"))
    st2.mount()
    for i in range(10):
        assert bytes(st2.read("c", ObjectId(f"o{i}"), 0, 0)) == \
            bytes([i]) * 100
    # mount compacted: journal now empty, snapshot carries the state
    assert os.path.getsize(st2._wal_path) == 0
    st3 = JournaledStore(str(tmp_path / "s"))
    st3.mount()
    assert bytes(st3.read("c", ObjectId("o3"), 0, 0)) == b"\x03" * 100


def test_torn_journal_tail_ignored(tmp_path):
    st = make_store(tmp_path / "s")
    st.queue_transaction(Transaction().create_collection("c"))
    st.queue_transaction(Transaction().write(
        "c", ObjectId("good"), 0, b"ok"))
    st._wal.close()
    # append garbage (a torn half-written frame)
    with open(st._wal_path, "ab") as f:
        f.write(b"\x40\x00\x00\x00TORN")
    st2 = JournaledStore(str(tmp_path / "s"))
    st2.mount()
    assert bytes(st2.read("c", ObjectId("good"), 0, 0)) == b"ok"
    assert not st2.exists("c", ObjectId("torn"))


def test_failed_txn_not_journaled(tmp_path):
    st = make_store(tmp_path / "s")
    st.queue_transaction(Transaction().create_collection("c"))
    size = os.path.getsize(st._wal_path)
    with pytest.raises(StoreError):
        st.queue_transaction(Transaction().remove("c", ObjectId("nope")))
    assert os.path.getsize(st._wal_path) == size  # nothing appended


def test_osd_restart_with_durable_store(tmp_path):
    """An OSD killed -9-style and revived on the same data dir serves
    its objects from disk."""
    c = MiniCluster(n_osd=3, threaded=False)
    c.pump()
    # swap osd.1's store for a journaled one BEFORE any writes
    c.kill_osd(1)
    st = make_store(tmp_path / "osd1")
    c._stores[1] = st
    c.start_osd(1)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.pool_create("p", pg_num=8)
    io = r.open_ioctx("p")
    for i in range(8):
        io.write_full(f"obj{i}", bytes([i]) * 500)
    c.pump()
    # hard-kill osd.1 (no umount) and revive from the same directory
    c.kill_osd(1)
    c._stores[1] = None
    fresh = JournaledStore(str(tmp_path / "osd1"))
    fresh.mount()
    c._stores[1] = fresh
    c.start_osd(1)
    c.pump()
    for _ in range(10):
        c.pump()
        if all(d.pgs_recovering() == 0 for d in c.osds.values()):
            break
    for i in range(8):
        assert io.read(f"obj{i}") == bytes([i]) * 500
    c.shutdown()
