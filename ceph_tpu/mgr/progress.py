"""Progress module: long-running cluster operations as trackable
events (ref: src/pybind/mgr/progress/module.py — `ceph progress`;
VERDICT r3 #10).

Events derive from the PG state digest the primaries report: a pool
entering recovery/backfill opens an event whose progress is the
fraction of affected PGs that have since left the state (the
reference's PgRecoveryEvent works the same way from pg_stats).
Completed events retire into a bounded history, mirroring
`progress ls`'s `completed` section."""
from __future__ import annotations

import itertools
import threading

from ..common.lockdep import make_lock
import time

#: states that constitute a long-running data-movement operation
#: (substring match on the pg state; "snaptrim" also covers
#: snaptrim_wait/snaptrim_error so queued trim work counts as
#: remaining — the trim analogue of the backfill event)
_TRACKED = ("recovering", "backfilling", "snaptrim")

#: completed-event history bound (ref: the module's max completed)
_MAX_DONE = 50


class ProgressModule:
    """Driven by MgrDaemon.tick(); reads `pg dump` through the mgr's
    mon command channel."""

    def __init__(self, mgr):
        self.mgr = mgr
        self._ids = itertools.count(1)
        #: (pool, state) -> event dict
        self.events: dict[tuple, dict] = {}
        self.completed: list[dict] = []
        #: the prometheus scrape thread reads while the mgr ticks
        self._lock = make_lock("mgr.progress")

    # ------------------------------------------------------------ tick
    def tick(self) -> int:
        """One sampling pass; returns the number of live events."""
        rc, _outs, pgs = self.mgr.mon_command({"prefix": "pg dump"})
        if rc != 0 or not isinstance(pgs, dict):
            with self._lock:
                return len(self.events)
        active: dict[tuple, set] = {}
        for pgid, st in pgs.items():
            state = st.get("state", "")
            pool = pgid.split(".", 1)[0]
            for kind in _TRACKED:
                if kind in state:
                    active.setdefault((pool, kind), set()).add(pgid)
        now = time.time()
        with self._lock:
            return self._apply_sample(active, now)

    def _apply_sample(self, active: dict, now: float) -> int:
        for key, pgset in active.items():
            ev = self.events.get(key)
            if ev is None:
                ev = self.events[key] = {
                    "id": f"pg-{key[1]}-{next(self._ids)}",
                    "message": f"pool {key[0]} PGs {key[1]}",
                    "started": now, "peak": len(pgset),
                    "remaining": len(pgset), "progress": 0.0,
                }
            ev["peak"] = max(ev["peak"], len(pgset))
            ev["remaining"] = len(pgset)
            ev["progress"] = round(1.0 - len(pgset) / ev["peak"], 4)
        for key in [k for k in self.events if k not in active]:
            ev = self.events.pop(key)
            ev["progress"] = 1.0
            ev["remaining"] = 0
            ev["finished"] = now
            self.completed.append(ev)
            del self.completed[:-_MAX_DONE]
        return len(self.events)

    # -- external event API (other modules report through here,
    # ref: the module's update()/complete() RPC used by e.g. the
    # balancer and upgrade orchestrators)
    def update(self, ev_id: str, message: str,
               progress: float) -> None:
        with self._lock:
            self._update(ev_id, message, progress)

    def _update(self, ev_id: str, message: str,
                progress: float) -> None:
        key = ("ext", ev_id)
        ev = self.events.get(key)
        if ev is None:
            ev = self.events[key] = {
                "id": ev_id, "message": message,
                "started": time.time(), "peak": 1, "remaining": 1,
                "progress": 0.0}
        ev["message"] = message
        ev["progress"] = max(0.0, min(1.0, progress))

    def complete(self, ev_id: str) -> None:
        with self._lock:
            ev = self.events.pop(("ext", ev_id), None)
            if ev is not None:
                ev["progress"] = 1.0
                ev["finished"] = time.time()
                self.completed.append(ev)
                del self.completed[:-_MAX_DONE]

    # ------------------------------------------------------------- view
    def ls(self) -> list[dict]:
        """`ceph progress` — the LIVE events (history() holds the
        completed ones)."""
        with self._lock:
            out = [dict(e) for e in self.events.values()]
        out.sort(key=lambda e: e["started"])
        return out

    def history(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self.completed]
