"""RBD-lite: block-device images striped over RADOS objects.

The librbd data-path model (ref: src/librbd/: image metadata in a
header object, data in `rbd_data.<id>.<objectno>` objects of size
2^order, io/ImageRequest.cc mapping block extents through the Striper;
naming scheme util::data_object_name): an image is a sparse array of
equal-size objects — absent objects read as zeros, partial writes touch
only the covered objects.

Round 3 adds the librbd depth features (ref: VERDICT r2 #6):

* **exclusive lock** — writers arbitrate through the cls `lock` class
  on the header object with cooperative hand-off over watch/notify
  (ref: src/librbd/exclusive_lock/, ManagedLock; RBD_LOCK_NAME
  "rbd_lock"); dead holders are detected by live-watcher comparison
  and broken (ref: break_lock on blocklisted owners);
* **object map + fast-diff** — 2-bit per-object existence states
  persisted per image and per snapshot (ref: src/librbd/object_map/,
  OBJECT_{NONEXISTENT,EXISTS,PENDING,EXISTS_CLEAN}), driving du and
  snapshot diffs without scanning data objects;
* **snapshot-backed COW clones** — children record (pool, image, snap,
  overlap); reads fall through to the protected parent snapshot,
  partial writes copy-up the covered object first, `flatten` detaches
  (ref: src/librbd/ parent/child linkage, cls_rbd children,
  io/CopyupRequest.cc).

API mirrors librbd's Python binding surface: RBD().create/remove/
list/clone, Image open -> read/write/discard/resize/stat/snap_*/
diff/du/flatten/close.
"""
from __future__ import annotations

import json
import threading

from ..common.lockdep import make_lock
import time

from ..client.rados import IoCtx, RadosError
from ..osdc import StripeLayout, Striper

RBD_DEFAULT_ORDER = 22          # 4 MiB objects (rbd_default_order)
#: header lock name (ref: src/librbd/utils: RBD_LOCK_NAME)
RBD_LOCK_NAME = "rbd_lock"


class RBDError(OSError):
    pass


def header_name(name: str) -> str:
    return f"rbd_header.{name}"


def data_name(name: str, objectno: int) -> str:
    """(ref: librbd util::data_object_name '%s.%016llx')."""
    return f"rbd_data.{name}.{objectno:016x}"


def object_map_name(name: str, snap_id: int | None = None) -> str:
    """(ref: librbd object_map::util RBD_OBJECT_MAP_PREFIX)."""
    base = f"rbd_object_map.{name}"
    return base if snap_id is None else f"{base}.{snap_id}"


class ObjectMap:
    """2-bit-per-object existence map (ref: src/librbd/object_map/,
    states src/include/rbd/object_map_types.h)."""

    NONEXISTENT = 0
    EXISTS = 1              # exists, dirty since the last snapshot
    PENDING = 2
    EXISTS_CLEAN = 3        # exists, unchanged since the last snapshot

    def __init__(self, ioctx: IoCtx, image_name: str, span: int,
                 snap_id: int | None = None):
        self.ioctx = ioctx
        self.image_name = image_name
        self.oid = object_map_name(image_name, snap_id)
        self.span = span
        try:
            raw = ioctx.read(self.oid)
        except RadosError:
            raw = b""
        self._bits = bytearray(raw)
        need = (span + 3) // 4
        if len(self._bits) < need:
            self._bits += b"\0" * (need - len(self._bits))
        #: dirty byte range awaiting flush (librbd updates the map
        #: in place — a full-map rewrite per IO would be span/4 bytes
        #: of write amplification on the data path)
        self._dirty: tuple[int, int] | None = None
        self._full_rewrite = False

    def get(self, objno: int) -> int:
        if objno >= self.span:
            return self.NONEXISTENT
        return (self._bits[objno // 4] >> (2 * (objno % 4))) & 3

    def set(self, objno: int, state: int, flush: bool = True) -> None:
        byte = objno // 4
        shift = 2 * (objno % 4)
        cur = self._bits[byte]
        new = (cur & ~(3 << shift)) | (state << shift)
        if new == cur:
            return
        self._bits[byte] = new
        if self._dirty is None:
            self._dirty = (byte, byte + 1)
        else:
            lo, hi = self._dirty
            self._dirty = (min(lo, byte), max(hi, byte + 1))
        if flush:
            self.flush()

    def resize(self, span: int) -> None:
        need = (span + 3) // 4
        if len(self._bits) < need:
            self._bits += b"\0" * (need - len(self._bits))
        else:
            del self._bits[need:]
            # clear trailing sub-byte states past the new span
            for objno in range(span, need * 4):
                self.set(objno, self.NONEXISTENT, flush=False)
        self.span = span
        self._full_rewrite = True      # length changed
        self.flush()

    def mark_clean(self) -> None:
        """EXISTS -> EXISTS_CLEAN after a snapshot (fast-diff epoch)."""
        for objno in range(self.span):
            if self.get(objno) == self.EXISTS:
                self.set(objno, self.EXISTS_CLEAN, flush=False)
        self.flush()

    def save_copy(self, snap_id: int) -> None:
        """Freeze the current map beside the snapshot
        (ref: object map snapshots, object_map_name(image, snap))."""
        self.ioctx.write_full(object_map_name(self.image_name, snap_id),
                              bytes(self._bits))

    def flush(self) -> None:
        if self._full_rewrite:
            self.ioctx.write_full(self.oid, bytes(self._bits))
        elif self._dirty is not None:
            lo, hi = self._dirty
            self.ioctx.write(self.oid, bytes(self._bits[lo:hi]),
                             offset=lo)
        self._dirty = None
        self._full_rewrite = False

    def remove(self) -> None:
        try:
            self.ioctx.remove(self.oid)
        except RadosError:
            pass

    def existing(self) -> list[int]:
        return [o for o in range(self.span)
                if self.get(o) != self.NONEXISTENT]


class RBD:
    """Pool-level image operations (ref: librbd::RBD)."""

    def create(self, ioctx: IoCtx, name: str, size: int,
               order: int = RBD_DEFAULT_ORDER, stripe_unit: int = 0,
               stripe_count: int = 1,
               journaling: bool = False) -> None:
        if self._exists(ioctx, name):
            raise RBDError(17, f"image {name!r} exists")
        obj_size = 1 << order
        su = stripe_unit or obj_size
        layout = StripeLayout(stripe_unit=su, stripe_count=stripe_count,
                              object_size=obj_size)
        layout.validate()
        meta = {"size": size, "order": order, "stripe_unit": su,
                "stripe_count": stripe_count}
        if journaling:
            # write-ahead mutation journal (ref: librbd journaling
            # feature; consumed by ceph_tpu.rbd.mirror)
            meta["journaling"] = True
            from ..journal import Journaler
            Journaler(ioctx, f"rbd.{name}", "master").create()
        ioctx.write_full(header_name(name), json.dumps(meta).encode())

    def remove(self, ioctx: IoCtx, name: str) -> None:
        img = Image(ioctx, name)
        try:
            if img.snaps:
                raise RBDError(39, f"image {name!r} has snapshots "
                                   "(purge them first)")
            img._detach_from_parent()
            for objno in range(img._object_span()):
                try:
                    ioctx.remove(data_name(name, objno))
                except RadosError:
                    pass
            img.object_map.remove()
            if img._journal is not None:
                img._journal.remove()
        finally:
            img.close()
        ioctx.remove(header_name(name))

    def clone(self, p_ioctx: IoCtx, p_name: str, p_snap: str,
              c_ioctx: IoCtx, c_name: str,
              order: int | None = None) -> None:
        """Snapshot-backed COW clone (ref: librbd::clone; parent must
        be protected — librbd/internal.cc clone preconditions; child
        records the parent link, parent records the child —
        cls_rbd children)."""
        parent = Image(p_ioctx, p_name)
        try:
            if p_snap not in parent.snaps:
                raise RBDError(2, f"snapshot {p_snap!r} not found")
            snap = parent.snaps[p_snap]
            if not snap.get("protected"):
                raise RBDError(22, f"snapshot {p_snap!r} is not "
                                   "protected")
            if self._exists(c_ioctx, c_name):
                raise RBDError(17, f"image {c_name!r} exists")
            if parent.layout.stripe_count != 1:
                raise RBDError(22, "clone requires stripe_count=1 "
                                   "parents")
            order = order if order is not None else parent.order
            overlap = int(snap["size"])
            meta = {"size": overlap, "order": order,
                    "stripe_unit": 1 << order, "stripe_count": 1,
                    "parent": {"pool": p_ioctx._pool_name(),
                               "image": p_name, "snap_name": p_snap,
                               "snap_id": snap["id"],
                               "overlap": overlap}}
            c_ioctx.write_full(header_name(c_name),
                               json.dumps(meta).encode())
            parent.meta_children.append(
                [c_ioctx._pool_name(), c_name, p_snap])
            parent._save_meta()
        finally:
            parent.close()

    def list(self, ioctx: IoCtx) -> list[str]:
        """(ref: librbd::RBD::list — header-object scan)."""
        return sorted(oid[len("rbd_header."):]
                      for oid in ioctx.list_objects()
                      if oid.startswith("rbd_header."))

    # -- live migration (ref: librbd::RBD migration_* API surface) -----
    def migration_prepare(self, src_ioctx: IoCtx, src_name: str,
                          dst_ioctx: IoCtx, dst_name: str) -> None:
        from .migration import migration_prepare
        migration_prepare(src_ioctx, src_name, dst_ioctx, dst_name)

    def migration_execute(self, dst_ioctx: IoCtx,
                          dst_name: str) -> None:
        from .migration import migration_execute
        migration_execute(dst_ioctx, dst_name)

    def migration_commit(self, dst_ioctx: IoCtx,
                         dst_name: str) -> None:
        from .migration import migration_commit
        migration_commit(dst_ioctx, dst_name)

    def migration_abort(self, dst_ioctx: IoCtx,
                        dst_name: str) -> None:
        from .migration import migration_abort
        migration_abort(dst_ioctx, dst_name)

    @staticmethod
    def _exists(ioctx: IoCtx, name: str) -> bool:
        try:
            ioctx.stat(header_name(name))
            return True
        except RadosError:
            return False


class Image:
    """(ref: librbd::Image / ImageCtx).

    Snapshots are librbd-style SELF-MANAGED rados snaps (ref:
    librbd::Operations::snap_create -> selfmanaged_snap_create +
    per-image SnapContext on every data-object write): snapids live in
    the image header, the write snapc rides on a private IoCtx, and
    opening at a snapshot reads each data object at that snapid."""

    def __init__(self, ioctx: IoCtx, name: str,
                 snapshot: str | None = None,
                 _migration_internal: bool = False):
        self.ioctx = ioctx
        self.name = name
        try:
            raw = ioctx.read(header_name(name))
        except RadosError as ex:
            raise RBDError(2, f"image {name!r} does not exist") from ex
        meta = json.loads(raw.decode())
        if meta.get("migration") and not _migration_internal:
            # a migration source only serves the destination's
            # fall-through reads; clients must open the destination
            # (ref: Migration.cc's migrating state gating opens)
            raise RBDError(30, f"image {name!r} is migrating to "
                           f"{meta['migration']['dst_image']!r}")
        self._migrating_source = bool(meta.get("migration"))
        self.size = int(meta["size"])
        self.order = int(meta["order"])
        self.layout = StripeLayout(
            stripe_unit=int(meta["stripe_unit"]),
            stripe_count=int(meta["stripe_count"]),
            object_size=1 << self.order)
        self.snaps: dict[str, dict] = meta.get("snaps", {})
        self.parent: dict | None = meta.get("parent")
        self.meta_children: list = meta.get("children", [])
        #: mirror state (ref: librbd mirror image info): None = not
        #: mirrored; else {"primary": bool, "epochs": [promotion ids]}
        self.mirror: dict | None = meta.get("mirror")
        #: write-ahead mutation journal (ref: librbd journaling)
        self.journaling = bool(meta.get("journaling"))
        self._journal = None
        if self.journaling:
            from ..journal import Journaler
            self._journal = Journaler(ioctx, f"rbd.{name}", "master")
        self._parent_image: "Image | None" = None
        self._snap_id: int | None = None
        if snapshot is not None:
            if snapshot not in self.snaps:
                raise RBDError(2, f"snapshot {snapshot!r} not found")
            self._snap_id = self.snaps[snapshot]["id"]
            self.size = int(self.snaps[snapshot]["size"])
        # writes go through a private IoCtx carrying the image snapc
        # (the caller's IoCtx must not inherit it)
        self._wio = IoCtx(ioctx.rados, ioctx.pool_id)
        self._refresh_snapc()
        self._open = True
        # exclusive-lock state (ref: librbd/exclusive_lock/ManagedLock)
        self._iolock = make_lock(f"rbd.image.{name}")
        self._lock_owned = False
        self._lock_cookie = f"{ioctx.rados.objecter.name}." \
                            f"{id(self):x}"
        self._watch_cookie: str | None = None
        # per-image object map (head only; snapshot maps are loaded on
        # demand for diffs)
        self.object_map = ObjectMap(self._wio, name,
                                    self._object_span())
        # write-back object cache (ref: librbd's ObjectCacher mount,
        # rbd_cache*): head IO only — snapshot opens read frozen state
        # and bypass it.  The exclusive lock is the coherence protocol:
        # release flushes + invalidates.
        self._oc = None
        from ..common.options import global_config
        if global_config()["rbd_cache"] and self._snap_id is None:
            from ..osdc.object_cacher import ObjectCacher
            cfg = global_config()
            self._oc = ObjectCacher(
                self._oc_read, self._oc_write,
                max_dirty=cfg["rbd_cache_max_dirty"],
                max_size=cfg["rbd_cache_size"],
                page=min(1 << self.order, 1 << 16))

    # -- object cache backing (oid = str(objectno)) ---------------------
    def _oc_read(self, oid: str, off: int, length: int) -> bytes:
        """Head object read with clone parent fall-through (the same
        resolution Image.read performs per extent)."""
        objno = int(oid)
        try:
            return self.ioctx.read(data_name(self.name, objno),
                                   length=length, offset=off)
        except RadosError as ex:
            if ex.errno_name != "ENOENT":
                raise
        parent = self._parent()
        if parent is not None and self.parent is not None:
            p_off = objno * (1 << self.order) + off
            p_len = min(length, self.parent["overlap"] - p_off)
            if p_len > 0:
                return parent.read(p_off, p_len)
        return b""

    def _oc_write(self, oid: str, off: int, data: bytes) -> None:
        """Backing write at flush time: copyup for parent-backed
        partial overwrites + object-map existence, exactly like the
        uncached write path."""
        objno = int(oid)
        partial = not (off == 0 and len(data) == 1 << self.order)
        if partial and objno < self._overlap_span() and \
                self.object_map.get(objno) == ObjectMap.NONEXISTENT:
            self._copyup(objno)
        self._wio._wait(self._wio.aio_write(
            data_name(self.name, objno), data, offset=off))
        self.object_map.set(objno, ObjectMap.EXISTS, flush=False)

    def flush(self) -> None:
        """Flush the write-back cache (ref: rbd_flush): dirty data
        reaches RADOS and the object map is persisted."""
        if self._oc is not None:
            with self._iolock:
                self._oc.flush()
                self.object_map.flush()

    # -- exclusive lock (ref: src/librbd/exclusive_lock/) --------------
    @property
    def lock_owner(self) -> bool:
        return self._lock_owned

    def _header_notify(self, notify_id, notifier, payload):
        """Watch callback on the header object: peers ask the holder to
        release (ref: librbd watch_notify REQUEST_LOCK)."""
        op = (payload or {}).get("op")
        if op == "request_lock" and self._lock_owned:
            # release must not run sync IO on the dispatch thread
            threading.Thread(target=self.release_lock,
                             daemon=True).start()
        return {"owner": self._lock_owned}

    def _ensure_watch(self) -> None:
        if self._watch_cookie is None:
            self._watch_cookie = self.ioctx.watch(
                header_name(self.name), self._header_notify)

    def acquire_lock(self, timeout: float = 30.0) -> None:
        """Take the exclusive write lock, cooperatively requesting it
        from a live holder and breaking a dead one
        (ref: ManagedLock acquire + break_lock for gone clients)."""
        self._check_open()
        if self._lock_owned:
            return
        self._ensure_watch()
        me = self.ioctx.rados.objecter.name
        hdr = header_name(self.name)
        deadline = time.monotonic() + timeout
        while True:
            try:
                self.ioctx.exec(hdr, "lock", "lock", {
                    "name": RBD_LOCK_NAME, "type": "exclusive",
                    "client": me, "cookie": self._lock_cookie,
                    "desc": "rbd exclusive lock"})
                self._lock_owned = True
                return
            except RadosError as ex:
                if ex.errno_name != "EBUSY":
                    raise
            info = self.ioctx.exec(hdr, "lock", "get_info",
                                   {"name": RBD_LOCK_NAME}) or {}
            lockers = info.get("lockers", [])
            # ask the holder to release; a holder that no longer
            # watches the header is dead -> break its lock
            replies, _timeouts = self.ioctx.notify(
                hdr, {"op": "request_lock"})
            live = {k.split("/", 1)[0] for k in replies}
            for lk in lockers:
                if lk["client"] not in live:
                    try:
                        self.ioctx.exec(hdr, "lock", "break_lock", {
                            "name": RBD_LOCK_NAME,
                            "locker": lk["client"],
                            "cookie": lk.get("cookie", "")})
                    except RadosError:
                        pass
            if time.monotonic() > deadline:
                raise RBDError(16, f"exclusive lock on {self.name!r} "
                                   "held")
            time.sleep(0.05)

    def release_lock(self) -> None:
        with self._iolock:
            if not self._lock_owned:
                return
            # the lock is the cache-coherence protocol: dirty data
            # must land and cached state drop BEFORE another client
            # can take the lock (ref: pre-release flush in
            # librbd's exclusive_lock PreReleaseRequest)
            if self._oc is not None:
                self._oc.flush()
                self.object_map.flush()
                self._oc.invalidate()
            try:
                self.ioctx.exec(header_name(self.name), "lock",
                                "unlock", {
                                    "name": RBD_LOCK_NAME,
                                    "client":
                                        self.ioctx.rados.objecter.name,
                                    "cookie": self._lock_cookie})
            except RadosError:
                pass
            self._lock_owned = False

    def _ensure_lock(self) -> None:
        with self._iolock:
            if not self._lock_owned:
                self.acquire_lock()

    # -- clone parent plumbing ------------------------------------------
    def _parent(self) -> "Image | None":
        if self.parent is None:
            return None
        if self._parent_image is None:
            pio = self.ioctx.rados.open_ioctx(self.parent["pool"])
            self._parent_image = Image(
                pio, self.parent["image"],
                snapshot=self.parent["snap_name"],
                _migration_internal=bool(
                    self.parent.get("migration")))
        return self._parent_image

    def _detach_from_parent(self) -> None:
        """Drop the parent link + deregister from its children."""
        if self.parent is None:
            return
        try:
            pio = self.ioctx.rados.open_ioctx(self.parent["pool"])
            p = Image(pio, self.parent["image"])
            me = [self.ioctx._pool_name(), self.name,
                  self.parent["snap_name"]]
            p.meta_children = [c for c in p.meta_children
                               if list(c) != me]
            p._save_meta()
            p.close()
        except RadosError:
            pass
        if self._parent_image is not None:
            self._parent_image.close()
            self._parent_image = None
        self.parent = None

    def _refresh_snapc(self) -> None:
        ids = sorted(s["id"] for s in self.snaps.values())
        if ids:
            self._wio.set_write_snapc(max(ids), ids)
        else:
            self._wio.write_snapc = None

    # -- metadata ------------------------------------------------------
    def stat(self) -> dict:
        """(ref: librbd image_info_t)."""
        return {"size": self.size, "order": self.order,
                "obj_size": 1 << self.order,
                "num_objs": self._object_span(),
                "stripe_unit": self.layout.stripe_unit,
                "stripe_count": self.layout.stripe_count}

    def _object_span(self) -> int:
        return self._span_for(self.size)

    def resize(self, size: int) -> None:
        """Grow or shrink; shrink removes whole objects past the end
        (ref: librbd Operations::resize / object trimming)."""
        self._check_open()
        self._check_writable()
        self._ensure_lock()
        if self._oc is not None:
            # flush, then drop: shrink removes backing objects the
            # cache may still shadow
            self.flush()
            self._oc.invalidate()
        if self._journal is not None:
            self._journal.append("resize", {"size": size})
        old_span = self._object_span()
        self.size = size
        new_span = self._object_span()
        for objno in range(new_span, old_span):
            try:
                self._wio.remove(data_name(self.name, objno))
            except RadosError:
                pass
        # shrinking a clone trims the parent overlap — regrowing must
        # read zeros, not resurrect parent snapshot bytes
        # (ref: librbd Operations::resize overlap trim)
        if self.parent is not None and \
                size < self.parent.get("overlap", 0):
            self.parent["overlap"] = size
        self.object_map.resize(new_span)
        self._save_meta()

    def _save_meta(self) -> None:
        meta = {"size": self.size, "order": self.order,
                "stripe_unit": self.layout.stripe_unit,
                "stripe_count": self.layout.stripe_count,
                "snaps": self.snaps}
        if self.parent is not None:
            meta["parent"] = self.parent
        if self.meta_children:
            meta["children"] = self.meta_children
        if self.journaling:
            meta["journaling"] = True
        if self.mirror is not None:
            meta["mirror"] = self.mirror
        self.ioctx.write_full(header_name(self.name),
                              json.dumps(meta).encode())

    # -- snapshots (ref: librbd::Operations snap_create/remove/rollback)
    def snap_create(self, snap_name: str) -> None:
        self._check_open()
        self._check_writable()
        self._ensure_lock()
        if snap_name in self.snaps:
            raise RBDError(17, f"snapshot {snap_name!r} exists")
        # dirty cached data belongs BEFORE the snapshot point
        # (ref: librbd flushes the ObjectCacher ahead of snap_create)
        self.flush()
        if self._journal is not None:
            self._journal.append("snap_create", {"name": snap_name})
        sid = self._wio.selfmanaged_snap_create()
        self.snaps[snap_name] = {"id": sid, "size": self.size}
        # fast-diff epoch: freeze the object map beside the snapshot,
        # then EXISTS -> EXISTS_CLEAN on the head map
        # (ref: librbd object map snapshots)
        self.object_map.save_copy(sid)
        self.object_map.mark_clean()
        self._refresh_snapc()
        self._save_meta()

    def snap_remove(self, snap_name: str) -> None:
        self._check_open()
        self._check_writable()
        self._refresh_header()
        if snap_name not in self.snaps:
            raise RBDError(2, f"snapshot {snap_name!r} not found")
        if self.snaps[snap_name].get("protected"):
            raise RBDError(16, f"snapshot {snap_name!r} is protected")
        self._ensure_lock()
        if self._journal is not None:
            self._journal.append("snap_remove", {"name": snap_name})
        sid = self.snaps.pop(snap_name)["id"]
        self._wio.selfmanaged_snap_remove(sid)
        try:
            self._wio.remove(object_map_name(self.name, sid))
        except RadosError:
            pass
        self._refresh_snapc()
        self._save_meta()

    def _refresh_header(self) -> None:
        """Re-read shared header state (snaps, children, parent) —
        another client's clone/protect may have advanced it
        (ref: librbd ImageCtx::refresh on header notify)."""
        try:
            raw = self.ioctx.read(header_name(self.name))
        except RadosError:
            return
        meta = json.loads(raw.decode())
        self.snaps = meta.get("snaps", {})
        self.meta_children = meta.get("children", [])
        self.parent = meta.get("parent")
        self._refresh_snapc()

    def snap_protect(self, snap_name: str) -> None:
        """Clones only hang off protected snapshots
        (ref: librbd Operations::snap_protect)."""
        self._check_open()
        self._check_writable()
        self._refresh_header()
        if snap_name not in self.snaps:
            raise RBDError(2, f"snapshot {snap_name!r} not found")
        if self._journal is not None:
            self._journal.append("snap_protect", {"name": snap_name})
        self.snaps[snap_name]["protected"] = True
        self._save_meta()

    def snap_unprotect(self, snap_name: str) -> None:
        """Refused while children exist
        (ref: Operations::snap_unprotect child scan)."""
        self._check_open()
        self._check_writable()
        self._refresh_header()
        if snap_name not in self.snaps:
            raise RBDError(2, f"snapshot {snap_name!r} not found")
        if any(c[2] == snap_name for c in self.meta_children):
            raise RBDError(16, f"snapshot {snap_name!r} has clones")
        if self._journal is not None:
            self._journal.append("snap_unprotect", {"name": snap_name})
        self.snaps[snap_name].pop("protected", None)
        self._save_meta()

    def snap_is_protected(self, snap_name: str) -> bool:
        if snap_name not in self.snaps:
            raise RBDError(2, f"snapshot {snap_name!r} not found")
        return bool(self.snaps[snap_name].get("protected"))

    def children(self) -> list[tuple[str, str]]:
        """(pool, image) of clones (ref: librbd::Image::list_children)."""
        self._refresh_header()
        return [(c[0], c[1]) for c in self.meta_children]

    def snap_list(self) -> list[dict]:
        return [{"name": n, "id": s["id"], "size": s["size"]}
                for n, s in sorted(self.snaps.items(),
                                   key=lambda kv: kv[1]["id"])]

    def snap_rollback(self, snap_name: str) -> None:
        """Restore every data object to its state at the snapshot
        (ref: librbd snap_rollback iterates the objects)."""
        self._check_open()
        self._check_writable()
        self._ensure_lock()
        if snap_name not in self.snaps:
            raise RBDError(2, f"snapshot {snap_name!r} not found")
        if self._oc is not None:
            # post-snap dirty data is exactly what rollback discards
            self._oc.invalidate(discard_dirty=True)
        if self._journal is not None:
            self._journal.append("snap_rollback", {"name": snap_name})
        snap = self.snaps[snap_name]
        span = max(self._object_span(), self._span_for(snap["size"]))
        # fan the per-object rollbacks out like the write path: one
        # round of aio futures, not span sequential round trips
        futs = [self._wio.rados.objecter.submit(
                    self._wio.pool_id, data_name(self.name, objno),
                    "rollback",
                    args=self._wio._margs({"snapid": snap["id"]}))
                for objno in range(span)]
        for f in futs:
            self._wio._wait(f)
        self.size = int(snap["size"])
        # the head object map reverts to the snapshot's frozen map
        try:
            frozen = self._wio.read(object_map_name(self.name,
                                                    snap["id"]))
            self._wio.write_full(object_map_name(self.name), frozen)
            self.object_map = ObjectMap(self._wio, self.name,
                                        self._object_span())
        except RadosError:
            pass
        self._save_meta()

    def _span_for(self, size: int) -> int:
        if size == 0:
            return 0
        last = Striper.file_to_extents(self.layout, size - 1, 1)
        return max(e.objectno for e in last) + 1

    def _check_writable(self) -> None:
        if self._snap_id is not None:
            raise RBDError(30, "image is open read-only at a snapshot")
        if self._migrating_source:
            raise RBDError(30, "image is a migration source")
        if self.mirror is not None and \
                not self.mirror.get("primary", True) and \
                not getattr(self, "_replaying", False):
            # a demoted mirror image refuses local writes — only the
            # primary's journal replayer may mutate it (ref: librbd's
            # non-primary write gate; the replayer sets _replaying)
            raise RBDError(30, "image is non-primary (demoted)")

    # -- IO ------------------------------------------------------------
    def _check_open(self) -> None:
        if not self._open:
            raise RBDError(9, "image is closed")

    def _clip(self, offset: int, length: int) -> int:
        if offset > self.size:
            raise RBDError(22, "offset beyond end of image")
        return min(length, self.size - offset)

    def _overlap_span(self) -> int:
        """Objects of this image backed by the parent snapshot."""
        if self.parent is None:
            return 0
        return self._span_for(min(self.parent["overlap"], self.size))

    def _copyup(self, objno: int) -> None:
        """Materialize a parent-backed object in the child before a
        partial write/zero (ref: librbd io/CopyupRequest.cc)."""
        parent = self._parent()
        if parent is None:
            return
        obj_size = 1 << self.order
        off = objno * obj_size
        length = min(obj_size, self.parent["overlap"] - off)
        if length <= 0:
            return
        data = parent.read(off, length)
        if data.strip(b"\0"):
            self._wio.write_full(data_name(self.name, objno), data)
        self.object_map.set(objno, ObjectMap.EXISTS)

    def write(self, offset: int, data: bytes) -> int:
        """(ref: librbd io/ImageRequest.cc write path: extents through
        the striper, one object op per extent).  Takes the exclusive
        lock, copies parent-backed objects up on partial overwrite,
        and tracks existence in the object map."""
        self._check_open()
        self._check_writable()
        with self._iolock:
            self._ensure_lock()
            length = self._clip(offset, len(data))
            if self._journal is not None and length:
                # write-ahead: the event lands in the journal before
                # the data objects (ref: librbd journaling ordering)
                self._journal.append("write", {
                    "off": offset, "data": bytes(data[:length])})
            if self._oc is not None:
                # write-back: pages buffer in the cache; copyup +
                # object-map existence happen at flush in _oc_write
                for ext in Striper.file_to_extents(self.layout,
                                                   offset, length):
                    buf = data[ext.logical_offset - offset:
                               ext.logical_offset - offset
                               + ext.length]
                    self._oc.write(str(ext.objectno), ext.offset, buf)
                return length
            obj_size = 1 << self.order
            over = self._overlap_span()
            futs = []
            for ext in Striper.file_to_extents(self.layout, offset,
                                               length):
                partial = not (ext.offset == 0
                               and ext.length == obj_size)
                if partial and ext.objectno < over and \
                        self.object_map.get(ext.objectno) == \
                        ObjectMap.NONEXISTENT:
                    self._copyup(ext.objectno)
                buf = data[ext.logical_offset - offset:
                           ext.logical_offset - offset + ext.length]
                futs.append((ext.objectno, self._wio.aio_write(
                    data_name(self.name, ext.objectno), buf,
                    offset=ext.offset)))
            for objno, f in futs:
                self._wio._wait(f)
                self.object_map.set(objno, ObjectMap.EXISTS,
                                    flush=False)
            self.object_map.flush()
            return length

    def read(self, offset: int, length: int) -> bytes:
        """Sparse-aware: missing objects/ranges read as zeros; clone
        reads fall through to the parent snapshot within the overlap
        (ref: io/ImageReadRequest parent read-from)."""
        self._check_open()
        length = self._clip(offset, length)
        if self._oc is not None and self._snap_id is None:
            out = bytearray(length)
            for ext in Striper.file_to_extents(self.layout, offset,
                                               length):
                buf = self._oc.read(str(ext.objectno), ext.offset,
                                    ext.length)
                base = ext.logical_offset - offset
                out[base:base + len(buf)] = buf
            return bytes(out)
        out = bytearray(length)
        pend = []
        for ext in Striper.file_to_extents(self.layout, offset, length):
            fut = self.ioctx.aio_read(
                data_name(self.name, ext.objectno),
                length=ext.length, offset=ext.offset,
                snapid=self._snap_id)
            pend.append((ext, fut))
        obj_size = 1 << self.order
        for ext, fut in pend:
            try:
                buf = self.ioctx._wait(fut).data
            except RadosError as ex:
                if ex.errno_name != "ENOENT":
                    raise
                buf = b""
                # whole-object miss on a clone: serve from the parent
                parent = self._parent()
                if parent is not None and self.parent is not None:
                    p_off = ext.objectno * obj_size + ext.offset
                    p_len = min(ext.length,
                                self.parent["overlap"] - p_off)
                    if p_len > 0:
                        buf = parent.read(p_off, p_len)
            base = ext.logical_offset - offset
            out[base:base + len(buf)] = buf
        return bytes(out)

    def discard(self, offset: int, length: int) -> None:
        """Zero a range (whole-object removes when covered,
        ref: io/ImageRequest.cc discard).  Parent-backed objects are
        zeroed, never removed — a remove would resurrect the parent's
        bytes through the fall-through read."""
        self._check_open()
        self._check_writable()
        with self._iolock:
            self._ensure_lock()
            length = self._clip(offset, length)
            if self._oc is not None:
                # flush dirty state, then drop exactly the discarded
                # extents — the backing removes/zeros below must not
                # be shadowed by cached pages, and the rest of the
                # cache stays warm
                self._oc.flush()
                for ext in Striper.file_to_extents(self.layout,
                                                   offset, length):
                    self._oc.discard(str(ext.objectno), ext.offset,
                                     ext.length)
            if self._journal is not None and length:
                self._journal.append("discard", {"off": offset,
                                                 "len": length})
            obj_size = 1 << self.order
            over = self._overlap_span()
            for ext in Striper.file_to_extents(self.layout, offset,
                                               length):
                oid = data_name(self.name, ext.objectno)
                whole = ext.offset == 0 and ext.length == obj_size
                backed = ext.objectno < over
                if whole and not backed:
                    try:
                        self._wio.remove(oid)
                    except RadosError:
                        pass
                    self.object_map.set(ext.objectno,
                                        ObjectMap.NONEXISTENT,
                                        flush=False)
                    continue
                if backed and not whole and \
                        self.object_map.get(ext.objectno) == \
                        ObjectMap.NONEXISTENT:
                    self._copyup(ext.objectno)
                self._wio.write(oid, b"\0" * ext.length,
                                offset=ext.offset)
                self.object_map.set(ext.objectno, ObjectMap.EXISTS,
                                    flush=False)
            self.object_map.flush()

    # -- object-map-driven queries (ref: librbd object_map fast-diff) --
    def du(self) -> int:
        """Provisioned bytes from the object map — no data-object scan
        (ref: rbd du fast-diff path)."""
        self._check_open()
        self.flush()        # cached writes count once they exist
        obj_size = 1 << self.order
        used = 0
        for objno in self.object_map.existing():
            used += min(obj_size, self.size - objno * obj_size)
        return used

    def diff_since(self, snap_name: str | None) -> list[dict]:
        """Changed objects since a snapshot (None = since creation),
        straight from the object maps (ref: diff_iterate with
        whole_object=true + fast-diff)."""
        self._check_open()
        if self._snap_id is None:
            self.flush()    # cached writes must reach the object map
        obj_size = 1 << self.order
        if snap_name is None:
            base = None
        else:
            if snap_name not in self.snaps:
                raise RBDError(2, f"snapshot {snap_name!r} not found")
            base = ObjectMap(self._wio, self.name,
                             self._span_for(
                                 int(self.snaps[snap_name]["size"])),
                             snap_id=self.snaps[snap_name]["id"])
        out = []
        for objno in range(self._object_span()):
            cur = self.object_map.get(objno)
            old = base.get(objno) if base is not None \
                else ObjectMap.NONEXISTENT
            exists_now = cur != ObjectMap.NONEXISTENT
            existed = old != ObjectMap.NONEXISTENT
            dirty = cur == ObjectMap.EXISTS
            if (exists_now != existed) or (exists_now and dirty):
                out.append({"objectno": objno,
                            "offset": objno * obj_size,
                            "length": min(obj_size,
                                          self.size - objno * obj_size),
                            "exists": exists_now})
        return out

    def flatten(self) -> None:
        """Copy every parent-backed block into the child and detach
        (ref: librbd Operations::flatten)."""
        self._check_open()
        self._check_writable()
        with self._iolock:
            self._ensure_lock()
            for objno in range(self._overlap_span()):
                if self.object_map.get(objno) == ObjectMap.NONEXISTENT:
                    self._copyup(objno)
            self.object_map.flush()
            self._detach_from_parent()
            self._save_meta()

    def close(self) -> None:
        if not self._open:
            return
        self.flush()
        self.release_lock()
        if self._watch_cookie is not None:
            try:
                self.ioctx.unwatch(header_name(self.name),
                                   self._watch_cookie)
            except RadosError:     # best-effort: peer may be gone
                pass
            self._watch_cookie = None
        if self._parent_image is not None:
            self._parent_image.close()
            self._parent_image = None
        self._open = False
