"""rbd live migration: prepare / execute / commit / abort (VERDICT r4
#7; ref: src/librbd/api/Migration.cc).

Model (the reference's flow, collapsed onto the clone/copyup
machinery that already exists in rbd/image.py):

* **prepare** — the destination image is created with a *migration
  parent* link to the source's HEAD (a clone link with no snapshot
  and full-size overlap).  The source header is marked migrating:
  direct opens now refuse with EROFS-style errors, so clients switch
  to the destination, whose reads fall through to the source for
  blocks not yet copied and whose writes copy-up first — IO continues
  throughout (ref: Migration.cc prepare creating the dst with the
  migration parent + the src's migrating state).
* **execute** — background deep-copy: every destination object still
  marked NONEXISTENT copies up from the source.  Client IO to the
  destination proceeds concurrently; copyup and writes serialize on
  the image lock per object (ref: Migration.cc execute ->
  DeepCopyRequest).
* **commit** — requires execute to have completed: the migration link
  detaches and the source image is deleted (ref: Migration.cc
  commit).
* **abort** — the destination is destroyed and the source unmarked;
  the source is untouched bit-for-bit because nothing ever wrote to
  it (ref: Migration.cc abort).

Scope note (documented divergence): a source with snapshots refuses
prepare — snapshot history migration (DeepCopy's SnapshotCopyRequest)
is not implemented; the reference migrates snaps too.
"""
from __future__ import annotations

import json

from ..client import IoCtx, RadosError
from .image import (Image, ObjectMap, RBD_LOCK_NAME, RBDError,
                    header_name)


def _read_meta(ioctx: IoCtx, name: str) -> dict:
    try:
        return json.loads(ioctx.read(header_name(name)).decode())
    except RadosError as ex:
        raise RBDError(2, f"image {name!r} does not exist") from ex


def _write_meta(ioctx: IoCtx, name: str, meta: dict) -> None:
    ioctx.write_full(header_name(name), json.dumps(meta).encode())


def migration_prepare(src_ioctx: IoCtx, src_name: str,
                      dst_ioctx: IoCtx, dst_name: str) -> None:
    """(ref: Migration.cc prepare)."""
    meta = _read_meta(src_ioctx, src_name)
    if meta.get("migration"):
        raise RBDError(16, f"{src_name!r} is already migrating")
    if meta.get("snaps"):
        raise RBDError(95, "migration of images with snapshots is "
                           "not supported")
    if meta.get("mirror") or meta.get("journaling"):
        raise RBDError(95, "migration of mirrored/journaled images "
                           "is not supported")
    # an active writer holds the exclusive lock: refuse, the operator
    # must quiesce first (the reference requires the source closed)
    try:
        info = src_ioctx.exec(header_name(src_name), "lock",
                              "get_info", {"name": RBD_LOCK_NAME}) \
            or {}
        if info.get("lockers"):
            raise RBDError(16, f"{src_name!r} has an active writer")
    except RadosError:
        pass
    try:
        dst_ioctx.stat(header_name(dst_name))
        raise RBDError(17, f"image {dst_name!r} exists")
    except RadosError:
        pass
    dst_meta = {
        "size": int(meta["size"]), "order": int(meta["order"]),
        "stripe_unit": int(meta["stripe_unit"]),
        "stripe_count": int(meta["stripe_count"]),
        "parent": {"pool": src_ioctx._pool_name(), "image": src_name,
                   "snap_name": None, "snap_id": None,
                   "overlap": int(meta["size"]), "migration": True},
        "migration_source": {"pool": src_ioctx._pool_name(),
                             "image": src_name},
    }
    _write_meta(dst_ioctx, dst_name, dst_meta)
    meta["migration"] = {"state": "prepared",
                         "dst_pool": dst_ioctx._pool_name(),
                         "dst_image": dst_name}
    _write_meta(src_ioctx, src_name, meta)


def migration_execute(dst_ioctx: IoCtx, dst_name: str) -> None:
    """Deep-copy every not-yet-copied block; safe to run while
    clients write to the destination (ref: Migration.cc execute)."""
    img = Image(dst_ioctx, dst_name)
    try:
        if img.parent is None or not img.parent.get("migration"):
            raise RBDError(22, f"{dst_name!r} is not a migration "
                               "destination")
        src = img.parent
        for objno in range(img._overlap_span()):
            with img._iolock:
                img._ensure_lock()
                # the exclusive lock is the coherence point: a client
                # writer we just took it from flushed its cache AND
                # its object-map bits on release — reload the map so
                # a stale NONEXISTENT can't copy the parent's block
                # over a client write (and so our later map flushes
                # never write stale bits back)
                img.object_map = ObjectMap(img._wio, dst_name,
                                           img._object_span())
                if img.object_map.get(objno) == ObjectMap.NONEXISTENT:
                    img._copyup(objno)
        smeta = _read_meta(dst_ioctx.rados.open_ioctx(src["pool"]),
                           src["image"])
        smeta["migration"]["state"] = "executed"
        _write_meta(dst_ioctx.rados.open_ioctx(src["pool"]),
                    src["image"], smeta)
    finally:
        img.close()


def migration_commit(dst_ioctx: IoCtx, dst_name: str) -> None:
    """Detach + delete the source (ref: Migration.cc commit)."""
    dmeta = _read_meta(dst_ioctx, dst_name)
    srcref = dmeta.get("migration_source")
    if srcref is None:
        raise RBDError(22, f"{dst_name!r} is not a migration "
                           "destination")
    sio = dst_ioctx.rados.open_ioctx(srcref["pool"])
    smeta = _read_meta(sio, srcref["image"])
    if (smeta.get("migration") or {}).get("state") != "executed":
        raise RBDError(22, "migration not executed yet")
    # detach: the destination stands alone from here
    dmeta.pop("parent", None)
    dmeta.pop("migration_source", None)
    _write_meta(dst_ioctx, dst_name, dmeta)
    # delete the source bypassing the migrating-open gate
    from .image import data_name
    span = (int(smeta["size"]) + (1 << int(smeta["order"])) - 1) \
        >> int(smeta["order"])
    for objno in range(span):
        try:
            sio.remove(data_name(srcref["image"], objno))
        except RadosError:
            pass
    for suffix in ("", *(f".{s['id']}" for s in
                         (smeta.get("snaps") or {}).values())):
        try:
            sio.remove(f"rbd_object_map.{srcref['image']}{suffix}")
        except RadosError:
            pass
    sio.remove(header_name(srcref["image"]))


def migration_abort(dst_ioctx: IoCtx, dst_name: str) -> None:
    """Destroy the destination, unmark the source (ref: Migration.cc
    abort).  The source was never written, so unmarking IS the
    restore."""
    dmeta = _read_meta(dst_ioctx, dst_name)
    srcref = dmeta.get("migration_source")
    if srcref is None:
        raise RBDError(22, f"{dst_name!r} is not a migration "
                           "destination")
    from .image import data_name
    span = (int(dmeta["size"]) + (1 << int(dmeta["order"])) - 1) \
        >> int(dmeta["order"])
    for objno in range(span):
        try:
            dst_ioctx.remove(data_name(dst_name, objno))
        except RadosError:
            pass
    try:
        dst_ioctx.remove(f"rbd_object_map.{dst_name}")
    except RadosError:
        pass
    dst_ioctx.remove(header_name(dst_name))
    sio = dst_ioctx.rados.open_ioctx(srcref["pool"])
    smeta = _read_meta(sio, srcref["image"])
    smeta.pop("migration", None)
    _write_meta(sio, srcref["image"], smeta)
