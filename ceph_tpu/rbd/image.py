"""RBD-lite: block-device images striped over RADOS objects.

The librbd data-path model (ref: src/librbd/: image metadata in a
header object, data in `rbd_data.<id>.<objectno>` objects of size
2^order, io/ImageRequest.cc mapping block extents through the Striper;
naming scheme util::data_object_name): an image is a sparse array of
equal-size objects — absent objects read as zeros, partial writes touch
only the covered objects.

API mirrors librbd's Python binding surface: RBD().create/remove/list,
Image open -> read/write/discard/resize/stat/close.
"""
from __future__ import annotations

import json

from ..client.rados import IoCtx, RadosError
from ..osdc import StripeLayout, Striper

RBD_DEFAULT_ORDER = 22          # 4 MiB objects (rbd_default_order)


class RBDError(OSError):
    pass


def header_name(name: str) -> str:
    return f"rbd_header.{name}"


def data_name(name: str, objectno: int) -> str:
    """(ref: librbd util::data_object_name '%s.%016llx')."""
    return f"rbd_data.{name}.{objectno:016x}"


class RBD:
    """Pool-level image operations (ref: librbd::RBD)."""

    def create(self, ioctx: IoCtx, name: str, size: int,
               order: int = RBD_DEFAULT_ORDER, stripe_unit: int = 0,
               stripe_count: int = 1) -> None:
        if self._exists(ioctx, name):
            raise RBDError(17, f"image {name!r} exists")
        obj_size = 1 << order
        su = stripe_unit or obj_size
        layout = StripeLayout(stripe_unit=su, stripe_count=stripe_count,
                              object_size=obj_size)
        layout.validate()
        meta = {"size": size, "order": order, "stripe_unit": su,
                "stripe_count": stripe_count}
        ioctx.write_full(header_name(name), json.dumps(meta).encode())

    def remove(self, ioctx: IoCtx, name: str) -> None:
        img = Image(ioctx, name)
        try:
            for objno in range(img._object_span()):
                try:
                    ioctx.remove(data_name(name, objno))
                except RadosError:
                    pass
        finally:
            img.close()
        ioctx.remove(header_name(name))

    def list(self, ioctx: IoCtx) -> list[str]:
        """(ref: librbd::RBD::list — header-object scan)."""
        return sorted(oid[len("rbd_header."):]
                      for oid in ioctx.list_objects()
                      if oid.startswith("rbd_header."))

    @staticmethod
    def _exists(ioctx: IoCtx, name: str) -> bool:
        try:
            ioctx.stat(header_name(name))
            return True
        except RadosError:
            return False


class Image:
    """(ref: librbd::Image / ImageCtx)."""

    def __init__(self, ioctx: IoCtx, name: str):
        self.ioctx = ioctx
        self.name = name
        try:
            raw = ioctx.read(header_name(name))
        except RadosError as ex:
            raise RBDError(2, f"image {name!r} does not exist") from ex
        meta = json.loads(raw.decode())
        self.size = int(meta["size"])
        self.order = int(meta["order"])
        self.layout = StripeLayout(
            stripe_unit=int(meta["stripe_unit"]),
            stripe_count=int(meta["stripe_count"]),
            object_size=1 << self.order)
        self._open = True

    # -- metadata ------------------------------------------------------
    def stat(self) -> dict:
        """(ref: librbd image_info_t)."""
        return {"size": self.size, "order": self.order,
                "obj_size": 1 << self.order,
                "num_objs": self._object_span(),
                "stripe_unit": self.layout.stripe_unit,
                "stripe_count": self.layout.stripe_count}

    def _object_span(self) -> int:
        if self.size == 0:
            return 0
        last = Striper.file_to_extents(self.layout, self.size - 1, 1)
        return max(e.objectno for e in last) + 1

    def resize(self, size: int) -> None:
        """Grow or shrink; shrink removes whole objects past the end
        (ref: librbd Operations::resize / object trimming)."""
        self._check_open()
        old_span = self._object_span()
        self.size = size
        new_span = self._object_span()
        for objno in range(new_span, old_span):
            try:
                self.ioctx.remove(data_name(self.name, objno))
            except RadosError:
                pass
        self._save_meta()

    def _save_meta(self) -> None:
        meta = {"size": self.size, "order": self.order,
                "stripe_unit": self.layout.stripe_unit,
                "stripe_count": self.layout.stripe_count}
        self.ioctx.write_full(header_name(self.name),
                              json.dumps(meta).encode())

    # -- IO ------------------------------------------------------------
    def _check_open(self) -> None:
        if not self._open:
            raise RBDError(9, "image is closed")

    def _clip(self, offset: int, length: int) -> int:
        if offset > self.size:
            raise RBDError(22, "offset beyond end of image")
        return min(length, self.size - offset)

    def write(self, offset: int, data: bytes) -> int:
        """(ref: librbd io/ImageRequest.cc write path: extents through
        the striper, one object op per extent)."""
        self._check_open()
        length = self._clip(offset, len(data))
        futs = []
        for ext in Striper.file_to_extents(self.layout, offset, length):
            buf = data[ext.logical_offset - offset:
                       ext.logical_offset - offset + ext.length]
            futs.append(self.ioctx.aio_write(
                data_name(self.name, ext.objectno), buf,
                offset=ext.offset))
        for f in futs:
            self.ioctx._wait(f)
        return length

    def read(self, offset: int, length: int) -> bytes:
        """Sparse-aware: missing objects/ranges read as zeros."""
        self._check_open()
        length = self._clip(offset, length)
        out = bytearray(length)
        pend = []
        for ext in Striper.file_to_extents(self.layout, offset, length):
            fut = self.ioctx.aio_read(
                data_name(self.name, ext.objectno),
                length=ext.length, offset=ext.offset)
            pend.append((ext, fut))
        for ext, fut in pend:
            try:
                buf = self.ioctx._wait(fut).data
            except RadosError as ex:
                if ex.errno_name != "ENOENT":
                    raise
                buf = b""
            base = ext.logical_offset - offset
            out[base:base + len(buf)] = buf
        return bytes(out)

    def discard(self, offset: int, length: int) -> None:
        """Zero a range (whole-object removes when covered,
        ref: io/ImageRequest.cc discard)."""
        self._check_open()
        length = self._clip(offset, length)
        obj_size = 1 << self.order
        for ext in Striper.file_to_extents(self.layout, offset, length):
            oid = data_name(self.name, ext.objectno)
            if ext.offset == 0 and ext.length == obj_size:
                try:
                    self.ioctx.remove(oid)
                except RadosError:
                    pass
            else:
                self.ioctx.write(oid, b"\0" * ext.length,
                                 offset=ext.offset)

    def close(self) -> None:
        self._open = False
