"""Monitor daemon: the map service endpoint on the messenger.

Command, subscription, boot, and failure-report handling over the wire
(ref: src/mon/Monitor.cc dispatch_op; OSDMonitor.cc preprocess/
prepare split; failure handling OSDMonitor.cc:2519 prepare_failure,
down-out: OSDMonitor.cc tick :4965).  Maps propagate to subscribers as
MMap incrementals on every committed epoch (src/mon/Monitor.cc
handle_subscribe).

Quorum (multi-mon): leadership comes from the rank-based Elector
(ceph_tpu.mon.elector); the leader drives every map mutation through
the replicated Paxos pipeline (majority accept before commit,
ceph_tpu.mon.paxos) and peons forward write traffic to it
(ref: src/mon/Monitor.cc forward_request_leader, MForward).  Reads
(preprocess commands, subscriptions) are served by any mon from its
committed store.  Leases keep peons convinced the leader lives; a
stale lease (or a reset from the leader's endpoint) triggers
re-election, and lagging mons catch up by replaying committed paxos
values (MPaxosSyncReq).  Mutations are serialized through a change
queue: one staged prepare -> one proposal -> commit -> ack, matching
the reference's paxos plug.
"""
from __future__ import annotations

import itertools
import threading

from ..common.lockdep import make_lock
from ..common.racecheck import shared_state
import time
from collections import deque

from ..common.log import dout
from ..common.options import global_config
from ..msg.messages import (MAuthRequest, MConfig, MFSMap, MLog,
                            MLogAck,
                            MMap, MMDSBeacon, MMgrCommand,
                            MMgrCommandReply,
                            MGR_UNAVAILABLE_EAGAIN, MMonCommand,
                            MMonCommandAck,
                            MMonElection, MMonForward, MMonLease,
                            MMonLeaseAck, MMonSubscribe, MOSDBoot,
                            MOSDFailure, MOSDPGTemp, MPaxosAccept,
                            MPaxosBegin,
                            MPaxosCommit, MPaxosStoreSync,
                            MPaxosSyncReq, MPGStats)
from ..msg.messenger import Dispatcher, LocalNetwork, Message, Messenger
from ..osd.osdmap import CEPH_OSD_AUTOOUT, CEPH_OSD_IN, OSDMap
from .config_monitor import ConfigMonitor
from .crash_service import CrashService
from .log_monitor import LogMonitor
from .elector import Elector
from .mds_monitor import MDSMonitor
from .osd_monitor import OSDMonitor
from .pg_map import OSDStatReport, PGMap, health_checks, health_status
from .paxos import Paxos
from .store import MonitorStore

LEASE_INTERVAL = 5.0          # leader lease period (mon_lease)
LEASE_TIMEOUT = 15.0          # peon re-elects after silence (mon_lease_ack)
# stale-lease re-election pacing: a mon that keeps losing its lease
# (partitioned away, or its victories never arrive back) must not
# force a quorum-wide election every tick — capped exponential,
# reset the moment it rejoins a reign (win, lose, or a fresh lease)
ELECTION_BACKOFF_BASE_S = 1.0
ELECTION_BACKOFF_CAP_S = 60.0


def build_initial(n_osd: int, osds_per_host: int = 1
                  ) -> tuple[OSDMap, "CrushWrapper"]:
    """Named crush tree (default/host*/osd.*) + replicated_rule + all
    OSDs up/in — the vstart-style bootstrap a fresh mon starts from
    (ref: OSDMap.cc build_simple with names via CrushWrapper)."""
    from ..crush.wrapper import CrushWrapper
    from ..osd.osdmap import CEPH_OSD_EXISTS, CEPH_OSD_UP
    w = CrushWrapper.build_flat(n_osd, osds_per_host=osds_per_host)
    w.add_simple_rule("replicated_rule", "default", "host")
    m = OSDMap()
    m.set_max_osd(n_osd)
    for osd in range(n_osd):
        m.osd_state[osd] = CEPH_OSD_EXISTS | CEPH_OSD_UP
        m.osd_weight[osd] = CEPH_OSD_IN
    m.crush = w.crush
    m.epoch = 1
    return m, w


# health tables shared between the dispatch thread (beacons, mgr
# health reports, failure reports) and the tick thread (auto-out,
# lease churn) — racecheck asserts both sides hold self._lock
@shared_state(only=("_down_stamp", "_module_health", "_mds_slow"),
              mutating=("_down_stamp", "_module_health", "_mds_slow"))
class Monitor(Dispatcher):
    """mon.<rank> (ref: src/mon/Monitor.h:201)."""

    def __init__(self, network: LocalNetwork, rank: int = 0,
                 initial_map: OSDMap | None = None,
                 initial_wrapper=None, store: MonitorStore | None = None,
                 threaded: bool = True, clock=time.monotonic,
                 mon_ranks: list[int] | None = None, keyring=None,
                 crash_dir: str | None = None):
        self.name = f"mon.{rank}"
        self.rank = rank
        #: injectable clock so harnesses can run the failure/auto-out
        #: machinery on simulated time consistently with OSD ticks
        self.clock = clock
        self.store = store or MonitorStore()
        self.paxos = Paxos(self.store)
        self.osdmon = OSDMonitor(self.paxos, initial_map, initial_wrapper)
        self.configmon = ConfigMonitor(self.paxos)
        self.logmon = LogMonitor(self.paxos)
        self.mdsmon = MDSMonitor(self.paxos)
        self.crashmon = CrashService(self.paxos)
        self.ms = Messenger.create(network, self.name, threaded=threaded)
        # own-crash capture: a mon IS the crash sink, so its reports
        # stage straight into the local crash table (spool covers the
        # window where paxos can't commit yet)
        from ..common.crash import CrashReporter
        self.crash_reporter = CrashReporter(
            self.name, crash_dir=crash_dir, post=self._post_own_crash)
        self.ms.crash_hook = self.crash_reporter.capture
        # cephx: the mon runs the key server and gates inbound traffic
        # (ref: AuthMonitor + CephxServiceHandler)
        self.cephx = None
        if keyring is not None:
            from ..auth import CephxServer, attach_cephx
            self.cephx = CephxServer(keyring)
            attach_cephx(self.ms, self.name, keyring)
        self.ms.add_dispatcher(self)
        # osdmap subscribers: entity -> next epoch they need
        self._subs: dict[str, int] = {}
        # config subscribers: entity -> last version sent
        self._config_subs: dict[str, int] = {}
        # fsmap subscribers: entity -> last epoch sent
        self._fsmap_subs: dict[str, int] = {}
        # failure reports: target osd -> {reporter: stamp}
        self._failure_reports: dict[int, dict[int, float]] = {}
        # active mgr (volatile, re-registered every mgr tick): the
        # routing target for mgr-module commands (ref: MgrMonitor's
        # active mgr tracking)
        self._active_mgr: str | None = None
        # in-flight mgr-proxied commands: tid -> client reply callback
        self._mgr_proxy: dict[int, object] = {}
        self._proxy_tids = itertools.count(1)
        # volatile mgr-module health + its report stamp (expired after
        # mon_mgr_health_grace so a dead mgr's warnings don't persist)
        self._module_health: dict[str, dict] = {}
        self._module_health_stamp: float | None = None
        # cluster statistics digest (ref: src/mon/PGMap.h)
        self.pgmap = PGMap()
        self._down_stamp: dict[int, float] = {}
        # op tracking + span ring: the mon serves the same
        # dump_ops_in_flight/dump_traces surface as every other daemon
        # (ref: Monitor.cc's op_tracker member)
        from ..common.tracked_op import OpTracker
        from ..common.tracing import Tracer
        self.op_tracker = OpTracker(
            history_size=global_config()["osd_op_history_size"])
        self.tracer = Tracer(self.name)
        #: per-MDS slow-op summaries off beacons: name -> {stamp,
        #: count, oldest_age} (volatile like _beacon; cleared when a
        #: beacon reports count 0)
        self._mds_slow: dict[str, dict] = {}
        # internal thread-liveness watchdog (ref: the ceph-mon's
        # HeartbeatMap wired through Monitor::tick): the tick worker
        # arms on its FIRST tick (a constructed-but-never-ticked mon
        # in a harness is not unhealthy) and a stalled tick loop
        # surfaces as the HEARTBEAT_STALE health check + in `status`
        from ..common.heartbeat_map import HeartbeatMap
        self.hbmap = HeartbeatMap()
        self._hb_handle = self.hbmap.add_worker(
            f"{self.name}.tick", grace=60.0, arm=False)
        self._lock = make_lock(f"mon.{rank}")
        # ---- quorum state ------------------------------------------
        self.mon_ranks = sorted(mon_ranks) if mon_ranks else [rank]
        self.standalone = len(self.mon_ranks) == 1
        self.is_leader = self.standalone
        self.leader_rank: int | None = rank if self.standalone else None
        self.elector = Elector(rank, self.mon_ranks,
                               send=self._send_rank,
                               on_win=self._on_win,
                               on_lose=self._on_lose)
        self.elector.epoch = self.store.get_int("elector", "epoch", 0)
        self.paxos.rank = rank
        self.paxos.on_peon_commit = self._on_peon_commit
        self._lease_stamp = self.clock()
        self._last_lease_sent = 0.0
        # stale-lease re-election pacing (shared helper; chaos found
        # the unpaced loop: a partitioned mon re-proposing every tick
        # drags the surviving quorum through an election each time)
        from ..common.backoff import Backoff
        self._elect_backoff = Backoff(
            base_s=ELECTION_BACKOFF_BASE_S,
            cap_s=ELECTION_BACKOFF_CAP_S, jitter=False,
            clock=self.clock)
        # serialized map mutations: (stage_fn, reply_cb)
        self._chg_queue: deque = deque()
        self._chg_busy = False
        self._chg_inflight_reply = None
        # freshly-won leaders freeze proposals until enough lease acks
        # confirm no peon holds history we lack (collect-phase analogue)
        self._catchup_pending: set[int] = set()

    # ------------------------------------------------------------ setup
    def init(self) -> None:
        self.osdmon.init()
        self.configmon.init()
        self.logmon.init()
        self.mdsmon.init()
        self.crashmon.init()
        self.ms.start()
        if not self.standalone:
            # quorum members drain once the election settles (_on_win/
            # _on_lose) — committing or forwarding here would EAGAIN
            self.elector.start()
            self._persist_elector()
        else:
            self._drain_crash_spool()

    def _drain_crash_spool(self) -> None:
        """Re-post every spooled own-crash report (next boot, or a
        fresh quorum).  A spool file is deleted only when the commit
        or the leader's ack lands; the table dedups by crash_id, so
        re-draining after a failed round is safe."""
        if not self.crash_reporter.crash_dir:
            return
        for meta in self.crash_reporter.spooled():
            self._post_own_crash(meta)

    def _post_own_crash(self, meta: dict) -> None:
        """Ship one of OUR crash reports to the crash table: the
        leader (or a standalone mon) commits it locally; a peon
        forwards it to the leader like a client command and retires
        the spool copy on the MMonCommandAck.  Mid-election (no
        leader yet) the spool keeps the durable copy until the
        post-election drain."""
        cid = meta["crash_id"]
        with self._lock:
            if self.is_leader:
                self._submit_change(
                    lambda: self.crashmon.prepare_command(
                        {"prefix": "crash post", "meta": dict(meta)}),
                    reply_cb=lambda r, outs, outb: (
                        self.crash_reporter.mark_delivered(cid)
                        if r == 0 else None),
                    svc=self.crashmon)
            elif self.leader_rank is not None:
                tid = self.crash_reporter.alloc_tid(cid)
                self._send_rank(self.leader_rank, MMonForward(
                    tid=tid, client=self.name,
                    cmd={"prefix": "crash post", "meta": dict(meta)}))

    def shutdown(self) -> None:
        if getattr(self, "asok", None) is not None:
            self.asok.shutdown()
        self.ms.shutdown()

    def start_admin_socket(self, path: str) -> None:
        """`ceph daemon mon.N <cmd>` endpoint
        (ref: Monitor::do_admin_command)."""
        from ..common.admin_socket import AdminSocket
        a = AdminSocket(path)

        def _via_preprocess(prefix):
            def fn(c):
                with self._lock:
                    res = self._preprocess_mon_command(
                        {**c, "prefix": prefix})
                r, outs, outb = res
                return r, outb if outb is not None else outs
            return fn
        for p in ("status", "health", "df", "quorum_status",
                  "pg stat"):
            a.register(p.replace(" ", "_") if p == "pg stat" else p,
                       f"mon {p}", _via_preprocess(p))
        a.register("config show", "live config",
                   lambda c: (0, global_config().dump()))
        from ..common.obs import register_obs_commands
        register_obs_commands(a, self.op_tracker, self.tracer)
        a.start()
        self.asok = a

    @property
    def osdmap(self) -> OSDMap:
        return self.osdmon.osdmap

    # --------------------------------------------------------- election
    def _send_rank(self, r: int, msg: Message) -> None:
        self.ms.connect(f"mon.{r}").send_message(msg)

    def _persist_elector(self) -> None:
        from .store import StoreTransaction
        tx = StoreTransaction()
        tx.put("elector", "epoch", self.elector.epoch)
        self.store.apply_transaction(tx)

    def _on_win(self, epoch: int, quorum: list[int]) -> None:
        self.is_leader = True
        self.leader_rank = self.rank
        self._elect_backoff.reset()
        self.paxos.quorum = quorum
        self.paxos.all_ranks = list(self.mon_ranks)
        self.paxos.epoch = epoch
        self.paxos.send = self._send_rank
        self.paxos.abort_inflight()
        self._fail_queued("EAGAIN")
        # collect-phase analogue: don't propose anything until lease
        # acks show whether a peon holds commits we missed (a revived
        # stale low-rank winner must not fork history at old versions)
        self._catchup_pending = {r for r in self.mon_ranks
                                 if r != self.rank}
        # fresh reign: re-stage on top of the committed state
        self.osdmon.update_from_paxos()
        self.osdmon.create_pending()
        self.configmon.update_from_paxos()
        self.configmon.create_pending()
        self.logmon.update_from_paxos()
        self.logmon.create_pending()
        self.mdsmon.update_from_paxos()
        self.mdsmon.create_pending()
        self.crashmon.update_from_paxos()
        self.crashmon.create_pending()
        self._persist_elector()
        self._broadcast_lease()
        self._publish()
        self._drain_crash_spool()

    def _on_lose(self, epoch: int, leader: int,
                 quorum: list[int]) -> None:
        self.is_leader = False
        self.leader_rank = leader
        self.paxos.quorum = quorum
        self.paxos.all_ranks = list(self.mon_ranks)
        self.paxos.epoch = epoch
        self.paxos.send = self._send_rank
        self.paxos.abort_inflight()
        self._fail_queued("EAGAIN")
        self._lease_stamp = self.clock()
        self._elect_backoff.reset()
        self._persist_elector()
        # catch up on anything we missed while electing
        self._send_rank(leader, MPaxosSyncReq(
            version=self.paxos.last_committed, rank=self.rank))
        self._drain_crash_spool()

    def _fail_queued(self, errno_name: str) -> None:
        # the in-flight proposal's client must get a fast EAGAIN too —
        # paxos.abort_inflight drops its commit callback silently
        if self._chg_inflight_reply is not None:
            cb = self._chg_inflight_reply
            self._chg_inflight_reply = None
            cb(-11, errno_name, None)
        while self._chg_queue:
            _stage, reply_cb, _svc = self._chg_queue.popleft()
            if reply_cb is not None:
                reply_cb(-11, errno_name, None)
        self._chg_busy = False

    def _broadcast_lease(self) -> None:
        self._last_lease_sent = self.clock()
        for r in self.mon_ranks:
            if r != self.rank:
                self._send_rank(r, MMonLease(
                    epoch=self.elector.epoch,
                    stamp=self._last_lease_sent,
                    last_committed=self.paxos.last_committed,
                    quorum=tuple(self.elector.quorum)))

    def _on_peon_commit(self) -> None:
        """A replicated value landed on this peon: refresh the services
        and serve our subscribers."""
        self.osdmon.update_from_paxos()
        self.configmon.update_from_paxos()
        self.logmon.update_from_paxos()
        self.mdsmon.update_from_paxos()
        self.crashmon.update_from_paxos()
        self._publish()

    # -------------------------------------------------------- dispatch
    def ms_dispatch(self, msg: Message) -> bool:
        with self._lock:
            if isinstance(msg, MAuthRequest):
                if self.cephx is not None:
                    self.ms.connect(msg.src).send_message(
                        self.cephx.handle_request(msg))
                return True
            if isinstance(msg, MMonCommand):
                self._handle_wire_command(msg.cmd, msg.src, msg.tid)
                return True
            if isinstance(msg, MMonSubscribe):
                self._handle_subscribe(msg)
                return True
            if isinstance(msg, MOSDBoot):
                if self._relay_if_peon(msg):
                    return True
                self._handle_boot(msg)
                return True
            if isinstance(msg, MOSDFailure):
                if self._relay_if_peon(msg):
                    return True
                self._handle_failure(msg)
                return True
            if isinstance(msg, MMDSBeacon):
                if self._relay_if_peon(msg):
                    return True
                self._handle_mds_beacon(msg)
                return True
            if isinstance(msg, MOSDPGTemp):
                if self._relay_if_peon(msg):
                    return True
                self._handle_pg_temp(msg)
                return True
            if isinstance(msg, MLog):
                if self._relay_if_peon(msg):
                    return True
                self._handle_log(msg)
                return True
            if isinstance(msg, MPGStats):
                self.pgmap.ingest(OSDStatReport(
                    osd=msg.osd, epoch=msg.epoch, stamp=msg.stamp,
                    pg_stats=msg.pg_stats, kb_total=msg.kb_total,
                    kb_used=msg.kb_used, kb_avail=msg.kb_avail,
                    perf=msg.perf, slow_ops=dict(msg.slow_ops or {})))
                # mirror OSD-originated reports to the other mons so
                # status/health/df answer the same from any rank (the
                # reference replicates the digest via MgrStatMonitor)
                if msg.src.startswith("osd."):
                    for r in self.mon_ranks:
                        if r != self.rank:
                            self._send_rank(r, msg)
                return True
            if isinstance(msg, MMonElection):
                self.elector.handle(msg)
                self._persist_elector()
                return True
            if isinstance(msg, MPaxosBegin):
                if not self.is_leader:
                    self.paxos.handle_begin(
                        msg, int(msg.src.split(".")[1]))
                return True
            if isinstance(msg, MPaxosAccept):
                if self.is_leader:
                    self.paxos.handle_accept(msg)
                return True
            if isinstance(msg, MPaxosCommit):
                self.paxos.handle_commit(msg)
                return True
            if isinstance(msg, MPaxosSyncReq):
                if self.is_leader:
                    for m in self.paxos.sync_reply(msg.version):
                        self._send_rank(msg.rank, m)
                return True
            if isinstance(msg, MMonLease):
                sender = int(msg.src.split(".")[1])
                if msg.epoch < self.elector.epoch:
                    return True     # stale reign
                if sender != self.leader_rank:
                    # a lease is a quorum-backed leadership claim:
                    # adopt it (heals diverged views after a
                    # double-win epoch)
                    self.elector.epoch = msg.epoch
                    self.elector.electing = False
                    self.elector.leader = sender
                    self.is_leader = False
                    self.leader_rank = sender
                    self.paxos.epoch = msg.epoch
                    self.paxos.send = self._send_rank
                    self.paxos.all_ranks = list(self.mon_ranks)
                    self._persist_elector()
                self._lease_stamp = self.clock()
                if msg.quorum:
                    # adopt the reigning quorum: ours may be a stale
                    # pre-partition view that still lists us, masking
                    # that the leader's election left us out
                    self.elector.quorum = list(msg.quorum)
                if self.rank in self.elector.quorum:
                    # an out-of-quorum peon keeps its backoff armed:
                    # leases alone must not pace-reset the re-propose
                    # loop that gets it readmitted
                    self._elect_backoff.reset()
                if msg.last_committed > self.paxos.last_committed:
                    self._send_rank(sender, MPaxosSyncReq(
                        version=self.paxos.last_committed,
                        rank=self.rank))
                elif msg.last_committed < self.paxos.last_committed:
                    # the (stale, freshly elected) leader is BEHIND us:
                    # push the commits it missed before it proposes
                    # conflicting versions
                    for m in self.paxos.sync_reply(msg.last_committed):
                        self._send_rank(sender, m)
                # lease ack completes the leader's collect phase
                self._send_rank(sender, MMonLeaseAck(
                    epoch=msg.epoch, rank=self.rank,
                    last_committed=self.paxos.last_committed))
                return True
            if isinstance(msg, MMonLeaseAck):
                if self.is_leader and msg.epoch == self.elector.epoch:
                    self._catchup_pending.discard(msg.rank)
                    # unfreeze on a majority (incl. self): a member
                    # that died right after the election must not
                    # freeze the reign forever
                    have = len(self.mon_ranks) - \
                        len(self._catchup_pending)
                    if have >= len(self.mon_ranks) // 2 + 1:
                        self._catchup_pending = set()
                        self._pump_changes()
                return True
            if isinstance(msg, MMgrCommandReply):
                cb = self._mgr_proxy.pop(msg.tid, None)
                if cb is not None:
                    cb(msg.result, msg.outs, msg.outb)
                return True
            if isinstance(msg, MMonCommandAck):
                # the leader acked an own-crash post we forwarded as
                # a peon: retire the spool copy (a non-zero result —
                # e.g. leadership raced away — leaves it for the next
                # post-election drain)
                self.crash_reporter.on_ack(msg.tid, msg.result)
                return True
            if isinstance(msg, MMonForward):
                if self.is_leader:
                    self._handle_wire_command(msg.cmd, msg.client,
                                              msg.tid)
                else:
                    # leadership raced away mid-forward: fast EAGAIN
                    # beats the client's 30s timeout
                    self.ms.connect(msg.client).send_message(
                        MMonCommandAck(tid=msg.tid, result=-11,
                                       outs="EAGAIN: not the leader"))
                return True
            if isinstance(msg, MPaxosStoreSync):
                if not self.is_leader:
                    self.paxos.apply_store_sync(msg)
                return True
        return False

    def ms_handle_reset(self, peer: str) -> None:
        with self._lock:
            if peer and peer == self._active_mgr:
                # the active mgr died: fail its in-flight proxied
                # commands fast instead of letting clients time out
                self._active_mgr = None
                for tid in list(self._mgr_proxy):
                    cb = self._mgr_proxy.pop(tid)
                    cb(-11, MGR_UNAVAILABLE_EAGAIN
                       + "active mgr went away", None)
            if not self.standalone and peer.startswith("mon.") and \
                    self.leader_rank is not None and \
                    peer == f"mon.{self.leader_rank}" and \
                    not self.is_leader and not self.elector.electing:
                # (electing guard: proposing to the dead leader reports
                # a reset synchronously — without it this would recurse)
                dout("mon", 1).write("%s: leader %s gone, re-electing",
                                     self.name, peer)
                self.elector.start()
                self._persist_elector()

    def _relay_if_peon(self, msg: Message) -> bool:
        """Peons relay map-mutating daemon traffic to the leader
        (payloads carry identities, so re-sending is safe)."""
        if self.is_leader:
            return False
        if self.leader_rank is not None:
            self._send_rank(self.leader_rank, msg)
        return True

    # -------------------------------------------------------- commands
    def _handle_wire_command(self, cmdmap: dict, client: str,
                             tid: int) -> None:
        # track the command like the OSD tracks client ops: a command
        # stuck behind a dead mgr / wedged paxos round ages into the
        # mon's dump_blocked_ops and the SLOW_OPS health feed
        self.op_tracker.start(
            (client, tid),
            f"mon_command({client} tid={tid} "
            f"{cmdmap.get('prefix', '?')})")

        def reply(r, outs, outb):
            self.op_tracker.finish((client, tid),
                                   "replied" if r == 0 else f"r={r}")
            self.ms.connect(client).send_message(MMonCommandAck(
                tid=tid, result=r, outs=outs, outb=outb))

        self._dispatch_command(cmdmap, reply, client=client, tid=tid)

    def _service_for(self, cmdmap: dict):
        """Command prefix -> owning PaxosService (ref:
        Monitor::dispatch_op's service fan-out)."""
        pfx = str(cmdmap.get("prefix", ""))
        if pfx.startswith("config"):
            return self.configmon
        if pfx == "log" or pfx.startswith("log "):
            return self.logmon
        if pfx.startswith(("fs ", "mds ")) or pfx in ("fs", "mds"):
            return self.mdsmon
        if pfx == "crash" or pfx.startswith("crash "):
            return self.crashmon
        return self.osdmon

    def _dispatch_command(self, cmdmap: dict, reply_cb,
                          client: str = "", tid: int = 0) -> None:
        """preprocess locally; stage writes through the change queue
        (leader) or forward them to it (peon,
        ref: Monitor::forward_request_leader).  The prefix routes to
        the owning PaxosService (ref: Monitor::dispatch_op's service
        fan-out).  Mgr-module prefixes (telemetry/insights) proxy to
        the registered active mgr instead (ref: the MgrMonitor routing
        of module commands)."""
        pfx = str(cmdmap.get("prefix", ""))
        if pfx.split(" ", 1)[0] in ("telemetry", "insights"):
            self._proxy_to_mgr(cmdmap, reply_cb)
            return
        res = self._preprocess_mon_command(cmdmap)
        if res is not None:
            reply_cb(*res)
            return
        svc = self._service_for(cmdmap)
        try:
            res = svc.preprocess_command(cmdmap)
        except (KeyError, ValueError, TypeError) as ex:
            reply_cb(-22, f"invalid command arguments: {ex}", None)
            return
        if res is not None:
            reply_cb(*res)
            return
        if not self.is_leader:
            if self.leader_rank is None or not client:
                reply_cb(-11, "EAGAIN: not the quorum leader", None)
                return
            # forward; the leader acks the client directly (so OUR
            # tracked op is done — it must not age into SLOW_OPS)
            self.op_tracker.finish((client, tid), "forwarded")
            self._send_rank(self.leader_rank, MMonForward(
                tid=tid, client=client, cmd=cmdmap))
            return
        self._submit_change(
            lambda: svc.prepare_command(cmdmap), reply_cb, svc)

    def _proxy_to_mgr(self, cmdmap: dict, reply_cb) -> None:
        """Relay a mgr-module command to the active mgr; its reply
        (MMgrCommandReply) comes back HERE and we ack the client over
        our learned connection — the mgr may have no route to an
        ad-hoc client entity (ref: MgrMonitor + MCommand routing)."""
        if self._active_mgr is None:
            reply_cb(-11, MGR_UNAVAILABLE_EAGAIN + "no active mgr",
                     None)
            return
        tid = next(self._proxy_tids)
        self._mgr_proxy[tid] = reply_cb
        ok = self.ms.connect(self._active_mgr).send_message(
            MMgrCommand(tid=tid, cmd=dict(cmdmap)))
        # a failed send resets synchronously (ms_handle_reset already
        # failed every proxied tid, including this one)
        if not ok and self._mgr_proxy.pop(tid, None) is not None:
            self._active_mgr = None
            reply_cb(-11, MGR_UNAVAILABLE_EAGAIN
                     + "active mgr unreachable", None)

    # ------------------------------------------- cluster-level commands
    # (ref: Monitor::handle_command's mon-level table — `ceph -s`
    #  Monitor.cc get_cluster_status, health get_health, df from PGMap)
    def quorum(self) -> list[int]:
        if self.standalone:
            return [self.rank]
        return sorted(self.paxos.quorum or [self.rank])

    def _preprocess_mon_command(self, cmdmap: dict):
        prefix = cmdmap.get("prefix", "")
        if prefix == "mgr register":
            # the active mgr announces itself (volatile; re-sent every
            # mgr tick) — the routing target for telemetry/insights
            # command proxying (ref: MgrMonitor beacon handling)
            self._active_mgr = str(cmdmap.get("name", "")) or None
            return 0, "", None
        if prefix == "mgr health report":
            # volatile module health (devicehealth etc.) — replaces
            # the previous report wholesale so cleared checks vanish,
            # and STAMPED so a dead mgr's last report expires after
            # mon_mgr_health_grace instead of warning forever
            self._module_health = {
                str(k): {"severity": str(v.get("severity",
                                               "HEALTH_WARN")),
                         "summary": str(v.get("summary", "")),
                         "detail": list(v.get("detail", []))}
                for k, v in dict(cmdmap.get("checks", {})).items()}
            self._module_health_stamp = self.clock()
            return 0, "", None
        if prefix == "osd perf dump":
            # per-daemon counters as last reported (the mgr's
            # prometheus module scrapes these; ref: DaemonState
            # perf_counters aggregation in src/mgr/)
            return 0, "", {f"osd.{o}": r.perf
                           for o, r in sorted(
                               self.pgmap.osd_reports.items())
                           if r.perf}
        if prefix not in ("status", "health", "health detail", "df",
                          "pg stat", "pg dump", "quorum_status",
                          "mon stat"):
            return None
        now = self.clock()
        up = {o for o in range(self.osdmap.max_osd)
              if self.osdmap.is_up(o)}
        pgs = self.pgmap.primary_pgs(up)    # one digest per command
        # non-OSD slow-op feeds: MDS beacons (expired with the beacon
        # grace so a dead daemon's last report doesn't warn forever)
        # and the mon's own command tracker
        grace_mds = global_config()["mds_beacon_grace"]
        slow = {name: s for name, s in self._mds_slow.items()
                if now - s.get("stamp", now) <= grace_mds}
        own = self.op_tracker.slow_summary()
        if own["count"]:
            slow[self.name] = own
        checks = health_checks(
            self.osdmap, self.pgmap, self.quorum(), self.mon_ranks,
            now, stale_after=global_config()
            ["mon_osd_stale_report_grace"], pgs=pgs, slow_ops=slow)
        # mgr-module health reports (devicehealth/crash etc.) merge in
        # (ref: MgrStatMonitor's health contributions — volatile here
        # rather than paxos'd: the mgr re-reports every tick, so a
        # failed-over mon repopulates within one period).  A report
        # older than mon_mgr_health_grace is a dead mgr's leftovers:
        # it must not warn forever (0 = never expire).
        grace = global_config()["mon_mgr_health_grace"]
        if self._module_health_stamp is not None and \
                (grace <= 0 or
                 now - self._module_health_stamp <= grace):
            checks.update(self._module_health)
        # own thread-liveness watchdog (ref: "heartbeat_map is_healthy
        # ... had timed out"): a mon tick loop that stopped beating
        # past its grace is a health warning, not a silent wedge
        checks.update(self.hbmap.health_check())
        if prefix in ("health", "health detail"):
            out = {"status": health_status(checks),
                   "checks": {k: {"severity": v["severity"],
                                  "summary": v["summary"]}
                              for k, v in checks.items()}}
            if prefix == "health detail":
                for k, v in checks.items():
                    out["checks"][k]["detail"] = v["detail"]
            return 0, out["status"], out
        if prefix in ("quorum_status", "mon stat"):
            return 0, "", {"quorum": self.quorum(),
                           "leader": self.leader_rank,
                           "mons": list(self.mon_ranks),
                           "election_epoch": self.elector.epoch}
        if prefix == "pg stat":
            t = self.pgmap.totals(pgs)
            states = self.pgmap.pg_states(pgs)
            return 0, (f"{t['num_pgs']} pgs: "
                       + ", ".join(f"{n} {s}" for s, n in
                                   sorted(states.items()))
                       + f"; {t['num_objects']} objects"), \
                {"states": states, **t}
        if prefix == "pg dump":
            return 0, "", pgs
        if prefix == "df":
            d = self.pgmap.df(pgs, up)
            d["pools"] = {
                self.osdmap.pool_names.get(pid, str(pid)): st
                for pid, st in d["pools"].items()}
            return 0, "", d
        # status == `ceph -s`
        n_in = sum(1 for o in range(self.osdmap.max_osd)
                   if self.osdmap.exists(o) and self.osdmap.is_in(o))
        exists = sum(1 for o in range(self.osdmap.max_osd)
                     if self.osdmap.exists(o))
        t = self.pgmap.totals(pgs)
        return 0, "", {
            "health": {"status": health_status(checks),
                       "checks": {k: v["summary"]
                                  for k, v in checks.items()}},
            "monmap": {"mons": list(self.mon_ranks),
                       "quorum": self.quorum(),
                       "leader": self.leader_rank},
            "osdmap": {"epoch": self.osdmap.epoch, "num_osds": exists,
                       "num_up_osds": len(up), "num_in_osds": n_in},
            "pgmap": {"num_pgs": t["num_pgs"],
                      "pgs_by_state": self.pgmap.pg_states(pgs),
                      "num_objects": t["num_objects"],
                      "bytes_data": t["bytes"],
                      **{k: v for k, v in
                         self.pgmap.df(pgs, up).items()
                         if k != "pools"}},
        }

    def handle_command(self, cmdmap: dict) -> tuple[int, str, object]:
        """Synchronous command path (tests/CLI).  Completes inline on a
        standalone mon; in a quorum a write's commit needs peon acks,
        so this API refuses it BEFORE staging anything — use the wire
        path there (reads work everywhere)."""
        slot: dict = {}
        with self._lock:
            if not self.standalone:
                res = self._preprocess_mon_command(cmdmap)
                if res is not None:
                    return res
                svc = self._service_for(cmdmap)
                try:
                    res = svc.preprocess_command(cmdmap)
                except (KeyError, ValueError, TypeError) as ex:
                    return -22, f"invalid command arguments: {ex}", None
                if res is not None:
                    return res
                raise RuntimeError(
                    "write command needs a quorum commit; use the "
                    "wire path")
            self._dispatch_command(
                cmdmap, lambda r, outs, outb: slot.update(
                    r=r, outs=outs, outb=outb))
        if "r" not in slot:
            raise RuntimeError(
                "command awaits quorum commit; use the wire path")
        return slot["r"], slot["outs"], slot["outb"]

    # ---------------------------------------------- serialized changes
    def _submit_change(self, stage, reply_cb=None, svc=None) -> None:
        """stage() runs prepare handlers against the service's pending
        state and returns (r, outs, outb) or None; the proposal commits
        before the next change stages (the reference's paxos plug)."""
        self._chg_queue.append((stage, reply_cb, svc or self.osdmon))
        self._pump_changes()

    def _pump_changes(self) -> None:
        # Re-entrancy guard: a stage() callback may itself submit a
        # change (e.g. the osd-failure stage logging through
        # clog_event -> logmon).  The nested call must only ENQUEUE —
        # running it inline would pop and propose a second service
        # while the outer frame's proposal is still being staged,
        # breaking the one-proposal-at-a-time plug.  The outer drain
        # loop picks nested submissions up in order.
        if getattr(self, "_pumping", False):
            return
        self._pumping = True
        try:
            while not self._chg_busy and self._chg_queue:
                if not self.is_leader:
                    self._fail_queued("EAGAIN")
                    return
                if self._catchup_pending:
                    return   # collect phase: lease acks will pump us
                stage, reply_cb, svc = self._chg_queue.popleft()
                try:
                    res = stage()
                except (KeyError, ValueError, TypeError) as ex:
                    svc.create_pending()
                    if reply_cb is not None:
                        reply_cb(-22,
                                 f"invalid command arguments: {ex}",
                                 None)
                    continue
                r, outs, outb = res if res is not None \
                    else (0, "", None)
                if r != 0 or svc._is_pending_empty():
                    svc.create_pending()
                    if reply_cb is not None:
                        reply_cb(r, outs, outb)
                    continue
                self._chg_busy = True
                self._chg_inflight_reply = reply_cb

                def committed(reply_cb=reply_cb, r=r, outs=outs,
                              outb=outb):
                    self._chg_busy = False
                    self._chg_inflight_reply = None
                    self._publish()
                    if reply_cb is not None:
                        reply_cb(r, outs, outb)
                    # async completion (paxos round-trip): drain what
                    # queued meanwhile; a SYNCHRONOUS completion
                    # (standalone mon) is suppressed by _pumping and
                    # the outer while-loop continues instead
                    self._pump_changes()

                svc.propose_pending(on_done=committed)
        finally:
            self._pumping = False

    # ---------------------------------------------------- subscriptions
    def _handle_subscribe(self, msg: MMonSubscribe) -> None:
        if msg.what == "config":
            self._config_subs[msg.src] = 0
            self._send_config(msg.src)
            return
        if msg.what == "fsmap":
            self._fsmap_subs[msg.src] = 0
            self._send_fsmap(msg.src)
            return
        if msg.what != "osdmap":
            return
        self._subs[msg.src] = msg.start or 1
        self._send_maps(msg.src)

    def _send_fsmap(self, entity: str) -> None:
        """Push the current fsmap when the subscriber hasn't seen this
        epoch (ref: Monitor handle_subscribe "fsmap" / MDSMonitor
        check_subs)."""
        m = self.mdsmon.fsmap
        if self._fsmap_subs.get(entity, 0) >= m.epoch:
            return
        self._fsmap_subs[entity] = m.epoch
        self.ms.connect(entity).send_message(
            MFSMap(epoch=m.epoch, fsmap=m))

    def _send_config(self, entity: str) -> None:
        """Push the entity's merged config when it changed since the
        last push (ref: ConfigMonitor::send_config / check_all_subs)."""
        ver = self.configmon.get_last_committed()
        if self._config_subs.get(entity, 0) >= ver:
            return
        self._config_subs[entity] = ver
        self.ms.connect(entity).send_message(MConfig(
            version=ver,
            values=self.configmon.entity_config(entity)))

    def _send_maps(self, entity: str) -> None:
        """Send everything from the subscriber's next epoch to current
        (ref: OSDMonitor.cc send_incremental)."""
        start = self._subs.get(entity, 1)
        cur = self.osdmap.epoch
        if start > cur:
            return
        first = self.osdmon.get_first_committed() or 1
        incs = []
        if start > first:
            for e in range(start, cur + 1):
                inc = self.osdmon.get_incremental(e)
                if inc is None:
                    incs = None
                    break
                incs.append(inc)
        else:
            incs = None
        if incs is not None and start > 1:
            m = MMap(incrementals=incs, first=start, last=cur)
        else:
            m = MMap(full_map=self.osdmon.get_full_map(cur),
                     first=cur, last=cur)
        self.ms.connect(entity).send_message(m)
        self._subs[entity] = cur + 1

    def _publish(self) -> None:
        """Push new epochs to all subscribers (post-commit)."""
        for entity in list(self._subs):
            self._send_maps(entity)
        for entity in list(self._config_subs):
            self._send_config(entity)
        for entity in list(self._fsmap_subs):
            self._send_fsmap(entity)

    # ------------------------------------------------------------- boot
    def _handle_boot(self, msg: MOSDBoot) -> None:
        """(ref: OSDMonitor.cc:3270 prepare_boot — mark up; a brand-new
        osd also gets EXISTS and full in-weight)."""
        osd = msg.osd
        if osd < 0:
            return
        self._failure_reports.pop(osd, None)
        self._down_stamp.pop(osd, None)

        def stage():
            m = self.osdmap
            if osd < m.max_osd and m.is_up(osd):
                return (1, "", None)      # nothing to do, no proposal
            inc = self.osdmon.pending_inc
            if osd >= m.max_osd:
                inc.new_max_osd = osd + 1
            inc.new_up_osds.append(osd)
            if osd >= m.max_osd or not m.exists(osd):
                inc.new_weight[osd] = CEPH_OSD_IN
            elif m.osd_state[osd] & CEPH_OSD_AUTOOUT and m.is_out(osd):
                # an auto-out osd comes back in on boot
                # (ref: mon_osd_auto_mark_auto_out_in)
                inc.new_weight[osd] = CEPH_OSD_IN
                inc.new_state[osd] = \
                    inc.new_state.get(osd, 0) | CEPH_OSD_AUTOOUT
            dout("mon", 1).write("%s: osd.%d boot", self.name, osd)
            return (0, "", None)

        self._submit_change(stage)

    # ---------------------------------------------------------- failure
    def _handle_failure(self, msg: MOSDFailure) -> None:
        """Quorum-of-reporters mark-down
        (ref: OSDMonitor.cc:2519 prepare_failure / check_failure:
        reporters must be distinct live peers, reports expire after the
        grace window)."""
        target = msg.target_osd
        reporter = msg.reporter
        m = self.osdmap
        if not (0 <= target < m.max_osd) or m.is_down(target):
            return
        if reporter == target or not (0 <= reporter < m.max_osd) or \
                m.is_down(reporter):
            return
        now = self.clock()
        grace = global_config()["osd_heartbeat_grace"]
        reports = self._failure_reports.setdefault(target, {})
        reports[reporter] = now
        for r, stamp in list(reports.items()):
            if now - stamp > grace:
                del reports[r]
        need = global_config()["mon_osd_min_down_reporters"]
        if len(reports) >= need:
            self._mark_down(target)

    def _handle_mds_beacon(self, msg: MMDSBeacon) -> None:
        """(ref: MDSMonitor::preprocess_beacon/prepare_beacon): stamp
        the gid, stage any fsmap change, and answer the sender with
        the current map so it learns assignments/standdowns without a
        separate subscription."""
        self.mdsmon.note_beacon(msg.gid, self.clock())
        # SLOW_OPS feed, MDS half: the beacon piggybacks the daemon's
        # op-tracker summary; count 0 clears the entry (drained)
        sl = dict(msg.slow_ops or {})
        if msg.name:
            if int(sl.get("count", 0)) > 0:
                self._mds_slow[msg.name] = dict(sl,
                                                stamp=self.clock())
            else:
                self._mds_slow.pop(msg.name, None)
        # reply to the daemon's ENTITY name, not msg.src: a beacon
        # relayed through a peon arrives with the peon's src
        src = msg.name or msg.src

        def reply(_r, _outs, _outb):
            m = self.mdsmon.fsmap
            self.ms.connect(src).send_message(
                MFSMap(epoch=m.epoch, fsmap=m))

        now = self.clock()
        self._submit_change(
            lambda: self.mdsmon.stage_beacon(msg, now),
            reply_cb=reply, svc=self.mdsmon)

    def _handle_pg_temp(self, msg: MOSDPGTemp) -> None:
        """pg_temp request from a peering primary (ref:
        OSDMonitor::prepare_pgtemp): pin the PG's acting set to the
        data holders while the up set backfills; an empty list clears
        the override when the backfill finishes."""
        def stage():
            m = self.osdmap
            pg = msg.pgid
            if pg is None or pg.pool not in m.pools or \
                    pg.ps >= m.pools[pg.pool].pg_num:
                return (1, "", None)
            want = [o for o in msg.osds
                    if 0 <= o < m.max_osd and m.is_up(o)]
            if msg.osds and not want:
                # a PIN whose members are all momentarily down must
                # not degenerate into a clear of the live override
                return (1, "", None)
            inc = self.osdmon.pending_inc
            cur = inc.new_pg_temp.get(pg, m.pg_temp.get(pg, []))
            if want == list(cur):
                return (1, "", None)       # no-op, no proposal
            if not want and pg not in m.pg_temp and \
                    pg not in inc.new_pg_temp:
                return (1, "", None)       # clearing nothing
            inc.new_pg_temp[pg] = want
            dout("mon", 4).write("%s: pg_temp %s -> %s (from osd.%d)",
                                 self.name, pg, want, msg.from_osd)
            return (0, "", None)

        self._submit_change(stage)

    def _handle_log(self, msg: MLog) -> None:
        """Daemon LogClient batch: stage through the logm paxos
        service and ack the sender's high-water seq once committed
        (ref: LogMonitor::prepare_log + MLogAck)."""
        src = msg.src
        by_name: dict[str, int] = {}
        for e in msg.entries:
            n = str(e.get("name", "?"))
            by_name[n] = max(by_name.get(n, -1), int(e.get("seq", 0)))

        def stage():
            if not self.logmon.stage_entries(list(msg.entries)):
                # pure resend: ack again without an empty proposal
                for n, s in by_name.items():
                    self.ms.connect(src).send_message(MLogAck(
                        name=n, last_seq=s))
                return (1, "", None)
            return (0, "", None)

        def done(r, _outs, _outb):
            if r == 0:
                for n, s in by_name.items():
                    self.ms.connect(src).send_message(MLogAck(
                        name=n, last_seq=s))

        self._submit_change(stage, reply_cb=done, svc=self.logmon)

    def clog_event(self, level: str, text: str) -> None:
        """Mon-originated cluster-log entry (osd down/out, health
        transitions) staged for the next logm proposal (ref: the
        mon_clog channel in LogMonitor).  Staging happens inside the
        serialized stage callback so the seq is computed against the
        pending state it actually lands on."""
        def stage():
            seq = self.logmon.last_seq_for(self.name) + 1 + len(
                [e for e in self.logmon.pending
                 if e["name"] == self.name])
            ok = self.logmon.stage_entries([{
                "seq": seq, "stamp": self.clock(),
                "name": self.name, "level": level, "text": text}])
            return (0, "", None) if ok else (1, "", None)
        self._submit_change(stage, svc=self.logmon)

    def _mark_down_pgmap(self, osd: int) -> None:
        """Drop a downed OSD's stat report: its capacity must leave the
        df totals and its stale primary claims must not fight the new
        primary's (ref: PGMap purged on osd removal)."""
        self.pgmap.forget(osd)

    def _mark_down(self, osd: int) -> None:
        self._failure_reports.pop(osd, None)
        self._down_stamp[osd] = self.clock()
        self._mark_down_pgmap(osd)

        def stage():
            if self.osdmap.is_down(osd):
                return (1, "", None)
            self.osdmon.pending_inc.new_down_osds.append(osd)
            dout("mon", 1).write("%s: marking osd.%d down", self.name,
                                 osd)
            # log only when this stage actually marks it (a racing
            # second failure quorum must not double-count the event)
            self.clog_event("warn", f"osd.{osd} marked down after "
                            "failure reports from its peers")
            return (0, "", None)

        self._submit_change(stage)

    # -------------------------------------------------------------- tick
    def tick(self, now: float | None = None) -> None:
        """Periodic: auto-out down OSDs; leases/re-election in a
        quorum (ref: OSDMonitor.cc:4965 tick; Monitor.cc tick).
        Crash-capturing entry: an unhandled tick exception lands in
        the crash table before propagating."""
        try:
            self._tick(now)
        except Exception as exc:
            self.crash_reporter.capture(exc)
            raise

    def _tick(self, now: float | None = None) -> None:
        self.hbmap.reset_timeout(self._hb_handle)
        with self._lock:
            now = self.clock() if now is None else now
            if not self.standalone:
                if self.is_leader:
                    if self._catchup_pending and \
                            now - self._last_lease_sent >= 1.0:
                        self._broadcast_lease()   # re-ask for acks
                    elif now - self._last_lease_sent >= LEASE_INTERVAL:
                        self._broadcast_lease()
                elif (self.leader_rank is None or
                        now - self._lease_stamp > LEASE_TIMEOUT or
                        self.rank not in self.elector.quorum) and \
                        self._elect_backoff.ready(now):
                    # third clause: a lease-fed peon OUTSIDE the
                    # quorum (its election ack got lost) must keep
                    # proposing — paced — until the quorum admits it
                    dout("mon", 1).write(
                        "%s: lease stale, re-electing (attempt %d)",
                        self.name, self._elect_backoff.failures + 1)
                    self._elect_backoff.fail(now)
                    self.elector.start()
                    self._persist_elector()
            if not self.is_leader:
                return
            # MDS beacon-lapse detection + standby promotion
            # (ref: MDSMonitor::tick)
            m = self.mdsmon.fsmap
            if m.ranks or m.standbys:
                self._submit_change(
                    lambda now=now: self.mdsmon.stage_failures(now),
                    svc=self.mdsmon)
            interval = global_config()["mon_osd_down_out_interval"]
            to_out = []
            for osd, stamp in list(self._down_stamp.items()):
                m = self.osdmap
                if m.is_up(osd):
                    del self._down_stamp[osd]
                    continue
                if interval and now - stamp >= interval and m.is_in(osd):
                    to_out.append(osd)
            if not to_out:
                return

            def stage():
                changed = False
                for osd in to_out:
                    m = self.osdmap
                    if m.is_up(osd) or m.is_out(osd):
                        continue
                    self.osdmon.pending_inc.new_weight[osd] = 0
                    self.osdmon.pending_inc.new_state[osd] = \
                        self.osdmon.pending_inc.new_state.get(osd, 0) \
                        | CEPH_OSD_AUTOOUT
                    changed = True
                    dout("mon", 1).write("%s: auto-out osd.%d",
                                         self.name, osd)
                    self.clog_event(
                        "warn", f"osd.{osd} auto-marked out after "
                        "staying down past the interval")
                return (0, "", None) if changed else (1, "", None)

            self._submit_change(stage)
