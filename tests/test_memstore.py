"""ObjectStore/MemStore tests (behavioral model: src/test/objectstore/
store_test.cc basic suites — SimpleWrite/SimpleClone/OmapSimple — plus
the atomicity guarantee this implementation adds on top of the
reference's assert-mid-apply behavior)."""
import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.store import MemStore, ObjectId, StoreError, Transaction


@pytest.fixture
def store():
    s = MemStore()
    s.mkfs()
    s.mount()
    t = Transaction().create_collection("cid")
    s.queue_transaction(t)
    return s


OID = ObjectId("obj1")


def test_write_read_extend(store):
    t = Transaction().write("cid", OID, 0, b"hello")
    store.queue_transaction(t)
    assert store.read("cid", OID) == b"hello"
    # overwrite + extend past EOF zero-fills the gap
    t = Transaction().write("cid", OID, 8, b"world")
    store.queue_transaction(t)
    assert store.read("cid", OID) == b"hello\0\0\0world"
    assert store.stat("cid", OID)["size"] == 13
    assert store.read("cid", OID, 8, 5) == b"world"
    assert store.read("cid", OID, 8) == b"world"


def test_zero_truncate(store):
    store.queue_transaction(Transaction().write("cid", OID, 0, b"x" * 16))
    store.queue_transaction(Transaction().zero("cid", OID, 4, 8))
    assert store.read("cid", OID) == b"x" * 4 + b"\0" * 8 + b"x" * 4
    store.queue_transaction(Transaction().truncate("cid", OID, 6))
    assert store.read("cid", OID) == b"x" * 4 + b"\0" * 2
    store.queue_transaction(Transaction().truncate("cid", OID, 10))
    assert store.stat("cid", OID)["size"] == 10


def test_touch_remove_exists(store):
    assert not store.exists("cid", OID)
    store.queue_transaction(Transaction().touch("cid", OID))
    assert store.exists("cid", OID)
    assert store.read("cid", OID) == b""
    store.queue_transaction(Transaction().remove("cid", OID))
    assert not store.exists("cid", OID)
    with pytest.raises(StoreError):
        store.queue_transaction(Transaction().remove("cid", OID))


def test_attrs(store):
    store.queue_transaction(
        Transaction().touch("cid", OID)
        .setattr("cid", OID, "hinfo", {"a": 1})
        .setattrs("cid", OID, {"x": b"1", "y": b"2"}))
    assert store.getattr("cid", OID, "hinfo") == {"a": 1}
    assert store.getattrs("cid", OID) == {"hinfo": {"a": 1},
                                          "x": b"1", "y": b"2"}
    store.queue_transaction(Transaction().rmattr("cid", OID, "x"))
    assert "x" not in store.getattrs("cid", OID)
    with pytest.raises(StoreError):
        store.getattr("cid", OID, "x")
    store.queue_transaction(Transaction().rmattrs("cid", OID))
    assert store.getattrs("cid", OID) == {}


def test_omap(store):
    store.queue_transaction(
        Transaction().omap_setkeys("cid", OID, {"k1": b"v1", "k2": b"v2"}))
    assert store.omap_get("cid", OID) == {"k1": b"v1", "k2": b"v2"}
    store.queue_transaction(Transaction().omap_rmkeys("cid", OID, ["k1"]))
    assert store.omap_get("cid", OID) == {"k2": b"v2"}
    store.queue_transaction(Transaction().omap_clear("cid", OID))
    assert store.omap_get("cid", OID) == {}


def test_clone_full_and_range(store):
    c2 = ObjectId("clone")
    store.queue_transaction(
        Transaction().write("cid", OID, 0, b"abcdefgh")
        .setattr("cid", OID, "tag", b"t")
        .omap_setkeys("cid", OID, {"k": b"v"})
        .clone("cid", OID, c2))
    assert store.read("cid", c2) == b"abcdefgh"
    assert store.getattr("cid", c2, "tag") == b"t"
    assert store.omap_get("cid", c2) == {"k": b"v"}
    # clone is independent of the source
    store.queue_transaction(Transaction().write("cid", OID, 0, b"XXXX"))
    assert store.read("cid", c2) == b"abcdefgh"
    c3 = ObjectId("range")
    store.queue_transaction(
        Transaction().clone_range("cid", OID, c3, 2, 4, 1))
    assert store.read("cid", c3) == b"\0XXef"


def test_collection_lifecycle(store):
    t = Transaction().create_collection("cid2")
    store.queue_transaction(t)
    assert store.collection_exists("cid2")
    assert set(store.list_collections()) == {"cid", "cid2"}
    with pytest.raises(StoreError):          # EEXIST
        store.queue_transaction(Transaction().create_collection("cid2"))
    store.queue_transaction(Transaction().touch("cid2", OID))
    with pytest.raises(StoreError):          # ENOTEMPTY
        store.queue_transaction(Transaction().remove_collection("cid2"))
    store.queue_transaction(
        Transaction().remove("cid2", OID).remove_collection("cid2"))
    assert not store.collection_exists("cid2")
    with pytest.raises(StoreError):
        store.collection_list("cid2")


def test_collection_move_rename(store):
    store.queue_transaction(Transaction().create_collection("dst"))
    store.queue_transaction(Transaction().write("cid", OID, 0, b"data"))
    new_oid = ObjectId("renamed")
    store.queue_transaction(
        Transaction().collection_move_rename("cid", OID, "dst", new_oid))
    assert not store.exists("cid", OID)
    assert store.read("dst", new_oid) == b"data"


def test_txn_atomicity_on_failure(store):
    """A failing op must leave NO effects from earlier ops in the txn."""
    store.queue_transaction(Transaction().write("cid", OID, 0, b"orig"))
    bad = (Transaction()
           .write("cid", OID, 0, b"new!")
           .touch("cid", ObjectId("side-effect"))
           .remove("cid", ObjectId("missing")))     # fails: ENOENT
    with pytest.raises(StoreError):
        store.queue_transaction(bad)
    assert store.read("cid", OID) == b"orig"
    assert not store.exists("cid", ObjectId("side-effect"))


def test_txn_order_within_txn(store):
    t = (Transaction()
         .write("cid", OID, 0, b"aaaa")
         .zero("cid", OID, 1, 2)
         .write("cid", OID, 2, b"Z"))
    store.queue_transaction(t)
    assert store.read("cid", OID) == b"a\0Za"


def test_collection_list_sorted(store):
    names = ["b", "a", "c"]
    t = Transaction()
    for n in names:
        t.touch("cid", ObjectId(n))
    store.queue_transaction(t)
    assert [o.name for o in store.collection_list("cid")] == ["a", "b", "c"]


def test_inject_read_err(store):
    store.queue_transaction(Transaction().write("cid", OID, 0, b"data"))
    store.inject_read_err("cid", OID)
    # gated by config
    cfg = global_config()
    old = cfg["objectstore_debug_inject_read_err"]
    try:
        cfg.set("objectstore_debug_inject_read_err", True)
        with pytest.raises(StoreError) as ei:
            store.read("cid", OID)
        assert ei.value.errno_name == "EIO"
        store.clear_read_err("cid", OID)
        assert store.read("cid", OID) == b"data"
    finally:
        cfg.set("objectstore_debug_inject_read_err", old)


def test_statfs(store):
    store.queue_transaction(Transaction().write("cid", OID, 0, b"x" * 100))
    fs = store.statfs()
    assert fs["used"] == 100
    assert fs["available"] == fs["total"] - 100
