"""Thrashers: randomized fault injection against a MiniCluster.

Port of the qa thrasher loops (ref: qa/tasks/ceph_manager.py:98
OSDThrasher: choose_action kill/revive/out/in with min-in guards,
interleaved with client IO, then heal and verify; qa/tasks/
mds_thrash.py MDSThrasher: kill active ranks under metadata load and
wait for the standby takeover ladder).  Deterministic: a seeded RNG
picks actions, the harness pumps the network and drives
heartbeat/mon ticks on simulated time.
"""
from __future__ import annotations

import random
import time as _time

from ..common.options import global_config
from .cluster import MiniCluster


class OSDThrasher:
    """`ec_pools` + `rados` arm the erasure-coded legs: chunk EIO
    injection (`objectstore_debug_inject_read_err` applied to EC
    shard reads — exercises the primary's remaining-shard retry and
    scrub's shard rebuild) joins the action mix, and min-guards should
    be sized so >= k shards of every stripe stay live (the caller
    knows its k+m)."""

    def __init__(self, cluster: MiniCluster, seed: int = 0,
                 min_in: int = 3, min_live: int = 3,
                 ec_pools=(), rados=None):
        self.c = cluster
        self.rng = random.Random(seed)
        self.min_in = min_in
        self.min_live = min_live
        self.all_osds = sorted(cluster.osds)
        self.dead: set[int] = set()
        self.out: set[int] = set()
        self.now = 10_000.0
        self.log: list[str] = []
        #: EC pool names eligible for shard-EIO injection
        self.ec_pools = list(ec_pools)
        self.r = rados
        #: live injections: (osd, cid, shard ObjectId)
        self.injected: list[tuple] = []
        #: objectstore_debug_inject_read_err value to restore after
        #: the EIO leg (None = we never flipped it)
        self._eio_flag_was: bool | None = None

    # ------------------------------------------------------------ state
    def _live(self) -> list[int]:
        return [o for o in self.all_osds if o not in self.dead]

    def _in(self) -> list[int]:
        return [o for o in self.all_osds if o not in self.out]

    def _tick_rounds(self, n: int = 3) -> None:
        """Advance simulated time in sub-grace steps so failure
        detection works the way production cadence does."""
        grace = global_config()["osd_heartbeat_grace"]
        for _ in range(n):
            self.now += grace / 2 + 1
            self.c.tick(self.now)

    # ---------------------------------------------------------- actions
    def kill_osd(self, osd: int | None = None) -> None:
        live = [o for o in self._live()]
        if len(live) <= self.min_live:
            return
        osd = osd if osd is not None else self.rng.choice(live)
        if osd in self.dead:
            return
        self.log.append(f"kill osd.{osd}")
        self.c.kill_osd(osd)
        self.dead.add(osd)
        self._tick_rounds()      # peers detect + mon marks down

    def revive_osd(self, osd: int | None = None) -> None:
        if not self.dead:
            return
        osd = osd if osd is not None else self.rng.choice(
            sorted(self.dead))
        self.log.append(f"revive osd.{osd}")
        self.c.revive_osd(osd)
        self.dead.discard(osd)
        if not self.c.threaded:
            self.c.pump()
        self._tick_rounds(1)

    def out_osd(self, osd: int | None = None) -> None:
        candidates = [o for o in self._in()]
        if len(candidates) <= self.min_in:
            return
        osd = osd if osd is not None else self.rng.choice(candidates)
        self.log.append(f"out osd.{osd}")
        self.c.mon.handle_command({"prefix": "osd out", "ids": [osd]})
        self.out.add(osd)
        if not self.c.threaded:
            self.c.pump()

    def in_osd(self, osd: int | None = None) -> None:
        candidates = sorted(o for o in self.out if o not in self.dead)
        if not candidates:
            return
        osd = osd if osd is not None else self.rng.choice(candidates)
        self.log.append(f"in osd.{osd}")
        self.c.mon.handle_command({"prefix": "osd in", "ids": [osd]})
        self.out.discard(osd)
        if not self.c.threaded:
            self.c.pump()

    def inject_shard_eio(self) -> None:
        """Mark one random EC chunk on a live OSD to fail reads with
        EIO (the ceph_manager inject_* analogue for shard read
        errors).  The victim shard's chunk read then errors through
        ECPGShard.handle_sub_read and the reading primary must
        reconstruct from the remaining shards."""
        if not self.ec_pools or self.r is None:
            return
        # the store only honors EIO marks while the dev flag is set —
        # an injection without it would be a silent no-op and the
        # thrash run would claim EIO coverage it never exercised
        cfg = global_config()
        if not cfg["objectstore_debug_inject_read_err"]:
            if self._eio_flag_was is None:
                self._eio_flag_was = False
            cfg.set("objectstore_debug_inject_read_err", True)
        from ..osd.ec_backend import ECPGShard, pg_cid
        from ..store import ObjectId
        pid = self.r.pool_lookup(self.rng.choice(self.ec_pools))
        live = list(self._live())
        self.rng.shuffle(live)
        for osd in live:
            d = self.c.osds.get(osd)
            if d is None:
                continue
            cands = [(pg, st) for pg, st in sorted(d.pgs.items())
                     if pg.pool == pid and
                     isinstance(st.shard, ECPGShard)]
            self.rng.shuffle(cands)
            for pg, st in cands:
                oids = st.shard.objects()
                if not oids:
                    continue
                oid = self.rng.choice(sorted(oids))
                st.shard.inject_read_err(oid)
                self.injected.append(
                    (osd, pg_cid(pg),
                     ObjectId(oid, shard=st.shard.shard)))
                self.log.append(f"eio osd.{osd} {pg} {oid}")
                return

    def clear_shard_eio(self) -> None:
        """Lift every live injection (stores survive kill/revive, so
        the exact marked ObjectIds clear even after remaps), and
        restore the dev flag if the thrasher flipped it."""
        while self.injected:
            osd, cid, soid = self.injected.pop()
            d = self.c.osds.get(osd)
            store = d.store if d is not None \
                else self.c._stores.get(osd)
            if store is not None:
                store.clear_read_err(cid, soid)
        if self._eio_flag_was is not None:
            global_config().set("objectstore_debug_inject_read_err",
                                self._eio_flag_was)
            self._eio_flag_was = None

    ACTIONS = ("kill_osd", "revive_osd", "out_osd", "in_osd",
               "inject_shard_eio", "clear_shard_eio")

    def choose_action(self) -> str:
        """(ref: ceph_manager.py choose_action weights)."""
        weights = {"kill_osd": 3, "revive_osd": 3,
                   "out_osd": 2, "in_osd": 2}
        if self.ec_pools:
            weights["inject_shard_eio"] = 1
            weights["clear_shard_eio"] = 1
        names = list(weights)
        return self.rng.choices(names,
                                weights=[weights[n] for n in names])[0]

    def do_thrash(self, rounds: int, between=None) -> None:
        """`between(i)` runs client IO between actions."""
        for i in range(rounds):
            getattr(self, self.choose_action())()
            if between is not None:
                between(i)

    # ------------------------------------------------------------- heal
    def heal(self, timeout_rounds: int = 50) -> None:
        """Revive + mark in everything, lift EIO injections, wait
        until no PG is recovering (ref: thrasher's final
        do_join/wait_for_clean)."""
        self.clear_shard_eio()
        for osd in sorted(self.dead):
            self.revive_osd(osd)
        for osd in sorted(self.out):
            self.in_osd(osd)
        import time
        for _ in range(timeout_rounds):
            if self.c.threaded:
                time.sleep(0.02)   # let messenger threads drain
            else:
                self.c.pump()
            if all(d.pgs_recovering() == 0
                   for d in self.c.osds.values()):
                return
            self._tick_rounds(1)   # unwedge map-waiting recoveries
        raise TimeoutError(
            f"cluster never went clean; log: {self.log}")


class MDSThrasher:
    """Kill/revive MDS ranks under live metadata load (ref:
    qa/tasks/mds_thrash.py MDSThrasher): each round hard-kills an
    active rank, backfills the standby pool, drives mon ticks past
    ``mds_beacon_grace`` on simulated time until the monitor promotes
    a standby through replay to active, and verifies clients keep
    serving.  Requires a threaded MiniCluster with beaconing MDS
    daemons (cluster.start_mds / start_mds_standby)."""

    def __init__(self, cluster: MiniCluster, seed: int = 0,
                 now: float = 50_000.0):
        self.c = cluster
        self.rng = random.Random(seed)
        # beacons sent BEFORE the first simulated tick are stamped
        # with the mon's real clock (time.monotonic() = host uptime);
        # a sim seed behind that runs mon time backward, so a dead
        # gid's last stamp stays "fresh" forever and failover never
        # fires (bit at host uptime > 50000s).  Only forward jumps
        # are safe: seed at whichever clock is further along.
        self.now = max(now, _time.monotonic() + 1.0)
        self.log: list[str] = []

    def _active_ranks(self) -> list[int]:
        return [r for r, i in self.c.fsmap().ranks.items()
                if i.state == "active"]

    def tick_grace(self, rounds: int = 3) -> None:
        """Advance simulated time past the beacon grace in sub-grace
        steps with real sleeps between jumps: live daemons' beacons
        (stamped with the mon's sim clock) land inside every window,
        so only genuinely dead gids fall past the grace — the OSD
        thrasher's grace/2 cadence applied to beacons."""
        grace = global_config()["mds_beacon_grace"]
        interval = global_config()["mds_beacon_interval"]
        for _ in range(rounds):
            self.now += grace / 2 + 0.1
            self.c.tick(self.now)
            _time.sleep(max(0.05, 2 * interval))
            self.c.tick(self.now)

    def kill_rank(self, rank: int | None = None) -> int:
        active = self._active_ranks()
        if not active:
            raise RuntimeError("no active rank to kill")
        rank = rank if rank is not None else self.rng.choice(active)
        self.log.append(f"kill mds.{rank}")
        self._killed_gid = self.c.fsmap().ranks[rank].gid
        self.c.adopt_promoted()
        self.c.kill_mds(rank)
        return rank

    def backfill_standby(self) -> None:
        self.log.append("add standby")
        self.c.start_mds_standby()

    def wait_takeover(self, rank: int, timeout_rounds: int = 40,
                      old_gid: int | None = None) -> None:
        """Drive ticks until the rank is active under a NEW gid (the
        dead holder's entry stays `active` until its beacon lapses,
        so plain active-ness is not takeover)."""
        if old_gid is None:
            old_gid = getattr(self, "_killed_gid", None)
        interval = global_config()["mds_beacon_interval"]
        for _ in range(timeout_rounds):
            info = self.c.fsmap().ranks.get(rank)
            if info is not None and info.state == "active" and \
                    (old_gid is None or info.gid != old_gid):
                self.c.adopt_promoted()
                return
            self.tick_grace(1)
            _time.sleep(max(0.05, interval))
        raise TimeoutError(
            f"mds.{rank} takeover never completed; log: {self.log}")

    def do_thrash(self, rounds: int, between=None) -> None:
        """`between(i)` runs client metadata IO between kills."""
        for i in range(rounds):
            if not self.c.standbys:
                self.backfill_standby()
                _time.sleep(2 * global_config()
                            ["mds_beacon_interval"])
            rank = self.kill_rank()
            self.wait_takeover(rank)
            if between is not None:
                between(i)
