"""EC coding over a 2-D (stripe, shard) device mesh.

Layout: data (S, k, N) placed with PartitionSpec('stripe', 'shard',
None) — each device holds a slice of the stripe batch and a subset of
the k data chunks (the device-resident analogue of chunk shards living
on k different OSDs).  Coding runs as one `shard_map` step per batch:

  * each device lifts its local chunk subset to GF(2) bit-planes and
    multiplies by its column slice of the companion matrix (partial
    bit-counts, MXU work, no communication);
  * a `psum` over the 'shard' axis XORs the partials (mod-2 of the
    summed counts) — this collective IS the reference's per-shard
    write fan-out (ref: src/osd/ECBackend.cc:2037-2070), riding ICI
    instead of the messenger;
  * the packed parity lands stripe-sharded, replicated over 'shard',
    ready for per-device placement.

Decode is the same structure with the erasure-specific decode matrix
over survivor chunks (ref: ECBackend.cc:1590 min-avail shard read +
reconstruct).
"""
from __future__ import annotations

import numpy as np

from ..ec import gf
from ..ec.matrix_code import make_decode_matrix


def make_mesh(n_devices: int | None = None, shard_ways: int | None = None,
              k: int = 8):
    """(stripe, shard) mesh over the first n devices; shard_ways must
    divide both the device count and k (chunk subsets stay equal)."""
    import jax
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n <= 0:
        raise ValueError(f"n_devices must be positive, got {n}")
    if n > len(devs):
        raise ValueError(f"{n} devices requested, {len(devs)} present")
    if shard_ways is None:
        shard_ways = next(c for c in (4, 2, 1)
                          if n % c == 0 and k % c == 0)
    if n % shard_ways or k % shard_ways:
        raise ValueError(
            f"shard_ways={shard_ways} must divide n={n} and k={k}")
    from jax.sharding import Mesh
    return Mesh(np.asarray(devs[:n]).reshape(n // shard_ways,
                                             shard_ways),
                ("stripe", "shard"))


class MeshECCoder:
    """Sharded encode/decode for one (k, m) code on one mesh."""

    def __init__(self, k: int, m: int, mesh,
                 encode_matrix: np.ndarray | None = None):
        import jax.numpy as jnp
        self.k = k
        self.m = m
        self.mesh = mesh
        self.shard_ways = mesh.devices.shape[1]
        if k % self.shard_ways:
            raise ValueError("k must divide over the shard axis")
        if encode_matrix is None:
            encode_matrix = gf.isa_rs_matrix(k, m)
        self.encode_matrix = np.ascontiguousarray(encode_matrix,
                                                  dtype=np.uint8)
        self._enc_bits = jnp.asarray(gf.expand_to_bitmatrix(
            self.encode_matrix[k:]).astype(np.int8))      # (8m, 8k)
        # one jitted shard_map step serves every matrix: jit re-traces
        # per argument shape and caches internally, so all erasure
        # patterns of one geometry share a single compilation
        self._step = None
        self._dec_bits: dict[str, object] = {}

    # ------------------------------------------------------- placement
    def shard_data(self, data_np: np.ndarray):
        """Host (S, k, N) -> device array sharded (stripe, shard)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(
            data_np, NamedSharding(self.mesh, P("stripe", "shard", None)))

    # ---------------------------------------------------------- encode
    def _coder(self):
        if self._step is None:
            self._step = self._build_coder()
        return self._step

    def _build_coder(self):
        """shard_map step: local partial bit-counts + psum('shard')."""
        import jax
        import jax.numpy as jnp
        try:
            from jax import shard_map          # jax >= 0.8
        except ImportError:                    # pragma: no cover
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def local_step(B_local, data_local):
            # data_local: (S/stripe_ways, k/shard_ways, N)
            s, kl, n = data_local.shape
            shifts = jnp.arange(8, dtype=jnp.uint8)
            bits = ((data_local[:, :, None, :] >>
                     shifts[None, None, :, None]) & 1)
            bits = bits.reshape(s, 8 * kl, n).astype(jnp.int8)
            partial = jnp.einsum("ij,sjn->sin", B_local, bits,
                                 preferred_element_type=jnp.int32)
            total = jax.lax.psum(partial, "shard")   # XOR via mod-2
            bits_out = total & 1                     # (s, 8r, n)
            r = bits_out.shape[1] // 8
            weights = (1 << jnp.arange(8, dtype=jnp.int32))
            planes = bits_out.reshape(s, r, 8, n) * \
                weights[None, None, :, None]
            return planes.sum(axis=2).astype(jnp.uint8)

        return jax.jit(shard_map(
            local_step, mesh=self.mesh,
            in_specs=(P(None, "shard"), P("stripe", "shard", None)),
            out_specs=P("stripe", None, None)))

    def encode(self, data):
        """data (S, k, N) sharded (stripe, shard) -> parity (S, m, N)
        sharded (stripe), one collective step."""
        return self._coder()(self._enc_bits, data)

    # ---------------------------------------------------------- decode
    def decode(self, decode_index: list[int], erasures: list[int],
               survivors):
        """survivors (S, k, N) — chunks `decode_index` in order,
        sharded (stripe, shard) -> reconstructed erasures (S, e, N)."""
        import jax.numpy as jnp
        sig = f"{tuple(decode_index)}-{tuple(erasures)}"
        bits = self._dec_bits.get(sig)
        if bits is None:
            dmat = make_decode_matrix(self.encode_matrix, self.k,
                                      list(decode_index), list(erasures))
            bits = jnp.asarray(
                gf.expand_to_bitmatrix(dmat).astype(np.int8))
            self._dec_bits[sig] = bits
        return self._coder()(bits, survivors)

    # ------------------------------------------------------ validation
    def check_parity(self, data_np: np.ndarray, parity) -> bool:
        """Full-batch oracle comparison (per-stripe, so stripe-axis
        placement bugs can't hide behind a correct stripe 0)."""
        got = np.asarray(parity)
        for i in range(data_np.shape[0]):
            want = gf.gf_matmul_bytes(self.encode_matrix[self.k:],
                                      data_np[i])
            if not np.array_equal(got[i], want):
                return False
        return True
