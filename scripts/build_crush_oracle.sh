#!/bin/sh
# Build the reference CRUSH C core as a shared library for fixture
# generation (scripts/gen_crush_fixtures.py).  Reads the read-only
# reference tree; writes only to /tmp.
set -e
REF=${REF:-/root/reference}
OUT=/tmp/crush_oracle
mkdir -p "$OUT"
: > "$OUT/acconfig.h"   # reference headers include it; empty stub suffices
gcc -O2 -shared -fPIC \
    -I"$OUT" -I"$REF/src" -I"$REF/src/crush" \
    "$(dirname "$0")/crush_oracle_shim.c" \
    "$REF/src/crush/builder.c" \
    "$REF/src/crush/mapper.c" \
    "$REF/src/crush/crush.c" \
    "$REF/src/crush/hash.c" \
    -o "$OUT/libcrush_oracle.so" -lm
echo "built $OUT/libcrush_oracle.so"
