"""RGW versioning + lifecycle + presigned URLs (VERDICT r3 #5; ref:
rgw versioned buckets, src/rgw/rgw_lc.cc, src/rgw/rgw_auth_s3.h
query-string auth)."""
import time
import urllib.error
import urllib.request
from xml.etree import ElementTree as ET

import pytest

from ceph_tpu.auth import KeyRing
from ceph_tpu.rgw import RGWGateway
from ceph_tpu.rgw.auth import presign, sign_request
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def gw(cluster):
    g = RGWGateway(cluster.rados(), pool="rgwv")
    g.start()
    yield g
    g.shutdown()


def req(gw, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{gw.port}{path}",
                               data=data, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


VERS_ON = (b'<VersioningConfiguration>'
           b'<Status>Enabled</Status></VersioningConfiguration>')
VERS_OFF = (b'<VersioningConfiguration>'
            b'<Status>Suspended</Status></VersioningConfiguration>')


def test_versioned_put_get_delete_roundtrip(gw):
    req(gw, "PUT", "/vb")
    req(gw, "PUT", "/vb?versioning", VERS_ON)
    st, _, body = req(gw, "GET", "/vb?versioning")
    assert b"<Status>Enabled</Status>" in body
    # three generations of one key
    vids = []
    for gen in (b"gen-one", b"gen-two", b"gen-three"):
        st, hdrs, _ = req(gw, "PUT", "/vb/doc", gen)
        assert st == 200
        vids.append(hdrs["x-amz-version-id"])
    assert len(set(vids)) == 3
    # plain GET serves the newest; versionId selects any generation
    assert req(gw, "GET", "/vb/doc")[2] == b"gen-three"
    assert req(gw, "GET", f"/vb/doc?versionId={vids[0]}")[2] == \
        b"gen-one"
    assert req(gw, "GET", f"/vb/doc?versionId={vids[1]}")[2] == \
        b"gen-two"
    # DELETE inserts a delete marker: key vanishes from reads/lists
    st, hdrs, _ = req(gw, "DELETE", "/vb/doc")
    assert st == 204 and hdrs.get("x-amz-delete-marker") == "true"
    marker_vid = hdrs["x-amz-version-id"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", "/vb/doc")
    assert ei.value.code == 404
    st, _, body = req(gw, "GET", "/vb")
    assert b"<Key>doc</Key>" not in body
    # old generations still read by versionId
    assert req(gw, "GET", f"/vb/doc?versionId={vids[2]}")[2] == \
        b"gen-three"
    # ListObjectVersions shows the whole stack incl. the marker
    st, _, body = req(gw, "GET", "/vb?versions")
    root = ET.fromstring(body)
    vers = [e for e in root.iter() if e.tag == "Version"]
    marks = [e for e in root.iter() if e.tag == "DeleteMarker"]
    assert len(vers) == 3 and len(marks) == 1
    # deleting the marker by versionId resurrects the key
    assert req(gw, "DELETE",
               f"/vb/doc?versionId={marker_vid}")[0] == 204
    assert req(gw, "GET", "/vb/doc")[2] == b"gen-three"
    # deleting a specific data version removes just that one
    assert req(gw, "DELETE", f"/vb/doc?versionId={vids[2]}")[0] == 204
    assert req(gw, "GET", "/vb/doc")[2] == b"gen-two"


def test_suspended_versioning_null_version(gw):
    req(gw, "PUT", "/sb")
    req(gw, "PUT", "/sb?versioning", VERS_ON)
    req(gw, "PUT", "/sb/k", b"versioned-era")
    req(gw, "PUT", "/sb?versioning", VERS_OFF)
    st, hdrs, _ = req(gw, "PUT", "/sb/k", b"null-era")
    assert hdrs["x-amz-version-id"] == "null"
    # overwrite replaces the null version, not stacking
    req(gw, "PUT", "/sb/k", b"null-era-2")
    st, _, body = req(gw, "GET", "/sb?versions")
    root = ET.fromstring(body)
    vids = [e.text for e in root.iter() if e.tag == "VersionId"]
    assert vids.count("null") == 1
    assert req(gw, "GET", "/sb/k")[2] == b"null-era-2"
    # the versioned-era generation is still addressable
    old = [v for v in vids if v != "null"]
    assert len(old) == 1
    assert req(gw, "GET", f"/sb/k?versionId={old[0]}")[2] == \
        b"versioned-era"


def test_lifecycle_config_and_expiration(gw):
    req(gw, "PUT", "/lcb")
    lc = (b'<LifecycleConfiguration><Rule><ID>exp</ID>'
          b'<Prefix>logs/</Prefix><Status>Enabled</Status>'
          b'<Expiration><Days>7</Days></Expiration>'
          b'</Rule></LifecycleConfiguration>')
    assert req(gw, "PUT", "/lcb?lifecycle", lc)[0] == 200
    st, _, body = req(gw, "GET", "/lcb?lifecycle")
    assert b"<Days>7</Days>" in body and b"logs/" in body
    req(gw, "PUT", "/lcb/logs/old.log", b"ancient")
    req(gw, "PUT", "/lcb/logs/new.log", b"fresh")
    req(gw, "PUT", "/lcb/data/keep.bin", b"outside prefix")
    # age the old object by rewriting its index mtime 8 days back
    ent = gw._index_entry("lcb", "logs/old.log")
    ent["mtime"] = time.strftime(
        "%Y-%m-%dT%H:%M:%S.000Z",
        time.gmtime(time.time() - 8 * 86400))
    import json
    from ceph_tpu.rgw.gateway import _index_obj, _shard_of
    gw.io.set_omap(_index_obj("lcb", _shard_of(
        "logs/old.log", gw._nshards("lcb"))),
        {"logs/old.log": json.dumps(ent).encode()})
    assert gw.lc_tick() == 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", "/lcb/logs/old.log")
    assert ei.value.code == 404
    assert req(gw, "GET", "/lcb/logs/new.log")[2] == b"fresh"
    assert req(gw, "GET", "/lcb/data/keep.bin")[2] == \
        b"outside prefix"
    # removing the config stops expiration
    assert req(gw, "DELETE", "/lcb?lifecycle")[0] == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", "/lcb?lifecycle")
    assert ei.value.code == 404


def test_lifecycle_versioned_and_noncurrent(gw):
    req(gw, "PUT", "/lcv")
    req(gw, "PUT", "/lcv?versioning", VERS_ON)
    lc = (b'<LifecycleConfiguration><Rule><ID>nc</ID>'
          b'<Prefix></Prefix><Status>Enabled</Status>'
          b'<Expiration><Days>10</Days></Expiration>'
          b'<NoncurrentVersionExpiration><NoncurrentDays>3'
          b'</NoncurrentDays></NoncurrentVersionExpiration>'
          b'</Rule></LifecycleConfiguration>')
    req(gw, "PUT", "/lcv?lifecycle", lc)
    req(gw, "PUT", "/lcv/f", b"v1")
    req(gw, "PUT", "/lcv/f", b"v2")
    # age everything 5 days: noncurrent v1 expires, current v2 stays
    ent = gw._index_entry("lcv", "f")
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                          time.gmtime(time.time() - 5 * 86400))
    for v in ent["versions"]:
        v["mtime"] = stamp
    gw._store_versions("lcv", "f", ent["versions"])
    assert gw.lc_tick() == 1
    st, _, body = req(gw, "GET", "/lcv?versions")
    vers = [e for e in ET.fromstring(body).iter()
            if e.tag == "Version"]
    assert len(vers) == 1
    assert req(gw, "GET", "/lcv/f")[2] == b"v2"
    # age current past 10 days: a delete marker appears
    ent = gw._index_entry("lcv", "f")
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                          time.gmtime(time.time() - 11 * 86400))
    for v in ent["versions"]:
        v["mtime"] = stamp
    gw._store_versions("lcv", "f", ent["versions"])
    assert gw.lc_tick() >= 1
    with pytest.raises(urllib.error.HTTPError):
        req(gw, "GET", "/lcv/f")
    st, _, body = req(gw, "GET", "/lcv?versions")
    assert b"<DeleteMarker>" in body


@pytest.fixture(scope="module")
def auth_gw(cluster):
    kr = KeyRing.generate(["client.s3"])
    g = RGWGateway(cluster.rados(), pool="rgwsig", keyring=kr)
    g.start()
    yield g, kr
    g.shutdown()


def _signed(gw, kr, method, path, data=b""):
    host = f"127.0.0.1:{gw.port}"
    hdrs = sign_request(method, path, {"host": host},
                        data or b"", "client.s3",
                        kr.get("client.s3"))
    return req(gw, method, path, data, hdrs)


def test_presigned_url_get(auth_gw):
    """boto3-style presigned GET accepted; expiry + tamper refused
    (ref: rgw_auth_s3.h query-string auth)."""
    gw, kr = auth_gw
    host = f"127.0.0.1:{gw.port}"
    assert _signed(gw, kr, "PUT", "/pre")[0] == 200
    assert _signed(gw, kr, "PUT", "/pre/obj",
                   b"presigned payload")[0] == 200
    # unauthenticated access is refused
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", "/pre/obj")
    assert ei.value.code == 403
    url = presign("GET", "/pre/obj", host, "client.s3",
                  kr.get("client.s3"), expires=120)
    st, _, body = req(gw, "GET", url)
    assert st == 200 and body == b"presigned payload"
    # tampered signature refused
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", url[:-4] + "beef")
    assert ei.value.code == 403
    # expired URL refused
    old = time.strftime("%Y%m%dT%H%M%SZ",
                        time.gmtime(time.time() - 3600))
    stale = presign("GET", "/pre/obj", host, "client.s3",
                    kr.get("client.s3"), expires=60, amz_date=old)
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", stale)
    assert ei.value.code == 403
    # presigned PUT works too
    purl = presign("PUT", "/pre/up", host, "client.s3",
                   kr.get("client.s3"))
    assert req(gw, "PUT", purl, b"uploaded via presign")[0] == 200
    gurl = presign("GET", "/pre/up", host, "client.s3",
                   kr.get("client.s3"))
    assert req(gw, "GET", gurl)[2] == b"uploaded via presign"
