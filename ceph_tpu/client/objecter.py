"""Objecter: object op submission with target calculation and resend.

The client-side engine (ref: src/osdc/Objecter.{h,cc}): each op's
target PG and primary OSD are computed from the client's osdmap
(_calc_target :1095), ops are tagged with tids and sent to the primary
(_op_submit :2378, _send_op), and every map epoch or connection reset
triggers a rescan — ops whose target changed (or that were parked
homeless for lack of a primary) are resent (_scan_requests,
handle_osd_map :1182).  The mon subscription keeps the map fresh.
"""
from __future__ import annotations

import itertools
import threading

from ..common.lockdep import make_lock
from typing import Optional

from ..common.log import dout
from ..common.options import global_config
from ..msg.messages import (MAuthReply, MGR_UNAVAILABLE_EAGAIN, MMap,
                            MMonCommand, MMonCommandAck,
                            MMonSubscribe, MWatchNotify, OSDOp,
                            OSDOpReply)
from ..msg.mon_client import MonHunter
from ..msg.messenger import Dispatcher, LocalNetwork, Message, Messenger
from ..osd.osdmap import OSDMap
from ..osd.types import PG

_client_ids = itertools.count(4100)


class OpFuture:
    """Completion handle for one op."""

    def __init__(self):
        self._ev = threading.Event()
        self.result: int = 0
        self.errno_name: str = ""
        self.data: bytes = b""
        self.attrs: dict = {}

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: float = 30.0) -> "OpFuture":
        if not self._ev.wait(timeout):
            raise TimeoutError("op timed out")
        return self

    def _complete(self, reply: OSDOpReply) -> None:
        self.result = reply.result
        self.errno_name = reply.errno_name
        self.data = reply.data
        self.attrs = reply.attrs
        self._ev.set()


class _Op:
    def __init__(self, tid: int, pool: int, oid: str, op: str,
                 offset: int, length: int, data: bytes,
                 future: OpFuture, pg_ps: Optional[int] = None,
                 args: Optional[dict] = None,
                 unordered: bool = False):
        self.tid = tid
        self.pool = pool
        self.oid = oid
        self.op = op
        self.offset = offset
        self.length = length
        self.data = data
        self.args = args or {}
        self.future = future
        self.unordered = unordered
        self.pg_ps = pg_ps        # PG-addressed op (pgls)
        self.pg: Optional[PG] = None
        self.target_osd = -1
        self.attempts = 0
        self.trace: Optional[dict] = None
        self.span = None          # the client-side span (trace root
        # unless a frontend scoped an ambient parent)
        self.parent_ctx: Optional[dict] = None


class Objecter(Dispatcher, MonHunter):
    """(ref: src/osdc/Objecter.h:1204)."""

    def __init__(self, network: LocalNetwork, name: str | None = None,
                 mon="mon.0", threaded: bool = True,
                 auth_secret: str | None = None):
        self.name = name or f"client.{next(_client_ids)}"
        # cephx: clients do the wire handshake (they hold only their
        # own secret); until the mon's ticket arrives nothing but the
        # MAuthRequest goes out (ref: MonClient::authenticate)
        self._cephx = None
        self.auth_error: str | None = None
        if auth_secret is not None:
            from ..auth import CephxClient
            self._cephx = CephxClient(self.name, auth_secret)
        self._init_mons(mon)
        self.osdmap = OSDMap()
        self._map_ev = threading.Event()
        self._lock = make_lock(f"objecter.{self.name}")
        self._tid = itertools.count(1)
        self.in_flight: dict[int, _Op] = {}
        self.homeless: list[_Op] = []
        # per-object op ordering (librados semantics: one client's ops
        # on one object complete in submission order — without this a
        # parked-then-retried older write can land AFTER a newer acked
        # write and silently win)
        self._obj_active: dict[tuple, int] = {}   # (pool, oid) -> tid
        self._obj_wait: dict[tuple, list] = {}
        # linger state: cookie -> watch registration
        # (ref: Objecter::LingerOp — watches re-register when the
        # object's primary moves)
        self.watches: dict[str, dict] = {}
        self._rescan_timer = None
        self._pending_cmds: dict = {}
        #: non-threaded harnesses set this to a network pump callable;
        #: synchronous waits then drive the cluster instead of blocking
        self.pump_hook = None
        # client-side span sink: the objecter roots (or, under an
        # ambient frontend scope, parents) one span per traced op, so
        # an assembled trace shows the submit->reply client leg too
        # (ref: the Objecter's op trace in src/osdc/Objecter.cc)
        from ..common.tracing import Tracer
        self.tracer = Tracer(self.name)
        self.ms = Messenger.create(network, self.name, threaded=threaded)
        self.ms.add_dispatcher(self)

    # ------------------------------------------------------------ setup
    def start(self) -> None:
        self.ms.start()
        if self._cephx is not None and not self._cephx.authenticated:
            self.ms.connect(self.mon).send_message(
                self._cephx.build_request())
            return        # subscription follows the MAuthReply
        self.ms.connect(self.mon).send_message(
            MMonSubscribe(what="osdmap", start=1))

    def shutdown(self) -> None:
        self.ms.shutdown()

    def wait_sync(self, done, timeout: float, ev=None) -> bool:
        """Wait for `done()` — blocking on `ev` (default: the map
        event) in threaded mode, pumping the harness network
        otherwise.  Call sites need no threaded-vs-pump branching."""
        import time
        ev = ev or self._map_ev
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            if done():
                return True
            if self.pump_hook is not None:
                self.pump_hook()
                if not done():
                    time.sleep(0.001)   # idle round: don't spin hot
            else:
                ev.wait(min(0.5, max(0.0, end - time.monotonic())))
                if ev is self._map_ev:
                    ev.clear()
        return done()

    def wait_for_map(self, epoch: int = 1, timeout: float = 30.0) -> None:
        if not self.wait_sync(lambda: self.osdmap.epoch >= epoch or
                              self.auth_error is not None, timeout):
            raise TimeoutError(
                f"no osdmap >= e{epoch} (have e{self.osdmap.epoch})")
        if self.auth_error is not None and self.osdmap.epoch < epoch:
            raise PermissionError(f"cephx: {self.auth_error}")

    # --------------------------------------------------------- dispatch
    def ms_dispatch(self, msg: Message) -> bool:
        if isinstance(msg, MAuthReply):
            if self._cephx is None:
                return True
            if self._cephx.ingest_reply(msg):
                self.ms.auth_signer = self._cephx
                # ticket renewal before expiry, fired from sign() so
                # every traffic pattern renews — data ops, mds
                # sessions, mon commands alike
                # (ref: MonClient::_check_auth_rotating)
                self._cephx.renew_hook = self._send_auth_renewal
                # initial auth subscribes from scratch; a ticket
                # renewal reply only needs maps we don't have yet
                self.ms.connect(self.mon).send_message(
                    MMonSubscribe(what="osdmap",
                                  start=self.osdmap.epoch + 1))
            else:
                self.auth_error = msg.errstr or "authentication failed"
                self._map_ev.set()       # unblock connect() waiters
            return True
        if isinstance(msg, MMap):
            self._handle_map(msg)
            return True
        if isinstance(msg, OSDOpReply):
            self._handle_reply(msg)
            return True
        if isinstance(msg, MWatchNotify):
            return self._handle_watch_notify(msg)
        if isinstance(msg, MMonCommandAck):
            return self._handle_command_ack(msg)
        return False

    def _send_auth_renewal(self) -> None:
        """Re-run the MAuthRequest handshake (called off-thread by the
        signer's renewal hook)."""
        if self._cephx is not None:
            self.ms.connect(self.mon).send_message(
                self._cephx.build_request())

    def _hunt_greeting(self) -> list:
        if self._cephx is not None and not self._cephx.authenticated:
            # a mon failover mid-handshake: re-authenticate at the new
            # mon first — an unsigned subscription would be dropped
            return [self._cephx.build_request()]
        return [MMonSubscribe(what="osdmap",
                              start=self.osdmap.epoch + 1)]

    def ms_handle_reset(self, peer: str) -> None:
        """Retarget ops aimed at a gone peer (ref:
        Objecter::ms_handle_reset :4487).  Never blindly resend to the
        same peer — route() reports the reset synchronously, so a
        resend to a dead endpoint would recurse; ops whose recalculated
        target is unchanged park homeless until a newer map (or the
        rescan timer) moves them.  A gone mon triggers the shared
        MonHunter walk."""
        if self._maybe_hunt(peer):
            return
        if not peer.startswith("osd."):
            return
        osd = int(peer[4:])
        with self._lock:
            # a reset peer lost its in-memory watch state even if it
            # comes back as the same primary: force re-registration
            for w in self.watches.values():
                if w.get("osd") == osd:
                    w["osd"] = None
            for op in list(self.in_flight.values()):
                if op.target_osd != osd:
                    continue
                self._calc_target(op)
                if op.target_osd == osd or op.target_osd < 0:
                    del self.in_flight[op.tid]
                    self.homeless.append(op)
                else:
                    self._send_op(op)
            if self.homeless:
                self._schedule_rescan()

    # --------------------------------------------------------- map flow
    def _handle_map(self, msg: MMap) -> None:
        with self._lock:
            self.osdmap = self.osdmap.ingest(msg.full_map,
                                             msg.incrementals)
            dout("client", 10).write("%s: osdmap e%d", self.name,
                                     self.osdmap.epoch)
            self._scan_requests()
        self._map_ev.set()

    def _scan_requests(self) -> None:
        """Recompute targets; resend what moved; adopt the homeless
        (ref: Objecter.cc:1182 handle_osd_map -> _scan_requests).

        The homeless list is swapped out BEFORE the drain: a resend
        whose target is gone fails synchronously through
        ms_handle_reset, which re-parks the op onto self.homeless — if
        the drain iterated self.homeless directly it would pick the op
        straight back up and spin forever (resend -> reset -> re-park
        -> resend ...) while holding the lock, livelocking every other
        thread.  Parked ops wait for the rescan timer instead."""
        for op in list(self.in_flight.values()):
            old = op.target_osd
            self._calc_target(op)
            if op.target_osd != old:
                if op.target_osd < 0:
                    del self.in_flight[op.tid]
                    self.homeless.append(op)
                else:
                    self._send_op(op)
        pending, self.homeless = self.homeless, []
        for op in pending:
            if op.pool not in self.osdmap.pools:
                # pool deleted while the op was parked
                self._complete_op(op, OSDOpReply(
                    tid=op.tid, result=-2, errno_name="ENOENT"))
                continue
            self._calc_target(op)
            if op.target_osd >= 0:
                self.in_flight[op.tid] = op
                self._send_op(op)
            else:
                self.homeless.append(op)
        self._relinger()

    # ------------------------------------------------------ target calc
    def _calc_target(self, op: _Op) -> None:
        """(ref: Objecter.cc:1095 _calc_target)."""
        try:
            if op.pg_ps is not None:
                raw = PG(op.pool, op.pg_ps)
                if op.pool not in self.osdmap.pools:
                    raise KeyError(op.pool)
            else:
                raw = self.osdmap.object_locator_to_pg(op.oid, op.pool)
        except KeyError:
            op.pg, op.target_osd = None, -1
            return
        pool = self.osdmap.pools[op.pool]
        op.pg = pool.raw_pg_to_pg(raw)
        _, _, _, acting_primary = self.osdmap.pg_to_up_acting_osds(raw)
        op.target_osd = acting_primary if acting_primary >= 0 and \
            self.osdmap.is_up(acting_primary) else -1

    # -------------------------------------------------------- op submit
    def submit(self, pool: int, oid: str, op: str, offset: int = 0,
               length: int = 0, data: bytes = b"",
               pg_ps: Optional[int] = None,
               args: Optional[dict] = None,
               unordered: bool = False) -> OpFuture:
        """(ref: Objecter.cc:2378 _op_submit).

        `unordered=True` opts the op out of per-object ordering (the
        librados semantics preserved by _obj_key): N such ops on one
        object all go to the wire at once instead of serializing
        behind each other.  Only safe for reads of objects the caller
        knows are immutable while the ops are in flight — the serve
        page-fetch wave (epoch-versioned artifact objects) is the
        intended user."""
        fut = OpFuture()
        o = _Op(next(self._tid), pool, oid, op, offset, length, data,
                fut, pg_ps=pg_ps, args=args, unordered=unordered)
        # capture the frontend's ambient trace NOW: a queued op may
        # launch later from the dispatch thread, where the submitting
        # handler's scope is gone
        from ..common.tracing import current_trace
        o.parent_ctx = current_trace()
        with self._lock:
            if self.osdmap.epoch > 0 and pool not in self.osdmap.pools:
                # pool does not exist in the current map: fail fast
                # instead of parking forever (ref: Objecter
                # _check_op_pool_dne)
                fut._complete(OSDOpReply(tid=o.tid, result=-2,
                                         errno_name="ENOENT"))
                return fut
            key = self._obj_key(o)
            if key is not None and key in self._obj_active:
                # an earlier op on this object is still outstanding:
                # hold ours back so completions stay in order
                self._obj_wait.setdefault(key, []).append(o)
                return fut
            if key is not None:
                self._obj_active[key] = o.tid
            self._launch(o)
        return fut

    #: ops exempt from per-object ordering: a notify_ack must never
    #: queue behind the notify op that is waiting for it (self-notify
    #: would deadlock until timeout), and watch re-registrations must
    #: not park behind in-flight writes
    _UNORDERED_OPS = frozenset({"notify_ack", "watch"})

    @classmethod
    def _obj_key(cls, op: _Op):
        if op.op in cls._UNORDERED_OPS or op.unordered:
            return None
        return (op.pool, op.oid) if op.oid else None

    def _launch(self, o: _Op) -> None:
        self._calc_target(o)
        if o.target_osd < 0:
            self.homeless.append(o)
        else:
            self.in_flight[o.tid] = o
            self._send_op(o)

    def _complete_op(self, op: _Op, reply: OSDOpReply) -> None:
        """Complete + release the object's next queued op (lock held).
        Drains with a loop: a recursive single step strands waiters
        behind an op that completes without ever becoming active
        (e.g. ENOENT on a deleted pool)."""
        if op.span is not None:
            op.span.event("reply" if reply.result == 0
                          else f"error:{reply.errno_name}")
            self.tracer.finish(op.span)
            op.span = None
        op.future._complete(reply)
        key = self._obj_key(op)
        if key is None or self._obj_active.get(key) != op.tid:
            return
        del self._obj_active[key]
        q = self._obj_wait.get(key, [])
        while q:
            nxt = q.pop(0)
            if self.osdmap.epoch > 0 and \
                    nxt.pool not in self.osdmap.pools:
                nxt.future._complete(OSDOpReply(
                    tid=nxt.tid, result=-2, errno_name="ENOENT"))
                continue
            self._obj_active[key] = nxt.tid
            self._launch(nxt)
            break
        if not q:
            self._obj_wait.pop(key, None)

    def _send_op(self, op: _Op) -> None:
        op.attempts += 1
        args = op.args
        pool = self.osdmap.pools.get(op.pool)
        if pool is not None and getattr(pool, "snap_seq", 0) \
                and "snapc" not in args:
            # every op carries the client's SnapContext so the primary
            # COWs against the snapshot the CLIENT saw, even when the
            # OSD's map lags (ref: MOSDOp carries snapc; Objecter
            # fills it from the pool in _op_submit).  An explicit
            # snapc (self-managed snaps: the IoCtx's write context)
            # always wins — the pool map knows nothing about
            # self-managed snapids.
            args = dict(args)
            args["snapc"] = {"seq": pool.snap_seq,
                             "snaps": sorted(pool.snaps)}
        if op.trace is None and global_config()["blkin_trace_all"]:
            from ..common.tracing import child_of, new_trace
            parent = op.parent_ctx
            # root a fresh trace, or continue the frontend's (RGW/MDS
            # request handlers scope theirs ambient) — either way the
            # objecter leg gets its OWN span and the wire carries a
            # child context, so resend attempts each show up as
            # distinct OSD spans under this one
            op.trace = child_of(parent) if parent else new_trace()
            op.span = self.tracer.start_span(
                op.trace, f"objecter_op:{op.op}")
            op.span.event(f"oid={op.oid}")
        if op.span is not None:
            op.span.event(
                f"send attempt={op.attempts} osd.{op.target_osd}")
        from ..common.tracing import child_of as _child_of
        self.ms.connect(f"osd.{op.target_osd}").send_message(OSDOp(
            pgid=op.pg, oid=op.oid, op=op.op, tid=op.tid,
            epoch=self.osdmap.epoch, offset=op.offset,
            length=op.length, data=op.data, args=args,
            trace=_child_of(op.trace)))

    # ---------------------------------------------------- watch/notify
    # (ref: Objecter linger ops + librados watch/notify API)
    def watch_register(self, pool: int, oid: str, cookie: str,
                       cb) -> OpFuture:
        with self._lock:
            self.watches[cookie] = {"pool": pool, "oid": oid,
                                    "cb": cb, "osd": None}
        return self.submit(pool, oid, "watch",
                           args={"cookie": cookie, "action": "watch"})

    def watch_unregister(self, pool: int, oid: str,
                         cookie: str) -> OpFuture:
        with self._lock:
            self.watches.pop(cookie, None)
        return self.submit(pool, oid, "watch",
                           args={"cookie": cookie, "action": "unwatch"})

    def _handle_watch_notify(self, msg: MWatchNotify) -> bool:
        with self._lock:
            w = self.watches.get(msg.cookie)
        if w is None:
            return True
        try:
            reply = w["cb"](msg.notify_id, msg.notifier, msg.payload)
        except Exception:
            dout("client", 0).write("%s: watch callback error on %s",
                                    self.name, msg.oid)
            reply = None
        self.submit(w["pool"], msg.oid, "notify_ack",
                    args={"notify_id": msg.notify_id,
                          "cookie": msg.cookie, "reply": reply})
        return True

    def _relinger(self) -> None:
        """Re-register watches whose primary moved (lock held) — the
        new primary has no in-memory Watch state, so the client
        re-establishes it like the reference's linger resend
        (Objecter::_linger_submit on map change)."""
        for cookie, w in list(self.watches.items()):
            try:
                raw = self.osdmap.object_locator_to_pg(w["oid"],
                                                       w["pool"])
                _, _, _, primary = self.osdmap.pg_to_up_acting_osds(raw)
            except KeyError:
                continue
            if primary < 0 or not self.osdmap.is_up(primary):
                # no live primary: whoever comes back (even the same
                # OSD, restarted with empty watch state) must get a
                # fresh registration
                w["osd"] = None
            elif primary != w.get("osd"):
                self.submit(w["pool"], w["oid"], "watch",
                            args={"cookie": cookie, "action": "watch"})

    def _handle_reply(self, msg: OSDOpReply) -> None:
        with self._lock:
            op = self.in_flight.get(msg.tid)
            if op is None:
                return
            if msg.errno_name == "ESTALE":
                # target wasn't primary (it may simply be behind on
                # maps): park + schedule a rescan so the op retries
                # even if no newer map reaches this client (ref: the
                # RETRY path in Objecter::handle_osd_op_reply :3547)
                del self.in_flight[op.tid]
                self.homeless.append(op)
                self._schedule_rescan()
                return
            del self.in_flight[op.tid]
            if op.op == "watch" and op.args.get("action") == "watch":
                # registration is confirmed only by a successful reply
                # — recording it at send time would let a failed
                # re-registration (e.g. ENOENT on a recovering
                # primary) kill the watch silently, since _relinger
                # would see the target as already covered
                w = self.watches.get(op.args.get("cookie"))
                if w is not None:
                    w["osd"] = op.target_osd if msg.result == 0 \
                        else None
            self._complete_op(op, msg)

    def _schedule_rescan(self, delay: float = 0.05) -> None:
        """Periodic retry for parked ops (the reference's tick_event).
        The interval doubles up to a cap and is jittered: many clients
        parked by the same outage must not re-probe the recovering
        primary in lockstep at fixed phases (the chaos harness's
        heal-at-the-wrong-phase schedules livelock exactly that)."""
        if getattr(self, "_rescan_timer", None) is not None:
            return

        def fire():
            with self._lock:
                self._rescan_timer = None
                # adopts + resends any homeless op whose map target
                # resolves (incl. the ESTALE case where the target is
                # unchanged but the OSD was behind on maps)
                self._scan_requests()
                if self.homeless:
                    self._schedule_rescan(min(delay * 2, 1.0))

        from ..common.backoff import full_jitter
        self._rescan_timer = threading.Timer(full_jitter(delay), fire)
        self._rescan_timer.daemon = True
        self._rescan_timer.start()

    # ---------------------------------------------------- mon commands
    def mon_command(self, cmd: dict, timeout: float = 30.0
                    ) -> tuple[int, str, object]:
        """Synchronous mon command round-trip.  EAGAIN (-11) answers —
        an election in flight, or a forward that raced leadership
        away — are retried until the deadline: the reference
        MonClient resends commands after an election rather than
        surfacing the churn to every caller.  Mgr-unavailable EAGAINs
        (MGR_UNAVAILABLE_EAGAIN outs) get only a short grace: it
        absorbs the fire-and-forget `mgr register` racing a command
        issued right after mgr start, but a cluster with no mgr at
        all must answer fast, not spin out the whole deadline."""
        import time
        from ..common.backoff import Backoff
        now = time.monotonic()
        deadline = now + timeout
        mgr_deadline = now + min(timeout, 1.0)
        # EAGAIN pacing: an election storm answers every resend with
        # -11; a fixed 0.1s retry re-probed in lockstep with the
        # churn (shared capped-exponential helper instead)
        backoff = Backoff(base_s=0.05, cap_s=1.0)
        while True:
            tid = next(self._tid)
            ev = threading.Event()
            slot: dict = {}
            with self._lock:
                self._pending_cmds[tid] = (ev, slot)
            self.ms.connect(self.mon).send_message(
                MMonCommand(tid=tid, cmd=cmd))
            if not self.wait_sync(
                    ev.is_set, max(0.1, deadline - time.monotonic()),
                    ev=ev):
                raise TimeoutError(
                    f"mon command {cmd.get('prefix')} timed out")
            if slot["r"] == -11:
                retry_until = deadline
                if str(slot["outs"] or "").startswith(
                        MGR_UNAVAILABLE_EAGAIN):
                    retry_until = mgr_deadline
                if time.monotonic() < retry_until:
                    if self.pump_hook is not None:
                        self.pump_hook()   # pump-mode: drive the
                        # election forward instead of sleeping blind
                        time.sleep(min(0.01, backoff.next_delay()))
                    else:
                        backoff.sleep()
                    continue
            return slot["r"], slot["outs"], slot["outb"]

    def dump_traces(self, trace_id: str | None = None) -> list[dict]:
        """The client's finished-span ring (the daemon-side analogue
        is the admin-socket `dump_traces`)."""
        return self.tracer.dump(trace_id)

    def _handle_command_ack(self, msg: MMonCommandAck) -> bool:
        entry = self._pending_cmds.pop(msg.tid, None)
        if entry is None:
            return False
        ev, slot = entry
        slot["r"], slot["outs"], slot["outb"] = \
            msg.result, msg.outs, msg.outb
        ev.set()
        return True
