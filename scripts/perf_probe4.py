"""Amortized timing: R unique encodes inside one jitted scan, one readback."""
import sys, time
sys.path.insert(0, "/root/repo")
import functools
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ceph_tpu.ec import gf
from ceph_tpu.ec.kernels import bitmatmul

k, m = 8, 4
chunk = 128 * 1024
rng = np.random.default_rng(0)
mat = gf.isa_rs_matrix(k, m)[k:]
B = jnp.asarray(gf.expand_to_bitmatrix(mat).astype(np.int8))
R = 50


@functools.partial(jax.jit, static_argnames=("which",))
def many(B, data, which):
    fn = {"xla": bitmatmul.gf_matmul_xla,
          "pallas": bitmatmul.gf_matmul_pallas}[which]
    def body(c, i):
        out = fn(B, data ^ i)
        return c + jnp.sum(out, dtype=jnp.int32), None
    acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(R, dtype=jnp.uint8))
    return acc


for stripes in (64, 256):
    data = jnp.asarray(rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))
    for label in ("xla", "pallas"):
        float(many(B, data, label))  # warm
        t0 = time.perf_counter()
        s = float(many(B, data, label))
        dt = (time.perf_counter() - t0) / R
        total_in = stripes * k * chunk
        total_out = stripes * m * chunk
        print(f"stripes={stripes:4d} {label:6s}: {dt*1e3:8.3f} ms/encode  "
              f"in {total_in/dt/1e9:8.2f} GB/s  io {(total_in+total_out)/dt/1e9:8.2f} GB/s")
