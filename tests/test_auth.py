"""cephx-lite: keyring, handshake, ticket verification, cluster gate
(ref: src/auth/cephx/CephxProtocol.cc, src/auth/KeyRing.cc)."""
import time

import pytest

from ceph_tpu.auth import (SERVICE_ENTITY, CephxClient, CephxServer,
                           CephxVerifier, KeyRing, generate_key)
from ceph_tpu.msg.messenger import Message
from ceph_tpu.testing import MiniCluster


def test_keyring_roundtrip(tmp_path):
    kr = KeyRing.generate(["mon.0", "osd.0", "client.a"])
    path = str(tmp_path / "keyring.json")
    kr.save(path)
    kr2 = KeyRing.load(path)
    assert kr2.keys == kr.keys
    sub = kr.subset("osd.0")
    assert set(sub.keys) == {"osd.0", SERVICE_ENTITY}


def _stamp(msg, src, seq=1):
    msg.src, msg.seq = src, seq
    return msg


def test_handshake_and_signatures():
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr)
    client = CephxClient("client.x", kr.get("client.x"))
    rep = server.handle_request(client.build_request())
    assert rep.result == 0
    assert client.ingest_reply(rep)
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    msg = client.sign(_stamp(Message(), "client.x", 7))
    assert ver.verify(msg)
    # header tampering invalidates the signature
    msg.seq = 8
    assert not ver.verify(msg)
    # unsigned fails; auth handshake types are exempt
    assert not ver.verify(_stamp(Message(), "client.x"))
    from ceph_tpu.msg.messages import MAuthRequest
    assert ver.verify(_stamp(MAuthRequest(), "client.x"))


def test_bad_credentials_rejected():
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr)
    # wrong secret
    bad = CephxClient("client.x", generate_key())
    assert server.handle_request(bad.build_request()).result == -13
    # unknown entity
    ghost = CephxClient("client.ghost", generate_key())
    assert server.handle_request(ghost.build_request()).result == -1
    # forged ticket (wrong service secret) never verifies
    forged = CephxClient.self_mint("client.x", generate_key())
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    assert not ver.verify(forged.sign(_stamp(Message(), "client.x")))


def test_expired_ticket_rejected():
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr, ticket_ttl=-1.0)     # born expired
    client = CephxClient("client.x", kr.get("client.x"))
    rep = server.handle_request(client.build_request())
    assert client.ingest_reply(rep)
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    assert not ver.verify(client.sign(_stamp(Message(), "client.x")))


def test_cephx_cluster_io():
    """Full cluster with cephx on: authenticated IO works; a client
    with a wrong key is refused."""
    c = MiniCluster(n_osd=4, threaded=True, auth="cephx")
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("authp", pg_num=8)
        io = r.open_ioctx("authp")
        io.write_full("sec", b"signed payload")
        assert io.read("sec") == b"signed payload"
        io.set_xattr("sec", "k", b"v")
        assert io.get_xattr("sec", "k") == b"v"
        # wrong secret: the mon refuses the handshake
        from ceph_tpu.client import Rados
        bad = Rados(c.network, name="client.evil",
                    mon=c.mon_names, auth_secret=generate_key())
        with pytest.raises(PermissionError):
            bad.connect(timeout=10.0)
        bad.shutdown()
        # no credentials at all: subscriptions are dropped, no map
        anon = Rados(c.network, name="client.anon", mon=c.mon_names)
        with pytest.raises(TimeoutError):
            anon.connect(timeout=2.0)
        anon.shutdown()
    finally:
        c.shutdown()
