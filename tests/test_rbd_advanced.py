"""librbd depth: exclusive lock arbitration, object map / fast-diff,
snapshot-backed COW clones, flatten (ref: src/librbd/exclusive_lock/,
src/librbd/object_map/, librbd clone + CopyupRequest; VERDICT r2 #6)."""
import threading

import pytest

from ceph_tpu.rbd import RBD, Image, RBDError
from ceph_tpu.rbd.image import ObjectMap, data_name
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("rbd", pg_num=8)
    yield c
    c.shutdown()


def _io(c):
    r = c.rados()
    return r.open_ioctx("rbd")


ORDER = 16      # 64 KiB objects keep the tests light


def test_exclusive_lock_two_clients_contend(cluster):
    """Two clients interleave writes; the lock hands off cooperatively
    via watch/notify and both clients' writes land."""
    io_a, io_b = _io(cluster), _io(cluster)
    RBD().create(io_a, "locky", size=1 << 20, order=ORDER)
    a = Image(io_a, "locky")
    b = Image(io_b, "locky")
    a.write(0, b"A" * 1000)
    assert a.lock_owner
    # b requests the lock; a releases via its watch callback
    b.write(1000, b"B" * 1000)
    assert b.lock_owner
    assert not a.lock_owner
    # and back again
    a.write(2000, b"C" * 1000)
    assert a.lock_owner and not b.lock_owner
    got = a.read(0, 3000)
    assert got == b"A" * 1000 + b"B" * 1000 + b"C" * 1000
    a.close()
    b.close()


def test_exclusive_lock_dead_holder_broken(cluster):
    """A holder whose client died (watch gone, no unlock) is detected
    by live-watcher comparison and its lock broken
    (ref: break_lock for blocklisted owners)."""
    r_dead = cluster.rados()
    io_dead = r_dead.open_ioctx("rbd")
    io_live = _io(cluster)
    RBD().create(io_live, "orphan", size=1 << 20, order=ORDER)
    d = Image(io_dead, "orphan")
    d.write(0, b"x" * 100)
    assert d.lock_owner
    # hard-kill the holder's client: watch disappears, lock remains
    r_dead.shutdown()
    survivor = Image(io_live, "orphan")
    survivor.write(0, b"y" * 100)       # breaks the stale lock
    assert survivor.lock_owner
    assert survivor.read(0, 100) == b"y" * 100
    survivor.close()


def test_object_map_tracks_existence_and_du(cluster):
    io = _io(cluster)
    RBD().create(io, "mapped", size=1 << 20, order=ORDER)  # 16 objects
    img = Image(io, "mapped")
    img.write(0, b"z" * 100)                    # object 0
    img.write(3 << ORDER, b"z" * (1 << ORDER))  # object 3, full
    img.flush()     # write-back cache: the map materializes at flush
    assert img.object_map.get(0) == ObjectMap.EXISTS
    assert img.object_map.get(1) == ObjectMap.NONEXISTENT
    assert img.object_map.get(3) == ObjectMap.EXISTS
    assert img.du() == 2 * (1 << ORDER)
    # discard a whole object drops it from the map
    img.discard(3 << ORDER, 1 << ORDER)
    assert img.object_map.get(3) == ObjectMap.NONEXISTENT
    assert img.du() == 1 << ORDER
    # the map survives reopen
    img.close()
    img2 = Image(io, "mapped")
    assert img2.object_map.get(0) == ObjectMap.EXISTS
    assert img2.object_map.get(3) == ObjectMap.NONEXISTENT
    img2.close()


def test_fast_diff_since_snapshot(cluster):
    io = _io(cluster)
    RBD().create(io, "differ", size=1 << 20, order=ORDER)
    img = Image(io, "differ")
    img.write(0, b"a" * 100)                     # obj 0
    img.write(5 << ORDER, b"a" * 100)            # obj 5
    img.snap_create("base")
    # after the snap, the head map is clean -> empty diff
    assert img.diff_since("base") == []
    img.write(5 << ORDER, b"b" * 50)             # dirty obj 5
    img.write(9 << ORDER, b"c" * 10)             # new obj 9
    diff = img.diff_since("base")
    assert [d["objectno"] for d in diff] == [5, 9]
    assert all(d["exists"] for d in diff)
    # diff since creation sees every existing object
    assert [d["objectno"] for d in img.diff_since(None)] == [0, 5, 9]
    img.snap_remove("base")
    img.close()


def test_clone_cow_read_write_flatten(cluster):
    io = _io(cluster)
    RBD().create(io, "parent", size=1 << 19, order=ORDER)  # 8 objects
    p = Image(io, "parent")
    p.write(0, b"P" * (1 << ORDER))          # obj 0 full
    p.write(2 << ORDER, b"Q" * 4096)         # obj 2 partial
    p.snap_create("gold")
    with pytest.raises(RBDError):            # must protect first
        RBD().clone(io, "parent", "gold", io, "child")
    p.snap_protect("gold")
    RBD().clone(io, "parent", "gold", io, "child")
    assert ("rbd", "child") in p.children()
    # parent writes after the snap do not leak into the clone
    p.write(0, b"Z" * 100)

    c = Image(io, "child")
    # reads fall through to the parent snapshot
    assert c.read(0, 100) == b"P" * 100
    assert c.read(2 << ORDER, 4096) == b"Q" * 4096
    assert c.read(5 << ORDER, 10) == b"\0" * 10
    # partial write copies the parent object up, preserving its bytes
    c.write((2 << ORDER) + 100, b"new")
    got = c.read(2 << ORDER, 4096)
    assert got[:100] == b"Q" * 100
    assert got[100:103] == b"new"
    assert got[103:] == b"Q" * (4096 - 103)
    # parent object is untouched
    assert p.read(2 << ORDER, 100) == b"Q" * 100
    # snapshot can't be unprotected or removed while the clone lives
    with pytest.raises(RBDError):
        p.snap_unprotect("gold")
    with pytest.raises(RBDError):
        p.snap_remove("gold")
    # flatten detaches: all parent blocks copied into the child
    c.flatten()
    assert c.parent is None
    assert c.read(0, 100) == b"P" * 100
    assert c.read(2 << ORDER, 100) == b"Q" * 100
    p2 = Image(io, "parent")
    assert ("rbd", "child") not in p2.children()
    p2.snap_unprotect("gold")
    p2.snap_remove("gold")
    p2.close()
    c.close()
    p.close()


def test_clone_discard_does_not_expose_parent(cluster):
    io = _io(cluster)
    RBD().create(io, "pdisc", size=1 << 18, order=ORDER)
    p = Image(io, "pdisc")
    p.write(0, b"S" * (1 << ORDER))
    p.snap_create("s")
    p.snap_protect("s")
    RBD().clone(io, "pdisc", "s", io, "cdisc")
    c = Image(io, "cdisc")
    # whole-object discard inside the overlap must zero, not remove —
    # a remove would resurrect the parent's bytes via fall-through
    c.discard(0, 1 << ORDER)
    assert c.read(0, 100) == b"\0" * 100
    c.close()
    p.close()


def test_remove_guards(cluster):
    io = _io(cluster)
    RBD().create(io, "guarded", size=1 << 18, order=ORDER)
    img = Image(io, "guarded")
    img.write(0, b"g")
    img.snap_create("s1")
    img.close()
    with pytest.raises(RBDError, match="snapshots"):
        RBD().remove(io, "guarded")
    img = Image(io, "guarded")
    img.snap_remove("s1")
    img.close()
    RBD().remove(io, "guarded")
    assert "guarded" not in RBD().list(io)


def test_rbd_cli_verbs(cluster):
    """rbd CLI verbs end-to-end (ref: src/tools/rbd/; cram-style CLI
    tier src/test/cli/rbd/)."""
    import io as _io_mod
    from ceph_tpu.tools.rbd_cli import main
    r = cluster.rados()

    def run(*argv):
        buf = _io_mod.StringIO()
        rc = main(list(argv), rados=r, out=buf)
        return rc, buf.getvalue()

    rc, _ = run("-p", "rbd", "create", "cli_img", "--size", "1M",
                "--order", "16")
    assert rc == 0
    rc, out = run("-p", "rbd", "ls")
    assert rc == 0 and "cli_img" in out.splitlines()
    rc, out = run("-p", "rbd", "info", "cli_img")
    assert rc == 0 and "1 MiB" in out
    rc, _ = run("-p", "rbd", "snap", "create", "cli_img@s1")
    assert rc == 0
    rc, _ = run("-p", "rbd", "snap", "protect", "cli_img@s1")
    assert rc == 0
    rc, out = run("-p", "rbd", "snap", "ls", "cli_img")
    assert rc == 0 and "s1" in out and "protected" in out
    rc, _ = run("-p", "rbd", "clone", "cli_img@s1", "cli_child")
    assert rc == 0
    rc, out = run("-p", "rbd", "children", "cli_img")
    assert rc == 0 and "rbd/cli_child" in out
    rc, out = run("-p", "rbd", "info", "cli_child")
    assert rc == 0 and "parent: rbd/cli_img@s1" in out
    rc, out = run("-p", "rbd", "du", "cli_img")
    assert rc == 0 and "used" in out
    rc, _ = run("-p", "rbd", "flatten", "cli_child")
    assert rc == 0
    rc, out = run("-p", "rbd", "children", "cli_img")
    assert rc == 0 and "cli_child" not in out
    rc, _ = run("-p", "rbd", "snap", "unprotect", "cli_img@s1")
    assert rc == 0
    rc, _ = run("-p", "rbd", "snap", "rm", "cli_img@s1")
    assert rc == 0
    rc, _ = run("-p", "rbd", "rm", "cli_child")
    assert rc == 0
    # removing a missing image fails cleanly
    rc, _ = run("-p", "rbd", "rm", "ghost")
    assert rc == 1
