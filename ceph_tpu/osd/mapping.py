"""Full-cluster PG→OSD mapping tables — the batch placement path.

TPU-native replacement for OSDMapMapping/ParallelPGMapper
(ref: src/osd/OSDMapMapping.{h,cc}): where the reference shards all PGs
of all pools across a ThreadPool and runs crush per PG, this module
computes every pool's placements in one vmapped CRUSH dispatch
(ceph_tpu.crush.batch) and applies the cheap per-PG epilogue steps
(upmap overrides, up filtering, primary affinity, temp overrides) as
vectorized numpy passes with sparse per-row fixups.

Falls back to the scalar OSDMap pipeline per pool when the crush map is
not batchable (legacy bucket algs etc.).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..crush.batch import BatchUnsupported, compile_map
from ..crush.types import CRUSH_ITEM_NONE
from .osdmap import (CEPH_OSD_DEFAULT_PRIMARY_AFFINITY, OSDMap)
from .types import PG

#: PGs per batched mapping dispatch (ParallelPGMapper-style sharding
#: of the PG space; one executable, bounded device memory)
BATCH_CHUNK = 1 << 16


@dataclass
class PoolMapping:
    """Placement table for one pool: row = pg.ps.

    acting rows may be wider than pool.size (a backfill pg_temp can
    name more osds than the pool size) or logically shorter (a partial
    pg_temp on an EC pool); acting_len holds each row's true length."""
    pool_id: int
    up: np.ndarray               # (pg_num, size) int32, NONE holes
    up_primary: np.ndarray       # (pg_num,) int32 (-1 none)
    acting: np.ndarray           # (pg_num, acting_width) int32
    acting_primary: np.ndarray   # (pg_num,) int32
    acting_len: np.ndarray       # (pg_num,) int32 — true row lengths
    up_len: np.ndarray           # (pg_num,) int32


class OSDMapMapping:
    """Precomputed pg→osd tables + reverse osd→pg map
    (ref: src/osd/OSDMapMapping.h:170)."""

    def __init__(self) -> None:
        self.epoch = -1
        self.pools: dict[int, PoolMapping] = {}
        self._shift_flags: dict[int, bool] = {}
        # compiled crush cache shared across pools of one update
        self._cc_cache: dict = {}

    # ------------------------------------------------------------------
    def update(self, osdmap: OSDMap, pool_ids=None) -> None:
        """Recompute tables for the map's current epoch.  With pool_ids
        given, only those pools are recomputed in place and other pools'
        tables are kept (ref: OSDMapMapping.cc:45 update(map) /
        update(map, pool))."""
        self._cc_cache = {}
        if pool_ids is None:
            self.pools = {}
            pool_ids = set(osdmap.pools)
        for pool_id in sorted(pool_ids):
            if pool_id in osdmap.pools:
                self.pools[pool_id] = self._map_pool(osdmap, pool_id)
            else:
                self.pools.pop(pool_id, None)
        self.epoch = osdmap.epoch

    def get(self, pg: PG) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) for one pg; empty
        results for unknown pools / out-of-range ps.

        The tables are indexed by *actual* pg ids (ps already in
        [0, pg_num)); a raw/out-of-range ps is the caller's bug, so it
        is rejected rather than folded (ref: OSDMapMapping.h:294
        ceph_assert(pgid.ps() < p->second.pg_num), which never folds)."""
        pm = self.pools.get(pg.pool)
        if pm is None:
            return [], -1, [], -1
        if not (0 <= pg.ps < len(pm.up)):
            return [], -1, [], -1
        shift = self._shift(pg.pool)
        up_row = pm.up[pg.ps][:pm.up_len[pg.ps]]
        acting_row = pm.acting[pg.ps][:pm.acting_len[pg.ps]]
        up = [int(o) for o in up_row
              if not (shift and o == CRUSH_ITEM_NONE)]
        acting = [int(o) for o in acting_row
                  if not (shift and o == CRUSH_ITEM_NONE)]
        return (up, int(pm.up_primary[pg.ps]),
                acting, int(pm.acting_primary[pg.ps]))

    def _shift(self, pool_id: int) -> bool:
        return self._shift_flags[pool_id]

    def get_osd_acting_pgs(self, osd: int) -> list[PG]:
        """Reverse map (ref: OSDMapMapping.cc:60 _build_rmap)."""
        out: list[PG] = []
        for pool_id, pm in self.pools.items():
            rows = np.nonzero((pm.acting == osd).any(axis=1))[0]
            out.extend(PG(pool_id, int(ps)) for ps in rows)
        return out

    def osd_pg_counts(self, n_osd: int, acting: bool = True) -> np.ndarray:
        """PGs per OSD across all pools (balancer/score input)."""
        counts = np.zeros(n_osd, dtype=np.int64)
        for pm in self.pools.values():
            t = pm.acting if acting else pm.up
            vals = t[(t != CRUSH_ITEM_NONE) & (t >= 0)]
            counts += np.bincount(vals, minlength=n_osd)[:n_osd]
        return counts

    # ------------------------------------------------------------------
    def _compiled(self, osdmap: OSDMap, pool_id: int):
        """CompiledCrushMap shared across pools with identical
        (crush, resolved choose_args) — avoids per-pool re-jits."""
        args = osdmap.crush.choose_args_get_with_fallback(pool_id)
        key = (id(osdmap.crush), id(args) if args is not None else None)
        cc = self._cc_cache.get(key)
        if cc is None:
            cc = compile_map(osdmap.crush, choose_args=args)
            self._cc_cache[key] = cc
        return cc

    def _map_pool(self, osdmap: OSDMap, pool_id: int) -> PoolMapping:
        pool = osdmap.pools[pool_id]
        self._shift_flags[pool_id] = pool.can_shift_osds()
        npg = pool.pg_num
        size = pool.size
        pss = np.arange(npg, dtype=np.int64)
        pps = pool.raw_pg_to_pps_batch(pss, pool_id)
        ruleno = osdmap.crush.find_rule(pool.crush_rule, pool.type, size)

        raw = np.full((npg, size), CRUSH_ITEM_NONE, dtype=np.int32)
        counts = np.zeros(npg, dtype=np.int32)
        if ruleno >= 0:
            try:
                cc = self._compiled(osdmap, pool_id)
                weights = np.asarray(osdmap.osd_weight, dtype=np.int64)
                # fixed-size dispatches: one compiled executable reused
                # across the pool, bounded device memory (a 1M-PG pool
                # in one dispatch overruns a v5e-1's working set; the
                # reference's ParallelPGMapper likewise shards the PG
                # space, OSDMapMapping.h:115)
                chunk = min(BATCH_CHUNK, npg)
                for lo in range(0, npg, chunk):
                    hi = min(lo + chunk, npg)
                    sl = pps[lo:hi]
                    if len(sl) < chunk:   # pad tail: same executable
                        sl = np.concatenate(
                            [sl, np.zeros(chunk - len(sl),
                                          dtype=sl.dtype)])
                    res, cnt = cc.map_batch(
                        sl, weights, ruleno=ruleno, result_max=size,
                        return_counts=True)
                    raw[lo:hi] = np.asarray(res)[:hi - lo]
                    counts[lo:hi] = np.asarray(cnt)[:hi - lo]
            except BatchUnsupported:
                from ..crush import mapper as crush_mapper
                ca = osdmap.crush.choose_args_get_with_fallback(pool_id)
                for ps in range(npg):
                    r = crush_mapper.do_rule(
                        osdmap.crush, ruleno, int(pps[ps]), size,
                        osdmap.osd_weight, choose_args=ca)
                    raw[ps, :len(r)] = r
                    counts[ps] = len(r)

        # mask out positions beyond each row's result count
        col = np.arange(size)
        raw = np.where(col[None, :] < counts[:, None], raw,
                       CRUSH_ITEM_NONE)

        state = np.zeros(max(osdmap.max_osd, 1), dtype=np.int64)
        state[:osdmap.max_osd] = osdmap.osd_state
        exists = (state & 1) != 0          # CEPH_OSD_EXISTS
        up_mask = exists & ((state & 2) != 0)  # CEPH_OSD_UP

        def lookup(table: np.ndarray, t: np.ndarray) -> np.ndarray:
            idx = np.clip(t, 0, len(table) - 1)
            ok = (t >= 0) & (t < osdmap.max_osd)
            return np.where(ok, table[idx], False)

        # _remove_nonexistent_osds (OSDMap.cc:2208)
        valid = raw != CRUSH_ITEM_NONE
        keep = valid & lookup(exists, raw)
        raw, counts = self._filter(pool, raw, keep, counts)

        # _raw_to_up_osds (OSDMap.cc:2309)
        valid = raw != CRUSH_ITEM_NONE
        keep = valid & lookup(up_mask, raw)
        up, up_len = self._filter(pool, raw, keep, counts)

        # primary = first non-NONE (OSDMap.cc:2252)
        up_primary = self._first_valid(up)

        # _apply_primary_affinity (OSDMap.cc:2334) — skip entirely when
        # all affinities are default, like the reference
        if osdmap.osd_primary_affinity is not None:
            aff = np.asarray(osdmap.osd_primary_affinity, dtype=np.int64)
            if (aff != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY).any():
                up, up_primary = self._apply_affinity(
                    osdmap, pool, pps, up, up_primary, aff)

        acting = up.copy()
        acting_primary = up_primary.copy()
        acting_len = up_len.copy()

        # sparse overrides (upmap / pg_temp / primary_temp): recompute
        # those rows through the scalar pipeline wholesale — exactness
        # guaranteed, and rows may be wider than pool.size (backfill
        # pg_temp) or shorter (partial temp on an EC pool)
        special = {
            pg.ps for src in (osdmap.pg_upmap, osdmap.pg_upmap_items,
                              osdmap.pg_temp, osdmap.primary_temp)
            for pg in src if pg.pool == pool_id and pg.ps < npg}
        if special:
            rows = {ps: osdmap.pg_to_up_acting_osds(PG(pool_id, ps))
                    for ps in sorted(special)}
            width = max([size] + [max(len(r[0]), len(r[2]))
                                  for r in rows.values()])
            if width > size:
                pad = np.full((npg, width - size), CRUSH_ITEM_NONE,
                              dtype=np.int32)
                up = np.concatenate([up, pad], axis=1)
                acting = np.concatenate([acting, pad], axis=1)
            for ps, (u, upp, a, actp) in rows.items():
                up[ps] = CRUSH_ITEM_NONE
                up[ps, :len(u)] = u
                up_len[ps] = len(u)
                up_primary[ps] = upp
                acting[ps] = CRUSH_ITEM_NONE
                acting[ps, :len(a)] = a
                acting_len[ps] = len(a)
                acting_primary[ps] = actp

        return PoolMapping(pool_id, up, up_primary, acting,
                           acting_primary, acting_len, up_len)

    @staticmethod
    def _filter(pool, table: np.ndarray, keep: np.ndarray,
                lengths: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Drop filtered entries: EC pools keep position (NONE holes,
        length unchanged); replicated pools compact left and shrink
        (OSDMap.cc:2211-2231,2311-2331).  Returns (table, lengths)."""
        out = np.where(keep, table, CRUSH_ITEM_NONE)
        if not pool.can_shift_osds():
            return out, lengths.copy()
        new_len = keep.sum(axis=1).astype(np.int32)
        # vectorized stable left-compaction: NONE entries sort last
        order = np.argsort(out == CRUSH_ITEM_NONE, axis=1, kind="stable")
        out = np.take_along_axis(out, order, axis=1)
        return out, new_len

    @staticmethod
    def _first_valid(table: np.ndarray) -> np.ndarray:
        valid = table != CRUSH_ITEM_NONE
        has = valid.any(axis=1)
        first = np.argmax(valid, axis=1)
        prim = table[np.arange(len(table)), first]
        return np.where(has, prim, -1).astype(np.int32)

    def _apply_affinity(self, osdmap, pool, pps, up, up_primary, aff):
        """Vectorized _apply_primary_affinity (OSDMap.cc:2334-2387)."""
        from ..crush.hashes import hash32_2
        npg, size = up.shape
        valid = up != CRUSH_ITEM_NONE
        idx = np.clip(up, 0, len(aff) - 1)
        a = np.where(valid, aff[idx], CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
        any_custom = (a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY).any(axis=1)
        # rejection draw per entry
        draws = hash32_2(np.broadcast_to(pps[:, None], up.shape).ravel(),
                         up.ravel()).reshape(up.shape).astype(np.int64)
        reject = valid & (a < 0x10000) & ((draws >> 16) >= a)
        accept = valid & ~reject
        has_accept = accept.any(axis=1)
        first_accept = np.argmax(accept, axis=1)
        has_valid = valid.any(axis=1)
        first_valid = np.argmax(valid, axis=1)
        pos = np.where(has_accept, first_accept,
                       np.where(has_valid, first_valid, -1))
        rows = np.nonzero(any_custom & (pos >= 0))[0]
        up = up.copy()
        up_primary = up_primary.copy()
        for r in rows:
            p = int(pos[r])
            up_primary[r] = up[r, p]
            if pool.can_shift_osds() and p > 0:
                up[r, 1:p + 1] = up[r, 0:p]
                up[r, 0] = up_primary[r]
        return up, up_primary
