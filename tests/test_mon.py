"""Monitor / OSDMonitor: paxos commit pipeline, pool & EC-profile
commands, boot/failure/subscription flow (ref: src/mon/OSDMonitor.cc,
src/test/mon/osd-pool-create.sh behaviors)."""
import time

import pytest

from ceph_tpu.mon import Monitor, MonitorStore, Paxos, StoreTransaction
from ceph_tpu.mon.monitor import build_initial
from ceph_tpu.msg.messages import (MMap, MMonCommand, MMonCommandAck,
                                   MMonSubscribe, MOSDBoot, MOSDFailure)
from ceph_tpu.msg.messenger import Dispatcher, LocalNetwork, Messenger
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PG, POOL_TYPE_ERASURE


@pytest.fixture
def mon():
    net = LocalNetwork()
    m, w = build_initial(8, osds_per_host=2)
    mon = Monitor(net, initial_map=m, initial_wrapper=w, threaded=False)
    mon.init()
    yield mon
    mon.shutdown()


# ----------------------------------------------------------------- store
def test_store_transactions():
    s = MonitorStore()
    tx = StoreTransaction()
    tx.put("p", "a", 1)
    tx.put("p", 5, "five")
    s.apply_transaction(tx)
    assert s.get("p", "a") == 1
    assert s.get("p", "5") == "five"  # int keys stringified
    tx2 = StoreTransaction()
    tx2.erase("p", "a")
    s.apply_transaction(tx2)
    assert s.get("p", "a") is None


def test_paxos_versions_and_trim():
    s = MonitorStore()
    p = Paxos(s, keep_versions=5)
    for i in range(12):
        tx = StoreTransaction()
        tx.put("svc", "x", i)
        assert p.propose(tx) == i + 1
    assert s.get("svc", "x") == 11
    assert p.last_committed == 12
    assert p.first_committed == 12 - 5
    # trimmed decided values are gone, recent ones remain
    assert s.get("paxos", 1) is None
    assert s.get("paxos", 12) is not None


# ------------------------------------------------------------- bootstrap
def test_monitor_bootstrap(mon):
    assert mon.osdmap.epoch >= 1
    assert mon.osdmap.max_osd == 8
    r, outs, outb = mon.handle_command({"prefix": "osd stat"})
    assert r == 0 and outb["num_up_osds"] == 8


def test_osd_tree_names(mon):
    r, outs, _ = mon.handle_command({"prefix": "osd tree"})
    assert r == 0
    assert "root default" in outs
    assert "host host0" in outs


# ------------------------------------------------------ pool create paths
def test_pool_create_replicated(mon):
    e0 = mon.osdmap.epoch
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool create", "pool": "data", "pg_num": 64})
    assert r == 0, outs
    assert mon.osdmap.epoch == e0 + 1
    pid = [p for p, n in mon.osdmap.pool_names.items() if n == "data"][0]
    pool = mon.osdmap.pools[pid]
    assert pool.size == 3 and pool.pg_num == 64
    # placements resolve through the named crush rule
    up, up_p, _, _ = mon.osdmap.pg_to_up_acting_osds(PG(pid, 0))
    assert len(up) == 3 and up_p in up
    # duplicate create fails
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool create", "pool": "data", "pg_num": 64})
    assert r == -17  # EEXIST


def test_pool_create_erasure_default_profile(mon):
    """EC pool via the implicit default profile: the mon drives the
    plugin's create_rule exactly like OSDMonitor.cc:6458."""
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool create", "pool": "ecpool", "pg_num": 32,
         "pool_type": "erasure"})
    assert r == 0, outs
    pid = [p for p, n in mon.osdmap.pool_names.items()
           if n == "ecpool"][0]
    pool = mon.osdmap.pools[pid]
    assert pool.type == POOL_TYPE_ERASURE
    assert pool.size == 3          # default profile k=2 m=1
    assert pool.min_size == 2      # k + min(1, m-1)
    assert pool.erasure_code_profile == "default"
    # the plugin-made erasure rule maps with NONE-capable indep
    up, _, _, _ = mon.osdmap.pg_to_up_acting_osds(PG(pid, 3))
    assert len(up) == 3


def test_pool_create_erasure_custom_profile(mon):
    r, outs, _ = mon.handle_command(
        {"prefix": "osd erasure-code-profile set", "name": "k3m2",
         "profile": {"plugin": "tpu", "k": "3", "m": "2",
                     "crush-failure-domain": "osd"}})
    assert r == 0, outs
    r, outs, outb = mon.handle_command(
        {"prefix": "osd erasure-code-profile get", "name": "k3m2"})
    assert r == 0 and outb["k"] == "3"
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool create", "pool": "ec32", "pg_num": 16,
         "pool_type": "erasure", "erasure_code_profile": "k3m2"})
    assert r == 0, outs
    pid = [p for p, n in mon.osdmap.pool_names.items() if n == "ec32"][0]
    pool = mon.osdmap.pools[pid]
    assert pool.size == 5 and pool.min_size == 4
    up, _, _, _ = mon.osdmap.pg_to_up_acting_osds(PG(pid, 1))
    assert len(up) == 5
    # profile now in use: rm refuses
    r, outs, _ = mon.handle_command(
        {"prefix": "osd erasure-code-profile rm", "name": "k3m2"})
    assert r == -16 and "in use" in outs


def test_profile_override_needs_force(mon):
    mon.handle_command(
        {"prefix": "osd erasure-code-profile set", "name": "p1",
         "profile": {"plugin": "tpu", "k": "2", "m": "1"}})
    r, outs, _ = mon.handle_command(
        {"prefix": "osd erasure-code-profile set", "name": "p1",
         "profile": {"plugin": "tpu", "k": "4", "m": "2"}})
    assert r == -1 and "force" in outs
    r, outs, _ = mon.handle_command(
        {"prefix": "osd erasure-code-profile set", "name": "p1",
         "profile": {"plugin": "tpu", "k": "4", "m": "2"},
         "force": True})
    assert r == 0


def test_pool_set_and_delete(mon):
    mon.handle_command({"prefix": "osd pool create", "pool": "p",
                        "pg_num": 8, "size": 2})
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool set", "pool": "p", "var": "pg_num",
         "val": "16"})
    assert r == 0
    pid = [p for p, n in mon.osdmap.pool_names.items() if n == "p"][0]
    assert mon.osdmap.pools[pid].pg_num == 16
    # pg_num shrink refused
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool set", "pool": "p", "var": "pg_num",
         "val": "8"})
    assert r == -1
    # delete needs the guard
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool delete", "pool": "p"})
    assert r == -1
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pool delete", "pool": "p",
         "yes_i_really_really_mean_it": True})
    assert r == 0
    assert pid not in mon.osdmap.pools


# ------------------------------------------------------ osd state commands
def test_osd_down_out_in(mon):
    e0 = mon.osdmap.epoch
    r, outs, _ = mon.handle_command({"prefix": "osd down", "ids": [3]})
    assert r == 0 and mon.osdmap.is_down(3)
    r, outs, _ = mon.handle_command({"prefix": "osd out", "ids": [3]})
    assert r == 0 and mon.osdmap.is_out(3)
    r, outs, _ = mon.handle_command({"prefix": "osd in", "ids": [3]})
    assert r == 0 and mon.osdmap.is_in(3)
    assert mon.osdmap.epoch == e0 + 3
    # idempotent: no epoch bump for an already-in osd
    r, outs, _ = mon.handle_command({"prefix": "osd in", "ids": [3]})
    assert r == 0 and "already" in outs


def test_reweight_and_upmap_commands(mon):
    mon.handle_command({"prefix": "osd pool create", "pool": "d",
                        "pg_num": 8})
    pid = [p for p, n in mon.osdmap.pool_names.items() if n == "d"][0]
    r, _, _ = mon.handle_command(
        {"prefix": "osd reweight", "id": 2, "weight": 0.5})
    assert r == 0
    assert mon.osdmap.osd_weight[2] == 0x8000
    up0, _, _, _ = mon.osdmap.pg_to_up_acting_osds(PG(pid, 0))
    frm = up0[0]
    to = next(o for o in range(8) if o not in up0)
    r, outs, _ = mon.handle_command(
        {"prefix": "osd pg-upmap-items", "pgid": f"{pid}.0",
         "id_pairs": [(frm, to)]})
    assert r == 0, outs
    assert PG(pid, 0) in mon.osdmap.pg_upmap_items
    r, _, _ = mon.handle_command(
        {"prefix": "osd rm-pg-upmap-items", "pgid": f"{pid}.0"})
    assert r == 0
    assert PG(pid, 0) not in mon.osdmap.pg_upmap_items


# ------------------------------------------------- wire: boot/failure/subs
class Client(Dispatcher):
    def __init__(self, net, name):
        self.ms = Messenger.create(net, name, threaded=False)
        self.ms.add_dispatcher(self)
        self.ms.start()
        self.maps = []
        self.acks = []

    def ms_dispatch(self, msg):
        if isinstance(msg, MMap):
            self.maps.append(msg)
            return True
        if isinstance(msg, MMonCommandAck):
            self.acks.append(msg)
            return True
        return False


def test_subscribe_and_publish():
    net = LocalNetwork()
    m, w = build_initial(4, osds_per_host=1)
    mon = Monitor(net, initial_map=m, initial_wrapper=w, threaded=False)
    mon.init()
    cl = Client(net, "client.1")
    cl.ms.connect("mon.0").send_message(MMonSubscribe(start=1))
    mon.ms.poll()
    cl.ms.poll()
    assert len(cl.maps) == 1 and cl.maps[0].full_map is not None
    e0 = cl.maps[0].full_map.epoch
    # a committed change pushes incrementals to the subscriber
    cl.ms.connect("mon.0").send_message(MMonCommand(
        tid=7, cmd={"prefix": "osd pool create", "pool": "x",
                    "pg_num": 8}))
    mon.ms.poll()
    cl.ms.poll()
    assert cl.acks and cl.acks[0].result == 0 and cl.acks[0].tid == 7
    assert len(cl.maps) == 2
    m2 = cl.maps[1]
    assert m2.incrementals and m2.first == e0 + 1
    # client can replay the incremental onto its map
    full = cl.maps[0].full_map
    for inc in m2.incrementals:
        full.apply_incremental(inc)
    assert full.epoch == mon.osdmap.epoch
    assert any(n == "x" for n in full.pool_names.values())
    mon.shutdown()


def test_boot_and_failure_flow():
    net = LocalNetwork()
    m, w = build_initial(4, osds_per_host=1)
    mon = Monitor(net, initial_map=m, initial_wrapper=w, threaded=False)
    mon.init()
    osd_ms = Messenger.create(net, "osd.2", threaded=False)
    osd_ms.start()
    # two distinct reporters -> mark down
    osd_ms.connect("mon.0").send_message(
        MOSDFailure(target_osd=2, reporter=0))
    mon.ms.poll()
    assert mon.osdmap.is_up(2)        # one reporter is not enough
    osd_ms.connect("mon.0").send_message(
        MOSDFailure(target_osd=2, reporter=1))
    mon.ms.poll()
    assert mon.osdmap.is_down(2)
    # auto-out after the down-out interval
    mon._down_stamp[2] = time.monotonic() - 1e6
    mon.tick()
    assert mon.osdmap.is_out(2)
    # boot brings it back up and (auto-out) back in
    osd_ms.connect("mon.0").send_message(MOSDBoot(osd=2))
    mon.ms.poll()
    assert mon.osdmap.is_up(2) and mon.osdmap.is_in(2)
    # boot of a brand-new osd extends the map
    osd_ms.connect("mon.0").send_message(MOSDBoot(osd=9))
    mon.ms.poll()
    assert mon.osdmap.max_osd == 10 and mon.osdmap.is_up(9)
    mon.shutdown()


def test_failed_command_does_not_leak_pending_state(mon):
    """A failed multi-id command must not leave earlier ids staged in
    pending_inc for the next command to commit."""
    r, outs, _ = mon.handle_command(
        {"prefix": "osd down", "ids": [0, 999]})
    assert r != 0
    assert mon.osdmap.is_up(0)
    r, _, _ = mon.handle_command({"prefix": "osd setmaxosd",
                                  "newmax": 8})
    assert r == 0
    assert mon.osdmap.is_up(0)  # stray mark-down must not ride along


def test_malformed_command_returns_einval(mon):
    r, outs, _ = mon.handle_command({"prefix": "osd down",
                                     "ids": ["abc"]})
    assert r == -22
    r, outs, _ = mon.handle_command({"prefix": "osd setmaxosd"})
    assert r == -22
    r, outs, _ = mon.handle_command({"prefix": "pg map",
                                     "pgid": "garbage"})
    assert r == -22
    # mon still healthy afterwards
    r, _, _ = mon.handle_command({"prefix": "osd stat"})
    assert r == 0


def test_failure_reports_validated_and_expire():
    net = LocalNetwork()
    m, w = build_initial(4, osds_per_host=1)
    mon = Monitor(net, initial_map=m, initial_wrapper=w, threaded=False)
    mon.init()
    ms = Messenger.create(net, "osd.9", threaded=False)
    ms.start()
    # self-report and invalid reporter ignored
    ms.connect("mon.0").send_message(MOSDFailure(target_osd=2, reporter=2))
    ms.connect("mon.0").send_message(MOSDFailure(target_osd=2, reporter=-1))
    ms.connect("mon.0").send_message(MOSDFailure(target_osd=2, reporter=77))
    mon.ms.poll()
    assert mon.osdmap.is_up(2)
    # stale report expired before a fresh one arrives
    ms.connect("mon.0").send_message(MOSDFailure(target_osd=2, reporter=0))
    mon.ms.poll()
    mon._failure_reports[2][0] -= 1e6  # age far past the grace window
    ms.connect("mon.0").send_message(MOSDFailure(target_osd=2, reporter=1))
    mon.ms.poll()
    assert mon.osdmap.is_up(2)  # stale + fresh != quorum
    # two fresh distinct reporters do mark it down
    ms.connect("mon.0").send_message(MOSDFailure(target_osd=2, reporter=0))
    mon.ms.poll()
    assert mon.osdmap.is_down(2)
    mon.shutdown()


def test_map_history_trimmed():
    net = LocalNetwork()
    m, w = build_initial(2, osds_per_host=1)
    from ceph_tpu.common.options import global_config
    cfg = global_config()
    old = cfg["mon_min_osdmap_epochs"]
    cfg.set("mon_min_osdmap_epochs", 5)
    try:
        mon = Monitor(net, initial_map=m, initial_wrapper=w,
                      threaded=False)
        mon.init()
        for i in range(12):
            mon.handle_command({"prefix": "osd pool create",
                                "pool": f"p{i}", "pg_num": 8})
        e = mon.osdmap.epoch
        assert mon.osdmon.get_version(f"full_{e}") is not None
        assert mon.osdmon.get_first_committed() == e - 5
        assert mon.osdmon.get_version(f"full_{e - 6}") is None
        mon.shutdown()
    finally:
        cfg.set("mon_min_osdmap_epochs", old)


def test_map_history_served(mon):
    mon.handle_command({"prefix": "osd pool create", "pool": "a",
                        "pg_num": 8})
    mon.handle_command({"prefix": "osd pool create", "pool": "b",
                        "pg_num": 8})
    e = mon.osdmap.epoch
    r, _, full = mon.handle_command({"prefix": "osd getmap",
                                     "epoch": e - 1})
    assert r == 0 and full.epoch == e - 1
    # monitor restart from the same store recovers the map
    mon2_store = mon.store
    net2 = LocalNetwork()
    mon2 = Monitor(net2, store=mon2_store, threaded=False)
    mon2.init()
    assert mon2.osdmap.epoch == e
    assert set(mon2.osdmap.pool_names.values()) >= {"a", "b"}
    mon2.shutdown()
