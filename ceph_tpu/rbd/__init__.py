"""RBD: block images striped over RADOS objects (ref: src/librbd/)."""
from .image import RBD, Image, RBDError

__all__ = ["RBD", "Image", "RBDError"]
