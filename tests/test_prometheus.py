"""Prometheus exporter (ref: src/pybind/mgr/prometheus/module.py)."""
import urllib.request

import pytest

from ceph_tpu.testing import MiniCluster


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def test_metrics_endpoint():
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("pm", pg_num=8)
        io = r.open_ioctx("pm")
        for i in range(5):
            io.write_full(f"m{i}", b"x" * 100)
        for _ in range(3):
            c.tick()
        mgr = c.start_mgr()
        exp = mgr.start_prometheus()
        text = _scrape(exp.port)
        lines = dict(
            l.rsplit(" ", 1) for l in text.splitlines()
            if l and not l.startswith("#"))
        assert lines["ceph_health_status"] == "0"
        assert lines["ceph_osd_up"] == "3"
        assert lines["ceph_pg_total"] == "8"
        assert lines['ceph_pg_state{state="active+clean"}'] == "8"
        assert lines["ceph_objects"] == "5"
        assert lines['ceph_pool_objects{pool="pm"}'] == "5"
        assert lines['ceph_pool_bytes{pool="pm"}'] == "500"
        assert float(lines["ceph_cluster_total_bytes"]) > 0
        # per-daemon counters from the piggybacked perf reports
        assert float(lines['ceph_daemon_op{daemon="osd.0"}']) >= 0
        # exposition format sanity: HELP/TYPE precede samples
        assert text.index("# HELP ceph_health_status") < \
            text.index("ceph_health_status 0")
        # 404 for other paths
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=10)
    finally:
        c.shutdown()


import urllib.error  # noqa: E402  (used in the test above)


def test_latency_histogram_families_parse():
    """Per-op-class latency histograms export as REAL prometheus
    histogram families: cumulative _bucket samples with le labels
    (ending at +Inf), plus _sum and _count, and count == the +Inf
    bucket (the exposition-format histogram contract)."""
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("ph", pg_num=8)
        io = r.open_ioctx("ph")
        for i in range(6):
            io.write_full(f"h{i}", b"y" * 256)
        for _ in range(3):
            c.tick()
        mgr = c.start_mgr()
        exp = mgr.start_prometheus()
        text = _scrape(exp.port)
        fam = "ceph_daemon_op_lat_client_seconds"
        assert f"# TYPE {fam} histogram" in text
        # parse one daemon's series
        import re
        buckets = {}
        s = cnt = None
        for ln in text.splitlines():
            m = re.match(
                rf'{fam}_bucket{{daemon="osd.0",le="([^"]+)"}} (\S+)',
                ln)
            if m:
                buckets[m.group(1)] = float(m.group(2))
            m = re.match(rf'{fam}_sum{{daemon="osd.0"}} (\S+)', ln)
            if m:
                s = float(m.group(1))
            m = re.match(rf'{fam}_count{{daemon="osd.0"}} (\S+)', ln)
            if m:
                cnt = float(m.group(1))
        assert buckets and s is not None and cnt is not None
        assert "+Inf" in buckets
        assert cnt == buckets["+Inf"] and cnt > 0
        # buckets are cumulative and monotone in le order
        ordered = sorted((float(k), v) for k, v in buckets.items()
                         if k != "+Inf")
        vals = [v for _k, v in ordered] + [buckets["+Inf"]]
        assert vals == sorted(vals)
        assert s > 0
    finally:
        c.shutdown()


def test_rgw_sync_lag_gauges():
    """Multisite observability (ISSUE 5 satellite): the exporter
    carries per-(zone, source) sync gauges, and after convergence the
    lag returns to 0 — the acceptance's 'caught up' read for an
    operator who only has the scrape."""
    import time

    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        g1, g2 = c.rgw_multisite(zones=("pz1", "pz2"))

        def put(gw, path, data=None):
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{gw.port}{path}", data=data,
                method="PUT"), timeout=30).read()
        put(g1, "/pmb")
        for i in range(4):
            put(g1, f"/pmb/o{i}", b"x%d" % i)
        end = time.monotonic() + 30
        while time.monotonic() < end and not (
                g1.sync.caught_up() and g2.sync.caught_up()):
            time.sleep(0.05)
        assert g2.sync.caught_up() and g1.sync.caught_up()
        mgr = c.start_mgr()
        exp = mgr.start_prometheus()
        text = _scrape(exp.port)
        assert "# HELP ceph_rgw_sync_lag_entries" in text
        assert "# HELP ceph_rgw_sync_behind_shards" in text
        lines = dict(
            l.rsplit(" ", 1) for l in text.splitlines()
            if l and not l.startswith("#"))
        # one row per (zone, source) direction, all caught up
        for zone, src in (("pz2", "pz1"), ("pz1", "pz2")):
            lbl = f'{{source="{src}",zone="{zone}"}}'
            assert lines[f"ceph_rgw_sync_lag_entries{lbl}"] == "0"
            assert lines[f"ceph_rgw_sync_behind_shards{lbl}"] == "0"
    finally:
        c.shutdown()
