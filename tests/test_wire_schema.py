"""Wire schema lockfile contract (ref: ceph-dencoder +
ceph-object-corpus pinning encodings across releases).

tests/fixtures/wire_schema.json pins name/(version, compat)/field
lists for every registered wire struct.  The static half (cephck
wire-drift) catches drift at lint time in msg/messages.py; this is
the runtime half: the LIVE registry must match the lockfile exactly,
for every struct — including the non-message ones (osdmap, crush,
fsmap) the AST rule can't see.
"""
import json
import pathlib

import pytest

from ceph_tpu.msg import encoding as wire
from ceph_tpu.msg.messages import SnapTrim, SnapTrimPurged, SnapTrimReply

LOCKFILE = pathlib.Path(__file__).resolve().parent / "fixtures" / \
    "wire_schema.json"


@pytest.fixture(scope="module")
def lockfile() -> dict:
    wire.ensure_registered()
    return json.loads(LOCKFILE.read_text())["structs"]


def test_registry_matches_lockfile(lockfile):
    live = wire.registered_schema()
    assert set(live) == set(lockfile), (
        "registered struct set drifted from the lockfile — for an "
        "INTENTIONAL wire change run scripts/gen_wire_schema.py and "
        "commit the diff")
    for name, got in live.items():
        assert got == lockfile[name], (
            f"{name}: schema drifted from the lockfile "
            f"(got {got}, pinned {lockfile[name]}) — bump the version "
            f"and regenerate via scripts/gen_wire_schema.py if this "
            f"evolution is deliberate")


def test_compat_never_exceeds_version(lockfile):
    for name, s in lockfile.items():
        assert s["compat"] <= s["version"], name


@pytest.mark.parametrize("msg", [
    SnapTrim(pgid=(3, 7), tid=42, oid="rbd_data.1", snap=5, clone=4,
             from_osd=2),
    SnapTrimReply(pgid=(3, 7), tid=42, from_osd=1, committed=True),
    SnapTrimPurged(pgid=(3, 7), snaps=[4, 5], from_osd=0),
], ids=lambda m: type(m).__name__)
def test_snaptrim_messages_roundtrip_and_match_lockfile(msg, lockfile):
    """The PR 2 snaptrim trio: frame round-trip is byte-faithful and
    the encoded field order is exactly the lockfile's."""
    back = wire.decode_message(wire.encode_message(msg))
    assert type(back) is type(msg)
    pinned = [f["name"] for f in lockfile[type(msg).__name__]["fields"]]
    for name in pinned:
        assert getattr(back, name) == getattr(msg, name), name
    # and the live registration exposes that same order
    live = wire.registered_schema()[type(msg).__name__]
    assert [f["name"] for f in live["fields"]] == pinned
