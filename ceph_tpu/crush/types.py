"""CRUSH map data model.

Python rendering of the crush_map structures (ref: src/crush/crush.h:
crush_bucket :229, crush_rule/crush_rule_step :44-97, crush_map :425-521).
Buckets are identified by negative ids (-1-index into buckets[]); devices by
non-negative ids.  Weights are 16.16 fixed point.
"""
from __future__ import annotations

from dataclasses import dataclass, field

# bucket algorithms (crush.h:140-190)
CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

# rule step opcodes (crush.h:52-69)
CRUSH_RULE_NOOP = 0
CRUSH_RULE_TAKE = 1
CRUSH_RULE_CHOOSE_FIRSTN = 2
CRUSH_RULE_CHOOSE_INDEP = 3
CRUSH_RULE_EMIT = 4
CRUSH_RULE_CHOOSELEAF_FIRSTN = 6
CRUSH_RULE_CHOOSELEAF_INDEP = 7
CRUSH_RULE_SET_CHOOSE_TRIES = 8
CRUSH_RULE_SET_CHOOSELEAF_TRIES = 9
CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES = 10
CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
CRUSH_RULE_SET_CHOOSELEAF_VARY_R = 12
CRUSH_RULE_SET_CHOOSELEAF_STABLE = 13

# sentinels (crush.h:33-37)
CRUSH_ITEM_UNDEF = 0x7FFFFFFE
CRUSH_ITEM_NONE = 0x7FFFFFFF

CRUSH_MAX_DEPTH = 10
CRUSH_HASH_RJENKINS1 = 0


@dataclass
class CrushBucket:
    id: int                     # negative
    type: int                   # bucket type id (host/rack/... from type map)
    alg: int = CRUSH_BUCKET_STRAW2
    hash: int = CRUSH_HASH_RJENKINS1
    weight: int = 0             # 16.16 total weight
    items: list[int] = field(default_factory=list)
    item_weights: list[int] = field(default_factory=list)  # 16.16
    # tree-bucket node weights (crush.h:318-321); built on demand
    node_weights: list[int] | None = None

    @property
    def size(self) -> int:
        return len(self.items)


@dataclass
class CrushRuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class CrushRuleMask:
    ruleset: int = 0
    type: int = 1               # pg_pool type: 1=replicated, 3=erasure
    min_size: int = 1
    max_size: int = 10


@dataclass
class CrushRule:
    steps: list[CrushRuleStep] = field(default_factory=list)
    mask: CrushRuleMask = field(default_factory=CrushRuleMask)


@dataclass
class ChooseArg:
    """choose_args override for one bucket (crush.h:281-295):
    optional id remap + per-position weight sets."""
    ids: list[int] | None = None
    weight_set: list[list[int]] | None = None   # [position][item] 16.16


@dataclass
class CrushMap:
    buckets: list[CrushBucket | None] = field(default_factory=list)
    rules: list[CrushRule | None] = field(default_factory=list)
    max_devices: int = 0
    # tunables (jewel profile defaults, ref: CrushWrapper.h:186-194)
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1
    # choose_args sets: name -> {bucket_id: ChooseArg}
    choose_args: dict = field(default_factory=dict)

    # choose_args fallback key (CrushWrapper.h:61)
    DEFAULT_CHOOSE_ARGS = -1

    @property
    def max_buckets(self) -> int:
        return len(self.buckets)

    def find_rule(self, ruleset: int, type_: int, size: int) -> int:
        """First rule whose mask matches (ref: crush_find_rule
        src/crush/mapper.c:41-54); -1 when none."""
        for i, r in enumerate(self.rules):
            if r is not None and r.mask.ruleset == ruleset and \
                    r.mask.type == type_ and \
                    r.mask.min_size <= size <= r.mask.max_size:
                return i
        return -1

    def choose_args_get_with_fallback(self, index):
        """choose_args for index, falling back to DEFAULT_CHOOSE_ARGS
        (ref: CrushWrapper.h:1438-1449)."""
        args = self.choose_args.get(index)
        if args is None:
            args = self.choose_args.get(self.DEFAULT_CHOOSE_ARGS)
        return args

    def bucket(self, item_id: int) -> CrushBucket | None:
        idx = -1 - item_id
        if 0 <= idx < len(self.buckets):
            return self.buckets[idx]
        return None

    def add_bucket(self, bucket: CrushBucket) -> int:
        if bucket.id is None or bucket.id >= 0:
            bucket.id = -1 - len(self.buckets)
            self.buckets.append(bucket)
        else:
            idx = -1 - bucket.id
            while len(self.buckets) <= idx:
                self.buckets.append(None)
            self.buckets[idx] = bucket
        return bucket.id

    def set_tunables_profile(self, profile: str) -> None:
        """argonaut/bobtail/firefly/hammer/jewel
        (ref: CrushWrapper.h:146-194)."""
        vals = {
            "argonaut": (2, 5, 19, 0, 0, 0),
            "bobtail": (0, 0, 50, 1, 0, 0),
            "firefly": (0, 0, 50, 1, 1, 0),
            "hammer": (0, 0, 50, 1, 1, 0),
            "jewel": (0, 0, 50, 1, 1, 1),
        }[profile]
        (self.choose_local_tries, self.choose_local_fallback_tries,
         self.choose_total_tries, self.chooseleaf_descend_once,
         self.chooseleaf_vary_r, self.chooseleaf_stable) = vals


# wire registration (ref: CrushWrapper::encode versions the crush map
# on the wire; here each struct is a versioned wire struct)
from ..msg.encoding import register_struct as _reg  # noqa: E402

for _cls in (CrushBucket, CrushRuleStep, CrushRuleMask, CrushRule,
             ChooseArg, CrushMap):
    _reg(_cls, version=1, compat=1)
