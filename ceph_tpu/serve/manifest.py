"""Artifact manifest: the durable catalog of a paged artifact.

An artifact (a model checkpoint, a KV-cache block pool) is a set of
named **shards**, each a sequence of fixed-size **pages** striped
over RADOS objects by the osdc Striper.  Ragged pages (the tail of a
checkpoint shard, short KV blocks) are carried byte-exact via
per-page valid lengths — the page GRID stays uniform so the layout
math stays uniform, only the byte counts differ (the same trick the
ObjectCacher's per-page vlen plays; ref: src/osdc/ObjectCacher.h
byte-granular BufferHeads).

The manifest itself is one JSON object (`<name>.manifest`) written
LAST by put(): data objects are epoch-versioned
(`<name>.e<epoch>.<shard>.<objectno:016x>`) and never overwritten, so
the manifest flip is the commit point and readers holding an older
manifest keep reading consistent bytes mid-republish.
"""
from __future__ import annotations

import json

from dataclasses import dataclass, field

from ..osdc.striper import ObjectExtent, StripeLayout, Striper

#: current manifest encoding version (bump on incompatible change)
MANIFEST_VERSION = 1


def manifest_oid(name: str) -> str:
    return f"{name}.manifest"


def data_oid(name: str, epoch: int, shard: str, objectno: int) -> str:
    """Epoch-versioned data object name: a re-put writes a fresh
    epoch's objects and flips the manifest, never overwriting live
    ones (which is what makes unordered page reads safe)."""
    return f"{name}.e{epoch}.{shard}.{objectno:016x}"


@dataclass
class ShardInfo:
    """One shard's page accounting.

    `vlens` holds ONLY the ragged pages (valid length < page_size);
    absent pages are full.  `size` is the shard's total valid bytes
    (== sum of per-page valid lengths).
    """
    n_pages: int
    size: int
    vlens: dict[int, int] = field(default_factory=dict)

    def vlen(self, page_id: int, page_size: int) -> int:
        return self.vlens.get(page_id, page_size)

    def to_json(self) -> dict:
        return {"n_pages": self.n_pages, "size": self.size,
                "vlens": {str(k): v for k, v in
                          sorted(self.vlens.items())}}

    @classmethod
    def from_json(cls, d: dict) -> "ShardInfo":
        return cls(n_pages=int(d["n_pages"]), size=int(d["size"]),
                   vlens={int(k): int(v)
                          for k, v in d.get("vlens", {}).items()})


@dataclass
class ArtifactManifest:
    name: str
    epoch: int
    page_size: int
    layout: StripeLayout
    shards: dict[str, ShardInfo]

    def to_json(self) -> bytes:
        return json.dumps({
            "version": MANIFEST_VERSION,
            "name": self.name,
            "epoch": self.epoch,
            "page_size": self.page_size,
            "layout": {"stripe_unit": self.layout.stripe_unit,
                       "stripe_count": self.layout.stripe_count,
                       "object_size": self.layout.object_size},
            "shards": {s: si.to_json()
                       for s, si in sorted(self.shards.items())},
        }, sort_keys=True).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ArtifactManifest":
        d = json.loads(raw.decode())
        ver = int(d.get("version", 0))
        if ver > MANIFEST_VERSION:
            raise ValueError(f"manifest version {ver} from the future")
        lay = d["layout"]
        return cls(
            name=d["name"], epoch=int(d["epoch"]),
            page_size=int(d["page_size"]),
            layout=StripeLayout(stripe_unit=int(lay["stripe_unit"]),
                                stripe_count=int(lay["stripe_count"]),
                                object_size=int(lay["object_size"])),
            shards={s: ShardInfo.from_json(si)
                    for s, si in d["shards"].items()})

    # ---------------------------------------------------- layout math
    def page_extents(self, shard: str, page_id: int
                     ) -> list[ObjectExtent]:
        """Object extents holding page `page_id`'s VALID bytes.  Page
        p lives at logical [p*page_size, p*page_size + vlen) of the
        shard's striped address space; a ragged page simply maps to
        shorter extents (the grid slot past vlen is never stored)."""
        si = self.shards[shard]
        if not 0 <= page_id < si.n_pages:
            raise IndexError(
                f"page {page_id} out of range (shard {shard!r} has "
                f"{si.n_pages} pages)")
        v = si.vlen(page_id, self.page_size)
        if v == 0:
            return []
        return Striper.file_to_extents(
            self.layout, page_id * self.page_size, v)

    def shard_objects(self, shard: str) -> list[int]:
        """All objectnos a shard's pages touch (delete/cleanup set)."""
        si = self.shards[shard]
        objs: set[int] = set()
        for p in range(si.n_pages):
            for ext in self.page_extents(shard, p):
                objs.add(ext.objectno)
        return sorted(objs)

    def data_oids(self) -> list[str]:
        return [data_oid(self.name, self.epoch, shard, objno)
                for shard in sorted(self.shards)
                for objno in self.shard_objects(shard)]


def paginate(data: bytes, page_size: int) -> tuple[int, int,
                                                   dict[int, int]]:
    """Stream -> (n_pages, size, ragged vlens): every page full
    except a ragged tail when len(data) is not page-aligned."""
    size = len(data)
    n_pages = max(1, -(-size // page_size))
    vlens: dict[int, int] = {}
    tail = size - (n_pages - 1) * page_size
    if tail != page_size:
        vlens[n_pages - 1] = tail
    return n_pages, size, vlens


def shard_from_pages(pages: list[bytes], page_size: int) -> ShardInfo:
    """Explicit page list (KV-cache blocks): any page may be ragged,
    each carried byte-exact via its valid length."""
    vlens: dict[int, int] = {}
    size = 0
    for i, pg in enumerate(pages):
        if len(pg) > page_size:
            raise ValueError(
                f"page {i}: {len(pg)} bytes > page_size {page_size}")
        size += len(pg)
        if len(pg) != page_size:
            vlens[i] = len(pg)
    return ShardInfo(n_pages=len(pages), size=size, vlens=vlens)
