"""Versioned wire encoding: TLV codec, ENCODE_START semantics, frame
integrity, and the committed corpus pin (ref: src/include/encoding.h,
src/msg/async/frames_v2.h, src/tools/ceph-dencoder +
ceph-object-corpus)."""
import dataclasses
import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.msg import encoding as wire
from ceph_tpu.tools import dencoder

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


# ------------------------------------------------------------ TLV core

@pytest.mark.parametrize("val", [
    None, True, False, 0, 1, -1, 127, 128, -12345678901234567890,
    2**200, 0.0, -1.5, float("inf"), "", "héllo", b"", b"\x00\xff",
    [], [1, "a", None], (1, (2, 3)), {"k": 1, 2: "v", (3, 4): b"x"},
    {1, 2, 3}, frozenset({"a"}), [{"deep": [(1, {"er": b"b"})]}],
])
def test_tlv_roundtrip(val):
    assert wire.decode(wire.encode(val)) == val


def test_ndarray_roundtrip():
    for arr in (np.arange(12, dtype=np.uint8).reshape(3, 4),
                np.array([1.5, -2.5], dtype=np.float32),
                np.zeros((0, 3), dtype=np.int64)):
        back = wire.decode(wire.encode(arr))
        assert back.dtype == arr.dtype and back.shape == arr.shape
        assert (back == arr).all()


def test_object_dtype_rejected():
    with pytest.raises(wire.WireError):
        wire.encode(np.array([object()], dtype=object))


def test_unregistered_type_rejected():
    class Rogue:
        pass
    with pytest.raises(wire.WireError, match="not wire-registered"):
        wire.encode(Rogue())


def test_depth_limit():
    bomb = []
    cur = bomb
    for _ in range(wire.MAX_DEPTH + 2):
        nxt = []
        cur.append(nxt)
        cur = nxt
    with pytest.raises(wire.WireError, match="deep"):
        wire.encode(bomb)
    # hand-crafted deep bytes must not blow the decoder's stack either
    deep = b"\x07\x01" * (wire.MAX_DEPTH + 2)
    with pytest.raises(wire.WireError):
        wire.decode(deep + b"\x00")


def test_truncated_rejected():
    blob = wire.encode({"k": [1, 2, 3]})
    for cut in (1, len(blob) // 2, len(blob) - 1):
        with pytest.raises(wire.WireError):
            wire.decode(blob[:cut])
    with pytest.raises(wire.WireError, match="trailing"):
        wire.decode(blob + b"\x00")


# ---------------------------------------------- ENCODE_START semantics

@dataclasses.dataclass
class _EvoV1:
    a: int = 0
    b: str = ""
    c: int = 99          # default fills the gap when decoding v0 bytes


@dataclasses.dataclass
class _EvoV2:
    a: int = 0
    b: str = ""
    c: int = 0
    d: list = dataclasses.field(default_factory=list)


@pytest.fixture
def evo_registry():
    """Register under a scratch name; restore the registry after."""
    saved_name = dict(wire._by_name)
    saved_cls = dict(wire._by_cls)
    yield
    wire._by_name.clear()
    wire._by_name.update(saved_name)
    wire._by_cls.clear()
    wire._by_cls.update(saved_cls)


def test_newer_writer_older_reader(evo_registry):
    """v2 bytes decode on a v1 reader: known prefix read, tail skipped
    via the ENCODE_START length (ref: encoding.h DECODE_FINISH)."""
    wire.register_struct(_EvoV2, name="EvoTest", version=2, compat=1)
    blob = wire.encode(_EvoV2(a=5, b="x", c=7, d=[1, 2]))
    # swap in the v1 implementation under the same wire name
    del wire._by_name["EvoTest"]
    del wire._by_cls[_EvoV2]
    wire.register_struct(_EvoV1, name="EvoTest", version=1, compat=1)
    got = wire.decode(blob)
    assert isinstance(got, _EvoV1)
    assert (got.a, got.b, got.c) == (5, "x", 7)


def test_older_writer_newer_reader(evo_registry):
    """v1 bytes decode on a v2 reader: missing fields take defaults."""
    wire.register_struct(_EvoV1, name="EvoTest", version=1, compat=1)
    blob = wire.encode(_EvoV1(a=3, b="y", c=1))
    del wire._by_name["EvoTest"]
    del wire._by_cls[_EvoV1]
    wire.register_struct(_EvoV2, name="EvoTest", version=2, compat=1)
    got = wire.decode(blob)
    assert isinstance(got, _EvoV2)
    assert (got.a, got.b, got.c, got.d) == (3, "y", 1, [])


def test_compat_rejection(evo_registry):
    """A struct whose compat exceeds the reader's version must refuse
    to decode (ref: DECODE_START struct_compat check)."""
    wire.register_struct(_EvoV2, name="EvoTest", version=3, compat=3)
    blob = wire.encode(_EvoV2(a=1))
    del wire._by_name["EvoTest"]
    del wire._by_cls[_EvoV2]
    wire.register_struct(_EvoV1, name="EvoTest", version=1, compat=1)
    with pytest.raises(wire.WireError, match="requires decoder"):
        wire.decode(blob)


def test_unknown_struct_rejected():
    @dataclasses.dataclass
    class _Ghost:
        x: int = 0
    saved = dict(wire._by_name), dict(wire._by_cls)
    wire.register_struct(_Ghost, name="GhostStruct")
    blob = wire.encode(_Ghost(x=1))
    wire._by_name.clear()
    wire._by_name.update(saved[0])
    wire._by_cls.clear()
    wire._by_cls.update(saved[1])
    with pytest.raises(wire.WireError, match="unknown wire struct"):
        wire.decode(blob)


# ------------------------------------------------------ message frames

def test_frame_roundtrip_and_tamper():
    from ceph_tpu.msg.messages import OSDOp
    msg = OSDOp(oid="o", op="write", data=b"abc", tid=4)
    frame = wire.encode_message(msg)
    assert wire.decode_message(frame) == msg
    # flip one payload byte: crc catches it
    bad = bytearray(frame)
    bad[len(frame) // 2] ^= 0x40
    with pytest.raises(wire.WireError):
        wire.decode_message(bytes(bad))
    # bad magic
    with pytest.raises(wire.WireError, match="magic"):
        wire.decode_message(b"XXXX" + frame[4:])
    # truncated
    with pytest.raises(wire.WireError):
        wire.decode_message(frame[:-2])


def test_frame_payload_must_be_struct():
    payload = wire.encode(42)
    from ceph_tpu.common.crc32c import crc32c
    import struct
    frame = struct.pack("!4sBI", wire.MAGIC, 0, len(payload)) + \
        payload + struct.pack("!I", crc32c(0, payload))
    with pytest.raises(wire.WireError, match="not a struct"):
        wire.decode_message(frame)


# ------------------------------------------------------------- corpus

def _corpus() -> dict:
    with open(FIXTURES / "wire_corpus.json") as f:
        return json.load(f)


def test_corpus_covers_all_types():
    corpus = _corpus()
    missing = [n for n in dencoder.sample_names() if n not in corpus]
    assert not missing, (
        f"wire types without corpus entries: {missing} — run "
        "scripts/gen_wire_corpus.py and commit the result")


def test_corpus_byte_stable():
    """Every type's canonical sample must encode to the committed
    bytes — encoding drift across rounds is a wire-compat break
    (ref: ceph-object-corpus non-regression)."""
    corpus = _corpus()
    drifted = []
    for name, hexblob in corpus.items():
        got = wire.encode(dencoder.sample(name)).hex()
        if got != hexblob:
            drifted.append(name)
    assert not drifted, (
        f"wire encoding drifted for {drifted}; if deliberate, bump the "
        "struct version and regenerate scripts/gen_wire_corpus.py")


def test_corpus_decodes():
    """Committed bytes must keep decoding (old writers stay readable),
    and re-encoding the decoded object must be stable."""
    corpus = _corpus()
    for name, hexblob in corpus.items():
        blob = bytes.fromhex(hexblob)
        obj = wire.decode(blob)
        assert wire.encode(obj) == blob, f"{name} re-encode differs"


def test_dencoder_cli(capsys):
    assert dencoder.main(["list"]) == 0
    out = capsys.readouterr().out.splitlines()
    assert "OSDMap" in out and "OSDOp" in out
    assert dencoder.main(["roundtrip", "MMap"]) == 0
    assert dencoder.main(["encode", "PG"]) == 0
    hexblob = capsys.readouterr().out.strip().splitlines()[-1]
    assert dencoder.main(["decode", "PG", hexblob]) == 0
    assert dencoder.main(["decode", "OSDOp", hexblob]) == 1
