"""cls version: object version gating used by rgw metadata
(ref: src/cls/version/cls_version.cc).  Version in a `cls_version`
xattr; conditional ops fail ECANCELED on mismatch like the
reference's VER_COND checks."""
from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, cls_method

_ATTR = "cls_version"


def _load(ctx) -> dict:
    try:
        return json.loads(ctx.getxattr(_ATTR))
    except ClsError:
        return {"ver": 0, "tag": ""}


def _store(ctx, v: dict) -> None:
    ctx.setxattr(_ATTR, json.dumps(v).encode())


@cls_method("version", "set", CLS_METHOD_WR)
def set_(ctx, ind):
    """(ref: cls_version.cc cls_version_set)."""
    _store(ctx, {"ver": int(ind["ver"]), "tag": ind.get("tag", "")})
    return None


@cls_method("version", "inc", CLS_METHOD_RD | CLS_METHOD_WR)
def inc(ctx, ind):
    """Bump; with `cond`+`ver` given, gate first
    (ref: cls_version_inc_conds)."""
    v = _load(ctx)
    if "cond" in ind:
        _check(v, ind)
    v["ver"] += 1
    _store(ctx, v)
    return None


@cls_method("version", "read", CLS_METHOD_RD)
def read(ctx, ind):
    """(ref: cls_version_read)."""
    return _load(ctx)


@cls_method("version", "check", CLS_METHOD_RD)
def check(ctx, ind):
    """Fail ECANCELED unless the stored version satisfies the
    condition (ref: cls_version.cc cls_version_check)."""
    _check(_load(ctx), ind)
    return None


def _check(v: dict, ind) -> None:
    ver, cond = int(ind["ver"]), ind.get("cond", "eq")
    ok = {"eq": v["ver"] == ver, "gt": v["ver"] > ver,
          "ge": v["ver"] >= ver}.get(cond)
    if ok is None:
        raise ClsError("EINVAL", f"cond {cond}")
    if not ok:
        raise ClsError("ECANCELED",
                       f"version {v['ver']} fails {cond} {ver}")
