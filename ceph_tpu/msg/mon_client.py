"""MonHunter: shared mon-session failover for daemons/clients.

The MonClient hunting behavior (ref: src/mon/MonClient.cc
reopen_session / _reopen_session rank rotation): an entity holds a mon
list, talks to one, and on a connection reset rotates to the next,
re-sending its session greeting (subscription/boot).  The walk is
iterative — a hunt send to another dead mon reports its reset
synchronously and must not recurse.
"""
from __future__ import annotations

from ..common.backoff import Backoff
from ..common.log import dout


class MonHunter:
    """Mixin; the host class must expose `self.ms` and override
    `_hunt_greeting()` with the session (re)establishment messages.

    A lap that reaches NO mon at all (every greeting send failed —
    the whole quorum dead or partitioned away) arms a capped
    exponential backoff: further resets inside the window are
    absorbed instead of re-walking the ring, so an unreachable quorum
    costs a handful of greetings per window rather than a greeting
    storm per dropped message (the chaos harness's mon-partition
    schedules hit exactly this)."""

    #: full-lap failure pacing (wall-clock; resets on any success)
    HUNT_BACKOFF_BASE_S = 0.05
    HUNT_BACKOFF_CAP_S = 2.0

    def _init_mons(self, mon) -> None:
        self.mons = [mon] if isinstance(mon, str) else list(mon)
        self._mon_i = 0
        self._mon_hunting = False
        self._hunt_backoff = Backoff(base_s=self.HUNT_BACKOFF_BASE_S,
                                     cap_s=self.HUNT_BACKOFF_CAP_S,
                                     jitter=False)

    @property
    def mon(self) -> str:
        return self.mons[self._mon_i]

    def _hunt_greeting(self) -> list:
        """Messages that re-establish the session at a new mon."""
        return []

    def _maybe_hunt(self, peer: str) -> bool:
        """Handle a reset of our current mon; True when it was ours
        (hunted, paced out, or nothing else to do)."""
        if peer != self.mon:
            return False
        if len(self.mons) <= 1 or self._mon_hunting:
            return True
        if not self._hunt_backoff.ready():
            return True         # all-mons-dead window: stay put
        self._mon_hunting = True
        reached = False
        try:
            for _ in range(len(self.mons) - 1):
                self._mon_i = (self._mon_i + 1) % len(self.mons)
                dout("ms", 1).write("%s: mon hunt -> %s",
                                    getattr(self, "name", "?"), self.mon)
                msgs = self._hunt_greeting()
                if not msgs:
                    reached = True
                    break
                if self.ms.connect(self.mon).send_message(msgs[0]):
                    for m in msgs[1:]:
                        self.ms.connect(self.mon).send_message(m)
                    reached = True
                    break
        finally:
            self._mon_hunting = False
        if reached:
            self._hunt_backoff.reset()
        else:
            self._hunt_backoff.fail()
        return True
