"""FSMap: the filesystem's MDS cluster map.

The src/mds/FSMap.h analogue, reduced to one filesystem: which daemon
(gid) holds each rank and in what state, plus the standby pool the
monitor promotes from.  Rank states walk the takeover ladder

    standby -> replay -> resolve -> active

(ref: MDSMap::DAEMON_STATE STATE_STANDBY/STATE_REPLAY/STATE_RESOLVE/
STATE_ACTIVE); a rank whose daemon's beacon lapsed past
``mds_beacon_grace`` is marked ``failed`` until a standby takes it
over.  The map is a Paxos-committed value (see
ceph_tpu.mon.mds_monitor) published to subscribers as MFSMap
incref epochs, exactly the osdmap subscription shape.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..msg.encoding import register_struct

#: rank/daemon states (ref: src/mds/MDSMap.h DAEMON_STATE)
STATE_STANDBY = "standby"
STATE_REPLAY = "replay"
STATE_RESOLVE = "resolve"
STATE_ACTIVE = "active"
STATE_FAILED = "failed"


@dataclass
class MDSInfo:
    """One daemon's slot in the map (ref: MDSMap::mds_info_t)."""
    gid: int = 0
    name: str = ""           # messenger entity ("mds.0", "mds.sb1")
    rank: int = -1
    state: str = STATE_STANDBY
    #: standby-replay target (-1 = plain standby)
    standby_replay_rank: int = -1


@dataclass
class FSMap:
    """(ref: src/mds/FSMap.h, one-filesystem reduction)."""
    epoch: int = 0
    #: rank -> holder; a ``failed`` entry keeps the rank visible with
    #: gid 0 until a standby is assigned
    ranks: dict = field(default_factory=dict)
    #: gid -> MDSInfo waiting for promotion
    standbys: dict = field(default_factory=dict)

    # ------------------------------------------------------- queries
    def rank_state(self, rank: int) -> str | None:
        info = self.ranks.get(rank)
        return info.state if info is not None else None

    def rank_gid(self, rank: int) -> int:
        info = self.ranks.get(rank)
        return info.gid if info is not None else 0

    def is_active(self, rank: int) -> bool:
        return self.rank_state(rank) == STATE_ACTIVE

    def gid_info(self, gid: int) -> MDSInfo | None:
        for info in self.ranks.values():
            if info.gid == gid:
                return info
        return self.standbys.get(gid)

    def live_gids(self) -> set[int]:
        """gids the monitor expects beacons from."""
        out = {i.gid for i in self.ranks.values()
               if i.state != STATE_FAILED and i.gid}
        out.update(self.standbys)
        return out

    def pick_standby(self, rank: int) -> MDSInfo | None:
        """Promotion choice: a standby-replay follower of this rank
        wins (warm journal cursor), else any standby — lowest gid for
        determinism (ref: FSMap::find_replacement_for)."""
        best = None
        for gid in sorted(self.standbys):
            info = self.standbys[gid]
            if info.standby_replay_rank == rank:
                return info
            if best is None and info.standby_replay_rank < 0:
                best = info
        if best is None and self.standbys:
            best = self.standbys[min(self.standbys)]
        return best


register_struct(MDSInfo)
register_struct(FSMap)
