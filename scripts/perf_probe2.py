"""Single-dispatch throughput measurement (no async-queue ambiguity)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.ec import gf
from ceph_tpu.ec.kernels import bitmatmul

k, m = 8, 4
chunk = 128 * 1024
rng = np.random.default_rng(0)
mat = gf.isa_rs_matrix(k, m)[k:]
B = jnp.asarray(gf.expand_to_bitmatrix(mat).astype(np.int8))

for stripes in (64, 256, 512):
    data = jnp.asarray(rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))
    for label, fn in (("xla", bitmatmul.gf_matmul_xla),
                      ("pallas", bitmatmul.gf_matmul_pallas)):
        out = jax.block_until_ready(fn(B, data))  # warm compile
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(B, data)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        total_in = stripes * k * chunk
        total_out = stripes * m * chunk
        print(f"stripes={stripes:4d} {label:6s}: {dt*1e3:8.3f} ms  "
              f"in {total_in/dt/1e9:8.2f} GB/s  io {(total_in+total_out)/dt/1e9:8.2f} GB/s")
