"""OSDMap: the cluster map and the object→PG→OSD mapping pipeline.

Faithful re-implementation of the reference OSDMap placement path
(ref: src/osd/OSDMap.{h,cc}):

  object_locator_to_pg (OSDMap.cc:2183) → pg_to_up_acting_osds
  (OSDMap.cc:2462 _pg_to_up_acting_osds):
    _pg_to_raw_osds   (:2232 — pps seed + crush do_rule)
    _apply_upmap      (:2262 — pg_upmap / pg_upmap_items overrides)
    _raw_to_up_osds   (:2309 — drop or NONE down/dne osds)
    _pick_primary     (:2252)
    _apply_primary_affinity (:2334 — probabilistic primary rejection)
    pg_temp / primary_temp overrides (_get_temp_osds :2389)

State mutation is epoch-driven via Incremental deltas
(OSDMap::Incremental, src/osd/OSDMap.h:396), applied by
`apply_incremental`.  The batched full-cluster mapping (the
OSDMapMapping/ParallelPGMapper replacement) lives in
ceph_tpu.osd.mapping and uses the vmapped CRUSH engine.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field

from ..crush import mapper as crush_mapper
from ..crush.hashes import hash32_2
from ..crush.types import CRUSH_ITEM_NONE, CrushMap
from .types import PG, PGPool

# osd_state bits (src/include/rados.h:115-118)
CEPH_OSD_EXISTS = 1 << 0
CEPH_OSD_UP = 1 << 1
CEPH_OSD_AUTOOUT = 1 << 2
CEPH_OSD_NEW = 1 << 3

CEPH_OSD_IN = 0x10000
CEPH_OSD_OUT = 0
CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000


@dataclass
class Incremental:
    """OSDMap delta (ref: src/osd/OSDMap.h:396-550, subset)."""
    epoch: int = 0
    new_max_osd: int | None = None
    new_pools: dict[int, PGPool] = field(default_factory=dict)
    old_pools: list[int] = field(default_factory=list)
    new_pool_names: dict[int, str] = field(default_factory=dict)
    new_up_osds: list[int] = field(default_factory=list)
    new_down_osds: list[int] = field(default_factory=list)
    new_weight: dict[int, int] = field(default_factory=dict)
    new_state: dict[int, int] = field(default_factory=dict)  # xor bits
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[PG, list[int]] = field(default_factory=dict)
    new_primary_temp: dict[PG, int] = field(default_factory=dict)
    new_pg_upmap: dict[PG, list[int]] = field(default_factory=dict)
    old_pg_upmap: list[PG] = field(default_factory=list)
    new_pg_upmap_items: dict[PG, list[tuple[int, int]]] = \
        field(default_factory=dict)
    old_pg_upmap_items: list[PG] = field(default_factory=list)
    new_crush: CrushMap | None = None
    new_erasure_code_profiles: dict[str, dict] = field(default_factory=dict)
    old_erasure_code_profiles: list[str] = field(default_factory=list)


class OSDMap:
    """The cluster map (ref: src/osd/OSDMap.h:180)."""

    def __init__(self) -> None:
        self.epoch = 0
        self.fsid = ""
        self.max_osd = 0
        self.osd_state: list[int] = []
        self.osd_weight: list[int] = []          # 16.16; 0x10000 = in
        self.osd_primary_affinity: list[int] | None = None
        self.pools: dict[int, PGPool] = {}
        self.pool_names: dict[int, str] = {}
        self.pool_max = -1
        self.crush = CrushMap()
        self.pg_upmap: dict[PG, list[int]] = {}
        self.pg_upmap_items: dict[PG, list[tuple[int, int]]] = {}
        self.pg_temp: dict[PG, list[int]] = {}
        self.primary_temp: dict[PG, int] = {}
        self.erasure_code_profiles: dict[str, dict] = {}
        self.flags = 0

    # ------------------------------------------------------------------
    # osd state queries (OSDMap.h:710-760)
    def set_max_osd(self, n: int) -> None:
        while len(self.osd_state) < n:
            self.osd_state.append(0)
            self.osd_weight.append(CEPH_OSD_OUT)
            if self.osd_primary_affinity is not None:
                self.osd_primary_affinity.append(
                    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
        del self.osd_state[n:]
        del self.osd_weight[n:]
        if self.osd_primary_affinity is not None:
            del self.osd_primary_affinity[n:]
        self.max_osd = n

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and \
            bool(self.osd_state[osd] & CEPH_OSD_EXISTS)

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and bool(self.osd_state[osd] & CEPH_OSD_UP)

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_out(self, osd: int) -> bool:
        return not self.exists(osd) or self.osd_weight[osd] == CEPH_OSD_OUT

    def is_in(self, osd: int) -> bool:
        return not self.is_out(osd)

    def get_primary_affinity(self, osd: int) -> int:
        if self.osd_primary_affinity is None:
            return CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
        return self.osd_primary_affinity[osd]

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = \
                [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * self.max_osd
        self.osd_primary_affinity[osd] = aff

    def get_pg_pool(self, pool_id: int) -> PGPool | None:
        return self.pools.get(pool_id)

    # ------------------------------------------------------------------
    # object → pg
    def object_locator_to_pg(self, name: str, pool_id: int,
                             key: str = "", nspace: str = "") -> PG:
        """OSDMap.cc:2163-2194 (map_to_pg)."""
        pool = self.pools.get(pool_id)
        if pool is None:
            raise KeyError(f"no pool {pool_id}")
        ps = pool.hash_key(key or name, nspace)
        return PG(pool_id, ps)

    # ------------------------------------------------------------------
    # pg → osds pipeline
    def _pg_to_raw_osds(self, pool: PGPool, pg: PG) -> tuple[list[int], int]:
        """OSDMap.cc:2232-2250: pps seed, rule mask resolution, crush,
        drop nonexistent.  choose_args are looked up by pool id with the
        default fallback (CrushWrapper::do_rule →
        choose_args_get_with_fallback, CrushWrapper.h:1574)."""
        pps = pool.raw_pg_to_pps(pg)
        ruleno = self.crush.find_rule(pool.crush_rule, pool.type, pool.size)
        osds: list[int] = []
        if ruleno >= 0:
            osds = crush_mapper.do_rule(
                self.crush, ruleno, pps, pool.size, self.osd_weight,
                choose_args=self.crush.choose_args_get_with_fallback(
                    pg.pool))
        self._remove_nonexistent_osds(pool, osds)
        return osds, pps

    def _remove_nonexistent_osds(self, pool: PGPool,
                                 osds: list[int]) -> None:
        """OSDMap.cc:2208-2230."""
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if o != CRUSH_ITEM_NONE and not self.exists(o):
                    osds[i] = CRUSH_ITEM_NONE

    def _apply_upmap(self, pool: PGPool, raw_pg: PG,
                     raw: list[int]) -> None:
        """OSDMap.cc:2262-2307."""
        pg = pool.raw_pg_to_pg(raw_pg)
        explicit = self.pg_upmap.get(pg)
        if explicit is not None:
            for osd in explicit:
                if osd != CRUSH_ITEM_NONE and 0 <= osd < self.max_osd and \
                        self.osd_weight[osd] == 0:
                    # target marked out: reject the whole upmap,
                    # including any pg_upmap_items (OSDMap.cc:2271 return)
                    return
            raw[:] = list(explicit)
        items = self.pg_upmap_items.get(pg)
        if items is not None:
            for frm, to in items:
                exists = False
                pos = -1
                for i, osd in enumerate(raw):
                    if osd == to:
                        exists = True
                        break
                    if osd == frm and pos < 0 and not (
                            to != CRUSH_ITEM_NONE and 0 <= to < self.max_osd
                            and self.osd_weight[to] == 0):
                        pos = i
                if not exists and pos >= 0:
                    raw[pos] = to

    def _raw_to_up_osds(self, pool: PGPool, raw: list[int]) -> list[int]:
        """OSDMap.cc:2309-2332."""
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and self.is_up(o)]
        return [o if (o != CRUSH_ITEM_NONE and self.exists(o)
                      and self.is_up(o)) else CRUSH_ITEM_NONE
                for o in raw]

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        """OSDMap.cc:2252-2260."""
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, seed: int, pool: PGPool,
                                osds: list[int], primary: int) -> int:
        """OSDMap.cc:2334-2387; returns the (possibly new) primary."""
        if self.osd_primary_affinity is None:
            return primary
        if not any(o != CRUSH_ITEM_NONE and
                   self.osd_primary_affinity[o] !=
                   CEPH_OSD_DEFAULT_PRIMARY_AFFINITY for o in osds):
            return primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = self.osd_primary_affinity[o]
            if a < CEPH_OSD_MAX_PRIMARY_AFFINITY and \
                    (int(hash32_2(seed, o)) >> 16) >= a:
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            # move the new primary to the front
            for i in range(pos, 0, -1):
                osds[i] = osds[i - 1]
            osds[0] = primary
        return primary

    def _get_temp_osds(self, pool: PGPool, pg: PG) -> tuple[list[int], int]:
        """OSDMap.cc:2389-2420."""
        pg = pool.raw_pg_to_pg(pg)
        temp_pg: list[int] = []
        for o in self.pg_temp.get(pg, []):
            if not self.exists(o) or self.is_down(o):
                if pool.can_shift_osds():
                    continue
                temp_pg.append(CRUSH_ITEM_NONE)
            else:
                temp_pg.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            for o in temp_pg:
                if o != CRUSH_ITEM_NONE:
                    temp_primary = o
                    break
        return temp_pg, temp_primary

    def pg_to_raw_osds(self, pg: PG) -> tuple[list[int], int]:
        """OSDMap.cc:2422-2432; returns (raw, primary)."""
        pool = self.pools.get(pg.pool)
        if pool is None:
            return [], -1
        raw, _ = self._pg_to_raw_osds(pool, pg)
        return raw, self._pick_primary(raw)

    def pg_to_raw_upmap(self, pg: PG) -> list[int]:
        """Raw crush placement with pg_upmap/pg_upmap_items applied but
        no up-filtering (OSDMap.cc:2434) — the balancer's view of what
        the current overrides produce."""
        pool = self.pools.get(pg.pool)
        if pool is None:
            return []
        raw, _ = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        return raw

    def pg_to_up_acting_osds(self, pg: PG, raw_pg_to_pg: bool = True) \
            -> tuple[list[int], int, list[int], int]:
        """OSDMap.cc:2462-2510 _pg_to_up_acting_osds; returns
        (up, up_primary, acting, acting_primary).  With raw_pg_to_pg
        (the default, like the reference) the ps may be a raw hash —
        every stage folds it; with False the ps must already be folded
        into [0, pg_num)."""
        pg = PG(pg.pool, pg.ps & 0xFFFFFFFF)  # ps_t is u32
        pool = self.pools.get(pg.pool)
        if pool is None or (not raw_pg_to_pg and pg.ps >= pool.pg_num):
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up_primary = self._apply_primary_affinity(pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    # ------------------------------------------------------------------
    # mutation
    def apply_incremental(self, inc: Incremental) -> None:
        """OSDMap.cc apply_incremental (subset, same semantics)."""
        if inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental epoch {inc.epoch} != {self.epoch}+1")
        self.epoch = inc.epoch
        if inc.new_crush is not None:
            self.crush = inc.new_crush
        if inc.new_max_osd is not None:
            self.set_max_osd(inc.new_max_osd)
        for pid, pool in inc.new_pools.items():
            self.pools[pid] = pool
            self.pool_max = max(self.pool_max, pid)
        for pid, name in inc.new_pool_names.items():
            self.pool_names[pid] = name
        for pid in inc.old_pools:
            self.pools.pop(pid, None)
            self.pool_names.pop(pid, None)
        for osd in inc.new_up_osds:
            self.osd_state[osd] |= CEPH_OSD_EXISTS | CEPH_OSD_UP
        for osd in inc.new_down_osds:
            self.osd_state[osd] &= ~CEPH_OSD_UP
        for osd, st in inc.new_state.items():
            self.osd_state[osd] ^= st
        for osd, w in inc.new_weight.items():
            self.osd_weight[osd] = w
            self.osd_state[osd] |= CEPH_OSD_EXISTS
        for osd, aff in inc.new_primary_affinity.items():
            self.set_primary_affinity(osd, aff)
        for pg, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pg] = list(osds)
            else:
                self.pg_temp.pop(pg, None)
        for pg, p in inc.new_primary_temp.items():
            if p >= 0:
                self.primary_temp[pg] = p
            else:
                self.primary_temp.pop(pg, None)
        for pg, osds in inc.new_pg_upmap.items():
            self.pg_upmap[pg] = list(osds)
        for pg in inc.old_pg_upmap:
            self.pg_upmap.pop(pg, None)
        for pg, items in inc.new_pg_upmap_items.items():
            self.pg_upmap_items[pg] = list(items)
        for pg in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pg, None)
        for name, profile in inc.new_erasure_code_profiles.items():
            self.erasure_code_profiles[name] = dict(profile)
        for name in inc.old_erasure_code_profiles:
            self.erasure_code_profiles.pop(name, None)

    def clone(self) -> "OSDMap":
        return copy.deepcopy(self)

    def ingest(self, full_map: "OSDMap | None",
               incrementals: list) -> "OSDMap":
        """Apply a map publish (full and/or incrementals) and return
        the resulting map — newer full maps replace, stale ones are
        ignored, incs apply in epoch order.  Shared by the OSD daemon
        and the Objecter (ref: OSD.cc handle_osd_map :8010,
        Objecter.cc handle_osd_map :1182)."""
        m = self
        if full_map is not None and full_map.epoch > m.epoch:
            m = full_map
        for inc in incrementals:
            if inc.epoch == m.epoch + 1:
                m.apply_incremental(inc)
        return m

    # ------------------------------------------------------------------
    # convenience builders (vstart-style, for tests/tools)
    def build_simple(self, n_osd: int, pg_pool: PGPool | None = None,
                     osds_per_host: int = 4) -> None:
        """osdmaptool --createsimple equivalent: flat host/osd straw2
        tree + one replicated pool (ref: src/osd/OSDMap.cc
        build_simple/build_simple_crush_map)."""
        from ..crush.types import (CRUSH_BUCKET_STRAW2, CrushBucket,
                                   CrushRule, CrushRuleStep,
                                   CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                   CRUSH_RULE_EMIT, CRUSH_RULE_TAKE)
        self.set_max_osd(n_osd)
        m = CrushMap()
        m.set_tunables_profile("jewel")
        host_ids = []
        for base in range(0, n_osd, osds_per_host):
            items = list(range(base, min(base + osds_per_host, n_osd)))
            w = [0x10000] * len(items)
            host_ids.append(m.add_bucket(CrushBucket(
                id=0, type=1, alg=CRUSH_BUCKET_STRAW2, items=items,
                item_weights=w, weight=sum(w))))
        hw = [m.bucket(h).weight for h in host_ids]
        root = m.add_bucket(CrushBucket(
            id=0, type=10, alg=CRUSH_BUCKET_STRAW2, items=host_ids,
            item_weights=hw, weight=sum(hw)))
        m.max_devices = n_osd
        m.rules.append(CrushRule(steps=[
            CrushRuleStep(CRUSH_RULE_TAKE, root),
            CrushRuleStep(CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1),
            CrushRuleStep(CRUSH_RULE_EMIT),
        ]))
        self.crush = m
        for osd in range(n_osd):
            self.osd_state[osd] = CEPH_OSD_EXISTS | CEPH_OSD_UP
            self.osd_weight[osd] = CEPH_OSD_IN
        if pg_pool is None:
            pg_pool = PGPool(pg_num=max(64, n_osd * 4),
                             pgp_num=max(64, n_osd * 4))
        self.pools[0] = pg_pool
        self.pool_names[0] = "rbd"
        self.pool_max = 0
        self.epoch = 1


# ------------------------------------------------- wire registration
# OSDMap encodes as a versioned wire struct like the reference's
# OSDMap::encode (ref: src/osd/OSDMap.cc encode w/ ENCODE_START).
def _register_wire() -> None:
    from ..msg.encoding import register_struct
    register_struct(Incremental, version=1, compat=1)
    register_struct(OSDMap, version=1, compat=1, fields=(
        "epoch", "fsid", "max_osd", "osd_state", "osd_weight",
        "osd_primary_affinity", "pools", "pool_names", "pool_max",
        "crush", "pg_upmap", "pg_upmap_items", "pg_temp",
        "primary_temp", "erasure_code_profiles", "flags"))


_register_wire()
