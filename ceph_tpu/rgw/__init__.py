"""rgw-lite: S3-flavored object gateway over the RADOS client
(ref: src/rgw — radosgw's REST frontend + bucket-index-on-omap
data layout, radically reduced)."""
from .gateway import RGWGateway

__all__ = ["RGWGateway"]
