"""Extended librados op surface: append/truncate/zero/create, xattrs,
omap, and atomic compound WriteOps — replicated AND erasure-coded pools
(ref: src/osd/PrimaryLogPG.cc do_osd_ops op switch :5770;
src/include/rados.h CEPH_OSD_OP_*; librados op surface
src/librados/librados_cxx.cc).  Also: metadata survives recovery and
deep scrub detects metadata divergence."""
import numpy as np
import pytest

from ceph_tpu.client import RadosError, WriteOp
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=6, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("rp", pg_num=16, pool_type="replicated")
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k2m2",
                   "profile": {"plugin": "tpu", "k": "2", "m": "2",
                               "crush-failure-domain": "host"}})
    r.pool_create("ecp", pg_num=16, pool_type="erasure",
                  erasure_code_profile="k2m2")
    yield c, r
    c.shutdown()


@pytest.fixture(params=["rp", "ecp"])
def io(cluster, request):
    _, r = cluster
    return r.open_ioctx(request.param)


@pytest.fixture()
def rio(cluster):
    _, r = cluster
    return r.open_ioctx("rp")


def _oid(request_node_name, suffix=""):
    return request_node_name.replace("[", "_").replace("]", "") + suffix


# ------------------------------------------------------------ data ops

def test_append(io, request):
    oid = _oid(request.node.name)
    io.write_full(oid, b"abc")
    io.append(oid, b"defgh")
    assert io.read(oid) == b"abcdefgh"
    assert io.stat(oid)["size"] == 8


def test_append_creates(io, request):
    oid = _oid(request.node.name)
    io.append(oid, b"fresh")
    assert io.read(oid) == b"fresh"


def test_truncate_down_and_up(io, request):
    oid = _oid(request.node.name)
    rng = np.random.default_rng(3)
    payload = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    io.write_full(oid, payload)
    io.truncate(oid, 1234)
    assert io.read(oid) == payload[:1234]
    # extending truncate zero-fills (ref: CEPH_OSD_OP_TRUNCATE)
    io.truncate(oid, 2000)
    assert io.read(oid) == payload[:1234] + b"\0" * (2000 - 1234)
    assert io.stat(oid)["size"] == 2000


def test_truncate_to_zero(io, request):
    oid = _oid(request.node.name)
    io.write_full(oid, b"x" * 4096)
    io.truncate(oid, 0)
    assert io.read(oid) == b""
    assert io.stat(oid)["size"] == 0


def test_zero_within_and_past_size(io, request):
    oid = _oid(request.node.name)
    io.write_full(oid, b"\xaa" * 1000)
    io.zero(oid, 100, 200)
    data = io.read(oid)
    assert data[:100] == b"\xaa" * 100
    assert data[100:300] == b"\0" * 200
    assert data[300:] == b"\xaa" * 700
    # zero never extends (librados semantics)
    io.zero(oid, 900, 500)
    assert io.stat(oid)["size"] == 1000
    assert io.read(oid)[900:] == b"\0" * 100


def test_create_exclusive(io, request):
    oid = _oid(request.node.name)
    io.create(oid, exclusive=True)
    assert io.stat(oid)["size"] == 0
    with pytest.raises(RadosError, match="EEXIST"):
        io.create(oid, exclusive=True)
    io.create(oid)                       # non-exclusive: fine


def test_write_full_shrinks(io, request):
    """A shorter write_full leaves no tail of the longer old object."""
    oid = _oid(request.node.name)
    io.write_full(oid, b"L" * 9000)
    io.write_full(oid, b"s" * 10)
    assert io.read(oid) == b"s" * 10
    assert io.stat(oid)["size"] == 10


# ------------------------------------------------------------- xattrs

def test_xattr_roundtrip(io, request):
    oid = _oid(request.node.name)
    io.write_full(oid, b"body")
    io.set_xattr(oid, "user.k1", b"v1")
    io.set_xattr(oid, "user.k2", b"v2")
    assert io.get_xattr(oid, "user.k1") == b"v1"
    assert io.get_xattrs(oid) == {"user.k1": b"v1", "user.k2": b"v2"}
    io.rm_xattr(oid, "user.k1")
    assert io.get_xattrs(oid) == {"user.k2": b"v2"}
    with pytest.raises(RadosError, match="ENODATA"):
        io.get_xattr(oid, "user.k1")
    with pytest.raises(RadosError, match="ENODATA"):
        io.rm_xattr(oid, "user.k1")
    # body untouched by metadata ops
    assert io.read(oid) == b"body"


def test_xattr_on_missing_object(io, request):
    oid = _oid(request.node.name)
    with pytest.raises(RadosError, match="ENOENT"):
        io.get_xattr(oid, "a")
    # setxattr creates the object (any write-class op does)
    io.set_xattr(oid, "a", b"1")
    assert io.stat(oid)["size"] == 0
    assert io.get_xattr(oid, "a") == b"1"


# --------------------------------------------------------------- omap

def test_omap_roundtrip(rio, request):
    oid = _oid(request.node.name)
    rio.write_full(oid, b"")
    rio.set_omap(oid, {"b": b"2", "a": b"1", "c": b"3"})
    vals, more = rio.get_omap_vals(oid)
    assert vals == {"a": b"1", "b": b"2", "c": b"3"} and not more
    rio.remove_omap_keys(oid, ["b"])
    keys, _ = rio.get_omap_keys(oid)
    assert keys == ["a", "c"]
    assert rio.get_omap_vals_by_keys(oid, ["a", "zz"]) == {"a": b"1"}
    rio.set_omap_header(oid, b"HDR")
    assert rio.get_omap_header(oid) == b"HDR"
    rio.clear_omap(oid)
    assert rio.get_omap_vals(oid)[0] == {}
    assert rio.get_omap_header(oid) == b""


def test_omap_pagination(rio, request):
    oid = _oid(request.node.name)
    rio.set_omap(oid, {f"k{i:03d}": str(i).encode() for i in range(20)})
    vals, more = rio.get_omap_vals(oid, max_return=7)
    assert len(vals) == 7 and more
    assert min(vals) == "k000" and max(vals) == "k006"
    vals2, more2 = rio.get_omap_vals(oid, after="k006", max_return=50)
    assert len(vals2) == 13 and not more2


def test_omap_rejected_on_ec_pool(cluster, request):
    _, r = cluster
    io = r.open_ioctx("ecp")
    oid = _oid(request.node.name)
    io.write_full(oid, b"x")
    with pytest.raises(RadosError, match="EOPNOTSUPP"):
        io.set_omap(oid, {"k": b"v"})
    with pytest.raises(RadosError, match="EOPNOTSUPP"):
        io.get_omap_vals(oid)


# ----------------------------------------------------- compound WriteOp

def test_writeop_atomic_compound(rio, request):
    oid = _oid(request.node.name)
    op = (WriteOp().write_full(b"payload")
          .set_xattr("tag", b"t1")
          .set_omap({"idx": b"7"}))
    rio.operate(oid, op)
    assert rio.read(oid) == b"payload"
    assert rio.get_xattr(oid, "tag") == b"t1"
    assert rio.get_omap_vals(oid)[0] == {"idx": b"7"}


def test_writeop_ec_data_plus_xattr(cluster, request):
    _, r = cluster
    io = r.open_ioctx("ecp")
    oid = _oid(request.node.name)
    io.operate(oid, WriteOp().write_full(b"E" * 4096)
               .set_xattr("m", b"1"))
    assert io.read(oid) == b"E" * 4096
    assert io.get_xattr(oid, "m") == b"1"
    # EC allows at most one data mutation per compound op
    with pytest.raises(RadosError, match="EINVAL"):
        io.operate(oid, WriteOp().write(b"a", 0).append(b"b"))


def test_writev_malformed_rejected(rio, request):
    """Wire-level malformed mutation vectors answer EINVAL instead of
    crashing the op handler (arity/type/range validation)."""
    oid = _oid(request.node.name)
    ob = rio.rados.objecter
    for bad_ops in ([["write", 0]],            # short tuple
                    [["truncate", -5]],        # negative size
                    [["write", "x", b"d"]],    # bad offset type
                    [["nosuch", 1]],           # unknown op
                    [["setxattrs", {"k": 3}]]):  # non-bytes value
        fut = ob.submit(rio.pool_id, oid, "writev",
                        args={"ops": bad_ops})
        assert ob.wait_sync(fut.done, 10, ev=fut._ev), bad_ops
        assert fut.errno_name == "EINVAL", bad_ops
    assert not rio.list_objects().count(oid)


def test_append_resolved_at_primary(cluster, request):
    """The replica fan-out carries a concrete (write, offset) — not a
    size-relative append a lagging replica could mis-resolve."""
    c, r = cluster
    io = r.open_ioctx("rp")
    oid = _oid(request.node.name)
    io.write_full(oid, b"base")
    io.append(oid, b"+tail")
    pid = r.pool_lookup("rp")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, _ = m.pg_to_up_acting_osds(raw)
    for osd in acting:
        assert c.osds[osd].pgs[pg].shard.read(oid) == b"base+tail"


# ------------------------------------------- metadata through recovery

def test_replicated_recovery_carries_metadata(cluster, request):
    """Kill an acting OSD; after re-peering+recovery the new copy has
    the xattrs, omap and header, not just the data."""
    c, r = cluster
    io = r.open_ioctx("rp")
    oid = _oid(request.node.name)
    io.operate(oid, WriteOp().write_full(b"D" * 2048)
               .set_xattr("x", b"xv").set_omap({"o": b"ov"})
               .set_omap_header(b"H"))
    pid = r.pool_lookup("rp")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    victim = next(o for o in acting if o != primary)
    e0 = m.epoch
    # mark it out: CRUSH remaps the PG onto a newcomer, which must
    # receive the full copy (data + metadata) through recovery pushes
    r.mon_command({"prefix": "osd out", "ids": [victim]})
    r.objecter.wait_for_map(e0 + 1)

    # the replacement member eventually holds the full copy
    import time
    deadline = time.monotonic() + 20
    ok = False
    while time.monotonic() < deadline and not ok:
        m2 = r.objecter.osdmap
        _, _, acting2, _ = m2.pg_to_up_acting_osds(raw)
        pg = m2.pools[pid].raw_pg_to_pg(raw)
        newcomer = [o for o in acting2 if o not in acting and o >= 0]
        if newcomer:
            st = c.osds[newcomer[0]].pgs.get(pg)
            if st is not None and st.shard is not None and \
                    st.shard.exists(oid):
                data, attrs, omap, hdr = st.shard.push_payload(oid)
                ok = (data == b"D" * 2048 and attrs == {"x": b"xv"}
                      and omap == {"o": b"ov"} and hdr == b"H")
        time.sleep(0.1)
    assert ok, "recovered copy is missing data or metadata"
    # restore the osd for later tests
    r.mon_command({"prefix": "osd in", "ids": [victim]})


def test_scrub_detects_and_repairs_omap_divergence(cluster, request):
    """Silently corrupt one replica's omap; deep scrub flags the object
    and repair restores it (ref: omap_digest comparison in
    be_compare_scrubmaps)."""
    c, r = cluster
    io = r.open_ioctx("rp")
    oid = _oid(request.node.name)
    io.write_full(oid, b"scrubme")
    io.set_omap(oid, {"good": b"1"})
    pid = r.pool_lookup("rp")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    victim = next(o for o in acting if o != primary)
    # corrupt the replica's omap directly in its store
    from ceph_tpu.osd.ec_backend import pg_cid
    from ceph_tpu.store import ObjectId, Transaction
    c.osds[victim].store.queue_transaction(
        Transaction().omap_setkeys(pg_cid(pg), ObjectId(oid),
                                   {"evil": b"666"}))
    res = r.pg_scrub(pid, pg.ps)
    assert oid in res["inconsistent"]
    res2 = r.pg_scrub(pid, pg.ps, repair=True)
    assert oid in res2["inconsistent"] and res2["repaired"] >= 1
    # divergence gone
    res3 = r.pg_scrub(pid, pg.ps)
    assert res3["inconsistent"] == []


def test_ec_xattr_survives_shard_rebuild(cluster, request):
    """Wipe one EC shard's attrs; scrub-repair rebuilds the shard with
    the user xattrs restored from the survivors."""
    c, r = cluster
    io = r.open_ioctx("ecp")
    oid = _oid(request.node.name)
    rng = np.random.default_rng(11)
    payload = rng.integers(0, 256, 8192, dtype=np.uint8).tobytes()
    io.write_full(oid, payload)
    io.set_xattr(oid, "keep", b"me")
    pid = r.pool_lookup("ecp")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    sidx, victim = next((i, o) for i, o in enumerate(acting)
                        if o != primary and 0 <= o < (1 << 30))
    from ceph_tpu.osd.mutations import uxattr_key
    from ceph_tpu.osd.ec_backend import pg_cid
    from ceph_tpu.store import ObjectId, Transaction
    c.osds[victim].store.queue_transaction(
        Transaction().rmattr(pg_cid(pg), ObjectId(oid, shard=sidx),
                             uxattr_key("keep")))
    res = r.pg_scrub(pid, pg.ps, repair=True)
    assert oid in res["inconsistent"]
    # shard attrs restored
    attrs = c.osds[victim].store.getattrs(pg_cid(pg),
                                          ObjectId(oid, shard=sidx))
    assert attrs.get(uxattr_key("keep")) == b"me"
    assert io.read(oid) == payload
