"""red: raw lock constructions the sanitizer can't see."""
import threading
from threading import Lock

a = threading.Lock()
b = threading.RLock()
c = threading.Condition()
d = Lock()
