"""mgr telemetry module: the anonymized cluster report
(ref: src/pybind/mgr/telemetry/module.py — channel-gated report of
cluster shape, crash summaries, and perf aggregates, with an explicit
anonymization contract: hashed cluster id, NO hostnames, NO raw
filesystem paths, NO entity names, NO pool names).

Channels (ref: telemetry's basic/crash/device/perf/ident):
  basic — daemon/pool/pg counts, EC profile parameters
  crash — crash summaries (entity TYPE only, path-stripped backtrace)
  perf  — cluster-wide perf-counter sums (no per-daemon breakdown)
  ident — OFF by default: entity names (the only channel allowed to
          carry them; everything else must stay anonymous)

The report compiles on the mgr tick from cached inputs, so the
`telemetry show` command handler (which runs on the mgr dispatch
thread) never issues a synchronous mon command.
"""
from __future__ import annotations

import hashlib
import time

from ..common.crash import sanitize_backtrace, utc_iso
from ..osd.types import POOL_TYPE_ERASURE

REPORT_VERSION = 1

DEFAULT_CHANNELS = ("basic", "crash", "perf")
ALL_CHANNELS = ("basic", "crash", "perf", "ident")

_EPERM = 1
_EAGAIN = 11
_EINVAL = 22


class TelemetryModule:
    """(ref: telemetry/module.py Module)."""

    def __init__(self, mgr, enabled: bool = True,
                 channels: tuple | None = None):
        self.mgr = mgr
        #: starting the module is the operator's opt-in (the reference
        #: gates on `telemetry on`; `telemetry off` still disables)
        self.enabled = enabled
        self.channels = {c: c in (channels or DEFAULT_CHANNELS)
                         for c in ALL_CHANNELS}
        self.last_report: dict | None = None
        self.last_report_stamp: float | None = None
        #: tick-cached perf aggregate (compile never hits the mon)
        self._perf_totals: dict[str, float] = {}

    # -------------------------------------------------- anonymization
    def cluster_id(self) -> str:
        """Stable hashed cluster identity: the mon set IS the cluster
        (ref: telemetry hashing the fsid — reversible identity never
        leaves the cluster)."""
        ident = ",".join(sorted(self.mgr.mons))
        return hashlib.sha256(ident.encode()).hexdigest()[:32]

    # ------------------------------------------------------------ tick
    def tick(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        if not self.enabled:
            return
        if self.channels.get("perf"):
            rc, _, perf = self.mgr.mon_command(
                {"prefix": "osd perf dump"})
            if rc == 0 and isinstance(perf, dict):
                totals: dict[str, float] = {}
                for counters in perf.values():
                    for key, val in counters.items():
                        if isinstance(val, (int, float)):
                            totals[key] = totals.get(key, 0.0) \
                                + float(val)
                self._perf_totals = totals
        self.last_report = self.compile_report(now)
        self.last_report_stamp = now

    def compile_report(self, now: float | None = None) -> dict:
        """Assemble the channel-gated report from mgr-local state
        (the subscribed osdmap + module caches)."""
        now = time.time() if now is None else now
        report: dict = {
            "report_version": REPORT_VERSION,
            "report_timestamp": utc_iso(now),
            "cluster_id": self.cluster_id(),
            "channels": sorted(c for c, on in self.channels.items()
                               if on),
        }
        m = self.mgr.osdmap
        if self.channels.get("basic"):
            up = sum(1 for o in range(m.max_osd) if m.is_up(o))
            n_in = sum(1 for o in range(m.max_osd) if m.is_in(o))
            exists = sum(1 for o in range(m.max_osd) if m.exists(o))
            ec_profiles = []
            for pool in m.pools.values():
                if pool.type != POOL_TYPE_ERASURE:
                    continue
                prof = m.erasure_code_profiles.get(
                    pool.erasure_code_profile, {})
                ec_profiles.append({
                    "k": int(prof.get("k", 0)),
                    "m": int(prof.get("m", 0)),
                    "plugin": str(prof.get("plugin", ""))})
            report["basic"] = {
                "n_mons": len(self.mgr.mons),
                "osds": {"total": exists, "up": up, "in": n_in},
                "osdmap_epoch": m.epoch,
                "pools": {
                    "count": len(m.pools),
                    "by_type": {
                        "erasure": sum(1 for p in m.pools.values()
                                       if p.type == POOL_TYPE_ERASURE),
                        "replicated": sum(
                            1 for p in m.pools.values()
                            if p.type != POOL_TYPE_ERASURE)},
                    "pg_num_total": sum(p.pg_num
                                        for p in m.pools.values()),
                    "ec_profiles": ec_profiles},
            }
        if self.channels.get("crash") and self.mgr.crash is not None:
            crashes = self.mgr.crash.last_crashes
            report["crash"] = {
                "summary": self.mgr.crash.summary(),
                "reports": [{
                    "entity_type": c.get("entity_type", "?"),
                    "timestamp": c.get("timestamp", ""),
                    "exc_type": c.get("exc_type", ""),
                    "backtrace": sanitize_backtrace(
                        list(c.get("backtrace", []))),
                    "archived": bool(c.get("archived")),
                } for c in crashes],
            }
        if self.channels.get("perf"):
            report["perf"] = {"cluster": dict(self._perf_totals)}
        if self.channels.get("ident"):
            # the ONLY channel carrying entity identity
            report["ident"] = {"mons": sorted(self.mgr.mons),
                               "mgr": self.mgr.name}
        return report

    # -------------------------------------------------------- commands
    def status(self) -> dict:
        return {"enabled": self.enabled,
                "channels": dict(self.channels),
                "last_report_timestamp":
                    None if self.last_report_stamp is None
                    else utc_iso(self.last_report_stamp)}

    def handle_command(self, cmd: dict) -> tuple[int, str, object]:
        """Mon-proxied CLI verbs — answers from cached state only
        (dispatch-thread safe)."""
        pfx = str(cmd.get("prefix", ""))
        if pfx == "telemetry status":
            return 0, "", self.status()
        if pfx == "telemetry on":
            self.enabled = True
            return 0, "telemetry enabled", None
        if pfx == "telemetry off":
            self.enabled = False
            self.last_report = None
            self.last_report_stamp = None
            return 0, "telemetry disabled", None
        if pfx == "telemetry channel":
            name = str(cmd.get("name", ""))
            if name not in self.channels:
                return -_EINVAL, \
                    f"unknown channel {name!r} (of {ALL_CHANNELS})", \
                    None
            self.channels[name] = bool(cmd.get("enabled", True))
            return 0, "", None
        if pfx == "telemetry show":
            if not self.enabled:
                return -_EPERM, "telemetry is off — enable with " \
                    "`telemetry on`", None
            if self.last_report is None:
                return -_EAGAIN, "no report compiled yet — the next " \
                    "mgr tick builds one", None
            return 0, "", self.last_report
        return -_EINVAL, f"unknown telemetry command {pfx!r}", None
