"""BlueStore-lite: block-file data + KV metadata, COW writes, at-rest
checksums, deferred small writes, compress-on-write, O(journal) replay
(ref: src/os/bluestore/BlueStore.cc, src/kv/RocksDBStore.cc;
VERDICT r2 #4)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from ceph_tpu.kv import LogDB
from ceph_tpu.store import (BlueStore, MemStore, ObjectId, StoreError,
                            Transaction)

O = ObjectId


def mk(tmp_path, **kw):
    st = BlueStore(str(tmp_path / "bs"), min_alloc=512, **kw)
    st.mkfs()
    st.mount()
    return st


# ------------------------------------------------------------ KV layer

def test_logdb_roundtrip_and_replay(tmp_path):
    db = LogDB(str(tmp_path / "kv"))
    t = db.transaction()
    t.set("P", "a", {"x": 1})
    t.set("P", "b", b"bytes")
    t.set("Q", "c", [1, 2, 3])
    db.submit_transaction(t)
    t = db.transaction()
    t.rmkey("P", "a")
    db.submit_transaction(t)
    db.close()
    db2 = LogDB(str(tmp_path / "kv"))
    assert db2.get("P", "a") is None
    assert db2.get("P", "b") == b"bytes"
    assert db2.get_by_prefix("Q") == {"c": [1, 2, 3]}
    db2.close()


def test_logdb_compaction_bounds_replay(tmp_path):
    db = LogDB(str(tmp_path / "kv"), compact_bytes=4096)
    for i in range(200):
        t = db.transaction()
        t.set("P", f"k{i}", b"v" * 100)
        db.submit_transaction(t)
    # WAL stayed bounded by compaction — replay is O(tail)
    assert db.wal_size() < 4096 + 4096
    db.close()
    db2 = LogDB(str(tmp_path / "kv"))
    assert len(db2.get_by_prefix("P")) == 200
    db2.close()


def test_logdb_torn_tail_ignored(tmp_path):
    db = LogDB(str(tmp_path / "kv"))
    t = db.transaction()
    t.set("P", "good", 1)
    db.submit_transaction(t)
    db.close()
    with open(str(tmp_path / "kv" / "kv.wal"), "ab") as f:
        f.write(b"\x00\x00\x01\x00garbage-torn-tail")
    db2 = LogDB(str(tmp_path / "kv"))
    assert db2.get("P", "good") == 1
    db2.close()


# ----------------------------------------- semantics parity w/ MemStore

def _drive(st) -> list:
    """Apply an op mix and collect observable state."""
    st.queue_transaction(Transaction().create_collection("c"))
    st.queue_transaction(
        Transaction()
        .write("c", O("a"), 0, b"hello world")
        .write("c", O("a"), 6, b"WORLD")
        .setattrs("c", O("a"), {"k1": b"v1", "oi": {"size": 11}})
        .omap_setkeys("c", O("a"), {"m1": b"x", "m2": b"y"}))
    st.queue_transaction(
        Transaction()
        .write("c", O("b"), 4096, b"sparse-tail")
        .zero("c", O("b"), 4090, 8)
        .truncate("c", O("b"), 4100)
        .clone("c", O("a"), O("a2"))
        .omap_rmkeys("c", O("a"), ["m2"]))
    st.queue_transaction(
        Transaction()
        .write("c", O("a2"), 0, b"DIVERGED")
        .rmattr("c", O("a2"), "k1")
        .collection_move_rename("c", O("b"), "c", O("b2")))
    out = []
    for oid in st.collection_list("c"):
        out.append((str(oid), st.read("c", oid, 0, 0),
                    sorted(st.getattrs("c", oid).items(),
                           key=lambda kv: kv[0]),
                    sorted(st.omap_get("c", oid).items())))
    out.append(st.stat("c", O("a"))["size"])
    return out


def test_semantics_match_memstore(tmp_path):
    ms = MemStore()
    ms.mkfs()
    ms.mount()
    bs = mk(tmp_path)
    assert _drive(bs) == _drive(ms)
    bs.umount()


def test_failed_txn_leaves_store_untouched(tmp_path):
    bs = mk(tmp_path)
    bs.queue_transaction(Transaction().create_collection("c"))
    bs.queue_transaction(Transaction().write("c", O("x"), 0, b"keep"))
    bad = (Transaction()
           .write("c", O("x"), 0, b"clobber")
           .remove("c", O("ghost")))          # fails: ENOENT
    with pytest.raises(StoreError):
        bs.queue_transaction(bad)
    assert bs.read("c", O("x")) == b"keep"
    bs.umount()


# ------------------------------------------------------- durability

def test_umount_remount_persists(tmp_path):
    bs = mk(tmp_path)
    rng = np.random.default_rng(5)
    payload = rng.integers(0, 256, 20000, dtype=np.uint8).tobytes()
    bs.queue_transaction(Transaction().create_collection("c"))
    bs.queue_transaction(
        Transaction()
        .write("c", O("big"), 0, payload)
        .setattrs("c", O("big"), {"oi": {"v": (1, 2)}})
        .omap_setkeys("c", O("big"), {"k": b"v"}))
    bs.umount()
    bs2 = BlueStore(str(tmp_path / "bs"), min_alloc=512)
    bs2.mount()
    assert bs2.read("c", O("big")) == payload
    assert bs2.getattr("c", O("big"), "oi") == {"v": (1, 2)}
    assert bs2.omap_get("c", O("big")) == {"k": b"v"}
    bs2.umount()


def test_kill9_replay_bounded(tmp_path):
    """Writes from a subprocess that dies via os._exit (no umount, no
    flush beyond commits) survive; replay reads only the KV wal tail."""
    script = f"""
import os, sys
sys.path.insert(0, {str(os.getcwd())!r})
from ceph_tpu.store import BlueStore, ObjectId, Transaction
st = BlueStore({str(tmp_path / "bs")!r}, min_alloc=512)
st.mkfs(); st.mount()
st.queue_transaction(Transaction().create_collection("c"))
for i in range(20):
    st.queue_transaction(
        Transaction().write("c", ObjectId(f"o{{i}}"), 0,
                            f"payload-{{i}}".encode() * 50))
st.queue_transaction(
    Transaction().write("c", ObjectId("o3"), 0, b"OVERWRITE"))
os._exit(9)          # kill -9: no umount, no atexit
"""
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 9, proc.stderr
    bs = BlueStore(str(tmp_path / "bs"), min_alloc=512)
    bs.mount()
    assert bs.read("c", O("o3"), 0, 9) == b"OVERWRITE"
    for i in range(20):
        if i == 3:
            continue
        assert bs.read("c", O(f"o{i}"), 0, 0) == \
            f"payload-{i}".encode() * 50
    assert bs.fsck() == []
    bs.umount()


# ---------------------------------------------------- checksums at rest

def test_bitrot_detected_on_read_and_fsck(tmp_path):
    bs = mk(tmp_path)
    bs.queue_transaction(Transaction().create_collection("c"))
    bs.queue_transaction(
        Transaction().write("c", O("v"), 0, b"precious" * 100))
    assert bs.read("c", O("v"), 0, 8) == b"precious"
    bs.corrupt_blob_bytes("c", O("v"))
    with pytest.raises(StoreError, match="checksum"):
        bs.read("c", O("v"), 0, 8)
    errs = bs.fsck()
    assert errs and "csum mismatch" in errs[0]
    bs.umount()


def test_bitrot_feeds_scrub_repair(tmp_path):
    """A BlueStore-backed OSD with flipped bits serves EIO; deep scrub
    flags the copy inconsistent and repair rewrites it from the
    authoritative replica."""
    from ceph_tpu.testing import MiniCluster
    from ceph_tpu.osd.ec_backend import pg_cid
    stores = {i: BlueStore(str(tmp_path / f"osd{i}"), min_alloc=512)
              for i in range(3)}
    for st in stores.values():
        st.mkfs()
        st.mount()
    c = MiniCluster(n_osd=3, threaded=True)
    # swap in durable stores before pools exist
    for i, st in stores.items():
        c.kill_osd(i)
        c._stores[i] = st
        c.start_osd(i)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("p", pg_num=4)
        io = r.open_ioctx("p")
        io.write_full("victim", b"gold" * 1000)
        pid = r.pool_lookup("p")
        m = c.mon.osdmap
        pg = m.pools[pid].raw_pg_to_pg(
            m.object_locator_to_pg("victim", pid))
        _up, _upp, acting, primary = m.pg_to_up_acting_osds(pg)
        replica = next(o for o in acting if o != primary)
        c.osds[replica].store.corrupt_blob_bytes(pg_cid(pg),
                                                 O("victim"))
        res = r.pg_scrub(pid, pg.ps, repair=True)
        assert res["inconsistent"] == ["victim"]
        assert res["repaired"] >= 1
        res2 = r.pg_scrub(pid, pg.ps)
        assert res2["inconsistent"] == []
        assert io.read("victim") == b"gold" * 1000
    finally:
        c.shutdown()


# ------------------------------------------- deferred + compression

def test_deferred_small_overwrite(tmp_path):
    bs = mk(tmp_path, deferred_max=512)
    bs.queue_transaction(Transaction().create_collection("c"))
    base = bytes(range(256)) * 16       # 4 KiB blob
    bs.queue_transaction(Transaction().write("c", O("d"), 0, base))
    blobs_before = len(bs._blobs)
    bs.queue_transaction(Transaction().write("c", O("d"), 100,
                                             b"PATCH"))
    # in-place deferred write: no new blob allocated
    assert len(bs._blobs) == blobs_before
    want = base[:100] + b"PATCH" + base[105:]
    assert bs.read("c", O("d")) == want
    assert bs.fsck() == []              # csum updated with the patch
    bs.umount()
    bs2 = BlueStore(str(tmp_path / "bs"), min_alloc=512,
                    deferred_max=512)
    bs2.mount()
    assert bs2.read("c", O("d")) == want
    bs2.umount()


def test_compress_on_write(tmp_path):
    bs = mk(tmp_path, compression="zlib", comp_min_len=1024)
    bs.queue_transaction(Transaction().create_collection("c"))
    data = b"A" * 65536                 # highly compressible
    bs.queue_transaction(Transaction().write("c", O("z"), 0, data))
    assert bs.read("c", O("z")) == data
    blob = next(iter(bs._blobs.values()))
    assert blob["comp"] == "zlib"
    assert blob["stored"] < len(data) // 10
    # incompressible data stays raw
    rng = np.random.default_rng(1)
    noise = rng.integers(0, 256, 65536, dtype=np.uint8).tobytes()
    bs.queue_transaction(Transaction().write("c", O("n"), 0, noise))
    assert bs.read("c", O("n")) == noise
    used = (bs._units - len(bs._free)) * bs.min_alloc
    assert used < len(data) + 2 * len(noise)
    bs.umount()


def test_blob_sharing_and_free(tmp_path):
    """Clones share blobs; rewriting/removing drops references and
    frees units back to the allocator."""
    bs = mk(tmp_path)
    bs.queue_transaction(Transaction().create_collection("c"))
    bs.queue_transaction(Transaction().write("c", O("s"), 0,
                                             b"shared" * 200))
    bs.queue_transaction(Transaction().clone("c", O("s"), O("t")))
    assert bs.read("c", O("t")) == b"shared" * 200
    used_before = bs._units - len(bs._free)
    bs.queue_transaction(Transaction().remove("c", O("s")))
    assert bs.read("c", O("t")) == b"shared" * 200   # blob survives
    assert bs._units - len(bs._free) == used_before
    bs.queue_transaction(Transaction().remove("c", O("t")))
    assert bs._units - len(bs._free) < used_before   # units freed
    bs.umount()


@pytest.mark.slow
def test_multiprocess_kill9_restart(tmp_path):
    """The full deployment story: mon + BlueStore OSD processes over
    TCP; SIGKILL one OSD and restart it on its data dir — the revived
    daemon replays its KV wal, re-subscribes, and serves (also pins
    the messenger's reconnect-and-resend to restarted peers)."""
    import json
    import signal
    import subprocess
    import time
    from ceph_tpu.client import Rados
    from ceph_tpu.msg.tcp import TcpNet, pick_free_ports

    names = ["mon.0", "osd.0", "osd.1", "osd.2"]
    ports = pick_free_ports(len(names))
    addrs = {n: ["127.0.0.1", p] for n, p in zip(names, ports)}
    mpath = tmp_path / "mm.json"
    mpath.write_text(json.dumps(
        {"addrs": addrs, "mon_ranks": [0], "n_osd": 3,
         "osds_per_host": 1}))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.getcwd())

    def start_osd(i):
        return subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.tools.daemon_main",
             "osd", "--id", str(i), "--monmap", str(mpath),
             "--data-dir", str(tmp_path / f"osd{i}")], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    procs = [subprocess.Popen(
        [sys.executable, "-m", "ceph_tpu.tools.daemon_main", "mon",
         "--rank", "0", "--monmap", str(mpath)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)]
    r = None
    osds = {}
    try:
        time.sleep(1.0)
        osds = {i: start_osd(i) for i in range(3)}
        r = Rados(TcpNet({k: tuple(v) for k, v in addrs.items()}),
                  name="client.970", op_timeout=10.0).connect(60.0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(1 for o in range(3)
                   if r.objecter.osdmap.is_up(o)) == 3:
                break
            time.sleep(0.2)
        r.pool_create("bp", pg_num=8)
        io = r.open_ioctx("bp")
        payload = os.urandom(200_000)
        io.write_full("durable", payload)
        io.set_xattr("durable", "k", b"v")
        osds[1].send_signal(signal.SIGKILL)
        osds[1].wait(timeout=10)
        osds[1] = start_osd(1)
        deadline = time.monotonic() + 60
        ok = False
        while time.monotonic() < deadline:
            try:
                if io.read("durable") == payload and \
                        io.get_xattr("durable", "k") == b"v":
                    ok = True
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert ok, "restarted BlueStore OSD never served its data"
    finally:
        if r is not None:
            r.shutdown()
        for p in list(osds.values()) + procs:
            p.terminate()
        for p in list(osds.values()) + procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_blob_split_keeps_tail_alive(tmp_path):
    """Punching the middle of a blob splits its lextent in two; a later
    overwrite of the head must NOT free the blob while the tail still
    references it (symmetric lextent-refcount deltas)."""
    bs = mk(tmp_path, deferred_max=0)       # force COW, no deferred
    bs.queue_transaction(Transaction().create_collection("c"))
    base = bytes(range(256)) * 64           # 16 KiB -> blob A
    bs.queue_transaction(Transaction().write("c", O("s"), 0, base))
    bs.queue_transaction(Transaction().write("c", O("s"), 4096,
                                             b"M" * 4096))  # split A
    bs.queue_transaction(Transaction().write("c", O("s"), 0,
                                             b"H" * 4096))  # head COW
    want = b"H" * 4096 + b"M" * 4096 + base[8192:]
    assert bs.read("c", O("s")) == want
    assert bs.fsck() == []
    # and the store survives remount with the same content
    bs.umount()
    bs2 = BlueStore(str(tmp_path / "bs"), min_alloc=512,
                    deferred_max=0)
    bs2.mount()
    assert bs2.read("c", O("s")) == want
    bs2.umount()


def test_two_deferred_writes_one_txn(tmp_path):
    """Both patches land and the blob csum matches the final bytes."""
    bs = mk(tmp_path, deferred_max=512)
    bs.queue_transaction(Transaction().create_collection("c"))
    base = bytes(range(256)) * 16           # 4 KiB blob
    bs.queue_transaction(Transaction().write("c", O("d"), 0, base))
    bs.queue_transaction(
        Transaction()
        .write("c", O("d"), 0, b"AA")
        .write("c", O("d"), 500, b"BB"))
    want = bytearray(base)
    want[0:2] = b"AA"
    want[500:502] = b"BB"
    assert bs.read("c", O("d")) == bytes(want)
    assert bs.fsck() == []


def test_failed_txn_returns_units(tmp_path):
    bs = mk(tmp_path)
    bs.queue_transaction(Transaction().create_collection("c"))
    free_before = len(bs._free) - bs._units   # negative used marker
    used_before = bs._units - len(bs._free)
    for _ in range(5):
        bad = (Transaction()
               .write("c", O("x"), 0, b"data" * 1000)
               .remove("c", O("ghost")))
        with pytest.raises(StoreError):
            bs.queue_transaction(bad)
    assert bs._units - len(bs._free) == used_before, \
        "failed transactions leaked allocator units"
    bs.umount()

def test_clone_then_deferred_write_same_txn(tmp_path):
    """Advisor r3 (high): a clone earlier in the SAME txn shares the
    blob while committed refs still read 1 — a small deferred write
    must NOT patch the shared blob in place (silent snapshot
    corruption).  This is exactly the snapshot-COW txn
    replicated_backend builds: clone for the snap, then the overwrite."""
    bs = mk(tmp_path, deferred_max=4096)
    bs.queue_transaction(Transaction().create_collection("c"))
    base = bytes(range(256)) * 16           # 4 KiB blob, uncompressed
    bs.queue_transaction(Transaction().write("c", O("h"), 0, base))
    bs.queue_transaction(
        Transaction()
        .clone("c", O("h"), O("h.snap"))
        .write("c", O("h"), 0, b"X" * 512))   # <= deferred_max
    assert bs.read("c", O("h.snap")) == base, \
        "snapshot clone must keep pre-write bytes"
    assert bs.read("c", O("h"))[:512] == b"X" * 512
    assert bs.read("c", O("h"))[512:] == base[512:]
    assert bs.fsck() == []
    # survives remount: the head's write was COW'd to a new blob
    bs.umount()
    bs2 = BlueStore(str(tmp_path / "bs"), min_alloc=512)
    bs2.mount()
    assert bs2.read("c", O("h.snap")) == base
    assert bs2.read("c", O("h"))[:512] == b"X" * 512
    bs2.umount()


def test_deferred_after_clone_removed_same_txn(tmp_path):
    """Counter-case: clone then REMOVE the clone in the same txn — the
    blob is single-ref again, deferral is legal and must still produce
    a consistent csum."""
    bs = mk(tmp_path, deferred_max=4096)
    bs.queue_transaction(Transaction().create_collection("c"))
    base = bytes(range(256)) * 16
    bs.queue_transaction(Transaction().write("c", O("h"), 0, base))
    bs.queue_transaction(
        Transaction()
        .clone("c", O("h"), O("tmp"))
        .remove("c", O("tmp"))
        .write("c", O("h"), 0, b"Y" * 256))
    assert bs.read("c", O("h"))[:256] == b"Y" * 256
    assert bs.read("c", O("h"))[256:] == base[256:]
    assert bs.fsck() == []


def test_statfs_disk_backed_capacity(tmp_path):
    """Advisor r3 (low): a disk-backed store must never report
    used > total from the MemStore RAM constant."""
    from ceph_tpu.common.options import global_config
    bs = mk(tmp_path)
    bs.queue_transaction(Transaction().create_collection("c"))
    bs.queue_transaction(Transaction().write("c", O("big"), 0,
                                             b"z" * (1 << 16)))
    st = bs.statfs()
    assert st["used"] <= st["total"]
    assert st["available"] == st["total"] - st["used"]
    # provisioned size wins when configured
    global_config().set("bluestore_device_bytes", 1 << 20)
    try:
        st = bs.statfs()
        assert st["total"] == 1 << 20
        assert st["used"] <= st["total"]
    finally:
        global_config().set("bluestore_device_bytes", 0)
    bs.umount()
