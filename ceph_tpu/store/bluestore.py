"""BlueStore-lite: block-file object store with KV metadata.

The reference's storage engine re-built at framework scale
(ref: src/os/bluestore/BlueStore.cc — txn entry `queue_transactions`
:10873, `_txc_add_transaction` :10977; blob/extent onode model;
allocators; checksums; compression; deferred writes; RocksDB metadata
via src/kv/RocksDBStore.cc).  What it keeps and why:

* **Data lives on a block file**, not RAM: objects map through a
  BlueStore-style two-level reference — `lextents` (logical ranges ->
  blob byte ranges) over immutable **blobs** (allocated unit runs with
  a crc32c over the stored bytes and an optional compression alg).
  Writes are COW: a new blob is written to FREE units and the lextent
  map cut over in the KV commit, so a crash never tears visible data.
* **Metadata in a KeyValueDB** (ceph_tpu.kv.LogDB = WAL + snapshot):
  mount replays O(wal tail), never O(dataset) — the JournaledStore
  failure mode this engine retires.
* **Checksums at rest**: every blob carries crc32c(stored bytes),
  verified on every read and by fsck; bitrot surfaces as EIO for the
  scrub/repair machinery instead of silent corruption.
* **Deferred small writes** (ref: bluestore deferred_write path): an
  overwrite <= `deferred_max` inside one uncompressed single-ref blob
  rides the KV WAL (data embedded) and is applied to the block file
  after commit; mount re-applies pending entries (idempotent).
* **Compress-on-write** finally consumes the compressor registry
  (ref: src/compressor/ consumed by BlueStore): blobs >=
  `comp_min_len` are compressed when the ratio pays, shrinking the
  unit run.
* **Allocator state is not persisted** — it is rebuilt at mount from
  the blob map (the reference's NCB "allocation from onodes" recovery
  model), eliminating allocator/metadata consistency bugs by design.
"""
from __future__ import annotations

import os
import threading

from ..common.lockdep import make_lock

from .. import compressor as comp_mod
from ..common.crc32c import crc32c
from ..common.options import global_config
from ..kv import KeyValueDB, LogDB
from .objectstore import (ObjectId, ObjectStore, StoreError, Transaction,
                          OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE,
                          OP_REMOVE, OP_SETATTRS, OP_RMATTR, OP_RMATTRS,
                          OP_CLONE, OP_CLONE_RANGE, OP_MKCOLL, OP_RMCOLL,
                          OP_COLL_MOVE_RENAME, OP_OMAP_CLEAR,
                          OP_OMAP_SETKEYS, OP_OMAP_RMKEYS)

# KV prefixes (ref: bluestore's rocksdb column prefixes PREFIX_OBJ etc.)
P_SUPER = "S"
P_COLL = "C"
P_ONODE = "O"
P_BLOB = "B"
P_DEFER = "D"


def _okey(cid: str, oid: ObjectId) -> str:
    from ..msg import encoding as wire
    return f"{cid}|{wire.encode(oid).hex()}"


def _okey_oid(key: str) -> ObjectId:
    from ..msg import encoding as wire
    return wire.decode(bytes.fromhex(key.split("|", 1)[1]))


class BlueStore(ObjectStore):
    """dir/ layout: `block` (data file) + `kv/` (LogDB)."""

    def __init__(self, path: str, min_alloc: int = 4096,
                 deferred_max: int = 4096,
                 compression: str = "none",
                 comp_min_len: int = 32768):
        self.path = path
        self.min_alloc = min_alloc
        self.deferred_max = deferred_max
        self.compression = compression
        self.comp_min_len = comp_min_len
        self.mounted = False
        self._lock = make_lock(f"bluestore.{path}")
        self._block = None
        #: device-health feed (ref: the SMART-style error counters
        #: mgr/devicehealth consumes): csum mismatches and read
        #: errors observed on this store's media
        self.media_errors = {"csum_errors": 0, "read_errors": 0}
        self.db: KeyValueDB | None = None
        # in-memory metadata mirror (metadata only — data stays on disk)
        self._colls: dict[str, dict[ObjectId, dict]] = {}
        self._blobs: dict[int, dict] = {}
        self._next_blob = 1
        self._free: set[int] = set()          # free allocation units
        self._units = 0                       # units provisioned so far
        self._read_err_objs: set = set()

    # ------------------------------------------------------- lifecycle
    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        open(os.path.join(self.path, "block"), "ab").close()

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._block = open(os.path.join(self.path, "block"), "r+b") \
            if os.path.exists(os.path.join(self.path, "block")) \
            else open(os.path.join(self.path, "block"), "w+b")
        self.db = LogDB(os.path.join(self.path, "kv"))
        self._load()
        self._replay_deferred()
        self.mounted = True

    def umount(self) -> None:
        if self.db is not None:
            self.db.close()
            self.db = None
        if self._block is not None:
            self._block.close()
            self._block = None
        self.mounted = False

    def _load(self) -> None:
        """Rebuild the in-memory mirror + allocator from KV
        (allocation recovered from the blob map, the NCB model)."""
        self._colls = {}
        for cid, meta in self.db.get_by_prefix(P_COLL).items():
            self._colls[cid] = {}
        for key, onode in self.db.get_by_prefix(P_ONODE).items():
            cid = key.split("|", 1)[0]
            self._colls.setdefault(cid, {})[_okey_oid(key)] = onode
        self._blobs = {int(k): v for k, v in
                       self.db.get_by_prefix(P_BLOB).items()}
        self._next_blob = max(self._blobs, default=0) + 1
        used = set()
        top = 0
        for b in self._blobs.values():
            start, count = b["units"]
            used.update(range(start, start + count))
            top = max(top, start + count)
        self._units = top
        self._free = set(range(top)) - used

    def _replay_deferred(self) -> None:
        """Apply pending deferred writes (data was in the KV WAL;
        idempotent re-apply, ref: bluestore deferred replay)."""
        pending = self.db.get_by_prefix(P_DEFER)
        if not pending:
            return
        txn = self.db.transaction()
        for key, d in pending.items():
            self._block.seek(d["off"])
            self._block.write(bytes(d["data"]))
            txn.rmkey(P_DEFER, key)
        self._block.flush()
        os.fsync(self._block.fileno())
        self.db.submit_transaction(txn)

    # ------------------------------------------------------- allocator
    def _allocate(self, n_units: int) -> int:
        """First-fit contiguous run; the block file grows on demand
        (ref: BitmapAllocator — contiguity keeps blob reads one
        seek)."""
        if n_units <= 0:
            raise StoreError("EINVAL", "zero allocation")
        free = sorted(self._free)
        run_start, run_len = None, 0
        for u in free:
            if run_start is not None and u == run_start + run_len:
                run_len += 1
            else:
                run_start, run_len = u, 1
            if run_len == n_units:
                for x in range(run_start, run_start + n_units):
                    self._free.discard(x)
                return run_start
        start = self._units
        self._units += n_units
        return start

    def _free_blob(self, blob_id: int, txn) -> None:
        b = self._blobs.pop(blob_id, None)
        if b is None:
            return
        start, count = b["units"]
        self._free.update(range(start, start + count))
        txn.rmkey(P_BLOB, str(blob_id))

    # ------------------------------------------------------ txn engine
    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            if self.db is None:
                raise StoreError("EIO", "store not mounted")
            ctx = _TxnCtx(self)
            try:
                for op in txn.ops:
                    self._apply(op, ctx)
            except Exception:
                ctx.abort()      # return allocated units to the pool
                raise
            ctx.commit()

    def _apply(self, op, ctx: "_TxnCtx") -> None:
        code = op[0]
        if code == OP_MKCOLL:
            _, cid, bits = op
            if cid in ctx.colls_view():
                raise StoreError("EEXIST", f"collection {cid}")
            ctx.new_coll(cid, bits)
            return
        if code == OP_RMCOLL:
            _, cid = op
            if ctx.coll(cid):
                raise StoreError("ENOTEMPTY", f"collection {cid}")
            ctx.rm_coll(cid)
            return
        if code == OP_COLL_MOVE_RENAME:
            _, oldcid, oldoid, cid, oid = op
            src = ctx.coll(oldcid)
            dst = ctx.coll(cid)
            if oldoid not in src:
                raise StoreError("ENOENT", f"{oldcid}/{oldoid}")
            if oid in dst and not (cid == oldcid and oid == oldoid):
                raise StoreError("EEXIST", f"{cid}/{oid}")
            ctx.move(oldcid, oldoid, cid, oid)
            return

        cid, oid = op[1], op[2]
        if code == OP_TOUCH:
            ctx.onode(cid, oid, create=True)
        elif code == OP_WRITE:
            _, _, _, off, data = op
            self._do_write(ctx, cid, oid, off, bytes(data))
        elif code == OP_ZERO:
            _, _, _, off, length = op
            o = ctx.onode(cid, oid, create=True)
            self._punch(ctx, o, off, length)
            o["size"] = max(o["size"], off + length)
        elif code == OP_TRUNCATE:
            _, _, _, size = op
            o = ctx.onode(cid, oid)
            if size < o["size"]:
                self._punch(ctx, o, size, o["size"] - size)
            o["size"] = size
        elif code == OP_REMOVE:
            ctx.remove(cid, oid)
        elif code == OP_SETATTRS:
            _, _, _, attrs = op
            o = ctx.onode(cid, oid, create=True)
            o["attrs"].update(attrs)
        elif code == OP_RMATTR:
            _, _, _, name = op
            ctx.onode(cid, oid)["attrs"].pop(name, None)
        elif code == OP_RMATTRS:
            ctx.onode(cid, oid)["attrs"].clear()
        elif code == OP_CLONE:
            _, _, _, noid = op
            ctx.clone(cid, oid, noid)
        elif code == OP_CLONE_RANGE:
            _, _, _, noid, srcoff, length, dstoff = op
            data = self._read_onode(ctx.onode(cid, oid), srcoff, length)
            self._do_write(ctx, cid, noid, dstoff, data)
        elif code == OP_OMAP_CLEAR:
            ctx.onode(cid, oid)
            ctx.omap_clear(cid, oid)
        elif code == OP_OMAP_SETKEYS:
            _, _, _, keys = op
            ctx.onode(cid, oid, create=True)
            ctx.omap_set(cid, oid, keys)
        elif code == OP_OMAP_RMKEYS:
            _, _, _, keys = op
            ctx.onode(cid, oid)
            ctx.omap_rm(cid, oid, keys)
        else:
            raise StoreError("EOPNOTSUPP", f"unknown op {code}")

    # -------------------------------------------------------- write IO
    def _do_write(self, ctx: "_TxnCtx", cid: str, oid: ObjectId,
                  off: int, data: bytes) -> None:
        if not data:
            ctx.onode(cid, oid, create=True)
            return
        o = ctx.onode(cid, oid, create=True)
        end = off + len(data)
        # deferred small overwrite: entirely inside ONE uncompressed
        # single-ref blob extent -> data rides the KV WAL, applied in
        # place after commit (ref: bluestore deferred writes)
        if len(data) <= self.deferred_max:
            hit = self._deferred_target(ctx, o, off, len(data))
            if hit is not None:
                self._deferred_write(ctx, o, hit, off, data)
                o["size"] = max(o["size"], end)
                o["mtime"] = 0
                return
        self._punch(ctx, o, off, len(data))
        blob_id = ctx.new_blob(data)
        o["lextents"].append([off, len(data), blob_id, 0])
        o["lextents"].sort()
        o["size"] = max(o["size"], end)

    def _deferred_target(self, ctx: "_TxnCtx", o: dict, off: int,
                         length: int):
        """The lextent wholly containing [off, off+length) whose blob
        can be patched in place, or None."""
        for le in o["lextents"]:
            loff, llen, blob_id, boff = le
            if loff <= off and off + length <= loff + llen:
                b = self._blobs_view().get(blob_id)
                if b is not None and b.get("comp") is None and \
                        self._pending_refs(ctx, blob_id, b) == 1:
                    return le
            if loff > off:
                break
        return None

    def _pending_refs(self, ctx: "_TxnCtx", blob_id: int, b: dict) -> int:
        """Effective lextent-reference count of `blob_id` at this point
        in the transaction.  Committed `refs` is only resolved at
        commit, so a clone EARLIER IN THE SAME TXN shares the blob
        while refs still reads 1 — an in-place deferred patch would
        then mutate the bytes the clone shares (silent snapshot
        corruption).  Adjust committed refs by the txn shadow: for
        every onode touched by this txn, subtract its committed lextent
        references and add its shadow ones."""
        refs = b.get("refs", 1)
        touched = set(ctx._onodes) | ctx._removed_onodes
        for (cid, oid) in touched:
            old = self._colls.get(cid, {}).get(oid)
            if old is not None:
                refs -= sum(1 for le in old["lextents"]
                            if le[2] == blob_id)
            cur = ctx._colls.get(cid, {}).get(oid)
            if cur is not None:
                refs += sum(1 for le in cur["lextents"]
                            if le[2] == blob_id)
        return refs

    def _blobs_view(self) -> dict:
        return self._blobs

    def _deferred_write(self, ctx: "_TxnCtx", o: dict, le,
                        off: int, data: bytes) -> None:
        loff, llen, blob_id, boff = le
        b = ctx.blob_mutable(blob_id)
        delta = boff + (off - loff)
        start, count = b["units"]
        blob_base = start * self.min_alloc
        abs_off = blob_base + delta
        # new stored bytes -> new csum.  The read-merge must overlay
        # deferred patches already queued in THIS txn (they are not on
        # disk yet): two small writes to one blob in one transaction
        # would otherwise produce a csum matching neither state.
        stored = bytearray(self._read_stored(b))
        for p_off, p_data in ctx._deferred:
            rel = p_off - blob_base
            if 0 <= rel < len(stored):
                stored[rel:rel + len(p_data)] = p_data
        stored[delta:delta + len(data)] = data
        b["csum"] = crc32c(0, bytes(stored))
        ctx.defer(abs_off, data)

    def _punch(self, ctx: "_TxnCtx", o: dict, off: int,
               length: int) -> None:
        """Remove logical coverage of [off, off+length), splitting
        boundary lextents; unreferenced blobs are freed."""
        end = off + length
        out = []
        for le in o["lextents"]:
            loff, llen, blob_id, boff = le
            lend = loff + llen
            if lend <= off or loff >= end:
                out.append(le)
                continue
            if loff < off:          # head survives
                out.append([loff, off - loff, blob_id, boff])
            if lend > end:          # tail survives
                out.append([end, lend - end, blob_id,
                            boff + (end - loff)])
        o["lextents"] = sorted(out)
        ctx.gc_blobs(o)

    # --------------------------------------------------------- read IO
    def _read_stored(self, b: dict) -> bytes:
        start, count = b["units"]
        self._block.seek(start * self.min_alloc)
        return self._block.read(b["stored"])

    def _blob_raw(self, blob_id: int) -> bytes:
        """Stored bytes -> raw bytes, csum-verified (every read passes
        the at-rest checksum gate, ref: bluestore _verify_csum)."""
        b = self._blobs.get(blob_id)
        if b is None:
            raise StoreError("EIO", f"missing blob {blob_id}")
        stored = self._read_stored(b)
        if crc32c(0, stored) != b["csum"]:
            self.media_errors["csum_errors"] += 1
            raise StoreError("EIO", f"blob {blob_id} checksum mismatch")
        if b.get("comp") is not None:
            return comp_mod.decompress(stored)
        return stored

    def _read_onode(self, o: dict, off: int, length: int) -> bytes:
        if length == 0:
            length = max(0, o["size"] - off)
        out = bytearray(length)
        for loff, llen, blob_id, boff in o["lextents"]:
            lend = loff + llen
            if lend <= off or loff >= off + length:
                continue
            raw = self._blob_raw(blob_id)
            s = max(off, loff)
            e = min(off + length, lend)
            out[s - off:e - off] = raw[boff + (s - loff):
                                       boff + (e - loff)]
        return bytes(out[:max(0, min(length, o["size"] - off))])

    # ----------------------------------------------- ObjectStore reads
    def _obj(self, cid: str, oid: ObjectId) -> dict:
        c = self._colls.get(cid)
        if c is None:
            raise StoreError("ENOENT", f"no collection {cid}")
        o = c.get(oid)
        if o is None:
            raise StoreError("ENOENT", f"{cid}/{oid}")
        return o

    def read(self, cid: str, oid: ObjectId, off: int = 0,
             length: int = 0) -> bytes:
        with self._lock:
            if ((cid, oid) in self._read_err_objs and
                    global_config()["objectstore_debug_inject_read_err"]):
                self.media_errors["read_errors"] += 1
                raise StoreError("EIO", f"injected read error {cid}/{oid}")
            return self._read_onode(self._obj(cid, oid), off, length)

    def stat(self, cid: str, oid: ObjectId) -> dict:
        with self._lock:
            return {"size": self._obj(cid, oid)["size"]}

    def exists(self, cid: str, oid: ObjectId) -> bool:
        with self._lock:
            c = self._colls.get(cid)
            return c is not None and oid in c

    def getattr(self, cid: str, oid: ObjectId, name: str):
        with self._lock:
            o = self._obj(cid, oid)
            if name not in o["attrs"]:
                raise StoreError("ENODATA", f"{oid} xattr {name}")
            return o["attrs"][name]

    def getattrs(self, cid: str, oid: ObjectId) -> dict:
        with self._lock:
            return dict(self._obj(cid, oid)["attrs"])

    def omap_get(self, cid: str, oid: ObjectId) -> dict[str, bytes]:
        with self._lock:
            self._obj(cid, oid)
            return dict(self.db.get_by_prefix(
                f"M{_okey(cid, oid)}"))

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._colls)

    def collection_exists(self, cid: str) -> bool:
        with self._lock:
            return cid in self._colls

    def collection_list(self, cid: str) -> list[ObjectId]:
        with self._lock:
            c = self._colls.get(cid)
            if c is None:
                raise StoreError("ENOENT", f"no collection {cid}")
            return sorted(c)

    def statfs(self) -> dict:
        """Capacity from the configured device size, or — when
        unprovisioned (0) — from the grow-on-demand block file, never
        the MemStore RAM constant (advisor: used must not exceed total
        or capacity logic like pg_autoscaler sees fictional headroom)."""
        with self._lock:
            used = (self._units - len(self._free)) * self.min_alloc
            total = global_config()["bluestore_device_bytes"]
            if total <= 0:
                total = max(self._units * self.min_alloc,
                            global_config()["memstore_device_bytes"])
            total = max(total, used)
            return {"total": total, "used": used,
                    "available": max(0, total - used)}

    # --------------------------------------------------- fault hooks
    def inject_read_err(self, cid: str, oid: ObjectId) -> None:
        self._read_err_objs.add((cid, oid))

    def clear_read_err(self, cid: str, oid: ObjectId) -> None:
        self._read_err_objs.discard((cid, oid))

    def corrupt_blob_bytes(self, cid: str, oid: ObjectId,
                           payload: bytes = b"ROT") -> None:
        """Test hook: flip stored bytes under an object's first blob
        WITHOUT updating its csum — simulated bitrot that the read
        path's checksum gate must catch."""
        with self._lock:
            o = self._obj(cid, oid)
            if not o["lextents"]:
                raise StoreError("ENOENT", "object has no data blobs")
            blob_id = o["lextents"][0][2]
            b = self._blobs[blob_id]
            self._block.seek(b["units"][0] * self.min_alloc)
            self._block.write(payload)
            self._block.flush()

    # --------------------------------------------------------- fsck
    def fsck(self) -> list[str]:
        """Verify every blob's at-rest checksum + onode references
        (ref: BlueStore::fsck)."""
        errors = []
        with self._lock:
            for cid, objs in self._colls.items():
                for oid, o in objs.items():
                    for loff, llen, blob_id, boff in o["lextents"]:
                        b = self._blobs.get(blob_id)
                        if b is None:
                            errors.append(
                                f"{cid}/{oid}: dangling blob {blob_id}")
                            continue
                        stored = self._read_stored(b)
                        if crc32c(0, stored) != b["csum"]:
                            errors.append(
                                f"{cid}/{oid}: csum mismatch in blob "
                                f"{blob_id}")
        return errors


class _TxnCtx:
    """One queue_transaction: shadow-validated metadata mutations +
    ordered block-file effects, committed atomically through the KV
    (ref: BlueStore TransContext)."""

    def __init__(self, store: BlueStore):
        self.s = store
        self.kv = store.db.transaction()
        self._colls: dict[str, dict] = {}        # shadow collections
        self._coll_meta: dict[str, dict | None] = {}
        self._onodes: dict[tuple, dict] = {}     # shadow onodes
        self._blob_shadow: dict[int, dict] = {}
        self._new_blobs: list[tuple[int, bytes]] = []  # id, stored
        self._deferred: list[tuple[int, bytes]] = []
        self._freed: list[int] = []
        self._omap_ops: list[tuple] = []
        self._removed_onodes: set = set()
        self._moved: list[tuple] = []

    # -- shadow views ---------------------------------------------------
    def colls_view(self):
        view = set(self.s._colls) | set(
            c for c, m in self._coll_meta.items() if m is not None)
        view -= {c for c, m in self._coll_meta.items() if m is None}
        return view

    def new_coll(self, cid: str, bits: int) -> None:
        self._coll_meta[cid] = {"bits": bits}
        self._colls[cid] = {}

    def rm_coll(self, cid: str) -> None:
        self.coll(cid)          # existence + emptiness checked by caller
        self._coll_meta[cid] = None
        self._colls.pop(cid, None)

    def coll(self, cid: str) -> dict:
        if cid in self._colls:
            return self._colls[cid]
        if self._coll_meta.get(cid, "absent") is None or \
                (cid not in self.s._colls and cid not in self._coll_meta):
            raise StoreError("ENOENT", f"no collection {cid}")
        c = dict(self.s._colls.get(cid, {}))
        self._colls[cid] = c
        return c

    def onode(self, cid: str, oid: ObjectId, create: bool = False) -> dict:
        key = (cid, oid)
        if key in self._onodes:
            return self._onodes[key]
        c = self.coll(cid)
        o = c.get(oid)
        if o is None:
            if not create:
                raise StoreError("ENOENT", f"no object {oid}")
            o = {"size": 0, "attrs": {}, "lextents": []}
        else:
            o = {"size": o["size"], "attrs": dict(o["attrs"]),
                 "lextents": [list(le) for le in o["lextents"]]}
        c[oid] = o
        self._onodes[key] = o
        self._removed_onodes.discard(key)
        return o

    def blob_mutable(self, blob_id: int) -> dict:
        b = self._blob_shadow.get(blob_id)
        if b is None:
            b = dict(self.s._blobs[blob_id])
            self._blob_shadow[blob_id] = b
        return b

    # -- effects --------------------------------------------------------
    def new_blob(self, raw: bytes) -> int:
        s = self.s
        stored, comp = raw, None
        if s.compression != "none" and len(raw) >= s.comp_min_len:
            packed = comp_mod.compress(raw, s.compression)
            if len(packed) < len(raw):
                stored, comp = packed, s.compression
        n_units = (len(stored) + s.min_alloc - 1) // s.min_alloc
        start = s._allocate(n_units)
        blob_id = s._next_blob
        s._next_blob += 1
        b = {"units": (start, n_units), "stored": len(stored),
             "raw": len(raw), "csum": crc32c(0, stored),
             "comp": comp, "refs": 1}
        self._blob_shadow[blob_id] = b
        self._new_blobs.append((blob_id, stored))
        return blob_id

    def defer(self, abs_off: int, data: bytes) -> None:
        self._deferred.append((abs_off, data))

    def gc_blobs(self, o: dict) -> None:
        # blob refcounts: decrement when an onode stops referencing;
        # resolved at commit over the final shadow state
        pass

    def remove(self, cid: str, oid: ObjectId) -> None:
        c = self.coll(cid)
        if oid not in c:
            raise StoreError("ENOENT", f"{cid}/{oid}")
        del c[oid]
        self._onodes.pop((cid, oid), None)
        self._removed_onodes.add((cid, oid))
        self._omap_ops.append(("clear", cid, oid))

    def clone(self, cid: str, oid: ObjectId, noid: ObjectId) -> None:
        c = self.coll(cid)
        if oid not in c:
            raise StoreError("ENOENT", f"{cid}/{oid}")
        src = c[oid]
        dst = {"size": src["size"], "attrs": dict(src["attrs"]),
               "lextents": [list(le) for le in src["lextents"]]}
        # blob reference increments resolve in commit()'s symmetric
        # lextent-count delta (an eager bump here would double-count)
        c[noid] = dst
        self._onodes[(cid, noid)] = dst
        self._removed_onodes.discard((cid, noid))
        # omap is cloned too (MemStore semantics)
        self._omap_ops.append(("clone", cid, oid, noid))

    def move(self, oldcid: str, oldoid: ObjectId, cid: str,
             oid: ObjectId) -> None:
        src = self.coll(oldcid)
        dst = self.coll(cid)
        o = src.pop(oldoid)
        dst[oid] = o
        self._onodes.pop((oldcid, oldoid), None)
        self._onodes[(cid, oid)] = o
        self._removed_onodes.add((oldcid, oldoid))
        self._removed_onodes.discard((cid, oid))
        self._omap_ops.append(("move", oldcid, oldoid, cid, oid))

    def omap_set(self, cid, oid, keys) -> None:
        self._omap_ops.append(("set", cid, oid, dict(keys)))

    def omap_rm(self, cid, oid, keys) -> None:
        self._omap_ops.append(("rm", cid, oid, list(keys)))

    def omap_clear(self, cid, oid) -> None:
        self._omap_ops.append(("clear", cid, oid))

    # -- commit ---------------------------------------------------------
    def abort(self) -> None:
        """Undo txn-local allocator effects after a failed op: units
        taken for new blobs go back to the free pool (the metadata
        shadow is simply dropped)."""
        s = self.s
        for blob_id, _stored in self._new_blobs:
            b = self._blob_shadow.get(blob_id)
            if b is None:
                continue
            start, count = b["units"]
            s._free.update(range(start, start + count))

    def commit(self) -> None:
        s = self.s
        # Blob reference resolution.  `refs` counts LEXTENT references
        # (a punch can split one lextent into two referencing the same
        # blob, a clone copies a whole map), so the delta must be
        # symmetric: splits INCREASE the count — a decrement-only
        # formula would free blob A while its tail lextent still
        # points at it (silent data loss once units are reused).
        refcount_after: dict[int, int] = {}
        touched = set(self._onodes) | self._removed_onodes
        for (cid, oid) in touched:
            c = self._colls.get(cid, {})
            o = c.get(oid)
            if o is None:
                continue
            for le in o["lextents"]:
                refcount_after[le[2]] = refcount_after.get(le[2], 0) + 1
        before: dict[int, int] = {}
        for (cid, oid) in touched:
            old = s._colls.get(cid, {}).get(oid)
            if old is None:
                continue
            for le in old["lextents"]:
                before[le[2]] = before.get(le[2], 0) + 1
        new_ids = {bid for bid, _ in self._new_blobs}
        for blob_id in set(before) | set(refcount_after) | new_ids:
            # new blobs carry refs=1 for the lextent that created them
            base = before.get(blob_id, 0) + \
                (1 if blob_id in new_ids else 0)
            delta = refcount_after.get(blob_id, 0) - base
            if delta == 0:
                continue
            b = self._blob_shadow.get(blob_id) or \
                dict(s._blobs.get(blob_id, {"refs": 0}))
            b["refs"] = b.get("refs", 1) + delta
            self._blob_shadow[blob_id] = b
            if b["refs"] <= 0:
                self._freed.append(blob_id)

        # 1) block-file writes for new blobs (free units; crash before
        #    the KV commit leaves unreferenced garbage, never torn data)
        for blob_id, stored in self._new_blobs:
            b = self._blob_shadow[blob_id]
            s._block.seek(b["units"][0] * s.min_alloc)
            s._block.write(stored)
        if self._new_blobs:
            s._block.flush()
            os.fsync(s._block.fileno())

        # 2) one atomic KV commit: onodes, blobs, colls, omap, deferred
        for cid, meta in self._coll_meta.items():
            if meta is None:
                self.kv.rmkey(P_COLL, cid)
            else:
                self.kv.set(P_COLL, cid, meta)
        for (cid, oid) in self._removed_onodes:
            self.kv.rmkey(P_ONODE, _okey(cid, oid))
        for (cid, oid), o in self._onodes.items():
            self.kv.set(P_ONODE, _okey(cid, oid), o)
        for blob_id in self._freed:
            self._blob_shadow.pop(blob_id, None)
            self.kv.rmkey(P_BLOB, str(blob_id))
        for blob_id, b in self._blob_shadow.items():
            self.kv.set(P_BLOB, str(blob_id), b)
        self._commit_omap()
        defer_keys = []
        for i, (abs_off, data) in enumerate(self._deferred):
            key = f"{abs_off}.{i}"
            defer_keys.append(key)
            self.kv.set(P_DEFER, key, {"off": abs_off, "data": data})
        s.db.submit_transaction(self.kv)

        # 3) apply deferred in place + clear the records
        if self._deferred:
            for abs_off, data in self._deferred:
                s._block.seek(abs_off)
                s._block.write(data)
            s._block.flush()
            os.fsync(s._block.fileno())
            t2 = s.db.transaction()
            for key in defer_keys:
                t2.rmkey(P_DEFER, key)
            s.db.submit_transaction(t2)

        # 4) in-memory cutover + unit free
        for cid, meta in self._coll_meta.items():
            if meta is None:
                s._colls.pop(cid, None)
        for cid, objs in self._colls.items():
            s._colls[cid] = objs
        for blob_id, b in self._blob_shadow.items():
            s._blobs[blob_id] = b
        for blob_id in self._freed:
            b = s._blobs.pop(blob_id, None)
            if b is not None:
                start, count = b["units"]
                s._free.update(range(start, start + count))

    def _commit_omap(self) -> None:
        s = self.s
        for op in self._omap_ops:
            kind = op[0]
            if kind == "set":
                _, cid, oid, keys = op
                pfx = f"M{_okey(cid, oid)}"
                for k, v in keys.items():
                    self.kv.set(pfx, k, bytes(v))
            elif kind == "rm":
                _, cid, oid, keys = op
                pfx = f"M{_okey(cid, oid)}"
                for k in keys:
                    self.kv.rmkey(pfx, k)
            elif kind == "clear":
                _, cid, oid = op
                self.kv.rmkeys_by_prefix(f"M{_okey(cid, oid)}")
            elif kind == "clone":
                _, cid, oid, noid = op
                src = s.db.get_by_prefix(f"M{_okey(cid, oid)}")
                # include keys set earlier in THIS txn
                pfx = f"M{_okey(cid, noid)}"
                self.kv.rmkeys_by_prefix(pfx)
                for k, v in src.items():
                    self.kv.set(pfx, k, v)
            elif kind == "move":
                _, oldcid, oldoid, cid, oid = op
                oldpfx = f"M{_okey(oldcid, oldoid)}"
                newpfx = f"M{_okey(cid, oid)}"
                vals = s.db.get_by_prefix(oldpfx)
                self.kv.rmkeys_by_prefix(oldpfx)
                for k, v in vals.items():
                    self.kv.set(newpfx, k, v)
