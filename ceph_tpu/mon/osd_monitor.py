"""OSDMonitor: the osdmap PaxosService — command engine + map authority.

Port of the reference's map-mutation path (ref: src/mon/OSDMonitor.cc):
commands split into *preprocess* (read-only, answered from the current
map) and *prepare* (mutations accumulated into ``pending_inc`` and
committed through Paxos).  The production entry point that makes the EC
plugins real is here: ``osd pool create ... erasure <profile>`` →
prepare_new_pool (OSDMonitor.cc:6333) → crush_rule_create_erasure
(:6458) → plugin ``create_rule`` — the same call chain the reference
drives through the mon.

Commands take cmdmap dicts ({"prefix": "osd pool create", ...}) like
the reference mon's parsed cmdmap; returns are (retcode, outs, outb).
"""
from __future__ import annotations

import copy

from ..common.log import dout
from ..crush.wrapper import CrushWrapper
from ..ec import registry as ec_registry
from ..osd.osdmap import (CEPH_OSD_EXISTS, CEPH_OSD_IN, CEPH_OSD_UP,
                          Incremental, OSDMap)
from ..osd.types import (PG, PGPool, POOL_TYPE_ERASURE,
                         POOL_TYPE_REPLICATED)
from ..msg import encoding as wire
from .paxos import Paxos, PaxosService
from .store import StoreTransaction

EEXIST, ENOENT, EINVAL, EPERM, EALREADY, EBUSY = 17, 2, 22, 1, 114, 16
EOPNOTSUPP = 95

# the reference's default profile (osd_pool_default_erasure_code_profile,
# src/common/options.cc) is jerasure k=2 m=1; ours defaults to the tpu
# plugin — the batched MXU coder — with the same geometry
DEFAULT_EC_PROFILE = {"plugin": "tpu", "k": "2", "m": "1",
                      "crush-failure-domain": "host"}


class OSDMonitor(PaxosService):
    """(ref: src/mon/OSDMonitor.h:537)."""

    def __init__(self, paxos: Paxos, initial_map: OSDMap | None = None,
                 initial_wrapper: CrushWrapper | None = None):
        super().__init__("osdmap", paxos)
        self.osdmap = OSDMap()
        self.wrapper = CrushWrapper()      # names for osdmap.crush
        self._initial_map = initial_map
        self._initial_wrapper = initial_wrapper
        self.pending_inc = Incremental()
        self._pending_wrapper: CrushWrapper | None = None
        self._bootstrap: tuple | None = None

    # ------------------------------------------------------- paxos hooks
    def create_initial(self) -> None:
        """(ref: OSDMonitor.cc:220 create_initial)."""
        if self._initial_map is not None:
            m = self._initial_map
            w = self._initial_wrapper or CrushWrapper()
            w.crush = m.crush
        else:
            m = OSDMap()
            m.epoch = 1
            w = CrushWrapper.build_flat(0)
            m.crush = w.crush
        self.pending_inc = Incremental(epoch=m.epoch)
        self._bootstrap = (m, w)

    def encode_pending(self, tx: StoreTransaction) -> None:
        """Write the inc + resulting full map at the new epoch
        (ref: OSDMonitor.cc:1350 encode_pending)."""
        if getattr(self, "_bootstrap", None) is not None:
            m, w = self._bootstrap
            self._bootstrap = None
            e = m.epoch
            self.put_version(tx, f"inc_{e}", None)
            self.put_version(tx, f"full_{e}", wire.encode((m, w)))
            self.put_version(tx, "last_committed", e)
            self.put_version(tx, "first_committed", e)
            return
        if self._is_pending_empty():
            return
        e = self.pending_inc.epoch
        nm = self.osdmap.clone()
        inc = copy.deepcopy(self.pending_inc)
        nm.apply_incremental(inc)
        w = self._pending_wrapper or self.wrapper
        w = copy.deepcopy(w)
        w.crush = nm.crush
        self.put_version(tx, f"inc_{e}", wire.encode(inc))
        self.put_version(tx, f"full_{e}", wire.encode((nm, w)))
        self.put_version(tx, "last_committed", e)
        # trim history beyond mon_min_osdmap_epochs
        # (ref: OSDMonitor.cc get_trim_to / PaxosService maybe_trim)
        from ..common.options import global_config
        keep = global_config()["mon_min_osdmap_epochs"]
        first = self.get_first_committed() or 1
        if e - first > keep:
            new_first = e - keep
            for v in range(first, new_first):
                tx.erase(self.service_name, f"inc_{v}")
                tx.erase(self.service_name, f"full_{v}")
            self.put_version(tx, "first_committed", new_first)

    def update_from_paxos(self) -> None:
        """Load the latest committed full map
        (ref: OSDMonitor.cc:370 update_from_paxos)."""
        e = self.get_last_committed()
        if e and e != self.osdmap.epoch:
            blob = self.get_version(f"full_{e}")
            self.osdmap, self.wrapper = wire.decode(blob)

    def create_pending(self) -> None:
        self.pending_inc = Incremental(epoch=self.osdmap.epoch + 1)
        self._pending_wrapper = None

    def _is_pending_empty(self) -> bool:
        blank = Incremental(epoch=self.pending_inc.epoch)
        return self.pending_inc == blank and self._pending_wrapper is None

    # ------------------------------------------------------ map history
    def get_full_map(self, epoch: int = 0) -> OSDMap | None:
        e = epoch or self.get_last_committed()
        blob = self.get_version(f"full_{e}")
        return wire.decode(blob)[0] if blob is not None else None

    def get_incremental(self, epoch: int) -> Incremental | None:
        blob = self.get_version(f"inc_{epoch}")
        return wire.decode(blob) if blob is not None else None

    # ------------------------------------------------------------- crush
    def _get_pending_crush(self) -> CrushWrapper:
        """Working copy for this command's crush mutation
        (ref: OSDMonitor.cc:383 _get_pending_crush)."""
        if self._pending_wrapper is not None:
            return self._pending_wrapper
        w = copy.deepcopy(self.wrapper)
        if self.pending_inc.new_crush is not None:
            w.crush = self.pending_inc.new_crush
        return w

    def _commit_pending_crush(self, w: CrushWrapper) -> None:
        self._pending_wrapper = w
        self.pending_inc.new_crush = w.crush

    # -------------------------------------------------------- ec profile
    def _get_profile(self, name: str) -> dict | None:
        """Pending-over-committed profile lookup, with the implicit
        'default' (ref: OSDMonitor.cc get_erasure_code_profile)."""
        if name in self.pending_inc.new_erasure_code_profiles:
            return self.pending_inc.new_erasure_code_profiles[name]
        if name in self.osdmap.erasure_code_profiles:
            return self.osdmap.erasure_code_profiles[name]
        if name == "default":
            return dict(DEFAULT_EC_PROFILE)
        return None

    def get_erasure_code(self, profile_name: str):
        """profile -> plugin instance (ref: OSDMonitor.cc:6495)."""
        profile = self._get_profile(profile_name)
        if profile is None:
            raise KeyError(f"no erasure-code-profile {profile_name!r}")
        plugin = profile.get("plugin")
        if not plugin:
            raise ValueError(
                f"profile {profile_name!r} has no plugin= entry")
        return ec_registry.factory(plugin, profile)

    def crush_rule_create_erasure(self, name: str,
                                  profile_name: str) -> int:
        """(ref: OSDMonitor.cc:6458)."""
        rid = self.wrapper.get_rule_id(name)
        if rid >= 0:
            return rid
        newcrush = self._get_pending_crush()
        rid = newcrush.get_rule_id(name)
        if rid >= 0:
            self._commit_pending_crush(newcrush)
            return rid
        ec = self.get_erasure_code(profile_name)
        rid = ec.create_rule(name, newcrush)
        self._commit_pending_crush(newcrush)
        return rid

    # -------------------------------------------------------- pool create
    def _prepare_pool_size(self, pool_type: int, profile_name: str,
                           repl_size: int) -> tuple[int, int]:
        """(size, min_size) (ref: OSDMonitor.cc:6657)."""
        if pool_type == POOL_TYPE_REPLICATED:
            size = repl_size or 3
            return size, max(1, size - size // 2)
        ec = self.get_erasure_code(profile_name)
        size = ec.get_chunk_count()
        k = ec.get_data_chunk_count()
        m = ec.get_coding_chunk_count()
        return size, k + min(1, m - 1)

    def prepare_new_pool(self, name: str, pg_num: int, pool_type: int,
                         erasure_code_profile: str = "",
                         crush_rule_name: str = "",
                         repl_size: int = 0) -> tuple[int, str]:
        """(ref: OSDMonitor.cc:6333 prepare_new_pool / :6849
        prepare_command pool create path)."""
        if name in self.osdmap.pool_names.values() or \
                name in self.pending_inc.new_pool_names.values():
            return -EEXIST, f"pool '{name}' already exists"
        if pool_type == POOL_TYPE_ERASURE:
            profile = erasure_code_profile or "default"
            if self._get_profile(profile) is None:
                return -ENOENT, \
                    f"erasure-code-profile {profile} does not exist"
            rule_name = crush_rule_name or name
            try:
                rule = self.crush_rule_create_erasure(rule_name, profile)
            except (KeyError, ValueError) as ex:
                return -EINVAL, str(ex)
        else:
            profile = ""
            if crush_rule_name:
                rule = self.wrapper.get_rule_id(crush_rule_name)
                if rule < 0:
                    return -ENOENT, \
                        f"crush rule {crush_rule_name} does not exist"
            else:
                # first replicated rule (ref: get_osd_pool_default_
                # crush_replicated_ruleset)
                rule = next(
                    (i for i, r in enumerate(self.osdmap.crush.rules)
                     if r is not None and r.mask.type ==
                     POOL_TYPE_REPLICATED), -1)
                if rule < 0:
                    return -ENOENT, "no default replicated crush rule"
        try:
            size, min_size = self._prepare_pool_size(
                pool_type, profile, repl_size)
        except (KeyError, ValueError) as ex:
            return -EINVAL, str(ex)
        pool_id = max([self.osdmap.pool_max] +
                      list(self.pending_inc.new_pools)) + 1
        crush = self._pending_wrapper.crush if self._pending_wrapper \
            else self.osdmap.crush
        ruleset = crush.rules[rule].mask.ruleset
        self.pending_inc.new_pools[pool_id] = PGPool(
            type=pool_type, size=size, min_size=min_size,
            crush_rule=ruleset, pg_num=pg_num, pgp_num=pg_num,
            erasure_code_profile=profile)
        self.pending_inc.new_pool_names[pool_id] = name
        dout("mon", 10).write("prepare_new_pool %s id %d rule %d",
                              name, pool_id, rule)
        return 0, f"pool '{name}' created"

    # ------------------------------------------------------------ lookup
    def _pool_by_name(self, name: str) -> int | None:
        for pid, n in self.osdmap.pool_names.items():
            if n == name:
                return pid
        return None

    def _resolve_osd(self, spec) -> int | None:
        if isinstance(spec, int):
            osd = spec
        else:
            s = str(spec)
            osd = int(s[4:] if s.startswith("osd.") else s)
        return osd if 0 <= osd < self.osdmap.max_osd else None

    # ---------------------------------------------------------- commands
    def preprocess_command(self, cmdmap: dict
                           ) -> tuple[int, str, object] | None:
        """Read-only commands (ref: OSDMonitor.cc:759
        preprocess_command); returns (r, outs, outb), or None when the
        command is not a read command (caller routes to prepare)."""
        prefix = cmdmap.get("prefix", "")
        m = self.osdmap
        if prefix == "osd stat":
            n_up = sum(1 for o in range(m.max_osd) if m.is_up(o))
            n_in = sum(1 for o in range(m.max_osd) if m.is_in(o))
            n = sum(1 for o in range(m.max_osd) if m.exists(o))
            outs = (f"e{m.epoch}: {n} osds: {n_up} up, {n_in} in")
            return 0, outs, {"epoch": m.epoch, "num_osds": n,
                             "num_up_osds": n_up, "num_in_osds": n_in}
        if prefix == "osd getmap":
            epoch = int(cmdmap.get("epoch", 0))
            full = self.get_full_map(epoch)
            if full is None:
                return -ENOENT, f"there is no map for epoch {epoch}", None
            return 0, f"got osdmap epoch {full.epoch}", full
        if prefix == "osd ls":
            osds = [o for o in range(m.max_osd) if m.exists(o)]
            return 0, "\n".join(str(o) for o in osds), osds
        if prefix == "osd dump":
            return 0, "", self._dump()
        if prefix == "osd tree":
            return 0, self._tree_text(), None
        if prefix == "osd erasure-code-profile ls":
            names = sorted(set(m.erasure_code_profiles) | {"default"})
            return 0, "\n".join(names), names
        if prefix == "osd erasure-code-profile get":
            name = cmdmap.get("name", "")
            p = self._get_profile(name)
            if p is None:
                return -ENOENT, f"unknown erasure code profile '{name}'", \
                    None
            outs = "\n".join(f"{k}={v}" for k, v in sorted(p.items()))
            return 0, outs, p
        if prefix == "osd pool ls":
            names = [m.pool_names[p] for p in sorted(m.pools)]
            return 0, "\n".join(names), names
        if prefix == "osd pool get":
            pid = self._pool_by_name(cmdmap.get("pool", ""))
            if pid is None:
                return -ENOENT, \
                    f"unrecognized pool '{cmdmap.get('pool')}'", None
            pool = m.pools[pid]
            var = cmdmap.get("var", "")
            vals = {"size": pool.size, "min_size": pool.min_size,
                    "pg_num": pool.pg_num, "pgp_num": pool.pgp_num,
                    "crush_rule": pool.crush_rule,
                    "erasure_code_profile": pool.erasure_code_profile}
            if var not in vals:
                return -EINVAL, f"invalid pool variable {var}", None
            return 0, f"{var}: {vals[var]}", vals[var]
        if prefix == "pg map":
            pgid = cmdmap.get("pgid", "")
            pool_s, _, ps_s = str(pgid).partition(".")
            pg = PG(int(pool_s), int(ps_s, 16))
            up, up_p, acting, acting_p = m.pg_to_up_acting_osds(pg)
            return 0, (f"osdmap e{m.epoch} pg {pgid} -> up {up} "
                       f"acting {acting}"), \
                {"up": up, "up_primary": up_p, "acting": acting,
                 "acting_primary": acting_p}
        return None

    def prepare_command(self, cmdmap: dict) -> tuple[int, str, object]:
        """Mutating commands — stage into pending_inc; caller proposes
        (ref: OSDMonitor.cc:6849 prepare_command)."""
        prefix = cmdmap.get("prefix", "")
        m = self.osdmap
        if prefix == "osd setmaxosd":
            n = int(cmdmap["newmax"])
            self.pending_inc.new_max_osd = n
            return 0, f"set new max_osd = {n}", None
        if prefix == "osd pool create":
            name = cmdmap["pool"]
            pg_num = int(cmdmap.get("pg_num", 0)) or 32
            ptype = {"replicated": POOL_TYPE_REPLICATED,
                     "erasure": POOL_TYPE_ERASURE}.get(
                cmdmap.get("pool_type", "replicated"))
            if ptype is None:
                return -EINVAL, \
                    f"unknown pool type {cmdmap.get('pool_type')}", None
            r, outs = self.prepare_new_pool(
                name, pg_num, ptype,
                erasure_code_profile=cmdmap.get(
                    "erasure_code_profile", ""),
                crush_rule_name=cmdmap.get("rule", ""),
                repl_size=int(cmdmap.get("size", 0)))
            return r, outs, None
        if prefix == "osd pool delete":
            pid = self._pool_by_name(cmdmap.get("pool", ""))
            if pid is None:
                return -ENOENT, "pool does not exist", None
            if cmdmap.get("yes_i_really_really_mean_it") not in (
                    True, "true", "--yes-i-really-really-mean-it"):
                return -EPERM, \
                    ("WARNING: this will PERMANENTLY DESTROY all data "
                     "in the pool; pass yes_i_really_really_mean_it "
                     "to proceed"), None
            self.pending_inc.old_pools.append(pid)
            return 0, f"pool '{cmdmap['pool']}' removed", None
        if prefix == "osd pool selfmanaged-snap create":
            # allocate a snapid the CLIENT manages (ref:
            # OSDMonitor's selfmanaged_snap path /
            # rados_ioctx_selfmanaged_snap_create): snap_seq bumps,
            # pool.snaps does NOT record it — the snapc travels with
            # client IO instead
            pid = self._pool_by_name(cmdmap.get("pool", ""))
            if pid is None:
                return -ENOENT, "pool does not exist", None
            pool = self.pending_inc.new_pools.get(pid) or \
                copy.deepcopy(m.pools[pid])
            if pool.is_erasure():
                return -EOPNOTSUPP, \
                    "snapshots on erasure-coded pools are not " \
                    "supported here", None
            pool.snap_seq += 1
            self.pending_inc.new_pools[pid] = pool
            return 0, "", pool.snap_seq
        if prefix == "osd pool selfmanaged-snap rm":
            pid = self._pool_by_name(cmdmap.get("pool", ""))
            if pid is None:
                return -ENOENT, "pool does not exist", None
            # record removal so no future SnapContext covers the id
            # (clone trimming stays lazy, like a never-running snap
            # trimmer)
            sid = int(cmdmap.get("snapid", 0))
            if sid > 0:
                pool = self.pending_inc.new_pools.get(pid) or \
                    copy.deepcopy(m.pools[pid])
                pool.removed_snaps = sorted(
                    set(pool.removed_snaps) | {sid})
                self.pending_inc.new_pools[pid] = pool
            return 0, "", None
        if prefix in ("osd pool mksnap", "osd pool rmsnap"):
            # pool snapshots (ref: OSDMonitor.cc prepare_command
            # "osd pool mksnap" -> pg_pool_t::add_snap, snap_seq bump)
            pid = self._pool_by_name(cmdmap.get("pool", ""))
            if pid is None:
                return -ENOENT, "pool does not exist", None
            snap = cmdmap.get("snap", "")
            if not snap:
                return -EINVAL, "missing snap name", None
            pool = self.pending_inc.new_pools.get(pid) or \
                copy.deepcopy(m.pools[pid])
            if prefix == "osd pool mksnap":
                if pool.is_erasure():
                    return -EOPNOTSUPP, \
                        "pool snapshots on erasure-coded pools are " \
                        "not supported here", None
                if snap in pool.snaps.values():
                    return -EEXIST, f"snap {snap} already exists", None
                pool.snap_seq += 1
                pool.snaps = dict(pool.snaps)
                pool.snaps[pool.snap_seq] = snap
                outs = f"created pool {cmdmap['pool']} snap {snap}"
            else:
                sid = next((i for i, n in pool.snaps.items()
                            if n == snap), None)
                if sid is None:
                    return -ENOENT, f"snap {snap} does not exist", None
                pool.snaps = {i: n for i, n in pool.snaps.items()
                              if i != sid}
                pool.removed_snaps = sorted(
                    set(pool.removed_snaps) | {sid})
                outs = f"removed pool {cmdmap['pool']} snap {snap}"
            self.pending_inc.new_pools[pid] = pool
            return 0, outs, None
        if prefix == "osd pool set":
            pid = self._pool_by_name(cmdmap.get("pool", ""))
            if pid is None:
                return -ENOENT, "pool does not exist", None
            pool = self.pending_inc.new_pools.get(pid) or \
                copy.deepcopy(m.pools[pid])
            var, val = cmdmap.get("var", ""), cmdmap.get("val", "")
            if var == "size":
                if pool.is_erasure():
                    return -EPERM, \
                        "can not change the size of an erasure-coded " \
                        "pool", None
                pool.size = int(val)
                pool.min_size = max(1, int(val) - int(val) // 2)
            elif var == "min_size":
                pool.min_size = int(val)
            elif var in ("pg_num", "pgp_num"):
                n = int(val)
                if var == "pg_num" and n < pool.pg_num:
                    return -EPERM, "pg_num reduction not supported", None
                if var == "pgp_num":
                    if n > pool.pg_num:
                        return -EINVAL, \
                            "pgp_num must not exceed pg_num", None
                    if n < pool.pgp_num:
                        return -EPERM, \
                            "pgp_num reduction not supported", None
                    # growth reseeds split PGs' placement; the peering
                    # statecharts' prior-interval queries + backfill
                    # chase the relocated data — replicated via
                    # osd/peering.py, EC via osd/ec_peering.py's
                    # cross-set chunk sources + pg_temp override
                setattr(pool, var, n)
                if var == "pg_num":
                    pool.pgp_num = min(pool.pgp_num, n)
            elif var == "crush_rule":
                rid = self.wrapper.get_rule_id(str(val))
                if rid < 0:
                    return -ENOENT, f"crush rule {val} does not exist", \
                        None
                pool.crush_rule = m.crush.rules[rid].mask.ruleset
            else:
                return -EINVAL, f"unrecognized variable '{var}'", None
            pool.calc_pg_masks()
            self.pending_inc.new_pools[pid] = pool
            return 0, f"set pool {pid} {var} to {val}", None
        if prefix == "osd erasure-code-profile set":
            name = cmdmap["name"]
            profile = dict(cmdmap.get("profile", {}))
            existing = self._get_profile(name)
            if existing is not None and existing != profile and \
                    not cmdmap.get("force"):
                return -EPERM, \
                    (f"will not override erasure code profile {name} "
                     "because the existing profile is different; pass "
                     "force=true to override"), None
            profile.setdefault("plugin", DEFAULT_EC_PROFILE["plugin"])
            # validate by instantiating
            try:
                ec_registry.factory(profile["plugin"], profile)
            except Exception as ex:
                return -EINVAL, f"invalid profile: {ex}", None
            self.pending_inc.new_erasure_code_profiles[name] = profile
            return 0, "", None
        if prefix == "osd erasure-code-profile rm":
            name = cmdmap["name"]
            for pid, pool in m.pools.items():
                if pool.erasure_code_profile == name:
                    return -EBUSY, \
                        (f"erasure code profile {name} is in use by "
                         f"pool {m.pool_names[pid]}"), None
            if name in m.erasure_code_profiles:
                self.pending_inc.old_erasure_code_profiles.append(name)
            return 0, "", None
        if prefix in ("osd down", "osd out", "osd in"):
            spec = cmdmap.get("ids", cmdmap.get("id"))
            specs = spec if isinstance(spec, list) else [spec]
            outs = []
            for s in specs:
                osd = self._resolve_osd(s)
                if osd is None:
                    return -EINVAL, f"osd id {s} does not exist", None
                if prefix == "osd down":
                    if m.is_down(osd):
                        outs.append(f"osd.{osd} is already down.")
                    else:
                        self.pending_inc.new_state[osd] = \
                            self.pending_inc.new_state.get(osd, 0) | \
                            CEPH_OSD_UP
                        outs.append(f"marked down osd.{osd}.")
                elif prefix == "osd out":
                    if m.is_out(osd):
                        outs.append(f"osd.{osd} is already out.")
                    else:
                        self.pending_inc.new_weight[osd] = 0
                        outs.append(f"marked out osd.{osd}.")
                else:
                    if m.is_in(osd):
                        outs.append(f"osd.{osd} is already in.")
                    else:
                        self.pending_inc.new_weight[osd] = CEPH_OSD_IN
                        outs.append(f"marked in osd.{osd}.")
            return 0, " ".join(outs), None
        if prefix == "osd reweight":
            osd = self._resolve_osd(cmdmap.get("id"))
            if osd is None:
                return -EINVAL, "osd does not exist", None
            w = float(cmdmap["weight"])
            if not 0.0 <= w <= 1.0:
                return -EINVAL, "weight must be in [0, 1]", None
            self.pending_inc.new_weight[osd] = int(w * CEPH_OSD_IN)
            return 0, f"reweighted osd.{osd} to {w}", None
        if prefix == "osd primary-affinity":
            osd = self._resolve_osd(cmdmap.get("id"))
            if osd is None:
                return -EINVAL, "osd does not exist", None
            w = float(cmdmap["weight"])
            self.pending_inc.new_primary_affinity[osd] = \
                int(w * 0x10000)
            return 0, f"set osd.{osd} primary-affinity to {w}", None
        if prefix == "osd upmap-batch":
            # one proposal for a whole balancer plan (the reference
            # batches via paxos round coalescing; an epoch per item
            # would flood every subscriber with incrementals)
            n = 0
            for pgid in cmdmap.get("rm", []):
                r, outs, _ = self.prepare_command(
                    {"prefix": "osd rm-pg-upmap-items", "pgid": pgid})
                if r != 0:
                    return r, f"rm {pgid}: {outs}", None
                n += 1
            for pgid, pairs in cmdmap.get("set", []):
                r, outs, _ = self.prepare_command(
                    {"prefix": "osd pg-upmap-items", "pgid": pgid,
                     "id_pairs": pairs})
                if r != 0:
                    return r, f"set {pgid}: {outs}", None
                n += 1
            return 0, f"staged {n} upmap changes", None
        if prefix in ("osd pg-upmap-items", "osd rm-pg-upmap-items"):
            pgid = str(cmdmap["pgid"])
            pool_s, _, ps_s = pgid.partition(".")
            pg = PG(int(pool_s), int(ps_s, 16))
            if pg.pool not in m.pools or \
                    pg.ps >= m.pools[pg.pool].pg_num:
                return -ENOENT, f"pg {pgid} does not exist", None
            if prefix == "osd rm-pg-upmap-items":
                self.pending_inc.old_pg_upmap_items.append(pg)
                return 0, f"no change (removed upmap for {pgid})", None
            pairs = cmdmap.get("id_pairs", [])
            items = [(int(a), int(b)) for a, b in pairs]
            for frm, to in items:
                if not (0 <= to < m.max_osd):
                    return -ENOENT, f"osd.{to} does not exist", None
            self.pending_inc.new_pg_upmap_items[pg] = items
            return 0, f"set {pgid} pg_upmap_items mapping to {items}", \
                None
        return -ENOENT, f"unknown command {prefix!r}", None

    # ------------------------------------------------------------- dumps
    def _dump(self) -> dict:
        m = self.osdmap
        return {
            "epoch": m.epoch,
            "max_osd": m.max_osd,
            "pools": [{
                "pool": pid, "pool_name": m.pool_names.get(pid, ""),
                "type": p.type, "size": p.size, "min_size": p.min_size,
                "pg_num": p.pg_num, "crush_rule": p.crush_rule,
                "erasure_code_profile": p.erasure_code_profile,
            } for pid, p in sorted(m.pools.items())],
            "osds": [{
                "osd": o, "up": int(m.is_up(o)), "in": int(m.is_in(o)),
                "weight": m.osd_weight[o] / CEPH_OSD_IN,
            } for o in range(m.max_osd) if m.exists(o)],
            "pg_upmap_items": [
                {"pgid": str(pg), "mappings": items}
                for pg, items in sorted(m.pg_upmap_items.items())],
            "erasure_code_profiles": dict(m.erasure_code_profiles),
        }

    def _tree_text(self) -> str:
        w = self.wrapper
        lines = ["ID  WEIGHT    TYPE NAME"]

        def walk(item: int, depth: int) -> None:
            b = w.crush.bucket(item)
            if b is None:
                name = w.get_item_name(item) or f"osd.{item}"
                lines.append(f"{item:3d} {'':{depth * 2}}{name}")
                return
            tname = w.type_map.get(b.type, str(b.type))
            name = w.get_item_name(item) or ""
            lines.append(
                f"{item:3d} {b.weight / 0x10000:8.4f}  "
                f"{'':{depth * 2}}{tname} {name}")
            for child in b.items:
                walk(child, depth + 1)

        children = {c for b in w.crush.buckets if b is not None
                    for c in b.items}
        roots = [b.id for b in w.crush.buckets
                 if b is not None and b.id not in children]
        for r in sorted(roots, reverse=True):
            walk(r, 0)
        return "\n".join(lines)
