"""MemStore: in-memory ObjectStore (model: src/os/memstore/MemStore.cc).

Objects are bytearrays + attr/omap dicts, collections are dicts.  A
transaction is validated against a shadow view first, then applied, so
`queue_transaction` is atomic: a failing op leaves the store untouched
(the reference instead asserts mid-apply — MemStore.cc
_do_transaction's unhandled-op abort; a Python framework can do
better).

Supports the `objectstore_debug_inject_read_err` config: objects
marked via `inject_read_err` fail reads with EIO until cleared
(ref: filestore_debug_inject_read_err option and
FileStore::debug_obj_on_delete semantics, src/common/options.cc:4851).
"""
from __future__ import annotations

import copy
import threading

from ..common.lockdep import make_lock

from ..common.options import global_config
from .objectstore import (ObjectId, ObjectStore, StoreError, Transaction,
                          OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE,
                          OP_REMOVE, OP_SETATTRS, OP_RMATTR, OP_RMATTRS,
                          OP_CLONE, OP_CLONE_RANGE, OP_MKCOLL, OP_RMCOLL,
                          OP_COLL_MOVE_RENAME, OP_OMAP_CLEAR,
                          OP_OMAP_SETKEYS, OP_OMAP_RMKEYS)


class _Object:
    __slots__ = ("data", "xattr", "omap")

    def __init__(self):
        self.data = bytearray()
        self.xattr: dict = {}
        self.omap: dict[str, bytes] = {}

    def clone(self) -> "_Object":
        o = _Object()
        o.data = bytearray(self.data)
        o.xattr = copy.deepcopy(self.xattr)
        o.omap = dict(self.omap)
        return o


# wire registration: the JournaledStore snapshot/WAL serializes whole
# collections through the typed codec (no pickle anywhere near disk)
from ..msg.encoding import register_struct as _reg  # noqa: E402

_reg(_Object, version=1, compat=1, fields=("data", "xattr", "omap"))


class MemStore(ObjectStore):
    def __init__(self, path: str = "mem"):
        self.path = path
        self.colls: dict[str, dict[ObjectId, _Object]] = {}
        self.mounted = False
        self._lock = make_lock(f"memstore.{path}")
        self._read_err_objs: set[tuple[str, ObjectId]] = set()

    # -- lifecycle ------------------------------------------------------
    def mkfs(self) -> None:
        self.colls = {}

    def mount(self) -> None:
        self.mounted = True

    def umount(self) -> None:
        self.mounted = False

    # -- fault injection ------------------------------------------------
    def inject_read_err(self, cid: str, oid: ObjectId) -> None:
        self._read_err_objs.add((cid, oid))

    def clear_read_err(self, cid: str, oid: ObjectId) -> None:
        self._read_err_objs.discard((cid, oid))

    # -- txn apply ------------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            # validate+apply on a copy-on-write shadow of the touched
            # collections (populated lazily by _get_coll), then swap
            # in — atomicity without deep-copying the whole store
            shadow: dict[str, dict] = {}
            created: set[str] = set()
            removed: set[str] = set()
            # copy-on-write object identity: clone an object before its
            # first mutation inside this txn
            dirtied: set[int] = set()
            for op in txn.ops:
                self._apply(op, shadow, created, removed, dirtied)
            for cid in removed:
                self.colls.pop(cid, None)
            for cid, objs in shadow.items():
                self.colls[cid] = objs

    def _get_coll(self, shadow, cid: str, created, removed):
        if cid in removed:
            raise StoreError("ENOENT", f"collection {cid} removed in txn")
        c = shadow.get(cid)
        if c is None:
            if cid in self.colls and cid not in created:
                c = shadow[cid] = dict(self.colls[cid])
            else:
                raise StoreError("ENOENT", f"no collection {cid}")
        return c

    def _mutable(self, coll: dict, oid: ObjectId, dirtied: set,
                 create: bool = False) -> _Object:
        o = coll.get(oid)
        if o is None:
            if not create:
                raise StoreError("ENOENT", f"no object {oid}")
            o = coll[oid] = _Object()
            dirtied.add(id(o))
            return o
        if id(o) not in dirtied:
            o = o.clone()
            coll[oid] = o
            dirtied.add(id(o))
        return o

    def _apply(self, op, shadow, created, removed, dirtied) -> None:
        code = op[0]
        if code == OP_MKCOLL:
            _, cid, _bits = op
            if cid in self.colls and cid not in removed or cid in shadow:
                raise StoreError("EEXIST", f"collection {cid}")
            removed.discard(cid)
            created.add(cid)
            shadow[cid] = {}
            return
        if code == OP_RMCOLL:
            _, cid = op
            c = self._get_coll(shadow, cid, created, removed)
            if c:
                raise StoreError("ENOTEMPTY", f"collection {cid}")
            shadow.pop(cid, None)
            created.discard(cid)
            removed.add(cid)
            return
        if code == OP_COLL_MOVE_RENAME:
            _, oldcid, oldoid, cid, oid = op
            src = self._get_coll(shadow, oldcid, created, removed)
            dst = self._get_coll(shadow, cid, created, removed)
            if oldoid not in src:
                raise StoreError("ENOENT", f"{oldcid}/{oldoid}")
            if oid in dst and not (cid == oldcid and oid == oldoid):
                raise StoreError("EEXIST", f"{cid}/{oid}")
            dst[oid] = src.pop(oldoid)
            return

        cid, oid = op[1], op[2]
        coll = self._get_coll(shadow, cid, created, removed)
        if code == OP_TOUCH:
            self._mutable(coll, oid, dirtied, create=True)
        elif code == OP_WRITE:
            _, _, _, off, data = op
            o = self._mutable(coll, oid, dirtied, create=True)
            end = off + len(data)
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[off:end] = data
        elif code == OP_ZERO:
            _, _, _, off, length = op
            o = self._mutable(coll, oid, dirtied, create=True)
            end = off + length
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[off:end] = b"\0" * length
        elif code == OP_TRUNCATE:
            _, _, _, size = op
            o = self._mutable(coll, oid, dirtied)
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif code == OP_REMOVE:
            if oid not in coll:
                raise StoreError("ENOENT", f"{cid}/{oid}")
            del coll[oid]
        elif code == OP_SETATTRS:
            _, _, _, attrs = op
            o = self._mutable(coll, oid, dirtied, create=True)
            o.xattr.update(attrs)
        elif code == OP_RMATTR:
            _, _, _, name = op
            o = self._mutable(coll, oid, dirtied)
            o.xattr.pop(name, None)
        elif code == OP_RMATTRS:
            o = self._mutable(coll, oid, dirtied)
            o.xattr.clear()
        elif code == OP_CLONE:
            _, _, _, noid = op
            if oid not in coll:
                raise StoreError("ENOENT", f"{cid}/{oid}")
            coll[noid] = coll[oid].clone()
            dirtied.add(id(coll[noid]))
        elif code == OP_CLONE_RANGE:
            _, _, _, noid, srcoff, length, dstoff = op
            if oid not in coll:
                raise StoreError("ENOENT", f"{cid}/{oid}")
            src = coll[oid].data[srcoff:srcoff + length]
            o = self._mutable(coll, noid, dirtied, create=True)
            end = dstoff + len(src)
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[dstoff:end] = src
        elif code == OP_OMAP_CLEAR:
            o = self._mutable(coll, oid, dirtied)
            o.omap.clear()
        elif code == OP_OMAP_SETKEYS:
            _, _, _, keys = op
            o = self._mutable(coll, oid, dirtied, create=True)
            o.omap.update(keys)
        elif code == OP_OMAP_RMKEYS:
            _, _, _, keys = op
            o = self._mutable(coll, oid, dirtied)
            for key in keys:
                o.omap.pop(key, None)
        else:
            raise StoreError("EOPNOTSUPP", f"unknown op {code}")

    # -- read side ------------------------------------------------------
    def _obj(self, cid: str, oid: ObjectId) -> _Object:
        c = self.colls.get(cid)
        if c is None:
            raise StoreError("ENOENT", f"no collection {cid}")
        o = c.get(oid)
        if o is None:
            raise StoreError("ENOENT", f"{cid}/{oid}")
        return o

    def read(self, cid: str, oid: ObjectId, off: int = 0,
             length: int = 0) -> bytes:
        with self._lock:
            if ((cid, oid) in self._read_err_objs
                    and global_config()["objectstore_debug_inject_read_err"]):
                raise StoreError("EIO", f"injected read error {cid}/{oid}")
            o = self._obj(cid, oid)
            if length == 0:
                length = len(o.data) - off
            return bytes(o.data[off:off + length])

    def stat(self, cid: str, oid: ObjectId) -> dict:
        with self._lock:
            o = self._obj(cid, oid)
            return {"size": len(o.data)}

    def exists(self, cid: str, oid: ObjectId) -> bool:
        with self._lock:
            c = self.colls.get(cid)
            return c is not None and oid in c

    def getattr(self, cid: str, oid: ObjectId, name: str):
        with self._lock:
            o = self._obj(cid, oid)
            if name not in o.xattr:
                raise StoreError("ENODATA", f"{oid} xattr {name}")
            return o.xattr[name]

    def getattrs(self, cid: str, oid: ObjectId) -> dict:
        with self._lock:
            return dict(self._obj(cid, oid).xattr)

    def omap_get(self, cid: str, oid: ObjectId) -> dict[str, bytes]:
        with self._lock:
            return dict(self._obj(cid, oid).omap)

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self.colls)

    def collection_exists(self, cid: str) -> bool:
        with self._lock:
            return cid in self.colls

    def collection_list(self, cid: str) -> list[ObjectId]:
        with self._lock:
            c = self.colls.get(cid)
            if c is None:
                raise StoreError("ENOENT", f"no collection {cid}")
            return sorted(c)

    def statfs(self) -> dict:
        with self._lock:
            used = sum(len(o.data) for c in self.colls.values()
                       for o in c.values())
            total = global_config()["memstore_device_bytes"]
            return {"total": total, "used": used,
                    "available": max(0, total - used)}
