"""Client access layer: Objecter (target calc + resend engine) and the
librados-like Rados/IoCtx API (ref: src/osdc/Objecter.cc,
src/librados/)."""
from .objecter import Objecter, OpFuture
from .rados import IoCtx, Rados, RadosError, WriteOp

__all__ = ["Objecter", "OpFuture", "Rados", "IoCtx", "RadosError",
           "WriteOp"]
