"""EC stripe math + per-shard integrity hashes — the ECUtil analogue.

Three pieces (ref: src/osd/ECUtil.{h,cc}):

* `StripeInfo` — the logical<->chunk offset algebra of `stripe_info_t`
  (ECUtil.h:27-79), verbatim semantics (pure integer math).
* `encode` / `decode` / `decode_concat` — stripe-batched plugin
  dispatch.  Where the reference loops stripe-by-stripe through the
  plugin (ECUtil.cc:120-159 encode, :9/:47 decode), the TPU build
  reshapes the whole buffer to (stripes, k, chunk) and runs ONE batched
  device dispatch (`encode_batch`/`decode_batch`) when the plugin
  supports it, falling back to the per-stripe loop for plugins with
  chunk remapping or sub-chunk semantics (lrc/shec/clay).
* `HashInfo` — cumulative per-shard crc32c (ECUtil.cc:161 append), the
  xattr-stored integrity metadata ECBackend checks on every sub-read.
"""
from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

from ..common import jaxguard
from ..common.crc32c import crc32c


class StripeInfo:
    """Offset algebra between the logical object stream and per-shard
    chunk space (ref: ECUtil.h:27-79 stripe_info_t).

    stripe_size = k (data chunk count), stripe_width = k * chunk_size.
    """

    def __init__(self, stripe_size: int, stripe_width: int):
        if stripe_width % stripe_size != 0:
            raise ValueError("stripe_width must be divisible by stripe_size")
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1) // self.stripe_width) \
            * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset - rem + self.stripe_width if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def aligned_offset_len_to_chunk(
            self, off_len: tuple[int, int]) -> tuple[int, int]:
        off, length = off_len
        return (self.aligned_logical_offset_to_chunk_offset(off),
                self.aligned_logical_offset_to_chunk_offset(length))

    def offset_len_to_stripe_bounds(
            self, off_len: tuple[int, int]) -> tuple[int, int]:
        off, length = off_len
        start = self.logical_to_prev_stripe_offset(off)
        full_len = self.logical_to_next_stripe_offset((off - start) + length)
        return (start, full_len)


def _identity_mapping(ec) -> bool:
    mapping = ec.get_chunk_mapping()
    return not mapping or mapping == list(range(len(mapping)))


def _batchable(ec) -> bool:
    return (hasattr(ec, "encode_batch") and _identity_mapping(ec)
            and ec.get_sub_chunk_count() == 1)


def encode(sinfo: StripeInfo, ec, data: bytes,
           want: Iterable[int] | None = None) -> dict[int, bytes]:
    """Encode a stripe-aligned logical buffer into per-shard chunk
    streams (ref: ECUtil.cc:120-159).

    Returns {shard: bytes} where each shard's buffer is the
    concatenation of that shard's chunk from every stripe.  One batched
    device dispatch for matrix plugins; per-stripe plugin.encode
    otherwise.
    """
    k = ec.get_data_chunk_count()
    m = ec.get_coding_chunk_count()
    n = k + m
    if want is None:
        want = range(n)
    want = set(want)
    if len(data) % sinfo.stripe_width != 0:
        raise ValueError("logical size must be stripe-aligned")
    if not data:
        return {}
    nstripes = len(data) // sinfo.stripe_width
    cs = sinfo.chunk_size

    if _batchable(ec):
        arr = np.frombuffer(data, dtype=np.uint8).reshape(nstripes, k, cs)
        # the one legal host->device crossing of the encode path is
        # the plugin's explicit staging; under CEPH_TPU_JAXGUARD any
        # IMPLICIT transfer inside the dispatch is an error
        with jaxguard.guard_transfers():
            parity_dev = ec.encode_batch(arr)
        parity = np.asarray(parity_dev)                 # (S, m, cs)
        out: dict[int, bytes] = {}
        # tobytes() emits C-order bytes from a strided view directly —
        # an ascontiguousarray here would copy each shard slice twice
        for shard in sorted(want):
            if shard < k:
                out[shard] = arr[:, shard, :].tobytes()
            else:
                out[shard] = parity[:, shard - k, :].tobytes()
        return out

    # general path: per-stripe plugin encode (handles chunk remapping
    # and sub-chunk plugins)
    parts: dict[int, list] = {i: [] for i in want}
    for s in range(nstripes):
        stripe = data[s * sinfo.stripe_width:(s + 1) * sinfo.stripe_width]
        encoded = ec.encode(want, stripe)
        for i in want:
            chunk = encoded[i]
            assert len(chunk) == cs
            # this per-stripe fallback only serves host-native (numpy)
            # plugins; batchable device plugins take the one-dispatch
            # path above, so no device boundary is crossed here
            # cephck: ignore[host-sync-hot-path] — host-native plugin path
            parts[i].append(np.asarray(chunk, dtype=np.uint8))
    return {i: np.concatenate(parts[i]).tobytes() for i in want}


def decode_concat(sinfo: StripeInfo, ec,
                  to_decode: Mapping[int, bytes],
                  timings: dict | None = None) -> bytes:
    """Rebuild the logical stream from >=k shard chunk streams
    (ref: ECUtil.cc:9 decode -> decode_concat per stripe).

    `timings`, when passed, receives {"stage": (t0, t1),
    "kernel": (t0, t1)} monotonic intervals separating the host-side
    survivor staging (reply buffers -> dense array layout) from the
    decode compute, so the read path's trace span can split into
    stage/kernel children (the decode_incl_stage gap of BENCH_r05
    made per-op visible)."""
    if not to_decode:
        raise ValueError("decode of no shards")
    lengths = {len(v) for v in to_decode.values()}
    if len(lengths) != 1:
        raise ValueError("shard buffers differ in length")
    total = lengths.pop()
    if total % sinfo.chunk_size != 0:
        raise ValueError("shard length not chunk-aligned")
    if total == 0:
        return b""
    k = ec.get_data_chunk_count()
    nstripes = total // sinfo.chunk_size
    cs = sinfo.chunk_size

    if _batchable(ec):
        # identity mapping: shards 0..k-1 ARE the data chunks
        out = decode(sinfo, ec, to_decode, want=range(k),
                     timings=timings)
        arrs = [np.frombuffer(out[i], dtype=np.uint8).reshape(nstripes, cs)
                for i in range(k)]
        return np.ascontiguousarray(
            np.stack(arrs, axis=1)).tobytes()  # (S, k, cs) -> logical

    # general path: the plugin's decode_concat knows the chunk mapping
    # (ref: ECUtil.cc:31 per-stripe ec_impl->decode_concat)
    import time as _time
    t0 = _time.monotonic()
    views = {i: np.frombuffer(v, dtype=np.uint8)
             for i, v in to_decode.items()}
    parts = []
    for s in range(nstripes):
        chunks = {i: v[s * cs:(s + 1) * cs] for i, v in views.items()}
        stripe = ec.decode_concat(chunks)
        assert len(stripe) == sinfo.stripe_width
        parts.append(stripe)
    if timings is not None:       # per-stripe path: no separate stage
        timings["kernel"] = (t0, _time.monotonic())
    return b"".join(parts)


def decode(sinfo: StripeInfo, ec, to_decode: Mapping[int, bytes],
           want: Iterable[int],
           timings: dict | None = None) -> dict[int, bytes]:
    """Reconstruct the `want` shards' chunk streams from available
    shard streams (ref: ECUtil.cc:47 decode(map out)).

    Batched: a single device dispatch reconstructs every stripe's
    missing chunks for matrix plugins.  `timings` (optional dict)
    receives "stage"/"kernel" monotonic intervals — see decode_concat.
    """
    want = sorted(set(want))
    avail = sorted(to_decode)
    if not to_decode:
        raise ValueError("decode of no shards")
    lengths = {len(v) for v in to_decode.values()}
    if len(lengths) != 1:
        raise ValueError("shard buffers differ in length")
    total = lengths.pop()
    if total == 0:
        return {i: b"" for i in want}
    cs = sinfo.chunk_size
    if total % cs != 0:
        raise ValueError("shard length not chunk-aligned")
    nstripes = total // cs
    k = ec.get_data_chunk_count()

    have = [i for i in want if i in to_decode]
    missing = [i for i in want if i not in to_decode]

    out: dict[int, bytes] = {i: to_decode[i] for i in have}
    if not missing:
        return out

    if _batchable(ec) and len(avail) >= k:
        import time as _time
        decode_index = avail[:k]
        t0 = _time.monotonic()
        stack = np.stack(
            [np.frombuffer(to_decode[i], dtype=np.uint8)
             .reshape(nstripes, cs) for i in decode_index], axis=1)
        t1 = _time.monotonic()
        # np.asarray forces the device dispatch (D2H sync), so the
        # kernel interval below is compute + readback, never
        # dispatch-only; the guard makes any implicit transfer inside
        # the dispatch an error under CEPH_TPU_JAXGUARD
        with jaxguard.guard_transfers():
            rec_dev = ec.decode_batch(decode_index, missing, stack)
        rec = np.asarray(rec_dev)
        t2 = _time.monotonic()
        if timings is not None:
            timings["stage"] = (t0, t1)
            timings["kernel"] = (t1, t2)
        for pos, i in enumerate(missing):
            # tobytes() handles the strided view; rec was synced once
            # above, so this loop is host memcpy only
            out[i] = rec[:, pos, :].tobytes()
        return out

    # general path: per-stripe plugin decode
    import time as _time
    t0 = _time.monotonic()
    parts: dict[int, list] = {i: [] for i in missing}
    for s in range(nstripes):
        chunks = {i: np.frombuffer(v, dtype=np.uint8)[s * cs:(s + 1) * cs]
                  for i, v in to_decode.items()}
        decoded = ec.decode(set(want), chunks, cs)
        for i in missing:
            # only non-batchable (host-native numpy) plugins reach this
            # per-stripe path: the asarray never crosses a device boundary
            # cephck: ignore[host-sync-hot-path] — host-native plugin path
            parts[i].append(np.asarray(decoded[i], dtype=np.uint8))
    for i in missing:
        out[i] = np.concatenate(parts[i]).tobytes()
    if timings is not None:       # per-stripe path: no separate stage
        timings["kernel"] = (t0, _time.monotonic())
    return out


# ---------------------------------------------------------------- repair
# Sub-chunk (network-optimal) single-shard repair: regenerating codes
# (clay) rebuild one lost chunk from q^(t-1)-of-q^t sub-chunk ranges
# of d helpers instead of k whole chunks (ref: ErasureCodeClay.cc:364
# get_repair_subchunks; "Fast Product-Matrix Regenerating Codes",
# arxiv 1412.3022).  These helpers translate the plugin's sub-chunk
# plan into byte extents over shard chunk STREAMS (many stripes per
# object) and drive the per-stripe repair decode.


def supports_subchunk_repair(ec) -> bool:
    """True when the plugin can rebuild a single shard from partial
    (sub-chunk) helper reads.  Non-regenerating plugins and
    sub_chunk_count == 1 codes fall back to full-chunk recovery.
    (Plan-driven recovery — repair_plan below — supersedes this gate
    for the OSD paths; it remains the sub-chunk capability probe.)"""
    return (ec.get_sub_chunk_count() > 1
            and hasattr(ec, "is_repair")
            and hasattr(ec, "minimum_to_repair")
            and hasattr(ec, "get_repair_subchunks"))


def repair_plan(ec, lost, avail):
    """The plugin's partial-read repair plan (ec.repair_schedule) for
    this erasure signature, or None — the caller then takes wholesale
    full-chunk recovery.  A plan names the helper shards, each
    helper's sub-chunk extents, and feeds the repair-schedule compiler
    (ceph_tpu.ec.repairc): clay ships q^(t-1)/q^t repair planes of d
    helpers, lrc the l whole chunks of the lost shard's local parity
    group, matrix codes k whole survivor chunks decoded straight to
    the lost shards."""
    from ..ec.interface import ErasureCodeError
    hook = getattr(ec, "repair_schedule", None)
    if hook is None:
        return None
    try:
        return hook(set(lost), set(avail))
    except ErasureCodeError:
        return None


def compiled_repair_streams(ec, plan, chunk_size: int,
                            helper_bufs: Mapping[int, bytes],
                            backend: str | None = None
                            ) -> dict[int, bytes]:
    """Rebuild every lost shard's chunk stream through the plan's
    compiled program (cached per erasure signature): gather the
    helpers' plane bytes, one grouped GF(2^8) matmul, scatter.
    Byte-identical to the interpreted decode path (pinned by the
    tests/test_repairc.py parity sweep)."""
    from ..ec.repairc import program_for
    return program_for(ec, plan).run(helper_bufs, chunk_size,
                                     backend=backend)


def repair_chunk_extents(ec, lost_shard: int,
                         chunk_size: int) -> list[tuple[int, int]]:
    """Byte extents WITHIN ONE CHUNK that helpers must serve to repair
    `lost_shard` (the plugin's sub-chunk plan scaled to bytes).  A
    shard stream repeats these per stripe (see ECSubRead.subchunks)."""
    sub_no = ec.get_sub_chunk_count()
    assert chunk_size % sub_no == 0
    ssz = chunk_size // sub_no
    nu = getattr(ec, "nu", 0)
    lost_node = lost_shard if lost_shard < ec.k else lost_shard + nu
    return [(idx * ssz, cnt * ssz)
            for idx, cnt in ec.get_repair_subchunks(lost_node)]


def expand_stream_extents(extents: list[tuple[int, int]],
                          chunk_size: int,
                          stream_len: int) -> list[tuple[int, int]]:
    """Per-chunk byte extents -> absolute extents over an
    nstripes x chunk_size shard stream."""
    if stream_len % chunk_size != 0:
        raise ValueError("shard stream not chunk-aligned")
    return [(s * chunk_size + off, length)
            for s in range(stream_len // chunk_size)
            for off, length in extents]


def repair_shard_stream(ec, chunk_size: int, lost_shard: int,
                        helper_bufs: Mapping[int, bytes]) -> bytes:
    """Rebuild `lost_shard`'s whole chunk stream from the helpers'
    CONCATENATED repair-plane bytes (one repair_blocksize block per
    stripe, as handle_sub_read assembles them).  Byte-identical to the
    chunk a full-decode + re-encode would produce."""
    extents = repair_chunk_extents(ec, lost_shard, chunk_size)
    rb = sum(length for _, length in extents)   # repair bytes / stripe
    lengths = {len(v) for v in helper_bufs.values()}
    if len(lengths) != 1:
        raise ValueError("helper repair buffers differ in length")
    total = lengths.pop()
    if rb == 0 or total % rb != 0:
        raise ValueError("helper buffer not repair-block aligned")
    nstripes = total // rb
    views = {s: np.frombuffer(v, dtype=np.uint8)
             for s, v in helper_bufs.items()}
    parts = []
    for st in range(nstripes):
        chunks = {s: v[st * rb:(st + 1) * rb] for s, v in views.items()}
        rebuilt = ec.decode({lost_shard}, chunks, chunk_size)
        # sub-chunk repair is the clay (host-native numpy) plugin's
        # path; no device array ever reaches this asarray
        # cephck: ignore[host-sync-hot-path] — host-native plugin path
        parts.append(np.asarray(rebuilt[lost_shard], dtype=np.uint8))
    return b"".join(p.tobytes() for p in parts)


class HashInfo:
    """Cumulative per-shard crc32c of everything ever appended to each
    shard (ref: ECUtil.cc:161 HashInfo::append; stored as an object
    xattr and checked by ECBackend::handle_sub_read ECBackend.cc:1059).

    Seed is -1 per shard (matching the reference's default-constructed
    cumulative_shard_hashes of (uint32_t)-1).
    """

    def __init__(self, num_chunks: int = 0):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * num_chunks
        self.projected_total_chunk_size = 0

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def append(self, old_size: int, to_append: Mapping[int, bytes]) -> None:
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"append at {old_size} but shard size is "
                f"{self.total_chunk_size}")
        sizes = {len(v) for v in to_append.values()}
        if len(sizes) != 1:
            raise ValueError("shard appends differ in length")
        size_to_append = sizes.pop()
        if self.has_chunk_hash():
            if len(to_append) != len(self.cumulative_shard_hashes):
                raise ValueError("append must cover every shard")
            for shard, buf in to_append.items():
                self.cumulative_shard_hashes[shard] = crc32c(
                    self.cumulative_shard_hashes[shard], buf)
        self.total_chunk_size += size_to_append
        self.projected_total_chunk_size = max(
            self.projected_total_chunk_size, self.total_chunk_size)

    def append_shard(self, shard: int, old_size: int,
                     buf: bytes) -> None:
        """Shard-local cumulative append for the ICI-fabric path: the
        chunk bytes exist only on the shard that fetched them, so each
        shard advances ITS hash; other entries in this copy are never
        consulted on this shard (handle_sub_read and scrub both check
        `get_chunk_hash(self.shard)` only)."""
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"append at {old_size} but shard size is "
                f"{self.total_chunk_size}")
        if self.has_chunk_hash():
            self.cumulative_shard_hashes[shard] = crc32c(
                self.cumulative_shard_hashes[shard], buf)
        self.total_chunk_size += len(buf)
        self.projected_total_chunk_size = max(
            self.projected_total_chunk_size, self.total_chunk_size)

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    # xattr codec (JSON-ish dict instead of the reference's binary
    # ENCODE_START framing; ref: ECUtil.cc:181 encode/decode)
    def to_dict(self) -> dict:
        return {"total_chunk_size": self.total_chunk_size,
                "cumulative_shard_hashes": list(
                    self.cumulative_shard_hashes)}

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        hi = cls()
        hi.total_chunk_size = d["total_chunk_size"]
        hi.cumulative_shard_hashes = list(d["cumulative_shard_hashes"])
        hi.projected_total_chunk_size = hi.total_chunk_size
        return hi

    def __eq__(self, other) -> bool:
        return (isinstance(other, HashInfo)
                and self.total_chunk_size == other.total_chunk_size
                and self.cumulative_shard_hashes
                == other.cumulative_shard_hashes)

    def __repr__(self) -> str:
        hashes = " ".join(hex(h) for h in self.cumulative_shard_hashes)
        return f"HashInfo(tcs={self.total_chunk_size} {hashes})"
