"""Admin socket: per-daemon out-of-band command endpoint.

The reference serves `ceph daemon <name> <cmd>` over a unix-domain
socket with a tiny length-prefixed JSON protocol
(ref: src/common/admin_socket.cc — AdminSocket::entry accept loop,
execute_command; registration via register_command).  Same here:
newline-delimited JSON request {"prefix": ...} -> JSON reply
{"rc": int, "out": any} over a SOCK_STREAM unix socket.

Daemons register command handlers; `admin_command()` is the client
(the `ceph daemon` CLI analogue).
"""
from __future__ import annotations

import json
import os
import socket
import threading
from typing import Any, Callable

from .log import dout

Handler = Callable[[dict], "tuple[int, Any]"]


class AdminSocket:
    """(ref: src/common/admin_socket.h:44)."""

    def __init__(self, path: str):
        self.path = path
        self._handlers: dict[str, tuple[str, Handler]] = {}
        self._listener: socket.socket | None = None
        self._running = False
        self.register("help", "list registered commands", self._help)

    def register(self, prefix: str, help_text: str,
                 fn: Handler) -> None:
        """(ref: AdminSocket::register_command)."""
        self._handlers[prefix] = (help_text, fn)

    def _help(self, _cmd: dict):
        return 0, {p: h for p, (h, _f) in sorted(self._handlers.items())}

    # -- server ----------------------------------------------------------
    def start(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._listener = socket.socket(socket.AF_UNIX,
                                       socket.SOCK_STREAM)
        self._listener.bind(self.path)
        self._listener.listen(8)
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name=f"asok-{os.path.basename(self.path)}",
                             daemon=True)
        t.start()

    def shutdown(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                self._serve_one(conn)
            except Exception:
                import traceback
                dout("asok", 1).write("admin socket error: %s",
                                      traceback.format_exc())
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve_one(self, conn: socket.socket) -> None:
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                return
            buf += chunk
        try:
            cmd = json.loads(buf.split(b"\n", 1)[0])
        except json.JSONDecodeError:
            conn.sendall(json.dumps(
                {"rc": -22, "out": "invalid json"}).encode() + b"\n")
            return
        prefix = cmd.get("prefix", "")
        entry = self._handlers.get(prefix)
        if entry is None:
            rc, out = -22, f"unknown command {prefix!r}; try 'help'"
        else:
            try:
                rc, out = entry[1](cmd)
            except Exception as ex:          # handler bug: report it
                rc, out = -22, f"{type(ex).__name__}: {ex}"
        conn.sendall(json.dumps({"rc": rc, "out": out},
                                default=str).encode() + b"\n")


def admin_command(path: str, cmd: dict | str,
                  timeout: float = 10.0) -> tuple[int, Any]:
    """Client side (`ceph daemon <sock> <cmd>` analogue)."""
    if isinstance(cmd, str):
        cmd = {"prefix": cmd}
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(path)
        s.sendall(json.dumps(cmd).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
        rep = json.loads(buf.split(b"\n", 1)[0])
        return rep["rc"], rep["out"]
    finally:
        s.close()


def main(argv=None) -> int:
    """`ceph daemon <sock> <cmd...>` analogue."""
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("usage: admin_socket <sock-path> <command...> "
              "[key=value ...]", file=sys.stderr)
        return 2
    path, words = argv[0], argv[1:]
    cmd: dict = {"prefix": " ".join(w for w in words if "=" not in w)}
    for w in words:
        if "=" in w:
            k, v = w.split("=", 1)
            cmd[k] = v
    rc, out = admin_command(path, cmd)
    print(json.dumps(out, indent=1, default=str))
    return 0 if rc == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
