"""Striper: logical byte ranges <-> RADOS object extents.

Port of the reference's striping math (ref: src/osdc/Striper.cc
file_to_extents :52-170, extent_to_file :236; layout validation
src/osd/osd_types.cc file_layout_t::is_valid): a file/image is striped
in `stripe_unit` blocks round-robin over `stripe_count` objects per
object set, each object holding `object_size / stripe_unit` stripes'
worth of its column.

    blockno   = off / su
    stripeno  = blockno / sc
    stripepos = blockno % sc
    objectset = stripeno / stripes_per_object
    objectno  = objectset * sc + stripepos
    obj_off   = (stripeno % stripes_per_object) * su + off % su
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StripeLayout:
    """file_layout_t subset (ref: src/include/fs_types.h)."""
    stripe_unit: int = 1 << 22
    stripe_count: int = 1
    object_size: int = 1 << 22

    def validate(self) -> None:
        """(ref: file_layout_t::is_valid)."""
        if self.stripe_unit <= 0 or self.stripe_count <= 0 or \
                self.object_size <= 0:
            raise ValueError("layout fields must be positive")
        if self.object_size % self.stripe_unit:
            raise ValueError(
                "object_size must be a multiple of stripe_unit")

    @property
    def stripes_per_object(self) -> int:
        return self.object_size // self.stripe_unit


@dataclass(frozen=True)
class ObjectExtent:
    """One contiguous range inside one object
    (ref: src/osdc/Striper.h ObjectExtent)."""
    objectno: int
    offset: int          # within the object
    length: int
    logical_offset: int  # within the file/image


class Striper:
    @staticmethod
    def file_to_extents(layout: StripeLayout, offset: int,
                        length: int) -> list[ObjectExtent]:
        """(ref: Striper.cc:52 file_to_extents)."""
        layout.validate()
        su = layout.stripe_unit
        sc = layout.stripe_count
        spo = layout.stripes_per_object
        out: list[ObjectExtent] = []
        pos = offset
        end = offset + length
        while pos < end:
            blockno = pos // su
            stripeno = blockno // sc
            stripepos = blockno % sc
            objectset = stripeno // spo
            objectno = objectset * sc + stripepos
            block_start = (stripeno % spo) * su
            block_off = pos % su
            obj_off = block_start + block_off
            n = min(su - block_off, end - pos)
            out.append(ObjectExtent(objectno, obj_off, n, pos))
            pos += n
        return out

    @staticmethod
    def extent_to_file(layout: StripeLayout, objectno: int,
                       off: int, length: int
                       ) -> list[tuple[int, int]]:
        """Object range -> [(logical_offset, len)]
        (ref: Striper.cc:236 extent_to_file)."""
        layout.validate()
        su = layout.stripe_unit
        sc = layout.stripe_count
        spo = layout.stripes_per_object
        objectset = objectno // sc
        stripepos = objectno % sc
        out: list[tuple[int, int]] = []
        pos = off
        end = off + length
        while pos < end:
            stripe_in_obj = pos // su
            off_in_block = pos % su
            stripeno = objectset * spo + stripe_in_obj
            blockno = stripeno * sc + stripepos
            logical = blockno * su + off_in_block
            n = min(su - off_in_block, end - pos)
            out.append((logical, n))
            pos += n
        return out
