"""Striper math + RBD-lite image IO + perf-counter wiring
(ref: src/osdc/Striper.cc, src/librbd/, src/osd/osd_perf_counters.cc)."""
import numpy as np
import pytest

from ceph_tpu.common.perf_counters import global_perf
from ceph_tpu.osdc import ObjectExtent, StripeLayout, Striper
from ceph_tpu.rbd import RBD, Image, RBDError
from ceph_tpu.testing import MiniCluster


# ---------------------------------------------------------------- striper
def test_striper_trivial_layout():
    lo = StripeLayout(stripe_unit=1 << 20, stripe_count=1,
                      object_size=1 << 20)
    exts = Striper.file_to_extents(lo, 0, 3 << 20)
    assert [(e.objectno, e.offset, e.length) for e in exts] == \
        [(0, 0, 1 << 20), (1, 0, 1 << 20), (2, 0, 1 << 20)]


def test_striper_round_robin():
    """su=4k, sc=3, os=8k: blocks round-robin over 3 objects, two
    stripes per object."""
    lo = StripeLayout(stripe_unit=4096, stripe_count=3,
                      object_size=8192)
    exts = Striper.file_to_extents(lo, 0, 6 * 4096)
    assert [(e.objectno, e.offset) for e in exts] == [
        (0, 0), (1, 0), (2, 0),      # stripe 0
        (0, 4096), (1, 4096), (2, 4096)]  # stripe 1
    # next object set starts at objectno 3
    exts2 = Striper.file_to_extents(lo, 6 * 4096, 4096)
    assert (exts2[0].objectno, exts2[0].offset) == (3, 0)


def test_striper_unaligned_window():
    lo = StripeLayout(stripe_unit=4096, stripe_count=2,
                      object_size=8192)
    exts = Striper.file_to_extents(lo, 1000, 5000)
    assert sum(e.length for e in exts) == 5000
    assert exts[0] == ObjectExtent(0, 1000, 3096, 1000)
    assert exts[1].objectno == 1 and exts[1].offset == 0
    # logical offsets cover [1000, 6000) without gaps
    covered = sorted((e.logical_offset, e.logical_offset + e.length)
                     for e in exts)
    pos = 1000
    for lo_, hi in covered:
        assert lo_ == pos
        pos = hi
    assert pos == 6000


def test_striper_roundtrip_inverse():
    lo = StripeLayout(stripe_unit=4096, stripe_count=3,
                      object_size=16384)
    rng = np.random.default_rng(0)
    for _ in range(50):
        off = int(rng.integers(0, 200000))
        ln = int(rng.integers(1, 30000))
        for e in Striper.file_to_extents(lo, off, ln):
            back = Striper.extent_to_file(lo, e.objectno, e.offset,
                                          e.length)
            assert back[0][0] == e.logical_offset
            assert sum(n for _, n in back) == e.length


def test_striper_validation():
    with pytest.raises(ValueError):
        StripeLayout(stripe_unit=3000, stripe_count=1,
                     object_size=8192).validate()
    with pytest.raises(ValueError):
        StripeLayout(stripe_unit=0).validate()


# ------------------------------------------------------------------- rbd
@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=5, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("rbd", pg_num=16)
    yield c, r
    c.shutdown()


def test_rbd_create_open_stat_list(cluster):
    c, r = cluster
    io = r.open_ioctx("rbd")
    rbd = RBD()
    rbd.create(io, "img", size=1 << 20, order=16)  # 64 KiB objects
    assert "img" in rbd.list(io)
    img = Image(io, "img")
    st = img.stat()
    assert st["size"] == 1 << 20 and st["obj_size"] == 1 << 16
    assert st["num_objs"] == 16
    with pytest.raises(RBDError):
        rbd.create(io, "img", size=1)  # duplicate
    img.close()
    with pytest.raises(RBDError):
        img.read(0, 1)  # closed


def test_rbd_write_read_spanning_objects(cluster):
    c, r = cluster
    io = r.open_ioctx("rbd")
    RBD().create(io, "span", size=1 << 20, order=16)
    img = Image(io, "span")
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
    off = 60_000  # crosses object 0 -> 3+ boundaries
    assert img.write(off, data) == len(data)
    assert img.read(off, len(data)) == data
    # sparse before/after
    assert img.read(0, 100) == b"\0" * 100
    # unwritten tail reads as zeros
    assert img.read(off + len(data), 50) == b"\0" * 50
    # overwrite inside
    img.write(off + 1000, b"X" * 70000)
    expect = bytearray(data)
    expect[1000:71000] = b"X" * 70000
    assert img.read(off, len(data)) == bytes(expect)


def test_rbd_striped_layout(cluster):
    c, r = cluster
    io = r.open_ioctx("rbd")
    RBD().create(io, "striped", size=1 << 20, order=16,
                 stripe_unit=4096, stripe_count=4)
    img = Image(io, "striped")
    data = bytes(range(256)) * 64  # 16 KiB: 4 stripe units
    img.write(0, data)
    assert img.read(0, len(data)) == data
    # units landed on four distinct objects
    objs = {e.objectno for e in Striper.file_to_extents(
        img.layout, 0, len(data))}
    assert objs == {0, 1, 2, 3}


def test_rbd_resize_and_clip(cluster):
    c, r = cluster
    io = r.open_ioctx("rbd")
    RBD().create(io, "rsz", size=1 << 18, order=16)
    img = Image(io, "rsz")
    img.write((1 << 18) - 100, b"y" * 500)   # clipped at image end
    assert img.read((1 << 18) - 100, 100) == b"y" * 100
    img.resize(1 << 19)
    assert Image(io, "rsz").size == 1 << 19
    img.resize(1 << 16)
    img2 = Image(io, "rsz")
    assert img2.size == 1 << 16
    with pytest.raises(RBDError):
        img2.read(1 << 17, 10)  # beyond end


def test_rbd_discard_and_remove(cluster):
    c, r = cluster
    io = r.open_ioctx("rbd")
    RBD().create(io, "disc", size=1 << 18, order=16)
    img = Image(io, "disc")
    img.write(0, b"z" * (1 << 17))
    img.discard(0, 1 << 16)          # whole first object dropped
    assert img.read(0, 1 << 16) == b"\0" * (1 << 16)
    assert img.read(1 << 16, 1 << 16) == b"z" * (1 << 16)
    RBD().remove(io, "disc")
    assert "disc" not in RBD().list(io)
    with pytest.raises(RBDError):
        Image(io, "disc")


# ----------------------------------------------------------- perf dump
def test_osd_perf_counters_wired(cluster):
    c, r = cluster
    io = r.open_ioctx("rbd")
    io.write_full("pobj", b"q" * 4096)
    io.read("pobj")
    dump = c.perf_collection.perf_dump()
    osd_dumps = [v for k, v in dump.items() if k.startswith("osd.")]
    assert osd_dumps
    assert sum(d["op"] for d in osd_dumps) > 0
    assert sum(d["op_w_bytes"] for d in osd_dumps) >= 4096
    assert sum(d["op_r_bytes"] for d in osd_dumps) >= 4096
    assert sum(d["subop_w"] for d in osd_dumps) > 0
    assert sum(d["map_epochs"] for d in osd_dumps) > 0
