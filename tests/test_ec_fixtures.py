"""Non-self-referential EC parity pins (VERDICT round-1 item 7).

Three independent lines of defense against transcription bugs in
ceph_tpu.ec.gf that would otherwise pass every round-trip test:

1. An INDEPENDENT GF(2^8) implementation (bitwise carryless multiply
   reduced mod 0x11d — no log/antilog tables, no shared code with
   gf.py) cross-checked exhaustively against gf.py's tables, plus
   hand-derived known-answer values.
2. The coding-matrix constructions rebuilt from their published
   formulas using only the independent arithmetic (ISA-L
   gf_gen_rs_matrix / gf_gen_cauchy1_matrix structure, jerasure
   RAID-6 and Cauchy constructions, Vandermonde systematization by
   independent Gauss-Jordan).
3. A committed golden chunk corpus (tests/fixtures/ec_corpus.json,
   scripts/gen_ec_corpus.py) re-encoded and compared byte-for-byte for
   every plugin/technique, plus exhaustive erasure-sweep decodes.
"""
import itertools
import json
import os

import numpy as np
import pytest

from ceph_tpu.ec import gf, registry

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


# ---------------------------------------------------------------------------
# 1. Independent field arithmetic
# ---------------------------------------------------------------------------

def mul_slow(a: int, b: int) -> int:
    """Carryless multiply reduced mod x^8+x^4+x^3+x^2+1 — shares nothing
    with gf.py's log/antilog construction."""
    p = 0
    for bit in range(8):
        if (b >> bit) & 1:
            p ^= a << bit
    for bit in range(15, 7, -1):
        if (p >> bit) & 1:
            p ^= 0x11D << (bit - 8)
    return p


def inv_slow(a: int) -> int:
    if a == 0:
        return 0
    return next(x for x in range(1, 256) if mul_slow(a, x) == 1)


def pow_slow(a: int, n: int) -> int:
    r = 1
    for _ in range(n):
        r = mul_slow(r, a)
    return r


def test_mul_table_exhaustive_vs_independent():
    MUL = gf.mul_table()
    for a in range(256):
        row = np.array([mul_slow(a, b) for b in range(256)],
                       dtype=np.uint8)
        assert np.array_equal(MUL[a], row), f"mul table row {a} wrong"


def test_inv_table_vs_independent():
    INV = gf.inv_table()
    for a in range(256):
        assert INV[a] == inv_slow(a), f"inv[{a}] wrong"


def test_hand_derived_known_answers():
    # 2*0x80: 0x100 ^ 0x11d = 0x1d
    assert gf.gf_mul(2, 0x80) == 0x1D
    # 2*0x8d: 0x11a ^ 0x11d = 0x07
    assert gf.gf_mul(2, 0x8D) == 0x07
    # 2*0x8e = 0x11c ^ 0x11d = 1, so inv(2) = 0x8e
    assert gf.gf_inv(2) == 0x8E
    # generator order: 2^255 = 1, and 2^8 = 0x1d by the reduction above
    assert gf.gf_pow(2, 255) == 1
    assert gf.gf_pow(2, 8) == 0x1D
    # 3 generates too: 3 = x+1; (x+1)^2 = x^2+1 = 5
    assert gf.gf_mul(3, 3) == 5


# ---------------------------------------------------------------------------
# 2. Matrix constructions rebuilt from published formulas
# ---------------------------------------------------------------------------

def invert_slow(mat):
    """Independent Gauss-Jordan over GF(2^8) using only mul_slow."""
    n = len(mat)
    m = [list(row) for row in mat]
    out = [[1 if i == j else 0 for j in range(n)] for i in range(n)]
    for i in range(n):
        if m[i][i] == 0:
            j = next(r for r in range(i + 1, n) if m[r][i])
            m[i], m[j] = m[j], m[i]
            out[i], out[j] = out[j], out[i]
        piv = inv_slow(m[i][i])
        m[i] = [mul_slow(piv, x) for x in m[i]]
        out[i] = [mul_slow(piv, x) for x in out[i]]
        for r in range(n):
            if r == i or m[r][i] == 0:
                continue
            f = m[r][i]
            m[r] = [x ^ mul_slow(f, y) for x, y in zip(m[r], m[i])]
            out[r] = [x ^ mul_slow(f, y) for x, y in zip(out[r], out[i])]
    return out


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 4), (5, 3)])
def test_isa_rs_matrix_structure(k, m):
    """ISA-L gf_gen_rs_matrix: coding row i = [gen^0..gen^(k-1)],
    gen = 2^(i-k) (ref: isa-l erasure_code gf_gen_rs_matrix)."""
    a = gf.isa_rs_matrix(k, m)
    assert np.array_equal(a[:k], np.eye(k, dtype=np.uint8))
    for i in range(m):
        gen = pow_slow(2, i)
        expect = [pow_slow(gen, j) for j in range(k)]
        assert list(a[k + i]) == expect, f"rs coding row {i}"
    assert (a[k] == 1).all()  # XOR row


@pytest.mark.parametrize("k,m", [(4, 2), (8, 4)])
def test_isa_cauchy_matrix_structure(k, m):
    """gf_gen_cauchy1_matrix: coding row i col j = 1/(i ^ j), i >= k."""
    a = gf.isa_cauchy_matrix(k, m)
    for i in range(k, k + m):
        for j in range(k):
            assert a[i, j] == inv_slow(i ^ j)


def test_jerasure_r6_structure():
    """RAID-6: P row all ones, Q row = 2^j."""
    mat = gf.jerasure_r6_coding_matrix(6)
    assert (mat[0] == 1).all()
    assert list(mat[1]) == [pow_slow(2, j) for j in range(6)]


@pytest.mark.parametrize("k,m", [(4, 2), (5, 3)])
def test_cauchy_original_structure(k, m):
    """jerasure cauchy_original: row i col j = 1/(i ^ (m+j))."""
    a = gf.cauchy_original_coding_matrix(k, m)
    for i in range(m):
        for j in range(k):
            assert a[i, j] == inv_slow(i ^ (m + j))


@pytest.mark.parametrize("k,m", [(4, 2), (6, 3)])
def test_jerasure_vandermonde_independent_rebuild(k, m):
    """reed_sol_van systematization rebuilt with the independent
    arithmetic: W = V @ inv(V[:k]), V[i][j] = i^j."""
    v = [[pow_slow(i, j) for j in range(k)] for i in range(k + m)]
    top_inv = invert_slow([row[:] for row in v[:k]])
    expect = [[0] * k for _ in range(m)]
    for i in range(m):
        for j in range(k):
            acc = 0
            for t in range(k):
                acc ^= mul_slow(v[k + i][t], top_inv[t][j])
            expect[i][j] = acc
    got = gf.jerasure_vandermonde_coding_matrix(k, m)
    assert [[int(x) for x in r] for r in got] == expect


def test_cauchy_good_row0_all_ones_and_mds():
    """cauchy_good column-normalizes row 0 to all ones and must stay
    MDS (every k x k submatrix of [I; C] invertible)."""
    k, m = 4, 2
    c = gf.cauchy_good_coding_matrix(k, m)
    assert (c[0] == 1).all()
    full = np.vstack([np.eye(k, dtype=np.uint8), c])
    for rows in itertools.combinations(range(k + m), k):
        sub = full[list(rows)]
        assert gf.gf_invert_matrix(sub) is not None, rows


# ---------------------------------------------------------------------------
# 3. Golden corpus + erasure sweeps
# ---------------------------------------------------------------------------

def _corpus():
    with open(os.path.join(FIXTURES, "ec_corpus.json")) as f:
        return json.load(f)


def test_corpus_reencode_byte_exact():
    corpus = _corpus()
    obj = bytes.fromhex(corpus["object_hex"])
    for entry in corpus["entries"]:
        ec = registry.factory(entry["plugin"], dict(entry["profile"]))
        assert ec.get_chunk_count() == entry["chunk_count"]
        assert ec.get_chunk_size(len(obj)) == entry["chunk_size"]
        encoded = ec.encode(set(range(entry["chunk_count"])), obj)
        for i_str, hexdata in entry["chunks"].items():
            got = bytes(encoded[int(i_str)])
            assert got == bytes.fromhex(hexdata), \
                f"{entry['plugin']} {entry['profile']} chunk {i_str}"


def test_corpus_decode_sweep():
    """All erasure patterns up to min(m, 3) of every corpus entry
    decode back to the archived chunks.  Only shec/lrc may skip
    patterns (their codes legitimately cannot recover every <=m-subset);
    MDS plugins must decode every pattern — a raising
    minimum_to_decode there is itself a regression."""
    corpus = _corpus()
    for entry in corpus["entries"]:
        ec = registry.factory(entry["plugin"], dict(entry["profile"]))
        n = entry["chunk_count"]
        chunks = {int(i): np.frombuffer(bytes.fromhex(h), dtype=np.uint8)
                  for i, h in entry["chunks"].items()}
        want = set(range(n))
        m = n - entry["data_chunk_count"]
        may_skip = entry["plugin"] in ("shec", "lrc")
        skipped = 0
        for sz in range(1, min(m, 3) + 1):
            for erasure in itertools.combinations(range(n), sz):
                avail = {i: c for i, c in chunks.items()
                         if i not in erasure}
                try:
                    ec.minimum_to_decode(want, set(avail))
                except Exception:
                    assert may_skip, \
                        (entry["plugin"], entry["profile"], erasure)
                    skipped += 1
                    continue
                decoded = ec.decode(want, avail)
                for i in range(n):
                    assert np.array_equal(decoded[i], chunks[i]), \
                        (entry["plugin"], entry["profile"], erasure, i)
        if not may_skip:
            assert skipped == 0
