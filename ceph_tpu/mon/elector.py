"""Elector: rank-based monitor leader election.

Simplified port of src/mon/Elector.{h,cc}: the mon with the lowest
rank among responsive peers wins.  A mon starts (or restarts) an
election by bumping the election epoch and proposing itself; every mon
acks the lowest-ranked proposer it has seen in the current epoch; a
proposer holding acks from a majority (counting itself) declares
victory and broadcasts the quorum.  Re-election triggers when the
leader's lease goes stale (Monitor.tick) or a peer proposes with a
newer epoch.
"""
from __future__ import annotations

from typing import Callable

from ..common.log import dout
from ..msg.messages import MMonElection


class Elector:
    def __init__(self, rank: int, ranks: list[int],
                 send: Callable[[int, object], None],
                 on_win: Callable[[int, list[int]], None],
                 on_lose: Callable[[int, int, list[int]], None]):
        self.rank = rank
        self.ranks = sorted(ranks)         # all mon ranks incl. self
        self.send = send                   # (peer_rank, msg)
        self.on_win = on_win               # (epoch, quorum)
        self.on_lose = on_lose             # (epoch, leader, quorum)
        self.epoch = 0
        self.electing = False
        self.acked_me: set[int] = set()
        self.leader: int | None = None
        self.quorum: list[int] = []

    @property
    def majority(self) -> int:
        return len(self.ranks) // 2 + 1

    # ------------------------------------------------------------ start
    def start(self) -> None:
        """Propose ourselves (ref: Elector::start)."""
        self.epoch += 1
        self.electing = True
        self.leader = None
        self.acked_me = {self.rank}
        dout("mon", 5).write("elector %d: starting election e%d",
                             self.rank, self.epoch)
        for r in self.ranks:
            if r != self.rank:
                self.send(r, MMonElection(op="propose",
                                          epoch=self.epoch,
                                          rank=self.rank))
        self._check_win()

    # ---------------------------------------------------------- handlers
    def handle(self, msg: MMonElection) -> None:
        if msg.op == "propose":
            self._handle_propose(msg)
        elif msg.op == "ack":
            self._handle_ack(msg)
        elif msg.op == "victory":
            self._handle_victory(msg)

    def _handle_propose(self, msg: MMonElection) -> None:
        """(ref: Elector::handle_propose — defer to lower rank,
        counter-propose otherwise).  Async delivery can let two
        proposers each collect a majority in one epoch (a winner's
        victory racing a late ack); conflicts are resolved by epoch
        bumps — a standing leader outranked by a proposal abdicates
        into a fresh epoch, whose single victory supersedes both."""
        if msg.epoch > self.epoch:
            self.epoch = msg.epoch
            self.electing = True
            self.leader = None
            self.acked_me = {self.rank}
        elif msg.epoch < self.epoch:
            # stale proposer: provoke it to catch up
            self.send(msg.rank, MMonElection(op="propose",
                                             epoch=self.epoch,
                                             rank=self.rank))
            return
        if msg.rank < self.rank:
            if not self.electing and self.leader == self.rank:
                # we won this epoch but a lower rank is proposing:
                # abdicate by DEFERRING in a fresh epoch (re-proposing
                # ourselves here livelocks: our broadcast reaches the
                # other voters first and we just win again)
                self.epoch += 1
                self.electing = True
                self.leader = None
                self.acked_me = set()
                self.send(msg.rank, MMonElection(op="ack",
                                                 epoch=self.epoch,
                                                 rank=self.rank))
                return
            # defer
            self.send(msg.rank, MMonElection(op="ack", epoch=self.epoch,
                                             rank=self.rank))
        else:
            # we outrank the proposer: push our own candidacy — to
            # EVERY rank, not just the proposer.  Under an asymmetric
            # partition the proposer may be unreachable from us; if
            # our counter-candidacy went only to it, every reachable
            # voter would sit in the bumped epoch never hearing a
            # proposal, and the quorum would stall until the lease
            # timeout restarted the whole election (found by the
            # chaos harness's asymmetric mon-partition schedule).
            for r in self.ranks:
                if r != self.rank:
                    self.send(r, MMonElection(op="propose",
                                              epoch=self.epoch,
                                              rank=self.rank))
            self._check_win()

    def _handle_ack(self, msg: MMonElection) -> None:
        if msg.epoch > self.epoch:
            # an abdicating leader deferred to us in a fresh epoch:
            # adopt it and keep collecting there
            self.epoch = msg.epoch
            self.electing = True
            self.leader = None
            self.acked_me = {self.rank}
        elif msg.epoch < self.epoch:
            return
        elif not self.electing:
            # late ack for an epoch we already won: the voter was one
            # delivery behind the majority when victory fired, and
            # dropping its ack would leave it a lease-fed peon OUTSIDE
            # the quorum forever (MON_DOWN that never clears — found
            # by the chaos harness's mon-partition heal).  Expand the
            # quorum and re-announce (ref: real Ceph avoids the race
            # by waiting out the full election timeout).
            if self.leader == self.rank and msg.rank in self.ranks \
                    and msg.rank not in self.quorum:
                self.acked_me.add(msg.rank)
                self.quorum = sorted(set(self.quorum) | {msg.rank})
                dout("mon", 1).write(
                    "elector %d: late ack from %d, quorum now %s",
                    self.rank, msg.rank, self.quorum)
                for r in self.ranks:
                    if r != self.rank:
                        self.send(r, MMonElection(op="victory",
                                                  epoch=self.epoch,
                                                  rank=self.rank,
                                                  quorum=self.quorum))
                self.on_win(self.epoch, self.quorum)
            return
        self.acked_me.add(msg.rank)
        self._check_win()

    def _check_win(self) -> None:
        if self.electing and len(self.acked_me) >= self.majority:
            self.electing = False
            self.leader = self.rank
            self.quorum = sorted(self.acked_me)
            dout("mon", 1).write("elector %d: WON e%d quorum %s",
                                 self.rank, self.epoch, self.quorum)
            # victory goes to EVERY rank, not just the quorum: a
            # conflicting same-epoch winner must learn of us so the
            # epoch-bump conflict resolution can run
            for r in self.ranks:
                if r != self.rank:
                    self.send(r, MMonElection(op="victory",
                                              epoch=self.epoch,
                                              rank=self.rank,
                                              quorum=self.quorum))
            self.on_win(self.epoch, self.quorum)

    def _handle_victory(self, msg: MMonElection) -> None:
        if msg.epoch < self.epoch:
            return
        if msg.epoch == self.epoch and not self.electing and \
                self.leader == self.rank and msg.rank > self.rank:
            # double win in one epoch (their late acks): we outrank
            # them — force a fresh epoch to supersede both victories
            self.start()
            return
        self.epoch = msg.epoch
        self.electing = False
        self.leader = msg.rank
        self.quorum = list(msg.quorum)
        dout("mon", 1).write("elector %d: leader is %d (e%d)",
                             self.rank, msg.rank, self.epoch)
        self.on_lose(self.epoch, msg.rank, self.quorum)
