"""racecheck smoke: the lockset sanitizer must have teeth before the
suite leans on it.

Two probes, mirroring scripts/jaxguard_smoke.py's role in
check_green:

1. RED — two threads write an instrumented attribute with no common
   lock: RaceError must trip and carry both access stacks.
2. GREEN — the same traffic with every writer under one make_lock()
   (and a queued hand-off through transfer_ownership): silent.

Exits 0 only when the red case trips AND the green case stays quiet;
anything else means the sanitizer the tier-1 gate runs is a no-op.
"""
import os
import pathlib
import sys
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("CEPH_TPU_LOCKDEP", "1")
os.environ.setdefault("CEPH_TPU_RACECHECK", "1")

from ceph_tpu.common import racecheck  # noqa: E402
from ceph_tpu.common.lockdep import make_lock  # noqa: E402


def main() -> int:
    if not racecheck.enable_if_configured():
        print("racecheck_smoke: sanitizer did not arm", file=sys.stderr)
        return 1

    @racecheck.shared_state(only=("table",), mutating=("table",))
    class Shared:
        def __init__(self):
            self.lock = make_lock("racecheck_smoke.shared")
            self.table = {}

        def put_locked(self, k, v):
            with self.lock:
                self.table[k] = v

        def put_bare(self, k, v):
            self.table[k] = v

    # -- RED: instrumented write from a second thread, no lock -------
    s = Shared()
    s.put_locked("seed", 0)
    tripped = []

    def bare_writer():
        try:
            for i in range(8):
                s.put_bare(f"k{i}", i)
        except racecheck.RaceError as e:
            tripped.append(e)
    t = threading.Thread(target=bare_writer, name="smoke-bare")
    t.start()
    t.join()
    # either the bare thread tripped, or its seed survives and the
    # next locked writer proves the empty intersection
    if not tripped:
        try:
            s.put_locked("post", 1)
        except racecheck.RaceError as e:
            tripped.append(e)
    if not tripped:
        print("racecheck_smoke: RED case did not trip — the "
              "sanitizer is blind", file=sys.stderr)
        return 1
    err = tripped[0]
    if not (err.prev and err.cur and err.cur[2]):
        print("racecheck_smoke: RaceError lacks the access stacks",
              file=sys.stderr)
        return 1

    # -- GREEN: same traffic, disciplined -----------------------------
    racecheck.reset()
    g = Shared()
    g.put_locked("seed", 0)

    def locked_writer():
        for i in range(8):
            g.put_locked(f"k{i}", i)
    threads = [threading.Thread(target=locked_writer) for _ in range(3)]
    for x in threads:
        x.start()
    locked_writer()
    for x in threads:
        x.join()

    # hand-off pattern: built by this thread, consumed by another
    @racecheck.shared_state(only=("payload",))
    class Op:
        def __init__(self):
            self.payload = "built"
    op = Op()
    racecheck.transfer_ownership(op)

    def consumer():
        op.payload = "consumed"
    t = threading.Thread(target=consumer)
    t.start()
    t.join()

    if racecheck.races():
        print("racecheck_smoke: GREEN case tripped:\n"
              + "\n".join(str(r) for r in racecheck.races()),
              file=sys.stderr)
        return 1
    print("racecheck_smoke: OK — red trips with both stacks, "
          "guarded/hand-off traffic is silent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
