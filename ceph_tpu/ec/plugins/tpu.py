"""The `tpu` erasure-code plugin — the north-star component.

A JAX/Pallas GF(2^8) Reed-Solomon/Cauchy code behind the exact
ErasureCodeInterface boundary (ref: src/erasure-code/ErasureCodeInterface.h).
The GF matmul hot loop runs on the TPU MXU as a bit-plane GF(2) matmul
(see ceph_tpu.ec.kernels.bitmatmul); matrices, chunk sizes and padding follow
the isa/jerasure plugins so chunks are byte-identical to the CPU reference.

Techniques (profile `technique=`):
  reed_sol_van  - ISA-L gf_gen_rs_matrix (default; parity with isa plugin)
  cauchy        - ISA-L gf_gen_cauchy1_matrix
  jerasure_reed_sol_van, reed_sol_r6_op, cauchy_orig, cauchy_good
                - jerasure-compatible matrices (parity with jerasure plugin)

Beyond the interface, the plugin exposes a batched device-resident path
(`encode_batch`/`decode_batch`) used by the benchmark and the EC backend:
many stripes are encoded per dispatch so the host<->device boundary stays
off the hot path.
"""
from __future__ import annotations

import numpy as np

from .. import gf
from ..interface import ErasureCodeProfile, ErasureCodeError, to_int, \
    sanity_check_k_m
from ..matrix_code import MatrixErasureCode, make_decode_matrix, \
    erasure_signature
from ..registry import ErasureCodePlugin

EC_TPU_DEFAULT_ALIGNMENT = 32  # match isa (EC_ISA_ADDRESS_ALIGNMENT)


def _matrices(technique: str, k: int, m: int) -> np.ndarray:
    eye = np.eye(k, dtype=np.uint8)
    if technique == "reed_sol_van":
        return gf.isa_rs_matrix(k, m)
    if technique == "cauchy":
        return gf.isa_cauchy_matrix(k, m)
    if technique == "jerasure_reed_sol_van":
        return np.vstack([eye, gf.jerasure_vandermonde_coding_matrix(k, m)])
    if technique == "reed_sol_r6_op":
        if m != 2:
            raise ErasureCodeError("reed_sol_r6_op requires m=2")
        return np.vstack([eye, gf.jerasure_r6_coding_matrix(k)])
    if technique == "cauchy_orig":
        return np.vstack([eye, gf.cauchy_original_coding_matrix(k, m)])
    if technique == "cauchy_good":
        return np.vstack([eye, gf.cauchy_good_coding_matrix(k, m)])
    raise ErasureCodeError(f"ENOENT: tpu technique={technique!r} not supported")


class ErasureCodeTpu(MatrixErasureCode):
    DEFAULT_K = "8"
    DEFAULT_M = "4"

    def __init__(self) -> None:
        super().__init__()
        self.technique = "reed_sol_van"
        self.alignment = EC_TPU_DEFAULT_ALIGNMENT
        self._encode_mm = None          # GFMatmul for coding rows
        self._decode_mm: dict[str, object] = {}  # signature -> GFMatmul

    def init(self, profile: ErasureCodeProfile) -> None:
        profile.setdefault("plugin", "tpu")
        self.technique = profile.setdefault("technique", "reed_sol_van")
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        self.alignment = to_int("tpu-alignment", profile,
                                str(EC_TPU_DEFAULT_ALIGNMENT))
        sanity_check_k_m(self.k, self.m)

    def get_chunk_size(self, object_size: int) -> int:
        # identical to the isa plugin (ErasureCodeIsa.cc:66-79) by default
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % self.alignment
        if modulo:
            chunk_size += self.alignment - modulo
        return chunk_size

    def prepare(self) -> None:
        from ..kernels.bitmatmul import GFMatmul
        self._prepare(_matrices(self.technique, self.k, self.m))
        self._encode_mm = GFMatmul(self.encode_matrix[self.k:])

    # -- matmul backend on device -----------------------------------------
    def matmul(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        from ..kernels.bitmatmul import GFMatmul
        if self._encode_mm is not None and mat is not None and \
                mat.shape == self._encode_mm_shape and \
                np.array_equal(mat, self.encode_matrix[self.k:]):
            mm = self._encode_mm
        else:
            mm = GFMatmul(mat)
        return np.asarray(mm(data))

    @property
    def _encode_mm_shape(self):
        return (self.m, self.k)

    # -- batched device API (the perf path) -------------------------------
    def encode_batch(self, data):
        """(..., k, N) uint8 (host or device) -> (..., m, N) parity, on device.

        One dispatch encodes every stripe in the batch; keep inputs as jax
        arrays to avoid transfers between calls.
        """
        return self._encode_mm(data)

    def decode_batch(self, decode_index: list[int], erasures: list[int], data):
        """Reconstruct `erasures` from survivor chunks.

        data: (..., k, N) survivor chunks ordered by decode_index.
        Returns (..., len(erasures), N) on device.  The decode companion
        matrix is cached per erasure signature (ISA-L table-cache analogue).
        """
        from ..kernels.bitmatmul import GFMatmul
        sig = erasure_signature(decode_index, erasures)
        mm = self._decode_mm.get(sig)
        if mm is None:
            dmat = make_decode_matrix(self.encode_matrix, self.k,
                                      list(decode_index), list(erasures))
            mm = GFMatmul(dmat)
            self._decode_mm[sig] = mm
        return mm(data)

    def decode_batch_full(self, erasures: list[int], data):
        """Reconstruct `erasures` straight from the FULL chunk array —
        device-resident survivor selection.

        data: (..., k+m, N) with every chunk slot present; the content
        of erased slots is ignored (their decode-matrix columns are
        zero), so no survivor gather/copy happens on either host or
        device.  Returns (..., len(erasures), N) on device.  Matrices
        cached per erasure signature in HBM (ISA-L table-cache
        analogue, ref: ErasureCodeIsaTableCache.cc)."""
        from ..kernels.bitmatmul import GFMatmul
        from ..matrix_code import make_decode_matrix_full
        n = self.k + self.m
        erased = sorted(int(e) for e in erasures)
        sig = "full" + "".join(f"-{e}" for e in erased)
        mm = self._decode_mm.get(sig)
        if mm is None:
            decode_index = [i for i in range(n)
                            if i not in set(erased)][:self.k]
            dmat = make_decode_matrix_full(self.encode_matrix, self.k,
                                           n, decode_index, erased)
            mm = GFMatmul(dmat)
            self._decode_mm[sig] = mm
        return mm(data)


PLUGIN = ErasureCodePlugin("tpu", ErasureCodeTpu)
