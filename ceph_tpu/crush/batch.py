"""Batched CRUSH mapper — vmapped straw2 placement on device.

The TPU-native replacement for the reference's bulk placement paths
(OSDMapMapping/ParallelPGMapper src/osd/OSDMapMapping.h:18, CrushTester
src/crush/CrushTester.cc:477, osdmaptool --test-map-pgs): instead of
sharding PGs over a thread pool, the CRUSH map is compiled to flat arrays
and `do_rule` becomes a pure jittable function of the PG seed `x`,
vmapped over millions of seeds.

Semantics are bit-exact with the scalar engine (ceph_tpu.crush.mapper,
itself validated against the reference C core src/crush/mapper.c):

- straw2 (bucket_straw2_choose, mapper.c:361): 16-bit rjenkins hash →
  fixed-point crush_ln (mapper.c:248) → truncating s64 division by
  weight → first-max argmax.  crush_ln's `(x*RH)>>48` product exceeds
  s64 range, so it is computed in split 32-bit limbs (int64-safe).
- choose_firstn (mapper.c:460): the reject/collision retry cascade is
  re-expressed as a flat state machine per replica: descend on type
  mismatch, collide-retry *in the same bucket* while
  `flocal <= local_retries`, re-descend from the take bucket while
  `ftotal < tries`, else skip the replica; invalid items skip the
  replica immediately (mapper.c:540,553).
- choose_indep (mapper.c:655): already a bounded, positionally-stable
  loop (`ftotal < tries`, holes = CRUSH_ITEM_NONE) — mapped to
  `lax.while_loop` over rounds with a masked in-round replica sweep,
  including the observable out2 staleness quirks of the C code.
- chooseleaf recursion (both variants) is a bounded one-replica leaf
  descent with `recurse_tries`; `vary_r`/`stable` honored.

Restrictions of the batch path (compile_map raises BatchUnsupported;
callers fall back to the scalar engine):
- straw2 buckets only (the modern default).  uniform/list/tree/straw
  need stateful permutation buffers or build-time straws that do not
  vectorize the same way.
- choose_local_fallback_tries == 0 (jewel default; the perm-fallback
  path mapper.c:519 is inherently stateful/sequential).
- rjenkins1 hash only (the only hash the reference defines).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

try:
    enable_x64 = jax.enable_x64
except AttributeError:      # newer jax moved the scoped toggle
    from jax.experimental import enable_x64

from ._ln_tables import RH_LH_TBL, LL_TBL
from .hashes import _mix
from .types import (
    CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1, CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE, CrushMap,
)

# All 64-bit straw2/ln arithmetic runs inside a scoped
# `jax.enable_x64(True)` (map_batch) so the global dtype-promotion
# config of the host program (and the EC int8/uint8 kernels) is never
# mutated.  Module constants are plain Python ints / numpy arrays so
# their dtype is resolved at trace time inside that scope.
S64_MIN = -(1 << 62)  # below any real draw (draws are > -2^49)
U16 = 0xFFFF
LN_BIAS = 0x1000000000000

_SEED = jnp.uint32(1315423911)
_X0 = jnp.uint32(231232)
_Y0 = jnp.uint32(1232)

# descend outcome codes
_HIT, _EMPTY, _BAD = 0, 1, 2


class BatchUnsupported(ValueError):
    """Raised when a map/rule cannot run on the batch path."""


# ---------------------------------------------------------------------------
# rjenkins1 in jnp (uint32 wraparound; ref: src/crush/hash.c:12-113).
# The 9-step hashmix is shared with the scalar engine (hashes._mix is
# operator-generic and tracer-safe).

def _u32(v):
    return jnp.asarray(v).astype(jnp.int64).astype(jnp.uint32)


def jhash2(a, b):
    a, b = _u32(a), _u32(b)
    h = _SEED ^ a ^ b
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(_X0, a, h)
    b, y, h = _mix(b, _Y0, h)
    return h


def jhash3(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    h = _SEED ^ a ^ b ^ c
    x = _X0
    y = _Y0
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


# ---------------------------------------------------------------------------
# fixed-point ln (ref: src/crush/mapper.c:247-289), int64-safe

# kept as numpy so the int64 dtype survives regardless of the global
# x64 flag; they become constants at trace time (inside the x64 scope)
_RH_LH = np.asarray(RH_LH_TBL, dtype=np.int64)
_LL = np.asarray(LL_TBL, dtype=np.int64)


def crush_ln_vec(u):
    """2^44*log2(u+1) fixed point, elementwise over int arrays."""
    x = (u.astype(jnp.int64) + 1) & 0xFFFFFFFF
    x17 = x & 0x1FFFF
    # bit_length(x17) via unrolled comparisons (x17 <= 0x1FFFF)
    bl = jnp.zeros_like(x17)
    for k in range(17):
        bl = bl + (x17 >= (1 << k)).astype(jnp.int64)
    bits = 16 - bl
    need = (x & 0x18000) == 0
    xn = jnp.where(need, x << jnp.clip(bits, 0, 16), x)
    iexpon = jnp.where(need, 15 - bits, 15)
    index1 = (xn >> 8) << 1
    rh_lh = jnp.asarray(_RH_LH)
    RH = rh_lh[index1 - 256]
    LH = rh_lh[index1 + 1 - 256]
    # (xn * RH) >> 48 without u64: split RH into 32-bit limbs
    p_lo = xn * (RH & 0xFFFFFFFF)
    p_hi = xn * (RH >> 32)
    xl64 = ((p_lo + ((p_hi & 0xFFFF) << 32)) >> 48) + (p_hi >> 16)
    index2 = xl64 & 0xFF
    LL = jnp.asarray(_LL)[index2]
    return (iexpon << 44) + ((LH + LL) >> 4)


def _build_ln16_table() -> np.ndarray:
    """crush_ln over the FULL 16-bit straw2 domain, precomputed host-
    side with the same fixed-point arithmetic (numpy int64).

    straw2 only ever evaluates ln on `hash & 0xFFFF` (mapper.c:377), so
    the whole function collapses to one 65536-entry device gather —
    measured 3x faster than the normalize/multiply/double-gather chain
    on v5e (the int64-emulated multiplies dominate there)."""
    x = (np.arange(65536, dtype=np.int64) + 1) & 0xFFFFFFFF
    x17 = x & 0x1FFFF
    bl = np.zeros_like(x17)
    for k in range(17):
        bl += (x17 >= (1 << k)).astype(np.int64)
    bits = 16 - bl
    need = (x & 0x18000) == 0
    xn = np.where(need, x << np.clip(bits, 0, 16), x)
    iexpon = np.where(need, 15 - bits, 15)
    index1 = (xn >> 8) << 1
    RH = _RH_LH[index1 - 256]
    LH = _RH_LH[index1 + 1 - 256]
    p_lo = xn * (RH & 0xFFFFFFFF)
    p_hi = xn * (RH >> 32)
    xl64 = ((p_lo + ((p_hi & 0xFFFF) << 32)) >> 48) + (p_hi >> 16)
    LL = _LL[xl64 & 0xFF]
    return (iexpon << 44) + ((LH + LL) >> 4)


#: ln(u+1) for every u in [0, 0xFFFF] — the straw2 hot-path table
_LN16 = _build_ln16_table()


def crush_ln16(u):
    """Table form of crush_ln_vec for 16-bit inputs (the straw2 path)."""
    return jnp.asarray(_LN16)[u]


# crush_ln is monotone in u EXCEPT at the very top: u=65535 normalizes
# x=u+1=0x10000 with iexpon capped at 15, so its value dips BELOW
# ln(65534) (and sits above ln(65533)).  The weight-class straw2 path
# relies on monotonicity, so it orders hashes through a key space that
# swaps that single pair.  Verified against the table here; if a
# regenerated table ever breaks differently, the class path disables
# itself rather than silently diverging.
_LN16_DIPS = np.nonzero(np.diff(_LN16.astype(np.int64)) < 0)[0]
LN16_MONO_BY_SWAP = (
    len(_LN16_DIPS) == 0
    or (len(_LN16_DIPS) == 1 and int(_LN16_DIPS[0]) == 65534
        and _LN16[65533] <= _LN16[65535]))


def _mono_key(u):
    """Involution mapping u-space <-> a space where ln16 is monotone
    (swaps 65534 and 65535; identity elsewhere, incl. the -1 dead
    sentinel)."""
    return jnp.where(u == 65534, jnp.int64(65535),
                     jnp.where(u == 65535, jnp.int64(65534), u))


def _div_trunc(a, b):
    """C truncating signed division, b > 0."""
    q = jnp.abs(a) // jnp.maximum(b, 1)
    return jnp.where(a < 0, -q, q)


# ---------------------------------------------------------------------------
# compiled map

@dataclass(frozen=True)
class _StaticCfg:
    """Everything _do_rule_one decides at TRACE time, as a hashable
    key.  The compiled executable is cached module-wide on this (plus
    jit's own shape keying), so a new CompiledCrushMap for every
    osdmap epoch — same topology shape, same rules — reuses the
    executable instead of paying a fresh XLA compile (the reference's
    mgr calls calc_pg_upmaps every tick; a ~40 s recompile per epoch
    would dwarf the mapping itself)."""
    steps: tuple          # ((op, arg1, arg2, take_ok), ...)
    result_max: int
    tries: int            # choose_total_tries + 1
    local_retries: int
    vary_r: int
    stable: int
    descend_once: int
    max_devices: int
    max_buckets: int
    n_positions: int
    max_depth: int
    n_class_max: int
    use_classes: bool
    first_valid: int


@dataclass
class _CmView:
    """The array half of a compiled map, rebuilt inside the jitted
    function from ARGUMENTS (not closure constants) so the weights/
    items tables are runtime inputs.  Field names mirror
    CompiledCrushMap — every choose helper works on either."""
    items: object
    ids: object
    weights: object
    sizes: object
    btypes: object
    valid: object
    class_of: object
    class_w: object
    static: object

    @property
    def max_buckets(self):
        return self.static.max_buckets

    @property
    def max_depth(self):
        return self.static.max_depth

    @property
    def n_positions(self):
        return self.static.n_positions

    @property
    def n_class_max(self):
        return self.static.n_class_max

    @property
    def use_classes(self):
        return self.static.use_classes

    @property
    def max_devices(self):
        return self.static.max_devices


#: module-wide executable cache: _StaticCfg -> jitted vmapped rule fn
_RULE_JIT: dict = {}


#: class-path cutoff: with more distinct weights per bucket than this,
#: the masked per-class max (I x C compares per draw) costs more than
#: the ln gathers it saves and the engine keeps the direct path
CLASS_PATH_MAX = 16


@dataclass
class CompiledCrushMap:
    """CrushMap flattened to arrays for the batch engine."""
    map_: CrushMap
    items: jnp.ndarray        # (B, I) int32 — bucket members (pad 0)
    ids: jnp.ndarray          # (B, I) int32 — straw2 hash ids (choose_args)
    weights: jnp.ndarray      # (P, B, I) int64 — per-position 16.16 weights
    sizes: jnp.ndarray        # (B,) int32
    btypes: jnp.ndarray       # (B,) int32
    valid: jnp.ndarray        # (B,) bool
    max_devices: int
    max_buckets: int
    n_positions: int
    max_depth: int            # longest bucket chain (static descend bound)
    #: weight-class tables (see _straw2): class_of (P, B, I) int32 with
    #: -1 for zero-weight/pad lanes; class_w (P, B, C) int64
    class_of: jnp.ndarray | None = None
    class_w: jnp.ndarray | None = None
    n_class_max: int = 0
    use_classes: bool = False
    #: id of any non-empty bucket (safe target for masked lanes)
    first_valid: int = -1

    # -- public API ---------------------------------------------------------
    def map_batch(self, xs, weight, ruleno=0, result_max=None,
                  return_counts=False):
        """Map a batch of inputs.  xs: (N,) int seeds; weight: (D,) int
        16.16 reweight vector (device in/out/partial).  Returns
        (N, result_max) int32 placements (CRUSH_ITEM_NONE holes),
        optionally with per-row result counts."""
        if not (0 <= ruleno < len(self.map_.rules)) or \
                self.map_.rules[ruleno] is None:
            raise BatchUnsupported(f"no rule {ruleno}")
        rule = self.map_.rules[ruleno]
        choose_ops = (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                      CRUSH_RULE_CHOOSELEAF_FIRSTN,
                      CRUSH_RULE_CHOOSELEAF_INDEP)
        if result_max is None:
            # a choose step with arg1 <= 0 means numrep = result_max
            # (mapper.c:972-976): no sensible default exists
            if any(s.op in choose_ops and s.arg1 <= 0 for s in rule.steps):
                raise BatchUnsupported(
                    f"rule {ruleno} has a choose step with numrep <= 0 "
                    "(numrep = result_max - pass result_max explicitly, "
                    "e.g. k+m for an EC rule)")
            # upper bound on emitted results: chained choose steps
            # multiply, emits accumulate
            wmax = 0
            total = 0
            for s in rule.steps:
                if s.op == CRUSH_RULE_TAKE:
                    wmax = 1
                elif s.op in choose_ops:
                    wmax *= s.arg1
                elif s.op == CRUSH_RULE_EMIT:
                    total += wmax
                    wmax = 0
            result_max = max(total, 1)
        m = self.map_
        steps = tuple(
            (st.op, st.arg1, st.arg2,
             bool((0 <= st.arg1 < m.max_devices)
                  or (st.arg1 < 0 and m.bucket(st.arg1) is not None))
             if st.op == CRUSH_RULE_TAKE else False)
            for st in rule.steps)
        static = _StaticCfg(
            steps=steps, result_max=int(result_max),
            tries=m.choose_total_tries + 1,
            local_retries=m.choose_local_tries,
            vary_r=m.chooseleaf_vary_r, stable=m.chooseleaf_stable,
            descend_once=m.chooseleaf_descend_once,
            max_devices=m.max_devices, max_buckets=self.max_buckets,
            n_positions=self.n_positions, max_depth=self.max_depth,
            n_class_max=self.n_class_max,
            use_classes=self.use_classes,
            first_valid=self.first_valid)
        with enable_x64(True):
            fn = _RULE_JIT.get(static)
            if fn is None:
                def one(arrays, x, weight, static=static):
                    cm = _CmView(*arrays, static)
                    return _do_rule_one(cm, static, x, weight)
                fn = jax.jit(jax.vmap(one, in_axes=(None, 0, None)))
                _RULE_JIT[static] = fn
            arrays = (self.items, self.ids, self.weights, self.sizes,
                      self.btypes, self.valid, self.class_of,
                      self.class_w)
            xs = jnp.asarray(xs, dtype=jnp.int64)
            weight = jnp.asarray(weight, dtype=jnp.int64)
            # the placement tables were staged once at compile_map;
            # under CEPH_TPU_JAXGUARD an implicit transfer inside the
            # batched mapping dispatch is an error
            from ..common import jaxguard
            with jaxguard.guard_transfers():
                res, cnt = fn(arrays, xs, weight)
        if return_counts:
            return res, cnt
        return res


def compile_map(map_: CrushMap, choose_args=None,
                class_path: bool | None = None) -> CompiledCrushMap:
    """Flatten a CrushMap for the batch engine (straw2-only).

    class_path: None = auto (on when every bucket has at most
    CLASS_PATH_MAX distinct positive weights per position); True/False
    force it — tests use this to pin each straw2 formulation."""
    if isinstance(choose_args, str):
        choose_args = map_.choose_args.get(choose_args)
    choose_args = choose_args or {}
    B = map_.max_buckets
    I = 1
    P = 1
    for b in map_.buckets:
        if b is None:
            continue
        if b.alg != CRUSH_BUCKET_STRAW2:
            raise BatchUnsupported(
                f"bucket {b.id}: alg {b.alg} not batchable (straw2 only)")
        if b.hash != CRUSH_HASH_RJENKINS1:
            raise BatchUnsupported(f"bucket {b.id}: non-rjenkins hash")
        I = max(I, b.size)
        arg = choose_args.get(b.id)
        if arg is not None and arg.weight_set is not None:
            P = max(P, len(arg.weight_set))
    if map_.choose_local_fallback_tries:
        raise BatchUnsupported("choose_local_fallback_tries > 0")
    # validate item references: the scalar oracle fails loudly on a
    # dangling bucket id; the batch engine must not silently diverge
    for b in map_.buckets:
        if b is None:
            continue
        for it in b.items:
            if it < 0 and (
                    -1 - it >= B or map_.buckets[-1 - it] is None):
                raise BatchUnsupported(
                    f"bucket {b.id} references missing bucket {it}")
    # longest bucket chain = static bound for the descend loops;
    # also rejects cyclic maps (the scalar engine would not terminate)
    depth_memo: dict[int, int] = {}

    def bdepth(bi: int, stack: set) -> int:
        if bi in stack:
            raise BatchUnsupported(f"bucket cycle through {-1 - bi}")
        if bi in depth_memo:
            return depth_memo[bi]
        stack.add(bi)
        d = 1
        for it in map_.buckets[bi].items:
            if it < 0:
                d = max(d, 1 + bdepth(-1 - it, stack))
        stack.remove(bi)
        depth_memo[bi] = d
        return d

    max_depth = max(
        (bdepth(bi, set()) for bi, b in enumerate(map_.buckets)
         if b is not None), default=1)

    items = np.zeros((B, I), dtype=np.int32)
    ids = np.zeros((B, I), dtype=np.int32)
    weights = np.zeros((P, B, I), dtype=np.int64)
    sizes = np.zeros((B,), dtype=np.int32)
    btypes = np.zeros((B,), dtype=np.int32)
    valid = np.zeros((B,), dtype=bool)
    for bi, b in enumerate(map_.buckets):
        if b is None:
            continue
        n = b.size
        valid[bi] = True
        sizes[bi] = n
        btypes[bi] = b.type
        items[bi, :n] = b.items
        arg = choose_args.get(b.id)
        ids[bi, :n] = (arg.ids if arg is not None and arg.ids is not None
                       else b.items)
        for p in range(P):
            if arg is not None and arg.weight_set is not None:
                ws = arg.weight_set[min(p, len(arg.weight_set) - 1)]
            else:
                ws = b.item_weights
            weights[p, bi, :n] = ws
    # -- weight classes (the straw2 argmax shortcut, see _straw2) -------
    # group each bucket's items by their exact weight; per draw the
    # engine takes a masked max of the raw 16-bit hashes per class and
    # evaluates ln only on the C class winners instead of all I items
    class_lists: dict[tuple[int, int], list[int]] = {}
    cmax = 1
    for bi, b in enumerate(map_.buckets):
        if b is None:
            continue
        for p in range(P):
            # dict preserves first-occurrence order with O(1)
            # membership (a list scan here was O(I*C) per bucket
            # per position on every compile)
            seen = {int(w): None for w in weights[p, bi, :b.size]
                    if w > 0}
            class_lists[(p, bi)] = list(seen)
            cmax = max(cmax, len(seen))
    use_classes = (cmax <= CLASS_PATH_MAX if class_path is None
                   else class_path) and LN16_MONO_BY_SWAP
    class_of = np.full((P, B, I), -1, dtype=np.int32)
    class_w = np.ones((P, B, cmax), dtype=np.int64)
    for (p, bi), seen in class_lists.items():
        class_w[p, bi, :len(seen)] = seen
        lut = {w: c for c, w in enumerate(seen)}
        n = map_.buckets[bi].size
        for i in range(n):
            w = int(weights[p, bi, i])
            if w > 0:
                class_of[p, bi, i] = lut[w]
    with enable_x64(True):  # weights table must stay int64
        return CompiledCrushMap(
            map_=map_, items=jnp.asarray(items), ids=jnp.asarray(ids),
            weights=jnp.asarray(weights), sizes=jnp.asarray(sizes),
            btypes=jnp.asarray(btypes), valid=jnp.asarray(valid),
            max_devices=map_.max_devices, max_buckets=B, n_positions=P,
            max_depth=max_depth, class_of=jnp.asarray(class_of),
            class_w=jnp.asarray(class_w), n_class_max=cmax,
            use_classes=use_classes,
            first_valid=next(
                (-1 - bi for bi, b in enumerate(map_.buckets)
                 if b is not None and b.size > 0), -1))


# ---------------------------------------------------------------------------
# core choose primitives (single-x; vmapped by map_batch)

def _straw2(cm: CompiledCrushMap, bidx, x, r, position):
    """bucket_straw2_choose (mapper.c:361-390) for dense bucket bidx.

    Two bit-identical formulations:

    * **class path** (default): `crush_ln` is monotonically
      nondecreasing and `draw = trunc(ln(u)/w)` is monotone in ln for
      fixed w > 0, so WITHIN a weight class the winning item is simply
      the one with the max 16-bit hash — no ln, no division.  The
      engine takes a masked max of the raw hashes per class (compile
      time grouped, C classes) and evaluates ln/div only on the C
      class winners; a uniform bucket (C=1) pays ONE ln per draw
      instead of I.  This is the TPU answer to the reference's
      per-item serial ln loop (mapper.c:377): the 64Ki-table gather
      was the placement wall (~5/6 of a draw pass), and it shrinks by
      I/C.  Ties keep C semantics: first index wins (argmax picks the
      first in-class max; cross-class ties resolve to the smallest
      item index, matching the strict `>` update in
      bucket_straw2_choose).
    * **direct path**: per-item ln gather — kept for maps with more
      than CLASS_PATH_MAX distinct weights in a bucket, where the
      (I x C) class masking would outgrow the gather it saves.
    """
    ids = cm.ids[bidx]
    pos = jnp.minimum(position, cm.n_positions - 1)
    u = jhash3(x, ids, r).astype(jnp.int64) & U16
    I = cm.items.shape[1]
    lane_ok = jnp.arange(I) < cm.sizes[bidx]
    if cm.use_classes:
        cls = cm.class_of[pos, bidx]                   # (I,) -1 = dead
        cw = cm.class_w[pos, bidx]                     # (C,)
        ue = jnp.where(lane_ok & (cls >= 0), u, jnp.int64(-1))
        uk = _mono_key(ue)          # ln16 is monotone in key space
        cmask = cls[None, :] == jnp.arange(cm.n_class_max)[:, None]
        kc = jnp.where(cmask, uk[None, :], jnp.int64(-1))   # (C, I)
        kmax = kc.max(axis=1)
        umax = _mono_key(kmax)      # back to u-space for the table
        # the class draw: ln(u)-LN_BIAS is always negative for 16-bit
        # u, so trunc(ln_val/w) = -(|ln_val| // w)
        absln = LN_BIAS - crush_ln16(jnp.maximum(umax, 0))
        k = absln // cw
        draws = jnp.where(kmax >= 0, -k, S64_MIN)      # (C,)
        # tie floor: the truncating division collapses a contiguous
        # key range onto the winning draw — the C core's strict->
        # update means the FIRST index in that range wins, not the
        # max-key one.  kk = min{key : ln16(unkey) >= thr}, found by
        # 16-step binary search in key space (C lanes, not I)
        x_thr = LN_BIAS - (k + 1) * cw + 1
        lo = jnp.zeros_like(kmax)
        hi = jnp.maximum(kmax, 0)
        for _ in range(16):
            mid = (lo + hi) >> 1
            ok = crush_ln16(_mono_key(mid)) >= x_thr
            hi = jnp.where(ok, mid, hi)
            lo = jnp.where(ok, lo, mid + 1)
        # first item index whose draw equals the class draw
        idx_c = jnp.where(cmask & (uk[None, :] >= hi[:, None]),
                          jnp.arange(I)[None, :], I).min(axis=1)
        best = draws.max()
        idx = jnp.where(draws == best, idx_c, I).min()
        idx = jnp.where(best == S64_MIN, 0, idx)       # all-dead bucket
        return cm.items[bidx, idx]
    w = cm.weights[pos, bidx]
    ln = crush_ln16(u) - LN_BIAS
    draws = jnp.where(w > 0, _div_trunc(ln, w), S64_MIN)
    draws = jnp.where(lane_ok, draws, S64_MIN - 1)
    return cm.items[bidx, jnp.argmax(draws)]


def _item_type(cm: CompiledCrushMap, item):
    bidx = jnp.clip(-1 - item, 0, cm.max_buckets - 1)
    return jnp.where(item < 0, cm.btypes[bidx], 0)


def _bucket_ok(cm: CompiledCrushMap, item):
    """item is a loadable bucket id."""
    inb = (item < 0) & ((-1 - item) < cm.max_buckets)
    bidx = jnp.clip(-1 - item, 0, cm.max_buckets - 1)
    return inb & cm.valid[bidx]


def _is_out(cm: CompiledCrushMap, weight, item, x):
    """Probabilistic reweight rejection (mapper.c:424-441)."""
    D = weight.shape[0]
    idx = jnp.clip(item, 0, D - 1)
    w = weight[idx]
    oob = item >= D
    return oob | ((w < 0x10000) & (
        (w == 0) | ((jhash2(x, item).astype(jnp.int64) & U16) >= w)))


def _descend(cm: CompiledCrushMap, x, r, start_item, target_type, position):
    """Straw2-walk from bucket `start_item` down until an item of
    target_type or a dead end.  Returns (item, parent, code):
    parent = bucket the item was chosen from (for in-bucket retries);
    code = _HIT | _EMPTY (a size-0 bucket was reached) | _BAD (invalid
    item id / non-bucket of wrong type, mapper.c:540,553).

    Mirrors the `retry_bucket` type-mismatch descent inside both
    crush_choose_firstn (mapper.c:546-556) and crush_choose_indep
    (mapper.c:744-773); the same r is used at every level.
    """
    def cond(st):
        cur, item, code, done, depth = st
        return (~done) & (depth < cm.max_depth)

    def body(st):
        cur, item, code, done, depth = st
        bidx = -1 - cur
        empty = cm.sizes[bidx] == 0
        nxt = _straw2(cm, bidx, x, r, position)
        ntype = _item_type(cm, nxt)
        bad = (nxt >= cm.max_devices) | \
              ((ntype != target_type) & ~_bucket_ok(cm, nxt))
        hit = (ntype == target_type) & (nxt < cm.max_devices)
        code2 = jnp.where(empty, _EMPTY,
                          jnp.where(bad, _BAD,
                                    jnp.where(hit, _HIT, code)))
        done2 = empty | bad | hit
        cur2 = jnp.where(done2, cur, nxt)
        item2 = jnp.where(hit & ~empty, nxt, item)
        return (cur2, item2, code2, done2, depth + 1)

    cur, item, code, done, _ = lax.while_loop(
        cond, body,
        (start_item, jnp.int32(0), jnp.int32(_BAD), jnp.bool_(False),
         jnp.int32(0)))
    # depth exhaustion counts as BAD (cannot happen on well-formed maps)
    code = jnp.where(done, code, _BAD)
    return item, cur, code


def _firstn_rep(cm, x, take_item, weight, rep, parent_r, target_type,
                out_arr, outpos, tries, local_retries, vary_r, stable,
                recurse_tries, recurse_to_leaf, out2_arr, result_max):
    """One replica of crush_choose_firstn (mapper.c:460-645): descend,
    reject/collide retry cascade.  Returns (item, leaf, skipped)."""
    pos_idx = jnp.arange(result_max)

    def cond(st):
        in_item, ftotal, flocal, item, leaf, done, skipped = st
        return ~done

    def body(st):
        in_item, ftotal, flocal, item, leaf, done, skipped = st
        r = rep + parent_r + ftotal
        item_n, parent, code = _descend(cm, x, r, in_item, target_type,
                                        outpos)
        bad = code == _BAD          # → skip this replica (no retry)
        empty = code == _EMPTY      # → reject (retry path)
        ok = code == _HIT
        collide = ok & jnp.any((pos_idx < outpos) & (out_arr == item_n))
        if recurse_to_leaf:
            sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)
            rep_eff = jnp.int32(0) if stable else outpos
            leaf_n, leaf_ok = _leaf_firstn(
                cm, x, item_n, weight, rep_eff, sub_r, recurse_tries,
                local_retries, out2_arr, outpos, result_max)
            leaf_ok = leaf_ok | (item_n >= 0)
            leaf_n = jnp.where(item_n >= 0, item_n, leaf_n)
        else:
            leaf_n, leaf_ok = jnp.int32(0), jnp.bool_(True)
        reject = empty | (ok & ~collide & (
            ~leaf_ok |
            ((_item_type(cm, item_n) == 0) &
             _is_out(cm, weight, item_n, x))))
        fail = reject | collide
        ftotal2 = ftotal + fail
        flocal2 = flocal + fail
        local_retry = collide & (flocal2 <= local_retries)
        redescent = fail & ~local_retry & (ftotal2 < tries)
        succ = ok & ~fail
        done2 = succ | bad | (fail & ~local_retry & ~redescent)
        skipped2 = bad | (fail & done2)
        in_next = jnp.where(local_retry, parent, take_item)
        flocal3 = jnp.where(local_retry, flocal2, 0)
        return (in_next, ftotal2, flocal3,
                jnp.where(succ, item_n, item),
                jnp.where(succ, leaf_n, leaf),
                done2, skipped2)

    st0 = (take_item, jnp.int32(0), jnp.int32(0), jnp.int32(0),
           jnp.int32(0), jnp.bool_(False), jnp.bool_(False))
    _, _, _, item, leaf, _, skipped = lax.while_loop(cond, body, st0)
    return item, leaf, skipped


def _leaf_firstn(cm, x, bucket_item, weight, rep_eff, parent_r, tries,
                 local_retries, out2_arr, outpos, result_max):
    """Inner chooseleaf descent (mapper.c:566-595 → one-replica recursive
    crush_choose_firstn with type 0, no further recursion).
    Returns (leaf, success)."""
    pos_idx = jnp.arange(result_max)

    def cond(st):
        in_item, ftotal, flocal, item, done, succ = st
        return ~done

    def body(st):
        in_item, ftotal, flocal, item, done, succ = st
        r = rep_eff + parent_r + ftotal
        item_n, parent, code = _descend(cm, x, r, in_item, 0, outpos)
        bad = code == _BAD
        empty = code == _EMPTY
        ok = code == _HIT
        collide = ok & jnp.any((pos_idx < outpos) & (out2_arr == item_n))
        reject = empty | (ok & ~collide & _is_out(cm, weight, item_n, x))
        fail = reject | collide
        ftotal2 = ftotal + fail
        flocal2 = flocal + fail
        local_retry = collide & (flocal2 <= local_retries)
        redescent = fail & ~local_retry & (ftotal2 < tries)
        s = ok & ~fail
        done2 = s | bad | (fail & ~local_retry & ~redescent)
        in_next = jnp.where(local_retry, parent, bucket_item)
        flocal3 = jnp.where(local_retry, flocal2, 0)
        return (in_next, ftotal2, flocal3,
                jnp.where(s, item_n, item), done2, s)

    st0 = (bucket_item, jnp.int32(0), jnp.int32(0), jnp.int32(0),
           jnp.bool_(False), jnp.bool_(False))
    _, _, _, item, _, succ = lax.while_loop(cond, body, st0)
    return item, succ


def _choose_firstn(cm, x, take_item, weight, numrep, target_type,
                   count0, tries, recurse_tries, local_retries,
                   recurse_to_leaf, vary_r, stable, result_max):
    """crush_choose_firstn over all replicas of one take segment.  The
    C core hands each take item a fresh output segment (o+osize, j=0,
    mapper.c:1038-1043), so the segment always starts at position 0 and
    `rep = 0 .. numrep-1` regardless of the stable tunable.  Returns
    (seg_out, seg_out2, got)."""
    pos_idx = jnp.arange(result_max)
    out = jnp.zeros((result_max,), dtype=jnp.int32)
    out2 = jnp.zeros((result_max,), dtype=jnp.int32)
    outpos = jnp.int32(0)
    count = count0
    for rep_off in range(numrep):
        active = count > 0
        item, leaf, skipped = _firstn_rep(
            cm, x, take_item, weight, jnp.int32(rep_off), jnp.int32(0),
            target_type, out, outpos, tries, local_retries, vary_r,
            stable, recurse_tries, recurse_to_leaf, out2, result_max)
        write = active & ~skipped
        out = jnp.where(write & (pos_idx == outpos), item, out)
        if recurse_to_leaf:
            out2 = jnp.where(write & (pos_idx == outpos), leaf, out2)
        outpos = outpos + write
        count = count - write
    return out, out2, outpos


def _leaf_indep(cm, x, bucket_item, weight, numrep, parent_r, tries,
                rep):
    """Inner chooseleaf descent for indep (mapper.c:781-790 → one-slot
    recursive crush_choose_indep, type 0).  Returns leaf or NONE."""
    def cond(st):
        ft, leaf, done = st
        return (~done) & (ft < tries)

    def body(st):
        ft, leaf, done = st
        r = rep + parent_r + numrep * ft
        item, parent, code = _descend(cm, x, r, bucket_item, 0, rep)
        ok = code == _HIT
        hard = code == _BAD
        reject = ok & _is_out(cm, weight, item, x)
        good = ok & ~reject
        # hard failure fills the slot with NONE permanently
        leaf2 = jnp.where(good, item,
                          jnp.where(hard, jnp.int32(CRUSH_ITEM_NONE), leaf))
        return (ft + 1, leaf2, good | hard)

    _, leaf, done = lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.int32(CRUSH_ITEM_NONE), jnp.bool_(False)))
    return leaf


def _choose_indep(cm, x, take_item, weight, left0, numrep, target_type,
                  tries, recurse_tries, recurse_to_leaf, result_max):
    """crush_choose_indep (mapper.c:655-830) over one take segment
    (segment-relative positions, see _choose_firstn): breadth-first,
    positionally stable; holes become CRUSH_ITEM_NONE.
    Returns (seg_out, seg_out2) with slots [0, left0) filled."""
    pos_idx = jnp.arange(result_max)
    in_range = pos_idx < left0
    out = jnp.where(in_range, CRUSH_ITEM_UNDEF, 0).astype(jnp.int32)
    out2 = jnp.where(in_range, CRUSH_ITEM_UNDEF, 0).astype(jnp.int32)
    endpos = left0
    outpos = jnp.int32(0)

    def round_body(st):
        out, out2, left, ftotal = st

        def slot(carry, rep_off):
            out, out2, left = carry
            rep = rep_off.astype(jnp.int32)
            slot_val = out[jnp.minimum(rep, result_max - 1)]
            todo = (rep < endpos) & (slot_val == CRUSH_ITEM_UNDEF)
            rr = rep + numrep * ftotal
            item, parent, code = _descend(cm, x, rr, take_item,
                                          target_type, outpos)
            ok = code == _HIT
            hard = code == _BAD  # → NONE immediately (mapper.c:731,758)
            collide = ok & jnp.any(in_range & (out == item))
            if recurse_to_leaf:
                leaf = jnp.where(
                    item < 0,
                    _leaf_indep(cm, x, item, weight, numrep, rr,
                                recurse_tries, rep),
                    item)
                leaf_fail = (item < 0) & (leaf == CRUSH_ITEM_NONE)
            else:
                leaf = jnp.int32(0)
                leaf_fail = jnp.bool_(False)
            reject = ok & ((_item_type(cm, item) == 0) &
                           _is_out(cm, weight, item, x))
            good = ok & ~collide & ~leaf_fail & ~reject
            sel = pos_idx == rep
            out = jnp.where(todo & sel & good, item, out)
            out = jnp.where(todo & sel & hard,
                            jnp.int32(CRUSH_ITEM_NONE), out)
            if recurse_to_leaf:
                # C writes out2[rep] before the is_out check, so a
                # rejected device leaves a stale out2 entry
                # (mapper.c:791-793); and a failed bucket recursion
                # leaves out2[rep] = NONE.  Replicate both.
                stale = todo & sel & ok & ~collide & (
                    ((item >= 0) & reject) | leaf_fail)
                out2 = jnp.where(todo & sel & good, leaf, out2)
                out2 = jnp.where(stale, jnp.where(leaf_fail,
                                                  jnp.int32(CRUSH_ITEM_NONE),
                                                  item), out2)
                out2 = jnp.where(todo & sel & hard,
                                 jnp.int32(CRUSH_ITEM_NONE), out2)
            left = left - (todo & (good | hard))
            return (out, out2, left), None

        (out, out2, left), _ = lax.scan(
            slot, (out, out2, left), jnp.arange(result_max))
        return out, out2, left, ftotal + 1

    def round_cond(st):
        _, _, left, ftotal = st
        return (left > 0) & (ftotal < tries)

    out, out2, left, _ = lax.while_loop(
        round_cond, round_body, (out, out2, left0, jnp.int32(0)))
    out = jnp.where(in_range & (out == CRUSH_ITEM_UNDEF),
                    CRUSH_ITEM_NONE, out)
    out2 = jnp.where(in_range & (out2 == CRUSH_ITEM_UNDEF),
                     CRUSH_ITEM_NONE, out2)
    return out, out2


# ---------------------------------------------------------------------------
# rule interpreter (steps are static; state is traced)

def _do_rule_one(cm, static: _StaticCfg, x, weight):
    """do_rule (mapper.c:900-1105) for one input x.  cm is a _CmView
    (arrays are traced jit arguments); every rule decision comes from
    the static config so the executable caches across map epochs."""
    result_max = static.result_max
    tries = static.tries
    leaf_tries = 0
    local_retries = static.local_retries
    vary_r = static.vary_r
    stable = static.stable

    x = jnp.asarray(x, dtype=jnp.int64)
    result = jnp.full((result_max,), CRUSH_ITEM_NONE, dtype=jnp.int32)
    rcount = jnp.int32(0)
    w_items = jnp.zeros((result_max,), dtype=jnp.int32)
    w_count = jnp.int32(0)
    w_max = 0  # static upper bound on w_count
    pos_idx = jnp.arange(result_max)
    safe_bucket = jnp.int32(static.first_valid)

    for op, arg1, arg2, take_ok in static.steps:
        if op == CRUSH_RULE_TAKE:
            if take_ok:
                w_items = w_items.at[0].set(arg1)
                w_count = jnp.int32(1)
                w_max = 1
        elif op == CRUSH_RULE_SET_CHOOSE_TRIES:
            if arg1 > 0:
                tries = arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                leaf_tries = arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if arg1 >= 0:
                local_retries = arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if arg1 > 0:
                raise BatchUnsupported("set_choose_local_fallback_tries > 0")
        elif op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                    CRUSH_RULE_CHOOSELEAF_FIRSTN,
                    CRUSH_RULE_CHOOSELEAF_INDEP):
            firstn = op in (CRUSH_RULE_CHOOSE_FIRSTN,
                            CRUSH_RULE_CHOOSELEAF_FIRSTN)
            recurse = op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                             CRUSH_RULE_CHOOSELEAF_INDEP)
            numrep = arg1
            if numrep <= 0:
                numrep += result_max
            o = jnp.zeros((result_max,), dtype=jnp.int32)
            c = jnp.zeros((result_max,), dtype=jnp.int32)
            osize = jnp.int32(0)
            if firstn:
                if leaf_tries:
                    recurse_tries = leaf_tries
                elif static.descend_once:
                    recurse_tries = 1
                else:
                    recurse_tries = tries
            else:
                recurse_tries = leaf_tries if leaf_tries else 1
            # numrep <= 0 after adjustment skips every take item but the
            # o/w swap still empties w (mapper.c:1010-1015,1077-1081)
            for wi in (range(w_max) if numrep > 0 else ()):
                wi_item = w_items[wi]
                wi_ok = (jnp.int32(wi) < w_count) & _bucket_ok(cm, wi_item)
                # masked execution: run the choose from a safe bucket
                # unconditionally, discard results when wi is invalid.
                # each take item writes a fresh segment spliced at osize
                # (C passes o+osize with j=0, mapper.c:1038-1070)
                take = jnp.where(wi_ok, wi_item, safe_bucket)
                if firstn:
                    seg_o, seg_c, got = _choose_firstn(
                        cm, x, take, weight, numrep, arg2,
                        result_max - osize, tries, recurse_tries,
                        local_retries, recurse, vary_r, stable,
                        result_max)
                else:
                    got = jnp.minimum(jnp.int32(numrep),
                                      result_max - osize)
                    seg_o, seg_c = _choose_indep(
                        cm, x, take, weight, got, numrep, arg2,
                        tries, recurse_tries, recurse, result_max)
                got = jnp.where(wi_ok, got, 0)
                seg_idx = jnp.clip(pos_idx - osize, 0, result_max - 1)
                mask = (pos_idx >= osize) & (pos_idx < osize + got)
                o = jnp.where(mask, seg_o[seg_idx], o)
                c = jnp.where(mask, seg_c[seg_idx], c)
                osize = osize + got
            if recurse:
                o = jnp.where(pos_idx < osize, c, o)
            w_items = o
            w_count = osize
            w_max = (min(result_max, max(w_max * numrep, 1))
                     if numrep > 0 else 0)
        elif op == CRUSH_RULE_EMIT:
            # gather formulation (result[p] = w[p - rcount] for the
            # emitted range) rather than a scatter with computed
            # indices: the scatter form miscompiles on the TPU backend
            # when o/c are dead after this step (wrong operand survives
            # fusion/DCE); the gather form is also cheaper
            src_idx = jnp.clip(pos_idx - rcount, 0, result_max - 1)
            emit = (pos_idx >= rcount) & ((pos_idx - rcount) < w_count)
            result = jnp.where(emit, w_items[src_idx], result)
            rcount = jnp.minimum(rcount + w_count, result_max)
            w_items = jnp.zeros((result_max,), dtype=jnp.int32)
            w_count = jnp.int32(0)
            w_max = 0
    return result, rcount
