"""AWS Signature Version 4 verification against the cephx keyring.

The reference authenticates S3 requests by recomputing the SigV4
signature from the stored secret key (ref: src/rgw/rgw_auth_s3.cc
AWSv4ComplMulti / rgw_auth_s3.h; algorithm per the public AWS SigV4
spec).  Here S3 access keys ARE cephx entities: access_key_id is the
entity name (e.g. "client.s3user"), the secret key is its keyring
secret — one credential store for the whole cluster, the way radosgw
users live in the cluster's auth database.

`KeystoneEngine` is the second, config-gated engine: OpenStack-token
validation against an external keystone endpoint (ref:
src/rgw/rgw_auth_keystone.cc TokenEngine) — a gateway constructed
with `keystone_url` accepts `X-Auth-Token` requests, everyone else
never takes the branch.
"""
from __future__ import annotations

import calendar as _calendar
import hashlib
import hmac
import json as _json
import time as _time
import urllib.error
import urllib.request
from urllib.parse import urlparse

from ..common.lockdep import make_lock

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED = "UNSIGNED-PAYLOAD"
#: accepted clock skew for x-amz-date (AWS uses 15 minutes); bounds
#: how long a captured signed request stays replayable
MAX_SKEW = 15 * 60.0


class SigV4Error(Exception):
    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(msg or code)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    """AWS4 key derivation chain."""
    k = _hmac(f"AWS4{secret}".encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _parse_amz_date(s: str) -> float:
    """X-Amz-Date/x-amz-date -> epoch seconds; SigV4Error on junk."""
    try:
        # timegm, not mktime-timezone: the stamp is UTC, and mktime
        # applies DST — every signed request (including all peer sync
        # traffic) would be RequestTimeTooSkewed by 3600s for half
        # the year on a DST-observing host
        return float(_calendar.timegm(
            _time.strptime(s, "%Y%m%dT%H%M%SZ")))
    except ValueError:
        raise SigV4Error("AccessDenied", "malformed amz date")


def canonical_query(query: str) -> str:
    """Sort the wire query pairs.  The wire form is already
    percent-encoded by the client (and that exact form was signed), so
    pairs are sorted as-received — re-quoting would double-encode and
    break spec-compliant clients."""
    if not query:
        return ""
    pairs = []
    for part in query.split("&"):
        if not part:
            continue
        if "=" not in part:
            part += "="
        pairs.append(tuple(part.split("=", 1)))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))


def parse_auth_header(value: str) -> dict:
    """'AWS4-HMAC-SHA256 Credential=..., SignedHeaders=..., Signature=...'"""
    if not value.startswith(ALGORITHM):
        raise SigV4Error("InvalidArgument", "unsupported auth scheme")
    out = {}
    for field in value[len(ALGORITHM):].split(","):
        field = field.strip()
        if "=" not in field:
            continue
        k, v = field.split("=", 1)
        out[k] = v
    for need in ("Credential", "SignedHeaders", "Signature"):
        if need not in out:
            raise SigV4Error("InvalidArgument", f"missing {need}")
    cred = out["Credential"].split("/")
    if len(cred) != 5 or cred[4] != "aws4_request":
        raise SigV4Error("InvalidArgument", "malformed credential")
    return {"access_key": cred[0], "date": cred[1], "region": cred[2],
            "service": cred[3],
            "signed_headers": out["SignedHeaders"].split(";"),
            "signature": out["Signature"]}


def verify(method: str, path: str, headers, body: bytes,
           lookup_secret) -> str:
    """Verify a SigV4-signed request; returns the authenticated entity
    or raises SigV4Error (ref: rgw_auth_s3.cc the same recompute-and-
    compare flow)."""
    auth_header = headers.get("Authorization")
    if not auth_header:
        raise SigV4Error("AccessDenied", "anonymous access disabled")
    a = parse_auth_header(auth_header)
    secret = lookup_secret(a["access_key"])
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", a["access_key"])
    # freshness: x-amz-date within the skew window and matching the
    # credential scope date — without this, one captured request is a
    # permanent bearer token (AWS enforces the same 15-minute window)
    amz_date_hdr = headers.get("x-amz-date", "")
    if not amz_date_hdr or amz_date_hdr[:8] != a["date"]:
        raise SigV4Error("AccessDenied", "x-amz-date/scope mismatch")
    when = _parse_amz_date(amz_date_hdr)
    if abs(_time.time() - when) > MAX_SKEW:
        raise SigV4Error("RequestTimeTooSkewed", amz_date_hdr)
    u = urlparse(path)
    canon_headers = ""
    for name in a["signed_headers"]:
        v = headers.get(name, "")
        canon_headers += f"{name}:{' '.join(v.split())}\n"
    payload_hash = headers.get("x-amz-content-sha256",
                               hashlib.sha256(body).hexdigest())
    if payload_hash == UNSIGNED:
        payload_part = UNSIGNED
    else:
        payload_part = hashlib.sha256(body).hexdigest()
        if payload_hash != payload_part:
            raise SigV4Error("XAmzContentSHA256Mismatch")
    canonical = "\n".join([
        method,
        u.path or "/",       # wire path is already percent-encoded;
        canonical_query(u.query),   # re-quoting would double-encode
        canon_headers,
        ";".join(a["signed_headers"]),
        payload_part,
    ])
    amz_date = headers.get("x-amz-date", "")
    scope = f"{a['date']}/{a['region']}/{a['service']}/aws4_request"
    sts = "\n".join([
        ALGORITHM, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, a["date"], a["region"], a["service"])
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, a["signature"]):
        raise SigV4Error("SignatureDoesNotMatch")
    return a["access_key"]


def verify_presigned(method: str, path: str, headers,
                     lookup_secret) -> str:
    """Query-string SigV4 (presigned URL) verification (ref:
    src/rgw/rgw_auth_s3.h's query-string path; the AWS
    `X-Amz-Signature` scheme): the signature, credential scope and
    expiry all ride the query, the payload is UNSIGNED-PAYLOAD, and
    only the listed headers (normally just `host`) are signed."""
    u = urlparse(path)
    q: dict[str, str] = {}
    for part in u.query.split("&"):
        if "=" in part:
            k, v = part.split("=", 1)
            q[k] = v
    from urllib.parse import unquote
    if unquote(q.get("X-Amz-Algorithm", "")) != ALGORITHM:
        raise SigV4Error("InvalidArgument", "unsupported algorithm")
    cred = unquote(q.get("X-Amz-Credential", "")).split("/")
    if len(cred) != 5 or cred[4] != "aws4_request":
        raise SigV4Error("InvalidArgument", "malformed credential")
    access_key, date, region, service = cred[:4]
    secret = lookup_secret(access_key)
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", access_key)
    amz_date = unquote(q.get("X-Amz-Date", ""))
    if amz_date[:8] != date:
        raise SigV4Error("AccessDenied", "date/scope mismatch")
    when = _parse_amz_date(amz_date)
    try:
        expires = min(int(q.get("X-Amz-Expires", "300")), 7 * 86400)
    except ValueError:
        raise SigV4Error("AccessDenied", "malformed X-Amz-Expires")
    now = _time.time()
    if now > when + expires:
        raise SigV4Error("AccessDenied", "request has expired")
    if when > now + MAX_SKEW:
        raise SigV4Error("RequestTimeTooSkewed", amz_date)
    signed = unquote(q.get("X-Amz-SignedHeaders", "host")).split(";")
    canon_headers = ""
    for name in signed:
        v = headers.get(name, "")
        canon_headers += f"{name}:{' '.join(str(v).split())}\n"
    # canonical query: every pair as received EXCEPT the signature
    cq = canonical_query("&".join(
        part for part in u.query.split("&")
        if not part.startswith("X-Amz-Signature=")))
    canonical = "\n".join([method, u.path or "/", cq, canon_headers,
                           ";".join(signed), UNSIGNED])
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, date, region, service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, q.get("X-Amz-Signature", "")):
        raise SigV4Error("SignatureDoesNotMatch")
    return access_key


def presign(method: str, path: str, host: str, access_key: str,
            secret: str, expires: int = 300, region: str = "default",
            amz_date: str | None = None) -> str:
    """Generate a presigned URL path+query (the boto3
    generate_presigned_url analogue for tests and in-tree clients)."""
    from urllib.parse import quote
    amz_date = amz_date or _time.strftime("%Y%m%dT%H%M%SZ",
                                          _time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    params = {
        "X-Amz-Algorithm": ALGORITHM,
        "X-Amz-Credential": quote(f"{access_key}/{scope}", safe=""),
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    pairs = sorted(params.items())
    cq = "&".join(f"{k}={v}" for k, v in pairs)
    canonical = "\n".join([method, path, cq, f"host:{host}\n", "host",
                           UNSIGNED])
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return f"{path}?{cq}&X-Amz-Signature={sig}"


def sign_request(method: str, path: str, headers: dict, body: bytes,
                 access_key: str, secret: str, region: str = "default",
                 amz_date: str | None = None) -> dict:
    """Client-side signer (tests + any in-tree S3 client): returns the
    headers to add (Authorization, x-amz-date, x-amz-content-sha256)."""
    import time as _time
    amz_date = amz_date or _time.strftime("%Y%m%dT%H%M%SZ",
                                          _time.gmtime())
    date = amz_date[:8]
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {k.lower(): v for k, v in headers.items()}
    headers.setdefault("x-amz-date", amz_date)
    headers["x-amz-content-sha256"] = payload_hash
    signed = sorted(set(headers) | {"x-amz-date",
                                    "x-amz-content-sha256"})
    u = urlparse(path)
    canon_headers = "".join(
        f"{n}:{' '.join(str(headers.get(n, '')).split())}\n"
        for n in signed)
    canonical = "\n".join([
        method, u.path or "/",     # caller passes the wire-encoded
        canonical_query(u.query),  # path; sign exactly what is sent
        canon_headers, ";".join(signed),
        payload_hash])
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    out["Authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out


# -- keystone (ref: src/rgw/rgw_auth_keystone.cc TokenEngine) ----------

class KeystoneError(Exception):
    """Token rejection carrying the HTTP status + S3 error code the
    gateway should surface (401 InvalidToken for bad tokens, 403
    AccessDenied — the EACCES analogue — for expired ones, 503 when
    keystone itself is unreachable)."""

    def __init__(self, status: int, code: str, msg: str = ""):
        self.status = status
        self.code = code
        self.msg = msg or code
        super().__init__(self.msg)


def _keystone_expiry(raw) -> float | None:
    """expires_at -> epoch seconds.  The stub keystone in tests speaks
    epoch floats; real keystone speaks ISO8601 Z — accept both."""
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        pass
    try:
        iso = str(raw).rstrip("Z").split(".")[0]
        return float(_calendar.timegm(_time.strptime(
            iso, "%Y-%m-%dT%H:%M:%S")))
    except ValueError:
        raise KeystoneError(503, "ServiceUnavailable",
                            f"keystone sent unparsable expiry {raw!r}")


class KeystoneEngine:
    """Validate OpenStack tokens against a keystone endpoint.

    The reference asks keystone `GET /v3/auth/tokens` with the
    candidate in `X-Subject-Token` and caches accepted tokens
    (rgw_keystone_token_cache_size) so every S3 request does not pay a
    round trip; expiry is enforced locally on each use — a cached
    token that has since expired is EACCES, not a free pass.
    """

    #: accepted tokens are revalidated against keystone after this —
    #: the cache bounds latency, the expires_at bound stays exact
    CACHE_TTL_S = 10.0
    #: distinct tokens cached (ref: rgw_keystone_token_cache_size);
    #: short-lived per-session tokens would otherwise grow the dict
    #: for the gateway's lifetime
    CACHE_MAX = 1024

    def __init__(self, url: str, timeout: float = 5.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        #: token -> (revalidate_after_monotonic, user, expires_epoch)
        self._cache: dict[str, tuple[float, str, float | None]] = {}
        self._lock = make_lock("rgw.keystone")

    def _check_expiry(self, expires: float | None,
                      token: str | None = None) -> None:
        if expires is not None and _time.time() >= expires:
            if token is not None:
                with self._lock:
                    self._cache.pop(token, None)    # dead weight: an
                    # expired token can never validate again
            raise KeystoneError(403, "AccessDenied",
                                "token expired (EACCES)")

    def validate(self, token: str) -> str:
        """-> the token's user name, or KeystoneError."""
        if not token:
            raise KeystoneError(401, "InvalidToken",
                                "missing X-Auth-Token")
        now = _time.monotonic()
        with self._lock:
            hit = self._cache.get(token)
        if hit and now < hit[0]:
            self._check_expiry(hit[2], token)
            return hit[1]
        req = urllib.request.Request(
            f"{self.url}/v3/auth/tokens", method="GET",
            headers={"X-Subject-Token": token})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout) as resp:
                body = _json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            if e.code in (401, 404):
                raise KeystoneError(401, "InvalidToken",
                                    "keystone rejected the token")
            raise KeystoneError(503, "ServiceUnavailable",
                                f"keystone answered {e.code}")
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise KeystoneError(503, "ServiceUnavailable",
                                f"keystone unreachable: {e}")
        except ValueError:
            raise KeystoneError(503, "ServiceUnavailable",
                                "keystone sent bad JSON")
        tok = body.get("token") or {}
        user = (tok.get("user") or {}).get("name") or ""
        if not user:
            raise KeystoneError(401, "InvalidToken",
                                "token has no user")
        expires = _keystone_expiry(tok.get("expires_at"))
        self._check_expiry(expires)
        wall = _time.time()
        with self._lock:
            if len(self._cache) >= self.CACHE_MAX:
                # reap expired + revalidation-stale entries first;
                # fall back to dropping the oldest insertion
                self._cache = {
                    t: v for t, v in self._cache.items()
                    if now < v[0] and
                    (v[2] is None or wall < v[2])}
                while len(self._cache) >= self.CACHE_MAX:
                    self._cache.pop(next(iter(self._cache)))
            self._cache[token] = (now + self.CACHE_TTL_S, user,
                                  expires)
        return user
