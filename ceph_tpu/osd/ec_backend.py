"""ECBackend: the erasure-coded PG data plane.

The write/read/recovery engine of an EC placement group
(ref: src/osd/ECBackend.{h,cc}).  Two halves:

* `ECPGShard` — runs on every OSD in the acting set: applies per-shard
  write transactions (`handle_sub_write`, ref: ECBackend.cc:912),
  serves chunk reads with HashInfo crc verification
  (`handle_sub_read`, ref: ECBackend.cc:987), and keeps the shard's
  PGLog.
* `ECBackend` — runs on the primary: the three-queue RMW write
  pipeline (`submit_transaction` -> `start_rmw` -> waiting_state ->
  waiting_reads -> waiting_commit, ref: ECBackend.cc:1479,1832,2138),
  reconstructing reads (`objects_read_and_reconstruct` +
  `get_min_avail_to_read_shards`, ref: ECBackend.h:139,
  ECBackend.cc:1590), and shard recovery (`recover_object`,
  ref: ECBackend.cc:735).

TPU-first shape: all stripe math/coding goes through ceph_tpu.osd.ecutil
so every encode/decode is ONE batched device dispatch per op — the
reference's per-stripe loop and per-shard buffer assembly collapse into
array reshapes around the kernel.  Chunk fan-out to co-located shards
can additionally ride ICI collectives (ceph_tpu.dist) when the shards
are device-resident; this module is the host-side protocol engine.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from ..common.log import dout
from ..common.tracing import child_of
from ..ec.interface import ErasureCodeError
from ..msg.messages import (ECSubRead, ECSubReadReply, ECSubWrite,
                            ECSubWriteReply)
from ..store import ObjectId, StoreError, Transaction
from . import ecutil
from . import mutations as mut
from .ecutil import HashInfo, StripeInfo
from .pg_log import PGLog
from .pg_types import (DELETE, EVersion, MODIFY, PGLogEntry, PGMissing,
                       ZERO_VERSION)

OI_ATTR = "_"          # object info xattr key (ref: OI_ATTR "_")
HINFO_ATTR = "hinfo_key"   # (ref: ECUtil.h ECUtil::get_hinfo_key())


def pg_cid(pgid) -> str:
    return f"pg_{pgid}"


def ec_tombstone_txn(cid: str, oid: str, shard: int, ver: tuple,
                     n_chunks: int) -> Transaction:
    """The versioned-whiteout delete for one shard: data trimmed,
    delete version recorded, hinfo reset.  Single source of truth for
    the tombstone layout (delete commit, recovery spread, scrub repair
    all write this shape)."""
    soid = ObjectId(oid, shard=shard)
    return (Transaction()
            .touch(cid, soid)
            .truncate(cid, soid, 0)
            .setattrs(cid, soid, {
                OI_ATTR: {"size": 0, "version": tuple(ver),
                          "whiteout": True},
                HINFO_ATTR: HashInfo(n_chunks).to_dict()}))


def spread_tombstones(pgid, k_plus_m: int, local_shard, whoami: int,
                      send_osd, oid: str, ver: tuple,
                      targets: dict) -> None:
    """Spread a delete to shards that missed it — the EC analogue of
    pushing a replicated whiteout.  `targets` is {shard_index: osd};
    the version guard keeps a racing newer write authoritative.  The
    single implementation behind the daemon's scrub repair AND the
    peering statechart's reconcile/backfill."""
    cid = pg_cid(pgid)
    for s, osd in targets.items():
        txn = ec_tombstone_txn(cid, oid, s, ver, k_plus_m)
        msg = ECSubWrite(pgid=pgid, tid=0, shard=s, txn=txn,
                         log_entries=[], oid=oid,
                         guard_version=tuple(ver))
        if osd == whoami:
            local_shard.handle_sub_write(msg)
        else:
            send_osd(osd, msg)


def newest_oi_attrs(per_shard: dict):
    """Authoritative metadata selection for recovery: among the
    gathered per-shard attr dicts, the one whose OI version is newest
    wins (ties -> lowest shard index, so a half-applied attr update
    racing a failure resolves deterministically).  Returns
    (version_tuple, oi, hinfo_dict, user_xattrs) or None when no
    shard reported attrs.  Single implementation behind the full and
    sub-chunk recovery paths on both the backend and the peering
    statechart."""
    best = None
    for s in sorted(per_shard):
        a = per_shard[s]
        oi = a.get(OI_ATTR) or {}
        ver = tuple(oi.get("version", (0, 0)))
        if best is None or ver > best[0]:
            best = (ver, oi, a.get(HINFO_ATTR), mut.user_xattrs(a))
    return best


def ec_store_inventory(store, cid: str) -> dict:
    """oid -> {shard_index: ((epoch, ver), whiteout)} straight from a
    PG collection, independent of any live ECPGShard (a peer whose map
    lags can still answer a peering scan from its store; after a remap
    an OSD may hold chunks for indexes it no longer serves).  Version-
    carrying so stale chunks lose to newer writes/tombstones
    (ref: EC backfill presence/version decisions)."""
    out: dict[str, dict] = {}
    if not store.collection_exists(cid):
        return out
    for o in store.collection_list(cid):
        if o.name == "pgmeta":
            continue
        try:
            oi = store.getattr(cid, o, OI_ATTR)
        except StoreError:
            oi = {}
        v = oi.get("version", (0, 0))
        # replicated collections store EVersion objects; EC stores
        # (epoch, version) tuples — normalize either
        ver = (v.epoch, v.version) if hasattr(v, "epoch") else \
            tuple(v) if v else (0, 0)
        out.setdefault(o.name, {})[o.shard] = (
            ver, bool(oi.get("whiteout")))
    return out


# --------------------------------------------------------------------- shard


class ECPGShard:
    """Per-OSD shard service for one PG.

    The shard's pg_log is durable in the pgmeta omap (same key format
    as the replicated shard's — ref: PGLog::write_log_and_missing), so
    a restarted OSD re-peers from real log bounds and the EC peering
    statechart's GetInfo/GetLog phases have honest history to compare.
    Unlike the replicated shard the entries ride a trailing
    transaction rather than the data txn (the data txn arrives
    pre-encoded from the primary); the window where data landed
    without its log entry resolves through peering's version
    reconcile, which reads authoritative versions from OI attrs."""

    def __init__(self, pgid, shard: int, store, k: int, m: int,
                 fabric=None, create: bool = True):
        self.pgid = pgid
        self.shard = shard
        self.store = store
        self.k = k
        self.m = m
        self.cid = pg_cid(pgid)
        self.pg_log = PGLog()
        #: shared ICIFabric when this OSD is device-mesh resident
        #: (ceph_tpu.dist.fabric) — fabric sub-writes gather their
        #: chunk slice from the mesh instead of the message
        self.fabric = fabric
        if create and not store.collection_exists(self.cid):
            store.queue_transaction(
                Transaction().create_collection(self.cid))
        self._load_log()

    # -- durable log (shared format with ReplicatedPGShard) ------------
    def _load_log(self) -> None:
        from ..msg import encoding as wire
        from .pg_log import IndexedLog
        from .replicated_backend import _TAIL_KEY, PGMETA
        if not self.store.collection_exists(self.cid) or \
                not self.store.exists(self.cid, PGMETA):
            return
        omap = self.store.omap_get(self.cid, PGMETA)
        entries = [wire.decode(v) for k, v in sorted(omap.items())
                   if k.startswith("l.")]
        if not entries and _TAIL_KEY not in omap:
            return
        tail = wire.decode(omap[_TAIL_KEY]) if _TAIL_KEY in omap \
            else ZERO_VERSION
        head = entries[-1].version if entries else tail
        self.pg_log = PGLog(IndexedLog(entries, head=head, tail=tail))

    def persist_log(self) -> None:
        """Rewrite the whole durable log (shared transaction builder
        with ReplicatedPGShard — non-log pgmeta keys survive)."""
        from .replicated_backend import build_persist_log_txn
        self.store.queue_transaction(
            build_persist_log_txn(self.store, self.cid,
                                  self.pg_log.log))

    def log_info(self) -> tuple:
        """(last_update, log_tail) — the pg_info_t core GetInfo
        exchanges."""
        return self.pg_log.log.head, self.pg_log.log.tail

    def _append_log_durable(self, entries: list) -> None:
        from ..common.options import global_config
        from ..msg import encoding as wire
        from .replicated_backend import _TAIL_KEY, _log_key, PGMETA
        txn = Transaction()
        txn.touch(self.cid, PGMETA)
        txn.omap_setkeys(self.cid, PGMETA,
                         {_log_key(e.version): wire.encode(e)
                          for e in entries})
        cfg = global_config()
        if len(self.pg_log.log) > cfg["osd_max_pg_log_entries"]:
            keep = cfg["osd_min_pg_log_entries"]
            dropped = self.pg_log.log.entries[:-keep]
            if dropped:
                txn.omap_rmkeys(self.cid, PGMETA,
                                [_log_key(e.version) for e in dropped])
                self.pg_log.log.entries = \
                    self.pg_log.log.entries[-keep:]
                self.pg_log.log.tail = dropped[-1].version
                self.pg_log.log.index()
                txn.omap_setkeys(self.cid, PGMETA, {
                    _TAIL_KEY: wire.encode(self.pg_log.log.tail)})
        self.store.queue_transaction(txn)

    # -- write side (ref: ECBackend.cc:912 handle_sub_write) -----------
    def handle_sub_write(self, m: ECSubWrite) -> ECSubWriteReply:
        try:
            if m.guard_version is not None and m.oid and \
                    self._local_version(
                        m.oid,
                        shard=m.shard if m.shard >= 0
                        else self.shard) > tuple(m.guard_version):
                # recovery push planned before a newer client write
                # landed here: the local copy is already authoritative,
                # rolling it back would lose the write.  Ack success —
                # the pushing primary's goal (shard at >= guard) holds.
                return ECSubWriteReply(pgid=self.pgid, tid=m.tid,
                                       shard=self.shard, committed=True)
            if m.txn is not None and not m.txn.empty():
                self.store.queue_transaction(m.txn)
            if m.fabric_key is not None:
                self._apply_fabric_write(m)
            fresh = [e for e in m.log_entries
                     if e.version > self.pg_log.log.head]
            for e in fresh:
                self.pg_log.append(e)
            if fresh:
                self._append_log_durable(fresh)
            committed = True
        except (StoreError, KeyError, ValueError) as err:
            dout("osd", 0).write("%s shard %s sub_write failed: %s",
                                 self.pgid, self.shard, err)
            committed = False
        return ECSubWriteReply(pgid=self.pgid, tid=m.tid,
                               shard=self.shard, committed=committed)

    def _local_version(self, oid: str, shard: int | None = None) -> tuple:
        """Stored OI version of a chunk — `shard` defaults to this
        service's own index; guarded pushes check the INCOMING
        message's shard (a map-lagging receiver may serve a different
        index than the one being pushed)."""
        soid = ObjectId(oid, shard=self.shard if shard is None
                        else shard)
        try:
            v = self.store.getattr(self.cid, soid, OI_ATTR).get(
                "version", (0, 0))
        except StoreError:
            return (0, 0)
        return (v.epoch, v.version) if hasattr(v, "epoch") else \
            tuple(v) if v else (0, 0)

    def remove_shard_object(self, oid: str) -> None:
        """Drop the local chunk for `oid` (peering divergence: the
        authoritative interval does not know this entry — the chunk
        re-arrives through recovery at the authoritative version)."""
        soid = ObjectId(oid, shard=self.shard)
        if self.store.exists(self.cid, soid):
            self.store.queue_transaction(
                Transaction().remove(self.cid, soid))

    def _apply_fabric_write(self, m: ECSubWrite) -> None:
        """Device-mesh data path: gather this shard's chunk slice from
        the staged mesh arrays and apply it locally, maintaining the
        shard's own cumulative HashInfo (the control txn in `m.txn`
        carried everything else).  The mesh psum step replaced the
        chunk-byte fan-out (ref: ECBackend.cc:2037-2070)."""
        if self.fabric is None:
            raise StoreError("EIO", "fabric write but not resident")
        chunk = self.fabric.fetch_chunk(m.fabric_key, self.shard)
        soid = ObjectId(m.oid, shard=self.shard)
        hd = self._hinfo(soid)
        if m.hinfo_append:
            if m.chunk_off == 0:
                hd = HashInfo(self.k + self.m)    # fresh stream
            elif hd is None or not hd.has_chunk_hash() or \
                    hd.get_total_chunk_size() != m.chunk_off:
                hd = None                         # history broken
            if hd is not None:
                hd.append_shard(self.shard, m.chunk_off, chunk)
        else:
            hd = None
        if hd is None:
            # overwrite / inconsistent history: size tracked,
            # cumulative hashes invalidated (host path does the same)
            old_total = 0
            prev = self._hinfo(soid)
            if prev is not None:
                old_total = prev.get_total_chunk_size()
            hd = HashInfo(0)
            hd.total_chunk_size = max(old_total,
                                      m.chunk_off + len(chunk))
        self.store.queue_transaction(
            Transaction()
            .write(self.cid, soid, m.chunk_off, chunk)
            .setattrs(self.cid, soid, {HINFO_ATTR: hd.to_dict()}))

    # -- read side (ref: ECBackend.cc:987 handle_sub_read) -------------
    def handle_sub_read(self, m: ECSubRead) -> ECSubReadReply:
        reply = ECSubReadReply(pgid=self.pgid, tid=m.tid,
                               shard=self.shard)
        for oid, off, length in m.to_read:
            soid = ObjectId(oid, shard=self.shard)
            try:
                if self._is_whiteout(soid):
                    raise StoreError("ENOENT",
                                     f"{oid} deleted (whiteout)")
                buf = self.store.read(self.cid, soid, off, length)
                # integrity gate: full-stream reads verify the
                # cumulative shard crc (ref: ECBackend.cc:1059-1075)
                if off == 0 and length == 0:
                    hd = self._hinfo(soid)
                    if hd is not None and hd.has_chunk_hash() \
                            and hd.get_total_chunk_size() == len(buf):
                        from ..common.crc32c import crc32c
                        if crc32c(0xFFFFFFFF, buf) != \
                                hd.get_chunk_hash(self.shard):
                            raise StoreError(
                                "EIO", f"shard {self.shard} crc mismatch"
                                f" on {oid}")
                reply.buffers_read[oid] = buf
            except StoreError as err:
                reply.errors[oid] = err.errno_name
        # v2 sub-chunk repair reads: per-chunk extents expanded over
        # the local stream, replied as ONE concatenated repair-plane
        # buffer per oid (the clay helper read,
        # ref: ErasureCodeClay.cc:364 get_repair_subchunks; the crc
        # gate does not apply — partial ranges cannot re-hash the
        # cumulative stream, the rebuilt shard is crc-verified on its
        # next full read instead)
        for oid, extents in getattr(m, "subchunks", {}).items():
            soid = ObjectId(oid, shard=self.shard)
            try:
                if self._is_whiteout(soid):
                    raise StoreError("ENOENT",
                                     f"{oid} deleted (whiteout)")
                if m.chunk_size <= 0:
                    raise StoreError("EINVAL", "subchunks w/o chunk_size")
                stream_len = self.store.stat(self.cid, soid)["size"]
                abs_extents = ecutil.expand_stream_extents(
                    [tuple(e) for e in extents], m.chunk_size,
                    stream_len)
                reply.buffers_read[oid] = b"".join(
                    self.store.read(self.cid, soid, off, length)
                    for off, length in abs_extents)
            except (StoreError, ValueError) as err:
                reply.errors[oid] = getattr(err, "errno_name", "EIO")
        for oid in m.attrs_to_read:
            soid = ObjectId(oid, shard=self.shard)
            try:
                reply.attrs_read[oid] = self.store.getattrs(
                    self.cid, soid)
            except StoreError as err:
                reply.errors.setdefault(oid, err.errno_name)
        return reply

    def _hinfo(self, soid: ObjectId) -> Optional[HashInfo]:
        try:
            return HashInfo.from_dict(
                self.store.getattr(self.cid, soid, HINFO_ATTR))
        except StoreError:
            return None

    # -- metadata reads (user xattrs are replicated on every shard, so
    #    the primary's local shard serves them) ------------------------
    def getxattrs(self, oid: str) -> dict[str, bytes]:
        soid = ObjectId(oid, shard=self.shard)
        if not self.exists(oid):
            raise StoreError("ENOENT", oid)
        return mut.user_xattrs(self.store.getattrs(self.cid, soid))

    def getxattr(self, oid: str, name: str) -> bytes:
        xattrs = self.getxattrs(oid)
        if name not in xattrs:
            raise StoreError("ENODATA", f"{oid} xattr {name}")
        return xattrs[name]

    def object_size(self, oid: str) -> int:
        """Logical object size from the oi xattr."""
        soid = ObjectId(oid, shard=self.shard)
        try:
            return self.store.getattr(self.cid, soid, OI_ATTR)["size"]
        except StoreError:
            return 0

    def objects(self) -> list[str]:
        return sorted({o.name for o in self.store.collection_list(self.cid)
                       if o.name != "pgmeta"
                       and not self._is_whiteout(o)})

    def _is_whiteout(self, soid: ObjectId) -> bool:
        try:
            return bool(self.store.getattr(self.cid, soid,
                                           OI_ATTR).get("whiteout"))
        except StoreError:
            return False

    def shard_inventory(self) -> dict:
        return ec_store_inventory(self.store, self.cid)

    def collection_bytes(self) -> int:
        """Physical bytes this shard's collection stores (chunk
        streams) — the store-accounting feed for pg stats."""
        from .snap_mapper import collection_bytes
        return collection_bytes(self.store, self.cid)

    def stat_summary(self) -> tuple[int, int, int]:
        """(client_objects, logical_bytes, store_bytes) in ONE
        collection pass (same contract as the replicated shard's):
        an object counts while ANY local shard stream of it is
        non-whiteout; logical size reads this service's own shard OI
        like object_size does."""
        if not self.store.collection_exists(self.cid):
            return (0, 0, 0)
        store = 0
        live: set[str] = set()
        sizes: dict[str, int] = {}
        for o in self.store.collection_list(self.cid):
            try:
                store += self.store.stat(self.cid, o)["size"]
            except StoreError:
                continue
            if o.name == "pgmeta":
                continue
            try:
                oi = self.store.getattr(self.cid, o, OI_ATTR)
            except StoreError:
                oi = {}
            if not oi.get("whiteout"):
                live.add(o.name)
            if o.shard == self.shard:
                sizes[o.name] = oi.get("size", 0)
        return (len(live), sum(sizes.get(nm, 0) for nm in live),
                store)

    # -- fault injection: objectstore_debug_inject_read_err applied to
    #    EC chunk reads.  The store's marks are per-ObjectId and chunk
    #    streams are shard-qualified, so this is the hook that lets
    #    harnesses (thrasher EIO injection) target "this OSD's chunk
    #    of oid" without knowing the ghobject layout; the EIO then
    #    surfaces through handle_sub_read -> the primary's
    #    remaining-shard retry/decode, and through scrub_map ->
    #    shard rebuild.
    def inject_read_err(self, oid: str) -> None:
        self.store.inject_read_err(self.cid,
                                   ObjectId(oid, shard=self.shard))

    def clear_read_err(self, oid: str) -> None:
        self.store.clear_read_err(self.cid,
                                  ObjectId(oid, shard=self.shard))

    def exists(self, oid: str) -> bool:
        soid = ObjectId(oid, shard=self.shard)
        return self.store.exists(self.cid, soid) and \
            not self._is_whiteout(soid)

    def scrub_map(self, deep: bool = True) -> dict:
        """Per-object shard integrity for scrub: the stored chunk
        stream re-hashed against the HashInfo cumulative crc
        (ref: ECBackend.cc be_deep_scrub :2424).  Whiteout tombstones
        are reported (with their delete version) so a shard that missed
        a delete is flagged rather than 'repaired' by resurrection."""
        from ..common.crc32c import crc32c
        out: dict[str, dict] = {}
        for oid, shards in self.shard_inventory().items():
            entry_iv = shards.get(self.shard)
            if entry_iv is None:
                continue
            ver, whiteout = tuple(entry_iv[0]), bool(entry_iv[1])
            if whiteout:
                out[oid] = {"size": 0, "crc": None, "ok": True,
                            "version": ver, "whiteout": True}
                continue
            soid = ObjectId(oid, shard=self.shard)
            try:
                buf = self.store.read(self.cid, soid, 0, 0)
            except StoreError:
                out[oid] = {"size": -1, "crc": None, "ok": False,
                            "version": ver, "whiteout": False}
                continue
            entry = {"size": len(buf), "crc": None, "ok": True,
                     "version": ver, "whiteout": False}
            if deep:
                crc = int(crc32c(0xFFFFFFFF, buf))
                entry["crc"] = crc
                hd = self._hinfo(soid)
                if hd is not None and hd.has_chunk_hash():
                    # a truncated/extended stream is itself an
                    # inconsistency, not a reason to skip the check
                    entry["ok"] = (
                        hd.get_total_chunk_size() == len(buf) and
                        crc == hd.get_chunk_hash(self.shard))
                entry["attrs_crc"] = mut.meta_digest(mut.user_xattrs(
                    self.store.getattrs(self.cid, soid)))
            out[oid] = entry
        return out


# ------------------------------------------------------------------ primary


@dataclass
class _Write:
    """One RMW pipeline op (ref: ECBackend.h Op).

    The client's mutation vector is classified when the op leaves
    waiting_state (all earlier same-object ops committed, so sizes are
    stable): `effect` holds the single data effect as
    ("write", off, data) / ("truncate", size) / ("full", data) / None
    (metadata-only); meta mutations ride along into every shard txn."""
    tid: int
    oid: str
    mutations: list
    delete: bool
    version: EVersion
    on_all_commit: Callable
    # pipeline state
    effect: Optional[tuple] = None
    meta: list = field(default_factory=list)
    reads_needed: Optional[tuple[int, int]] = None   # logical (off,len)
    reads_ready: bool = False    # RMW reads landed (or none needed)
    read_error: bool = False
    old_segment: bytes = b""
    pending_shards: set = field(default_factory=set)
    failed_shards: set = field(default_factory=set)
    log_entry: Optional[PGLogEntry] = None
    phase: str = "state"      # state -> reads -> commit -> done
    trace: Optional[dict] = None      # blkin context for fan-out spans
    # ICI-fabric staging (set when the write rode the device mesh)
    fabric_key: Optional[tuple] = None
    chunk_off: int = 0
    hinfo_append: bool = False


@dataclass
class _Read:
    tid: int
    reads: dict                     # oid -> (off, len)
    on_complete: Callable
    for_recovery: bool = False
    want_attrs: bool = False
    pending_shards: set = field(default_factory=set)
    shard_bufs: dict = field(default_factory=dict)   # oid -> {shard: buf}
    shard_attrs: dict = field(default_factory=dict)  # oid -> {shard: attrs}
    shard_errs: dict = field(default_factory=dict)   # oid -> {shard: err}
    retried: bool = False
    #: oid -> (chunk_off, chunk_len, logical_base); (0,0,0)=full stream
    chunk_windows: dict = field(default_factory=dict)
    trace: Optional[dict] = None      # blkin context for decode spans


class ECBackend:
    """Primary-side engine for one EC PG.

    `send(shard_index, msg)` delivers a message to the acting OSD
    holding that shard (the harness/daemon wires this to the
    messenger); the local shard is invoked inline like the reference's
    self-dispatch (ref: ECBackend.cc:2060,2073).
    """

    def __init__(self, pgid, ec, whoami: int,
                 acting: list[int],
                 local_shard: ECPGShard,
                 send: Callable[[int, object], bool],
                 epoch: int = 1, tid_gen=None, fabric=None,
                 send_osd: Callable[[int, object], bool] | None = None):
        self.pgid = pgid
        self.ec = ec
        #: ICIFabric when the acting set can be device-mesh co-resident
        #: (ceph_tpu.dist.fabric); None or non-covering acting sets use
        #: the host encode + messenger chunk fan-out
        self.fabric = fabric
        self.k = ec.get_data_chunk_count()
        self.m = ec.get_coding_chunk_count()
        cs = ec.get_chunk_size(self.k * 4096)
        self.sinfo = StripeInfo(self.k, self.k * cs)
        self.whoami = whoami
        self.acting = list(acting)
        self.local_shard = local_shard
        self.send = send
        #: OSD-id addressed send for pushes outside the acting set
        #: (EC backfill targets); shard-index send covers everything
        #: else
        self.send_osd = send_osd or (lambda _osd, _msg: False)
        self.epoch = epoch
        self.last_version = ZERO_VERSION
        self.committed_to = ZERO_VERSION
        # missing per shard index (peering fills this; harness may too)
        self.peer_missing: dict[int, PGMissing] = {
            s: PGMissing() for s in range(len(acting))}
        self._tid = 0
        # optional shared generator: a daemon rebuilding backends after
        # a map change must not restart tids or a stale sub-reply could
        # alias a new op
        self._tid_gen = tid_gen
        from ..common.lockdep import make_lock
        # name carries the daemon identity: several OSDs share one
        # process in tests, and lockdep must see osd.0's and osd.1's
        # backends for one PG as DIFFERENT locks
        self._lock = make_lock(f"osd.{whoami}.ecbackend.{pgid}")
        # the three-queue pipeline (ref: ECBackend.h waiting_state/
        # waiting_reads/waiting_commit)
        self.waiting_state: list[_Write] = []
        self.waiting_reads: list[_Write] = []
        self.waiting_commit: list[_Write] = []
        self._checking = False      # _check_ops re-entrancy guard
        self._recheck = False
        self.tid_to_op: dict[int, _Write] = {}
        self.in_flight_reads: dict[int, _Read] = {}
        #: span sink for the Pallas encode/decode kernel regions —
        #: the owning daemon points this at its Tracer; None (library
        #: use, tracing off) costs nothing on the hot path
        self.tracer = None
        #: PerfCounters sink (the owning daemon's) for the recovery
        #: bandwidth pair: recovery_bytes_read (helper bytes pulled
        #: over the wire) / recovery_bytes_rebuilt (chunk bytes pushed
        #: to targets) — how the sub-chunk repair saving is proven
        self.perf = None
        #: in-flight sub-chunk repair state: tid -> dict
        self._sub_repairs: dict[int, dict] = {}

    def _perf_inc(self, key: str, n: int = 1) -> None:
        if self.perf is not None and n:
            self.perf.inc(key, n)

    # -- utilities ------------------------------------------------------
    def _next_tid(self) -> int:
        if self._tid_gen is not None:
            return next(self._tid_gen)
        self._tid += 1
        return self._tid

    def fail_in_flight(self) -> None:
        """Abort every queued/pending op with failure callbacks — used
        when the daemon tears a backend down on an acting-set change so
        no client op is silently dropped (the reference requeues
        through peering; see PG::on_change)."""
        with self._lock:
            writes = list(self.tid_to_op.values())
            reads = list(self.in_flight_reads.values())
            subs = list(self._sub_repairs.values())
            self.tid_to_op.clear()
            self.in_flight_reads.clear()
            self._sub_repairs.clear()
            self.waiting_state.clear()
            self.waiting_reads.clear()
            self.waiting_commit.clear()
        for op in writes:
            if op.fabric_key is not None and self.fabric is not None:
                self.fabric.release(op.fabric_key)
            op.on_all_commit(False)
        for rd in reads:
            rd.on_complete({}, {oid: "ESTALE" for oid in rd.reads})
        for job in subs:
            # sub-chunk repair jobs carry their completion separately
            # (their _Read's on_complete is a placeholder) — fail them
            # explicitly so recovery accounting never hangs
            job["on_done"](False)

    def _next_version(self) -> EVersion:
        self.last_version = EVersion(self.epoch,
                                     self.last_version.version + 1)
        return self.last_version

    def _alive_shards(self) -> list[int]:
        return [s for s in range(len(self.acting))
                if self.acting[s] >= 0]

    def _avail_shards(self, oid: str) -> list[int]:
        """Shards that exist and are not missing the object
        (ref: ECBackend.cc:1526 get_all_avail_shards)."""
        out = []
        for s in self._alive_shards():
            missing = self.peer_missing.get(s)
            if missing is not None and missing.is_missing(oid):
                continue
            out.append(s)
        return out

    def object_size(self, oid: str) -> int:
        return self.local_shard.object_size(oid)

    # ==================================================================
    # write path (ref: ECBackend.cc:1479 submit_transaction,
    #             :1832 start_rmw, :2138 check_ops)
    # ==================================================================
    def submit_transaction(self, oid: str, muts: list,
                           on_all_commit: Callable,
                           snapc: dict | None = None,
                           trace: dict | None = None) -> int:
        # snapc ignored: EC pools don't support snapshots here
        with self._lock:
            tid = self._next_tid()
            # a write against an object the primary shard is missing
            # would RMW against a phantom size-0 object and fan out
            # corrupted stripes; the reference blocks such ops until
            # recovery (PrimaryLogPG wait_for_unreadable_object) — here
            # the op is rejected and the caller must recover first
            pm = self.peer_missing.get(self.local_shard.shard)
            if pm is not None and pm.is_missing(oid):
                on_all_commit(False)
                return tid
            delete = mut.is_delete(muts)
            op = _Write(tid=tid, oid=oid, mutations=list(muts),
                        delete=delete, version=self._next_version(),
                        on_all_commit=on_all_commit)
            op.trace = trace
            op.log_entry = PGLogEntry(
                DELETE if delete else MODIFY, oid, op.version,
                prior_version=self._object_prior_version(oid))
            self.tid_to_op[tid] = op
            self.waiting_state.append(op)
            self._check_ops()
            return tid

    def _object_prior_version(self, oid: str) -> EVersion:
        e = self.local_shard.pg_log.log.objects.get(oid)
        return e.version if e is not None else ZERO_VERSION

    def _check_ops(self) -> None:
        """Drain the pipeline in order (ref: ECBackend.cc:2138
        check_ops: state->reads may pipeline, reads->commit is strictly
        FIFO so sub-writes hit every shard in version order).

        Re-entrancy-safe: inline replies during a fan-out loop recurse
        into this method; the nested call must NOT advance the pipeline
        (it would interleave a later op's sub-writes ahead of the
        current op's remaining sends) — it just flags the outer frame
        to loop again."""
        if self._checking:
            self._recheck = True
            return
        self._checking = True
        try:
            while True:
                self._recheck = False
                progress = self._try_state_to_reads()
                progress = self._try_reads_to_commit() or progress
                if not progress and not self._recheck:
                    break
        finally:
            self._checking = False
        self._try_finish_commits()

    def _try_state_to_reads(self) -> bool:
        """(ref: ECBackend.cc:1858 try_state_to_reads)"""
        if not self.waiting_state:
            return False
        op = self.waiting_state[0]
        # per-object ordering: an earlier in-flight op on the same
        # object must commit first so the RMW read sees its data (the
        # reference serializes via the ExtentCache)
        for other in self.waiting_reads + self.waiting_commit:
            if other.oid == op.oid:
                return False
        self.waiting_state.pop(0)
        op.phase = "reads"
        self.waiting_reads.append(op)
        if op.delete:
            op.reads_ready = True
            return True
        self._classify(op)
        plan = self._write_plan(op)
        if plan is None:
            op.reads_ready = True         # aligned append: no reads
            return True
        op.reads_needed = plan
        off, length = plan
        self.objects_read_and_reconstruct(
            {op.oid: (off, length)},
            lambda results, errors, op=op: self._rmw_reads_done(
                op, results, errors))
        return True

    def _classify(self, op: _Write) -> None:
        """Resolve the mutation vector against the now-stable object
        size into one data effect + the metadata tail
        (ref: ECTransaction::get_write_plan derives the same per-op
        extent plan)."""
        op.meta = mut.meta_mutations(op.mutations)
        op.effect = None
        size = self.object_size(op.oid)
        for m in mut.data_mutations(op.mutations):
            kind = m[0]
            if kind == mut.M_WRITE:
                op.effect = ("write", m[1], m[2])
            elif kind == mut.M_APPEND:
                op.effect = ("write", size, m[1])
            elif kind == mut.M_WRITEFULL:
                op.effect = ("full", m[1])
            elif kind == mut.M_ZERO:
                off, length = m[1], m[2]
                end = min(off + length, size)
                if end > off:       # zero never extends (librados)
                    op.effect = ("write", off, b"\0" * (end - off))
            elif kind == mut.M_TRUNCATE:
                t = m[1]
                if t == size:
                    op.effect = None
                elif t > size:
                    # extending truncate materializes the zero tail so
                    # reconstructing reads see real chunks
                    op.effect = ("write", size, b"\0" * (t - size))
                else:
                    op.effect = ("truncate", t)

    def _try_reads_to_commit(self) -> bool:
        """Commit ONLY the front of waiting_reads once its reads are in
        (ref: ECBackend.cc:1932 try_reads_to_commit operates on
        waiting_reads.front()) — later ops never overtake, so shards
        receive sub-writes in version order."""
        progressed = False
        while self.waiting_reads and \
                getattr(self.waiting_reads[0], "reads_ready", False):
            op = self.waiting_reads.pop(0)
            if getattr(op, "read_error", False):
                self._finish(op, ok=False)
            else:
                self._start_commit(op)
            progressed = True
        return progressed

    def _write_plan(self, op: _Write) -> Optional[tuple[int, int]]:
        """Which logical range must be read before this op can be
        encoded (ref: ECTransaction.h get_write_plan: the stripes the
        write only partially overwrites).  None = no RMW read."""
        if op.effect is None or op.effect[0] == "full":
            return None                  # metadata-only / full replace
        old_size = self.object_size(op.oid)
        if old_size == 0:
            return None
        if op.effect[0] == "truncate":
            # keep the partial tail stripe's surviving bytes
            t = op.effect[1]
            start = self.sinfo.logical_to_prev_stripe_offset(t)
            return None if t == start else (start, t - start)
        _, offset, data = op.effect
        start, length = self.sinfo.offset_len_to_stripe_bounds(
            (offset, max(len(data), 1)))
        old_aligned = self.sinfo.logical_to_next_stripe_offset(old_size)
        read_start = start
        read_end = min(start + length, old_aligned)
        if read_start >= read_end:
            return None                  # pure append past old data
        # full-stripe overwrite of existing stripes still merges with
        # nothing — skip the read when the write covers those stripes
        # entirely
        w_start, w_end = offset, offset + len(data)
        if w_start <= read_start and w_end >= read_end:
            return None
        return (read_start, read_end - read_start)

    def _rmw_reads_done(self, op: _Write, results: dict,
                        errors: dict) -> None:
        with self._lock:
            if errors.get(op.oid):
                op.read_error = True
            else:
                op.old_segment = results.get(op.oid, b"")
            op.reads_ready = True
            self._check_ops()

    def _start_commit(self, op: _Write) -> None:
        """Encode + fan out per-shard transactions."""
        op.phase = "commit"
        self.waiting_commit.append(op)
        if op.delete:
            # versioned whiteout tombstone per shard (like the
            # replicated path): a stale shard returning after the
            # delete loses to the tombstone in recovery instead of
            # resurrecting the object
            cid = pg_cid(self.pgid)
            ver = (op.version.epoch, op.version.version)
            shard_txns = {
                s: ec_tombstone_txn(cid, op.oid, s, ver,
                                    self.k + self.m)
                for s in self._alive_shards()}
            new_size = 0
            shards = {}
        elif op.effect is None:
            shard_txns = self._meta_txns(op)
        else:
            shards, shard_txns, new_size = self._encode_write(op)
        op.pending_shards = set(shard_txns)
        for s, txn in shard_txns.items():
            msg = ECSubWrite(pgid=self.pgid, tid=op.tid, shard=s,
                             txn=txn, log_entries=[op.log_entry],
                             trace=child_of(op.trace),
                             oid=op.oid, fabric_key=op.fabric_key,
                             chunk_off=op.chunk_off,
                             hinfo_append=op.hinfo_append)
            if self.acting[s] == self.whoami:
                reply = self.local_shard.handle_sub_write(msg)
                self._on_write_reply(op, reply)
            else:
                if not self.send(s, msg):
                    op.failed_shards.add(s)
                    op.pending_shards.discard(s)
        self._maybe_commit_done(op)

    def _apply_meta(self, txn: Transaction, cid: str, soid,
                    metas: list) -> None:
        """Apply the metadata tail of a mutation vector to one shard's
        txn.  User xattrs live on EVERY shard (the reference stores
        attrs with each shard object — ECTransaction::generate_
        transactions setattrs fan out identically)."""
        for m in metas:
            if m[0] == mut.M_SETXATTRS:
                txn.setattrs(cid, soid, {mut.uxattr_key(k): bytes(v)
                                         for k, v in m[1].items()})
            elif m[0] == mut.M_RMXATTR:
                txn.rmattr(cid, soid, mut.uxattr_key(m[1]))
            # M_CREATE: the leading touch creates the shard object

    def _meta_txns(self, op: _Write) -> dict[int, Transaction]:
        """Metadata-only transaction: no encode, per-shard attr
        updates + version bump."""
        cid = pg_cid(self.pgid)
        size = self.object_size(op.oid)
        existed = self.local_shard.exists(op.oid)
        txns = {}
        for s in self._alive_shards():
            soid = ObjectId(op.oid, shard=s)
            txn = Transaction().touch(cid, soid)
            self._apply_meta(txn, cid, soid, op.meta)
            attrs = {OI_ATTR: {"size": size,
                               "version": (op.version.epoch,
                                           op.version.version)}}
            if not existed:
                attrs[HINFO_ATTR] = HashInfo(self.k + self.m).to_dict()
            txn.setattrs(cid, soid, attrs)
            txns[s] = txn
        return txns

    def _encode_write(self, op: _Write):
        """Merge old+new logical bytes, batch-encode, build shard txns."""
        sinfo = self.sinfo
        old_size = self.object_size(op.oid)
        kind = op.effect[0]
        if kind == "full":
            data = op.effect[1]
            offset, start = 0, 0
            length = sinfo.logical_to_next_stripe_offset(len(data))
            new_size = len(data)
        elif kind == "truncate":
            t = op.effect[1]
            start = sinfo.logical_to_prev_stripe_offset(t)
            offset, data = start, b""
            length = sinfo.logical_to_next_stripe_offset(t) - start
            new_size = t
        else:
            _, offset, data = op.effect
            start, length = sinfo.offset_len_to_stripe_bounds(
                (offset, max(len(data), 1)))
            new_size = max(old_size, offset + len(data))
        seg = bytearray(length)
        if op.old_segment:
            seg[:len(op.old_segment)] = op.old_segment
        if kind == "truncate":
            # drop everything past the new end within the tail stripe
            seg = seg[:op.effect[1] - start]
            seg += b"\0" * (-len(seg) % sinfo.stripe_width)
        rel = offset - start
        seg[rel:rel + len(data)] = data
        chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(start)
        cid = pg_cid(self.pgid)

        # ICI-fabric path: encode + chunk fan-out as one mesh collective
        # step; messages become control-plane only (ref: the per-shard
        # fan-out this replaces, ECBackend.cc:2037-2070)
        if (self.fabric is not None and seg
                and kind in ("write", "full")
                and self.fabric.covers(
                    [self.acting[s] for s in self._alive_shards()])
                and self.fabric.supports(self.ec)):
            return self._encode_write_fabric(op, kind, bytes(seg),
                                             start, chunk_off,
                                             old_size, new_size)
        # kernel span, only when this op is traced: ecutil.encode
        # returns host bytes, so the device dispatch is fully forced
        # (block_until_ready-equivalent) by the time the span closes —
        # the staged-encode cost shows up as its own span instead of
        # hiding inside the osd_op (ref: the ECBackend.cc:1508 trace
        # events around the encode)
        ksp = None if self.tracer is None else \
            self.tracer.start_span(child_of(op.trace),
                                   "ec_encode_kernel")
        shards = ecutil.encode(sinfo, self.ec, bytes(seg))
        if ksp is not None:
            ksp.event(f"bytes={len(seg)} k={self.k} m={self.m}")
            self.tracer.finish(ksp)

        # cumulative hinfo only survives pure stripe-aligned appends:
        # start is stripe-aligned, so start == old_size iff the old
        # object ended exactly on a stripe boundary and this write
        # begins there (ref: the reference maintains HashInfo for
        # appends; ec overwrites invalidate it)
        # a full replace re-encodes the whole stream, so its hinfo is
        # rebuilt fresh (cumulative from chunk 0) rather than invalidated
        is_append = (start == old_size and kind == "write") \
            or kind == "full"
        old_hinfo = None if kind == "full" else self.local_shard._hinfo(
            ObjectId(op.oid, shard=self.local_shard.shard))
        # one hinfo for all shards (it carries every shard's hash);
        # computed once — _next_hinfo advances the cumulative state
        if kind == "truncate":
            hi = HashInfo(0)
            hi.total_chunk_size = chunk_off + (
                len(next(iter(shards.values()))) if shards else 0)
            hi_dict = hi.to_dict()
        else:
            hi_dict = self._next_hinfo(
                old_hinfo, chunk_off, shards, is_append).to_dict()
        txns = {}
        for s in self._alive_shards():
            soid = ObjectId(op.oid, shard=s)
            txn = Transaction()
            txn.touch(cid, soid)
            if kind in ("full", "truncate"):
                # discard shard bytes past the new chunk extent
                txn.truncate(cid, soid, chunk_off)
            if shards.get(s, b"") or kind == "write":
                txn.write(cid, soid, chunk_off, shards.get(s, b""))
            txn.setattrs(cid, soid, {
                OI_ATTR: {"size": new_size,
                          "version": (op.version.epoch,
                                      op.version.version)},
                HINFO_ATTR: hi_dict,
            })
            self._apply_meta(txn, cid, soid, op.meta)
            txns[s] = txn
        return shards, txns, new_size

    def _encode_write_fabric(self, op: _Write, kind: str, seg: bytes,
                             start: int, chunk_off: int,
                             old_size: int, new_size: int):
        """Stage the encode on the device mesh; per-shard txns carry
        only control metadata (touch/truncate/oi/meta) — each shard
        gathers its chunk slice from the mesh and maintains its own
        HashInfo locally (ECPGShard._apply_fabric_write)."""
        key = (self.pgid, op.tid)
        self.fabric.stage_encode(key, self.ec, seg,
                                 self.sinfo.chunk_size)
        op.fabric_key = key
        op.chunk_off = chunk_off
        op.hinfo_append = (start == old_size and kind == "write") \
            or kind == "full"
        cid = pg_cid(self.pgid)
        txns = {}
        for s in self._alive_shards():
            soid = ObjectId(op.oid, shard=s)
            txn = Transaction()
            txn.touch(cid, soid)
            if kind == "full":
                txn.truncate(cid, soid, chunk_off)
            txn.setattrs(cid, soid, {
                OI_ATTR: {"size": new_size,
                          "version": (op.version.epoch,
                                      op.version.version)}})
            self._apply_meta(txn, cid, soid, op.meta)
            txns[s] = txn
        return {}, txns, new_size

    def _next_hinfo(self, old: Optional[HashInfo], chunk_off: int,
                    shards: dict, is_append: bool) -> HashInfo:
        if is_append:
            hi = old if old is not None else HashInfo(self.k + self.m)
            if not shards:                 # empty write (object create)
                return hi
            if hi.has_chunk_hash() \
                    and hi.get_total_chunk_size() == chunk_off:
                hi.append(chunk_off, shards)
                return hi
        # overwrite (or inconsistent history): size still tracked,
        # cumulative chunk hashes invalidated
        hi = HashInfo(0)
        sz = chunk_off + (len(next(iter(shards.values()))) if shards else 0)
        if old is not None:
            sz = max(sz, old.get_total_chunk_size())
        hi.total_chunk_size = sz
        return hi

    def handle_sub_write_reply(self, m: ECSubWriteReply) -> None:
        """(ref: ECBackend.cc:1122)"""
        with self._lock:
            op = self.tid_to_op.get(m.tid)
            if op is None:
                return
            self._on_write_reply(op, m)
            self._maybe_commit_done(op)
            self._check_ops()

    def _on_write_reply(self, op: _Write, m: ECSubWriteReply) -> None:
        op.pending_shards.discard(m.shard)
        if not m.committed:
            op.failed_shards.add(m.shard)

    def _maybe_commit_done(self, op: _Write) -> None:
        if op.phase == "commit" and not op.pending_shards:
            self._finish(op, ok=not op.failed_shards)

    def _finish(self, op: _Write, ok: bool) -> None:
        if op in self.waiting_commit:
            self.waiting_commit.remove(op)
        if op.fabric_key is not None and self.fabric is not None:
            self.fabric.release(op.fabric_key)
        op.phase = "done"
        op.ok = ok
        self._try_finish_commits()

    def _try_finish_commits(self) -> None:
        """Complete client callbacks strictly in tid order
        (ref: the reference completes via in-order check_ops)."""
        while self.tid_to_op:
            first_tid = min(self.tid_to_op)
            op = self.tid_to_op[first_tid]
            if op.phase != "done":
                break
            del self.tid_to_op[first_tid]
            if getattr(op, "ok", False):
                self.committed_to = max(self.committed_to, op.version)
            op.on_all_commit(getattr(op, "ok", False))

    # ==================================================================
    # read path (ref: ECBackend.h:139 objects_read_and_reconstruct,
    #            ECBackend.cc:1590 get_min_avail_to_read_shards)
    # ==================================================================
    def objects_read_and_reconstruct(
            self, reads: dict, on_complete: Callable,
            for_recovery: bool = False,
            want_attrs: bool = False,
            trace: dict | None = None) -> None:
        with self._lock:
            tid = self._next_tid()
            rd = _Read(tid=tid, reads=dict(reads),
                       on_complete=on_complete,
                       for_recovery=for_recovery,
                       want_attrs=want_attrs, trace=trace)
            # translate each logical window into a per-shard chunk
            # window so a small read never pulls whole shard streams
            # (ref: ECBackend.cc:1590 builds per-shard offset/len
            # lists the same way); (0, 0) = full stream (crc gate)
            rd.chunk_windows = {}
            for oid, window in rd.reads.items():
                if window is None or window[1] == 0:
                    rd.chunk_windows[oid] = (0, 0, 0)
                else:
                    s_off, s_len = self.sinfo.offset_len_to_stripe_bounds(
                        window)
                    rd.chunk_windows[oid] = (
                        self.sinfo.aligned_logical_offset_to_chunk_offset(
                            s_off),
                        self.sinfo.aligned_logical_offset_to_chunk_offset(
                            s_len),
                        s_off)
            # choose shards: minimum_to_decode over available shards
            want_chunks = set(range(self.k + self.m)) if for_recovery \
                else {self.ec.chunk_index(i) for i in range(self.k)}
            per_shard: dict[int, list] = {}
            errors: dict[str, str] = {}
            for oid in rd.reads:
                avail = set(self._avail_shards(oid))
                try:
                    need = self.ec.minimum_to_decode(
                        want_chunks & set(range(self.k + self.m)),
                        avail)
                except Exception:
                    errors[oid] = "EIO"
                    continue
                for s in need:
                    per_shard.setdefault(s, []).append(oid)
            if errors and len(errors) == len(rd.reads):
                on_complete({}, errors)
                return
            self.in_flight_reads[tid] = rd
            rd.pending_shards = set(per_shard)
            for s, oids in per_shard.items():
                self._dispatch_read(rd, s, self._sub_read_msg(rd, s, oids))
            self._maybe_read_done(rd)

    def _sub_read_msg(self, rd: _Read, s: int, oids) -> ECSubRead:
        return ECSubRead(
            pgid=self.pgid, tid=rd.tid, shard=s,
            to_read=[(oid,) + rd.chunk_windows[oid][:2] for oid in oids],
            attrs_to_read=list(oids) if rd.want_attrs else [],
            trace=child_of(rd.trace))

    def _dispatch_read(self, rd: _Read, s: int, msg: ECSubRead) -> None:
        if self.acting[s] == self.whoami:
            reply = self.local_shard.handle_sub_read(msg)
            self._on_read_reply(rd, reply)
        else:
            if not self.send(s, msg):
                rd.pending_shards.discard(s)
                for oid, _, _ in msg.to_read:
                    rd.shard_errs.setdefault(oid, {})[s] = "ECONNREFUSED"

    def handle_sub_read_reply(self, m: ECSubReadReply) -> None:
        """(ref: ECBackend.cc:1155)"""
        with self._lock:
            rd = self.in_flight_reads.get(m.tid)
            if rd is None:
                return
            self._on_read_reply(rd, m)
            self._maybe_read_done(rd)

    def _on_read_reply(self, rd: _Read, m: ECSubReadReply) -> None:
        rd.pending_shards.discard(m.shard)
        for oid, buf in m.buffers_read.items():
            rd.shard_bufs.setdefault(oid, {})[m.shard] = buf
        for oid, attrs in m.attrs_read.items():
            rd.shard_attrs.setdefault(oid, {})[m.shard] = attrs
        for oid, err in m.errors.items():
            rd.shard_errs.setdefault(oid, {})[m.shard] = err

    def _maybe_read_done(self, rd: _Read) -> None:
        # in_flight membership doubles as the completion guard: inline
        # (same-thread) replies can finish the read while the dispatch
        # loop is still running, and the loop's final check must not
        # complete it a second time
        if rd.pending_shards or rd.tid not in self.in_flight_reads:
            return
        sub_job = self._sub_repairs.pop(rd.tid, None)
        if sub_job is not None:
            # sub-chunk repair reads don't retry shard-by-shard: any
            # miss falls back to the full-chunk rebuild wholesale
            self.in_flight_reads.pop(rd.tid, None)
            self._complete_subchunk_repair(rd, sub_job)
            return
        # errors? try remaining shards once
        # (ref: ECBackend.cc:1628 get_remaining_shards retry)
        needs_retry = []
        for oid in rd.reads:
            errs = rd.shard_errs.get(oid, {})
            if not errs:
                continue
            got = set(rd.shard_bufs.get(oid, {}))
            remaining = [s for s in self._avail_shards(oid)
                         if s not in got and s not in errs]
            if len(got) < self.k and remaining and not rd.retried:
                needs_retry.extend(
                    (oid, s) for s in
                    remaining[:self.k - len(got)])
        if needs_retry:
            rd.retried = True
            per_shard: dict[int, list] = {}
            for oid, s in needs_retry:
                per_shard.setdefault(s, []).append(oid)
            rd.pending_shards |= set(per_shard)
            for s, oids in per_shard.items():
                self._dispatch_read(rd, s, self._sub_read_msg(rd, s, oids))
            # an inline retry reply may have recursed and completed the
            # read already — re-check both guards before falling through
            if rd.pending_shards or rd.tid not in self.in_flight_reads:
                return
        self.in_flight_reads.pop(rd.tid, None)
        self._complete_read(rd)

    def _complete_read(self, rd: _Read) -> None:
        results: dict[str, bytes] = {}
        errors: dict[str, str] = {}
        if rd.for_recovery:
            # recovery-bandwidth accounting: every helper byte this
            # rebuild pulled over the wire (the number sub-chunk
            # repair shrinks)
            self._perf_inc("recovery_bytes_read", sum(
                len(b) for per in rd.shard_bufs.values()
                for b in per.values()))
        for oid, window in rd.reads.items():
            bufs = {s: b for s, b in rd.shard_bufs.get(oid, {}).items()}
            if len(bufs) < self.k:
                errors[oid] = "EIO"
                continue
            base = rd.chunk_windows[oid][2]   # logical offset of bufs[0]
            # kernel span when the read is traced: decode_concat's
            # output is host bytes, so survivor staging (the host-side
            # gather/stack that dominates decode_incl_stage in
            # BENCH_r05) AND the device decode are both inside the
            # span when it closes — and the two regions land as
            # `stage` / `kernel` CHILD spans so the split is visible
            # per op in SLO reports
            ksp = None if self.tracer is None or rd.trace is None \
                else self.tracer.start_span(child_of(rd.trace),
                                            "ec_decode_kernel")
            timings: dict | None = {} if ksp is not None else None
            logical = ecutil.decode_concat(self.sinfo, self.ec, bufs,
                                           timings=timings)
            if ksp is not None:
                ksp.event(f"shards={len(bufs)} "
                          f"bytes={len(logical)}")
                self.tracer.finish(ksp)
                kctx = {"trace_id": ksp.trace_id, "span": ksp.span_id,
                        "parent": ksp.parent}
                for stage_name in ("stage", "kernel"):
                    iv = (timings or {}).get(stage_name)
                    if iv is not None:
                        self.tracer.record_span(
                            child_of(kctx), stage_name, iv[0], iv[1])
            size = self._oi_size(rd, oid)
            # highest valid logical byte we can serve from this read
            limit = base + len(logical) if size is None \
                else min(size, base + len(logical))
            if window is None:
                off, length = base, max(limit - base, 0)
            else:
                off, length = window
                if length == 0:
                    length = max(limit - off, 0)
            end = min(off + length, limit)
            results[oid] = logical[max(off - base, 0):max(end - base, 0)]
        if rd.want_attrs:
            rd.on_complete(results, errors, rd.shard_attrs)
        else:
            rd.on_complete(results, errors)

    def _oi_size(self, rd: _Read, oid: str) -> Optional[int]:
        attrs = rd.shard_attrs.get(oid, {})
        for a in attrs.values():
            oi = a.get(OI_ATTR)
            if oi:
                return oi["size"]
        # distinguish "size 0" from "unknown": only a missing oi attr
        # means unknown (a falsy-0 fallback would pad empty objects
        # with a stripe of zeros)
        try:
            return self.local_shard.store.getattr(
                pg_cid(self.pgid),
                ObjectId(oid, shard=self.local_shard.shard),
                OI_ATTR)["size"]
        except StoreError:
            return None

    # ==================================================================
    # recovery (ref: ECBackend.cc:735 recover_object,
    #           :567 continue_recovery_op)
    # ==================================================================
    def recover_object(self, oid: str, target_shards,
                       on_done: Callable, version=None,
                       target_osds: dict | None = None) -> None:
        """Reconstruct `oid`'s chunks on target shards and push them.

        `version`: the authoritative object version to stamp on the
        rebuilt shards.  Callers whose pg_log was rebuilt (daemon
        peering/scrub) MUST pass it — the local prior-version fallback
        is only correct while the primary's log is intact.

        `target_osds`: optional {shard_index: osd} override for
        pushes outside the acting set — the EC backfill case, where a
        temp primary rebuilds chunks for the UP set's shards while
        the old acting set still serves (ref: ECBackend recovery
        pushing to backfill targets).

        Plan-driven recovery: when the plugin publishes a repair
        schedule for the erasure signature (ec.repair_schedule —
        clay's d-helper sub-chunk planes, lrc's l-survivor local
        parity group, matrix codes' k-survivor direct decode), the
        helpers serve only the plan's extents and the lost chunks
        rebuild through the signature's COMPILED repair program
        (ceph_tpu.ec.repairc: one gather/GF-matmul/scatter dispatch,
        cached per signature) — no logical decode + re-encode.  Codes
        without a plan, or any repair-read failure, fall back to the
        wholesale full-chunk rebuild below."""
        targets = sorted(set(target_shards))
        if self._try_subchunk_recover(oid, targets, on_done, version,
                                      target_osds):
            return
        self._recover_object_full(oid, targets, on_done, version,
                                  target_osds)

    def _recover_object_full(self, oid: str, targets, on_done,
                             version=None, target_osds=None) -> None:
        # read enough shards (+ attrs) to rebuild the logical object
        self.objects_read_and_reconstruct(
            {oid: None}, lambda r, e, a=None: self._recovery_reads_done(
                oid, targets, r, e, on_done, version, a, target_osds),
            for_recovery=True, want_attrs=True)

    # -- plan-driven (repair-bandwidth-optimal) rebuild ---------------
    def _try_subchunk_recover(self, oid: str, targets, on_done,
                              version=None, target_osds=None) -> bool:
        """Plan a compiled-program rebuild; False -> caller takes the
        full-chunk path (no plan for this erasure signature, or the
        helper set can't cover the plan's repair degree)."""
        avail = {s for s in self._avail_shards(oid)
                 if s not in set(targets)}
        plan = ecutil.repair_plan(self.ec, targets, avail)
        if plan is None or set(plan.lost) != set(targets):
            return False
        cs = self.sinfo.chunk_size
        try:
            byte_extents = plan.byte_extents(cs)
        except ValueError:
            return False
        with self._lock:
            tid = self._next_tid()
            rd = _Read(tid=tid, reads={oid: None},
                       on_complete=lambda *_: None,
                       for_recovery=True, want_attrs=True)
            self.in_flight_reads[tid] = rd
            self._sub_repairs[tid] = {
                "oid": oid, "plan": plan,
                "helpers": set(plan.helper_ids()),
                "on_done": on_done,
                "version": version, "target_osds": target_osds,
            }
            rd.pending_shards = set(plan.helper_ids())
            for s, extents in byte_extents.items():
                msg = ECSubRead(
                    pgid=self.pgid, tid=tid, shard=s,
                    to_read=[], attrs_to_read=[oid],
                    subchunks={oid: list(extents)}, chunk_size=cs,
                    trace=child_of(rd.trace))
                self._dispatch_read(rd, s, msg)
            self._maybe_read_done(rd)
        return True

    def _complete_subchunk_repair(self, rd: _Read, job: dict) -> None:
        oid, plan = job["oid"], job["plan"]
        on_done = job["on_done"]
        targets = list(plan.lost)
        bufs = rd.shard_bufs.get(oid, {})
        got = {s: bufs[s] for s in job["helpers"] if s in bufs}
        if set(got) != job["helpers"] or rd.shard_errs.get(oid):
            # any helper failure: fall back to the full-chunk rebuild
            # (it tolerates arbitrary shard sets via minimum_to_decode)
            self._recover_object_full(oid, targets, on_done,
                                      job["version"],
                                      job["target_osds"])
            return
        self._perf_inc("recovery_bytes_read",
                       sum(len(b) for b in got.values()))
        try:
            streams = ecutil.compiled_repair_streams(
                self.ec, plan, self.sinfo.chunk_size, got)
        except (ValueError, KeyError, AssertionError,
                ErasureCodeError) as ex:
            dout("osd", 0).write("%s compiled repair of %s failed: %r",
                                 self.pgid, oid, ex)
            self._recover_object_full(oid, targets, on_done,
                                      job["version"],
                                      job["target_osds"])
            return
        # authoritative metadata from the newest-oi helper: object
        # size/version, the shared HashInfo (it carries EVERY shard's
        # cumulative crc — including the rebuilt ones), user xattrs
        best = newest_oi_attrs(rd.shard_attrs.get(oid, {}))
        if best is None:
            self._recover_object_full(oid, targets, on_done,
                                      job["version"],
                                      job["target_osds"])
            return
        _, oi, hinfo_dict, user_attrs = best
        version = job["version"]
        if version is None:
            version = EVersion(*oi.get("version", (0, 0))) \
                if oi.get("version") else self._object_prior_version(oid)
        # one push per rebuilt shard; on_done fires once with the
        # aggregate outcome (the push_rebuilt contract)
        pending = set(targets)
        state = {"ok": True, "done": False}

        def agg(shard):
            def cb(committed):
                state["ok"] = state["ok"] and bool(committed)
                pending.discard(shard)
                if not pending and not state["done"]:
                    state["done"] = True
                    on_done(state["ok"])
            return cb

        for lost in targets:
            self._push_repaired_shard(
                oid, lost, streams[lost], oi.get("size", 0), version,
                hinfo_dict, user_attrs, agg(lost), job["target_osds"])

    def _push_repaired_shard(self, oid: str, shard: int, stream: bytes,
                             size: int, version, hinfo_dict,
                             user_attrs: dict, on_done,
                             target_osds=None) -> None:
        """Push ONE rebuilt chunk stream (the sub-chunk repair result)
        — the single-shard analogue of push_rebuilt, no re-encode."""
        with self._lock:
            cid = pg_cid(self.pgid)
            soid = ObjectId(oid, shard=shard)
            attrs = {OI_ATTR: {"size": size,
                               "version": (version.epoch,
                                           version.version)},
                     **{mut.uxattr_key(k): v
                        for k, v in user_attrs.items()}}
            if hinfo_dict is not None:
                attrs[HINFO_ATTR] = hinfo_dict
            txn = (Transaction()
                   .touch(cid, soid)
                   .truncate(cid, soid, 0)
                   .write(cid, soid, 0, stream)
                   .setattrs(cid, soid, attrs))
            tid = self._next_tid()
            msg = ECSubWrite(pgid=self.pgid, tid=tid, shard=shard,
                             txn=txn, log_entries=[], oid=oid,
                             guard_version=(version.epoch,
                                            version.version))
            self._perf_inc("recovery_bytes_rebuilt", len(stream))

            def reply_cb(s, committed, oid=oid):
                if committed:
                    pm = self.peer_missing.get(s)
                    if pm is not None:
                        pm.rm(oid)
                on_done(committed)

            dest = (dict(target_osds).get(shard)
                    if target_osds else
                    (self.acting[shard] if shard < len(self.acting)
                     else -1))
            if dest == self.whoami and shard == self.local_shard.shard:
                rep = self.local_shard.handle_sub_write(msg)
                reply_cb(shard, rep.committed)
                return
            self._recovery_cbs = getattr(self, "_recovery_cbs", {})
            self._recovery_cbs[tid] = (shard, reply_cb)
            send = (lambda m: self.send_osd(dest, m)) if target_osds \
                else (lambda m: self.send(shard, m))
            if dest is None or dest < 0 or not send(msg):
                self._recovery_cbs.pop(tid, None)
                reply_cb(shard, False)

    def _recovery_reads_done(self, oid: str, targets, results, errors,
                             on_done, version=None,
                             shard_attrs=None,
                             target_osds=None) -> None:
        if errors.get(oid) or oid not in results:
            on_done(False)
            return
        # authoritative user xattrs from the newest-oi surviving shard
        user_attrs: dict = {}
        best = newest_oi_attrs((shard_attrs or {}).get(oid, {}))
        if best is not None:
            user_attrs = best[3]
        self.push_rebuilt(oid, results[oid], targets, on_done,
                          version=version, user_attrs=user_attrs,
                          target_osds=target_osds)

    def push_rebuilt(self, oid: str, logical: bytes, targets,
                     on_done: Callable, version=None,
                     user_attrs: dict | None = None,
                     target_osds: dict | None = None) -> None:
        """Encode a rebuilt logical object and push its chunks to
        `targets` (shard indexes).  `target_osds` optionally overrides
        the destination OSD per shard — the EC peering statechart's
        backfill path rebuilds from cross-set sources and pushes to
        up-set shards outside the current acting set."""
        user_attrs = user_attrs or {}
        with self._lock:
            # re-encode the full object: every shard's chunk stream
            width = self.sinfo.stripe_width
            padded = logical + b"\0" * (-len(logical) % width)
            shards = ecutil.encode(self.sinfo, self.ec, padded)
            hinfo = HashInfo(self.k + self.m)
            if shards:
                hinfo.append(0, shards)
            size = len(logical)
            if version is None:
                version = self._object_prior_version(oid)
            cid = pg_cid(self.pgid)
            # all targets pending up front: an inline (synchronous)
            # reply mid-loop must not see an empty set and complete
            # the whole recovery early
            pending = set(targets)
            state = {"ok": True, "done": False}

            def reply_cb(s, committed):
                pending.discard(s)
                if committed:
                    # only the acked shard's missing entry clears
                    pm = self.peer_missing.get(s)
                    if pm is not None:
                        pm.rm(oid)
                else:
                    state["ok"] = False
                if not pending and not state["done"]:
                    state["done"] = True
                    on_done(state["ok"])

            self._recovery_cbs = getattr(self, "_recovery_cbs", {})
            osd_map = dict(target_osds) if target_osds else None
            if not targets:
                on_done(True)
                return
            self._perf_inc("recovery_bytes_rebuilt",
                           sum(len(shards.get(s, b"")) for s in targets))
            for s in targets:
                soid = ObjectId(oid, shard=s)
                txn = (Transaction()
                       .touch(cid, soid)
                       .truncate(cid, soid, 0)
                       .write(cid, soid, 0, shards.get(s, b""))
                       .setattrs(cid, soid, {
                           OI_ATTR: {"size": size,
                                     "version": (version.epoch,
                                                 version.version)},
                           HINFO_ATTR: hinfo.to_dict(),
                           **{mut.uxattr_key(k): v
                              for k, v in user_attrs.items()}}))
                tid = self._next_tid()
                msg = ECSubWrite(pgid=self.pgid, tid=tid, shard=s,
                                 txn=txn, log_entries=[], oid=oid,
                                 guard_version=(version.epoch,
                                                version.version))
                dest = osd_map.get(s) if osd_map else (
                    self.acting[s] if s < len(self.acting) else -1)
                if dest == self.whoami and \
                        s == self.local_shard.shard:
                    rep = self.local_shard.handle_sub_write(msg)
                    reply_cb(s, rep.committed)
                elif osd_map is not None:
                    self._recovery_cbs[tid] = (s, reply_cb)
                    if dest is None or dest < 0 or not self.send_osd(
                            dest, msg):
                        self._recovery_cbs.pop(tid, None)
                        reply_cb(s, False)
                else:
                    self._recovery_cbs[tid] = (s, reply_cb)
                    if not self.send(s, msg):
                        self._recovery_cbs.pop(tid, None)
                        reply_cb(s, False)

    def handle_recovery_write_reply(self, m: ECSubWriteReply) -> bool:
        """Route recovery push acks (returns True if consumed)."""
        with self._lock:
            cbs = getattr(self, "_recovery_cbs", {})
            entry = cbs.pop(m.tid, None)
            if entry is None:
                return False
            s, cb = entry
            cb(s, m.committed)
            return True
