"""Monitor daemon: the map service endpoint on the messenger.

Command, subscription, boot, and failure-report handling over the wire
(ref: src/mon/Monitor.cc dispatch_op; OSDMonitor.cc preprocess/
prepare split; failure handling OSDMonitor.cc:2519 prepare_failure,
down-out: OSDMonitor.cc tick :4965).  One instance is the map
authority; OSDs and clients subscribe and receive MMap incrementals on
every committed epoch — the propagation path the reference runs through
the mon session subs (src/mon/Monitor.cc handle_subscribe).
"""
from __future__ import annotations

import threading
import time

from ..common.log import dout
from ..common.options import global_config
from ..msg.messages import (MMap, MMonCommand, MMonCommandAck,
                            MMonSubscribe, MOSDBoot, MOSDFailure)
from ..msg.messenger import Dispatcher, LocalNetwork, Message, Messenger
from ..osd.osdmap import CEPH_OSD_AUTOOUT, CEPH_OSD_IN, OSDMap
from .osd_monitor import OSDMonitor
from .paxos import Paxos
from .store import MonitorStore


def build_initial(n_osd: int, osds_per_host: int = 1
                  ) -> tuple[OSDMap, "CrushWrapper"]:
    """Named crush tree (default/host*/osd.*) + replicated_rule + all
    OSDs up/in — the vstart-style bootstrap a fresh mon starts from
    (ref: OSDMap.cc build_simple with names via CrushWrapper)."""
    from ..crush.wrapper import CrushWrapper
    from ..osd.osdmap import CEPH_OSD_EXISTS, CEPH_OSD_UP
    w = CrushWrapper.build_flat(n_osd, osds_per_host=osds_per_host)
    w.add_simple_rule("replicated_rule", "default", "host")
    m = OSDMap()
    m.set_max_osd(n_osd)
    for osd in range(n_osd):
        m.osd_state[osd] = CEPH_OSD_EXISTS | CEPH_OSD_UP
        m.osd_weight[osd] = CEPH_OSD_IN
    m.crush = w.crush
    m.epoch = 1
    return m, w


class Monitor(Dispatcher):
    """mon.<rank> (ref: src/mon/Monitor.h:201)."""

    def __init__(self, network: LocalNetwork, rank: int = 0,
                 initial_map: OSDMap | None = None,
                 initial_wrapper=None, store: MonitorStore | None = None,
                 threaded: bool = True, clock=time.monotonic):
        self.name = f"mon.{rank}"
        #: injectable clock so harnesses can run the failure/auto-out
        #: machinery on simulated time consistently with OSD ticks
        self.clock = clock
        self.store = store or MonitorStore()
        self.paxos = Paxos(self.store)
        self.osdmon = OSDMonitor(self.paxos, initial_map, initial_wrapper)
        self.ms = Messenger.create(network, self.name, threaded=threaded)
        self.ms.add_dispatcher(self)
        # osdmap subscribers: entity -> next epoch they need
        self._subs: dict[str, int] = {}
        # failure reports: target osd -> {reporter: stamp}
        self._failure_reports: dict[int, dict[int, float]] = {}
        self._down_stamp: dict[int, float] = {}
        self._lock = threading.RLock()

    # ------------------------------------------------------------ setup
    def init(self) -> None:
        self.osdmon.init()
        self.ms.start()

    def shutdown(self) -> None:
        self.ms.shutdown()

    @property
    def osdmap(self) -> OSDMap:
        return self.osdmon.osdmap

    # -------------------------------------------------------- dispatch
    def ms_dispatch(self, msg: Message) -> bool:
        with self._lock:
            if isinstance(msg, MMonCommand):
                r, outs, outb = self.handle_command(msg.cmd)
                self.ms.connect(msg.src).send_message(
                    MMonCommandAck(tid=msg.tid, result=r, outs=outs,
                                   outb=outb))
                return True
            if isinstance(msg, MMonSubscribe):
                self._handle_subscribe(msg)
                return True
            if isinstance(msg, MOSDBoot):
                self._handle_boot(msg)
                return True
            if isinstance(msg, MOSDFailure):
                self._handle_failure(msg)
                return True
        return False

    # -------------------------------------------------------- commands
    def handle_command(self, cmdmap: dict) -> tuple[int, str, object]:
        """Synchronous command path (also used by tests/CLI directly).
        A failed prepare resets the pending delta so partially staged
        state can never ride along with the next command."""
        with self._lock:
            try:
                res = self.osdmon.preprocess_command(cmdmap)
                if res is not None:
                    return res
                r, outs, outb = self.osdmon.prepare_command(cmdmap)
            except (KeyError, ValueError, TypeError) as ex:
                self.osdmon.create_pending()
                return -22, f"invalid command arguments: {ex}", None
            if r == 0:
                self.osdmon.propose_pending()
                self._publish()
            else:
                self.osdmon.create_pending()
            return r, outs, outb

    # ---------------------------------------------------- subscriptions
    def _handle_subscribe(self, msg: MMonSubscribe) -> None:
        if msg.what != "osdmap":
            return
        self._subs[msg.src] = msg.start or 1
        self._send_maps(msg.src)

    def _send_maps(self, entity: str) -> None:
        """Send everything from the subscriber's next epoch to current
        (ref: OSDMonitor.cc send_incremental)."""
        start = self._subs.get(entity, 1)
        cur = self.osdmap.epoch
        if start > cur:
            return
        first = self.osdmon.get_first_committed() or 1
        incs = []
        if start > first:
            for e in range(start, cur + 1):
                inc = self.osdmon.get_incremental(e)
                if inc is None:
                    incs = None
                    break
                incs.append(inc)
        else:
            incs = None
        if incs is not None and start > 1:
            m = MMap(incrementals=incs, first=start, last=cur)
        else:
            m = MMap(full_map=self.osdmon.get_full_map(cur),
                     first=cur, last=cur)
        self.ms.connect(entity).send_message(m)
        self._subs[entity] = cur + 1

    def _publish(self) -> None:
        """Push new epochs to all subscribers (post-commit)."""
        for entity in list(self._subs):
            self._send_maps(entity)

    # ------------------------------------------------------------- boot
    def _handle_boot(self, msg: MOSDBoot) -> None:
        """(ref: OSDMonitor.cc:3270 prepare_boot — mark up; a brand-new
        osd also gets EXISTS and full in-weight)."""
        osd = msg.osd
        m = self.osdmap
        if osd < 0:
            return
        if osd >= m.max_osd:
            self.osdmon.pending_inc.new_max_osd = osd + 1
        if osd >= m.max_osd or not m.is_up(osd):
            inc = self.osdmon.pending_inc
            inc.new_up_osds.append(osd)
            if osd >= m.max_osd or not m.exists(osd):
                inc.new_weight[osd] = CEPH_OSD_IN
            elif m.osd_state[osd] & CEPH_OSD_AUTOOUT and m.is_out(osd):
                # an auto-out osd comes back in on boot
                # (ref: mon_osd_auto_mark_auto_out_in)
                inc.new_weight[osd] = CEPH_OSD_IN
                inc.new_state[osd] = \
                    inc.new_state.get(osd, 0) | CEPH_OSD_AUTOOUT
            self.osdmon.propose_pending()
            dout("mon", 1).write("%s: osd.%d boot -> e%d", self.name,
                                 osd, self.osdmap.epoch)
            self._publish()
        self._failure_reports.pop(osd, None)
        self._down_stamp.pop(osd, None)

    # ---------------------------------------------------------- failure
    def _handle_failure(self, msg: MOSDFailure) -> None:
        """Quorum-of-reporters mark-down
        (ref: OSDMonitor.cc:2519 prepare_failure / check_failure:
        reporters must be distinct live peers, reports expire after the
        grace window)."""
        target = msg.target_osd
        reporter = msg.reporter
        m = self.osdmap
        if not (0 <= target < m.max_osd) or m.is_down(target):
            return
        if reporter == target or not (0 <= reporter < m.max_osd) or \
                m.is_down(reporter):
            return
        now = self.clock()
        grace = global_config()["osd_heartbeat_grace"]
        reports = self._failure_reports.setdefault(target, {})
        reports[reporter] = now
        for r, stamp in list(reports.items()):
            if now - stamp > grace:
                del reports[r]
        need = global_config()["mon_osd_min_down_reporters"]
        if len(reports) >= need:
            self._mark_down(target)

    def _mark_down(self, osd: int) -> None:
        self.osdmon.pending_inc.new_down_osds.append(osd)
        self.osdmon.propose_pending()
        self._failure_reports.pop(osd, None)
        self._down_stamp[osd] = self.clock()
        dout("mon", 1).write("%s: marked osd.%d down -> e%d", self.name,
                             osd, self.osdmap.epoch)
        self._publish()

    # -------------------------------------------------------------- tick
    def tick(self, now: float | None = None) -> None:
        """Periodic: auto-out OSDs down longer than
        mon_osd_down_out_interval (ref: OSDMonitor.cc:4965 tick)."""
        with self._lock:
            now = self.clock() if now is None else now
            interval = global_config()["mon_osd_down_out_interval"]
            changed = False
            for osd, stamp in list(self._down_stamp.items()):
                m = self.osdmap
                if m.is_up(osd):
                    del self._down_stamp[osd]
                    continue
                if interval and now - stamp >= interval and m.is_in(osd):
                    self.osdmon.pending_inc.new_weight[osd] = 0
                    self.osdmon.pending_inc.new_state[osd] = \
                        self.osdmon.pending_inc.new_state.get(osd, 0) | \
                        CEPH_OSD_AUTOOUT
                    changed = True
                    dout("mon", 1).write("%s: auto-out osd.%d", self.name,
                                         osd)
            if changed:
                self.osdmon.propose_pending()
                self._publish()
