"""LogMonitor: the cluster log replicated through the mon quorum
(VERDICT r4 #4; ref: src/mon/LogMonitor.cc persisting LogEntry batches
through paxos; src/common/LogEntry.h).

Every daemon's LogClient batches entries (`{seq, stamp, name, level,
text}`) into MLog messages; the leader stages them here, commits them
like any map mutation (so `log last` answers identically across mon
failover), acks the sender's high-water seq, and keeps a bounded
recent ring plus per-severity counters for health/prometheus.
"""
from __future__ import annotations

from ..msg import encoding as wire
from .paxos import Paxos, PaxosService
from .store import StoreTransaction

_EINVAL = 22

#: severity order for `log last <n> <level>` filtering
LEVELS = ("debug", "info", "warn", "error")


def _lvl(level: str) -> int:
    try:
        return LEVELS.index(level)
    except ValueError:
        return 1


class LogMonitor(PaxosService):
    """(ref: src/mon/LogMonitor.h)."""

    #: committed ring bound (the reference trims its summary the same
    #: way; ref: LogMonitor.cc log keeping a tail)
    MAX_ENTRIES = 500

    def __init__(self, paxos: Paxos):
        super().__init__("logm", paxos)
        #: committed: {"entries": [...], "last_by_name": {name: seq},
        #:             "counts": {level: n}}
        self.summary: dict = {"entries": [], "last_by_name": {},
                              "counts": {}}
        self.pending: list[dict] = []

    # ------------------------------------------------------- paxos hooks
    def create_initial(self) -> None:
        self.pending = []
        self._bootstrap = True

    def encode_pending(self, tx: StoreTransaction) -> None:
        if getattr(self, "_bootstrap", False):
            self._bootstrap = False
            self.put_version(tx, "v_1", wire.encode(self.summary))
            self.put_version(tx, "last_committed", 1)
            self.put_version(tx, "first_committed", 1)
            return
        if not self.pending:
            return
        new = {"entries": list(self.summary["entries"]),
               "last_by_name": dict(self.summary["last_by_name"]),
               "counts": dict(self.summary["counts"])}
        for e in self.pending:
            last = new["last_by_name"].get(e["name"], -1)
            if e["seq"] <= last:
                continue            # resend of an already-committed entry
            new["last_by_name"][e["name"]] = e["seq"]
            new["entries"].append(e)
            new["counts"][e["level"]] = \
                new["counts"].get(e["level"], 0) + 1
        new["entries"] = new["entries"][-self.MAX_ENTRIES:]
        v = self.get_last_committed() + 1
        self.put_version(tx, f"v_{v}", wire.encode(new))
        self.put_version(tx, "last_committed", v)

    def update_from_paxos(self) -> None:
        v = self.get_last_committed()
        if v:
            blob = self.get_version(f"v_{v}")
            if blob is not None:
                self.summary = wire.decode(blob)

    def create_pending(self) -> None:
        self.pending = []

    def _is_pending_empty(self) -> bool:
        return not self.pending

    # ------------------------------------------------------- staging
    def stage_entries(self, entries: list[dict]) -> bool:
        """Queue daemon entries for the next proposal; returns True if
        anything new was staged (dup seqs are dropped here too so a
        resend storm doesn't force empty proposals)."""
        staged = False
        pend_last: dict[str, int] = {}
        for e in self.pending:
            pend_last[e["name"]] = max(pend_last.get(e["name"], -1),
                                       e["seq"])
        for e in entries:
            name = str(e.get("name", "?"))
            seq = int(e.get("seq", 0))
            last = max(self.summary["last_by_name"].get(name, -1),
                       pend_last.get(name, -1))
            if seq <= last:
                continue
            pend_last[name] = seq
            self.pending.append({
                "seq": seq, "stamp": float(e.get("stamp", 0.0)),
                "name": name,
                "level": str(e.get("level", "info")),
                "text": str(e.get("text", ""))})
            staged = True
        return staged

    def last_seq_for(self, name: str) -> int:
        return self.summary["last_by_name"].get(name, -1)

    # ------------------------------------------------------- commands
    def preprocess_command(self, cmdmap: dict):
        prefix = cmdmap.get("prefix", "")
        if prefix == "log last":
            n = int(cmdmap.get("num", 20))
            floor = _lvl(str(cmdmap.get("level", "debug")))
            out = [e for e in self.summary["entries"]
                   if _lvl(e["level"]) >= floor]
            return 0, "", out[-n:]
        if prefix == "log counts":
            return 0, "", dict(self.summary["counts"])
        if prefix == "log":
            if not cmdmap.get("logtext"):
                return -_EINVAL, "usage: log <text>", None
            return None                     # stage it
        return NotImplemented

    def prepare_command(self, cmdmap: dict):
        """Operator-injected entry (ref: `ceph log <text>` ->
        LogMonitor::prepare_command)."""
        text = str(cmdmap.get("logtext", ""))
        name = str(cmdmap.get("who", "client.admin"))
        seq = self.last_seq_for(name) + 1 + len(
            [e for e in self.pending if e["name"] == name])
        self.pending.append({"seq": seq, "stamp": 0.0, "name": name,
                             "level": str(cmdmap.get("level", "info")),
                             "text": text})
        return 0, "logged", None
