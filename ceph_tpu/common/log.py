"""Leveled per-subsystem logging — the dout/ldout analogue.

Models the reference's debug macros and per-subsystem gather levels
(ref: src/common/debug.h:23-31 dout/ldout/derr, src/common/subsys.h
per-subsystem level table, src/log/Log.cc async ring buffer).  Python's
stdlib logging supplies the async/sink machinery; this module supplies
the subsystem level table and `dout(subsys, level)` gating so call
sites read like the reference's.
"""
from __future__ import annotations

import logging
import sys

_default_level = 1

#: subsystem -> explicit gather level override
#: (ref: subsys.h per-subsystem table; unset subsystems use the
#: default, which the `log_level` config option drives)
_levels: dict[str, int] = {}
_loggers: dict[str, logging.Logger] = {}

class _StderrHandler(logging.StreamHandler):
    """Resolves sys.stderr per-record (not at import) so redirection
    — and pytest capture — see the log stream."""

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):   # StreamHandler.__init__ assigns; ignore
        pass


_handler = _StderrHandler()
_handler.setFormatter(logging.Formatter(
    "%(asctime)s %(name)s %(levelname).1s %(message)s"))


def set_subsys_level(subsys: str, level: int) -> None:
    """`debug_<subsys> = N` equivalent."""
    with _lock:
        _levels[subsys] = level


def set_default_level(level: int) -> None:
    """Gather level for subsystems without an explicit override —
    driven by the `log_level` config option."""
    global _default_level
    _default_level = level


# imported (and _lock constructed) AFTER set_default_level exists:
# make_lock -> global_config() re-enters this half-initialized module
# for exactly that symbol while resolving the `log_level` observer
from .lockdep import make_lock  # noqa: E402

_lock = make_lock("log.registry")


def _logger(subsys: str) -> logging.Logger:
    lg = _loggers.get(subsys)
    if lg is None:
        with _lock:
            lg = _loggers.get(subsys)
            if lg is None:
                lg = logging.getLogger(f"ceph_tpu.{subsys}")
                if not lg.handlers:
                    lg.addHandler(_handler)
                    lg.propagate = False
                lg.setLevel(logging.DEBUG)
                _loggers[subsys] = lg
    return lg


class _NullCtx:
    def write(self, *a, **kw):
        pass

    def __bool__(self):
        return False


_null = _NullCtx()


class _DoutCtx:
    __slots__ = ("_lg", "_level")

    def __init__(self, lg: logging.Logger, level: int):
        self._lg = lg
        self._level = level

    def write(self, msg: str, *args) -> None:
        # level 0 errors -> ERROR, 1 -> INFO, deeper -> DEBUG, matching
        # the reference's derr(=level -1/0) vs dout(>=10 verbose) split
        if self._level <= 0:
            self._lg.error(msg, *args)
        elif self._level <= 1:
            self._lg.info(msg, *args)
        else:
            self._lg.debug(msg, *args)

    def __bool__(self):
        return True


def dout(subsys: str, level: int):
    """`dout(subsys, level).write("...")` — returns a no-op sink when
    the subsystem's gather level is below `level`, so message
    construction cost is skipped exactly like the dout macro."""
    if level > _levels.get(subsys, _default_level):
        return _null
    return _DoutCtx(_logger(subsys), level)


def derr(subsys: str):
    return dout(subsys, 0)
