"""Prometheus exporter (ref: src/pybind/mgr/prometheus/module.py)."""
import urllib.request

import pytest

from ceph_tpu.testing import MiniCluster


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def test_metrics_endpoint():
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("pm", pg_num=8)
        io = r.open_ioctx("pm")
        for i in range(5):
            io.write_full(f"m{i}", b"x" * 100)
        for _ in range(3):
            c.tick()
        mgr = c.start_mgr()
        exp = mgr.start_prometheus()
        text = _scrape(exp.port)
        lines = dict(
            l.rsplit(" ", 1) for l in text.splitlines()
            if l and not l.startswith("#"))
        assert lines["ceph_health_status"] == "0"
        assert lines["ceph_osd_up"] == "3"
        assert lines["ceph_pg_total"] == "8"
        assert lines['ceph_pg_state{state="active+clean"}'] == "8"
        assert lines["ceph_objects"] == "5"
        assert lines['ceph_pool_objects{pool="pm"}'] == "5"
        assert lines['ceph_pool_bytes{pool="pm"}'] == "500"
        assert float(lines["ceph_cluster_total_bytes"]) > 0
        # per-daemon counters from the piggybacked perf reports
        assert float(lines['ceph_daemon_op{daemon="osd.0"}']) >= 0
        # exposition format sanity: HELP/TYPE precede samples
        assert text.index("# HELP ceph_health_status") < \
            text.index("ceph_health_status 0")
        # 404 for other paths
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=10)
    finally:
        c.shutdown()


import urllib.error  # noqa: E402  (used in the test above)
