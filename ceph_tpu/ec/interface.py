"""Erasure-code plugin contract and shared base plumbing.

Python rendering of Ceph's EC plugin boundary with the exact method surface
of `ErasureCodeInterface` (ref: src/erasure-code/ErasureCodeInterface.h:170-462)
and the shared base-class behavior of `ErasureCode`
(ref: src/erasure-code/ErasureCode.{h,cc}):

* systematic codes: an object is split into k data chunks; m coding chunks
  are computed from them; any k of the k+m chunks recover the object;
* `get_chunk_size(object_size)` defines per-plugin padding/alignment;
* `encode` pads the input with zeros to k*chunk_size and delegates the math
  to `encode_chunks` (ref: ErasureCode.cc:151-207 encode_prepare/encode);
* `decode` fills in missing chunks then delegates to `decode_chunks`;
* an optional `mapping=` profile string remaps chunk positions
  (ref: ErasureCode.cc:274 to_mapping);
* `minimum_to_decode` defaults to "any k available chunks" greedy
  (ref: ErasureCode.cc:103 _minimum_to_decode).

Buffers are numpy uint8 arrays internally; `bytes` at the outer API.
"""
from __future__ import annotations

import abc
from typing import Iterable, Mapping

import numpy as np

ErasureCodeProfile = dict  # str -> str, like Ceph's ErasureCodeProfile

SIMD_ALIGN = 32  # ref: ErasureCode.cc:42 (buffer alignment; informational here)


class ErasureCodeError(Exception):
    """Raised where the C++ interface returns -EINVAL/-EIO/-ENOENT."""


def to_int(name: str, profile: ErasureCodeProfile, default: str) -> int:
    v = profile.setdefault(name, default)
    if v == "":
        v = profile[name] = default
    try:
        return int(v)
    except ValueError as e:
        raise ErasureCodeError(f"could not convert {name}={v!r} to int") from e


def to_bool(name: str, profile: ErasureCodeProfile, default: str) -> bool:
    v = str(profile.setdefault(name, default)).lower()
    return v in ("yes", "true", "1")


def sanity_check_k_m(k: int, m: int) -> None:
    if k < 2:
        raise ErasureCodeError(f"k={k} must be >= 2")
    if m < 1:
        raise ErasureCodeError(f"m={m} must be >= 1")


class ErasureCodeInterface(abc.ABC):
    """Abstract EC plugin contract (ErasureCodeInterface.h:170-462)."""

    @abc.abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Initialize from a profile; raises ErasureCodeError on bad profiles."""

    @abc.abstractmethod
    def get_profile(self) -> ErasureCodeProfile: ...

    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m."""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Sub-chunk granularity (1 except for regenerating codes like clay)."""
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, object_size: int) -> int: ...

    @abc.abstractmethod
    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        """chunk id -> list of (sub-chunk offset, count) to read."""

    @abc.abstractmethod
    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set: ...

    def repair_schedule(self, erasures: set, available: set):
        """RepairPlan (ceph_tpu.ec.repairc) for rebuilding `erasures`
        whole from partial helper reads, or None when this code has no
        better schedule than wholesale full-chunk recovery for the
        signature.  Plans feed the repair-schedule compiler: the OSD
        recovery paths lower a returned plan to one fused
        gather/matmul/scatter program, cached per signature."""
        return None

    @abc.abstractmethod
    def encode(self, want_to_encode: Iterable[int], data: bytes
               ) -> dict[int, np.ndarray]: ...

    @abc.abstractmethod
    def encode_chunks(self, want_to_encode: Iterable[int],
                      encoded: dict[int, np.ndarray]) -> None: ...

    @abc.abstractmethod
    def decode(self, want_to_read: Iterable[int],
               chunks: Mapping[int, np.ndarray], chunk_size: int = 0
               ) -> dict[int, np.ndarray]: ...

    @abc.abstractmethod
    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None: ...

    @abc.abstractmethod
    def get_chunk_mapping(self) -> list[int]: ...

    @abc.abstractmethod
    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes: ...

    def create_rule(self, name: str, crush) -> int:
        """Create a CRUSH rule suitable for this code (indep/erasure);
        implemented by the base class once a CrushWrapper is supplied."""
        raise NotImplementedError


def _as_chunk(buf, blocksize: int) -> np.ndarray:
    a = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, bytearray, memoryview)) \
        else np.asarray(buf, dtype=np.uint8)
    if a.size == blocksize:
        return a
    out = np.zeros(blocksize, dtype=np.uint8)
    out[:a.size] = a
    return out


class ErasureCode(ErasureCodeInterface):
    """Shared plumbing mirroring src/erasure-code/ErasureCode.{h,cc}."""

    def __init__(self) -> None:
        self._profile: ErasureCodeProfile = {}
        self.chunk_mapping: list[int] = []
        self.rule_root = "default"
        self.rule_failure_domain = "host"
        self.rule_device_class = ""

    # -- profile -----------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = profile.setdefault("crush-root", "default")
        self.rule_failure_domain = profile.setdefault("crush-failure-domain", "host")
        self.rule_device_class = profile.setdefault("crush-device-class", "")
        self._profile = profile

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def parse(self, profile: ErasureCodeProfile) -> None:
        """Base parse: the `mapping=` remap string (ErasureCode.cc:274)."""
        mapping = profile.get("mapping")
        if mapping:
            data_pos = [i for i, c in enumerate(mapping) if c == "D"]
            coding_pos = [i for i, c in enumerate(mapping) if c != "D"]
            self.chunk_mapping = data_pos + coding_pos

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if i < len(self.chunk_mapping) else i

    def get_chunk_mapping(self) -> list[int]:
        return self.chunk_mapping

    # -- minimum_to_decode -------------------------------------------------
    def _minimum_to_decode(self, want_to_read: set, available: set) -> set:
        if want_to_read <= available:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available) < k:
            raise ErasureCodeError("EIO: not enough available chunks")
        return set(sorted(available)[:k])

    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        ids = self._minimum_to_decode(set(want_to_read), set(available))
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in ids}

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set:
        return self._minimum_to_decode(set(want_to_read), set(available))

    # -- encode ------------------------------------------------------------
    def encode_prepare(self, data: bytes) -> dict[int, np.ndarray]:
        """Split + zero-pad into k data chunks, allocate m coding chunks
        (ref: ErasureCode.cc:151 encode_prepare)."""
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = self.get_chunk_size(len(data))
        raw = np.frombuffer(data, dtype=np.uint8)
        encoded: dict[int, np.ndarray] = {}
        for i in range(k):
            encoded[self.chunk_index(i)] = _as_chunk(
                raw[i * blocksize:(i + 1) * blocksize], blocksize)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return encoded

    def encode(self, want_to_encode: Iterable[int], data: bytes
               ) -> dict[int, np.ndarray]:
        want = set(want_to_encode)
        encoded = self.encode_prepare(data)
        self.encode_chunks(want, encoded)
        return {i: c for i, c in encoded.items() if i in want}

    # -- decode ------------------------------------------------------------
    def _decode(self, want_to_read: set, chunks: Mapping[int, np.ndarray]
                ) -> dict[int, np.ndarray]:
        chunks = {i: np.asarray(c, dtype=np.uint8) for i, c in chunks.items()}
        if want_to_read <= set(chunks):
            return {i: chunks[i] for i in want_to_read}
        if not chunks:
            raise ErasureCodeError("EIO: no chunks")
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = len(next(iter(chunks.values())))
        decoded = {}
        for i in range(k + m):
            decoded[i] = (chunks[i].copy() if i in chunks
                          else np.zeros(blocksize, dtype=np.uint8))
        self.decode_chunks(want_to_read, chunks, decoded)
        return {i: decoded[i] for i in want_to_read}

    def decode(self, want_to_read: Iterable[int],
               chunks: Mapping[int, np.ndarray], chunk_size: int = 0
               ) -> dict[int, np.ndarray]:
        return self._decode(set(want_to_read), chunks)

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = self._decode(want, chunks)
        return b"".join(decoded[self.chunk_index(i)].tobytes() for i in range(k))

    # -- crush rule --------------------------------------------------------
    def create_rule(self, name: str, crush) -> int:
        """indep/erasure rule under crush-root with crush-failure-domain
        (ref: ErasureCode.cc:64 create_rule -> add_simple_rule).  The
        rule mask must admit pool.size == k+m — wide codes exceed the
        legacy default ceiling of 10."""
        return crush.add_simple_rule(
            name, self.rule_root, self.rule_failure_domain,
            self.rule_device_class, "indep", rule_type="erasure",
            max_size=self.get_chunk_count())
