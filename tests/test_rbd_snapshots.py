"""RBD image snapshots over self-managed rados snaps
(ref: librbd Operations::snap_create/rollback;
rados_ioctx_selfmanaged_snap_* + per-image SnapContext)."""
import numpy as np
import pytest

from ceph_tpu.rbd import RBD, Image
from ceph_tpu.rbd.image import RBDError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("rbd", pg_num=16)
    yield c, r
    c.shutdown()


@pytest.fixture()
def io(cluster):
    _, r = cluster
    return r.open_ioctx("rbd")


def test_snap_create_read_back(io):
    RBD().create(io, "disk", size=1 << 22, order=16)
    img = Image(io, "disk")
    rng = np.random.default_rng(9)
    v1 = rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
    img.write(0, v1)
    img.snap_create("s1")
    v2 = rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
    img.write(0, v2)
    img.snap_create("s2")
    img.write(50_000, b"\xff" * 1000)
    # live image has the latest bytes
    live = img.read(0, 200_000)
    assert live[50_000:51_000] == b"\xff" * 1000
    # snapshots read back their frozen state
    s1 = Image(io, "disk", snapshot="s1")
    assert s1.read(0, 200_000) == v1
    s2 = Image(io, "disk", snapshot="s2")
    assert s2.read(0, 200_000) == v2
    assert [s["name"] for s in img.snap_list()] == ["s1", "s2"]
    # snapshot handles are read-only
    with pytest.raises(RBDError):
        s1.write(0, b"nope")
    with pytest.raises(RBDError):
        s1.snap_create("inner")


def test_snap_rollback(io):
    RBD().create(io, "rbk", size=1 << 20, order=16)
    img = Image(io, "rbk")
    img.write(0, b"stable state " * 1000)
    img.snap_create("good")
    img.write(0, b"BROKEN!!" * 2000)
    img.snap_rollback("good")
    assert img.read(0, 13_000) == (b"stable state " * 1000)
    # rollback restores the size recorded at snap time
    assert img.size == 1 << 20


def test_snap_remove_and_missing(io):
    RBD().create(io, "rmv", size=1 << 20, order=16)
    img = Image(io, "rmv")
    img.write(0, b"x" * 100)
    img.snap_create("tmp")
    img.snap_remove("tmp")
    assert img.snap_list() == []
    with pytest.raises(RBDError):
        img.snap_remove("tmp")
    with pytest.raises(RBDError):
        Image(io, "rmv", snapshot="tmp")


def test_snap_of_sparse_and_grown_image(io):
    RBD().create(io, "grow", size=1 << 20, order=16)
    img = Image(io, "grow")
    img.write(0, b"A" * 10)
    img.snap_create("small")
    img.resize(1 << 21)
    img.write((1 << 20) + 5, b"beyond old end")
    snap = Image(io, "grow", snapshot="small")
    assert snap.size == 1 << 20
    assert snap.read(0, 10) == b"A" * 10
    # reading at the snapshot never sees post-snap objects: at the
    # snapshot's size the read clips empty, past it it's an error
    assert snap.read((1 << 20) - 10, 10 ** 3) == b"\0" * 10
    with pytest.raises(RBDError):
        snap.read((1 << 20) + 1, 10)
