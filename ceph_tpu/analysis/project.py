"""Project-wide context for cephck v2: symbol table + call graph.

Per-file AST matching (cephck v1) cannot see that a loop in
osd/ec_backend.py calls a helper in ec/kernels/bitmatmul.py that host-
syncs, or that a callsite in crush/ invokes a jit wrapper declared two
modules away.  ProjectContext is the cross-module half of the engine:
it is built ONCE over every scanned file and handed to rules next to
the per-file FileContext, carrying

* a module table (repo-relative path -> dotted module name -> AST),
* per-module import aliases, expanded to canonical dotted names
  (``np.asarray`` -> ``numpy.asarray``, ``jnp.dot`` ->
  ``jax.numpy.dot``) so rules match semantics, not spelling,
* a symbol table of every module-level function/method,
* the jit registry: every symbol wrapped in ``jax.jit`` (decorator,
  ``functools.partial(jax.jit, ...)`` or ``name = jax.jit(f)``
  assignment), with its declared static args,
* a best-effort call graph over project-internal calls (plain names,
  imported symbols, module attributes, ``self.method``).

Resolution is deliberately conservative: a name the table cannot pin
resolves to None and rules must stay silent about it — cross-module
analysis buys reach, not license to guess.
"""
from __future__ import annotations

import ast
from typing import Iterator


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target: ``threading.Lock``,
    ``time.perf_counter``, ``self._loop`` — empty for dynamic funcs."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


def module_name(rel: str) -> str:
    """Dotted module name for a repo-relative path
    (``ceph_tpu/ec/gf.py`` -> ``ceph_tpu.ec.gf``)."""
    parts = rel.split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p) or "<module>"


def jit_statics(call: ast.Call) -> tuple[set[int], set[str]]:
    """Declared (static positions, static names) of a jit/partial-jit
    call — empty sets when none are declared."""
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    nums.add(v.value)
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    names.add(v.value)
    return nums, names


def _is_jit_name(name: str) -> bool:
    return name.split(".")[-1] == "jit"


def _partial_jit(call: ast.Call) -> bool:
    """``functools.partial(jax.jit, ...)`` (decorator or assignment)."""
    return dotted(call.func).split(".")[-1] == "partial" and any(
        isinstance(a, (ast.Name, ast.Attribute)) and _is_jit_name(dotted(a))
        for a in call.args)


class ModuleInfo:
    """One scanned module's symbols, import aliases and jit registry."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.name = module_name(rel)
        self.tree = tree
        #: local alias -> canonical dotted prefix ("np" -> "numpy")
        self.imports: dict[str, str] = {}
        #: qualname ("f", "Cls.meth") -> def node
        self.functions: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        #: jit-wrapped symbol -> (static positions, static names)
        self.jitted: dict[str, tuple[set[int], set[str]]] = {}
        #: names bound by module-level statements (containers a traced
        #: function could leak into)
        self.module_names: set[str] = set()
        self._collect()

    # -- alias expansion ----------------------------------------------

    def expand(self, name: str) -> str:
        """Canonical dotted name for a local spelling: resolves the
        FIRST component through the import table (``jnp.dot`` ->
        ``jax.numpy.dot``); unknown heads pass through unchanged."""
        if not name:
            return name
        head, _, rest = name.partition(".")
        target = self.imports.get(head)
        if target is None:
            return name
        return f"{target}.{rest}" if rest else target

    # -- collection ---------------------------------------------------

    def _package_parts(self) -> list[str]:
        return self.name.split(".")[:-1]

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        self.imports.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom):
                pkg = self._package_parts()
                if node.level:
                    pkg = pkg[:len(pkg) - (node.level - 1)] \
                        if node.level <= len(pkg) + 1 else []
                    base = ".".join(pkg + ([node.module]
                                           if node.module else []))
                else:
                    base = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    full = f"{base}.{a.name}" if base else a.name
                    self.imports[a.asname or a.name] = full
        for node in self.tree.body:
            self._collect_stmt(node, prefix="")
            for t in getattr(node, "targets", []) or \
                    ([node.target] if isinstance(
                        node, (ast.AnnAssign, ast.AugAssign)) else []):
                if isinstance(t, ast.Name):
                    self.module_names.add(t.id)

    def _collect_stmt(self, node: ast.stmt, prefix: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{prefix}{node.name}"
            self.functions[qual] = node
            st = self._jit_of_decorators(node)
            if st is not None:
                self.jitted[qual] = st
                if prefix:          # methods also reachable by name
                    self.jitted.setdefault(node.name, st)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                self._collect_stmt(item, prefix=f"{node.name}.")
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            st = self._jit_of_call(node.value)
            if st is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.jitted[f"{prefix}{t.id}" if prefix
                                    else t.id] = st
        # jit assignments inside function bodies (``fn = jax.jit(...)``
        # behind a cache) register under their local name too, so
        # callsite rules recognize `fn(...)` as a jitted call
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call):
                    st = self._jit_of_call(sub.value)
                    if st is not None:
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                self.jitted.setdefault(t.id, st)

    def _jit_of_decorators(self, fn) -> tuple[set[int], set[str]] | None:
        for d in fn.decorator_list:
            name = self.expand(dotted(d))
            if _is_jit_name(name):
                return (set(), set()) if not isinstance(d, ast.Call) \
                    else jit_statics(d)
            if isinstance(d, ast.Call):
                if _is_jit_name(self.expand(dotted(d.func))):
                    return jit_statics(d)
                if _partial_jit(d):
                    return jit_statics(d)
        return None

    def _jit_of_call(self, call: ast.Call) -> tuple[set[int],
                                                    set[str]] | None:
        """statics if `call` evaluates to a jit wrapper:
        ``jax.jit(f, ...)`` or ``partial(jax.jit, ...)``.  The func
        must be a plain name — ``jit(f)(x)``'s OUTER call (func is
        itself a Call) invokes the wrapper, it does not build one."""
        if isinstance(call.func, (ast.Name, ast.Attribute)) and \
                _is_jit_name(self.expand(dotted(call.func))):
            return jit_statics(call)
        if _partial_jit(call):
            return jit_statics(call)
        return None


class ProjectContext:
    """The cross-module pass: module/symbol tables + call graph over
    every file of one engine run."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}     # dotted name ->
        self.by_rel: dict[str, ModuleInfo] = {}
        #: (modname, qualname) -> {(modname, qualname), ...}
        self.call_graph: dict[tuple[str, str],
                              set[tuple[str, str]]] = {}
        #: reverse: callee -> {caller, ...} (built by finalize)
        self.callers: dict[tuple[str, str],
                           set[tuple[str, str]]] = {}
        self._finalized = False

    def add(self, rel: str, tree: ast.Module) -> ModuleInfo:
        mod = ModuleInfo(rel, tree)
        self.modules[mod.name] = mod
        self.by_rel[rel] = mod
        self._finalized = False
        return mod

    def module_for(self, rel: str) -> ModuleInfo | None:
        return self.by_rel.get(rel)

    # -- resolution ---------------------------------------------------

    def resolve(self, mod: ModuleInfo, name: str,
                caller_qual: str = "") -> tuple[ModuleInfo, str] | None:
        """Resolve a call-target spelling in `mod` to a (module,
        qualname) the project owns; None for externals/dynamic."""
        if not name:
            return None
        if name.startswith("self.") and "." in caller_qual:
            cls = caller_qual.split(".")[0]
            qual = f"{cls}.{name[5:]}"
            if qual in mod.functions:
                return mod, qual
            return None
        if name in mod.functions:
            return mod, name
        full = mod.expand(name)
        # longest module prefix the project owns, remainder = qualname
        parts = full.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            owner = self.modules.get(".".join(parts[:cut]))
            if owner is not None:
                qual = ".".join(parts[cut:])
                if qual in owner.functions:
                    return owner, qual
                if qual in owner.jitted:
                    return owner, qual
                return None
        return None

    def jit_statics_of(self, mod: ModuleInfo, name: str,
                       caller_qual: str = "") -> tuple[set[int],
                                                       set[str]] | None:
        """statics when `name` at a callsite in `mod` is a jit wrapper
        (local, method, or imported from another scanned module);
        None when it is not known to be jitted."""
        if name in mod.jitted:
            return mod.jitted[name]
        if name.startswith("self."):
            attr = name[5:]
            if attr in mod.jitted:
                return mod.jitted[attr]
            if "." in caller_qual:
                qual = f"{caller_qual.split('.')[0]}.{attr}"
                if qual in mod.jitted:
                    return mod.jitted[qual]
            return None
        resolved = self.resolve(mod, name, caller_qual)
        if resolved is not None:
            owner, qual = resolved
            return owner.jitted.get(qual)
        return None

    # -- call graph ---------------------------------------------------

    def finalize(self) -> None:
        """Build the project call graph (idempotent)."""
        if self._finalized:
            return
        self.call_graph = {}
        for mod in self.modules.values():
            for qual, fn in mod.functions.items():
                edges = self.call_graph.setdefault((mod.name, qual),
                                                   set())
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    target = self.resolve(mod, dotted(node.func), qual)
                    if target is not None:
                        edges.add((target[0].name, target[1]))
        # reverse edges (callee -> callers): caller-walking rules
        # (guarded-by coverage) would otherwise rescan the whole
        # graph per hop
        self.callers = {}
        for src, dsts in self.call_graph.items():
            for dst in dsts:
                self.callers.setdefault(dst, set()).add(src)
        self._finalized = True

    def callees(self, mod: ModuleInfo, qual: str) -> set[tuple[str, str]]:
        self.finalize()
        return self.call_graph.get((mod.name, qual), set())

    def reachable(self, mod: ModuleInfo, qual: str,
                  max_depth: int = 3) -> Iterator[tuple[str, str]]:
        """(modname, qualname) pairs reachable from one function,
        breadth-first, depth-bounded — callers use it for "does this
        loop reach device/host-sync code" questions."""
        self.finalize()
        seen: set[tuple[str, str]] = set()
        frontier = {(mod.name, qual)}
        for _ in range(max_depth):
            nxt: set[tuple[str, str]] = set()
            for node in frontier:
                for tgt in self.call_graph.get(node, ()):
                    if tgt not in seen:
                        seen.add(tgt)
                        nxt.add(tgt)
                        yield tgt
            if not nxt:
                return
            frontier = nxt
