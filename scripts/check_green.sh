#!/usr/bin/env bash
# check_green.sh — the ship gate: run the tier-1 suite and fail on ANY
# red test (failure, error, or collection error).
#
# Round-5 shipped a snapshot with deterministically-red tests because
# nothing between "tests ran" and "snapshot shipped" asserted green.
# This script IS that assertion: wire it into any verify/release flow
# (`bash scripts/check_green.sh`) — exit 0 means every collected
# tier-1 test passed, anything else means do not ship.
set -u -o pipefail

cd "$(dirname "$0")/.."
LOG="${TMPDIR:-/tmp}/check_green.$$.log"
trap 'rm -f "$LOG"' EXIT

timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=${passed}"

if [ "$rc" -ne 0 ]; then
    echo "check_green: RED (pytest rc=$rc) — do not ship" >&2
    exit 1
fi
if grep -aqE '^(FAILED|ERROR) ' "$LOG"; then
    echo "check_green: RED (F/E lines present) — do not ship" >&2
    exit 1
fi
if [ "$passed" -eq 0 ]; then
    echo "check_green: RED (zero tests passed — collection broke?)" >&2
    exit 1
fi
echo "check_green: GREEN (${passed} passed)"
