"""ECUtil tests: stripe algebra, batched encode/decode, HashInfo.

Stripe-algebra cases are ported from the reference's gtest
(ref: src/test/osd/TestECBackend.cc:22-60 TEST(ECUtil, stripe_info_t));
crc32c vectors from src/test/common/test_crc32c.cc:18-45.
"""
import numpy as np
import pytest

from ceph_tpu.common.crc32c import crc32c, _crc32c_py
from ceph_tpu.ec import registry
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.ecutil import HashInfo, StripeInfo


def test_crc32c_reference_vectors():
    # ref: src/test/common/test_crc32c.cc:18-45
    assert crc32c(0, b"foo bar baz") == 4119623852
    assert crc32c(1234, b"foo bar baz") == 881700046
    assert crc32c(0, b"whiz bang boom") == 2360230088
    assert crc32c(5678, b"whiz bang boom") == 3743019208
    assert crc32c(0, b"\x01" * 5) == 2715569182
    assert crc32c(0, b"\x01" * 35) == 440531800
    assert crc32c(0, b"\x01" * 4096000) == 31583199
    assert crc32c(1234, b"\x01" * 4096000) == 1400919119


def test_crc32c_python_fallback_matches_native():
    data = bytes(range(256)) * 7 + b"tail"
    assert _crc32c_py(0, data) == crc32c(0, data)
    assert _crc32c_py(0xDEADBEEF, data) == crc32c(0xDEADBEEF, data)


def test_stripe_info_reference_cases():
    # ref: TestECBackend.cc TEST(ECUtil, stripe_info_t)
    swidth, ssize = 4096, 4
    s = StripeInfo(ssize, swidth)
    cs = s.chunk_size
    assert s.stripe_width == swidth
    assert s.logical_to_next_chunk_offset(0) == 0
    assert s.logical_to_next_chunk_offset(1) == cs
    assert s.logical_to_next_chunk_offset(swidth - 1) == cs
    assert s.logical_to_prev_chunk_offset(0) == 0
    assert s.logical_to_prev_chunk_offset(swidth) == cs
    assert s.logical_to_prev_chunk_offset(2 * swidth - 1) == cs
    assert s.logical_to_next_stripe_offset(0) == 0
    assert s.logical_to_next_stripe_offset(swidth - 1) == swidth
    assert s.logical_to_prev_stripe_offset(swidth) == swidth
    assert s.logical_to_prev_stripe_offset(2 * swidth - 1) == swidth
    assert s.aligned_logical_offset_to_chunk_offset(2 * swidth) == 2 * cs
    assert s.aligned_chunk_offset_to_logical_offset(2 * cs) == 2 * swidth
    assert s.aligned_offset_len_to_chunk((swidth, 10 * swidth)) == \
        (cs, 10 * cs)
    assert s.offset_len_to_stripe_bounds((swidth - 10, 20)) == (0, 2 * swidth)


def _make_ec(plugin="isa", k=4, m=2, **extra):
    profile = {"k": str(k), "m": str(m), **extra}
    return registry.factory(plugin, profile)


def _sinfo_for(ec):
    cs = ec.get_chunk_size(ec.get_data_chunk_count() * 4096)
    k = ec.get_data_chunk_count()
    return StripeInfo(k, k * cs)


@pytest.mark.parametrize("plugin", ["isa", "jerasure", "tpu"])
def test_ecutil_encode_decode_roundtrip(plugin):
    ec = _make_ec(plugin)
    sinfo = _sinfo_for(ec)
    rng = np.random.default_rng(7)
    nstripes = 5
    data = rng.integers(0, 256, nstripes * sinfo.stripe_width,
                        dtype=np.uint8).tobytes()
    shards = ecutil.encode(sinfo, ec, data)
    assert set(shards) == set(range(6))
    assert all(len(v) == nstripes * sinfo.chunk_size
               for v in shards.values())
    # full logical rebuild from the k data shards
    assert ecutil.decode_concat(
        sinfo, ec, {i: shards[i] for i in range(4)}) == data
    # degraded rebuild: lose shards 1 and 4
    avail = {i: shards[i] for i in (0, 2, 3, 5)}
    out = ecutil.decode(sinfo, ec, avail, want=[1, 4])
    assert out[1] == shards[1]
    assert out[4] == shards[4]
    assert ecutil.decode_concat(sinfo, ec, avail) == data


def test_ecutil_batch_matches_per_stripe_loop():
    """The batched dispatch must produce byte-identical shard streams to
    the reference's per-stripe loop formulation."""
    ec = _make_ec("tpu", k=3, m=2)
    sinfo = _sinfo_for(ec)
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, 4 * sinfo.stripe_width,
                        dtype=np.uint8).tobytes()
    shards = ecutil.encode(sinfo, ec, data)
    # per-stripe oracle via the scalar plugin API
    w = sinfo.stripe_width
    for s in range(4):
        stripe = data[s * w:(s + 1) * w]
        encoded = ec.encode(set(range(5)), stripe)
        for i in range(5):
            got = shards[i][s * sinfo.chunk_size:(s + 1) * sinfo.chunk_size]
            assert got == encoded[i].tobytes(), (s, i)


def test_ecutil_encode_rejects_unaligned():
    ec = _make_ec("isa")
    sinfo = _sinfo_for(ec)
    with pytest.raises(ValueError):
        ecutil.encode(sinfo, ec, b"x" * (sinfo.stripe_width + 1))
    assert ecutil.encode(sinfo, ec, b"") == {}


def test_ecutil_remapped_plugin_falls_back():
    """A plugin with a chunk remap (mapping=) must still round-trip via
    the per-stripe path."""
    ec = _make_ec("isa", k=2, m=1, mapping="_DD")
    k = 2
    cs = ec.get_chunk_size(k * 1024)
    sinfo = StripeInfo(k, k * cs)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 3 * sinfo.stripe_width,
                        dtype=np.uint8).tobytes()
    shards = ecutil.encode(sinfo, ec, data)
    assert ecutil.decode_concat(sinfo, ec, shards) == data


def test_hash_info_append_and_chain():
    hi = HashInfo(3)
    assert hi.has_chunk_hash()
    a = {0: b"aaa", 1: b"bbb", 2: b"ccc"}
    hi.append(0, a)
    assert hi.get_total_chunk_size() == 3
    # chaining: two appends == one append of the concatenation
    b = {0: b"ddd", 1: b"eee", 2: b"fff"}
    hi.append(3, b)
    one = HashInfo(3)
    one.append(0, {i: a[i] + b[i] for i in a})
    assert hi == one
    # crc matches direct computation with -1 seed
    assert hi.get_chunk_hash(0) == crc32c(crc32c(0xFFFFFFFF, b"aaa"), b"ddd")


def test_hash_info_append_guards():
    hi = HashInfo(2)
    hi.append(0, {0: b"xx", 1: b"yy"})
    with pytest.raises(ValueError):
        hi.append(0, {0: b"xx", 1: b"yy"})      # wrong old_size
    with pytest.raises(ValueError):
        hi.append(2, {0: b"x"})                  # not all shards
    with pytest.raises(ValueError):
        hi.append(2, {0: b"x", 1: b"yy"})        # ragged append


def test_hash_info_dict_roundtrip():
    hi = HashInfo(4)
    hi.append(0, {i: bytes([i]) * 16 for i in range(4)})
    hi2 = HashInfo.from_dict(hi.to_dict())
    assert hi2 == hi
    assert hi2.projected_total_chunk_size == 16
