"""ICI fabric in the OSD data plane: EC-pool writes whose chunk
distribution rides the device-mesh psum step, with host messages as
control plane (ref: the per-shard fan-out this replaces,
src/osd/ECBackend.cc:2037-2070)."""
import numpy as np
import pytest

from ceph_tpu.dist import ICIFabric
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def fabric_cluster():
    c = MiniCluster(n_osd=6, threaded=False, fabric=ICIFabric())
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k2m2",
                   "profile": {"plugin": "tpu", "k": "2", "m": "2",
                               "crush-failure-domain": "host"}})
    r.pool_create("ec", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k2m2")
    c.pump()
    yield c, r
    c.shutdown()


def locate(c, r, pool, oid):
    pid = r.pool_lookup(pool)
    m = c.mon.osdmap
    pg = m.pools[pid].raw_pg_to_pg(m.object_locator_to_pg(oid, pid))
    up, up_p, acting, acting_p = m.pg_to_up_acting_osds(pg)
    return pid, pg, acting, acting_p


def test_ec_write_rides_the_mesh(fabric_cluster):
    c, r = fabric_cluster
    io = r.open_ioctx("ec")
    rng = np.random.default_rng(3)
    objs = {f"f{i}": rng.integers(0, 256, 20000 + 17 * i,
                                  dtype=np.uint8).tobytes()
            for i in range(6)}
    before = c.fabric.stats["staged"]
    for oid, data in objs.items():
        io.write_full(oid, data)
    c.pump()
    # the writes ran the psum fan-out, not the host encode
    assert c.fabric.stats["staged"] >= before + len(objs)
    assert c.fabric.stats["fetched"] >= 4 * len(objs)  # k+m per write
    # staging buffers are released once every shard committed
    assert c.fabric.staged_count() == 0
    for oid, data in objs.items():
        assert io.read(oid) == data


def test_fabric_chunks_match_host_encode(fabric_cluster):
    """Byte parity: each shard's stored chunk stream must equal what
    the host encode path would have produced (the mesh step is an
    accelerated identical computation, not an alternative format)."""
    from ceph_tpu.osd import ecutil
    from ceph_tpu.osd.ec_backend import pg_cid
    from ceph_tpu.store import ObjectId
    c, r = fabric_cluster
    io = r.open_ioctx("ec")
    payload = bytes(range(256)) * 64          # 16 KiB deterministic
    io.write_full("parity_probe", payload)
    c.pump()
    pid, pg, acting, primary = locate(c, r, "ec", "parity_probe")
    backend = c.osds[primary].pgs[pg].backend
    sinfo = backend.sinfo
    padded = payload + b"\0" * (-len(payload) % sinfo.stripe_width)
    want = ecutil.encode(sinfo, backend.ec, padded)
    for s, osd in enumerate(acting):
        if osd < 0:
            continue
        store = c.osds[osd].store
        got = store.read(pg_cid(pg), ObjectId("parity_probe", shard=s),
                         0, 0)
        assert got == want[s], f"shard {s} chunk stream differs"


def test_fabric_append_keeps_hinfo_and_scrub_clean(fabric_cluster):
    c, r = fabric_cluster
    io = r.open_ioctx("ec")
    sinfo = None
    io.write_full("appender", b"")
    # stripe-aligned appends keep the cumulative per-shard crc valid
    pid, pg, acting, primary = locate(c, r, "ec", "appender")
    sinfo = c.osds[primary].pgs[pg].backend.sinfo
    chunk = b"A" * sinfo.stripe_width
    for i in range(3):
        io.append("appender", chunk)
    c.pump()
    assert io.read("appender") == chunk * 3
    res = r.pg_scrub(pid, pg.ps)
    assert res["inconsistent"] == []


def test_fabric_degraded_read(fabric_cluster):
    """Chunks distributed by the mesh decode correctly when a shard
    holder dies — proof the psum placed real, correct parity."""
    c, r = fabric_cluster
    io = r.open_ioctx("ec")
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, 50000, dtype=np.uint8).tobytes()
    io.write_full("degraded", data)
    c.pump()
    pid, pg, acting, primary = locate(c, r, "ec", "degraded")
    victim = next(o for o in acting if o >= 0 and o != primary)
    c.kill_osd(victim)
    # reads reconstruct from survivors (client retries on reset)
    assert io.read("degraded") == data
    c.revive_osd(victim)
    c.pump()
    c.wait_all_up()


def test_non_resident_acting_falls_back(fabric_cluster):
    """An acting set with a non-resident OSD must use the host path —
    the fabric is an accelerator, not a correctness dependency."""
    c, r = fabric_cluster
    fab = c.fabric
    # simulate one acting OSD not being co-resident
    osd = next(iter(c.osds))
    fab.resident.discard(osd)
    try:
        io = r.open_ioctx("ec")
        staged_before = fab.stats["staged"]
        data = b"host-path" * 1000
        # find an object whose acting set includes the non-resident osd
        for i in range(40):
            oid = f"fb{i}"
            _pid, _pg, acting, _p = locate(c, r, "ec", oid)
            if osd in acting:
                io.write_full(oid, data)
                c.pump()
                assert io.read(oid) == data
                break
        else:
            pytest.skip("no pg maps onto the non-resident osd")
        # that write did not stage on the mesh
        assert fab.stats["staged"] == staged_before
    finally:
        fab.register_resident(osd)
