"""rbd CLI: image management verbs over a running cluster.

The `rbd` tool surface (ref: src/tools/rbd/, action/*.cc verbs),
connected like the rados CLI via --monmap (TCP daemon world of
tools/daemon_main + vstart):

    rbd --monmap mm.json create -p rbd --size 16M img
    rbd --monmap mm.json ls -p rbd
    rbd --monmap mm.json info -p rbd img
    rbd --monmap mm.json snap create -p rbd img@s1
    rbd --monmap mm.json clone -p rbd img@s1 child
    rbd --monmap mm.json du -p rbd img
    rbd --monmap mm.json flatten -p rbd child

`main(argv, rados=...)` accepts a pre-connected client so the test
tier drives the verbs in-process (the cram-style CLI tier model,
ref: src/test/cli/rbd/).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from ..rbd import RBD, Image, RBDError


def _parse_size(s: str) -> int:
    s = s.strip().upper()
    mult = 1
    for suf, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30),
                   ("T", 1 << 40)):
        if s.endswith(suf):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


def _fmt_size(n: int) -> str:
    for suf, m in (("TiB", 1 << 40), ("GiB", 1 << 30),
                   ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= m:
            return f"{n / m:.4g} {suf}"
    return f"{n} B"


def _split_spec(spec: str) -> tuple[str, str | None]:
    """"image[@snap]" -> (image, snap|None)."""
    if "@" in spec:
        img, snap = spec.split("@", 1)
        return img, snap
    return spec, None


def _connect(args):
    from ..client import Rados
    from .rados_cli import _net_from_monmap
    name = f"client.{os.getpid() % 50000 + 10000}"
    net = _net_from_monmap(args.monmap, getattr(args, "keyring", ""))
    return Rados(net, name=name,
                 op_timeout=args.timeout).connect(args.timeout)


# ------------------------------------------------------------ commands

def cmd_create(io, a, out):
    RBD().create(io, a.image, _parse_size(a.size), order=a.order)
    print(f"created image {a.image}", file=out)


def cmd_ls(io, a, out):
    for name in RBD().list(io):
        print(name, file=out)


def cmd_info(io, a, out):
    name, snap = _split_spec(a.image)
    img = Image(io, name, snapshot=snap)
    st = img.stat()
    print(f"rbd image '{name}':", file=out)
    print(f"\tsize {_fmt_size(st['size'])} in {st['num_objs']} "
          f"objects", file=out)
    print(f"\torder {st['order']} ({_fmt_size(st['obj_size'])} "
          f"objects)", file=out)
    if img.parent is not None:
        p = img.parent
        print(f"\tparent: {p['pool']}/{p['image']}@{p['snap_name']} "
              f"(overlap {_fmt_size(p['overlap'])})", file=out)
    img.close()


def cmd_rm(io, a, out):
    RBD().remove(io, a.image)
    print(f"removed image {a.image}", file=out)


def cmd_resize(io, a, out):
    img = Image(io, a.image)
    img.resize(_parse_size(a.size))
    img.close()
    print(f"resized image {a.image}", file=out)


def cmd_du(io, a, out):
    img = Image(io, a.image)
    used = img.du()
    st = img.stat()
    print(f"{a.image} provisioned {_fmt_size(st['size'])} used "
          f"{_fmt_size(used)}", file=out)
    img.close()


def cmd_diff(io, a, out):
    name, snap = _split_spec(a.image)
    img = Image(io, name)
    for d in img.diff_since(a.from_snap):
        kind = "data" if d["exists"] else "zero"
        print(f"{d['offset']}\t{d['length']}\t{kind}", file=out)
    img.close()


def cmd_snap(io, a, out):
    name, snap = _split_spec(a.image)
    img = Image(io, name)
    try:
        if a.snap_cmd == "create":
            img.snap_create(snap)
            print(f"created snapshot {name}@{snap}", file=out)
        elif a.snap_cmd == "ls":
            for s in img.snap_list():
                prot = " (protected)" if \
                    img.snap_is_protected(s["name"]) else ""
                print(f"{s['id']}\t{s['name']}\t"
                      f"{_fmt_size(s['size'])}{prot}", file=out)
        elif a.snap_cmd == "rm":
            img.snap_remove(snap)
            print(f"removed snapshot {name}@{snap}", file=out)
        elif a.snap_cmd == "rollback":
            img.snap_rollback(snap)
            print(f"rolled back to {name}@{snap}", file=out)
        elif a.snap_cmd == "protect":
            img.snap_protect(snap)
            print(f"protected {name}@{snap}", file=out)
        elif a.snap_cmd == "unprotect":
            img.snap_unprotect(snap)
            print(f"unprotected {name}@{snap}", file=out)
    finally:
        img.close()


def cmd_clone(io, a, out):
    p_name, p_snap = _split_spec(a.parent_spec)
    if p_snap is None:
        raise RBDError(22, "clone needs parent@snap")
    RBD().clone(io, p_name, p_snap, io, a.child)
    print(f"cloned {p_name}@{p_snap} -> {a.child}", file=out)


def cmd_flatten(io, a, out):
    img = Image(io, a.image)
    img.flatten()
    img.close()
    print(f"flattened image {a.image}", file=out)


def cmd_children(io, a, out):
    name, snap = _split_spec(a.image)
    img = Image(io, name)
    for pool, child in img.children():
        print(f"{pool}/{child}", file=out)
    img.close()


# ---------------------------------------------------------------- main

def main(argv=None, rados=None, out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="rbd")
    ap.add_argument("--monmap", help="cluster monmap json")
    ap.add_argument("--keyring", default="",
                    help="keyring JSON (secure-mode clusters)")
    ap.add_argument("-p", "--pool", default="rbd")
    ap.add_argument("--timeout", type=float, default=30.0)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("create")
    p.add_argument("image")
    p.add_argument("--size", required=True)
    p.add_argument("--order", type=int, default=22)
    sub.add_parser("ls")
    for verb in ("info", "rm", "du", "flatten", "children"):
        p = sub.add_parser(verb)
        p.add_argument("image")
    p = sub.add_parser("resize")
    p.add_argument("image")
    p.add_argument("--size", required=True)
    p = sub.add_parser("diff")
    p.add_argument("image")
    p.add_argument("--from-snap", default=None)
    p = sub.add_parser("snap")
    p.add_argument("snap_cmd", choices=["create", "ls", "rm",
                                        "rollback", "protect",
                                        "unprotect"])
    p.add_argument("image")
    p = sub.add_parser("clone")
    p.add_argument("parent_spec")
    p.add_argument("child")
    a = ap.parse_args(argv)

    own = rados is None
    r = rados if rados is not None else _connect(a)
    try:
        io = r.open_ioctx(a.pool)
        handler = globals()[f"cmd_{a.cmd}"]
        handler(io, a, out)
        return 0
    except (RBDError, OSError) as ex:
        print(f"rbd: {ex}", file=sys.stderr)
        return 1
    finally:
        if own:
            r.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
